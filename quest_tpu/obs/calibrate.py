"""On-device calibration: measure the planner's constants where they run.

Every decision the scheduling/serving stack makes — ``select_engine``'s
XLA-vs-Pallas pick, the placement search's boundary-collective tradeoff,
the overlap model's hideable fractions — runs on
``planner.time_model``'s roofline, whose constants were hard-coded from
one fleet's bench rows (``MEASURED_EFFICIENCY``, the ``ChipSpec``
bandwidths).  The ledger's ``O_MODEL_DRIFT`` diagnostic could say
"re-calibrate" but nothing could actually do it.  This module is the
machinery:

- **The harness** (:func:`run_calibration`) times the real execution
  primitives on the live backend: compiled chains of per-gate XLA
  appliers split by qubit position class (lane 0-6 / sublane 7-9 / fiber
  10-16 / high >= 17 — the axis groups of ops/epoch_pallas.py), diagonal
  ladders and wide ``mrz`` parity rotations (the kinds XLA fuses, so the
  fit sees what compiled circuits actually pay), swap chains, the Pallas
  epoch executor's fused block/pack passes (interpret mode on CPU, the
  real kernels on TPU), and — when a mesh is visible — ``ppermute``
  pairwise exchanges and ``bitperm`` reshards by payload bytes.
- **The fit**: each measurement implies an efficiency ``eff =
  2·state_bytes / (pass_seconds · chip.hbm_bytes_per_sec)`` — exactly
  the constant ``time_model`` multiplies the roofline by — and the
  per-engine-class fit is the geometric mean of its measurements, with
  the **residual spread** (the worst multiplicative deviation of any
  measurement from the fit) recorded per class.  The profile's
  ``wall_band`` is derived from that spread: the band the ledger then
  checks measured walls against *on any platform* — calibration is what
  makes a CPU wall clock comparable to the model at all.
- **The profile**: one versioned JSON document
  (:data:`PROFILE_FORMAT`) keyed by platform, device kind,
  jax/jaxlib/libtpu versions and git sha, with a content-hash
  ``profile_id`` so every decision and ledger record can carry exact
  provenance.  :func:`save_profile` / :func:`load_profile` /
  :func:`validate_profile` are the persistence surface;
  :func:`activate` (or ``QUEST_TPU_CALIBRATION=/path.json``) makes the
  profile live, at which point ``planner.efficiency_for`` /
  ``time_model`` / ``engine_time_model`` / ``select_engine`` and the
  scheduler's placement search read the fitted constants in place of the
  hard-coded defaults, and ``obs/ledger.py`` switches its wall band to
  the fitted one.

Entry point: ``python -m quest_tpu.analysis --calibrate`` runs the
harness, writes/refreshes the profile, and reports which engine and
placement decisions flip under measured constants.  The CI
``calibrate-selftest`` job runs it on the CPU backend and gates the 17q
QFT trace-report ledger clean under the fitted band.  See
docs/OBSERVABILITY.md "Calibration".
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import json
import math
import os
import threading
import time

__all__ = ["PROFILE_FORMAT", "DEFAULT_STALE_AFTER_S", "CalibrationProfile",
           "make_profile", "validate_profile", "save_profile",
           "load_profile", "activate", "deactivate", "active_profile",
           "active_summary", "use_profile", "run_calibration"]

#: the profile schema tag (bumped on incompatible changes)
PROFILE_FORMAT = "quest-tpu-calibration-v1"

#: staleness default: a week-old profile still loads, but the serve
#: scrape's ``obs_calibration_stale`` gauge flips and ``active_summary``
#: reports it — hardware does not drift daily, software stacks do weekly
DEFAULT_STALE_AFTER_S = 7 * 86400.0

#: engine classes the fit must cover for a profile to be loadable — the
#: constants the planner actually reads (planner.MEASURED_EFFICIENCY keys)
REQUIRED_CLASSES = ("f32_gate", "f64_gate", "pallas_epoch")

#: multiplicative safety margin on the fitted residual spread when the
#: wall band is derived — measurement noise on a loaded host must not turn
#: an in-family run into drift
_BAND_MARGIN = 1.6

#: the wall band is never tighter than [1/2, 2]: below run-to-run noise
#: on shared hosts a tighter band would alarm on weather, not drift
_MIN_BAND_SPREAD = 2.0


# ---------------------------------------------------------------------------
# the profile document
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class CalibrationProfile:
    """One calibration run's fitted constants + provenance.  Immutable;
    build through :func:`make_profile` (which stamps the content-hash
    ``profile_id``) or :func:`load_profile`."""
    format: str
    created_epoch_s: float
    platform: str
    device_kind: str
    versions: dict            # jax / jaxlib / libtpu / numpy / python
    git_sha: str
    chip: str                 # the ChipSpec the efficiencies are relative to
    num_qubits: int
    efficiencies: dict        # engine class -> fitted achieved/peak fraction
    fit_residuals: dict       # engine class -> multiplicative spread (>= 1)
    wall_band: tuple          # (lo, hi) measured/predicted band for the ledger
    collective_bytes_per_sec: dict  # 'permute'/'reshard' -> fitted bytes/s
    measurements: dict        # raw harness rows (documentation payload)
    stale_after_s: float
    profile_id: str

    def as_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["wall_band"] = list(self.wall_band)
        return d

    def age_s(self, now: float | None = None) -> float:
        return (time.time() if now is None else now) - self.created_epoch_s

    def stale(self, now: float | None = None) -> bool:
        return self.age_s(now) > self.stale_after_s

    def summary(self, now: float | None = None) -> dict:
        """The provenance stamp engine decisions and ledger records carry:
        small, JSON-ready, and enough to find the full profile again."""
        residuals = list(self.fit_residuals.values()) or [1.0]
        return {
            "profile_id": self.profile_id,
            "platform": self.platform,
            "device_kind": self.device_kind,
            "age_s": round(self.age_s(now), 3),
            "stale": self.stale(now),
            "wall_band": list(self.wall_band),
            "residual_max": max(residuals),
        }


def _profile_hash(doc: dict) -> str:
    """Content hash over everything but the id itself — tamper-evident,
    and stable across save/load round-trips."""
    body = {k: v for k, v in doc.items() if k != "profile_id"}
    text = json.dumps(body, sort_keys=True, default=float)
    return hashlib.sha256(text.encode()).hexdigest()[:12]


def make_profile(*, efficiencies: dict, fit_residuals: dict | None = None,
                 wall_band: tuple | None = None,
                 collective_bytes_per_sec: dict | None = None,
                 measurements: dict | None = None,
                 platform: str | None = None, device_kind: str = "",
                 chip: str = "v5e", num_qubits: int = 0,
                 created_epoch_s: float | None = None,
                 stale_after_s: float = DEFAULT_STALE_AFTER_S,
                 versions: dict | None = None,
                 git_sha: str = "") -> CalibrationProfile:
    """Assemble a profile and stamp its content-hash id.  The harness
    builds through here; tests build adversarial/synthetic profiles the
    same way so the schema check cannot be sidestepped."""
    fit_residuals = dict(fit_residuals or
                         {k: 1.0 for k in efficiencies})
    if wall_band is None:
        spread = max(max(fit_residuals.values(), default=1.0)
                     * _BAND_MARGIN, _MIN_BAND_SPREAD)
        wall_band = (1.0 / spread, spread)
    if platform is None or versions is None:
        env = _environment_stamp()
        platform = platform if platform is not None else env["platform"]
        versions = versions if versions is not None else env["versions"]
        device_kind = device_kind or env["device_kind"]
        git_sha = git_sha or env["git_sha"]
    doc = {
        "format": PROFILE_FORMAT,
        "created_epoch_s": (time.time() if created_epoch_s is None
                            else float(created_epoch_s)),
        "platform": platform,
        "device_kind": device_kind,
        "versions": dict(versions),
        "git_sha": git_sha,
        "chip": chip,
        "num_qubits": int(num_qubits),
        "efficiencies": {k: float(v) for k, v in efficiencies.items()},
        "fit_residuals": {k: float(v) for k, v in fit_residuals.items()},
        "wall_band": [float(wall_band[0]), float(wall_band[1])],
        "collective_bytes_per_sec": {
            k: float(v) for k, v in (collective_bytes_per_sec or {}).items()},
        "measurements": measurements or {},
        "stale_after_s": float(stale_after_s),
    }
    doc["profile_id"] = _profile_hash(doc)
    return _from_doc(doc)


def _from_doc(doc: dict) -> CalibrationProfile:
    return CalibrationProfile(
        format=doc["format"],
        created_epoch_s=float(doc["created_epoch_s"]),
        platform=doc["platform"],
        device_kind=doc.get("device_kind", ""),
        versions=dict(doc.get("versions", {})),
        git_sha=doc.get("git_sha", ""),
        chip=doc.get("chip", "v5e"),
        num_qubits=int(doc.get("num_qubits", 0)),
        efficiencies={k: float(v) for k, v in doc["efficiencies"].items()},
        fit_residuals={k: float(v)
                       for k, v in doc.get("fit_residuals", {}).items()},
        wall_band=(float(doc["wall_band"][0]), float(doc["wall_band"][1])),
        collective_bytes_per_sec={
            k: float(v)
            for k, v in doc.get("collective_bytes_per_sec", {}).items()},
        measurements=doc.get("measurements", {}),
        stale_after_s=float(doc.get("stale_after_s",
                                    DEFAULT_STALE_AFTER_S)),
        profile_id=doc["profile_id"],
    )


def validate_profile(doc: dict) -> list:
    """Schema-check a profile document; returns the problem list (empty =
    valid) — the same contract shape as ``validate_chrome_trace``."""
    problems: list = []
    if not isinstance(doc, dict):
        return ["profile is not a JSON object"]
    if doc.get("format") != PROFILE_FORMAT:
        problems.append(f"format is {doc.get('format')!r}, "
                        f"not {PROFILE_FORMAT!r}")
    for field in ("created_epoch_s", "platform", "efficiencies",
                  "wall_band", "profile_id"):
        if field not in doc:
            problems.append(f"missing field {field!r}")
    effs = doc.get("efficiencies")
    if isinstance(effs, dict):
        for cls in REQUIRED_CLASSES:
            if cls not in effs:
                problems.append(f"efficiencies missing engine class {cls!r}")
        for cls, v in effs.items():
            if not isinstance(v, (int, float)) or not 0.0 < float(v):
                problems.append(f"efficiency {cls!r} = {v!r} is not a "
                                "positive number")
    elif effs is not None:
        problems.append("efficiencies is not an object")
    band = doc.get("wall_band")
    if isinstance(band, (list, tuple)) and len(band) == 2:
        lo, hi = band
        if not (isinstance(lo, (int, float)) and isinstance(hi, (int, float))
                and 0.0 < lo < hi):
            problems.append(f"wall_band {band!r} is not 0 < lo < hi")
    elif band is not None:
        problems.append(f"wall_band {band!r} is not a [lo, hi] pair")
    for cls, r in (doc.get("fit_residuals") or {}).items():
        if not isinstance(r, (int, float)) or float(r) < 1.0:
            problems.append(f"fit_residual {cls!r} = {r!r} must be >= 1")
    if "profile_id" in doc and not problems:
        want = _profile_hash(doc)
        if doc["profile_id"] != want:
            problems.append(f"profile_id {doc['profile_id']!r} does not "
                            f"match content hash {want!r} (edited by hand?)")
    return problems


def save_profile(profile: CalibrationProfile, path: str) -> dict:
    """Write one JSON document; returns it."""
    doc = profile.as_dict()
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1, default=float)
        fh.write("\n")
    return doc


def load_profile(path: str) -> CalibrationProfile:
    """Load + schema-validate; raises ``ValueError`` listing problems."""
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    problems = validate_profile(doc)
    if problems:
        raise ValueError(f"{path}: not a valid {PROFILE_FORMAT} profile: "
                         + "; ".join(problems))
    return _from_doc(doc)


# ---------------------------------------------------------------------------
# activation: the one live profile the planner/ledger read
# ---------------------------------------------------------------------------

_ACTIVE: CalibrationProfile | None = None
_ENV_CHECKED = False
_LOCK = threading.Lock()


def activate(profile: CalibrationProfile) -> CalibrationProfile:
    """Make ``profile`` the process-wide live calibration: from here on
    ``planner.efficiency_for``/``time_model``/``select_engine`` read its
    fitted constants and the ledger checks walls against its band."""
    global _ACTIVE, _ENV_CHECKED
    with _LOCK:
        _ACTIVE = profile
        _ENV_CHECKED = True
    return profile


def deactivate() -> None:
    """Back to the hard-coded defaults (and stop the env-var autoload —
    an explicit deactivate wins over ``QUEST_TPU_CALIBRATION``)."""
    global _ACTIVE, _ENV_CHECKED
    with _LOCK:
        _ACTIVE = None
        _ENV_CHECKED = True


def active_profile() -> CalibrationProfile | None:
    """The live profile, autoloading ``QUEST_TPU_CALIBRATION=/path.json``
    once on first use (a bad file warns and disables the autoload rather
    than failing whatever run asked)."""
    global _ACTIVE, _ENV_CHECKED
    with _LOCK:
        if _ACTIVE is not None or _ENV_CHECKED:
            return _ACTIVE
        _ENV_CHECKED = True
        path = os.environ.get("QUEST_TPU_CALIBRATION")
    if path:
        try:
            prof = load_profile(path)
        except (OSError, ValueError) as exc:
            import warnings
            warnings.warn(f"QUEST_TPU_CALIBRATION: {exc}", RuntimeWarning,
                          stacklevel=2)
            return None
        with _LOCK:
            _ACTIVE = prof
    return _ACTIVE


def active_summary() -> dict | None:
    """The live profile's provenance stamp, or None — what
    ``select_engine`` decisions, ledger records and the serve scrape's
    staleness gauges carry."""
    prof = active_profile()
    return None if prof is None else prof.summary()


@contextlib.contextmanager
def use_profile(profile: CalibrationProfile | None):
    """Scoped activation (tests, the --calibrate decision-flip report):
    restores the previous live profile — including "none" — on exit."""
    global _ACTIVE, _ENV_CHECKED
    with _LOCK:
        prev, prev_checked = _ACTIVE, _ENV_CHECKED
        _ACTIVE, _ENV_CHECKED = profile, True
    try:
        yield profile
    finally:
        with _LOCK:
            _ACTIVE, _ENV_CHECKED = prev, prev_checked


# ---------------------------------------------------------------------------
# the microbenchmark harness
# ---------------------------------------------------------------------------

def _environment_stamp() -> dict:
    """Platform/versions/git provenance (the bench.py _provenance shape,
    local so obs stays dependency-light)."""
    import platform as _plat
    versions: dict = {"python": _plat.python_version()}
    plat = "unknown"
    kind = ""
    try:
        import jax
        versions["jax"] = jax.__version__
        dev = jax.devices()[0]
        plat = dev.platform
        kind = getattr(dev, "device_kind", "")
    except Exception:
        pass
    try:
        import jaxlib
        versions["jaxlib"] = jaxlib.__version__
    except Exception:
        pass
    try:
        import libtpu
        versions["libtpu"] = getattr(libtpu, "__version__", "present")
    except Exception:
        pass
    try:
        import numpy as np
        versions["numpy"] = np.__version__
    except Exception:
        pass
    git_sha = ""
    try:
        import subprocess
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        git_sha = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=root,
            capture_output=True, text=True, timeout=10).stdout.strip()
    except Exception:
        pass
    return {"platform": plat, "device_kind": kind, "versions": versions,
            "git_sha": git_sha}


def _haar_unitary(rng):
    import numpy as np
    g = rng.normal(size=(2, 2)) + 1j * rng.normal(size=(2, 2))
    u, r = np.linalg.qr(g)
    return u * (np.diag(r) / np.abs(np.diag(r)))


def _time_chain(ops_key: tuple, n: int, dtype, repeats: int,
                iters: int) -> float:
    """Seconds per op of a COMPILED chain of ``ops_key`` applied ``iters``
    times (fori_loop, norm readback bounding the timing; the bench.py
    _run_layered discipline: overhead probed and subtracted, min over
    repeats so noise only makes the number pessimistic).  Compiling the
    probe itself records into the runtime compile counters."""
    from functools import partial

    import jax
    import jax.numpy as jnp

    from ..circuit import _apply_one
    from . import counters as _counters

    @partial(jax.jit, static_argnames=())
    def run(s, k):
        def body(_, st):
            for op in ops_key:
                st = _apply_one(st, op)
            return st
        s = jax.lax.fori_loop(0, k, body, s)
        return jnp.sum(s[0] * s[0] + s[1] * s[1])

    state = jnp.zeros((2, 1 << n), dtype=dtype).at[0, 0].set(1.0)
    t0 = time.perf_counter()
    float(run(state, 1))            # compile + warm
    _counters.record_compile(time.perf_counter() - t0)
    float(run(state, 0))
    best = None
    for _ in range(max(1, repeats)):
        t0 = time.perf_counter()
        float(run(state, 0))
        overhead = time.perf_counter() - t0
        t0 = time.perf_counter()
        float(run(state, iters))
        dt = time.perf_counter() - t0
        per = max(dt - overhead, 1e-9) / (iters * len(ops_key))
        best = per if best is None else min(best, per)
    return best


def _chain_circuits(n: int) -> dict:
    """The f32 gate-engine measurement suite: per position class a chain
    of DISTINCT-qubit ops compiled as one program (so the fit sees XLA's
    real fusion behaviour for each kind — a diagonal ladder fuses, dense
    gathers mostly do not; the spread between kinds is exactly the
    structural model error the fitted wall band must cover)."""
    import numpy as np

    from ..circuit import Circuit
    rng = np.random.default_rng(17)
    suite: dict = {}

    def dense(label, qubits):
        c = Circuit(n)
        for q in qubits:
            c.unitary(q, _haar_unitary(rng))
        if c.ops:
            suite[label] = c

    dense("dense_lane", range(0, min(7, n)))
    dense("dense_sublane", range(7, min(10, n)))
    dense("dense_fiber", range(10, min(17, n)))
    dense("dense_high", range(17, n))
    diag = Circuit(n)
    for j in range(min(8, n - 1)):
        diag.phase_shift(n - 1, math.pi / (1 << (j + 1)), controls=(j,))
    suite["diagonal_ladder"] = diag
    sw = Circuit(n)
    for q in range(min(4, n // 2)):
        sw.swap(q, n - 1 - q)
    suite["swap_chain"] = sw
    if n >= 13:
        mrz = Circuit(n)
        # unlifted-ok: calibration probe — one fixed angle, compiled once
        mrz.multi_rotate_z(tuple(range(12)), 0.37)
        suite["mrz_wide"] = mrz
    return suite


def _implied_efficiency(per_pass_s: float, n: int, precision: int,
                        chip) -> float:
    """The MEASURED_EFFICIENCY-shaped constant one measured pass implies:
    time_model charges ``2 · state_bytes / (hbm_peak · eff)`` per pass, so
    ``eff = 2 · state_bytes / (pass_s · hbm_peak)``."""
    bytes_per_amp = 8 if precision == 1 else 16
    state_bytes = (1 << n) * bytes_per_amp
    return 2.0 * state_bytes / (per_pass_s * chip.hbm_bytes_per_sec)


def _fit_class(values: dict) -> tuple:
    """(geomean fit, multiplicative residual spread >= 1) of the implied
    efficiencies of one engine class."""
    effs = [v for v in values.values() if v > 0]
    if not effs:
        return 0.0, 1.0
    fit = math.exp(sum(math.log(e) for e in effs) / len(effs))
    spread = max(max(e / fit, fit / e) for e in effs)
    return fit, spread


#: the smallest register the degenerate-geometry microbench runs at (the
#: ``pallas_epoch_small`` class: one single-block VMEM tile per pass)
_SMALL_CAL_QUBITS = 12


def _measure_pallas(n: int, repeats: int, iters: int, rows: dict,
                    chip) -> dict:
    """Fused passes through the real epoch executor, per PASS KIND
    (interpret mode on CPU — slow but truthful for THAT backend, which is
    the point: a CPU profile must rate the interpret-mode engine as the
    non-starter it is).  Returns ``{engine_class: {label: efficiency}}``
    covering the three kinds the planner prices separately: fused block
    passes (``pallas_epoch``), staged high-qubit pack passes — dense AND
    controlled-dense, the widened envelope's new lowering —
    (``pallas_epoch_pack``), and the degenerate single-block geometry of
    10-16 qubit registers (``pallas_epoch_small``)."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from ..circuit import Circuit
    from ..ops import epoch_pallas as _ep
    from . import counters as _counters

    rng = np.random.default_rng(29)
    values: dict = {}

    def measure(label: str, engine_class: str, circuit) -> None:
        nq = circuit.num_qubits
        ops = circuit.key()
        plan = _ep.plan_circuit(ops, nq)
        if plan.pallas_passes == 0 or plan.xla_ops:
            return
        t0 = time.perf_counter()
        call = _ep.jit_program(ops)
        state = jnp.zeros((2, 1 << nq), jnp.float32).at[0, 0].set(1.0)
        state = call(state)
        jax.block_until_ready(state)
        _counters.record_compile(time.perf_counter() - t0)
        best = None
        for _ in range(max(1, repeats)):
            t0 = time.perf_counter()
            for _ in range(iters):
                state = call(state)
            jax.block_until_ready(state)
            dt = time.perf_counter() - t0
            per = max(dt, 1e-9) / (iters * plan.hbm_passes)
            best = per if best is None else min(best, per)
        eff = _implied_efficiency(best, nq, 1, chip)
        values.setdefault(engine_class, {})[label] = eff
        rows[f"pallas_{label}"] = {
            "engine_class": engine_class, "kind": label,
            "seconds_per_pass": best, "implied_efficiency": eff,
            "hbm_passes": plan.hbm_passes, "ops": len(ops),
            "num_qubits": nq, "precision": 1}

    block_cls = ("pallas_epoch" if n >= _ep.HIGH_BASE
                 else "pallas_epoch_small")
    c = Circuit(n)
    for q in range(0, 7):
        c.unitary(q, _haar_unitary(rng))
    measure("block_lane", block_cls, c)
    if n > _ep.HIGH_BASE:
        c = Circuit(n)
        for q in range(_ep.HIGH_BASE, n):
            c.unitary(q, _haar_unitary(rng))
        measure("pack_high", "pallas_epoch_pack", c)
        c = Circuit(n)
        for _ in range(3):
            c.multi_qubit_unitary((_ep.HIGH_BASE,), _haar_unitary(rng),
                                  controls=(0,))
        measure("pack_controlled", "pallas_epoch_pack", c)
    if n >= _ep.HIGH_BASE and _ep.epoch_supported(_SMALL_CAL_QUBITS):
        c = Circuit(_SMALL_CAL_QUBITS)
        for q in range(0, 7):
            c.unitary(q, _haar_unitary(rng))
        measure("block_small", "pallas_epoch_small", c)
    # fused superoperator stages (density noise channels): a mirrored
    # damping+depolarising layer on a small Choi-doubled register, every
    # channel a flip/select stage (``pallas_epoch_super`` — the class
    # engine_time_model prices super-carrying passes at)
    from ..circuit import DensityCircuit
    dn = _SMALL_CAL_QUBITS // 2
    dc = DensityCircuit(dn)
    for q in range(dn):
        dc.unitary(q, _haar_unitary(rng))
    for q in range(0, dn, 2):
        dc.damp(q, 0.05)        # unlifted-ok: calibration probe channel
    for q in range(1, dn, 2):
        dc.depolarise(q, 0.05)  # unlifted-ok: calibration probe channel
    measure("super_block", "pallas_epoch_super", dc)
    return values


def _measure_collectives(repeats: int, rows: dict) -> dict:
    """ppermute pairwise exchange + bitperm reshard on the visible mesh,
    fitted as effective bytes/sec per comm class (the constants absorb
    topology — they were measured on the deployment's own mesh; without
    >= 2 devices the sweep is skipped and the profile records none).

    The fit is the TWO-POINT SLOPE between a small and a large payload:
    ``bw = (bytes_hi - bytes_lo) / (t_hi - t_lo)``.  Probe payloads are
    inevitably latency-dominated (dispatch + collective setup swamp the
    wire time of a KB-scale shard), and a naive bytes/seconds ratio at
    probe scale would undershoot the deployment's real bandwidth by
    orders of magnitude — the slope cancels the fixed per-collective
    latency, which is what time_model's linear bytes/bw term wants.  A
    non-positive slope (noise: the large probe timed no slower) falls
    back to the large payload's ratio, the conservative bound."""
    import jax
    import jax.numpy as jnp

    samples: dict = {}
    devices = jax.devices()
    nd = 1
    while nd * 2 <= min(len(devices), 8):
        nd *= 2
    if nd < 2:
        return {}
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.collectives import pairwise_exchange
    from ..parallel.mesh import make_amps_mesh
    mesh = make_amps_mesh(devices[:nd])
    sharding = NamedSharding(mesh, P(None, "amps"))
    for label, m in (("small", 14), ("large", 20)):
        shard_bytes = (1 << m) // nd * 8
        state = jax.device_put(
            jnp.zeros((2, 1 << m), jnp.float32).at[0, 0].set(1.0), sharding)

        ex = jax.jit(lambda s: pairwise_exchange(s, mesh, 1))
        jax.block_until_ready(ex(state))
        best = None
        for _ in range(max(1, repeats)):
            t0 = time.perf_counter()
            jax.block_until_ready(ex(state))
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        samples.setdefault("permute", []).append((shard_bytes, best))
        rows[f"collective_permute_{label}"] = {
            "comm_class": "permute", "payload_bytes": shard_bytes,
            "seconds": best, "devices": nd}

        from ..ops.apply import apply_bit_permutation
        hi, lo = m - 1, 0
        bp = jax.jit(lambda s: apply_bit_permutation(s, (lo, hi), (hi, lo)),
                     out_shardings=sharding)
        jax.block_until_ready(bp(state))
        best = None
        for _ in range(max(1, repeats)):
            t0 = time.perf_counter()
            jax.block_until_ready(bp(state))
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        moved = 2 * shard_bytes
        samples.setdefault("reshard", []).append((moved, best))
        rows[f"collective_reshard_{label}"] = {
            "comm_class": "reshard", "payload_bytes": moved,
            "seconds": best, "devices": nd}
    out: dict = {}
    for cls, pts in samples.items():
        bw, fit, t_lo, t_hi = _fit_collective_points(pts)
        out[cls] = bw
        rows[f"collective_{cls}_fit"] = {
            "comm_class": cls, "bytes_per_sec": bw, "fit": fit,
            "latency_s_small": t_lo, "latency_s_large": t_hi}
    return out


def _fit_collective_points(pts: list) -> tuple:
    """(bytes_per_sec, fit_kind, t_small, t_large) from two (bytes,
    seconds) probes: the slope cancels the fixed per-collective latency
    (see :func:`_measure_collectives`); a non-positive slope falls back
    to the large probe's plain ratio."""
    (b_lo, t_lo), (b_hi, t_hi) = sorted(pts)
    if t_hi > t_lo:
        return (b_hi - b_lo) / (t_hi - t_lo), "two_point_slope", t_lo, t_hi
    return b_hi / t_hi, "ratio_fallback", t_lo, t_hi


def run_calibration(chip=None, num_qubits: int | None = None,
                    repeats: int = 3, iters: int = 4,
                    include_f64: bool = True, include_pallas: bool = True,
                    collectives: bool = True,
                    stale_after_s: float = DEFAULT_STALE_AFTER_S
                    ) -> CalibrationProfile:
    """Run the microbenchmark harness on the live backend and fit a
    :class:`CalibrationProfile`.

    ``chip`` names the reference :class:`planner.ChipSpec` the
    efficiencies are expressed against (default v5e — the same convention
    as the hard-coded ``MEASURED_EFFICIENCY``); on a non-TPU backend the
    fitted fractions are simply small, which is truthful: they make
    ``time_model`` predict THIS platform's walls, which is what lets the
    ledger check walls here at all.  Classes the harness does not measure
    directly (``f32_fused``/``f32_inplace``/``f64_best``) are derived by
    scaling the hard-coded default with the measured correction of their
    base class, and recorded as such in ``measurements['derived']``."""
    import jax

    from ..parallel import planner as _planner
    chip = chip or _planner.V5E
    if num_qubits is None:
        num_qubits = 18 if include_pallas else 14
    n = int(num_qubits)
    if include_pallas:
        from ..ops import epoch_pallas as _ep
        include_pallas = _ep.epoch_supported(n, 1)
    import jax.numpy as jnp

    rows: dict = {}
    f32_values: dict = {}
    for label, circuit in _chain_circuits(n).items():
        per = _time_chain(circuit.key(), n, jnp.float32, repeats, iters)
        eff = _implied_efficiency(per, n, 1, chip)
        f32_values[label] = eff
        rows[f"f32_{label}"] = {
            "engine_class": "f32_gate", "kind": label,
            "seconds_per_pass": per, "implied_efficiency": eff,
            "ops": len(circuit.ops), "precision": 1}

    f64_values: dict = {}
    if include_f64:
        suite = _chain_circuits(n)
        for label in ("dense_lane", "dense_fiber", "diagonal_ladder"):
            circuit = suite.get(label)
            if circuit is None:
                continue
            per = _time_chain(circuit.key(), n, jnp.float64, repeats,
                              max(1, iters // 2))
            eff = _implied_efficiency(per, n, 2, chip)
            f64_values[label] = eff
            rows[f"f64_{label}"] = {
                "engine_class": "f64_gate", "kind": label,
                "seconds_per_pass": per, "implied_efficiency": eff,
                "ops": len(circuit.ops), "precision": 2}

    pallas_values: dict = {}
    if include_pallas:
        pallas_values = _measure_pallas(n, repeats, max(1, iters // 2),
                                        rows, chip)

    defaults = _planner.MEASURED_EFFICIENCY
    efficiencies: dict = {}
    residuals: dict = {}
    derived: list = []

    fit32, spread32 = _fit_class(f32_values)
    efficiencies["f32_gate"] = fit32 or defaults["f32_gate"]
    residuals["f32_gate"] = spread32
    ratio32 = efficiencies["f32_gate"] / defaults["f32_gate"]

    if f64_values:
        fit64, spread64 = _fit_class(f64_values)
        efficiencies["f64_gate"] = fit64
        residuals["f64_gate"] = spread64
    else:
        efficiencies["f64_gate"] = defaults["f64_gate"] * ratio32
        residuals["f64_gate"] = spread32
        derived.append("f64_gate")
    ratio64 = efficiencies["f64_gate"] / defaults["f64_gate"]

    if pallas_values.get("pallas_epoch"):
        fitp, spreadp = _fit_class(pallas_values["pallas_epoch"])
        efficiencies["pallas_epoch"] = fitp
        residuals["pallas_epoch"] = spreadp
    else:
        efficiencies["pallas_epoch"] = defaults["pallas_epoch"] * ratio32
        residuals["pallas_epoch"] = spread32
        derived.append("pallas_epoch")
    # the widened envelope's pass kinds (staged high-qubit packs, the
    # degenerate small-register geometry): fitted where the harness
    # measured them, else the default scaled by the block-pass correction
    ratio_p = efficiencies["pallas_epoch"] / defaults["pallas_epoch"]
    for cls in ("pallas_epoch_pack", "pallas_epoch_small"):
        if pallas_values.get(cls):
            fitc, spreadc = _fit_class(pallas_values[cls])
            efficiencies[cls] = fitc
            residuals[cls] = spreadc
        else:
            efficiencies[cls] = defaults[cls] * ratio_p
            residuals[cls] = residuals["pallas_epoch"]
            derived.append(cls)

    # classes without a dedicated probe: the default scaled by the measured
    # correction of the class they ride on (fused/in-place ride the f32
    # gate engine's platform correction, f64_best rides f64's)
    for cls, base_ratio in (("f32_fused", ratio32), ("f32_inplace", ratio32),
                            ("f64_best", ratio64)):
        efficiencies[cls] = defaults[cls] * base_ratio
        residuals[cls] = residuals["f32_gate" if cls.startswith("f32")
                                   else "f64_gate"]
        derived.append(cls)

    coll: dict = {}
    if collectives:
        coll = _measure_collectives(repeats, rows)

    spread_all = max([residuals[c] for c in REQUIRED_CLASSES]
                     + [_MIN_BAND_SPREAD / _BAND_MARGIN])
    band_hi = spread_all * _BAND_MARGIN
    wall_band = (1.0 / band_hi, band_hi)

    rows["derived"] = derived
    rows["harness"] = {"repeats": repeats, "iters": iters,
                       "backend": jax.default_backend(),
                       "devices": len(jax.devices())}
    return make_profile(
        efficiencies=efficiencies, fit_residuals=residuals,
        wall_band=wall_band, collective_bytes_per_sec=coll,
        measurements=rows, chip=chip.name, num_qubits=n,
        stale_after_s=stale_after_s)
