"""Serve flight recorder: a bounded ring of recent request records.

Metrics aggregates (serve/metrics.py) answer "how is the service doing";
they cannot answer "what were the last 256 requests doing when the queue
filled".  The flight recorder keeps exactly that: one fixed-size record per
request — admission time, queue depth at admission, batch id, queue wait,
execution time, and the terminal outcome (``ok`` | ``deadline`` |
``queue_full`` | ``cancelled`` | ``error:<type>``) — in a ring buffer whose
memory never grows with traffic.

The service dumps the ring on ``E_QUEUE_FULL``, on a worker-side
execution error, and — probed services (obs/numerics.py) — on the first
NaN/Inf outcome in a batch (reason ``O_NUMERIC_NAN``): the "something is
wrong NOW" moments.  It keeps the last
dump for post-mortems, and exposes both the live ring and the last dump
through ``python -m quest_tpu.serve --selftest --json`` (the
``flight_recorder`` document key; docs/OBSERVABILITY.md has the format).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque

__all__ = ["FlightRecord", "FlightRecorder", "DEFAULT_CAPACITY"]

DEFAULT_CAPACITY = 256


@dataclasses.dataclass
class FlightRecord:
    """One request's flight: times are ``time.time()`` epoch seconds so
    dumps correlate across processes; ``wait_s``/``exec_s`` are filled when
    the request reaches a batch."""
    request_id: int
    class_key: str
    enqueue_t: float
    queue_depth: int
    deadline_ms: float | None = None
    admitted: bool = True
    batch_id: int | None = None
    wait_s: float | None = None
    exec_s: float | None = None
    outcome: str = "pending"
    # numeric-health payload of a probed request (obs/numerics.py
    # NumericRecord.as_health): norm, drift vs band, NaN/Inf counts,
    # findings — None when the request ran unprobed
    numeric_health: dict | None = None

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class FlightRecorder:
    """Thread-safe ring of :class:`FlightRecord`.  ``capacity`` bounds both
    memory and dump size; old records fall off the far end."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)  # guarded-by: _lock
        self._by_rid: dict = {}                          # guarded-by: _lock
        self.last_dump: dict | None = None               # guarded-by: _lock
        # lock-free: monotone int gauge; scrape readers tolerate off-by-one
        self.dumps = 0

    # -- recording ----------------------------------------------------------
    def admit(self, request_id: int, class_key: str, queue_depth: int,
              deadline_ms: float | None = None) -> FlightRecord:
        rec = FlightRecord(request_id, class_key, time.time(), queue_depth,
                           deadline_ms)
        with self._lock:
            if len(self._ring) == self.capacity:
                old = self._ring[0]
                self._by_rid.pop(old.request_id, None)
            self._ring.append(rec)
            self._by_rid[request_id] = rec
        return rec

    def reject(self, request_id: int, class_key: str,
               queue_depth: int) -> FlightRecord:
        """Record a request bounced at admission (``E_QUEUE_FULL``).  The
        serving layer passes a distinct NEGATIVE id here — a bounced
        request never had a real request id, and a synthetic positive one
        could alias (and later mis-resolve) an admitted request."""
        rec = self.admit(request_id, class_key, queue_depth)
        rec.admitted = False
        rec.outcome = "queue_full"
        return rec

    def resolve(self, request_id: int, outcome: str, *,
                batch_id: int | None = None, wait_s: float | None = None,
                exec_s: float | None = None,
                numeric_health: dict | None = None) -> None:
        """Fill a record's terminal fields; unknown ids (already rung out)
        are ignored — the ring is best-effort recent history, not a
        database."""
        with self._lock:
            rec = self._by_rid.get(request_id)
            if rec is None:
                return
            rec.outcome = outcome
            if batch_id is not None:
                rec.batch_id = batch_id
            if wait_s is not None:
                rec.wait_s = wait_s
            if exec_s is not None:
                rec.exec_s = exec_s
            if numeric_health is not None:
                rec.numeric_health = numeric_health

    # -- reading ------------------------------------------------------------
    def records(self) -> list[FlightRecord]:
        with self._lock:
            return list(self._ring)

    def dump(self, reason: str) -> dict:
        """Snapshot the ring (oldest first) with a reason tag; kept as
        ``last_dump`` and returned for immediate logging."""
        with self._lock:
            doc = {"reason": reason, "time": time.time(),
                   "capacity": self.capacity,
                   "records": [r.as_dict() for r in self._ring]}
            self.last_dump = doc
            self.dumps += 1
        return doc

    def snapshot(self) -> dict:
        """The ``--selftest --json`` payload: the live ring plus the last
        dump (if any)."""
        with self._lock:
            return {"capacity": self.capacity,
                    "depth": len(self._ring),
                    "dumps": self.dumps,
                    "records": [r.as_dict() for r in self._ring],
                    "last_dump": self.last_dump}
