"""Thread-safe span recorder with request-id correlation.

A :class:`Span` is one timed host-side region (cache lookup, schedule
search, engine selection, batch execution ...) with structured attributes
and two correlation links: ``parent_id`` (the enclosing span, carried by a
``contextvars.ContextVar`` so nesting works across call boundaries inside
one thread/context) and ``request_id`` (set by the serving layer at the
front door via :func:`request` and inherited by every span recorded while
that context is live — the propagation contract of
docs/OBSERVABILITY.md).

Two properties are load-bearing:

- **Disabled tracing is free.**  ``span()`` with the recorder disabled
  returns one shared no-op context manager — no allocation, no lock, no
  clock read — so the serving hot path can be instrumented unconditionally
  (the ``serve_vqe_16q_batch64`` overhead contract: < 1% wall, asserted in
  tests/test_obs.py).
- **Spans line up with device timelines.**  An enabled span enters a
  ``jax.profiler.TraceAnnotation`` of the same name, so an XProf capture of
  the same run shows the host spans as named regions above the device
  lanes.

The module-level recorder singleton (``_RECORDER``) is created at import —
one process, one trace — and registers an ``atexit`` dump hook so a crash
still leaves a readable trace when ``QUEST_TPU_TRACE_DUMP`` names a file.
Import-time process-state mutation is exactly what the purity lint's
``P_IMPORT_TIME_STATE_MUTATION`` rule exists to flag; this module is the
one allowlisted observability site (analysis/purity.py), the same contract
``_compat.py`` has for the x64 default.
"""

from __future__ import annotations

import atexit
import contextlib
import contextvars
import dataclasses
import os
import threading
import time

__all__ = ["Span", "TraceRecorder", "recorder", "span", "emit_span",
           "request", "current_request_id", "note", "collect_notes",
           "enable_tracing", "disable_tracing", "reset_tracing",
           "tracing_enabled", "obs_snapshot", "key_hash"]

#: recorder capacity default: large enough that no CI/selftest workload
#: ever overflows.  Beyond it NEW spans are dropped (counted) — except
#: spans some recorded child already references as parent, which are
#: admitted so the export never carries a dangling parent_id (the
#: validator treats an orphan as a hard problem)
DEFAULT_MAX_SPANS = 1 << 18

_PARENT: contextvars.ContextVar = contextvars.ContextVar(
    "quest_obs_parent", default=None)
_REQUEST: contextvars.ContextVar = contextvars.ContextVar(
    "quest_obs_request", default=None)
_NOTES: contextvars.ContextVar = contextvars.ContextVar(
    "quest_obs_notes", default=None)


@dataclasses.dataclass
class Span:
    """One recorded host region.  ``t0`` is seconds on the recorder's
    ``perf_counter`` clock (``TraceRecorder.t0_perf`` is the trace
    origin); ``attrs`` carries the structured payload (class key, engine,
    cache outcome, pass count, comm bytes ...)."""
    name: str
    span_id: int
    parent_id: int | None
    request_id: int | None
    t0: float
    dur: float
    thread: str
    attrs: dict


class _NoopSpan:
    """The disabled-path context manager: one shared instance, no state."""
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


class _LiveSpan:
    """Context manager recording one span on exit.  Yields the open
    :class:`Span` so callers can set attributes mid-flight
    (``sp.attrs["engine"] = resolved``)."""
    __slots__ = ("_rec", "_span", "_token", "_ann")

    def __init__(self, rec: "TraceRecorder", name: str, attrs: dict):
        self._rec = rec
        self._span = Span(name, rec._next_id(), _PARENT.get(),
                          _REQUEST.get(), 0.0, 0.0,
                          threading.current_thread().name, attrs)
        self._token = None
        self._ann = None

    def __enter__(self) -> Span:
        self._token = _PARENT.set(self._span.span_id)
        try:
            import jax
            self._ann = jax.profiler.TraceAnnotation(self._span.name)
            self._ann.__enter__()
        except Exception:       # profiler unavailable: spans still record
            self._ann = None
        self._span.t0 = time.perf_counter()
        return self._span

    def __exit__(self, *exc):
        self._span.dur = time.perf_counter() - self._span.t0
        if self._ann is not None:
            self._ann.__exit__(*exc)
        _PARENT.reset(self._token)
        self._rec._append(self._span)
        return False


class TraceRecorder:
    """Bounded, thread-safe span store.  Disabled by default; spans beyond
    ``max_spans`` are counted as dropped rather than evicting older ones
    (see DEFAULT_MAX_SPANS)."""

    def __init__(self, max_spans: int = DEFAULT_MAX_SPANS,
                 enabled: bool = False):
        self.max_spans = int(max_spans)
        # lock-free: bool flip read unlocked on the disabled-tracing hot path (< 1% overhead contract)
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._spans: list[Span] = []    # guarded-by: _lock
        self._referenced: set = set()   # guarded-by: _lock (parent ids of recorded spans)
        self._present: set = set()      # guarded-by: _lock (ids of recorded spans)
        self._dropped = 0               # guarded-by: _lock
        self._ids = 0                   # guarded-by: _lock
        self.t0_perf = time.perf_counter()  # guarded-by: _lock
        self.t0_epoch = time.time()         # guarded-by: _lock

    # -- recording ----------------------------------------------------------
    def span(self, name: str, **attrs):
        """Context manager timing one region; no-op (and allocation-free)
        while the recorder is disabled."""
        if not self.enabled:
            return _NOOP
        return _LiveSpan(self, name, attrs)

    def emit(self, name: str, *, t0: float, dur: float,
             parent_id: int | None = None, request_id: int | None = None,
             **attrs) -> int | None:
        """Record a span retroactively from explicit ``perf_counter``
        timestamps — the serving layer's per-request execution spans are
        emitted after the shared batch completes.  Returns the span id.

        An explicit ``parent_id`` must name an already-RECORDED span; if
        that parent was dropped at the capacity bound the span is recorded
        as a root instead, so the export never carries a dangling
        parent_id."""
        if not self.enabled:
            return None
        validate_parent = parent_id is not None
        if parent_id is None:
            parent_id = _PARENT.get()
        sp = Span(name, self._next_id(), parent_id,
                  request_id if request_id is not None else _REQUEST.get(),
                  t0, dur, threading.current_thread().name, attrs)
        # the explicit-parent presence check happens inside _append, under
        # the same lock as the _present set it consults (the old unlocked
        # membership probe was the concurrency auditor's first real find)
        self._append(sp, validate_parent=validate_parent)
        return sp.span_id

    def _next_id(self) -> int:
        with self._lock:
            self._ids += 1
            return self._ids

    def _append(self, sp: Span, validate_parent: bool = False) -> None:
        # Spans append on EXIT, children before parents — so a full buffer
        # must still admit a span some recorded child already references as
        # parent, or the export would carry a dangling parent_id (the
        # orphan the validator hard-fails on).  The overshoot is bounded by
        # open-span nesting depth x threads, not by traffic.
        with self._lock:
            if (validate_parent and sp.parent_id is not None
                    and sp.parent_id not in self._present):
                sp.parent_id = None     # dropped parent: record as a root
            if (len(self._spans) >= self.max_spans
                    and sp.span_id not in self._referenced):
                self._dropped += 1
                return
            self._spans.append(sp)
            self._present.add(sp.span_id)
            if sp.parent_id is not None:
                self._referenced.add(sp.parent_id)

    # -- lifecycle ----------------------------------------------------------
    def enable(self) -> "TraceRecorder":
        self.enabled = True
        return self

    def disable(self) -> "TraceRecorder":
        self.enabled = False
        return self

    def reset(self) -> "TraceRecorder":
        with self._lock:
            self._spans = []
            self._referenced = set()
            self._present = set()
            self._dropped = 0
            self._ids = 0
            self.t0_perf = time.perf_counter()
            self.t0_epoch = time.time()
        return self

    # -- reading ------------------------------------------------------------
    def spans(self) -> list[Span]:
        with self._lock:
            return list(self._spans)

    def snapshot(self) -> dict:
        with self._lock:
            return {"enabled": int(self.enabled),
                    "spans": len(self._spans),
                    "dropped": self._dropped}


# ---------------------------------------------------------------------------
# module-level singleton + convenience API
# ---------------------------------------------------------------------------

_RECORDER = TraceRecorder(
    enabled=os.environ.get("QUEST_TPU_TRACE") == "1")


def recorder() -> TraceRecorder:
    """The process-wide recorder (one process, one trace)."""
    return _RECORDER


def span(name: str, **attrs):
    """``with span("cache.lookup", outcome="hit") as sp: ...`` on the
    process recorder; free while tracing is disabled."""
    return _RECORDER.span(name, **attrs)


def emit_span(name: str, *, t0: float, dur: float,
              parent_id: int | None = None, request_id: int | None = None,
              **attrs) -> int | None:
    return _RECORDER.emit(name, t0=t0, dur=dur, parent_id=parent_id,
                          request_id=request_id, **attrs)


def enable_tracing(max_spans: int | None = None) -> TraceRecorder:
    if max_spans is not None:
        _RECORDER.max_spans = int(max_spans)
    return _RECORDER.enable()


def disable_tracing() -> TraceRecorder:
    return _RECORDER.disable()


def reset_tracing() -> TraceRecorder:
    return _RECORDER.reset()


def tracing_enabled() -> bool:
    return _RECORDER.enabled


def obs_snapshot() -> dict:
    """Tracing + ledger + runtime counters + calibration staleness for the
    shared metrics registry (the serve Prometheus scrape re-exports these
    as ``obs_*`` gauges — all numeric by contract).

    The calibration gauges are the serve-side half of the calibration
    loop (obs/calibrate.py): ``calibration_loaded`` says whether the
    planner is running on fitted constants at all, ``calibration_age_s``
    / ``calibration_stale`` say whether the operator should re-run
    ``analysis --calibrate`` (age is -1 with no profile loaded)."""
    from .calibrate import active_profile
    from .counters import global_counters
    from .ledger import global_ledger
    snap = _RECORDER.snapshot()
    led = global_ledger().snapshot()
    run = global_counters().snapshot()
    prof = active_profile()
    return {"trace_enabled": snap["enabled"],
            "trace_spans": snap["spans"],
            "trace_dropped": snap["dropped"],
            "ledger_records": led["records"],
            "ledger_drift_total": led["drift_total"],
            "compiles_total": run["compiles_total"],
            "compile_seconds_total": run["compile_seconds_total"],
            "dispatches_total": run["dispatches_total"],
            "dispatch_seconds_total": run["dispatch_seconds_total"],
            "hbm_peak_bytes": run["hbm_peak_bytes"],
            "calibration_loaded": 0 if prof is None else 1,
            "calibration_age_s": -1.0 if prof is None
            else round(prof.age_s(), 3),
            "calibration_stale": 0 if prof is None or not prof.stale()
            else 1}


@contextlib.contextmanager
def request(request_id: int | None):
    """Bind a request id to the current context: every span recorded while
    inside inherits it — the serving layer's correlation contract."""
    token = _REQUEST.set(request_id)
    try:
        yield
    finally:
        _REQUEST.reset(token)


def current_request_id() -> int | None:
    return _REQUEST.get()


def note(key: str, value) -> None:
    """Attach an out-of-band observation to the nearest enclosing
    :func:`collect_notes` scope (e.g. the cache reports hit/miss to the
    service without widening its return type).  No-op outside a scope."""
    notes = _NOTES.get()
    if notes is not None:
        notes[key] = value


@contextlib.contextmanager
def collect_notes():
    """``with collect_notes() as notes: ...`` — collects every
    :func:`note` recorded by callees into ``notes`` (a dict)."""
    notes: dict = {}
    token = _NOTES.set(notes)
    try:
        yield notes
    finally:
        _NOTES.reset(token)


def key_hash(obj) -> str:
    """Short stable-within-process correlation tag for a hashable key
    (structural class keys are long tuples; traces want a label)."""
    return f"{hash(obj) & 0xFFFFFFFFFFFF:012x}"


def _dump_at_exit() -> None:
    """Write the Chrome-trace JSON to ``QUEST_TPU_TRACE_DUMP`` at process
    exit (crash included, as long as the interpreter unwinds) so a dead
    serve process still leaves its trace behind."""
    path = os.environ.get("QUEST_TPU_TRACE_DUMP")
    if not path or not _RECORDER.spans():
        return
    import json

    from .export import chrome_trace
    try:
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(chrome_trace(recorder=_RECORDER), fh)
    except OSError:
        pass


atexit.register(_dump_at_exit)
