"""quest_tpu — a TPU-native universal quantum-circuit simulation framework.

A ground-up re-design of the capability surface of QuEST (reference:
/root/reference, v3.2.0 — C99 statevector/density-matrix simulator with
OpenMP/MPI/CUDA backends) for TPU: amplitudes are (optionally sharded)
jax.Arrays, gates are fused XLA tensor contractions, distribution is
jax.sharding + GSPMD collectives over the ICI mesh, and whole circuits can be
compiled to single XLA programs via the circuit layer.

Public API: the reference's full function surface (createQureg, hadamard,
controlledNot, mixDamping, calcExpecPauliHamil, ...) plus TPU-native
extensions (precision control, mesh control, circuit compilation).
"""

import os as _os

if _os.environ.get("QUEST_TPU_DISTRIBUTED"):
    # "HOST:PORT,NUM_PROCESSES,PROCESS_ID": join a jax.distributed
    # coordinator BEFORE anything below runs a JAX computation — the
    # runtime refuses to initialize afterwards, and `python -m
    # quest_tpu.deploy` imports this package before its own main() can
    # act.  SPMD launchers (the CI deploy-selftest job, SLURM scripts)
    # set the variable; everyone else never enters this branch.
    import jax as _jax
    _addr, _n, _i = _os.environ["QUEST_TPU_DISTRIBUTED"].rsplit(",", 2)
    _jax.distributed.initialize(coordinator_address=_addr,
                                num_processes=int(_n),
                                process_id=int(_i))

from .precision import set_precision, get_precision, real_eps  # noqa: F401  (configures x64)
from .api import *  # noqa: F401,F403
from .api import __all__ as _api_all
from .api import (_amps_buffer, _hamil_buffers,  # C-shim helpers  # noqa: F401
                  _validate_create_qureg, _validate_create_diag,
                  _matrix_from_buffer)
from .circuit import (Circuit, DensityCircuit, compile_circuit,  # noqa: F401
                      apply_circuit, random_circuit, qft_circuit,
                      validate_density_operands)
from .autodiff import (Param, ParamCircuit, build as build_param_circuit,  # noqa: F401
                       adjoint_gradient_fn, expectation_fn, state_fn)
from .trajectories import (trajectory_expectation_fn,  # noqa: F401
                           trajectory_state_fn)
from .serve import (CacheOptions, CompileCache, GradResult,  # noqa: F401
                    QuESTService, ServeResult)
from .grad import (TrainingResult, sgd, training_loop)  # noqa: F401
from .deploy import (ExecutableStore, Replica, ReplicaPool, Router,  # noqa: F401
                     RouterConfig, broadcast_hot_keys, process_replica)
from .obs import (TraceRecorder, FlightRecorder, Ledger,  # noqa: F401
                  enable_tracing, disable_tracing, tracing_enabled,
                  chrome_trace, trace_report, global_ledger,
                  SLOConfig, SLOMonitor, process_shard, save_shard,
                  load_shard, merge_shards, merge_files,
                  validate_chrome_trace,
                  CalibrationProfile, run_calibration, save_profile,
                  load_profile, validate_profile, activate_calibration,
                  deactivate_calibration, active_profile, use_profile,
                  RuntimeCounters, global_counters, hbm_watermark,
                  NumericLedger, NumericRecord, global_numeric_ledger,
                  state_probe_vector, densmatr_probe_vector, ulp_band,
                  epoch_pass_probes, corruption_selftest)

__version__ = "0.1.0"
__all__ = list(_api_all) + [
    "set_precision", "get_precision", "real_eps",
    "Circuit", "DensityCircuit", "compile_circuit", "apply_circuit",
    "random_circuit", "qft_circuit", "validate_density_operands",
    "Param", "ParamCircuit", "build_param_circuit", "expectation_fn",
    "state_fn", "adjoint_gradient_fn",
    "trajectory_state_fn", "trajectory_expectation_fn",
    "QuESTService", "ServeResult", "GradResult", "CompileCache",
    "CacheOptions", "training_loop", "TrainingResult", "sgd",
    "ReplicaPool", "Replica", "Router", "RouterConfig", "ExecutableStore",
    "process_replica", "broadcast_hot_keys",
    "TraceRecorder", "FlightRecorder", "Ledger", "enable_tracing",
    "disable_tracing", "tracing_enabled", "chrome_trace", "trace_report",
    "global_ledger",
    "SLOConfig", "SLOMonitor", "process_shard", "save_shard", "load_shard",
    "merge_shards", "merge_files", "validate_chrome_trace",
    "CalibrationProfile", "run_calibration", "save_profile",
    "load_profile", "validate_profile", "activate_calibration",
    "deactivate_calibration", "active_profile", "use_profile",
    "RuntimeCounters", "global_counters", "hbm_watermark",
    "NumericLedger", "NumericRecord", "global_numeric_ledger",
    "state_probe_vector", "densmatr_probe_vector", "ulp_band",
    "epoch_pass_probes", "corruption_selftest",
]
