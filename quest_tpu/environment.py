"""Execution environment: device mesh + sharding.

The reference's ``QuESTEnv`` wraps MPI init/finalize and rank discovery
(ref: QuEST/include/QuEST.h:242-246, QuEST_cpu_distributed.c:129-160).  On TPU
the equivalent is a ``jax.sharding.Mesh`` over the chips: a single SPMD
program replaces the rank-per-process model, and "numRanks" becomes the mesh
size.  The amplitude axis of every distributed Qureg is sharded over the
mesh's single ``"amps"`` axis, which reproduces the reference's contiguous
chunk-per-rank layout (rank r owns global window [r*chunk, (r+1)*chunk)) while
letting XLA's GSPMD partitioner insert the collectives the reference hand-wrote
with MPI_Sendrecv/Allreduce/Bcast.
"""

from __future__ import annotations

import dataclasses

import jax
from jax.sharding import Mesh, NamedSharding

from . import rng
from .parallel.mesh import (AMPS_AXIS, amp_sharding,  # noqa: F401
                            make_amps_mesh, replicated_sharding)
from .validation import validate_num_ranks


def _largest_pow2_leq(x: int) -> int:
    p = 1
    while p * 2 <= x:
        p *= 2
    return p


@dataclasses.dataclass
class QuESTEnv:
    """Device mesh + seeding context (ref analogue: QuESTEnv, QuEST.h:242-246)."""

    mesh: Mesh | None
    num_ranks: int
    rank: int = 0  # single-controller SPMD: the host drives all shards

    def __post_init__(self):
        # weak registry of Quregs created under this env, so syncQuESTEnv
        # blocks exactly this env's pending work (not every live array in
        # the process)
        import weakref
        object.__setattr__(self, "_quregs", weakref.WeakSet())

    def _register(self, qureg) -> None:
        self._quregs.add(qureg)

    @property
    def sharding(self) -> NamedSharding | None:
        """Sharding for a (2, 2^n) SoA amplitude pair: re/im replicated on
        axis 0, the amplitude axis split over the mesh — reproducing the
        reference's contiguous chunk-per-rank layout."""
        if self.mesh is None or self.num_ranks == 1:
            return None
        return amp_sharding(self.mesh)

    def replicated(self) -> NamedSharding | None:
        if self.mesh is None or self.num_ranks == 1:
            return None
        return replicated_sharding(self.mesh)


def create_quest_env(num_devices: int | None = None, devices=None) -> QuESTEnv:
    """Ref analogue: createQuESTEnv (QuEST_cpu_local.c:170-180 / _distributed.c:129-160).

    Builds a 1-D mesh over the available accelerator devices.  With one device
    the mesh is omitted and everything is shard-free (the "local backend").
    ``num_devices`` may be passed to use a subset (must be a power of 2).
    """
    if devices is None:
        devices = jax.devices()
    if num_devices is None:
        num_devices = _largest_pow2_leq(len(devices))
    validate_num_ranks(num_devices, "createQuESTEnv")
    if num_devices > len(devices):
        raise ValueError(
            f"requested {num_devices} devices but only {len(devices)} available")
    devices = devices[:num_devices]
    if num_devices == 1:
        env = QuESTEnv(mesh=None, num_ranks=1)
    else:
        env = QuESTEnv(mesh=make_amps_mesh(devices), num_ranks=num_devices)
    rng.seed_quest_default()
    return env


def destroy_quest_env(env: QuESTEnv) -> None:
    """Ref analogue: destroyQuESTEnv — nothing to tear down under JAX."""


def sync_quest_env(env: QuESTEnv) -> None:
    """Ref analogue: syncQuESTEnv (MPI_Barrier).

    Blocks until every Qureg created under this env has drained its pending
    device work.  Per-device execution is in-order, so blocking on the env's
    quregs (a weak registry, not a scan of every live array in the process)
    is a complete barrier for this env's work.

    ``block_until_ready`` alone is NOT trusted here: through remote-device
    tunnels it has been observed returning early (an 83 µs return on a 2 s
    op).  The authoritative barrier is a scalar readback from every
    addressable shard — a device->host transfer cannot complete before the
    producing computation has, on any stack.  This is the same barrier the
    benchmark layer uses for its timings."""
    for q in list(getattr(env, "_quregs", ())):
        amps = getattr(q, "amps", None)
        if amps is None:
            continue
        amps.block_until_ready()
        for sh in amps.addressable_shards:
            if sh.data.size:
                float(sh.data.reshape(-1)[0])


def sync_quest_success(env: QuESTEnv, success_code: int) -> int:
    """Ref analogue: syncQuESTSuccess (Allreduce LAND) — trivial single-controller."""
    return int(success_code)


def get_environment_string(env: QuESTEnv, qureg) -> str:
    mode = "distributed" if env.num_ranks > 1 else "local"
    plat = jax.devices()[0].platform
    return (f"EXEC=TPU-SPMD/{plat} MODE={mode} NUMDEVICES={env.num_ranks} "
            f"QUBITS={qureg.num_qubits_represented}")


def report_quest_env(env: QuESTEnv) -> None:
    """Structure mirrors the reference's report (QuEST_cpu_local.c:194-205),
    describing the actual TPU/XLA execution environment."""
    from .precision import get_precision
    print("EXECUTION ENVIRONMENT:")
    print(f"Running distributed (SPMD) version on {env.num_ranks} device(s)")
    print(f"Backend platform: {jax.devices()[0].platform}")
    print(f"Precision: size of qreal is {4 * get_precision()} bytes")
