"""OpenQASM 2.0 circuit logger.

Ref analogue: QuEST/src/QuEST_qasm.{h,c} — a growable text buffer per Qureg
recording every API call as QASM or a structured comment.  A Python list of
lines replaces the realloc'd char buffer; gate labels and the header format
match the reference's output (qasm.c:38-53, :61-84) so downstream tooling
reads either."""

from __future__ import annotations

import cmath
import math

QUREG_LABEL = "q"
MESREG_LABEL = "c"
COMMENT_PREF = "//"

GATE_LABELS = {
    "sigma_x": "x",
    "sigma_y": "y",
    "sigma_z": "z",
    "t": "t",
    "s": "s",
    "hadamard": "h",
    "rotate_x": "Rx",
    "rotate_y": "Ry",
    "rotate_z": "Rz",
    "unitary": "U",
    "phase_shift": "Rz",
    "swap": "swap",
    "sqrt_swap": "sqrtswap",
}


class QASMLogger:
    """Ref analogue: QASMLogger struct (QuEST.h:62-69)."""

    def __init__(self, num_qubits: int):
        self.num_qubits = num_qubits
        self.is_logging = False
        self.lines: list[str] = []
        self._header = (f"OPENQASM 2.0;\nqreg {QUREG_LABEL}[{num_qubits}];\n"
                        f"creg {MESREG_LABEL}[{num_qubits}];\n")

    def clone(self) -> "QASMLogger":
        c = QASMLogger(self.num_qubits)
        c.is_logging = self.is_logging
        c.lines = list(self.lines)
        return c

    # --- recording ---------------------------------------------------------
    def _add(self, line: str) -> None:
        if self.is_logging:
            self.lines.append(line)

    def record_gate(self, gate: str, controls, target: int, params=()) -> None:
        if not self.is_logging:
            return
        label = GATE_LABELS.get(gate, gate)
        ctrl_pref = "c" * len(controls)
        if params:
            pstr = "(" + ",".join(_fmt_real(p) for p in params) + ")"
        else:
            pstr = ""
        qubits = [f"{QUREG_LABEL}[{c}]" for c in controls] + [f"{QUREG_LABEL}[{target}]"]
        self._add(f"{ctrl_pref}{label}{pstr} {','.join(qubits)};\n")

    def record_param_gate(self, gate: str, controls, target: int, *params) -> None:
        self.record_gate(gate, controls, target, params)

    def record_compact_unitary(self, alpha: complex, beta: complex,
                               controls, target: int) -> None:
        if not self.is_logging:
            return
        rz2, ry, rz1, _ = _zyz_from_compact(alpha, beta)
        self.record_gate("rotate_z", controls, target, (rz2,))
        self.record_gate("rotate_y", controls, target, (ry,))
        self.record_gate("rotate_z", controls, target, (rz1,))

    def record_unitary(self, u, controls, target: int) -> None:
        if not self.is_logging:
            return
        rz2, ry, rz1, phase = _zyz_from_unitary(u)
        self.record_gate("rotate_z", controls, target, (rz2,))
        self.record_gate("rotate_y", controls, target, (ry,))
        self.record_gate("rotate_z", controls, target, (rz1,))
        if abs(phase) > 1e-12 and not controls:
            self.record_comment(f"Here, the matrix had a global phase of {_fmt_real(phase)}")

    def record_measurement(self, qubit: int) -> None:
        self._add(f"measure {QUREG_LABEL}[{qubit}] -> {MESREG_LABEL}[{qubit}];\n")

    def record_init_zero(self) -> None:
        if not self.is_logging:
            return
        for q in range(self.num_qubits):
            self._add(f"reset {QUREG_LABEL}[{q}];\n")

    def record_init_plus(self) -> None:
        if not self.is_logging:
            return
        self.record_init_zero()
        for q in range(self.num_qubits):
            self._add(f"h {QUREG_LABEL}[{q}];\n")

    def record_init_classical(self, state_ind: int) -> None:
        if not self.is_logging:
            return
        self.record_init_zero()
        for q in range(self.num_qubits):
            if (state_ind >> q) & 1:
                self._add(f"x {QUREG_LABEL}[{q}];\n")

    def record_comment(self, comment: str) -> None:
        self._add(f"{COMMENT_PREF} {comment}\n")

    # --- retrieval ---------------------------------------------------------
    def recorded(self) -> str:
        return self._header + "".join(self.lines)

    def clear(self) -> None:
        self.lines = []

    def print(self) -> None:
        print(self.recorded(), end="")

    def write_to_file(self, filename: str) -> None:
        with open(filename, "w") as f:
            f.write(self.recorded())


def _fmt_real(x: float) -> str:
    return f"{float(x):g}"


def _zyz_from_compact(alpha: complex, beta: complex):
    """ZYZ Euler angles of the compact unitary [[a, -b*], [b, a*]]
    (ref analogue: getZYZRotAnglesFromComplexPair, QuEST_common.c)."""
    a, b = complex(alpha), complex(beta)
    ry = 2 * math.acos(min(1.0, abs(a)))
    rz1 = cmath.phase(a) + cmath.phase(b) if abs(b) > 1e-15 else 2 * cmath.phase(a)
    rz2 = cmath.phase(a) - cmath.phase(b) if abs(b) > 1e-15 else 0.0
    return rz2, ry, rz1, 0.0


def _zyz_from_unitary(u):
    """Factor a general 2x2 unitary as e^{iφ} Rz(rz1)·Ry(ry)·Rz(rz2)."""
    import numpy as np
    m = np.asarray(u, dtype=complex).reshape(2, 2)
    det = m[0, 0] * m[1, 1] - m[0, 1] * m[1, 0]
    phase = cmath.phase(det) / 2
    su = m * cmath.exp(-1j * phase)
    # su = [[a, -b*],[b, a*]]
    rz2, ry, rz1, _ = _zyz_from_compact(su[0, 0], su[1, 0])
    return rz2, ry, rz1, phase
