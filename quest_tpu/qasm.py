"""OpenQASM 2.0 circuit logger.

Ref analogue: QuEST/src/QuEST_qasm.{h,c} — a growable text buffer per Qureg
recording every API call as QASM or a structured comment.  A Python list of
lines replaces the realloc'd char buffer; gate labels, the U-gate ZYZ output,
and the controlled-gate global-phase fix-ups match the reference's output
(qasm.c:38-53, :195-300) so downstream tooling reads either.
"""

from __future__ import annotations

import cmath
import math

QUREG_LABEL = "q"
MESREG_LABEL = "c"
COMMENT_PREF = "//"

GATE_LABELS = {
    "sigma_x": "x",
    "sigma_y": "y",
    "sigma_z": "z",
    "t": "t",
    "s": "s",
    "hadamard": "h",
    "rotate_x": "Rx",
    "rotate_y": "Ry",
    "rotate_z": "Rz",
    "unitary": "U",
    "phase_shift": "Rz",
    "swap": "swap",
    "sqrt_swap": "sqrtswap",
}


class QASMLogger:
    """Ref analogue: QASMLogger struct (QuEST.h:62-69)."""

    def __init__(self, num_qubits: int):
        self.num_qubits = num_qubits
        self.is_logging = False
        self.lines: list[str] = []
        self._header = (f"OPENQASM 2.0;\nqreg {QUREG_LABEL}[{num_qubits}];\n"
                        f"creg {MESREG_LABEL}[{num_qubits}];\n")

    def clone(self) -> "QASMLogger":
        c = QASMLogger(self.num_qubits)
        c.is_logging = self.is_logging
        c.lines = list(self.lines)
        return c

    # --- recording ---------------------------------------------------------
    def _add(self, line: str) -> None:
        if self.is_logging:
            self.lines.append(line)

    def _gate_line(self, gate: str, controls, target: int, params=()) -> None:
        """One '{c*}label(params) q[c],..,q[t];' line — the reference's
        addGateToQASM format (qasm.c:128-176)."""
        label = GATE_LABELS.get(gate, gate)
        ctrl_pref = "c" * len(controls)
        pstr = ("(" + ",".join(_fmt_real(p) for p in params) + ")") if params else ""
        qubits = [f"{QUREG_LABEL}[{c}]" for c in controls] + [f"{QUREG_LABEL}[{target}]"]
        self._add(f"{ctrl_pref}{label}{pstr} {','.join(qubits)};\n")

    def record_gate(self, gate: str, controls, target: int, params=()) -> None:
        if not self.is_logging:
            return
        self._gate_line(gate, controls, target, params)
        # controlled phase shifts discard a global phase in QASM's Rz form;
        # the reference restores it with an uncontrolled Rz(angle/2) on the
        # target (qasm.c: qasm_recordControlledParamGate / MultiControlled...)
        if gate == "phase_shift" and controls and params:
            kind = "controlled" if len(controls) == 1 else "multicontrolled"
            self.record_comment("Restoring the discarded global phase of the "
                                f"previous {kind} phase gate")
            self._gate_line("rotate_z", (), target, (params[0] / 2.0,))

    def record_param_gate(self, gate: str, controls, target: int, *params) -> None:
        self.record_gate(gate, controls, target, params)

    def record_compact_unitary(self, alpha: complex, beta: complex,
                               controls, target: int) -> None:
        """One U(rz2, ry, rz1) gate (ref: qasm_recordCompactUnitary)."""
        if not self.is_logging:
            return
        rz2, ry, rz1 = _zyz_from_compact(alpha, beta)
        self._gate_line("unitary", controls, target, (rz2, ry, rz1))

    def record_unitary(self, u, controls, target: int) -> None:
        """U(rz2, ry, rz1); when controlled, the matrix's global phase is
        physical, so append the reference's uncontrolled-Rz fix-up
        (ref: qasm_recordControlledUnitary, qasm.c:279-300)."""
        if not self.is_logging:
            return
        alpha, beta, phase = _pair_and_phase_from_unitary(u)
        rz2, ry, rz1 = _zyz_from_compact(alpha, beta)
        self._gate_line("unitary", controls, target, (rz2, ry, rz1))
        if controls:
            self.record_comment("Restoring the discarded global phase of the "
                                "previous controlled unitary")
            self._gate_line("rotate_z", (), target, (phase,))

    def record_axis_rotation(self, angle: float, axis, controls, target: int) -> None:
        """Rotation about an arbitrary axis as a U gate
        (ref: qasm_recordAxisRotation / qasm_recordControlledAxisRotation)."""
        if not self.is_logging:
            return
        ux, uy, uz = _unit_axis(axis)
        s = math.sin(angle / 2.0)
        alpha = complex(math.cos(angle / 2.0), -s * uz)
        beta = complex(s * uy, -s * ux)
        rz2, ry, rz1 = _zyz_from_compact(alpha, beta)
        self._gate_line("unitary", controls, target, (rz2, ry, rz1))

    def record_measurement(self, qubit: int) -> None:
        self._add(f"measure {QUREG_LABEL}[{qubit}] -> {MESREG_LABEL}[{qubit}];\n")

    def record_init_zero(self) -> None:
        if not self.is_logging:
            return
        for q in range(self.num_qubits):
            self._add(f"reset {QUREG_LABEL}[{q}];\n")

    def record_init_plus(self) -> None:
        if not self.is_logging:
            return
        self.record_init_zero()
        for q in range(self.num_qubits):
            self._add(f"h {QUREG_LABEL}[{q}];\n")

    def record_init_classical(self, state_ind: int) -> None:
        if not self.is_logging:
            return
        self.record_init_zero()
        for q in range(self.num_qubits):
            if (state_ind >> q) & 1:
                self._add(f"x {QUREG_LABEL}[{q}];\n")

    def record_comment(self, comment: str) -> None:
        self._add(f"{COMMENT_PREF} {comment}\n")

    # --- retrieval ---------------------------------------------------------
    def recorded(self) -> str:
        return self._header + "".join(self.lines)

    def clear(self) -> None:
        self.lines = []

    def print(self) -> None:
        print(self.recorded(), end="")

    def write_to_file(self, filename: str) -> None:
        with open(filename, "w") as f:
            f.write(self.recorded())


def _fmt_real(x: float) -> str:
    return f"{float(x):.14g}"


def _unit_axis(axis):
    ux, uy, uz = (float(a) for a in axis)
    mag = math.sqrt(ux * ux + uy * uy + uz * uz)
    return ux / mag, uy / mag, uz / mag


def _zyz_from_compact(alpha: complex, beta: complex):
    """ZYZ Euler angles (rz2, ry, rz1) with
    U(α, β) = Rz(rz2)·Ry(ry)·Rz(rz1) under Rz(t) = diag(e^{-it/2}, e^{it/2}):
    ry = 2 acos|α|, rz2 = -arg(α)+arg(β), rz1 = -arg(α)-arg(β)
    (ref analogue: getZYZRotAnglesFromComplexPair, QuEST_common.c:124-133)."""
    a, b = complex(alpha), complex(beta)
    ry = 2.0 * math.acos(min(1.0, abs(a)))
    alpha_phase = math.atan2(a.imag, a.real)
    beta_phase = math.atan2(b.imag, b.real)
    rz2 = -alpha_phase + beta_phase
    rz1 = -alpha_phase - beta_phase
    return rz2, ry, rz1


def _pair_and_phase_from_unitary(u):
    """Split a 2x2 unitary into exp(iφ)·U(α, β) with φ the mean phase of the
    diagonal (ref analogue: getComplexPairAndPhaseFromUnitary,
    QuEST_common.c:136-150)."""
    import numpy as np
    m = np.asarray(u, dtype=complex).reshape(2, 2)
    phase = (cmath.phase(m[0, 0]) + cmath.phase(m[1, 1])) / 2.0
    rot = cmath.exp(-1j * phase)
    return m[0, 0] * rot, m[1, 0] * rot, phase
