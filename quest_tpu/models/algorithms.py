"""Canonical quantum-algorithm circuit builders.

All return :class:`quest_tpu.circuit.Circuit` objects that compile to single
fused XLA programs via ``compile_circuit`` / ``apply_circuit``.
"""

from __future__ import annotations

import math

import numpy as np

from ..circuit import Circuit, qft_circuit, random_circuit  # noqa: F401


def ghz_circuit(num_qubits: int) -> Circuit:
    """|0..0> + |1..1> (unnormalised notation): H then a CNOT chain."""
    c = Circuit(num_qubits)
    c.h(0)
    for q in range(num_qubits - 1):
        c.cnot(q, q + 1)
    return c


def bernstein_vazirani_circuit(num_qubits: int, secret: int) -> Circuit:
    """One-query secret-string recovery (ref analogue:
    examples/bernstein_vazirani_circuit.c — qubit 0 is the ancilla)."""
    c = Circuit(num_qubits)
    c.x(0)
    bits = secret
    for qb in range(1, num_qubits):
        if bits & 1:
            c.cnot(0, qb)
        bits >>= 1
    return c


def grover_circuit(num_qubits: int, marked: int, iterations: int | None = None) -> Circuit:
    """Grover search for basis state ``marked`` on n qubits."""
    n = num_qubits
    if iterations is None:
        iterations = max(1, int(round(math.pi / 4 * math.sqrt(2 ** n))))
    c = Circuit(n)
    for q in range(n):
        c.h(q)
    for _ in range(iterations):
        # oracle: phase-flip |marked> — Z on qubit n-1 controlled on the rest
        # matching the marked bit pattern
        controls = tuple(range(n - 1))
        states = tuple((marked >> q) & 1 for q in range(n - 1))
        if (marked >> (n - 1)) & 1:
            c.ops.append(_controlled_z(n - 1, controls, states))
        else:
            c.x(n - 1)
            c.ops.append(_controlled_z(n - 1, controls, states))
            c.x(n - 1)
        # diffusion: H X (multi-controlled Z) X H
        for q in range(n):
            c.h(q)
        for q in range(n):
            c.x(q)
        c.z(n - 1, controls=tuple(range(n - 1)))
        for q in range(n):
            c.x(q)
        for q in range(n):
            c.h(q)
    return c


def _controlled_z(target: int, controls, states):
    from ..circuit import GateOp
    dp = np.stack([np.array([1.0, -1.0]), np.zeros(2)])
    return GateOp("diagonal", (target,), tuple(controls), tuple(states),
                  tuple(dp.ravel()), dp.shape)


def phase_estimation_circuit(num_eval_qubits: int, phase: float) -> Circuit:
    """Estimate the eigenphase of a Z-rotation eigenstate: ``phase`` in [0,1)
    appears on the evaluation register after an inverse QFT.

    Layout: qubits [0, m) = evaluation register, qubit m = eigenstate |1>."""
    m = num_eval_qubits
    c = Circuit(m + 1)
    c.x(m)  # eigenstate |1> of the phase gate
    for q in range(m):
        c.h(q)
    for q in range(m):
        # controlled-U^(2^q), U = diag(1, e^{2 pi i phase})
        c.phase_shift(m, 2 * math.pi * phase * (1 << q), controls=(q,))
    # inverse QFT on the evaluation register (reverse the QFT gate sequence,
    # conjugating the phases)
    fwd = qft_circuit(m)
    inv_ops = []
    for op in reversed(fwd.ops):
        if op.kind == "diagonal":
            p = np.asarray(op.matrix, dtype=np.float64).reshape(op.shape)
            conj = np.stack([p[0], -p[1]])
            from ..circuit import GateOp
            inv_ops.append(GateOp("diagonal", op.targets, op.controls,
                                  op.control_states, tuple(conj.ravel()), op.shape))
        elif op.kind == "matrix":
            p = np.asarray(op.matrix, dtype=np.float64).reshape(op.shape)
            # unitary inverse = conjugate transpose
            inv = np.stack([p[0].T, -p[1].T])
            from ..circuit import GateOp
            inv_ops.append(GateOp("matrix", op.targets, op.controls,
                                  op.control_states, tuple(inv.ravel()), op.shape))
        else:
            inv_ops.append(op)  # swap / x are self-inverse
    # shift eval-register ops are already on qubits [0, m)
    c.ops.extend(inv_ops)
    return c


def trotter_circuit(hamil, time: float, order: int, reps: int) -> Circuit:
    """Symmetrized Suzuki-Trotter circuit of a PauliHamil as a compiled
    Circuit (the fused-program twin of applyTrotterCircuit, which follows the
    reference's recursion — QuEST_common.c:698-780)."""
    from ..validation import validate_trotter_params

    validate_trotter_params(order, reps, "trotter_circuit")
    n = hamil.num_qubits
    c = Circuit(n)

    def add_exp_term(coeff, codes, t):
        # exp(-i coeff t P): basis-change each qubit to Z, multiRotateZ, undo
        targets = [q for q in range(n) if codes[q] != 0]
        if not targets:
            # global phase e^{-i coeff t}: fold into a 1-qubit diagonal
            ph = np.exp(-1j * coeff * t)
            c._diag([ph, ph], (0,))
            return
        h = np.array([[1, 1], [1, -1]]) / math.sqrt(2)
        sdg_h = np.array([[1, -1j], [1, 1j]]) / math.sqrt(2)  # Y -> Z basis
        for q in targets:
            if codes[q] == 1:
                c._mat(h, (q,))
            elif codes[q] == 2:
                c._mat(sdg_h, (q,))
        # exp(-i (coeff t) Z..Z) = multiRotateZ with angle 2*coeff*t
        angle = 2.0 * coeff * t
        dim = 1 << len(targets)
        diag = np.array([np.exp(-1j * angle / 2 * (1 - 2 * (bin(i).count("1") % 2)))
                         for i in range(dim)])
        c._diag(diag, tuple(targets))
        for q in targets:
            if codes[q] == 1:
                c._mat(h, (q,))
            elif codes[q] == 2:
                c._mat(sdg_h.conj().T, (q,))

    def trotterize(t, ord_):
        terms = list(range(hamil.num_sum_terms))
        if ord_ == 1:
            for k in terms:
                add_exp_term(hamil.term_coeffs[k], hamil.pauli_codes[k], t)
        elif ord_ == 2:
            for k in terms:
                add_exp_term(hamil.term_coeffs[k], hamil.pauli_codes[k], t / 2)
            for k in reversed(terms):
                add_exp_term(hamil.term_coeffs[k], hamil.pauli_codes[k], t / 2)
        else:
            # Suzuki recursion (ref: QuEST_common.c:744-762)
            p = 1.0 / (4 - 4 ** (1.0 / (ord_ - 1)))
            for _ in range(2):
                trotterize(p * t, ord_ - 2)
            trotterize((1 - 4 * p) * t, ord_ - 2)
            for _ in range(2):
                trotterize(p * t, ord_ - 2)

    for _ in range(reps):
        trotterize(time / reps, order)
    return c
