"""Algorithm library: canonical circuit families as :class:`quest_tpu.Circuit`
builders.  The reference ships these only as examples (examples/*.c); here
they are first-class, compiled workloads (and the benchmark configs of
BASELINE.md)."""

from .algorithms import (bernstein_vazirani_circuit, ghz_circuit,  # noqa: F401
                         grover_circuit, phase_estimation_circuit,
                         qft_circuit, random_circuit, trotter_circuit)
from .variational import (hardware_efficient_ansatz, maxcut_hamiltonian,  # noqa: F401
                          pauli_sum_matrix, qaoa_maxcut_circuit,
                          tfim_hamiltonian)
