"""Variational circuit families and Hamiltonian builders (VQE / QAOA).

No reference analogue: these are the workloads the differentiable layer
(quest_tpu/autodiff.py) exists for.  Everything returns either a
:class:`~quest_tpu.autodiff.ParamCircuit` (trainable structure) or a
:class:`~quest_tpu.matrices.PauliHamil` (observable), so objectives compose
as ``expectation_fn(circuit, hamil)`` → jax.value_and_grad / optax.
"""

from __future__ import annotations

import numpy as np

from ..autodiff import ParamCircuit
from ..matrices import PauliHamil

__all__ = ["hardware_efficient_ansatz", "qaoa_maxcut_circuit",
           "maxcut_hamiltonian", "tfim_hamiltonian", "pauli_sum_matrix"]

_I, _X, _Y, _Z = 0, 1, 2, 3


def hardware_efficient_ansatz(num_qubits: int, layers: int,
                              final_rotations: bool = True) -> ParamCircuit:
    """The standard hardware-efficient VQE ansatz: per-layer Ry+Rz rotations
    on every qubit followed by a brickwork CZ entangler, with an optional
    closing rotation layer.  Parameters: ``(layers + final) * 2 * n``."""
    pc = ParamCircuit(num_qubits)
    for layer in range(layers):
        for q in range(num_qubits):
            pc.ry(q, pc.param())
            pc.rz(q, pc.param())
        for q in range(layer % 2, num_qubits - 1, 2):
            pc.cz(q, q + 1)
    if final_rotations:
        for q in range(num_qubits):
            pc.ry(q, pc.param())
            pc.rz(q, pc.param())
    return pc


def qaoa_maxcut_circuit(num_qubits: int, edges, p: int) -> ParamCircuit:
    """Depth-``p`` QAOA for MaxCut: |+…+⟩, then alternating cost layers
    exp(-iγ Z_a Z_b) per edge and mixer layers exp(-iβ X_q).  Parameter
    layout: [γ_1, β_1, …, γ_p, β_p] (2p parameters; each γ/β is shared by
    its whole layer via the Param affine transform)."""
    pc = ParamCircuit(num_qubits)
    for q in range(num_qubits):
        pc.h(q)
    for _ in range(p):
        gamma = pc.param()
        for a, b in edges:
            # exp(-iγ ZZ) = multiRotateZ(2γ) on (a, b)
            pc.multi_rotate_z((a, b), 2.0 * gamma)
        beta = pc.param()
        for q in range(num_qubits):
            pc.rx(q, 2.0 * beta)
    return pc


def maxcut_hamiltonian(num_qubits: int, edges) -> PauliHamil:
    """C = Σ_(a,b) (Z_a Z_b − 1)/2 — minimised at −(max cut size), so the
    QAOA objective is a plain energy minimisation."""
    edges = list(edges)
    terms = len(edges) + 1
    h = PauliHamil(num_qubits, terms)
    for t, (a, b) in enumerate(edges):
        h.pauli_codes[t, a] = _Z
        h.pauli_codes[t, b] = _Z
        h.term_coeffs[t] = 0.5
    h.term_coeffs[-1] = -0.5 * len(edges)  # identity term (all codes 0)
    return h


def tfim_hamiltonian(num_qubits: int, field: float = 1.0,
                     coupling: float = 1.0, periodic: bool = False) -> PauliHamil:
    """Transverse-field Ising chain H = −J Σ Z_i Z_{i+1} − h Σ X_i — the
    standard VQE testbed with a nontrivial entangled ground state."""
    n = num_qubits
    bonds = [(i, (i + 1) % n) for i in range(n if periodic and n > 2 else n - 1)]
    h = PauliHamil(n, len(bonds) + n)
    for t, (a, b) in enumerate(bonds):
        h.pauli_codes[t, a] = _Z
        h.pauli_codes[t, b] = _Z
        h.term_coeffs[t] = -coupling
    for q in range(n):
        h.pauli_codes[len(bonds) + q, q] = _X
        h.term_coeffs[len(bonds) + q] = -field
    return h


_P1 = {_I: np.eye(2), _X: np.array([[0, 1], [1, 0]], dtype=complex),
       _Y: np.array([[0, -1j], [1j, 0]]), _Z: np.diag([1.0, -1.0]).astype(complex)}


def pauli_sum_matrix(hamil: PauliHamil) -> np.ndarray:
    """Dense 2^n × 2^n matrix of a PauliHamil (host-side; for exact
    diagonalisation baselines in tests/examples).  Qubit 0 is the
    least-significant index bit, matching the amplitude ordering."""
    dim = 1 << hamil.num_qubits
    out = np.zeros((dim, dim), dtype=complex)
    for t in range(hamil.num_sum_terms):
        m = np.eye(1, dtype=complex)
        for q in range(hamil.num_qubits):  # qubit 0 least significant: kron from the top
            m = np.kron(_P1[int(hamil.pauli_codes[t, q])], m)
        out += hamil.term_coeffs[t] * m
    return out
