"""ctypes binding to the native gate-fusion engine (native/fusion.cpp).

The shared library is built on first use with the system toolchain and cached
under ``native/build/``.  If no compiler is available the fusion API degrades
to a no-op (circuits still run, just without native pre-fusion).
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess

import numpy as np

_KINDS = {"matrix": 0, "diagonal": 1, "x": 2, "y": 3, "y*": 4, "swap": 5}
_KIND_NAMES = {v: k for k, v in _KINDS.items()}

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(_ROOT, "native", "fusion.cpp")
_BUILD_DIR = os.path.join(_ROOT, "native", "build")
_LIB = os.path.join(_BUILD_DIR, "libquest_fusion.so")

_lib = None
_load_failed = False


def _ensure_lib():
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    try:
        if not os.path.exists(_LIB) or (os.path.getmtime(_LIB)
                                        < os.path.getmtime(_SRC)):
            os.makedirs(_BUILD_DIR, exist_ok=True)
            subprocess.run(["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
                            _SRC, "-o", _LIB], check=True, capture_output=True)
        lib = ctypes.CDLL(_LIB)
        lib.quest_fuse_circuit.restype = ctypes.POINTER(ctypes.c_uint8)
        lib.quest_fuse_circuit.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                           ctypes.POINTER(ctypes.c_int64),
                                           ctypes.c_int32]
        lib.quest_free_buffer.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
        assert lib.quest_fusion_abi_version() == 3
        _lib = lib
    except Exception:
        _load_failed = True
    return _lib


def _pack(ops) -> bytes:
    parts = [struct.pack("<q", len(ops))]
    for op in ops:
        kind = _KINDS[op.kind]
        targets = np.asarray(op.targets, dtype=np.int32)
        controls = np.asarray(op.controls, dtype=np.int32)
        states = np.asarray(op.control_states if op.control_states
                            else (1,) * len(op.controls), dtype=np.int32)
        payload = (np.asarray(op.matrix, dtype=np.float64)
                   if op.matrix is not None else np.zeros(0))
        parts.append(struct.pack("<iiiq", kind, targets.size, controls.size,
                                 payload.size))
        parts.append(targets.tobytes())
        parts.append(controls.tobytes())
        parts.append(states.tobytes())
        parts.append(payload.tobytes())
    return b"".join(parts)


def _unpack(buf: bytes):
    from .circuit import GateOp

    ops = []
    (n,) = struct.unpack_from("<q", buf, 0)
    off = 8
    for _ in range(n):
        kind, nt, nc, pl = struct.unpack_from("<iiiq", buf, off)
        off += 20
        targets = np.frombuffer(buf, np.int32, nt, off); off += 4 * nt
        controls = np.frombuffer(buf, np.int32, nc, off); off += 4 * nc
        states = np.frombuffer(buf, np.int32, nc, off); off += 4 * nc
        payload = np.frombuffer(buf, np.float64, pl, off); off += 8 * pl
        name = _KIND_NAMES[kind]
        if name == "matrix":
            d = int(round((pl // 2) ** 0.5))
            shape = (2, d, d)
        elif name == "diagonal":
            shape = (2, pl // 2)
        else:
            shape = None
        ops.append(GateOp(name, tuple(int(t) for t in targets),
                          tuple(int(c) for c in controls),
                          tuple(int(s) for s in states) if nc else (),
                          tuple(payload) if pl else None, shape))
    return ops


def _fuse_segment(ops, lib, max_pack: int):
    packed = _pack(ops)
    out_len = ctypes.c_int64()
    ptr = lib.quest_fuse_circuit(packed, len(packed), ctypes.byref(out_len),
                                 max_pack)
    try:
        data = ctypes.string_at(ptr, out_len.value)
    finally:
        lib.quest_free_buffer(ptr)
    return _unpack(data)


def fuse_ops(ops, max_pack: int = 7):
    """Run the native fusion pass over a GateOp list; returns the (possibly
    shorter) equivalent list, or the input unchanged if the library is
    unavailable.  ``max_pack`` is the kron-packing width: 7 qubits = 128
    basis states = one f32 MXU tile (pass 1 to disable packing).

    Kinds outside the fusion ABI (e.g. wide ``mrz`` parity rotations, whose
    payload is an angle, not a matrix) act as barriers: the runs between
    them fuse independently and the op itself passes through untouched."""
    lib = _ensure_lib()
    if lib is None or not ops:
        return list(ops)
    out: list = []
    seg: list = []
    for op in ops:
        if op.kind in _KINDS:
            seg.append(op)
        else:
            if seg:
                out.extend(_fuse_segment(seg, lib, max_pack))
                seg = []
            out.append(op)
    if seg:
        out.extend(_fuse_segment(seg, lib, max_pack))
    return out


def available() -> bool:
    return _ensure_lib() is not None
