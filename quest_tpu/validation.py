"""Input validation for the public API.

Mirrors the check surface of the reference's validation layer
(ref: QuEST/src/QuEST_validation.c:32-165 error codes, :200+ guards), but as
idiomatic Python exceptions instead of the reference's weak-symbol
``invalidQuESTInputError``/exit(1) mechanism: every guard raises
``QuESTError``, which tests catch directly (the reference needed a linker
trick to make its C errors catchable from C++ tests; an exception type is the
native equivalent).
"""

from __future__ import annotations

import math

import numpy as np


class QuESTError(ValueError):
    """Raised for any invalid user input to the API."""

    def __init__(self, code: str, message: str, func: str | None = None):
        self.code = code
        self.func = func
        self.message = message  # un-prefixed text (the C shim's errMsg)
        prefix = f"{func}: " if func else ""
        super().__init__(prefix + message)


class ErrorCode:
    """Symbolic error codes (subset of ref QuEST_validation.c:32-98 in use)."""
    INVALID_NUM_RANKS = "E_INVALID_NUM_RANKS"
    INVALID_NUM_CREATE_QUBITS = "E_INVALID_NUM_CREATE_QUBITS"
    INVALID_TARGET_QUBIT = "E_INVALID_TARGET_QUBIT"
    INVALID_CONTROL_QUBIT = "E_INVALID_CONTROL_QUBIT"
    INVALID_QUBIT_INDEX = "E_INVALID_QUBIT_INDEX"
    INVALID_STATE_INDEX = "E_INVALID_STATE_INDEX"
    INVALID_AMP_INDEX = "E_INVALID_AMP_INDEX"
    INVALID_ELEM_INDEX = "E_INVALID_ELEM_INDEX"
    INVALID_NUM_AMPS = "E_INVALID_NUM_AMPS"
    INVALID_NUM_ELEMS = "E_INVALID_NUM_ELEMS"
    INVALID_OFFSET_NUM_AMPS = "E_INVALID_OFFSET_NUM_AMPS_QUREG"
    INVALID_OFFSET_NUM_ELEMS = "E_INVALID_OFFSET_NUM_ELEMS_DIAG"
    TARGET_IS_CONTROL = "E_TARGET_IS_CONTROL"
    TARGET_IN_CONTROLS = "E_TARGET_IN_CONTROLS"
    CONTROL_TARGET_COLLISION = "E_CONTROL_TARGET_COLLISION"
    QUBITS_NOT_UNIQUE = "E_QUBITS_NOT_UNIQUE"
    TARGETS_NOT_UNIQUE = "E_TARGETS_NOT_UNIQUE"
    CONTROLS_NOT_UNIQUE = "E_CONTROLS_NOT_UNIQUE"
    INVALID_NUM_QUBITS = "E_INVALID_NUM_QUBITS"
    INVALID_NUM_TARGETS = "E_INVALID_NUM_TARGETS"
    INVALID_NUM_CONTROLS = "E_INVALID_NUM_CONTROLS"
    NON_UNITARY_MATRIX = "E_NON_UNITARY_MATRIX"
    NON_UNITARY_COMPLEX_PAIR = "E_NON_UNITARY_COMPLEX_PAIR"
    ZERO_VECTOR = "E_ZERO_VECTOR"
    SYS_TOO_BIG_TO_PRINT = "E_SYS_TOO_BIG_TO_PRINT"
    COLLAPSE_STATE_ZERO_PROB = "E_COLLAPSE_STATE_ZERO_PROB"
    INVALID_QUBIT_OUTCOME = "E_INVALID_QUBIT_OUTCOME"
    CANNOT_OPEN_FILE = "E_CANNOT_OPEN_FILE"
    SECOND_ARG_MUST_BE_STATEVEC = "E_SECOND_ARG_MUST_BE_STATEVEC"
    MISMATCHING_QUREG_DIMENSIONS = "E_MISMATCHING_QUREG_DIMENSIONS"
    MISMATCHING_QUREG_TYPES = "E_MISMATCHING_QUREG_TYPES"
    DEFINED_ONLY_FOR_STATEVECS = "E_DEFINED_ONLY_FOR_STATEVECS"
    DEFINED_ONLY_FOR_DENSMATRS = "E_DEFINED_ONLY_FOR_DENSMATRS"
    INVALID_PROB = "E_INVALID_PROB"
    UNNORM_PROBS = "E_UNNORM_PROBS"
    INVALID_ONE_QUBIT_DEPHASE_PROB = "E_INVALID_ONE_QUBIT_DEPHASE_PROB"
    INVALID_TWO_QUBIT_DEPHASE_PROB = "E_INVALID_TWO_QUBIT_DEPHASE_PROB"
    INVALID_ONE_QUBIT_DEPOL_PROB = "E_INVALID_ONE_QUBIT_DEPOL_PROB"
    INVALID_TWO_QUBIT_DEPOL_PROB = "E_INVALID_TWO_QUBIT_DEPOL_PROB"
    INVALID_ONE_QUBIT_PAULI_PROBS = "E_INVALID_ONE_QUBIT_PAULI_PROBS"
    INVALID_CONTROLS_BIT_STATE = "E_INVALID_CONTROLS_BIT_STATE"
    MISMATCHING_NUM_CONTROL_STATES = "E_MISMATCHING_NUM_CONTROL_STATES"
    INVALID_PAULI_CODE = "E_INVALID_PAULI_CODE"
    MISMATCHING_NUM_PAULI_CODES = "E_MISMATCHING_NUM_PAULI_CODES"
    INVALID_NUM_SUM_TERMS = "E_INVALID_NUM_SUM_TERMS"
    CANNOT_FIT_MULTI_QUBIT_MATRIX = "E_CANNOT_FIT_MULTI_QUBIT_MATRIX"
    INVALID_UNITARY_SIZE = "E_INVALID_UNITARY_SIZE"
    COMPLEX_MATRIX_NOT_INIT = "E_COMPLEX_MATRIX_NOT_INIT"
    INVALID_NUM_ONE_QUBIT_KRAUS_OPS = "E_INVALID_NUM_ONE_QUBIT_KRAUS_OPS"
    INVALID_NUM_TWO_QUBIT_KRAUS_OPS = "E_INVALID_NUM_TWO_QUBIT_KRAUS_OPS"
    INVALID_NUM_N_QUBIT_KRAUS_OPS = "E_INVALID_NUM_N_QUBIT_KRAUS_OPS"
    INVALID_KRAUS_OPS = "E_INVALID_KRAUS_OPS"
    MISMATCHING_NUM_TARGS_KRAUS_SIZE = "E_MISMATCHING_NUM_TARGS_KRAUS_SIZE"
    DISTRIB_QUREG_TOO_SMALL = "E_DISTRIB_QUREG_TOO_SMALL"
    DISTRIB_DIAG_OP_TOO_SMALL = "E_DISTRIB_DIAG_OP_TOO_SMALL"
    NUM_AMPS_EXCEED_TYPE = "E_NUM_AMPS_EXCEED_TYPE"
    INVALID_PAULI_HAMIL_PARAMS = "E_INVALID_PAULI_HAMIL_PARAMS"
    INVALID_PAULI_HAMIL_FILE_PARAMS = "E_INVALID_PAULI_HAMIL_FILE_PARAMS"
    CANNOT_PARSE_PAULI_HAMIL_FILE_COEFF = "E_CANNOT_PARSE_PAULI_HAMIL_FILE_COEFF"
    CANNOT_PARSE_PAULI_HAMIL_FILE_PAULI = "E_CANNOT_PARSE_PAULI_HAMIL_FILE_PAULI"
    INVALID_PAULI_HAMIL_FILE_PAULI_CODE = "E_INVALID_PAULI_HAMIL_FILE_PAULI_CODE"
    MISMATCHING_PAULI_HAMIL_QUREG_NUM_QUBITS = "E_MISMATCHING_PAULI_HAMIL_QUREG_NUM_QUBITS"
    INVALID_TROTTER_ORDER = "E_INVALID_TROTTER_ORDER"
    INVALID_TROTTER_REPS = "E_INVALID_TROTTER_REPS"
    MISMATCHING_QUREG_DIAGONAL_OP_SIZE = "E_MISMATCHING_QUREG_DIAGONAL_OP_SIZE"
    DIAGONAL_OP_NOT_INITIALISED = "E_DIAGONAL_OP_NOT_INITIALISED"
    PLANE_ONLY_1Q = "E_PLANE_ONLY_1Q"
    PLANE_ONLY = "E_PLANE_ONLY"
    QUREG_NOT_INITIALISED = "E_QUREG_NOT_INITIALISED"
    INVALID_SCHEDULE_OPTION = "E_INVALID_SCHEDULE_OPTION"
    # serving layer (quest_tpu/serve) — no reference analogue: the C API has
    # no request queue; these are the backpressure/deadline contract of
    # QuESTService (docs/SERVING.md)
    QUEUE_FULL = "E_QUEUE_FULL"
    DEADLINE_EXCEEDED = "E_DEADLINE_EXCEEDED"
    SERVICE_SHUTDOWN = "E_SERVICE_SHUTDOWN"
    # gradient serving (quest_tpu/grad) — the adjoint method's admission
    # contract: reverse gate replay uncomputes states by EXACT inverses,
    # so the circuit must be unitary and the register a statevector
    # (docs/SERVING.md "Gradient serving")
    GRADIENT_NOT_UNITARY = "E_GRADIENT_NOT_UNITARY"
    GRADIENT_DENSITY_MODE = "E_GRADIENT_DENSITY_MODE"


# Human-readable messages; tests substring-match these, mirroring the
# reference test suite's REQUIRE_THROWS_WITH pattern.
MESSAGES = {
    ErrorCode.INVALID_NUM_RANKS: "Invalid number of nodes. Distributed simulation can only make use of a power-of-2 number of node.",
    ErrorCode.INVALID_NUM_CREATE_QUBITS: "Invalid number of qubits. Must create >0.",
    ErrorCode.INVALID_QUBIT_INDEX: "Invalid qubit index. Must be >=0 and <numQubits.",
    ErrorCode.INVALID_TARGET_QUBIT: "Invalid target qubit. Must be >=0 and <numQubits.",
    ErrorCode.INVALID_CONTROL_QUBIT: "Invalid control qubit. Must be >=0 and <numQubits.",
    ErrorCode.INVALID_STATE_INDEX: "Invalid state index. Must be >=0 and <2^numQubits.",
    ErrorCode.INVALID_AMP_INDEX: "Invalid amplitude index. Must be >=0 and <2^numQubits.",
    ErrorCode.INVALID_ELEM_INDEX: "Invalid element index. Must be >=0 and <2^numQubits.",
    ErrorCode.INVALID_NUM_AMPS: "Invalid number of amplitudes. Must be >=0 and <=2^numQubits.",
    ErrorCode.INVALID_NUM_ELEMS: "Invalid number of elements. Must be >=0 and <=2^numQubits.",
    ErrorCode.INVALID_OFFSET_NUM_AMPS: "More amplitudes given than exist in the statevector from the given starting index.",
    ErrorCode.INVALID_OFFSET_NUM_ELEMS: "More elements given than exist in the diagonal operator from the given starting index.",
    ErrorCode.TARGET_IS_CONTROL: "Control qubit cannot equal target qubit.",
    ErrorCode.TARGET_IN_CONTROLS: "Control qubits cannot include target qubit.",
    ErrorCode.CONTROL_TARGET_COLLISION: "Control and target qubits must be disjoint.",
    ErrorCode.QUBITS_NOT_UNIQUE: "The qubits must be unique.",
    ErrorCode.TARGETS_NOT_UNIQUE: "The target qubits must be unique.",
    ErrorCode.CONTROLS_NOT_UNIQUE: "The control qubits should be unique.",
    ErrorCode.INVALID_NUM_QUBITS: "Invalid number of qubits. Must be >0 and <=numQubits.",
    ErrorCode.INVALID_NUM_TARGETS: "Invalid number of target qubits. Must be >0 and <=numQubits.",
    ErrorCode.INVALID_NUM_CONTROLS: "Invalid number of control qubits. Must be >0 and <numQubits.",
    ErrorCode.NON_UNITARY_MATRIX: "Matrix is not unitary.",
    ErrorCode.NON_UNITARY_COMPLEX_PAIR: "Compact matrix formed by given complex numbers is not unitary.",
    ErrorCode.ZERO_VECTOR: "Invalid axis vector. Must be non-zero.",
    ErrorCode.SYS_TOO_BIG_TO_PRINT: "Invalid system size. Cannot print output for systems greater than 5 qubits.",
    ErrorCode.COLLAPSE_STATE_ZERO_PROB: "Can't collapse to state with zero probability.",
    ErrorCode.INVALID_QUBIT_OUTCOME: "Invalid measurement outcome -- must be either 0 or 1.",
    ErrorCode.CANNOT_OPEN_FILE: "Could not open file ({}).",
    ErrorCode.SECOND_ARG_MUST_BE_STATEVEC: "Second argument must be a state-vector.",
    ErrorCode.MISMATCHING_QUREG_DIMENSIONS: "Dimensions of the qubit registers don't match.",
    ErrorCode.MISMATCHING_QUREG_TYPES: "Registers must both be state-vectors or both be density matrices.",
    ErrorCode.DEFINED_ONLY_FOR_STATEVECS: "Operation valid only for state-vectors.",
    ErrorCode.DEFINED_ONLY_FOR_DENSMATRS: "Operation valid only for density matrices.",
    ErrorCode.INVALID_PROB: "Probabilities must be in [0, 1].",
    ErrorCode.UNNORM_PROBS: "Probabilities must sum to ~1.",
    ErrorCode.INVALID_ONE_QUBIT_DEPHASE_PROB: "The probability of a single qubit dephase error cannot exceed 1/2, which maximally mixes.",
    ErrorCode.INVALID_TWO_QUBIT_DEPHASE_PROB: "The probability of a two-qubit qubit dephase error cannot exceed 3/4, which maximally mixes.",
    ErrorCode.INVALID_ONE_QUBIT_DEPOL_PROB: "The probability of a single qubit depolarising error cannot exceed 3/4, which maximally mixes.",
    ErrorCode.INVALID_TWO_QUBIT_DEPOL_PROB: "The probability of a two-qubit depolarising error cannot exceed 15/16, which maximally mixes.",
    ErrorCode.INVALID_ONE_QUBIT_PAULI_PROBS: "The probability of any X, Y or Z error cannot exceed the probability of no error.",
    ErrorCode.INVALID_CONTROLS_BIT_STATE: "The state of the control qubits must be a bit sequence (0s and 1s).",
    ErrorCode.MISMATCHING_NUM_CONTROL_STATES: "The number of control states must match the number of control qubits.",
    ErrorCode.INVALID_PAULI_CODE: "Invalid Pauli code. Codes must be 0 (or PAULI_I), 1 (PAULI_X), 2 (PAULI_Y) or 3 (PAULI_Z) to indicate the identity, X, Y and Z operators respectively.",
    ErrorCode.MISMATCHING_NUM_PAULI_CODES: "The number of Pauli codes must match the number of target qubits.",
    ErrorCode.INVALID_NUM_SUM_TERMS: "Invalid number of terms in the Pauli sum. The number of terms must be >0.",
    ErrorCode.CANNOT_FIT_MULTI_QUBIT_MATRIX: "The specified matrix targets too many qubits; the batches of amplitudes to modify cannot all fit in a single distributed node's memory.",
    ErrorCode.INVALID_UNITARY_SIZE: "The matrix size does not match the number of target qubits.",
    ErrorCode.COMPLEX_MATRIX_NOT_INIT: "The ComplexMatrixN was not successfully created (possibly insufficient memory available).",
    ErrorCode.INVALID_NUM_ONE_QUBIT_KRAUS_OPS: "At least 1 and at most 4 single qubit Kraus operators may be specified.",
    ErrorCode.INVALID_NUM_TWO_QUBIT_KRAUS_OPS: "At least 1 and at most 16 two-qubit Kraus operators may be specified.",
    ErrorCode.INVALID_NUM_N_QUBIT_KRAUS_OPS: "At least 1 and at most 4*N^2 of N-qubit Kraus operators may be specified.",
    ErrorCode.INVALID_KRAUS_OPS: "The specified Kraus map is not a completely positive, trace preserving map.",
    ErrorCode.MISMATCHING_NUM_TARGS_KRAUS_SIZE: "Every Kraus operator must be of the same number of qubits as the number of targets.",
    ErrorCode.DISTRIB_QUREG_TOO_SMALL: "Too few qubits. The created qureg must have at least one amplitude per node used in distributed simulation.",
    ErrorCode.DISTRIB_DIAG_OP_TOO_SMALL: "Too few qubits. The created DiagonalOp must contain at least one element per node used in distributed simulation.",
    ErrorCode.NUM_AMPS_EXCEED_TYPE: "Too many qubits (max of log2(SIZE_MAX)). Cannot store the number of amplitudes per-node in the size_t type.",
    ErrorCode.INVALID_PAULI_HAMIL_PARAMS: "The number of qubits and terms in the PauliHamil must be strictly positive.",
    ErrorCode.INVALID_PAULI_HAMIL_FILE_PARAMS: "The number of qubits and terms in the PauliHamil file ({}) must be strictly positive.",
    ErrorCode.CANNOT_PARSE_PAULI_HAMIL_FILE_COEFF: "Failed to parse the next expected term coefficient in PauliHamil file ({}).",
    ErrorCode.CANNOT_PARSE_PAULI_HAMIL_FILE_PAULI: "Failed to parse the next expected Pauli code in PauliHamil file ({}).",
    ErrorCode.INVALID_PAULI_HAMIL_FILE_PAULI_CODE: "The PauliHamil file ({}) contained an invalid pauli code ({}). Codes must be 0 (or PAULI_I), 1 (PAULI_X), 2 (PAULI_Y) or 3 (PAULI_Z) to indicate the identity, X, Y and Z operators respectively.",
    ErrorCode.MISMATCHING_PAULI_HAMIL_QUREG_NUM_QUBITS: "The PauliHamil must act on the same number of qubits as exist in the Qureg.",
    ErrorCode.INVALID_TROTTER_ORDER: "The Trotterisation order must be 1, or an even number (for higher-order Suzuki symmetrized expansions).",
    ErrorCode.INVALID_TROTTER_REPS: "The number of Trotter repetitions must be >=1.",
    ErrorCode.MISMATCHING_QUREG_DIAGONAL_OP_SIZE: "The qureg must represent an equal number of qubits as that in the applied diagonal operator.",
    ErrorCode.DIAGONAL_OP_NOT_INITIALISED: "The diagonal operator has not been initialised through createDiagonalOperator().",
    ErrorCode.PLANE_ONLY_1Q: "This register uses plane-pair storage (the single-chip memory ceiling); only single-qubit uncontrolled gates are supported at this size. Apply multi-qubit/controlled gates on a register below the plane-storage threshold.",
    ErrorCode.QUREG_NOT_INITIALISED: "The register's amplitude storage has not been initialised, or was already destroyed (destroyQureg).",
    ErrorCode.INVALID_SCHEDULE_OPTION: "Unknown scheduler option. Circuit.schedule accepts only chip, precision, placement, reorder, overlap and pipeline_chunks.",
    ErrorCode.QUEUE_FULL: "The serving queue holds max_queue pending requests; this request was rejected for backpressure. Retry after the queue drains, raise max_queue, or add capacity.",
    ErrorCode.DEADLINE_EXCEEDED: "The request's deadline expired before a batch slot was available; it was completed exceptionally without executing.",
    ErrorCode.SERVICE_SHUTDOWN: "The service is shut down (or shutting down): this request was not executed. Submit to a live replica, or restart the service.",
    ErrorCode.GRADIENT_NOT_UNITARY: "Adjoint gradients require a unitary circuit: the backward sweep uncomputes states by exact gate inverses, which noise channels and non-unitary operators do not have. Use jax.grad(expectation_fn(..., density=True)) for noisy gradients.",
    ErrorCode.GRADIENT_DENSITY_MODE: "Adjoint gradients are defined for statevector registers only; a density-matrix (Choi-doubled) state cannot be uncomputed by gate inverses. Use jax.grad(expectation_fn(..., density=True)).",
    ErrorCode.PLANE_ONLY: "This register uses plane-pair storage (the single-chip memory ceiling); the requested operation needs the stacked amplitude array, which cannot be materialised at this size. Supported in plane mode: init*, single-qubit gates, applyFullQFT, measure/collapse, probabilities, amplitude reads.",
}


def _throw(code: str, func: str | None = None, *fmt) -> None:
    msg = MESSAGES[code]
    if fmt:
        msg = msg.format(*fmt)
    raise QuESTError(code, msg, func)


# ---------------------------------------------------------------------------
# guards (names follow the reference's validate* contract)
# ---------------------------------------------------------------------------

def validate_num_ranks(num_ranks: int, func=None):
    if num_ranks < 1 or (num_ranks & (num_ranks - 1)) != 0:
        _throw(ErrorCode.INVALID_NUM_RANKS, func)


def validate_create_num_qubits(num_qubits: int, env, func=None, factor: int = 1):
    """``factor=2`` for density quregs: the flattened state has 2n qubits
    (ref: validateNumQubitsInQureg, QuEST_validation.c — called with the
    state-vector qubit count)."""
    if num_qubits < 1:
        _throw(ErrorCode.INVALID_NUM_CREATE_QUBITS, func)
    if factor * num_qubits > 63:  # calcLog2(SIZE_MAX) on 64-bit (2^64-1 -> 63)
        _throw(ErrorCode.NUM_AMPS_EXCEED_TYPE, func)
    if 2 ** (factor * num_qubits) < env.num_ranks:
        _throw(ErrorCode.DISTRIB_QUREG_TOO_SMALL, func)


def validate_target(qureg, target: int, func=None):
    if not (0 <= int(target) < qureg.num_qubits_represented):
        _throw(ErrorCode.INVALID_TARGET_QUBIT, func)


def validate_control_target(qureg, control: int, target: int, func=None):
    validate_target(qureg, target, func)
    if not (0 <= int(control) < qureg.num_qubits_represented):
        _throw(ErrorCode.INVALID_CONTROL_QUBIT, func)
    if int(control) == int(target):
        _throw(ErrorCode.TARGET_IS_CONTROL, func)


def validate_unique_targets(qureg, q1: int, q2: int, func=None):
    validate_target(qureg, q1, func)
    validate_target(qureg, q2, func)
    if int(q1) == int(q2):
        _throw(ErrorCode.TARGETS_NOT_UNIQUE, func)


def validate_num_targets(qureg, num_targets: int, func=None):
    if num_targets < 1 or num_targets > qureg.num_qubits_represented:
        _throw(ErrorCode.INVALID_NUM_TARGETS, func)


def validate_num_controls(qureg, num_controls: int, func=None):
    if num_controls < 1 or num_controls >= qureg.num_qubits_represented:
        _throw(ErrorCode.INVALID_NUM_CONTROLS, func)


def validate_multi_targets(qureg, targets, func=None):
    validate_num_targets(qureg, len(targets), func)
    for t in targets:
        validate_target(qureg, t, func)
    if len(set(int(t) for t in targets)) != len(targets):
        _throw(ErrorCode.TARGETS_NOT_UNIQUE, func)


def validate_multi_qubits(qureg, qubits, func=None):
    """Plain qubit-group guard (ref: validateMultiQubits — used by the
    multi-controlled phase gates, whose wires are all peers): plain-qubit
    error texts, not the target-flavoured ones."""
    if len(qubits) < 1 or len(qubits) > qureg.num_qubits_represented:
        _throw(ErrorCode.INVALID_NUM_QUBITS, func)
    for q in qubits:
        if not (0 <= int(q) < qureg.num_qubits_represented):
            _throw(ErrorCode.INVALID_QUBIT_INDEX, func)
    if len(set(int(q) for q in qubits)) != len(qubits):
        _throw(ErrorCode.QUBITS_NOT_UNIQUE, func)


def validate_multi_controls(qureg, controls, func=None):
    validate_num_controls(qureg, len(controls), func)
    for c in controls:
        if not (0 <= int(c) < qureg.num_qubits_represented):
            _throw(ErrorCode.INVALID_CONTROL_QUBIT, func)
    if len(set(int(c) for c in controls)) != len(controls):
        _throw(ErrorCode.CONTROLS_NOT_UNIQUE, func)


def validate_multi_controls_target(qureg, controls, target, func=None):
    validate_target(qureg, target, func)
    validate_multi_controls(qureg, controls, func)
    if int(target) in set(int(c) for c in controls):
        _throw(ErrorCode.TARGET_IN_CONTROLS, func)


def validate_multi_controls_multi_targets(qureg, controls, targets, func=None):
    validate_multi_controls(qureg, controls, func)
    validate_multi_targets(qureg, targets, func)
    if set(int(c) for c in controls) & set(int(t) for t in targets):
        _throw(ErrorCode.CONTROL_TARGET_COLLISION, func)


def validate_control_state(control_state, num_controls: int, func=None):
    control_state = list(control_state)
    if len(control_state) != num_controls:
        _throw(ErrorCode.MISMATCHING_NUM_CONTROL_STATES, func)
    for b in control_state:
        if int(b) not in (0, 1):
            _throw(ErrorCode.INVALID_CONTROLS_BIT_STATE, func)


def validate_state_index(qureg, state_ind: int, func=None):
    if not (0 <= int(state_ind) < 2 ** qureg.num_qubits_represented):
        _throw(ErrorCode.INVALID_STATE_INDEX, func)


def validate_amp_index(qureg, index: int, func=None):
    if not (0 <= int(index) < qureg.num_amps_total):
        _throw(ErrorCode.INVALID_AMP_INDEX, func)


def validate_num_amps(qureg, start_ind: int, num_amps: int, func=None):
    validate_amp_index(qureg, start_ind, func)
    if num_amps < 0 or num_amps > qureg.num_amps_total:
        _throw(ErrorCode.INVALID_NUM_AMPS, func)
    if start_ind + num_amps > qureg.num_amps_total:
        _throw(ErrorCode.INVALID_OFFSET_NUM_AMPS, func)


def _is_unitary(mat: np.ndarray, eps: float) -> bool:
    dim = mat.shape[0]
    prod = mat @ mat.conj().T
    return bool(np.all(np.abs(prod - np.eye(dim)) < eps))


def validate_one_qubit_unitary(u, func=None, eps=None):
    from .precision import CONFIG
    eps = eps if eps is not None else CONFIG.real_eps
    if not _is_unitary(np.asarray(u, dtype=np.complex128).reshape(2, 2), eps):
        _throw(ErrorCode.NON_UNITARY_MATRIX, func)


def validate_two_qubit_unitary(u, func=None, eps=None):
    from .precision import CONFIG
    eps = eps if eps is not None else CONFIG.real_eps
    if not _is_unitary(np.asarray(u, dtype=np.complex128).reshape(4, 4), eps):
        _throw(ErrorCode.NON_UNITARY_MATRIX, func)


def validate_multi_qubit_matrix_size(u, num_targets: int, func=None):
    u = np.asarray(u)
    if u.shape != (2 ** num_targets, 2 ** num_targets):
        _throw(ErrorCode.INVALID_UNITARY_SIZE, func)


def validate_multi_qubit_unitary(u, num_targets: int, func=None, eps=None):
    from .precision import CONFIG
    eps = eps if eps is not None else CONFIG.real_eps
    validate_multi_qubit_matrix_size(u, num_targets, func)
    if not _is_unitary(np.asarray(u, dtype=np.complex128), eps):
        _throw(ErrorCode.NON_UNITARY_MATRIX, func)


def validate_multi_qubit_matrix_fits_in_shard(qureg, num_targets: int, func=None):
    """Ref analogue: E_CANNOT_FIT_MULTI_QUBIT_MATRIX (QuEST_validation.c:437).

    With a sharded amplitude axis over R devices, dense k-target gates are
    routed so their amplitude groups are shard-local; that needs
    2^k <= 2^n / R."""
    num_ranks = qureg.env.num_ranks if qureg.env is not None else 1
    if 2 ** num_targets > qureg.num_amps_total // max(num_ranks, 1):
        _throw(ErrorCode.CANNOT_FIT_MULTI_QUBIT_MATRIX, func)


def validate_unitary_complex_pair(alpha, beta, func=None, eps=None):
    from .precision import CONFIG
    eps = eps if eps is not None else CONFIG.real_eps
    if abs(abs(alpha) ** 2 + abs(beta) ** 2 - 1.0) > eps:
        _throw(ErrorCode.NON_UNITARY_COMPLEX_PAIR, func)


def validate_vector(v, func=None):
    """Axis magnitude must exceed REAL_EPS (ref: validateVector,
    QuEST_validation.c:189)."""
    from .precision import CONFIG
    if math.sqrt(v[0] ** 2 + v[1] ** 2 + v[2] ** 2) <= CONFIG.real_eps:
        _throw(ErrorCode.ZERO_VECTOR, func)


def validate_qureg_init(qureg, func=None):
    """The register still owns amplitude storage (ref analogue: QuEST's
    validateQuregAllocation) — a destroyed register (destroyQureg) has
    neither the stacked array nor plane-pair storage.  The numeric-health
    helpers (calc_total_prob & co.) guard on this so probing a dead
    register raises ``E_QUREG_NOT_INITIALISED`` instead of an
    AttributeError from subscripting None."""
    if (getattr(qureg, "_amps", None) is None
            and getattr(qureg, "_planes", None) is None):
        _throw(ErrorCode.QUREG_NOT_INITIALISED, func)


def validate_state_vec_qureg(qureg, func=None):
    if qureg.is_density_matrix:
        _throw(ErrorCode.DEFINED_ONLY_FOR_STATEVECS, func)


def validate_density_matr_qureg(qureg, func=None):
    if not qureg.is_density_matrix:
        _throw(ErrorCode.DEFINED_ONLY_FOR_DENSMATRS, func)


def validate_outcome(outcome: int, func=None):
    if int(outcome) not in (0, 1):
        _throw(ErrorCode.INVALID_QUBIT_OUTCOME, func)


def validate_measurement_prob(prob: float, func=None, eps=None):
    """Outcome probability must exceed REAL_EPS (ref: validateMeasurementProb,
    QuEST_validation.c:491-492) — collapsing onto rounding noise would
    renormalise garbage into an apparently valid state."""
    from .precision import CONFIG
    eps = eps if eps is not None else CONFIG.real_eps
    if prob <= eps:
        _throw(ErrorCode.COLLAPSE_STATE_ZERO_PROB, func)


def validate_matching_qureg_dims(q1, q2, func=None):
    if q1.num_qubits_represented != q2.num_qubits_represented:
        _throw(ErrorCode.MISMATCHING_QUREG_DIMENSIONS, func)


def validate_matching_qureg_types(q1, q2, func=None):
    if q1.is_density_matrix != q2.is_density_matrix:
        _throw(ErrorCode.MISMATCHING_QUREG_TYPES, func)


def validate_second_qureg_state_vec(qureg2, func=None):
    if qureg2.is_density_matrix:
        _throw(ErrorCode.SECOND_ARG_MUST_BE_STATEVEC, func)


def validate_prob(prob: float, func=None):
    if prob < 0 or prob > 1:
        _throw(ErrorCode.INVALID_PROB, func)


def validate_one_qubit_dephase_prob(prob: float, func=None):
    if prob < 0 or prob > 1 / 2.0:
        if prob < 0 or prob > 1:
            _throw(ErrorCode.INVALID_PROB, func)
        _throw(ErrorCode.INVALID_ONE_QUBIT_DEPHASE_PROB, func)


def validate_two_qubit_dephase_prob(prob: float, func=None):
    if prob < 0 or prob > 1:
        _throw(ErrorCode.INVALID_PROB, func)
    if prob > 3 / 4.0:
        _throw(ErrorCode.INVALID_TWO_QUBIT_DEPHASE_PROB, func)


def validate_one_qubit_depol_prob(prob: float, func=None):
    if prob < 0 or prob > 1:
        _throw(ErrorCode.INVALID_PROB, func)
    if prob > 3 / 4.0:
        _throw(ErrorCode.INVALID_ONE_QUBIT_DEPOL_PROB, func)


def validate_one_qubit_damping_prob(prob: float, func=None):
    if prob < 0 or prob > 1:
        _throw(ErrorCode.INVALID_PROB, func)


def validate_two_qubit_depol_prob(prob: float, func=None):
    if prob < 0 or prob > 1:
        _throw(ErrorCode.INVALID_PROB, func)
    if prob > 15 / 16.0:
        _throw(ErrorCode.INVALID_TWO_QUBIT_DEPOL_PROB, func)


def validate_pauli_probs(prob_x: float, prob_y: float, prob_z: float, func=None):
    for p in (prob_x, prob_y, prob_z):
        if p < 0 or p > 1:
            _throw(ErrorCode.INVALID_PROB, func)
    prob_no_error = 1 - prob_x - prob_y - prob_z
    if prob_x > prob_no_error or prob_y > prob_no_error or prob_z > prob_no_error:
        _throw(ErrorCode.INVALID_ONE_QUBIT_PAULI_PROBS, func)


def validate_pauli_codes(codes, num_paulis: int, func=None):
    codes = list(codes)
    if len(codes) != num_paulis:
        _throw(ErrorCode.MISMATCHING_NUM_PAULI_CODES, func)
    for c in codes:
        if int(c) not in (0, 1, 2, 3):
            _throw(ErrorCode.INVALID_PAULI_CODE, func)


def validate_num_pauli_sum_terms(num_terms: int, func=None):
    if num_terms < 1:
        _throw(ErrorCode.INVALID_NUM_SUM_TERMS, func)


def validate_pauli_hamil(hamil, func=None):
    if hamil.num_qubits < 1 or hamil.num_sum_terms < 1:
        _throw(ErrorCode.INVALID_PAULI_HAMIL_PARAMS, func)
    validate_pauli_codes(hamil.pauli_codes.ravel(), hamil.num_qubits * hamil.num_sum_terms, func)


def validate_matching_hamil_qureg_dims(qureg, hamil, func=None):
    if qureg.num_qubits_represented != hamil.num_qubits:
        _throw(ErrorCode.MISMATCHING_PAULI_HAMIL_QUREG_NUM_QUBITS, func)


def validate_trotter_params(order: int, reps: int, func=None):
    if order < 1 or (order > 1 and order % 2 != 0):
        _throw(ErrorCode.INVALID_TROTTER_ORDER, func)
    if reps < 1:
        _throw(ErrorCode.INVALID_TROTTER_REPS, func)


def validate_num_kraus_ops(num_targets: int, num_ops: int, func=None):
    max_ops = (2 ** num_targets) ** 2
    if num_ops < 1 or num_ops > max_ops:
        if num_targets == 1:
            _throw(ErrorCode.INVALID_NUM_ONE_QUBIT_KRAUS_OPS, func)
        if num_targets == 2:
            _throw(ErrorCode.INVALID_NUM_TWO_QUBIT_KRAUS_OPS, func)
        _throw(ErrorCode.INVALID_NUM_N_QUBIT_KRAUS_OPS, func)


def validate_kraus_cptp(ops, func=None, eps=None):
    """Sum_i K_i^dag K_i == I (ref: isCompletelyPositiveMapN, QuEST_validation.c:246+)."""
    from .precision import CONFIG
    eps = eps if eps is not None else CONFIG.real_eps
    mats = [np.asarray(k, dtype=np.complex128) for k in ops]
    dim = mats[0].shape[0]
    acc = np.zeros((dim, dim), dtype=np.complex128)
    for k in mats:
        acc += k.conj().T @ k
    if not np.all(np.abs(acc - np.eye(dim)) < 10 * eps):
        _throw(ErrorCode.INVALID_KRAUS_OPS, func)


def validate_kraus_sizes(ops, num_targets: int, func=None):
    dim = 2 ** num_targets
    for k in ops:
        if np.asarray(k).shape != (dim, dim):
            _throw(ErrorCode.MISMATCHING_NUM_TARGS_KRAUS_SIZE, func)


def validate_diag_op_init(op, func=None):
    if getattr(op, "amps", None) is None:
        _throw(ErrorCode.DIAGONAL_OP_NOT_INITIALISED, func)


def validate_matching_qureg_diag_dims(qureg, op, func=None):
    if qureg.num_qubits_represented != op.num_qubits:
        _throw(ErrorCode.MISMATCHING_QUREG_DIAGONAL_OP_SIZE, func)


def validate_diag_op_elems(op, start_ind: int, num_elems: int, func=None):
    if not (0 <= int(start_ind) < 2 ** op.num_qubits):
        _throw(ErrorCode.INVALID_ELEM_INDEX, func)
    if num_elems < 0 or num_elems > 2 ** op.num_qubits:
        _throw(ErrorCode.INVALID_NUM_ELEMS, func)
    if start_ind + num_elems > 2 ** op.num_qubits:
        _throw(ErrorCode.INVALID_OFFSET_NUM_ELEMS, func)


def validate_report_size(qureg, func=None):
    if qureg.num_qubits_represented > 5:
        _throw(ErrorCode.SYS_TOO_BIG_TO_PRINT, func)
