"""Functional op layer: pure jitted programs over amplitude arrays.

Modules: apply (gate engine), diagonal phases, init (state builders),
measure (probabilities/collapse), calc (reductions), decoherence (channels).
"""
