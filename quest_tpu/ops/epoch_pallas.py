"""General Pallas epoch executor: the in-place engines as a circuit backend.

``ops/qft_inplace.py`` proved that the fastest way to run a circuit on one
chip is NOT one XLA pass per gate but a handful of aliased Pallas passes:
BENCH_r05 has the in-place engine at 2.1-2.7e11 amps/s against the XLA
engine's 7.1e10 on the same 28q QFT.  That module, however, is a hand-written
closed form only QFT circuits can reach.  This module generalizes its three
tricks into a backend ``compile_circuit`` can target for ARBITRARY scheduled
windows of 1q/2q/diagonal ops (PAPER.md's thesis: interchangeable kernel
implementations behind one dispatch layer; ROADMAP item 2):

1. **Fused block passes.**  Every op whose dense action is confined to one
   minor axis group of the tile view — lane (qubits 0-6), sublane (7-9) or
   fiber (10-16) — and every diagonal/parity op on ANY wires (their factor
   is a function of the global amplitude index, which each block can
   reconstruct from ``program_id``) is block-local.  A maximal run of such
   ops becomes ONE aliased Pallas pass applying all of them MXU/VPU-resident
   in VMEM: k gates for one HBM read+write of the state.  Registers of
   17-30 qubits walk (F=128, S=8, L=128) blocks; registers of 10-16 qubits
   use the DEGENERATE geometry — the whole state is one (2^(n-10), 8, 128)
   VMEM tile, one grid step — so small circuits (the 16q VQE ansatz) lower
   to a handful of fused passes instead of falling outside the envelope.

2. **Staged pack passes for high qubits.**  Dense ops on qubits >= 17 —
   controlled or not: the control predicate is computed from the global
   amplitude index reconstructed off ``program_id``, exactly like block
   controls — run through the aliased pack engine: a (left, W, right)
   factorisation whose blocks hold the FULL high-group axis, applying a
   static program of dense/diagonal/parity stages per HBM pass.
   Consecutive uncontrolled (or identically-controlled) dense stages
   compose host-side; diagonals and mrz ops interleave as elementwise
   stages, so a QFT stage's H + its whole controlled-phase ladder is one
   stage run inside one pass.

3. **Cross-group 2q dense windows.**  A 2-target dense gate whose targets
   straddle two axis groups no longer splits the epoch: it is lowered
   EXACTLY by a block-matrix (cosine-sine) decomposition over the odd bit
   — ``U = (V1 (+) V2) . R . (W1 (+) W2)`` with the direct sums
   block-diagonal over the odd bit (two controlled 1q dense ops on the
   even bit) and the middle factor a pair of controlled Givens rotations
   on the odd bit — six single-target controlled dense ops, each confined
   to one group, each fusing into the surrounding block/pack passes (a
   minor-minor gate costs ZERO extra passes; a minor-high gate at most a
   pack stage plus stream boundaries).  The decomposition is verified
   host-side against the original payload and falls back to the XLA gate
   engine if reconstruction fails (exotic degenerate payloads).

4. **Fused superoperator stages (density noise channels).**  A density
   matrix runs as its Choi-doubled 2n-qubit vector (circuit.DensityCircuit)
   and a decoherence channel is an arbitrary — NON-unitary — dense op on
   the paired wires (q, q+n), which straddle axis groups by construction
   and which the odd-bit decomposition (unitary-only) cannot reach.  Such
   ops lower as elementwise ``super`` stages: the four partner amplitudes
   are reconstructed by structured bit-flips of the resident block
   (``_apply_super_spec``) and combined with payload entries selected off
   the global amplitude index — any 4x4 matrix, one VPU stage, zero extra
   HBM passes.  Block passes reach any bit pair below the block span; pack
   passes reach (low bit, W-axis bit) by widening their column block to
   cover the low partner (``PackPass.min_cols``).  A 14-density-qubit
   damping+depolarising layer (42 ops on the doubled register) lowers to
   3 fused passes.  Dephasing channels are DIAGONAL superoperators and ride
   the existing diag machinery untouched.

5. **Deferred qubit map.**  ``swap``/``bitperm`` ops never move data: they
   update a logical->physical wire permutation that later ops absorb into
   their wiring (the residual permutation is carried across epoch
   boundaries and materialized once, by ``reconcile_perm``, at the end of
   the program — or returned to plane-pair callers, the unordered-QFT
   convention).  The QFT's trailing swap network therefore costs ZERO
   passes.

The lowering runs TWO pending streams — a block pass and a pack pass —
reordering ops between them only when a conservative commutation rule
(disjoint wires; diagonal pairs; diagonal-vs-control block-diagonality)
proves the swap sound, so a mixed window's high-qubit pack no longer
splits the minor-block run: a 28q QFT lowers to 3 fused passes, a 24q
random circuit layer run to ~2 per layer.  ``check_epoch_plan`` proves
every reorder and decomposition IR-equivalent (the same Mazurkiewicz-trace
+ window-oracle domains that certify scheduler rewrites) and
``probe_epoch_execution`` runs the actual kernels against the XLA engine.

Ops outside the supported set (>=3-target dense gates straddling groups,
>5-target general diagonals) split the epoch: they execute through the XLA
gate engine between Pallas segments, with wires translated through the
live permutation, so ANY circuit compiles — the planner's engine cost
model (parallel/planner.py ``select_engine``) just rates mostly-
unsupported circuits as XLA wins.

Envelope: f32 plane storage, 10 <= n <= 30 (degenerate single-block
geometry below 17; int32 block indices above 30 would overflow).  The
residual permutation MUST be materialized before any sharded collective
(the map renames amplitude-index bits, which a mesh reshards on —
docs/DESIGN.md); the engine is therefore single-device, and
``select_engine`` pins multi-device deployments to XLA.

Plane-pair donation: :func:`run_planes` (returns the residual map),
:func:`jit_program_planes` (donated, reconciled, truly in place) and the
``(2, N)`` compat entries :func:`run_ops_planes` / :func:`jit_program`.
The donated programs' input/output aliasing is machine-audited by
``analysis/jaxpr_audit.audit_epoch_donation``.
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl

from .. import _compat
from .. import obs as _obs

from .pallas_layer import (LANE, SUB, _FIBER_COLS, _fiber_group, _interpret)
from .qft_inplace import _block_k

__all__ = ["EnginePlan", "Segment", "plan_circuit", "epoch_supported",
           "run_ops_planes", "run_planes", "jit_program",
           "jit_program_planes", "MIN_QUBITS", "MAX_QUBITS", "HIGH_BASE"]

MIN_QUBITS = 10   # degenerate single-block geometry floor (one (F, 8, 128)
                  # VMEM tile needs at least the 2^10 sublane x lane plane)
HIGH_BASE = 17    # qubits >= HIGH_BASE run through pack passes; below, the
                  # (fiber, sublane, lane) block view covers them
MAX_QUBITS = 30   # int32 global amplitude indices in the kernels

# widest general diagonal lowered as in-kernel selects (2^5 = 32 entries);
# wider diagonals fall back to the XLA gather engine
_DIAG_CAP = 5

# axis groups of the minor qubits in the (F, S, L) tile view
_LANE_Q = (0, 7)
_SUB_Q = (7, 10)
_FIBER_Q = (10, 17)

# cross-group decomposition: host-side reconstruction tolerance — a factor
# set that fails to rebuild the payload falls back to the XLA engine
_CSD_TOL = 1e-9

# superoperator stages (arbitrary — non-unitary — 2-target dense ops lowered
# as elementwise bit-flip/select stages, the density-channel lowering): the
# widest column block a pack pass will widen to so a low target bit stays
# in-block.  w * cols * 4 B stays <= 16 MiB per plane at the widest group
# (w = 128), inside the v5e/v5p VMEM budget with double buffering.
_SUPER_COLS_CAP = 1 << 15

_X_PAIR = np.stack([np.array([[0.0, 1.0], [1.0, 0.0]]), np.zeros((2, 2))])
_Y_PAIR = np.stack([np.zeros((2, 2)), np.array([[0.0, -1.0], [1.0, 0.0]])])
_YC_PAIR = np.stack([np.zeros((2, 2)), np.array([[0.0, 1.0], [-1.0, 0.0]])])


# ---------------------------------------------------------------------------
# host-side lowering: ops -> passes
# ---------------------------------------------------------------------------

def _embed_axis(up: np.ndarray, rel: tuple, width: int) -> np.ndarray:
    """Embed a (2, 2^k, 2^k) real-pair unitary acting on axis-index bits
    ``rel`` (matrix index bit j <-> axis bit rel[j], the engine-wide
    targets[j] convention) into the full (2, 2^width, 2^width) axis matrix,
    identity on the remaining bits."""
    dim = 1 << width
    m = up[0] + 1j * up[1]
    a = np.arange(dim)
    sub = np.zeros(dim, np.int64)
    mask = 0
    for j, p in enumerate(rel):
        sub |= ((a >> p) & 1) << j
        mask |= 1 << p
    rest = a & ~mask
    out = m[sub[:, None], sub[None, :]] * (rest[:, None] == rest[None, :])
    return np.stack([out.real, out.imag])  # f64; cast to f32 at pass build


def _pair_compose(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Complex compose on real pairs: ``a`` AFTER ``b`` (a @ b)."""
    return np.stack([a[0] @ b[0] - a[1] @ b[1],
                     a[0] @ b[1] + a[1] @ b[0]])


def _dense_pair(op) -> np.ndarray:
    """The (2, 2^k, 2^k) real-pair matrix of a dense-kind op."""
    if op.kind == "x":
        return _X_PAIR
    if op.kind == "y":
        return _Y_PAIR
    if op.kind == "y*":
        return _YC_PAIR
    return op.payload()


def _cstates(op) -> tuple:
    return tuple(op.control_states) or (1,) * len(op.controls)


def _geometry(n_amps: int) -> tuple:
    """(grid_size, 3-D view shape, block shape) of the block walk.  At
    n >= 17 the standard (F=128, S=8, L=128) 2^17-amp blocks; below, the
    DEGENERATE geometry — the whole state is one (2^(n-10), 8, 128) block,
    a single grid step, every supported op block-local.  Both views are
    byte-identical to the flat layout (free bitcasts)."""
    block_amps = LANE * SUB * LANE
    if n_amps >= block_amps:
        top = n_amps // block_amps
        return top, (top * LANE, SUB, LANE), (LANE, SUB, LANE)
    f = n_amps // (SUB * LANE)
    return 1, (f, SUB, LANE), (f, SUB, LANE)


@dataclasses.dataclass(frozen=True, eq=False)
class BlockPass:
    """One fused block-local Pallas pass: ``specs`` is the static kernel
    program (see ``_epoch_block_kernel``), ``mats`` the deduplicated
    embedded axis matrices it matmuls with."""
    specs: tuple
    mats: tuple          # of np (2, D, D) float32, D in {128, 8, 2^(n-10)}

    @property
    def kind(self) -> str:
        return "block"

    @property
    def super_stages(self) -> int:
        return sum(1 for s in self.specs if s[0] == "super")


@dataclasses.dataclass(frozen=True, eq=False)
class PackPass:
    """One aliased staged pack pass over the (left, W, right) view of a
    high-qubit group [base, base+log2(W)): ``specs`` is the static stage
    program (dense contractions of the W axis — controlled or not — plus
    diagonal/mrz/superoperator elementwise stages), ``mats`` the composed
    packs.  ``min_cols`` widens the column block when a superoperator
    stage couples a low target bit: the bit must be in-block for the
    stage's flip access (0 = the default _FIBER_COLS geometry)."""
    base: int
    width: int
    specs: tuple
    mats: tuple          # of np (2, W, W) float32
    min_cols: int = 0

    @property
    def kind(self) -> str:
        return "pack"

    @property
    def super_stages(self) -> int:
        return sum(1 for s in self.specs if s[0] == "super")


@dataclasses.dataclass
class Segment:
    """A maximal single-engine run: ``ops`` are the window's ops with wires
    already translated to PHYSICAL positions, in EMITTED pass order (the
    audit/reporting view and, for xla segments, the execution list);
    ``passes`` is the Pallas lowering (pallas segments only)."""
    engine: str                  # 'pallas' | 'xla'
    ops: list
    passes: list


@dataclasses.dataclass
class EnginePlan:
    """The epoch executor's static lowering of one circuit."""
    num_qubits: int
    segments: list
    residual_perm: tuple         # perm[logical] = physical position
    deferred_ops: int            # swap/bitperm ops absorbed with zero passes

    @property
    def pallas_passes(self) -> int:
        return sum(len(s.passes) for s in self.segments
                   if s.engine == "pallas")

    @property
    def block_passes(self) -> int:
        return sum(1 for s in self.segments if s.engine == "pallas"
                   for p in s.passes if p.kind == "block")

    @property
    def pack_passes(self) -> int:
        return sum(1 for s in self.segments if s.engine == "pallas"
                   for p in s.passes if p.kind == "pack")

    @property
    def super_block_passes(self) -> int:
        """Block passes containing >=1 superoperator stage (priced at the
        ``pallas_epoch_super`` efficiency class — flip/select elementwise
        stages, not matmuls)."""
        return sum(1 for s in self.segments if s.engine == "pallas"
                   for p in s.passes
                   if p.kind == "block" and p.super_stages)

    @property
    def super_pack_passes(self) -> int:
        return sum(1 for s in self.segments if s.engine == "pallas"
                   for p in s.passes
                   if p.kind == "pack" and p.super_stages)

    @property
    def super_passes(self) -> int:
        return self.super_block_passes + self.super_pack_passes

    @property
    def super_stages(self) -> int:
        """Total fused superoperator stages across every pass — the number
        of density channels (or other non-unitary 2-target ops) the plan
        lowered WITHOUT an XLA fallback or an extra HBM pass."""
        return sum(p.super_stages for s in self.segments
                   if s.engine == "pallas" for p in s.passes)

    @property
    def pallas_ops(self) -> int:
        return sum(len(s.ops) for s in self.segments if s.engine == "pallas")

    @property
    def xla_ops(self) -> int:
        return sum(len(s.ops) for s in self.segments if s.engine == "xla")

    @property
    def hbm_passes(self) -> int:
        """Modeled HBM passes of the lowered program: one per Pallas pass,
        one per XLA-segment gate.  The deferred residual permutation is
        excluded — it is carried, not executed (the unordered-transform
        convention of qft_inplace), and single-chip materialization is two
        plane gathers charged to whoever forces it."""
        return self.pallas_passes + self.xla_ops

    def summary(self) -> dict:
        return {
            "num_qubits": self.num_qubits,
            "segments": [{"engine": s.engine, "ops": len(s.ops),
                          "passes": len(s.passes) if s.engine == "pallas"
                          else len(s.ops)}
                         for s in self.segments],
            "pallas_passes": self.pallas_passes,
            "block_passes": self.block_passes,
            "pack_passes": self.pack_passes,
            "super_passes": self.super_passes,
            "super_stages": self.super_stages,
            "pallas_ops": self.pallas_ops,
            "xla_ops": self.xla_ops,
            "deferred_ops": self.deferred_ops,
            "hbm_passes": self.hbm_passes,
            "degenerate_geometry": self.num_qubits < HIGH_BASE,
            "residual_nontrivial": self.residual_perm
            != tuple(range(self.num_qubits)),
        }


def _phys_op(op, perm: list):
    """``op`` with targets/controls translated through the live
    logical->physical map (bitperm destination payloads are wires too)."""
    from ..circuit import GateOp
    t = tuple(perm[q] for q in op.targets)
    c = tuple(perm[q] for q in op.controls)
    mat = op.matrix
    if op.kind == "bitperm":
        mat = tuple(float(perm[int(d)]) for d in op.matrix)
    if t == op.targets and c == op.controls and mat == op.matrix:
        return op
    return GateOp(op.kind, t, c, op.control_states, mat, op.shape)


def _absorb_perm(perm: list, op) -> None:
    """Fold a logical ``swap``/``bitperm`` into the deferred map: content
    of logical wire t now answers to logical name d, so later ops on d land
    on t's physical home (G_d . P = P . G_t for the permutation P)."""
    if op.kind == "swap":
        a, b = op.targets
        perm[a], perm[b] = perm[b], perm[a]
    else:
        old = list(perm)
        for t, d in zip(op.targets, op.matrix):
            perm[int(d)] = old[t]


def _axis_group(targets: tuple) -> tuple | None:
    """The minor axis group confining all (physical) ``targets``, or None."""
    for group in (_LANE_Q, _SUB_Q, _FIBER_Q):
        if all(group[0] <= t < group[1] for t in targets):
            return group
    return None


def _classify(op, n: int) -> str:
    """Lowering class of a PHYSICAL op: 'defer' (absorbed into the qubit
    map), 'block' (fused block-local pass), 'either' (diagonal family —
    executable in both streams), 'pack' (high-qubit staged pass),
    'cross2q' (2-target dense straddling groups: decomposed), or 'xla'
    (gate-engine fallback splitting the epoch)."""
    if op.kind in ("swap", "bitperm"):
        return "defer"
    if op.kind == "mrz":
        return "either"
    if op.kind == "diagonal":
        return "either" if len(op.targets) <= _DIAG_CAP else "xla"
    if op.kind in ("matrix", "x", "y", "y*"):
        if _axis_group(op.targets) is not None:
            return "block"
        if min(op.targets) >= HIGH_BASE:
            base, hi = _fiber_group(min(op.targets), n)
            if max(op.targets) < hi:
                return "pack"
        if len(op.targets) == 2:
            return "cross2q"
        return "xla"
    return "xla"


def _stream_commutes(a, b) -> bool:
    """Conservative (cheap, exact-rule-only) commutation used to reorder
    ops between the two pending streams: disjoint wires; two overall-
    diagonal ops; a diagonal whose shared wires are all the other op's
    controls (block-diagonality).  A strict subset of the equivalence
    checker's oracle, so every reorder the plan makes is provable."""
    wa = set(a.targets) | set(a.controls)
    wb = set(b.targets) | set(b.controls)
    shared = wa & wb
    if not shared:
        return True
    da = a.kind in ("diagonal", "mrz")
    db = b.kind in ("diagonal", "mrz")
    if da and db:
        return True
    if da and shared <= set(b.controls):
        return True
    if db and shared <= set(a.controls):
        return True
    return False


# ---------------------------------------------------------------------------
# cross-group 2q dense: the odd-bit block (cosine-sine) decomposition
# ---------------------------------------------------------------------------

def _complete_column(m: np.ndarray, i: int) -> None:
    """Replace near-zero column ``i`` of a 2x2 with a unit vector
    orthogonal to the other column (the degenerate-singular-value fill)."""
    other = m[:, 1 - i]
    for k in range(2):
        cand = np.zeros(2, complex)
        cand[k] = 1.0
        cand = cand - other * np.vdot(other, cand)
        nrm = np.linalg.norm(cand)
        if nrm > 0.5:
            m[:, i] = cand / nrm
            return


def _csd2(u: np.ndarray) -> tuple | None:
    """Cosine-sine decomposition of a 4x4 unitary partitioned over its
    HIGH index bit: ``u == blkdiag(V1, V2) @ [[C, -S], [S, C]] @
    blkdiag(W1h, W2h)`` with C, S real non-negative diagonals.  The
    factors are verified against ``u`` host-side; None when the
    reconstruction misses ``_CSD_TOL`` (degenerate payloads fall back)."""
    U00, U01 = u[:2, :2], u[:2, 2:]
    U10, U11 = u[2:, :2], u[2:, 2:]
    V1, c, W1h = np.linalg.svd(U00)
    c = np.clip(c, 0.0, 1.0)
    s = np.sqrt(np.maximum(0.0, 1.0 - c * c))
    # U10 W1 = V2 S exactly (X^H X = W1^H (I - U00^H U00) W1 = S^2), so the
    # normalized columns of X ARE V2 wherever s_i > 0
    x = U10 @ W1h.conj().T
    V2 = np.zeros((2, 2), complex)
    for i in range(2):
        nrm = np.linalg.norm(x[:, i])
        if nrm > _CSD_TOL:
            V2[:, i] = x[:, i] / nrm
    for i in range(2):
        if np.linalg.norm(V2[:, i]) < 0.5:
            _complete_column(V2, i)
    # W2h rows from whichever relation is well-conditioned per row:
    # U11 = V2 C W2h and U01 = -V1 S W2h
    y = V2.conj().T @ U11
    z = V1.conj().T @ U01
    W2h = np.zeros((2, 2), complex)
    for i in range(2):
        if c[i] >= s[i]:
            W2h[i] = y[i] / c[i]
        else:
            W2h[i] = -z[i] / s[i]
    zero = np.zeros((2, 2))
    rec = (np.block([[V1, zero], [zero, V2]])
           @ np.block([[np.diag(c), -np.diag(s)], [np.diag(s), np.diag(c)]])
           @ np.block([[W1h, zero], [zero, W2h]]))
    if np.max(np.abs(rec - u)) > _CSD_TOL:
        return None
    return V1, V2, c, s, W1h, W2h


_BIT_SWAP_P = np.array([[1, 0, 0, 0], [0, 0, 1, 0],
                        [0, 1, 0, 0], [0, 0, 0, 1]], float)


def _cross2q_factors(op) -> list | None:
    """Exact lowering of a 2-target dense op whose targets straddle two
    axis groups into single-target controlled dense factors (application
    order), each confined to one group.  Generic payloads take the
    cosine-sine route (six factors); block-diagonal and anti-diagonal
    payloads take exact two/three-factor shortcuts.  None when the
    decomposition cannot be verified — the caller falls back to the XLA
    gate engine for that op."""
    from ..circuit import GateOp
    up = _dense_pair(op)
    u = (up[0] + 1j * up[1]).astype(complex)
    t0, t1 = op.targets
    # the odd (decomposition) bit is the higher physical position: the
    # middle rotations land in ITS group's stream, the block-diagonal
    # factors on the lower target's
    if t1 >= t0:
        a_t, b_t = t0, t1
        jb = 1
    else:
        a_t, b_t = t1, t0
        jb = 0
    if jb == 0:  # payload index bit 0 is the odd bit: reorder to (b, a)
        u = _BIT_SWAP_P @ u @ _BIT_SWAP_P
    ctl = tuple(op.controls)
    cst = tuple(op.control_states) or (1,) * len(ctl)

    def gate(m, target, cbit=None, cval=1):
        if np.max(np.abs(m - np.eye(2))) < 1e-12:
            return None  # identity factor: skip
        controls = ctl + (() if cbit is None else (cbit,))
        states = cst + (() if cbit is None else (cval,))
        mp = np.stack([np.asarray(m).real, np.asarray(m).imag])
        return GateOp("matrix", (target,), controls, states,
                      tuple(mp.ravel()), (2, 2, 2))

    U00, U01 = u[:2, :2], u[:2, 2:]
    U10, U11 = u[2:, :2], u[2:, 2:]
    off = max(np.max(np.abs(U01)), np.max(np.abs(U10)))
    dia = max(np.max(np.abs(U00)), np.max(np.abs(U11)))
    if off < _CSD_TOL:   # block diagonal over the odd bit: two factors
        factors = [gate(U00, a_t, b_t, 0), gate(U11, a_t, b_t, 1)]
    elif dia < _CSD_TOL:  # anti-diagonal: X on the odd bit after the blocks
        factors = [gate(U10, a_t, b_t, 0), gate(U01, a_t, b_t, 1),
                   gate(_X_PAIR[0], b_t)]
    else:
        res = _csd2(u)
        if res is None:
            return None
        V1, V2, c, s, W1h, W2h = res
        r0 = np.array([[c[0], -s[0]], [s[0], c[0]]])
        r1 = np.array([[c[1], -s[1]], [s[1], c[1]]])
        factors = [gate(W1h, a_t, b_t, 0), gate(W2h, a_t, b_t, 1),
                   gate(r0, b_t, a_t, 0), gate(r1, b_t, a_t, 1),
                   gate(V1, a_t, b_t, 0), gate(V2, a_t, b_t, 1)]
    return [f for f in factors if f is not None]


# ---------------------------------------------------------------------------
# superoperator stages: arbitrary 2-target dense ops as elementwise flips
# ---------------------------------------------------------------------------

def _super_spec(op) -> tuple:
    """Kernel spec of a 2-target dense op applied ELEMENTWISE: the (2, 4, 4)
    payload is baked as float32 tuples (matrix index bit j <-> targets[j],
    the engine-wide convention, which for a density channel recorded on
    (q, q+n) is exactly ops/decoherence.py's row_bit + 2*col_bit layout).
    Unlike the dense matmul and odd-bit decomposition paths this stage
    never requires unitarity: the kernels reconstruct the four partner
    amplitudes by structured bit-flips of the block and combine them with
    payload entries selected off the global amplitude index — any 4x4
    matrix, one VPU stage, zero extra HBM passes."""
    up = _dense_pair(op)
    return ("super", tuple(op.targets),
            tuple(tuple(np.float32(x) for x in row) for row in up[0]),
            tuple(tuple(np.float32(x) for x in row) for row in up[1]),
            op.controls, _cstates(op))


def _super_route(op, n: int):
    """Where a 2-target dense op the odd-bit decomposition rejected can
    still run as a fused superoperator stage:

    - ``("super_block",)`` — both targets inside the block span
      (bits < min(n, HIGH_BASE)): the (F, S, L) block holds every partner
      amplitude, any bit pair works.
    - ``("pack_dense", base, hi)`` — both targets on ONE high group's W
      axis (possible in groups widened below HIGH_BASE): the ordinary
      embedded W-axis contraction applies, non-unitary payloads included.
    - ``("super_pack", base, hi)`` — high target on a W axis, low target
      below the group base: the pass widens its column block to cover the
      low bit (bounded by ``_SUPER_COLS_CAP``) — the density-channel case,
      ket bit q paired with bra bit q+n.
    - ``None`` — no fused form: the op falls back to the XLA gate engine.
    """
    t_lo, t_hi = sorted(op.targets)
    span = min(n, HIGH_BASE)
    if t_hi < span:
        return ("super_block",)
    if t_hi >= HIGH_BASE:
        base, hi = _fiber_group(t_hi, n)
        if t_hi < hi:
            if t_lo >= base:
                return ("pack_dense", base, hi)
            if (2 << t_lo) <= min(_SUPER_COLS_CAP, 1 << base):
                return ("super_pack", base, hi)
    return None


# ---------------------------------------------------------------------------
# stream builders
# ---------------------------------------------------------------------------

class _BlockBuilder:
    """Accumulates block-class ops into one BlockPass.  ``ops`` carries the
    pending physical ops in program order (the plan's audit record and the
    cross-stream commutation witness list)."""

    def __init__(self, n: int):
        # degenerate geometry: the fiber axis is only n-10 bits wide
        self._fiber_width = min(n, HIGH_BASE) - _FIBER_Q[0]
        self.specs: list = []
        self.mats: list = []
        self._mat_idx: dict = {}
        self.ops: list = []

    def _intern(self, m: np.ndarray) -> int:
        key = m.tobytes()
        i = self._mat_idx.get(key)
        if i is None:
            i = self._mat_idx[key] = len(self.mats)
            self.mats.append(m)
        return i

    def add(self, op) -> None:
        self.ops.append(op)
        if op.kind == "mrz":
            half = float(op.matrix[0]) / 2.0
            self.specs.append(("mrz", op.targets,
                               float(np.cos(half)), float(np.sin(half))))
            return
        if op.kind == "diagonal":
            d = op.payload()
            self.specs.append(("diag", op.targets, op.controls, _cstates(op),
                               tuple(np.float32(x) for x in d[0]),
                               tuple(np.float32(x) for x in d[1])))
            return
        group = _axis_group(op.targets)
        lo, hi = group
        axis = {0: "lane", 7: "sub", 10: "fiber"}[lo]
        width = self._fiber_width if axis == "fiber" else hi - lo
        m = _embed_axis(_dense_pair(op), tuple(t - lo for t in op.targets),
                        width).astype(np.float32)
        self.specs.append(("dense", axis, self._intern(m), op.controls,
                           _cstates(op)))

    def add_super(self, op) -> None:
        """A 2-target dense op on ARBITRARY in-block bits (cross-group, and
        non-unitary superoperators the odd-bit decomposition cannot reach)
        as one elementwise flip/select stage — see ``_apply_super_spec``."""
        self.ops.append(op)
        self.specs.append(_super_spec(op))

    def flush(self) -> tuple:
        if not self.specs:
            return None, []
        out = BlockPass(tuple(self.specs), tuple(self.mats))
        ops = self.ops
        self.specs, self.mats, self._mat_idx, self.ops = [], [], {}, []
        return out, ops


class _PackBuilder:
    """Accumulates high-qubit pack-class ops (and diagonal-family ops
    routed to the pack stream) into one staged PackPass on the
    [base, hi) group.  Adjacent dense stages with identical control
    predicates compose host-side into one pack."""

    def __init__(self, base: int, hi: int):
        self.base = base
        self.hi = hi
        self.width = 1 << (hi - base)
        self.specs: list = []
        self.mats: list = []     # f64 until flush
        self.ops: list = []
        self.min_cols = 0        # widened column block for super stages

    def add(self, op) -> None:
        self.ops.append(op)
        if op.kind == "mrz":
            half = float(op.matrix[0]) / 2.0
            self.specs.append(("mrz", op.targets,
                               float(np.cos(half)), float(np.sin(half))))
            return
        if op.kind == "diagonal":
            d = op.payload()
            self.specs.append(("diag", op.targets, op.controls, _cstates(op),
                               tuple(np.float32(x) for x in d[0]),
                               tuple(np.float32(x) for x in d[1])))
            return
        m = _embed_axis(_dense_pair(op),
                        tuple(t - self.base for t in op.targets),
                        self.hi - self.base)
        key = (op.controls, _cstates(op))
        last = self.specs[-1] if self.specs else None
        if (last is not None and last[0] == "dense"
                and (last[2], last[3]) == key):
            self.mats[last[1]] = _pair_compose(m, self.mats[last[1]])
            return
        self.mats.append(m)
        self.specs.append(("dense", len(self.mats) - 1, op.controls,
                           _cstates(op)))

    def add_super(self, op) -> None:
        """Superoperator stage coupling one W-axis bit with one low bit:
        the low bit must be inside the column block, so the pass widens
        ``min_cols`` to cover it (``_run_pack_pass``)."""
        self.ops.append(op)
        lo = min(op.targets)
        if lo < self.base:
            self.min_cols = max(self.min_cols, 2 << lo)
        self.specs.append(_super_spec(op))

    def flush(self) -> tuple:
        if not self.specs:
            return None, []
        out = PackPass(self.base, self.width, tuple(self.specs),
                       tuple(m.astype(np.float32) for m in self.mats),
                       self.min_cols)
        ops = self.ops
        self.specs, self.mats, self.ops = [], [], []
        self.min_cols = 0
        return out, ops


def epoch_supported(num_qubits: int, precision: int = 1) -> bool:
    """Whether the epoch engine's envelope admits this register at all
    (individual ops may still fall back per-window).  The remaining
    out-of-envelope cases: f64 states (the kernels are f32 plane engines),
    registers below the 10-qubit degenerate-block floor or above the
    30-qubit int32-index ceiling — and multi-device meshes, which
    ``select_engine`` pins to XLA (the deferred qubit map must materialize
    before sharded collectives).  A DENSITY circuit's register is its
    Choi-doubled 2n-qubit vector, so the same [10, 30] window reads as
    density n in [5, 15]."""
    return precision == 1 and MIN_QUBITS <= num_qubits <= MAX_QUBITS


@lru_cache(maxsize=64)
def plan_circuit(ops: tuple, num_qubits: int) -> EnginePlan:
    """Lower an op tuple (logical wires) into the epoch executor's static
    plan: engine segments, fused passes, and the deferred residual
    permutation.  Pure host work, cached per (ops, n); a cache miss records
    an ``epoch.plan`` span (tracing on) with the lowering's pass counts."""
    with _obs.span("epoch.plan", ops=len(ops), num_qubits=num_qubits) as sp:
        plan = _plan_circuit_impl(ops, num_qubits)
        if sp is not None:
            sp.attrs["hbm_passes"] = plan.hbm_passes
            sp.attrs["pallas_passes"] = plan.pallas_passes
            sp.attrs["xla_ops"] = plan.xla_ops
            sp.attrs["deferred_ops"] = plan.deferred_ops
        return plan


def _plan_circuit_impl(ops: tuple, num_qubits: int) -> EnginePlan:
    n = num_qubits
    if not MIN_QUBITS <= n <= MAX_QUBITS:
        raise ValueError(
            f"epoch executor needs {MIN_QUBITS} <= n <= {MAX_QUBITS}, got {n}")
    perm = list(range(n))
    segments: list = []
    block = _BlockBuilder(n)
    # ONE pending pack builder PER high group (insertion-ordered): a
    # mirrored density layer touches every bra group in turn, and a single
    # pack slot would flush the whole window on each group switch — 10
    # passes/layer where three suffice.  Emission order is block first,
    # then packs in creation order; every cross-stream reorder that
    # emission implies is proven by _stream_commutes at routing time.
    packs: dict[int, _PackBuilder] = {}
    deferred = 0

    def seg(engine: str) -> Segment:
        if not segments or segments[-1].engine != engine:
            segments.append(Segment(engine, [], []))
        return segments[-1]

    def flush_streams() -> None:
        bp, bops = block.flush()
        flushed = [(bp, bops)] if bp is not None else []
        for pb in packs.values():
            pp, pops = pb.flush()
            if pp is not None:
                flushed.append((pp, pops))
        packs.clear()
        if not flushed:
            return
        s = seg("pallas")
        for p, pops in flushed:
            s.passes.append(p)
            s.ops.extend(pops)

    def commutes_with_packs(op, skip: int | None = None) -> bool:
        """Adding ``op`` to the block stream (or to pack ``skip``) emits it
        before every other pending pack's ops: sound only when it commutes
        with all of them."""
        return all(_stream_commutes(op, q)
                   for b, pb in packs.items() if b != skip
                   for q in pb.ops)

    def pack_for(pop, base: int, hi: int) -> "_PackBuilder | None":
        """The pending pack builder for [base, hi), or None when adding
        ``pop`` there cannot be proven sound (the caller flushes)."""
        if not commutes_with_packs(pop, skip=base):
            return None
        pb = packs.get(base)
        if pb is None:
            pb = packs[base] = _PackBuilder(base, hi)
        return pb

    def route_super(pop, sup: tuple) -> None:
        if sup[0] == "super_block":
            # same soundness condition as any block op: it executes before
            # every pending pack pass
            if not commutes_with_packs(pop):
                flush_streams()
            block.add_super(pop)
            return
        base, hi = sup[1], sup[2]
        pb = pack_for(pop, base, hi)
        if pb is None:
            flush_streams()
            pb = packs[base] = _PackBuilder(base, hi)
        if sup[0] == "pack_dense":
            pb.add(pop)
        else:
            pb.add_super(pop)

    def route(pop, cls: str) -> None:
        if cls == "block":
            # a block op executes BEFORE the pending pack passes: sound
            # only when it commutes with everything already in them
            if not commutes_with_packs(pop):
                flush_streams()
            block.add(pop)
            return
        if cls == "either":
            # diagonal family: block-executable in both streams — prefer
            # the block stream, fall to a pack stream when order pins it
            if commutes_with_packs(pop):
                block.add(pop)
                return
            # pinned behind exactly the packs it conflicts with: join the
            # LAST conflicting pack when the later ones tolerate the
            # reorder, else flush everything
            conflict = [b for b, pb in packs.items()
                        if not all(_stream_commutes(pop, q) for q in pb.ops)]
            order = list(packs)
            last = conflict[-1]
            after = order[order.index(last) + 1:]
            if all(_stream_commutes(pop, q)
                   for b in after for q in packs[b].ops):
                packs[last].add(pop)
            else:
                flush_streams()
                block.add(pop)
            return
        base, hi = _fiber_group(min(pop.targets), n)
        pb = pack_for(pop, base, hi)
        if pb is None:
            flush_streams()
            pb = packs[base] = _PackBuilder(base, hi)
        pb.add(pop)

    for op in ops:
        pop = _phys_op(op, perm)
        cls = _classify(pop, n)
        if cls == "defer":
            _absorb_perm(perm, op)
            deferred += 1
            continue
        if cls == "cross2q":
            factors = _cross2q_factors(pop)
            if factors is not None:
                for f in factors:
                    route(f, _classify(f, n))
                continue
            # the odd-bit decomposition needs a unitary payload; a density
            # channel's superoperator (or any degenerate dense payload)
            # lowers as ONE elementwise superoperator stage instead — same
            # pass, zero extra HBM traffic — wherever both partner bits
            # are reachable inside a block
            sup = _super_route(pop, n)
            if sup is not None:
                route_super(pop, sup)
                continue
            cls = "xla"
        if cls == "xla":
            flush_streams()
            seg("xla").ops.append(pop)
            continue
        route(pop, cls)
    flush_streams()
    return EnginePlan(n, segments, tuple(perm), deferred)


# ---------------------------------------------------------------------------
# shared spec appliers (traced inside both kernels)
# ---------------------------------------------------------------------------

def _ctrl_mask(k, controls: tuple, cstates: tuple):
    m = None
    for c, st in zip(controls, cstates):
        t = ((k >> c) & 1) == st
        m = t if m is None else (m & t)
    return m


def _apply_diag_spec(spec, k, xr, xi):
    _, targets, controls, cstates, dr, di = spec
    idx = None
    for j, t in enumerate(targets):
        b = ((k >> t) & 1) << j if j else (k >> t) & 1
        idx = b if idx is None else idx | b
    vr = jnp.full_like(xr, 1.0)
    vi = jnp.zeros_like(xr)
    for b in range(len(dr)):
        if dr[b] == np.float32(1.0) and di[b] == np.float32(0.0):
            continue  # entries equal to 1 are never written
        eq = idx == b
        vr = jnp.where(eq, jnp.float32(dr[b]), vr)
        vi = jnp.where(eq, jnp.float32(di[b]), vi)
    if controls:
        m = _ctrl_mask(k, controls, cstates)
        vr = jnp.where(m, vr, jnp.float32(1.0))
        vi = jnp.where(m, vi, jnp.float32(0.0))
    return xr * vr - xi * vi, xr * vi + xi * vr


def _apply_mrz_spec(spec, k, xr, xi):
    _, targets, c_, s_ = spec
    par = None
    for t in targets:
        b = (k >> t) & 1
        par = b if par is None else par ^ b
    cc = jnp.float32(c_)
    sn = jnp.where(par == 1, jnp.float32(s_), jnp.float32(-s_))
    return xr * cc - xi * sn, xr * sn + xi * cc


def _flip_block_bit(x, j: int):
    """``y[k] = x[k ^ (1 << j)]`` on an (F, S, L) block array: split the
    axis holding global bit ``j`` at its stride and reverse the 2-wide
    factor — pure VPU data movement, no HBM traffic."""
    f, s, l = x.shape
    if j < _SUB_Q[0]:
        y = x.reshape(f, s, l >> (j + 1), 2, 1 << j)
        return jnp.flip(y, 3).reshape(f, s, l)
    if j < _FIBER_Q[0]:
        m = j - _SUB_Q[0]
        y = x.reshape(f, s >> (m + 1), 2, 1 << m, l)
        return jnp.flip(y, 2).reshape(f, s, l)
    m = j - _FIBER_Q[0]
    y = x.reshape(f >> (m + 1), 2, 1 << m, s, l)
    return jnp.flip(y, 1).reshape(f, s, l)


def _flip_pack_bit(x, j: int, base: int):
    """``_flip_block_bit`` twin for the (W, cols) pack view: W-axis bits
    live at [base, hi), column bits at [0, log2 cols) — the pass geometry
    guarantees a superoperator stage's bits are one of each."""
    w, cols = x.shape
    if j >= base:
        m = j - base
        y = x.reshape(w >> (m + 1), 2, 1 << m, cols)
        return jnp.flip(y, 1).reshape(w, cols)
    y = x.reshape(w, cols >> (j + 1), 2, 1 << j)
    return jnp.flip(y, 2).reshape(w, cols)


def _apply_super_spec(spec, k, xr, xi, flip):
    """Arbitrary 2-target dense op as ONE elementwise stage.  For element
    k with target bits (b0, b1) the output is
    ``sum_{a,b} S[(b0,b1),(a,b)] * x[k with bits set to (a, b)]``: the four
    partner amplitudes come from structured bit-flips (``flip``), the
    coefficient row is selected off the global amplitude index like a
    diagonal stage.  All-zero payload columns are skipped host-side, so a
    damping/depolarising superoperator (diagonal plus ONE coupling column)
    costs two flip/select terms, not four.  This is the stage that makes a
    density noise channel block-local: its targets (q, q+n) straddle axis
    groups by construction, where the matmul paths cannot reach and the
    odd-bit decomposition requires unitarity."""
    _, targets, srr, sri, controls, cstates = spec
    t0, t1 = targets
    b0 = (k >> t0) & 1
    b1 = (k >> t1) & 1
    row = b0 + 2 * b1
    f0r, f0i = flip(xr, t0), flip(xi, t0)
    # x with bit t0 forced to 0 / 1
    forced = ((jnp.where(b0 == 0, xr, f0r), jnp.where(b0 == 0, xi, f0i)),
              (jnp.where(b0 == 0, f0r, xr), jnp.where(b0 == 0, f0i, xi)))
    flipped1: dict = {}
    nr = jnp.zeros_like(xr)
    ni = jnp.zeros_like(xi)
    zero = np.float32(0.0)
    for col in range(4):
        ca, cb = col & 1, col >> 1
        colr = tuple(srr[r][col] for r in range(4))
        coli = tuple(sri[r][col] for r in range(4))
        if all(v == zero for v in colr + coli):
            continue
        ar, ai = forced[ca]
        if ca not in flipped1:
            flipped1[ca] = (flip(ar, t1), flip(ai, t1))
        g1r, g1i = flipped1[ca]
        yr = jnp.where(b1 == cb, ar, g1r)
        yi = jnp.where(b1 == cb, ai, g1i)
        cr = jnp.zeros_like(xr)
        ci = jnp.zeros_like(xr)
        for r in range(4):
            if colr[r] == zero and coli[r] == zero:
                continue
            sel = row == r
            if colr[r] != zero:
                cr = jnp.where(sel, jnp.float32(colr[r]), cr)
            if coli[r] != zero:
                ci = jnp.where(sel, jnp.float32(coli[r]), ci)
        nr = nr + cr * yr - ci * yi
        ni = ni + cr * yi + ci * yr
    if controls:
        m = _ctrl_mask(k, controls, cstates)
        nr = jnp.where(m, nr, xr)
        ni = jnp.where(m, ni, xi)
    return nr, ni


# ---------------------------------------------------------------------------
# the fused block kernel
# ---------------------------------------------------------------------------

def _epoch_block_kernel(specs: tuple, block_amps: int, *refs):
    """Apply a static program of block-local ops to one (F, S, L) block.

    ``specs`` entries (everything host-constant; the only kernel INPUTS are
    the deduplicated embedded axis matrices, two refs each):

    - ``('dense', axis, mat_idx, controls, cstates)``: complex contraction
      of the lane/sublane/fiber axis with embedded matrix ``mat_idx``;
      controls select per element off the global amplitude index.
    - ``('diag', targets, controls, cstates, dr, di)``: elementwise complex
      multiply by the diagonal entry selected by the targets' index bits
      (entries equal to 1 are never written — a controlled phase costs one
      select).
    - ``('mrz', targets, cos, sin)``: parity-keyed phase rotation,
      exp(-i a/2 Z..Z); the trig is precomputed host-side in f64 (the mrz
      angle-precision contract, see circuit.op_operands).
    """
    nmats = (len(refs) - 4) // 2
    mats = refs[:2 * nmats]
    re_ref, im_ref, ore_ref, oim_ref = refs[2 * nmats:]
    hp = jax.lax.Precision.HIGHEST
    xr = re_ref[...]
    xi = im_ref[...]
    f, s, l = xr.shape
    k = _block_k(xr.shape, pl.program_id(0) * jnp.int32(block_amps))

    def rdot(x, m):     # minor axis: out[., j] = sum_l x[., l] m[j, l]
        return jax.lax.dot_general(x, m, (((1,), (1,)), ((), ())),
                                   precision=hp,
                                   preferred_element_type=x.dtype)

    def ldot(m, x):     # leading axis: out[j, .] = sum_a m[j, a] x[a, .]
        return jax.lax.dot_general(m, x, (((1,), (0,)), ((), ())),
                                   precision=hp,
                                   preferred_element_type=x.dtype)

    for spec in specs:
        tag = spec[0]
        if tag == "dense":
            _, axis, mi, controls, cstates = spec
            mr = mats[2 * mi][...]
            mim = mats[2 * mi + 1][...]
            if axis == "lane":
                ar = xr.reshape(f * s, l)
                ai = xi.reshape(f * s, l)
                nr = (rdot(ar, mr) - rdot(ai, mim)).reshape(f, s, l)
                ni = (rdot(ar, mim) + rdot(ai, mr)).reshape(f, s, l)
            elif axis == "sub":
                # left-multiply with S leading (see pallas_layer csub)
                ar = xr.transpose(1, 0, 2).reshape(s, f * l)
                ai = xi.transpose(1, 0, 2).reshape(s, f * l)
                nr = (ldot(mr, ar) - ldot(mim, ai)).reshape(s, f, l) \
                    .transpose(1, 0, 2)
                ni = (ldot(mim, ar) + ldot(mr, ai)).reshape(s, f, l) \
                    .transpose(1, 0, 2)
            else:
                ar = xr.reshape(f, s * l)
                ai = xi.reshape(f, s * l)
                nr = (ldot(mr, ar) - ldot(mim, ai)).reshape(f, s, l)
                ni = (ldot(mim, ar) + ldot(mr, ai)).reshape(f, s, l)
            if controls:
                m = _ctrl_mask(k, controls, cstates)
                nr = jnp.where(m, nr, xr)
                ni = jnp.where(m, ni, xi)
            xr, xi = nr, ni
        elif tag == "diag":
            xr, xi = _apply_diag_spec(spec, k, xr, xi)
        elif tag == "super":
            xr, xi = _apply_super_spec(spec, k, xr, xi, _flip_block_bit)
        else:
            xr, xi = _apply_mrz_spec(spec, k, xr, xi)
    ore_ref[...] = xr
    oim_ref[...] = xi


def _run_block_pass(re, im, bp: BlockPass):
    top, shape3, blk = _geometry(re.shape[0])
    ins = []
    in_specs = []
    for m in bp.mats:
        d = m.shape[1]
        ins += [jnp.asarray(m[0]), jnp.asarray(m[1])]
        in_specs += [pl.BlockSpec((d, d), lambda i: (0, 0))] * 2
    state_spec = pl.BlockSpec(blk, lambda i: (i, 0, 0))
    run = pl.pallas_call(
        partial(_epoch_block_kernel, bp.specs, blk[0] * blk[1] * blk[2]),
        interpret=_interpret(),
        grid=(top,),
        in_specs=in_specs + [state_spec, state_spec],
        out_specs=[state_spec, state_spec],
        out_shape=[
            jax.ShapeDtypeStruct(shape3, re.dtype),
            jax.ShapeDtypeStruct(shape3, re.dtype),
        ],
        # out block (i) reads only in block (i): the state planes alias
        # their outputs and the whole fused pass runs truly in place
        input_output_aliases={len(ins): 0, len(ins) + 1: 1},
    )
    out_re, out_im = run(*ins, re.reshape(shape3), im.reshape(shape3))
    return out_re.reshape(-1), out_im.reshape(-1)


# ---------------------------------------------------------------------------
# the staged pack kernel (high-qubit groups)
# ---------------------------------------------------------------------------

def _epoch_pack_kernel(specs: tuple, w: int, right: int, cols: int,
                       base: int, *refs):
    """Apply a static stage program to one (W, cols) block of the
    (left, W, right) high-group view.  The global amplitude index of
    element (f, c) of grid block (i, j) is
    ``k = (i*W + f) * right + j*cols + c`` (int32: n <= 30), off which
    control predicates, diagonal factors and mrz parities are computed —
    so controlled dense ops on high qubits no longer force an XLA segment.

    Stages: ``('dense', mat_idx, controls, cstates)`` contracts the W axis
    with the composed pack; ``('diag', ...)``/``('mrz', ...)`` are the
    same elementwise stages as the block kernel."""
    nmats = (len(refs) - 4) // 2
    mats = refs[:2 * nmats]
    re_ref, im_ref, ore_ref, oim_ref = refs[2 * nmats:]
    hp = jax.lax.Precision.HIGHEST
    xr = re_ref[...]
    xi = im_ref[...]
    f = jax.lax.broadcasted_iota(jnp.int32, xr.shape, 0)
    cix = jax.lax.broadcasted_iota(jnp.int32, xr.shape, 1)
    k = ((pl.program_id(0) * jnp.int32(w) + f) * jnp.int32(right)
         + pl.program_id(1) * jnp.int32(cols) + cix)

    def ldot(m, x):
        return jax.lax.dot_general(m, x, (((1,), (0,)), ((), ())),
                                   precision=hp,
                                   preferred_element_type=x.dtype)

    for spec in specs:
        tag = spec[0]
        if tag == "dense":
            _, mi, controls, cstates = spec
            mr = mats[2 * mi][...]
            mim = mats[2 * mi + 1][...]
            nr = ldot(mr, xr) - ldot(mim, xi)
            ni = ldot(mim, xr) + ldot(mr, xi)
            if controls:
                m = _ctrl_mask(k, controls, cstates)
                nr = jnp.where(m, nr, xr)
                ni = jnp.where(m, ni, xi)
            xr, xi = nr, ni
        elif tag == "diag":
            xr, xi = _apply_diag_spec(spec, k, xr, xi)
        elif tag == "super":
            xr, xi = _apply_super_spec(spec, k, xr, xi,
                                       partial(_flip_pack_bit, base=base))
        else:
            xr, xi = _apply_mrz_spec(spec, k, xr, xi)
    ore_ref[...] = xr
    oim_ref[...] = xi


def _run_pack_pass(re, im, pp: PackPass):
    n_amps = re.shape[0]
    right = 1 << pp.base
    w = pp.width
    left = n_amps // (right * w)
    # superoperator stages widen the column block so their low partner bit
    # stays inside one grid block (PackPass.min_cols; bounded by the
    # _SUPER_COLS_CAP VMEM budget at plan time)
    cols = min(max(_FIBER_COLS, pp.min_cols), right)
    shape = (left * w, right)  # rank-2: rows a*w+f, block rows = one group
    ins = []
    in_specs = []
    for m in pp.mats:
        ins += [jnp.asarray(m[0]), jnp.asarray(m[1])]
        in_specs += [pl.BlockSpec((w, w), lambda i, j: (0, 0))] * 2
    state_spec = pl.BlockSpec((w, cols), lambda i, j: (i, j))
    run = pl.pallas_call(
        partial(_epoch_pack_kernel, pp.specs, w, right, cols, pp.base),
        interpret=_interpret(),
        grid=(left, right // cols),
        in_specs=in_specs + [state_spec, state_spec],
        out_specs=[state_spec, state_spec],
        out_shape=[
            jax.ShapeDtypeStruct(shape, re.dtype),
            jax.ShapeDtypeStruct(shape, re.dtype),
        ],
        # in-place: out block (i, j) reads only in block (i, j)
        input_output_aliases={len(ins): 0, len(ins) + 1: 1},
    )
    out_re, out_im = run(*ins, re.reshape(shape), im.reshape(shape))
    return out_re.reshape(-1), out_im.reshape(-1)


# ---------------------------------------------------------------------------
# execution entry points
# ---------------------------------------------------------------------------

def run_planes(re: jax.Array, im: jax.Array, ops: tuple):
    """Apply ``ops`` to plane-pair storage through the epoch plan.
    CONSUMES both planes (every Pallas pass aliases).  Returns
    ``(re, im, residual_perm)`` — the deferred qubit map is NOT
    materialized: logical wire q's content sits at position
    ``residual_perm[q]`` (the qft_inplace ``bit_reversal=False``
    convention); callers chain further epochs or reconcile once."""
    plan = plan_circuit(tuple(ops), int(re.shape[0]).bit_length() - 1)
    for segment in plan.segments:
        if segment.engine == "pallas":
            for p in segment.passes:
                if p.kind == "block":
                    re, im = _run_block_pass(re, im, p)
                else:
                    re, im = _run_pack_pass(re, im, p)
        else:
            from ..circuit import _apply_one
            state = jnp.stack([re, im])
            for op in segment.ops:
                state = _apply_one(state, op)
            re, im = state[0], state[1]
    return re, im, plan.residual_perm


def run_ops_planes(state: jax.Array, ops: tuple) -> jax.Array:
    """(2, N) compatibility entry: plane split, epoch chain, residual
    permutation reconciled PER PLANE (``reconcile_perm_planes`` — the
    aliasing chain is never broken by a premature stack), one stack at the
    boundary.  Under a donating jit (:func:`jit_program`) XLA aliases that
    stack into the donated input buffer; plane-pair callers use
    :func:`jit_program_planes` and never stack at all."""
    from .apply import num_qubits_of, reconcile_perm_planes
    n = num_qubits_of(state)
    if state.dtype != jnp.float32:
        raise ValueError(f"epoch executor is f32-only, got {state.dtype}")
    if not MIN_QUBITS <= n <= MAX_QUBITS:
        raise ValueError(
            f"epoch executor needs {MIN_QUBITS} <= n <= {MAX_QUBITS}, got {n}")
    re, im, perm = run_planes(state[0], state[1], tuple(ops))
    re, im = reconcile_perm_planes(re, im, perm)
    return jnp.stack([re, im])


def jit_program(ops, donate: bool = False):
    """One jitted ``state -> state`` program over the epoch plan.  Traced
    with x64 disabled (the Mosaic lowering constraint shared by every
    in-place engine; safe here because mrz phases are precomputed host-side
    in f64 — no traced f64 operand exists in the program)."""
    ops = tuple(ops)

    @partial(jax.jit, donate_argnums=(0,) if donate else ())
    def run(state):
        return run_ops_planes(state, ops)

    def call(state):
        with _compat.enable_x64(False):
            return run(state)

    return call


def jit_program_planes(ops, donate: bool = True):
    """The plane-pair twin of :func:`jit_program`: one jitted
    ``(re, im) -> (re, im)`` program with BOTH planes donated, the residual
    qubit map reconciled per plane, and no (2, N) stack anywhere — the
    truly in-place program plane-storage registers need at the 30-qubit
    single-chip ceiling.  Input/output aliasing is machine-audited by
    ``analysis.audit_epoch_donation``."""
    ops = tuple(ops)

    @partial(jax.jit, donate_argnums=(0, 1) if donate else ())
    def run(re, im):
        from .apply import reconcile_perm_planes
        re, im, perm = run_planes(re, im, ops)
        return reconcile_perm_planes(re, im, perm)

    def call(re, im):
        if re.dtype != jnp.float32 or im.dtype != jnp.float32:
            raise ValueError("epoch executor is f32-only, got "
                             f"({re.dtype}, {im.dtype}) planes")
        with _compat.enable_x64(False):
            return run(re, im)

    return call
