"""General Pallas epoch executor: the in-place engines as a circuit backend.

``ops/qft_inplace.py`` proved that the fastest way to run a circuit on one
chip is NOT one XLA pass per gate but a handful of aliased Pallas passes:
BENCH_r05 has the in-place engine at 2.1-2.7e11 amps/s against the XLA
engine's 7.1e10 on the same 28q QFT.  That module, however, is a hand-written
closed form only QFT circuits can reach.  This module generalizes its three
tricks into a backend ``compile_circuit`` can target for ARBITRARY scheduled
windows of 1q/2q/diagonal ops (PAPER.md's thesis: interchangeable kernel
implementations behind one dispatch layer; ROADMAP item 2):

1. **Fused block passes.**  Every op whose dense action is confined to one
   minor axis group of the tile view — lane (qubits 0-6), sublane (7-9) or
   fiber (10-16) — and every diagonal/parity op on ANY wires (their factor
   is a function of the global amplitude index, which each (F=128, S=8,
   L=128) block can reconstruct from ``program_id``) is block-local.  A
   maximal run of such ops becomes ONE aliased Pallas pass applying all of
   them MXU/VPU-resident in VMEM: k gates for one HBM read+write of the
   state, the generalization of ``_qft_tail_kernel``'s 33-passes-in-one.

2. **Fiber passes for high qubits.**  Dense uncontrolled ops on qubits
   >= 17 run through the aliased fiber engine (``pallas_layer
   _apply_fiber_p``); consecutive ops in the same 7-qubit fiber group are
   kron-embedded and composed host-side into one pack — one pass per group
   per run, the generalization of the per-stage H passes.

3. **Deferred qubit map.**  ``swap``/``bitperm`` ops never move data: they
   update a logical->physical wire permutation that later ops absorb into
   their wiring (the residual permutation is carried across epoch
   boundaries and materialized once, by ``reconcile_perm``, at the end of
   the program — or returned to plane-pair callers, the unordered-QFT
   convention).  The QFT's trailing swap network therefore costs ZERO
   passes, and the whole transform lowers to exactly the hand-written
   engine's ``2(n-17)+1`` HBM passes (regression-tested).

Ops outside the supported set (cross-group multi-target dense gates,
controlled dense on high qubits, >5-target general diagonals) split the
epoch: they execute through the XLA gate engine between Pallas segments,
with wires translated through the live permutation, so ANY circuit compiles
— the planner's engine cost model (parallel/planner.py ``select_engine``)
just rates mostly-unsupported circuits as XLA wins.

Envelope: f32 plane storage, 17 <= n <= 30 (the in-place layer floor; int32
block indices).  Correctness gate: ``analysis/equivalence.py
check_epoch_plan`` proves every lowering IR-equivalent to its window and
``probe_epoch_execution`` runs the actual kernels (``pl.pallas_call``
interpret mode on CPU) against the XLA engine — both wired into
``--verify-schedule --engine pallas`` and the tier-1 suite.  The residual
permutation MUST be materialized before any sharded collective (the map
renames amplitude-index bits, which a mesh reshards on — docs/DESIGN.md);
the engine is therefore single-device, and ``select_engine`` pins
multi-device deployments to XLA.
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl

from .. import _compat
from .. import obs as _obs

from .pallas_layer import (LANE, SUB, _fiber_group, _interpret, _shape3,
                           _state_spec)
from .qft_inplace import _block_k

__all__ = ["EnginePlan", "Segment", "plan_circuit", "epoch_supported",
           "run_ops_planes", "run_planes", "jit_program", "MIN_QUBITS",
           "MAX_QUBITS"]

MIN_QUBITS = 17   # the (fiber, sublane, lane) block view floor
MAX_QUBITS = 30   # int32 global amplitude indices in the block kernels

# widest general diagonal lowered as in-kernel selects (2^5 = 32 entries);
# wider diagonals fall back to the XLA gather engine
_DIAG_CAP = 5

# axis groups of the minor 17 qubits in the (F, S, L) tile view
_LANE_Q = (0, 7)
_SUB_Q = (7, 10)
_FIBER_Q = (10, 17)

_X_PAIR = np.stack([np.array([[0.0, 1.0], [1.0, 0.0]]), np.zeros((2, 2))])
_Y_PAIR = np.stack([np.zeros((2, 2)), np.array([[0.0, -1.0], [1.0, 0.0]])])
_YC_PAIR = np.stack([np.zeros((2, 2)), np.array([[0.0, 1.0], [-1.0, 0.0]])])


# ---------------------------------------------------------------------------
# host-side lowering: ops -> passes
# ---------------------------------------------------------------------------

def _embed_axis(up: np.ndarray, rel: tuple, width: int) -> np.ndarray:
    """Embed a (2, 2^k, 2^k) real-pair unitary acting on axis-index bits
    ``rel`` (matrix index bit j <-> axis bit rel[j], the engine-wide
    targets[j] convention) into the full (2, 2^width, 2^width) axis matrix,
    identity on the remaining bits."""
    dim = 1 << width
    m = up[0] + 1j * up[1]
    a = np.arange(dim)
    sub = np.zeros(dim, np.int64)
    mask = 0
    for j, p in enumerate(rel):
        sub |= ((a >> p) & 1) << j
        mask |= 1 << p
    rest = a & ~mask
    out = m[sub[:, None], sub[None, :]] * (rest[:, None] == rest[None, :])
    return np.stack([out.real, out.imag])  # f64; cast to f32 at pass build


def _pair_compose(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Complex compose on real pairs: ``a`` AFTER ``b`` (a @ b)."""
    return np.stack([a[0] @ b[0] - a[1] @ b[1],
                     a[0] @ b[1] + a[1] @ b[0]])


def _dense_pair(op) -> np.ndarray:
    """The (2, 2^k, 2^k) real-pair matrix of a dense-kind op."""
    if op.kind == "x":
        return _X_PAIR
    if op.kind == "y":
        return _Y_PAIR
    if op.kind == "y*":
        return _YC_PAIR
    return op.payload()


def _cstates(op) -> tuple:
    return tuple(op.control_states) or (1,) * len(op.controls)


@dataclasses.dataclass(frozen=True, eq=False)
class BlockPass:
    """One fused block-local Pallas pass: ``specs`` is the static kernel
    program (see ``_epoch_block_kernel``), ``mats`` the deduplicated
    embedded axis matrices it matmuls with."""
    specs: tuple
    mats: tuple          # of np (2, D, D) float32, D in {128, 8}

    @property
    def kind(self) -> str:
        return "block"


@dataclasses.dataclass(frozen=True, eq=False)
class FiberPass:
    """One aliased fiber pass: the composed kron pack of a run of dense
    ops on one high-qubit fiber group [base, base+log2(width))."""
    base: int
    width: int
    pack: np.ndarray     # (2, width, width) float32

    @property
    def kind(self) -> str:
        return "fiber"


@dataclasses.dataclass
class Segment:
    """A maximal single-engine run: ``ops`` are the window's ops with wires
    already translated to PHYSICAL positions (the audit/reporting view and,
    for xla segments, the execution list); ``passes`` is the Pallas
    lowering (pallas segments only)."""
    engine: str                  # 'pallas' | 'xla'
    ops: list
    passes: list


@dataclasses.dataclass
class EnginePlan:
    """The epoch executor's static lowering of one circuit."""
    num_qubits: int
    segments: list
    residual_perm: tuple         # perm[logical] = physical position
    deferred_ops: int            # swap/bitperm ops absorbed with zero passes

    @property
    def pallas_passes(self) -> int:
        return sum(len(s.passes) for s in self.segments
                   if s.engine == "pallas")

    @property
    def pallas_ops(self) -> int:
        return sum(len(s.ops) for s in self.segments if s.engine == "pallas")

    @property
    def xla_ops(self) -> int:
        return sum(len(s.ops) for s in self.segments if s.engine == "xla")

    @property
    def hbm_passes(self) -> int:
        """Modeled HBM passes of the lowered program: one per Pallas pass,
        one per XLA-segment gate.  The deferred residual permutation is
        excluded — it is carried, not executed (the unordered-transform
        convention of qft_inplace), and single-chip materialization is two
        plane gathers charged to whoever forces it."""
        return self.pallas_passes + self.xla_ops

    def summary(self) -> dict:
        return {
            "num_qubits": self.num_qubits,
            "segments": [{"engine": s.engine, "ops": len(s.ops),
                          "passes": len(s.passes) if s.engine == "pallas"
                          else len(s.ops)}
                         for s in self.segments],
            "pallas_passes": self.pallas_passes,
            "pallas_ops": self.pallas_ops,
            "xla_ops": self.xla_ops,
            "deferred_ops": self.deferred_ops,
            "hbm_passes": self.hbm_passes,
            "residual_nontrivial": self.residual_perm
            != tuple(range(self.num_qubits)),
        }


def _phys_op(op, perm: list):
    """``op`` with targets/controls translated through the live
    logical->physical map (bitperm destination payloads are wires too)."""
    from ..circuit import GateOp
    t = tuple(perm[q] for q in op.targets)
    c = tuple(perm[q] for q in op.controls)
    mat = op.matrix
    if op.kind == "bitperm":
        mat = tuple(float(perm[int(d)]) for d in op.matrix)
    if t == op.targets and c == op.controls and mat == op.matrix:
        return op
    return GateOp(op.kind, t, c, op.control_states, mat, op.shape)


def _absorb_perm(perm: list, op) -> None:
    """Fold a logical ``swap``/``bitperm`` into the deferred map: content
    of logical wire t now answers to logical name d, so later ops on d land
    on t's physical home (G_d . P = P . G_t for the permutation P)."""
    if op.kind == "swap":
        a, b = op.targets
        perm[a], perm[b] = perm[b], perm[a]
    else:
        old = list(perm)
        for t, d in zip(op.targets, op.matrix):
            perm[int(d)] = old[t]


def _axis_group(targets: tuple) -> tuple | None:
    """The minor axis group confining all (physical) ``targets``, or None."""
    for group in (_LANE_Q, _SUB_Q, _FIBER_Q):
        if all(group[0] <= t < group[1] for t in targets):
            return group
    return None


def _classify(op, n: int) -> str:
    """Lowering class of a PHYSICAL op: 'defer' (absorbed into the qubit
    map), 'block' (fused block-local pass), 'fiber' (high-qubit pack pass),
    or 'xla' (gate-engine fallback splitting the epoch)."""
    if op.kind in ("swap", "bitperm"):
        return "defer"
    if op.kind == "mrz":
        return "block"
    if op.kind == "diagonal":
        return "block" if len(op.targets) <= _DIAG_CAP else "xla"
    if op.kind in ("matrix", "x", "y", "y*"):
        if _axis_group(op.targets) is not None:
            return "block"
        if not op.controls and min(op.targets) >= MIN_QUBITS:
            base, hi = _fiber_group(min(op.targets), n)
            if max(op.targets) < hi:
                return "fiber"
        return "xla"
    return "xla"


class _BlockBuilder:
    """Accumulates consecutive block-class ops into one BlockPass."""

    def __init__(self):
        self.specs: list = []
        self.mats: list = []
        self._mat_idx: dict = {}

    def _intern(self, m: np.ndarray) -> int:
        key = m.tobytes()
        i = self._mat_idx.get(key)
        if i is None:
            i = self._mat_idx[key] = len(self.mats)
            self.mats.append(m)
        return i

    def add(self, op) -> None:
        if op.kind == "mrz":
            half = float(op.matrix[0]) / 2.0
            self.specs.append(("mrz", op.targets,
                               float(np.cos(half)), float(np.sin(half))))
            return
        if op.kind == "diagonal":
            d = op.payload()
            self.specs.append(("diag", op.targets, op.controls, _cstates(op),
                               tuple(np.float32(x) for x in d[0]),
                               tuple(np.float32(x) for x in d[1])))
            return
        group = _axis_group(op.targets)
        lo, hi = group
        axis = {0: "lane", 7: "sub", 10: "fiber"}[lo]
        m = _embed_axis(_dense_pair(op), tuple(t - lo for t in op.targets),
                        hi - lo).astype(np.float32)
        self.specs.append(("dense", axis, self._intern(m), op.controls,
                           _cstates(op)))

    def flush(self):
        if not self.specs:
            return None
        out = BlockPass(tuple(self.specs), tuple(self.mats))
        self.specs, self.mats, self._mat_idx = [], [], {}
        return out


def epoch_supported(num_qubits: int, precision: int = 1) -> bool:
    """Whether the epoch engine's envelope admits this register at all
    (individual ops may still fall back per-window)."""
    return precision == 1 and MIN_QUBITS <= num_qubits <= MAX_QUBITS


@lru_cache(maxsize=64)
def plan_circuit(ops: tuple, num_qubits: int) -> EnginePlan:
    """Lower an op tuple (logical wires) into the epoch executor's static
    plan: engine segments, fused passes, and the deferred residual
    permutation.  Pure host work, cached per (ops, n); a cache miss records
    an ``epoch.plan`` span (tracing on) with the lowering's pass counts."""
    with _obs.span("epoch.plan", ops=len(ops), num_qubits=num_qubits) as sp:
        plan = _plan_circuit_impl(ops, num_qubits)
        if sp is not None:
            sp.attrs["hbm_passes"] = plan.hbm_passes
            sp.attrs["pallas_passes"] = plan.pallas_passes
            sp.attrs["xla_ops"] = plan.xla_ops
            sp.attrs["deferred_ops"] = plan.deferred_ops
        return plan


def _plan_circuit_impl(ops: tuple, num_qubits: int) -> EnginePlan:
    n = num_qubits
    if not MIN_QUBITS <= n <= MAX_QUBITS:
        raise ValueError(
            f"epoch executor needs {MIN_QUBITS} <= n <= {MAX_QUBITS}, got {n}")
    perm = list(range(n))
    segments: list = []
    builder = _BlockBuilder()
    fiber_run: list | None = None   # [base, width, pack]
    deferred = 0

    def seg(engine: str) -> Segment:
        if not segments or segments[-1].engine != engine:
            segments.append(Segment(engine, [], []))
        return segments[-1]

    def flush_block():
        bp = builder.flush()
        if bp is not None:
            seg("pallas").passes.append(bp)

    def flush_fiber():
        nonlocal fiber_run
        if fiber_run is not None:
            seg("pallas").passes.append(
                FiberPass(fiber_run[0], fiber_run[1],
                          fiber_run[2].astype(np.float32)))
            fiber_run = None

    for op in ops:
        pop = _phys_op(op, perm)
        cls = _classify(pop, n)
        if cls == "defer":
            _absorb_perm(perm, op)
            deferred += 1
            continue
        if cls == "block":
            flush_fiber()
            builder.add(pop)
            seg("pallas").ops.append(pop)
            continue
        if cls == "fiber":
            flush_block()
            base, hi = _fiber_group(min(pop.targets), n)
            width = 1 << (hi - base)
            pack = _embed_axis(_dense_pair(pop),
                               tuple(t - base for t in pop.targets),
                               hi - base)
            if fiber_run is not None and fiber_run[0] == base:
                fiber_run[2] = _pair_compose(pack, fiber_run[2])
            else:
                flush_fiber()
                fiber_run = [base, width, pack]
            seg("pallas").ops.append(pop)
            continue
        flush_block()
        flush_fiber()
        seg("xla").ops.append(pop)
    flush_block()
    flush_fiber()
    return EnginePlan(n, segments, tuple(perm), deferred)


# ---------------------------------------------------------------------------
# the fused block kernel
# ---------------------------------------------------------------------------

def _epoch_block_kernel(specs: tuple, *refs):
    """Apply a static program of block-local ops to one (F, S, L) block.

    ``specs`` entries (everything host-constant; the only kernel INPUTS are
    the deduplicated embedded axis matrices, two refs each):

    - ``('dense', axis, mat_idx, controls, cstates)``: complex contraction
      of the lane/sublane/fiber axis with embedded matrix ``mat_idx``;
      controls select per element off the global amplitude index.
    - ``('diag', targets, controls, cstates, dr, di)``: elementwise complex
      multiply by the diagonal entry selected by the targets' index bits
      (entries equal to 1 are never written — a controlled phase costs one
      select).
    - ``('mrz', targets, cos, sin)``: parity-keyed phase rotation,
      exp(-i a/2 Z..Z); the trig is precomputed host-side in f64 (the mrz
      angle-precision contract, see circuit.op_operands).
    """
    nmats = (len(refs) - 4) // 2
    mats = refs[:2 * nmats]
    re_ref, im_ref, ore_ref, oim_ref = refs[2 * nmats:]
    hp = jax.lax.Precision.HIGHEST
    xr = re_ref[...]
    xi = im_ref[...]
    f, s, l = xr.shape
    k = _block_k(xr.shape, pl.program_id(0) * jnp.int32(LANE * SUB * LANE))

    def bit(q):
        return (k >> q) & 1

    def ctrl(controls, cstates):
        m = None
        for c, st in zip(controls, cstates):
            t = bit(c) == st
            m = t if m is None else (m & t)
        return m

    def rdot(x, m):     # minor axis: out[., j] = sum_l x[., l] m[j, l]
        return jax.lax.dot_general(x, m, (((1,), (1,)), ((), ())),
                                   precision=hp,
                                   preferred_element_type=x.dtype)

    def ldot(m, x):     # leading axis: out[j, .] = sum_a m[j, a] x[a, .]
        return jax.lax.dot_general(m, x, (((1,), (0,)), ((), ())),
                                   precision=hp,
                                   preferred_element_type=x.dtype)

    for spec in specs:
        tag = spec[0]
        if tag == "dense":
            _, axis, mi, controls, cstates = spec
            mr = mats[2 * mi][...]
            mim = mats[2 * mi + 1][...]
            if axis == "lane":
                ar = xr.reshape(f * s, l)
                ai = xi.reshape(f * s, l)
                nr = (rdot(ar, mr) - rdot(ai, mim)).reshape(f, s, l)
                ni = (rdot(ar, mim) + rdot(ai, mr)).reshape(f, s, l)
            elif axis == "sub":
                # left-multiply with S leading (see pallas_layer csub)
                ar = xr.transpose(1, 0, 2).reshape(s, f * l)
                ai = xi.transpose(1, 0, 2).reshape(s, f * l)
                nr = (ldot(mr, ar) - ldot(mim, ai)).reshape(s, f, l) \
                    .transpose(1, 0, 2)
                ni = (ldot(mim, ar) + ldot(mr, ai)).reshape(s, f, l) \
                    .transpose(1, 0, 2)
            else:
                ar = xr.reshape(f, s * l)
                ai = xi.reshape(f, s * l)
                nr = (ldot(mr, ar) - ldot(mim, ai)).reshape(f, s, l)
                ni = (ldot(mim, ar) + ldot(mr, ai)).reshape(f, s, l)
            if controls:
                m = ctrl(controls, cstates)
                nr = jnp.where(m, nr, xr)
                ni = jnp.where(m, ni, xi)
            xr, xi = nr, ni
        elif tag == "diag":
            _, targets, controls, cstates, dr, di = spec
            idx = None
            for j, t in enumerate(targets):
                b = bit(t) << j if j else bit(t)
                idx = b if idx is None else idx | b
            vr = jnp.full_like(xr, 1.0)
            vi = jnp.zeros_like(xr)
            for b in range(len(dr)):
                if dr[b] == np.float32(1.0) and di[b] == np.float32(0.0):
                    continue
                eq = idx == b
                vr = jnp.where(eq, jnp.float32(dr[b]), vr)
                vi = jnp.where(eq, jnp.float32(di[b]), vi)
            if controls:
                m = ctrl(controls, cstates)
                vr = jnp.where(m, vr, jnp.float32(1.0))
                vi = jnp.where(m, vi, jnp.float32(0.0))
            xr, xi = xr * vr - xi * vi, xr * vi + xi * vr
        else:
            _, targets, c_, s_ = spec
            par = None
            for t in targets:
                par = bit(t) if par is None else par ^ bit(t)
            cc = jnp.float32(c_)
            sn = jnp.where(par == 1, jnp.float32(s_), jnp.float32(-s_))
            xr, xi = xr * cc - xi * sn, xr * sn + xi * cc
    ore_ref[...] = xr
    oim_ref[...] = xi


def _run_block_pass(re, im, bp: BlockPass):
    top, shape3 = _shape3(re.shape[0])
    ins = []
    in_specs = []
    for m in bp.mats:
        d = m.shape[1]
        ins += [jnp.asarray(m[0]), jnp.asarray(m[1])]
        in_specs += [pl.BlockSpec((d, d), lambda i: (0, 0))] * 2
    run = pl.pallas_call(
        partial(_epoch_block_kernel, bp.specs),
        interpret=_interpret(),
        grid=(top,),
        in_specs=in_specs + [_state_spec(), _state_spec()],
        out_specs=[_state_spec(), _state_spec()],
        out_shape=[
            jax.ShapeDtypeStruct(shape3, re.dtype),
            jax.ShapeDtypeStruct(shape3, re.dtype),
        ],
        # out block (i) reads only in block (i): the state planes alias
        # their outputs and the whole fused pass runs truly in place
        input_output_aliases={len(ins): 0, len(ins) + 1: 1},
    )
    out_re, out_im = run(*ins, re.reshape(shape3), im.reshape(shape3))
    return out_re.reshape(-1), out_im.reshape(-1)


def _run_fiber_pass(re, im, fp: FiberPass):
    from .pallas_layer import _apply_fiber_p
    return _apply_fiber_p(re, im, jnp.asarray(fp.pack), fp.base, fp.width)


# ---------------------------------------------------------------------------
# execution entry points
# ---------------------------------------------------------------------------

def run_planes(re: jax.Array, im: jax.Array, ops: tuple):
    """Apply ``ops`` to plane-pair storage through the epoch plan.
    CONSUMES both planes (every Pallas pass aliases).  Returns
    ``(re, im, residual_perm)`` — the deferred qubit map is NOT
    materialized: logical wire q's content sits at position
    ``residual_perm[q]`` (the qft_inplace ``bit_reversal=False``
    convention); callers chain further epochs or reconcile once."""
    plan = plan_circuit(tuple(ops), int(re.shape[0]).bit_length() - 1)
    for segment in plan.segments:
        if segment.engine == "pallas":
            for p in segment.passes:
                if p.kind == "block":
                    re, im = _run_block_pass(re, im, p)
                else:
                    re, im = _run_fiber_pass(re, im, p)
        else:
            from ..circuit import _apply_one
            state = jnp.stack([re, im])
            for op in segment.ops:
                state = _apply_one(state, op)
            re, im = state[0], state[1]
    return re, im, plan.residual_perm


def run_ops_planes(state: jax.Array, ops: tuple) -> jax.Array:
    """(2, N) compatibility entry: plane split, epoch chain, residual
    permutation reconciled (``reconcile_perm`` — fused prefix transposes).
    The plane slice/stack at the boundaries costs a state copy next to the
    truly in-place :func:`run_planes`; fine through 29 qubits."""
    from .apply import num_qubits_of, reconcile_perm
    n = num_qubits_of(state)
    if state.dtype != jnp.float32:
        raise ValueError(f"epoch executor is f32-only, got {state.dtype}")
    if not MIN_QUBITS <= n <= MAX_QUBITS:
        raise ValueError(
            f"epoch executor needs {MIN_QUBITS} <= n <= {MAX_QUBITS}, got {n}")
    re, im, perm = run_planes(state[0], state[1], tuple(ops))
    return reconcile_perm(jnp.stack([re, im]), perm)


def jit_program(ops, donate: bool = False):
    """One jitted ``state -> state`` program over the epoch plan.  Traced
    with x64 disabled (the Mosaic lowering constraint shared by every
    in-place engine; safe here because mrz phases are precomputed host-side
    in f64 — no traced f64 operand exists in the program)."""
    ops = tuple(ops)

    @partial(jax.jit, donate_argnums=(0,) if donate else ())
    def run(state):
        return run_ops_planes(state, ops)

    def call(state):
        with _compat.enable_x64(False):
            return run(state)

    return call
