"""In-place QFT for the largest single-chip states (f32, n >= 17).

The circuit QFT (circuit.py qft_circuit; ref analogue QuEST's
H + controlled-phase + swap construction) is H(q) followed by a
controlled-phase ladder for each qubit.  Every gate in the ladder after H(q)
is diagonal and mutually commuting, so the whole ladder collapses to ONE
closed-form elementwise pass:

    angle(k) = pi * bit_q(k) * (k mod 2^q) / 2^q

since sum_{j<q} bit_j(k) * pi / 2^(q-j) = pi * (k mod 2^q) / 2^q.  A full
n-qubit QFT is therefore n single-gate Pallas passes (one per H, in place —
ops/pallas_layer.py) + n fused diagonal passes + one final bit-reversal
permutation, instead of the n(n+1)/2 + n/2 gate applications of the circuit
form.

The WHOLE transform is one jitted donated program.  That is a memory
requirement, not a convenience: a per-gate program chain re-lays the flat
planes into the Pallas passes' tiled 2-D views on every call boundary (a
state-sized relayout copy per plane that defeats donation — observed OOM at
n=30), while inside one program XLA threads the layout through, the Pallas
input_output_aliases keep every pass at one state copy, and only the final
bit-reversal (which cannot alias) peaks at one extra PLANE: in 4 GiB + out
4 GiB + other plane 4 GiB = 12 GiB at n=30 — which is what lets a 30-qubit
8 GiB state run the full QFT on a 15.75 GiB chip.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .pallas_layer import _gate1_body, layer_supported

_INV_SQRT2 = 0.7071067811865476


def _ladder_diag(re, im, q: int):
    """The fused controlled-phase ladder following H(q): multiply amplitude k
    by exp(i * pi * bit_q(k) * (k mod 2^q) / 2^q).  One elementwise pass."""
    n_amps = re.shape[0]
    k = jax.lax.iota(jnp.uint32, n_amps)
    m = (k & jnp.uint32((1 << q) - 1)).astype(jnp.float32)
    bit = ((k >> q) & 1).astype(jnp.float32)
    ang = (jnp.float32(np.pi) / jnp.float32(1 << q)) * m * bit
    c, s = jnp.cos(ang), jnp.sin(ang)
    return re * c - im * s, re * s + im * c


def _rev_perm(bits: int) -> np.ndarray:
    """Host-side table: i -> bit-reversal of i over ``bits`` bits."""
    k = np.arange(1 << bits, dtype=np.uint32)
    r = np.zeros_like(k)
    for b in range(bits):
        r |= ((k >> b) & 1) << (bits - 1 - b)
    return r.astype(np.int32)


def _bit_reverse(plane, n: int):
    """Permute amplitude index k -> reverse of its n-bit pattern (the QFT's
    trailing swap network).

    A direct (2,)*n transpose is catastrophic on TPU (the trailing dim-2
    axes tile at T(2,128): 64x padding = 256 GiB at n=30).  Instead factor
    k = row*2^b + col (row: a high bits, col: b low bits), so
    rev_n(k) = rev_b(col)*2^a + rev_a(row) and the permutation is

        out[i, j] = in[rev_a(j), rev_b(i)]  =  (in[rev_a] .T)[rev_b][i, j]

    — two ROW gathers (contiguous 2^b-element rows) around one 2-D
    transpose, every step tile-friendly and peaking at in+out = 2 planes."""
    a = n // 2
    b = n - a
    x = plane.reshape(1 << a, 1 << b)
    x = x[jnp.asarray(_rev_perm(a))]      # rows permuted: [rev_a(j), col]
    x = x.T                               # [col, rev_a(j)]
    x = x[jnp.asarray(_rev_perm(b))]      # [rev_b(col), rev_a(j)]
    return x.reshape(-1)


@partial(jax.jit, donate_argnums=(0, 1), static_argnames=("bit_reversal",))
def _qft_all(re, im, bit_reversal: bool):
    n = int(re.shape[0]).bit_length() - 1
    h = jnp.asarray([[[_INV_SQRT2, _INV_SQRT2], [_INV_SQRT2, -_INV_SQRT2]],
                     [[0.0, 0.0], [0.0, 0.0]]], dtype=re.dtype)
    for q in range(n - 1, -1, -1):
        re, im = _gate1_body(re, im, h, q)
        if q:
            re, im = _ladder_diag(re, im, q)
    if bit_reversal:
        # Reverse the planes STRICTLY one after the other: each reversal
        # peaks at in+out (it cannot alias), and letting the scheduler
        # interleave the two puts four state-sized buffers in flight.  The
        # barrier pins im's reversal behind re's completion.
        re = _bit_reverse(re, n)
        re, im = jax.lax.optimization_barrier((re, im))
        im = _bit_reverse(im, n)
    return re, im


def qft_planes(re: jax.Array, im: jax.Array, *, bit_reversal: bool = True):
    """Full QFT on plane-pair storage (matching circuit.qft_circuit's
    convention when ``bit_reversal`` is True).  CONSUMES both planes.  f32,
    n >= 17 (the Pallas layer-engine floor).

    ``bit_reversal=False`` returns the transform in bit-reversed amplitude
    order — amplitude k of the true QFT lands at index reverse_n(k) — the
    standard unordered-transform convention of FFT libraries.  This is the
    required mode at the single-chip ceiling (n=30, an 8 GiB state): the
    gate+ladder passes all run in place, but the final reversal cannot
    alias (it needs a second copy of each plane in flight), and
    args(8G, reserved for the aliased outputs) + 2 reversal temps(4G each)
    exceeds the 15.75 GiB HBM.  At n <= 29 both modes fit."""
    n = int(re.shape[0]).bit_length() - 1
    if not layer_supported(n):
        raise ValueError(f"in-place QFT needs n >= 17, got {n}")
    if re.dtype != jnp.float32 or im.dtype != jnp.float32:
        raise ValueError(f"in-place QFT is f32-only, got {re.dtype}/{im.dtype}")
    with jax.enable_x64(False):
        return _qft_all(re, im, bit_reversal)
