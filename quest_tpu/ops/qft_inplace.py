"""In-place QFT for the largest single-chip states (f32, n >= 17).

The circuit QFT (circuit.py qft_circuit; ref analogue QuEST's
H + controlled-phase + swap construction) is H(q) followed by a
controlled-phase ladder for each qubit.  Every gate in the ladder after H(q)
is diagonal and mutually commuting, so the whole ladder collapses to ONE
closed-form elementwise pass:

    angle(k) = pi * bit_q(k) * (k mod 2^q) / 2^q

since sum_{j<q} bit_j(k) * pi / 2^(q-j) = pi * (k mod 2^q) / 2^q.

The program, per stage, high qubits first:

- q >= 17: H(q) as a fused flip+elementwise XLA pass per plane (_h_flip —
  H is real, so the planes transform independently), then the fused ladder
  as ONE aliased Pallas pass (_ladder_pallas — a joint plane rotation needs
  both inputs for both outputs, which in XLA form holds four state buffers;
  the aliased kernel runs it truly in place).
- q <= 16: ALL 33 remaining circuit passes (17 H + 16 ladders) are
  block-local in the (fiber=128, sublane=8, lane=128) tile view, and ONE
  Pallas pass (_apply_tail_p / _qft_tail_kernel) applies them per block,
  MXU/VPU-resident in VMEM.

That is ~2(n-17)+1 HBM passes for the whole transform instead of the
n(n+1)/2 + n/2 gate applications of the circuit form.

The WHOLE transform is one jitted donated program, and every stage either
aliases in place or (the _h_flip XLA passes) peaks at one extra plane with
the two planes barriered so at most three state-sized buffers are ever in
flight — 12 GiB at n=30 on a 15.75 GiB chip.  Everything stays in FLAT
byte order (the 3-D (top*128, 8, 128) T(8,128) view is byte-identical to
flat, so those reshapes are free bitcasts); routing H through e.g. the
banded 2-D fiber-pass views of pallas_layer instead costs a state-sized
relayout copy per plane at each layout boundary, which is exactly what
OOM'd the earlier per-gate formulation at n=30.

The ONLY piece that cannot run in place is the trailing bit-reversal (out
block (i) reads in block rev(i)): it needs a second copy of each plane in
flight, so at n=30 the transform runs with ``bit_reversal=False`` — the
standard unordered-FFT convention; n <= 29 fits the ordered output.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl

from .. import _compat

from .pallas_layer import (LANE, SUB, _interpret, _shape3, _state_spec,
                           layer_supported)

_INV_SQRT2 = 0.7071067811865476


def _h_flip(plane, q: int, n: int):
    """H on high qubit q as a fused flip+elementwise pass on ONE plane (H is
    real, so the planes transform independently): out = (x[k^2^q] +
    sgn(bit_q)*x[k]) / sqrt(2).  Runs on the FLAT layout — critically, this
    keeps every stage boundary in flat byte order, so the tail pass's 3-D
    view is a free bitcast instead of a state-sized relayout (the Pallas
    fiber pass's banded 2-D output layout forced one 4 GiB relayout copy
    per plane at the tail boundary — over HBM at n=30)."""
    # (pre, 2, mid, 128): the flip axis in the middle with a tile-sized
    # minor lane axis — the geometry the f64 gather engine's partner flips
    # compile cleanly with (ops/apply.py _dense_gather); a (pre, 2, 2^q)
    # view with a 2^q-wide minor dim drew a transposed-layout 4 GiB copy
    # from XLA at n=30
    x4 = plane.reshape(1 << (n - q - 1), 2, 1 << (q - 7), 128)
    sgn = jnp.asarray([1.0, -1.0], plane.dtype).reshape(1, 2, 1, 1)
    out = (jnp.flip(x4, axis=1) + x4 * sgn) * plane.dtype.type(_INV_SQRT2)
    return out.reshape(-1)


def _axis_h(j: int, bits: int) -> np.ndarray:
    """H at bit j of a ``bits``-wide axis: I_{2^(bits-1-j)} (x) H (x) I_{2^j}
    (qubit 0 = LSB, matching _kron_gates / the grouped view's bit order)."""
    h = np.array([[1.0, 1.0], [1.0, -1.0]], np.float32) * np.float32(_INV_SQRT2)
    return np.kron(np.eye(1 << (bits - 1 - j), dtype=np.float32),
                   np.kron(h, np.eye(1 << j, dtype=np.float32)))


def _block_k(shape, base):
    """Amplitude index of each element of an (F, S, L) block whose first
    flat amplitude is ``base`` — int32: Mosaic has no uint32->f32 cast, and
    indices stay < 2^31 through n=30."""
    f, s, l = shape
    return (base
            + jax.lax.broadcasted_iota(jnp.int32, (f, s, l), 0) * (SUB * LANE)
            + jax.lax.broadcasted_iota(jnp.int32, (f, s, l), 1) * LANE
            + jax.lax.broadcasted_iota(jnp.int32, (f, s, l), 2))


def _ladder_cos_sin(k, q: int):
    """cos/sin of the fused-ladder angle pi*bit_q(k)*(k mod 2^q)/2^q.
    (k mod 2^q) can reach 2^29; the f32 cast rounds its low bits, a phase
    error <= pi*2^5/2^q ~ 2e-7 rad — far below f32 amplitude precision."""
    ang = ((k & jnp.int32((1 << q) - 1)) * ((k >> q) & 1)).astype(
        jnp.float32) * jnp.float32(np.pi / (1 << q))
    return jnp.cos(ang), jnp.sin(ang)


def _qft_tail_kernel(inverse: bool, h7_ref, hs_ref, re_ref, im_ref,
                     ore_ref, oim_ref):
    """Apply QFT stages q=16..0 — H(q) then its fused phase ladder — to one
    (F=128, S=8, L=128) block; ``inverse`` runs the adjoint (ascending q,
    negated ladder before each H — H is real-symmetric, so self-adjoint).

    Every one of these 33 circuit passes is BLOCK-LOCAL: H(q) acts on a
    lane/sublane/fiber bit, and ladder(q)'s angle pi*bit_q*(k mod 2^q)/2^q
    reads only bits < q <= 16 — the block-local 17-bit index, identical for
    every block.  One HBM pass replaces all of them; per block the work is
    14 (128x128) + 3 (8x8) real matmul pairs (H is real) and 16 elementwise
    phase rotations, MXU/VPU-resident in VMEM.  The lane and fiber axes are
    both 7 bits wide, so ONE stack of 7 (128x128) H matrices serves both."""
    hp = jax.lax.Precision.HIGHEST
    xr = re_ref[...]
    xi = im_ref[...]
    f, s, l = xr.shape
    k = _block_k(xr.shape, 0)  # block-local: fiber 10-16, sub 7-9, lane 0-6

    def ldot(m, x):
        return jax.lax.dot_general(
            m, x, dimension_numbers=(((1,), (0,)), ((), ())),
            precision=hp, preferred_element_type=x.dtype)

    def rdot(x, m):  # out[., j] = sum_l x[., l] m[j, l]
        return jax.lax.dot_general(
            x, m, dimension_numbers=(((1,), (1,)), ((), ())),
            precision=hp, preferred_element_type=x.dtype)

    def hadamard(xr, xi, q):
        if q >= 10:  # fiber bit: left-multiply over the leading axis
            m = h7_ref[q - 10]
            return (ldot(m, xr.reshape(f, s * l)).reshape(f, s, l),
                    ldot(m, xi.reshape(f, s * l)).reshape(f, s, l))
        if q >= 7:  # sublane bit (left-multiply, S leading — see
            m = hs_ref[q - 7]  # _layer17_kernel's csub rationale)
            a = xr.transpose(1, 0, 2).reshape(s, f * l)
            b = xi.transpose(1, 0, 2).reshape(s, f * l)
            return (ldot(m, a).reshape(s, f, l).transpose(1, 0, 2),
                    ldot(m, b).reshape(s, f, l).transpose(1, 0, 2))
        m = h7_ref[q]  # lane bit: right-multiply over the minor axis
        return (rdot(xr.reshape(f * s, l), m).reshape(f, s, l),
                rdot(xi.reshape(f * s, l), m).reshape(f, s, l))

    def ladder(xr, xi, q):
        c, sn = _ladder_cos_sin(k, q)
        if inverse:
            sn = -sn
        return xr * c - xi * sn, xr * sn + xi * c

    if inverse:  # adjoint order: ladder^-1(q) then H(q), q ascending
        for q in range(17):
            if q:
                xr, xi = ladder(xr, xi, q)
            xr, xi = hadamard(xr, xi, q)
    else:
        for q in range(16, -1, -1):
            xr, xi = hadamard(xr, xi, q)
            if q:
                xr, xi = ladder(xr, xi, q)
    ore_ref[...] = xr
    oim_ref[...] = xi


def _apply_tail_p(re, im, inverse: bool = False):
    """Run the 17-qubit QFT tail (stages q=16..0, or its adjoint) in ONE
    in-place HBM pass (geometry and aliasing exactly as
    pallas_layer._apply_layer17_p)."""
    top, shape3 = _shape3(re.shape[0])
    h7 = np.stack([_axis_h(j, 7) for j in range(7)])  # lane AND fiber
    hs = np.stack([_axis_h(j, 3) for j in range(3)])

    run = pl.pallas_call(
        partial(_qft_tail_kernel, inverse),
        interpret=_interpret(),
        grid=(top,),
        in_specs=[
            pl.BlockSpec((7, LANE, LANE), lambda i: (0, 0, 0)),
            pl.BlockSpec((3, SUB, SUB), lambda i: (0, 0, 0)),
            _state_spec(),
            _state_spec(),
        ],
        out_specs=[_state_spec(), _state_spec()],
        out_shape=[
            jax.ShapeDtypeStruct(shape3, re.dtype),
            jax.ShapeDtypeStruct(shape3, re.dtype),
        ],
        input_output_aliases={2: 0, 3: 1},
    )
    # The planes arrive in whatever layout the preceding passes produced;
    # reshaping into the kernel's 3-D view may be a state-sized relayout
    # copy.  Sequence the two relayouts (barrier) so the first plane's dead
    # argument buffer is reusable for the second plane's copy — without it
    # both 4 GiB temps coexist and the 30q program exceeds HBM.
    re3 = re.reshape(shape3)
    re3, im = jax.lax.optimization_barrier((re3, im))
    im3 = im.reshape(shape3)
    out_re, out_im = run(jnp.asarray(h7), jnp.asarray(hs), re3, im3)
    return out_re.reshape(-1), out_im.reshape(-1)


def _ladder_diag(re, im, q: int):
    """The fused controlled-phase ladder following H(q): multiply amplitude k
    by exp(i * pi * bit_q(k) * (k mod 2^q) / 2^q).  One elementwise pass.

    XLA form, used by tests as the reference; the QFT program itself uses
    :func:`_ladder_pallas` — a joint plane rotation needs both inputs for
    both outputs, so the XLA form holds FOUR state buffers at its peak
    (over HBM at n=30), while the aliased Pallas form runs truly in place."""
    n_amps = re.shape[0]
    k = jax.lax.iota(jnp.uint32, n_amps)
    m = (k & jnp.uint32((1 << q) - 1)).astype(jnp.float32)
    bit = ((k >> q) & 1).astype(jnp.float32)
    ang = (jnp.float32(np.pi) / jnp.float32(1 << q)) * m * bit
    c, s = jnp.cos(ang), jnp.sin(ang)
    return re * c - im * s, re * s + im * c


def _ladder_kernel(q: int, inverse: bool, re_ref, im_ref, ore_ref, oim_ref):
    """Block-local ladder rotation: out block (i) reads only in block (i),
    so the planes alias their outputs — the rotation runs in place."""
    xr = re_ref[...]
    xi = im_ref[...]
    k = _block_k(xr.shape, pl.program_id(0) * jnp.int32(LANE * SUB * LANE))
    c, sn = _ladder_cos_sin(k, q)
    if inverse:
        sn = -sn
    ore_ref[...] = xr * c - xi * sn
    oim_ref[...] = xr * sn + xi * c


def _ladder_pallas(re, im, q: int, inverse: bool = False):
    """In-place ladder pass on the 3-D flat-ordered view (free bitcast)."""
    top, shape3 = _shape3(re.shape[0])
    run = pl.pallas_call(
        partial(_ladder_kernel, q, inverse),
        interpret=_interpret(),
        grid=(top,),
        in_specs=[_state_spec(), _state_spec()],
        out_specs=[_state_spec(), _state_spec()],
        out_shape=[
            jax.ShapeDtypeStruct(shape3, re.dtype),
            jax.ShapeDtypeStruct(shape3, re.dtype),
        ],
        input_output_aliases={0: 0, 1: 1},
    )
    out_re, out_im = run(re.reshape(shape3), im.reshape(shape3))
    return out_re.reshape(-1), out_im.reshape(-1)


def _rev_perm(bits: int) -> np.ndarray:
    """Host-side table: i -> bit-reversal of i over ``bits`` bits."""
    k = np.arange(1 << bits, dtype=np.uint32)
    r = np.zeros_like(k)
    for b in range(bits):
        r |= ((k >> b) & 1) << (bits - 1 - b)
    return r.astype(np.int32)


def _bit_reverse(plane, n: int):
    """Permute amplitude index k -> reverse of its n-bit pattern (the QFT's
    trailing swap network).

    A direct (2,)*n transpose is catastrophic on TPU (the trailing dim-2
    axes tile at T(2,128): 64x padding = 256 GiB at n=30).  Instead factor
    k = row*2^b + col (row: a high bits, col: b low bits), so
    rev_n(k) = rev_b(col)*2^a + rev_a(row) and the permutation is

        out[i, j] = in[rev_a(j), rev_b(i)]  =  (in[rev_a] .T)[rev_b][i, j]

    — two ROW gathers (contiguous 2^b-element rows) around one 2-D
    transpose, every step tile-friendly and peaking at in+out = 2 planes."""
    a = n // 2
    b = n - a
    x = plane.reshape(1 << a, 1 << b)
    x = x[jnp.asarray(_rev_perm(a))]      # rows permuted: [rev_a(j), col]
    x = x.T                               # [col, rev_a(j)]
    x = x[jnp.asarray(_rev_perm(b))]      # [rev_b(col), rev_a(j)]
    return x.reshape(-1)


def _reverse_planes(re, im, n):
    # Reverse the planes STRICTLY one after the other: each reversal peaks
    # at in+out (it cannot alias), and letting the scheduler interleave the
    # two puts four state-sized buffers in flight.  The barrier pins im's
    # reversal behind re's completion.
    re = _bit_reverse(re, n)
    re, im = jax.lax.optimization_barrier((re, im))
    return re, _bit_reverse(im, n)


def _h_flip_stage(re, im, q, n):
    # H per plane, barriered so the two flip passes never hold four
    # state-sized buffers at once
    re = _h_flip(re, q, n)
    re, im = jax.lax.optimization_barrier((re, im))
    return re, _h_flip(im, q, n)


@partial(jax.jit, donate_argnums=(0, 1),
         static_argnames=("bit_reversal", "inverse"))
def _qft_all(re, im, bit_reversal: bool, inverse: bool):
    n = int(re.shape[0]).bit_length() - 1
    if not inverse:
        for q in range(n - 1, 16, -1):
            re, im = _h_flip_stage(re, im, q, n)
            re, im = _ladder_pallas(re, im, q)
        # stages q=16..0 are block-local: ONE Pallas pass for all 33
        re, im = _apply_tail_p(re, im)
        if bit_reversal:
            re, im = _reverse_planes(re, im, n)
    else:
        # adjoint, stages reversed: (un)reverse first, then the tail's
        # adjoint, then ladder^-1(q) before H(q) for q ascending
        if bit_reversal:
            re, im = _reverse_planes(re, im, n)
        re, im = _apply_tail_p(re, im, inverse=True)
        for q in range(17, n):
            re, im = _ladder_pallas(re, im, q, inverse=True)
            re, im = _h_flip_stage(re, im, q, n)
    return re, im


def qft_planes(re: jax.Array, im: jax.Array, *, bit_reversal: bool = True,
               inverse: bool = False):
    """Full QFT — or, with ``inverse``, its adjoint — on plane-pair storage
    (matching circuit.qft_circuit's convention when ``bit_reversal`` is
    True).  CONSUMES both planes.  f32, n >= 17 (the Pallas layer-engine
    floor).  ``inverse=True`` undoes the forward transform of the SAME
    ``bit_reversal`` mode (the common primitive of phase estimation).

    ``bit_reversal=False`` returns the transform in bit-reversed amplitude
    order — amplitude k of the true QFT lands at index reverse_n(k) — the
    standard unordered-transform convention of FFT libraries.  This is the
    required mode at the single-chip ceiling (n=30, an 8 GiB state): the
    gate+ladder passes all run in place, but the final reversal cannot
    alias (it needs a second copy of each plane in flight), and
    args(8G, reserved for the aliased outputs) + 2 reversal temps(4G each)
    exceeds the 15.75 GiB HBM.  At n <= 29 both modes fit."""
    n = int(re.shape[0]).bit_length() - 1
    if not layer_supported(n):
        raise ValueError(f"in-place QFT needs n >= 17, got {n}")
    if re.dtype != jnp.float32 or im.dtype != jnp.float32:
        raise ValueError(f"in-place QFT is f32-only, got {re.dtype}/{im.dtype}")
    with _compat.enable_x64(False):
        return _qft_all(re, im, bit_reversal, inverse)
