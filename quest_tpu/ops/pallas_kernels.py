"""Hand-written Pallas kernels for the hot paths.

The default engine lowers everything through XLA (ops/apply.py), which
already fuses elementwise chains into the MXU matmuls.  This module provides
a hand-scheduled alternative for the single hottest op — the fused-layer
dense pack on the lane block, i.e. a (128, 128) complex matrix applied to
every 128-amplitude lane group (the program bench.py measures) — so the
claim "Pallas kernels for the hot ops" is a real, testable artifact and a
baseline for future hand-tuning.

Enable with ``QUEST_TPU_PALLAS=1`` (or ``use_pallas(True)``); apply_matrix
routes eligible gates (uncontrolled dense packs whose targets are exactly a
prefix of the lane block) here.  Measured on a v5e chip the XLA path and
this kernel are within ~10% of each other — XLA's fusion is already
MXU-shaped for this op — so XLA stays the default.

Layout: the (2, 2^n) SoA state is viewed as (2, M, 128); each kernel
instance loads a (BLOCK, 128) row-tile of re and im, contracts with the
transposed (128, 128) real/imag matrix planes on the MXU, and writes the
row-tile back — one HBM pass, four matmuls per tile:

    out_re = re @ Ur^T - im @ Ui^T
    out_im = re @ Ui^T + im @ Ur^T
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .. import _compat

LANE = 128
_BLOCK_ROWS = 512  # rows of 128 amps per kernel instance (256 KiB f32 tile)

_enabled = os.environ.get("QUEST_TPU_PALLAS", "0") == "1"


def use_pallas(on: bool) -> None:
    """Route eligible eager dense gates through the Pallas kernel."""
    global _enabled
    _enabled = bool(on)


def pallas_enabled() -> bool:
    return _enabled


def _lane_matmul_kernel(ur_ref, ui_ref, re_ref, im_ref, ore_ref, oim_ref):
    # out[g, j] = sum_k s[g, k] U[j, k]: contract both operands' axis 1 via
    # dot_general (no in-kernel or host-side transpose — Mosaic handles the
    # MXU operand orientation natively)
    ur = ur_ref[...]
    ui = ui_ref[...]
    re = re_ref[...]
    im = im_ref[...]
    dot = partial(jax.lax.dot_general,
                  dimension_numbers=(((1,), (1,)), ((), ())),
                  precision=jax.lax.Precision.HIGHEST,
                  preferred_element_type=re.dtype)
    ore_ref[...] = dot(re, ur) - dot(im, ui)
    oim_ref[...] = dot(re, ui) + dot(im, ur)


def apply_lane_matrix_eager(state: jax.Array, u: jax.Array, plan) -> jax.Array:
    """Eager entry: expand the matrix to the lane block and run the kernel.
    Mosaic lowering on this stack requires x64 off, so the whole jit runs
    inside an ``enable_x64(False)`` scope — f32 operands are unaffected."""
    from .apply import _expand_matrix
    with _compat.enable_x64(False):
        u = _expand_matrix(jnp.asarray(u, jnp.float32), plan, jnp.float32)
        return apply_lane_matrix(state, u)


@partial(jax.jit, static_argnames=())
def apply_lane_matrix(state: jax.Array, u: jax.Array) -> jax.Array:
    """Apply a (2, 128, 128) complex-pair matrix to the lane block of a
    (2, 2^n) state (targets = qubits 0..6), n >= 7 + log2(_BLOCK_ROWS)."""
    n_amps = state.shape[1]
    rows = n_amps // LANE
    block = min(_BLOCK_ROWS, rows)
    grid = rows // block

    interpret = jax.default_backend() == "cpu"  # no Mosaic on CPU

    def run(plane):
        return pl.pallas_call(
            _lane_matmul_kernel,
            interpret=interpret,
            grid=(grid,),
            in_specs=[
                pl.BlockSpec((LANE, LANE), lambda i: (0, 0)),  # Ur
                pl.BlockSpec((LANE, LANE), lambda i: (0, 0)),  # Ui
                pl.BlockSpec((block, LANE), lambda i: (i, 0)),  # re tile
                pl.BlockSpec((block, LANE), lambda i: (i, 0)),  # im tile
            ],
            out_specs=[
                pl.BlockSpec((block, LANE), lambda i: (i, 0)),
                pl.BlockSpec((block, LANE), lambda i: (i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((rows, LANE), state.dtype),
                jax.ShapeDtypeStruct((rows, LANE), state.dtype),
            ],
        )(*plane)

    re = state[0].reshape(rows, LANE)
    im = state[1].reshape(rows, LANE)
    out_re, out_im = run((u[0].astype(state.dtype),
                          u[1].astype(state.dtype), re, im))
    return jnp.stack([out_re.reshape(-1), out_im.reshape(-1)])


def eligible(plan, n: int) -> bool:
    """True when the gate is a pure lane-block dense op this kernel covers:
    uncontrolled, slots exactly the 128-wide lane axis, state large enough
    to tile."""
    return (plan.slice_idx is None
            and plan.fold_pattern is None
            and not plan.reroute
            and plan.slot_dims == (LANE,)
            and n >= 7 + 3)  # >= one (8, 128) tile per instance
