"""The universal gate engine: dense / diagonal / permutation ops on the
amplitude tensor.

Storage format — SoA real pair.  A state of n qubits is a real array of shape
``(2, 2^n)``: ``state[0]`` the real parts, ``state[1]`` the imaginary parts.
This mirrors the reference's ComplexArray layout (ref: QuEST.h:77-81) but for
a TPU-specific reason: TPU XLA does not support complex element types at
program boundaries (c128 not at all), so every kernel here performs complex
arithmetic explicitly on real operands — which also makes the f32 and f64
paths identical and keeps every matmul on the MXU's native types.
Matrices are likewise passed as ``(2, 2^k, 2^k)`` real pairs.

Design (TPU-first, not a port): the 2^n amplitude vector is viewed as an
n-axis tensor of shape (2,)*n, with axis ``n-1-q`` holding qubit ``q`` (qubit
0 is the least-significant index bit, matching the reference's amplitude
ordering).  A k-qubit dense gate is then a (2^k x 2^k) x (2^k x 2^(n-k))
real-matmul quartet after transposing the target axes to the front — fused
XLA ops the compiler tiles onto the MXU, instead of the reference's
hand-written pair-index loops (ref: QuEST_cpu.c:1688 compactUnitaryLocal,
:1846 multiControlledMultiQubitUnitaryLocal).  Controlled gates are static
slices, diagonal gates broadcast multiplies, Pauli-X/SWAP are axis
flips/transposes — all static shapes, so everything jits once per
(n, targets, controls) class and XLA fuses adjacent ops.

When the trailing amplitude axis is sharded over the device mesh, these same
programs are partitioned by GSPMD: a matmul over a sharded target axis
becomes the collective-permute exchange the reference hand-rolls with
MPI_Sendrecv (ref: QuEST_cpu_distributed.c:479-507), and axis transposes
become all-to-all reshards (the reference's swap-based rerouting,
:1381-1479).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

# Real matmuls must not be demoted to bf16 on the MXU: amplitudes need full
# mantissas.  HIGHEST keeps f32 gates f32-accurate (and f64 stays f64).
_PRECISION = jax.lax.Precision.HIGHEST


def mat_pair(u) -> np.ndarray:
    """Host-side helper: complex matrix -> stacked (2, d, d) real pair."""
    u = np.asarray(u, dtype=np.complex128)
    return np.stack([u.real, u.imag])


def num_qubits_of(state: jax.Array) -> int:
    n = int(state.shape[1]).bit_length() - 1
    assert state.shape == (2, 1 << n), f"bad state shape {state.shape}"
    return n


def _as_tensor(state: jax.Array) -> jax.Array:
    """(2, 2^n) -> (2,)+(2,)*n; axis of qubit q is ``n - q`` (axis 0 is re/im)."""
    n = num_qubits_of(state)
    return state.reshape((2,) + (2,) * n)


def _axis(q: int, n: int) -> int:
    """Axis of qubit q within a (2,)*n single-part tensor."""
    return n - 1 - q


def _control_index(n: int, controls, control_states):
    """Index tuple slicing the sub-tensor where each control axis is fixed at
    its required bit (leading re/im axis untouched), plus remaining qubits."""
    idx = [slice(None)] * (n + 1)
    for c, s in zip(controls, control_states):
        idx[1 + _axis(c, n)] = int(s)
    remaining = [q for q in range(n - 1, -1, -1) if q not in set(controls)]
    return tuple(idx), remaining


def _cmul(ar, ai, br, bi):
    """(ar+i ai)(br+i bi) — the explicit complex product used everywhere."""
    return ar * br - ai * bi, ar * bi + ai * br


def _apply_dense_to_axes(t: jax.Array, u: jax.Array, targets, axis_qubits):
    """Apply a (2,2^k,2^k) real-pair matrix on the axes of ``t`` (leading
    re/im axis) holding ``targets``.  Matrix basis convention matches the
    reference: targets[0] is the least-significant bit of the row index."""
    k = len(targets)
    pos = {q: a for a, q in enumerate(axis_qubits)}
    src = [1 + pos[q] for q in reversed(targets)]  # row bit order: targets[0] last
    t = jnp.moveaxis(t, src, range(1, k + 1))
    shape = t.shape
    t = t.reshape(2, 1 << k, -1)
    re, im = t[0], t[1]
    ur, ui = u[0].astype(t.dtype), u[1].astype(t.dtype)
    out_re = (jnp.matmul(ur, re, precision=_PRECISION)
              - jnp.matmul(ui, im, precision=_PRECISION))
    out_im = (jnp.matmul(ur, im, precision=_PRECISION)
              + jnp.matmul(ui, re, precision=_PRECISION))
    t = jnp.stack([out_re, out_im]).reshape(shape)
    return jnp.moveaxis(t, range(1, k + 1), src)


@partial(jax.jit, static_argnames=("targets", "controls", "control_states"))
def apply_matrix(state: jax.Array, u: jax.Array, targets: tuple,
                 controls: tuple = (), control_states: tuple = ()) -> jax.Array:
    """The universal dense gate (ref analogue:
    statevec_multiControlledMultiQubitUnitary, QuEST_cpu.c:1846).

    ``u`` is a (2, 2^k, 2^k) real pair and may represent a non-unitary matrix
    (used by applyMatrixN / Kraus superoperators)."""
    n = num_qubits_of(state)
    if not control_states:
        control_states = (1,) * len(controls)
    t = _as_tensor(state)
    if controls:
        idx, remaining = _control_index(n, controls, control_states)
        sub = t[idx]
        sub = _apply_dense_to_axes(sub, u, targets, remaining)
        t = t.at[idx].set(sub)
    else:
        t = _apply_dense_to_axes(t, u, targets, list(range(n - 1, -1, -1)))
    return t.reshape(2, -1)


def _diag_factor(k: int, n: int, diag: jax.Array, targets, axis_qubits):
    """Broadcastable (fr, fi) factors for a (2, 2^k) diagonal over the target
    axes of a (2,)*len(axis_qubits) single-part tensor."""
    pos = {q: a for a, q in enumerate(axis_qubits)}
    d = diag.reshape((2,) + (2,) * k)  # axis 1+j holds targets[k-1-j]
    axes_pos = [pos[q] for q in reversed(targets)]
    order = list(np.argsort(axes_pos))
    d = jnp.moveaxis(d, [1 + j for j in order], range(1, k + 1))
    shape = [1] * len(axis_qubits)
    for p in axes_pos:
        shape[p] = 2
    d = d.reshape((2,) + tuple(shape))
    return d[0], d[1]


@partial(jax.jit, static_argnames=("targets", "controls", "control_states"))
def apply_diagonal(state: jax.Array, diag: jax.Array, targets: tuple,
                   controls: tuple = (), control_states: tuple = ()) -> jax.Array:
    """Diagonal gate: amplitudes multiplied by ``diag[bits(targets)]``, given
    as a (2, 2^k) real pair.  Never moves data — a pure broadcast multiply,
    embarrassingly parallel on a sharded state (the reference's diagonal
    kernels are likewise comm-free, ref: QuEST_cpu.c:2978-3109)."""
    n = num_qubits_of(state)
    k = len(targets)
    if not control_states:
        control_states = (1,) * len(controls)
    t = _as_tensor(state)

    def mul(sub, axis_qubits):
        fr, fi = _diag_factor(k, n, diag.astype(sub.dtype), targets, axis_qubits)
        re, im = sub[0], sub[1]
        out_re, out_im = _cmul(re, im, fr, fi)
        return jnp.stack([out_re, out_im])

    if controls:
        idx, remaining = _control_index(n, controls, control_states)
        t = t.at[idx].set(mul(t[idx], remaining))
    else:
        t = mul(t, list(range(n - 1, -1, -1)))
    return t.reshape(2, -1)


@partial(jax.jit, static_argnames=("target", "controls", "control_states"))
def apply_pauli_x(state: jax.Array, target: int,
                  controls: tuple = (), control_states: tuple = ()) -> jax.Array:
    """X / CNOT / Toffoli as an axis flip — a pure permutation, no arithmetic
    (ref analogue: pauliXLocal QuEST_cpu.c:2498, controlledNotLocal :2584)."""
    n = num_qubits_of(state)
    if not control_states:
        control_states = (1,) * len(controls)
    t = _as_tensor(state)
    if controls:
        idx, remaining = _control_index(n, controls, control_states)
        sub = t[idx]
        a = 1 + remaining.index(target)
        t = t.at[idx].set(jnp.flip(sub, axis=a))
    else:
        t = jnp.flip(t, axis=1 + _axis(target, n))
    return t.reshape(2, -1)


@partial(jax.jit, static_argnames=("target", "controls", "control_states", "conj_fac"))
def apply_pauli_y(state: jax.Array, target: int,
                  controls: tuple = (), control_states: tuple = (),
                  conj_fac: int = 1) -> jax.Array:
    """Y = flip + (−i, +i) phases; ``conj_fac=-1`` gives Y* for density-matrix
    shadow ops (ref analogue: pauliYLocal(conjFac), QuEST_cpu.c:2682).

    Multiplying (re, im) by ±i is a swap-and-negate — still no arithmetic
    beyond sign flips."""
    n = num_qubits_of(state)
    if not control_states:
        control_states = (1,) * len(controls)
    t = _as_tensor(state)

    def y_on(sub, a):
        flipped = jnp.flip(sub, axis=a)
        re, im = flipped[0], flipped[1]
        # phase is (−i) at bit 0 and (+i) at bit 1 (times conj_fac):
        # (+i)(re+i im) = −im + i re ;  s = ∓1 selects the bit's sign
        s = jnp.array([-conj_fac, conj_fac], dtype=sub.dtype)
        shape = [1] * (sub.ndim - 1)
        shape[a - 1] = 2
        s = s.reshape(shape)
        return jnp.stack([-s * im, s * re])

    if controls:
        idx, remaining = _control_index(n, controls, control_states)
        sub = t[idx]
        t = t.at[idx].set(y_on(sub, 1 + remaining.index(target)))
    else:
        t = y_on(t, 1 + _axis(target, n))
    return t.reshape(2, -1)


@partial(jax.jit, static_argnames=("q1", "q2"))
def swap_qubit_amps(state: jax.Array, q1: int, q2: int) -> jax.Array:
    """SWAP gate = transpose of two tensor axes (ref analogue:
    swapQubitAmpsLocal/Distributed, QuEST_cpu.c:3536/:3579 — there a pairwise
    rewrite, here a layout change XLA turns into an all-to-all when sharded)."""
    n = num_qubits_of(state)
    t = _as_tensor(state)
    t = jnp.swapaxes(t, 1 + _axis(q1, n), 1 + _axis(q2, n))
    return t.reshape(2, -1)


@partial(jax.jit, static_argnames=("targets",))
def apply_multi_rotate_z(state: jax.Array, angle: jax.Array, targets: tuple) -> jax.Array:
    """exp(-i angle/2 Z⊗..⊗Z): phase by ±angle/2 keyed on bit-parity of the
    target mask (ref analogue: multiRotateZ, QuEST_cpu.c:3109).

    Separable trick: z = Π_q (1-2 b_q) ∈ {±1} is a broadcast product, then the
    phase is cos(θ/2) − i sin(θ/2)·z — no gather, no parity popcount."""
    n = num_qubits_of(state)
    t = _as_tensor(state)
    z = jnp.ones((), dtype=t.dtype)
    pm = jnp.array([1.0, -1.0], dtype=t.dtype)
    for q in targets:
        shape = [1] * n
        shape[_axis(q, n)] = 2
        z = z * pm.reshape(shape)
    half = angle.astype(t.dtype) / 2
    fr = jnp.cos(half)
    fi = -jnp.sin(half) * z
    re, im = t[0], t[1]
    out_re, out_im = _cmul(re, im, fr, fi)
    return jnp.stack([out_re, out_im]).reshape(2, -1)


@jax.jit
def apply_full_diagonal(state: jax.Array, diag: jax.Array) -> jax.Array:
    """Elementwise multiply by a full (2, 2^n) diagonal operator (ref:
    statevec_applyDiagonalOp, QuEST_cpu.c:3661)."""
    dr, di = diag[0].astype(state.dtype), diag[1].astype(state.dtype)
    out_re, out_im = _cmul(state[0], state[1], dr, di)
    return jnp.stack([out_re, out_im])


@partial(jax.jit, static_argnames=("num_qubits",))
def densmatr_apply_diagonal(state: jax.Array, diag: jax.Array, num_qubits: int) -> jax.Array:
    """ρ(r,c) *= op_r — the diagonal op multiplies along the row (ket) index
    (ref analogue: densmatr_applyDiagonalOpLocal, QuEST_cpu.c:3696)."""
    dim = 1 << num_qubits
    m = state.reshape(2, dim, dim)  # [re/im, col, row]
    dr = diag[0].astype(state.dtype)[None, :]
    di = diag[1].astype(state.dtype)[None, :]
    out_re, out_im = _cmul(m[0], m[1], dr, di)
    return jnp.stack([out_re, out_im]).reshape(2, -1)
