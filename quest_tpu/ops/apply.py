"""The universal gate engine: dense / diagonal / permutation ops on the
amplitude tensor.

Storage format — SoA real pair.  A state of n qubits is a real array of shape
``(2, 2^n)``: ``state[0]`` the real parts, ``state[1]`` the imaginary parts.
This mirrors the reference's ComplexArray layout (ref: QuEST.h:77-81) but for
a TPU-specific reason: TPU XLA does not support complex element types at
program boundaries (c128 not at all), so every kernel here performs complex
arithmetic explicitly on real operands — which also makes the f32 and f64
paths identical and keeps every matmul on the MXU's native types.
Matrices are likewise passed as ``(2, 2^k, 2^k)`` real pairs.

Design (TPU-first, not a port).  Two hardware facts drive everything:

1. **Tiling.**  TPU buffers are tiled (8, 128) over their last two dims; any
   reshape that exposes a small trailing axis pays up to 64x padding in
   memory (measured: a (…,2,2,…,2) view of a 64 MB state materialised 16 GB
   and OOM'd the chip).  So the minor 7 qubits (128 = lane width) are NEVER
   split into their own axes, and neither are the next 3 (8 = f32 sublanes):
   every view of the state ends in (…, 8, 128) exactly matching the tile.

2. **MXU.**  The matrix unit natively contracts 128-wide operands.  A gate
   touching the lane block is therefore *expanded* (kron with identity +
   static bit-reorder, built inside the traced program so matrices stay
   runtime values) to act on the whole 128-wide lane axis — one native MXU
   matmul per gate, instead of the reference's pair-index loops
   (ref: QuEST_cpu.c:1688 compactUnitaryLocal, :1846
   multiControlledMultiQubitUnitaryLocal).  Gates on the sublane block
   contract the 8-wide axis; gates on higher ("prefix") qubits get their own
   size-2 axes and contract those directly.  Program rank stays O(k) —
   independent of n — so XLA compile time is flat as the state grows (a full
   (2,)*n factorisation hit multi-minute compiles by 24 qubits).

Controlled gates: controls on prefix qubits are static slices (halving the
memory traffic per control); controls inside the lane/sublane blocks are
folded into the expanded matrix as diag(I, U).  Diagonal gates are broadcast
multiplies by a block-expanded factor — never any data movement, and the
factor's trailing dims match the tile.  Parity phases (multiRotateZ) use a
fused iota + population_count pass with no reshape at all.

When the trailing amplitude axis is sharded over the device mesh, these same
programs are partitioned by GSPMD: the sharded prefix of the amplitude axis
maps to the leading merged axis of the grouped view, a contraction over a
sharded prefix axis becomes the collective-permute exchange the reference
hand-rolls with MPI_Sendrecv (ref: QuEST_cpu_distributed.c:479-507), and
axis transposes become all-to-all reshards (the reference's swap-based
rerouting, :1381-1479).  The lane/sublane blocks are always shard-local, so
the hot MXU matmuls never communicate.
"""

from __future__ import annotations

import dataclasses
import os
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

# Real matmuls must not be demoted to bf16 on the MXU: amplitudes need full
# mantissas.  HIGHEST keeps f32 gates f32-accurate (and f64 stays f64).
_PRECISION = jax.lax.Precision.HIGHEST

LANE_QUBITS = 7  # 2^7 = 128: the TPU lane width (minor tile dim)
SUB_QUBITS = 3   # 2^3 = 8: f32 sublane count (second-minor tile dim)
_EXPAND_CAP = 10  # max bits in an expanded matrix (2^10 = 1024) before rerouting


def mat_pair(u) -> np.ndarray:
    """Host-side helper: complex matrix -> stacked (2, d, d) real pair."""
    u = np.asarray(u, dtype=np.complex128)
    return np.stack([u.real, u.imag])


def num_qubits_of(state: jax.Array) -> int:
    n = int(state.shape[1]).bit_length() - 1
    assert state.shape == (2, 1 << n), f"bad state shape {state.shape}"
    return n


@lru_cache(maxsize=None)
def _blocks(n: int) -> tuple[int, int]:
    """(lane, sublane) block widths in qubits: lane covers qubits [0, l),
    sublane [l, l+s); qubits >= l+s are 'prefix' qubits."""
    l = min(LANE_QUBITS, n)
    s = min(SUB_QUBITS, n - l)
    return l, s


@lru_cache(maxsize=None)
def grouped_shape(n: int, groups: tuple, isolate_sub: bool = False):
    """Minimal-rank factorisation of the 2^n amplitude axis.

    ``groups`` is a tuple of disjoint ``(start_qubit, length)`` runs of
    *prefix* qubits; each run is isolated as ONE axis of dim 2^length (so a
    contiguous multi-qubit gate contracts a single wide axis — one MXU
    matmul, not a tangle of size-2 contractions).  Every maximal run of
    untouched prefix qubits merges into one axis; the lane block is always
    the minor axis, and the sublane block is isolated only when the gate
    touches it (``isolate_sub``), else it merges into the run above — either
    way the trailing two dims are at least (8, 128), matching the f32 tile,
    so no view ever pays layout padding.  Returns
    ``(dims, axis_of, sub_axis, lane_axis)`` with ``dims`` ordered
    most-significant-first (qubit 0 is the least-significant index bit,
    matching the reference's amplitude ordering); ``axis_of[start_qubit]``
    is the axis index within ``dims``.
    """
    l, s = _blocks(n)
    lo = l + s
    by_top = {start + length - 1: (start, length) for start, length in groups}
    assert all(start >= lo for start, _ in groups), \
        f"groups {groups} inside minor blocks"
    dims: list[int] = []
    axis_of: dict[int, int] = {}
    run = 0
    q = n - 1
    while q >= lo:
        if q in by_top:
            start, length = by_top[q]
            if run:
                dims.append(1 << run)
                run = 0
            axis_of[start] = len(dims)
            dims.append(1 << length)
            q = start - 1
        else:
            run += 1
            q -= 1
    sub_axis = None
    if s and isolate_sub:
        if run:
            dims.append(1 << run)
            run = 0
        sub_axis = len(dims)
        dims.append(1 << s)
    else:
        run += s  # sublane qubits join the trailing merged run
    if run:
        dims.append(1 << run)
    lane_axis = None
    if l:
        lane_axis = len(dims)
        dims.append(1 << l)
    return tuple(dims), axis_of, sub_axis, lane_axis


# ---------------------------------------------------------------------------
# gate plans: the static (host-side, cached) structure of one gate application
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class _Plan:
    """Static structure of one gate application on an n-qubit state."""
    n: int
    dims: tuple            # grouped single-part shape
    slice_idx: tuple | None  # prefix-control slice (incl. leading re/im axis)
    slot_axes: tuple       # single-part axes of matrix slots, MSB-first
    slot_dims: tuple       # dim of each slot, MSB-first
    fold_k: int            # gate targets count (matrix is 2^fold_k wide pre-fold)
    fold_pattern: int | None  # minor-control bit pattern to fold, or None
    fold_c: int            # number of folded minor controls
    kron_bits: int         # identity-expansion bits
    perm: tuple | None     # bit-reorder permutation of the expanded matrix
    reroute: tuple         # ((from_qubit, to_qubit), ...) swaps when too wide


@lru_cache(maxsize=None)
def _gate_plan(n: int, targets: tuple, controls: tuple,
               control_states: tuple, diagonal: bool) -> _Plan:
    l, s = _blocks(n)
    lo = l + s
    pctrl = [(c, st) for c, st in zip(controls, control_states) if c >= lo]
    mctrl = [(c, st) for c, st in zip(controls, control_states) if c < lo]
    gate_bits = list(targets) + [c for c, _ in mctrl]
    lane_inv = l and any(q < l for q in gate_bits)
    sub_inv = s and any(l <= q < lo for q in gate_bits)

    # desired LSB-first bit order of the (expanded) matrix
    slots_lsb: list = []
    if lane_inv:
        slots_lsb += list(range(l))
    if sub_inv:
        slots_lsb += list(range(l, lo))
    prefix_targets = sorted(q for q in targets if q >= lo)
    slots_lsb += prefix_targets
    m = len(slots_lsb)

    if not diagonal and m > _EXPAND_CAP and (lane_inv or sub_inv):
        # too wide to expand: swap every minor gate qubit up to a free prefix
        # position first (the reference's own rerouting trick,
        # ref: QuEST_cpu_distributed.c:1381-1479)
        busy = set(gate_bits) | {c for c, _ in pctrl}
        free = [q for q in range(n - 1, lo - 1, -1) if q not in busy]
        minors = sorted(b for b in gate_bits if b < lo)
        if len(free) < len(minors):
            # not enough free prefix qubits to reroute: the expanded matrix
            # would exceed 2^_EXPAND_CAP.  Refuse, like the reference's
            # fits-in-node guard (ref: QuEST_validation.c:144,
            # validateMultiQubitMatrixFitsInNode :437)
            from ..validation import ErrorCode, _throw
            _throw(ErrorCode.CANNOT_FIT_MULTI_QUBIT_MATRIX)
        moves, mapping = [], {}
        for q in minors:
            p = free.pop(0)
            moves.append((q, p))
            mapping[q] = p
        return dataclasses.replace(
            _gate_plan(n,
                       tuple(mapping.get(q, q) for q in targets),
                       tuple(mapping.get(c, c) for c in controls),
                       control_states, diagonal),
            reroute=tuple(moves))

    # maximal contiguous runs of prefix targets — each one axis, one wide
    # contraction dim
    runs: list[tuple[int, int]] = []
    for q in prefix_targets:
        if runs and q == runs[-1][0] + runs[-1][1]:
            runs[-1] = (runs[-1][0], runs[-1][1] + 1)
        else:
            runs.append((q, 1))
    groups = tuple(sorted(runs + [(c, 1) for c, _ in pctrl]))
    dims, axis_of, sub_axis, lane_axis = grouped_shape(n, groups, bool(sub_inv))
    rank = len(dims) + 1  # leading re/im axis

    slice_idx = None
    removed: list[int] = []
    if pctrl:
        idx: list = [slice(None)] * rank
        for c, st in pctrl:
            idx[1 + axis_of[c]] = int(st)
        slice_idx = tuple(idx)
        removed = sorted(axis_of[c] for c, _ in pctrl)

    def adj(a: int) -> int:
        return a - sum(1 for r in removed if r < a)

    # slots MSB-first: prefix runs desc, then sublane, then lane — which is
    # ascending single-part axis order by construction
    slot_axes: list[int] = []
    slot_dims: list[int] = []
    for start, length in reversed(runs):
        slot_axes.append(adj(axis_of[start]))
        slot_dims.append(1 << length)
    if sub_inv:
        slot_axes.append(adj(sub_axis))
        slot_dims.append(1 << s)
    if lane_inv:
        slot_axes.append(adj(lane_axis))
        slot_dims.append(1 << l)

    # matrix bit order: current = targets + folded minor controls + identity
    # expansion bits (ascending); desired = slots_lsb
    cur = gate_bits + [q for q in slots_lsb if q not in set(gate_bits)]
    qpos = {q: i for i, q in enumerate(cur)}
    idx_arr = np.arange(1 << m, dtype=np.int64)
    to_cur = np.zeros_like(idx_arr)
    for i, q in enumerate(slots_lsb):
        to_cur |= ((idx_arr >> i) & 1) << qpos[q]
    perm = None if np.array_equal(to_cur, idx_arr) else tuple(to_cur.tolist())

    pattern = None
    if mctrl:
        pattern = sum(st << i for i, (_, st) in enumerate(mctrl))

    return _Plan(n=n, dims=dims, slice_idx=slice_idx,
                 slot_axes=tuple(slot_axes), slot_dims=tuple(slot_dims),
                 fold_k=len(targets), fold_pattern=pattern, fold_c=len(mctrl),
                 kron_bits=m - len(gate_bits), perm=perm, reroute=())


def _expand_matrix(u: jax.Array, plan: _Plan, dtype) -> jax.Array:
    """Fold minor controls, kron-expand with identity over untouched block
    qubits, and bit-reorder — all inside the traced program so the matrix
    stays a runtime value (parametrised gates don't recompile)."""
    u = u.astype(dtype)
    if plan.fold_pattern is not None:
        dim = 1 << (plan.fold_k + plan.fold_c)
        # int32 start indices: with x64 on, a bare Python int lowers as an
        # s64 constant that the SPMD partitioner compares against its own
        # s32 shard arithmetic — jaxlib 0.4.36's HLO verifier rejects the
        # mixed compare AFTER partitioning (s64[] vs s32[]), killing every
        # sharded minor-control gate (the dist8 suite)
        off = jnp.int32(plan.fold_pattern << plan.fold_k)
        ur = jax.lax.dynamic_update_slice(jnp.eye(dim, dtype=dtype), u[0], (off, off))
        ui = jax.lax.dynamic_update_slice(jnp.zeros((dim, dim), dtype=dtype), u[1], (off, off))
        u = jnp.stack([ur, ui])
    if plan.kron_bits:
        eye = jnp.eye(1 << plan.kron_bits, dtype=dtype)
        u = jnp.stack([jnp.kron(eye, u[0]), jnp.kron(eye, u[1])])
    if plan.perm is not None:
        p = np.asarray(plan.perm)
        u = u[:, p][:, :, p]
    return u


def _expand_diag(d: jax.Array, plan: _Plan, dtype) -> jax.Array:
    """Diagonal analogue of :func:`_expand_matrix` (vector form)."""
    d = d.astype(dtype)
    if plan.fold_pattern is not None:
        dim = 1 << (plan.fold_k + plan.fold_c)
        # int32 start index — same partitioner s64/s32 story as
        # _expand_matrix above
        off = jnp.int32(plan.fold_pattern << plan.fold_k)
        dr = jax.lax.dynamic_update_slice(jnp.ones(dim, dtype=dtype), d[0], (off,))
        di = jax.lax.dynamic_update_slice(jnp.zeros(dim, dtype=dtype), d[1], (off,))
        d = jnp.stack([dr, di])
    if plan.kron_bits:
        d = jnp.concatenate([d] * (1 << plan.kron_bits), axis=1)
    if plan.perm is not None:
        d = d[:, np.asarray(plan.perm)]
    return d


def _cmul(ar, ai, br, bi):
    """(ar+i ai)(br+i bi) — the explicit complex product used everywhere."""
    return ar * br - ai * bi, ar * bi + ai * br


def _gauss_mode() -> str:
    """Complex-product strategy selector: returns the validated env value
    ('auto', '1' or '0'); the consumer maps it to the 3m / 4m forms.

    QUEST_TPU_GAUSS=1 forces 3m everywhere, =0 forces 4m; default 'auto'
    uses 3m only for f64 on an accelerator backend, from on-chip A/B
    measurement (v5e, 24q random circuit): emulated f64 is
    MXU-FLOP-bound, so dropping the 4th matmul wins 20-23% fused AND
    unfused; f32 fused packs are HBM-bound and the 4m form fuses better
    (6.1e10 vs 5.0e10 amps/s — 3m's (re+im) temp costs an extra
    materialisation).  On CPU, f64 keeps 4m: native f64 gains little,
    and 3m's cancelation (m3-m1-m2) costs ~1 extra ulp at the summand
    magnitude — measured 1.14e-13 absolute on the reference suite's
    O(100)-magnitude debug states, marginally over the Catch2 suite's
    REAL_EPS bar (2 of 53,057 assertions).  4m keeps the full reference
    suite and the <1e-14 binary agreement green.

    The auto selection keys on the PROCESS's default backend, not on
    where each array is placed: in a mixed-placement process (accelerator
    attached but the computation pinned to CPU devices) the accelerator
    choice applies — set QUEST_TPU_GAUSS=0 there if CPU-side
    bit-stability matters.

    Read once at import (the value participates in traced programs, so a
    mid-process change would silently not retrace already-compiled
    signatures — set the variable before importing quest_tpu)."""
    return _GAUSS_MODE


def _env_choice(name: str, default: str, allowed: tuple) -> str:
    """Read a policy env var once at import, rejecting unknown values loudly
    (a typo like QUEST_TPU_GAUSS=3m must not silently behave as 'auto')."""
    val = os.environ.get(name, default)
    if val not in allowed:
        raise ValueError(
            f"{name}={val!r} is not a valid setting; expected one of {allowed}")
    return val


_GAUSS_MODE = _env_choice("QUEST_TPU_GAUSS", "auto", ("auto", "0", "1"))


def _control_style() -> str:
    """How prefix-qubit controls are applied: 'slice' (default) or 'select'.

    'slice' updates the controlled half-slab through a static slice —
    half the memory traffic per control, the right choice on a single
    chip (measured f64 3-control: 9.2 ms vs 98 ms at 24q).  But when the
    control axis is SHARDED, GSPMD lowers the slice-update as an exchange
    (collective-permute + all-reduce — the reference, by contrast, just
    skips non-matching amps locally, ref QuEST_cpu.c:2173).  'select'
    applies the gate to the whole state and keeps it where every control
    matches — an elementwise mask with ZERO collectives regardless of
    sharding, at the cost of the full-state gate.  Set
    QUEST_TPU_CONTROL_STYLE=select for multi-chip runs whose circuits
    put controls on sharded qubits.  Read once at import (participates
    in traced programs)."""
    return _CONTROL_STYLE


_CONTROL_STYLE = _env_choice("QUEST_TPU_CONTROL_STYLE", "slice",
                             ("slice", "select"))


def _dense_on(sub: jax.Array, u: jax.Array, plan: _Plan) -> jax.Array:
    """Contract the (2, D, D) expanded matrix against the slot axes of
    ``sub`` (leading re/im axis).  One integer-label einsum per real product
    — a single dot_general whose flattened contraction is up to 128 wide
    (the MXU's native tile) with the lane axis minor.

    The complex product uses Gauss's 3-multiplication form at f64
    (m1 = Ur·x, m2 = Ui·y, m3 = (Ur+Ui)·(x+y); out = (m1-m2, m3-m1-m2)),
    where the emulated-f64 matmuls dominate; see :func:`_gauss_mode` for
    the measured policy."""
    dims = plan.slot_dims
    ur = u[0].reshape(dims + dims)
    ui = u[1].reshape(dims + dims)
    rank = sub.ndim - 1
    ns = len(dims)
    s_lab = list(range(rank))
    o_lab = [rank + i for i in range(ns)]
    u_lab = o_lab + [s_lab[a] for a in plan.slot_axes]
    r_lab = list(s_lab)
    for i, a in enumerate(plan.slot_axes):
        r_lab[a] = o_lab[i]

    def mm(mat, s):
        return jnp.einsum(mat, u_lab, s, s_lab, r_lab, precision=_PRECISION)

    re, im = sub[0], sub[1]
    mode = _gauss_mode()
    if mode == "1" or (mode != "0" and sub.dtype == jnp.float64
                       and jax.default_backend() != "cpu"):
        m1 = mm(ur, re)
        m2 = mm(ui, im)
        m3 = mm(ur + ui, re + im)
        return jnp.stack([m1 - m2, m3 - m1 - m2])
    out_re = mm(ur, re) - mm(ui, im)
    out_im = mm(ur, im) + mm(ui, re)
    return jnp.stack([out_re, out_im])


_CHUNK_TARGET_BYTES = 256 * 1024 * 1024


def _chunk_spec(plan: _Plan, sub_shape: tuple, itemsize: int):
    """(axis, chunks) for piecewise application of a dense gate on a huge
    f64 state, or None.

    XLA's emulated-f64 dot_general materialises split-representation
    temporaries of ~2x the state size per matmul (observed in the
    allocation dump: two f32[...,2,128] 8 GiB temps for ONE lane gate on a
    2^28-amp f64 state — 36 GiB total on a 16 GiB chip).  Slicing a large
    non-contracted axis and applying the gate chunk-by-chunk inside a
    fori_loop bounds those temporaries at ~2 x _CHUNK_TARGET_BYTES while
    keeping true IEEE f64 arithmetic.  The axis index is in _dense_on's
    convention (leading re/im axis excluded)."""
    total = itemsize
    for d in sub_shape:
        total *= int(d)
    if total <= 4 * _CHUNK_TARGET_BYTES:
        return None
    rank = len(sub_shape) - 1
    cands = [a for a in range(rank) if a not in plan.slot_axes]
    if not cands:
        return None
    want = 1
    while total // want > 2 * _CHUNK_TARGET_BYTES:
        want *= 2
    # prefer the MINOR-most adequate axis: the amplitude sharding lives on
    # the leading (major) axis, and a loop-varying dynamic-slice over a
    # sharded axis would turn each chunk into a cross-shard gather — the
    # minor axes are always shard-local.  (The >1 GiB trigger above keys on
    # the GLOBAL state size: a many-way-sharded state may chunk when its
    # per-shard slab is already small, which costs loop overhead but stays
    # shard-local and correct.)
    for axis in reversed(cands):
        if int(sub_shape[1 + axis]) >= want:
            return axis, want
    # nothing is wide enough: fall back to the largest non-leading axis, and
    # to the (possibly sharded) leading axis only when it is the sole option
    nonlead = [a for a in cands if a != 0 and int(sub_shape[1 + a]) > 1]
    if nonlead:
        axis = max(nonlead, key=lambda a: sub_shape[1 + a])
    elif 0 in cands:
        axis = 0
    else:
        return None
    chunks = min(want, int(sub_shape[1 + axis]))
    return (axis, chunks) if chunks > 1 else None


def _dense_chunked(sub: jax.Array, u: jax.Array, plan: _Plan) -> jax.Array:
    """Apply :func:`_dense_on`, chunking huge f64 states (see _chunk_spec)."""
    spec = None
    if sub.dtype == jnp.float64:
        spec = _chunk_spec(plan, sub.shape, sub.dtype.itemsize)
    if spec is None:
        return _dense_on(sub, u, plan)
    axis, chunks = spec
    w = sub.shape[1 + axis] // chunks

    def body(i, out):
        piece = jax.lax.dynamic_slice_in_dim(sub, i * w, w, 1 + axis)
        return jax.lax.dynamic_update_slice_in_dim(
            out, _dense_on(piece, u, plan), i * w, 1 + axis)

    return jax.lax.fori_loop(0, chunks, body, jnp.zeros_like(sub))


# ---------------------------------------------------------------------------
# the f64 gather engine
# ---------------------------------------------------------------------------
#
# XLA emulates f64 dot_general by splitting each operand into hi/lo f32
# parts and issuing several f32 matmuls with ~2x-state-size temporaries —
# measured ~100 ms for ONE 1-qubit gate on a 24q f64 state (v5e), against a
# 3.7 ms elementwise f64 pass.  A dense k-qubit gate is, however, just a
# 2^k-term XOR-shift sum:
#
#     new[i] = sum_m  u[b(i), b(i)^m] * state[i ^ shift(m)]
#
# where b(i) are the k target bits of amplitude index i and shift(m) places
# the k-bit pattern m on the target positions.  Each term is ONE partner
# gather (a static lane/sublane permutation or a prefix-axis flip — pure
# data movement, dtype-agnostic) times an elementwise coefficient keyed on
# the target bits (a tiny broadcastable table lookup).  No dot_general at
# all: measured 11 ms (1q) / 16 ms (2q) per gate at 24q f64 — 6-9x the
# emulated-matmul engine.  f32 keeps the MXU engine (measured faster there).
#
# ``patterns`` is a static sparsity hint: only these m are summed.  Callers
# (ops/decoherence.py) use it for superoperators whose off-pattern
# coefficients are exactly zero — a depolarising channel needs 2 of 4
# patterns, a two-qubit depolarising 4 of 16.

_GATHER_CAP = 4  # max gate qubits for the gather engine (2^k partner terms)

_F64_STYLE = _env_choice("QUEST_TPU_F64_STYLE", "auto",
                         ("auto", "gather", "matmul"))


def _use_gather(dtype, k: int, patterns) -> bool:
    """Gather engine policy: f64 only — by default only on accelerator
    backends (CPU f64 matmuls are native and the matmul engine's summation
    order keeps the <1e-14 binary agreement with the reference there)."""
    if dtype != jnp.float64 or _F64_STYLE == "matmul":
        return False
    if (1 << k if patterns is None else len(patterns)) > (1 << _GATHER_CAP):
        return False
    return _F64_STYLE == "gather" or jax.default_backend() != "cpu"


def _dense_1q_f64(state: jax.Array, u: jax.Array, q: int) -> jax.Array:
    """Specialised f64 single-target dense gate.

    The generic gather engine's accumulate form (zeros + one fused
    multiply-add per partner pattern, coefficient gathers from the matrix)
    measured 48-99 GB/s for a 24q f64 1q gate on the v5e; this direct
    two-term form — one static partner move (axis flip / sublane take /
    lane permutation) and a per-target-bit coefficient broadcast, with the
    output written once — measures 172-238 GB/s on the same configs.  The
    f64 density/random bench rows are built from exactly these gates, so
    the 2-4x per-pass win is the difference between the emulated-f64 rows
    crawling and streaming."""
    n = num_qubits_of(state)
    l, s = _blocks(n)
    q = int(q)
    ur = u[0].astype(state.dtype)
    ui = u[1].astype(state.dtype)

    # per-target-bit coefficients: out(bit) = diag(bit)*x + off(bit)*partner
    def coeff(plane, bit_vec):
        # plane is (2, 2); entries indexed [bit, bit] (diag) / [bit, 1-bit]
        diag = jnp.where(bit_vec == 0, plane[0, 0], plane[1, 1])
        off = jnp.where(bit_vec == 0, plane[0, 1], plane[1, 0])
        return diag, off

    if q >= l + s:
        view = (1 << (n - q - 1), 2, 1 << (q - l - s), 1 << s, 1 << l)
        bshape = (1, 2, 1, 1, 1)
        bits = jnp.arange(2)
        move = lambda x: jnp.flip(x, axis=1)
    elif q >= l:
        view = (1 << (n - l - s), 1 << s, 1 << l)
        bshape = (1, 1 << s, 1)
        bits = (jnp.arange(1 << s) >> (q - l)) & 1
        perm = np.arange(1 << s) ^ (1 << (q - l))
        move = lambda x: jnp.take(x, perm, axis=1)
    else:
        view = (1 << (n - l - s), 1 << s, 1 << l)
        bshape = (1, 1, 1 << l)
        bits = (jnp.arange(1 << l) >> q) & 1
        perm = np.arange(1 << l) ^ (1 << q)
        move = lambda x: x[..., perm]

    dr, orr = coeff(ur, bits)
    di, oi = coeff(ui, bits)
    dr = dr.reshape(bshape)
    di = di.reshape(bshape)
    orr = orr.reshape(bshape)
    oi = oi.reshape(bshape)

    xr = state[0].reshape(view)
    xi = state[1].reshape(view)

    def run(cxr, cxi):
        pr = move(cxr)
        pi = move(cxi)
        out_re = cxr * dr - cxi * di + pr * orr - pi * oi
        out_im = cxr * di + cxi * dr + pr * oi + pi * orr
        return out_re, out_im

    total = state.dtype.itemsize * 2 * state.shape[1]
    if total <= 4 * _CHUNK_TARGET_BYTES:
        out_re, out_im = run(xr, xi)
        return jnp.stack([out_re.reshape(-1), out_im.reshape(-1)])

    # huge states: unchunked, in + two moved partner planes + out exceed HBM
    # (a 1q gate on a 4 GiB Choi vector peaks > 15.75 GiB); chunk along a
    # non-wire axis exactly as _dense_gather does — partner moves stay
    # inside the chunk because the chunk axis is never the target axis
    caxis = 2 if q >= l + s and view[2] >= 8 else 0
    chunks = 1
    per = total
    while per > 2 * _CHUNK_TARGET_BYTES and chunks < view[caxis]:
        chunks *= 2
        per //= 2
    w = view[caxis] // chunks

    def body(i, out):
        o_re, o_im = out
        cr = jax.lax.dynamic_slice_in_dim(xr, i * w, w, caxis)
        ci = jax.lax.dynamic_slice_in_dim(xi, i * w, w, caxis)
        rr, ri = run(cr, ci)
        o_re = jax.lax.dynamic_update_slice_in_dim(o_re, rr, i * w, caxis)
        o_im = jax.lax.dynamic_update_slice_in_dim(o_im, ri, i * w, caxis)
        return o_re, o_im

    out_re, out_im = jax.lax.fori_loop(
        0, chunks, body, (jnp.zeros(view, state.dtype),
                          jnp.zeros(view, state.dtype)))
    return jnp.stack([out_re.reshape(-1), out_im.reshape(-1)])


@lru_cache(maxsize=None)
def _gather_plan(n: int, wires: tuple):
    """View factorisation for the gather engine: every PREFIX wire (target or
    control) gets its own size-2 axis; the sublane axis is isolated only when
    a wire lives there; the lane axis is never split (bit moves inside it are
    static lane permutations, preserving the (8, 128) tile)."""
    l, s = _blocks(n)
    lo = l + s
    groups = tuple(sorted((q, 1) for q in wires if q >= lo))
    sub_involved = any(l <= q < lo for q in wires)
    return grouped_shape(n, groups, sub_involved) + (l, s)


def _dense_gather(state: jax.Array, u: jax.Array, targets: tuple,
                  controls: tuple = (), control_states: tuple = (),
                  patterns: tuple | None = None) -> jax.Array:
    """Apply a dense (2, 2^k, 2^k) gate via the XOR-shift gather sum above.
    Plain traceable function (targets/controls/patterns must be static).

    Huge states are processed in chunks along a non-wire axis: partner
    moves happen only along target axes, so each chunk's partners lie inside
    the chunk — the loop bounds the materialised partner copies the same way
    _dense_chunked bounds the emulated-matmul temporaries (unchunked, a 1q
    gate on a 4 GiB density state peaks at in + out + 2 partner planes
    > 15.75 GiB HBM)."""
    n = num_qubits_of(state)
    k = len(targets)
    dims, axis_of, sub_axis, lane_axis, l, s = _gather_plan(
        n, tuple(sorted({*targets, *controls})))
    t = state.reshape((2,) + dims)
    body_rank = len(dims)

    def wire_bits(q: int) -> jax.Array:
        """Bit q of the amplitude index, broadcastable over the view body."""
        shape = [1] * body_rank
        if q < l:
            v = (np.arange(1 << l) >> q) & 1
            shape[lane_axis] = 1 << l
        elif q < l + s:
            v = (np.arange(1 << s) >> (q - l)) & 1
            shape[sub_axis] = 1 << s
        else:
            v = np.arange(2)
            shape[axis_of[q]] = 2
        return jnp.asarray(v.reshape(shape), dtype=jnp.int32)

    bidx = jnp.zeros((1,) * body_rank, dtype=jnp.int32)
    for j, q in enumerate(targets):
        bidx = bidx + (wire_bits(q) << j)

    chi = None
    if controls:
        # comm-free 'select' form: keep the gated value only where every
        # control bit matches (works for any control position — an
        # elementwise mask, zero collectives even on sharded controls)
        for c, st in zip(controls, control_states):
            bit = wire_bits(c) == int(st)
            chi = bit if chi is None else chi & bit

    ur, ui = u[0].astype(state.dtype), u[1].astype(state.dtype)

    def run(tc: jax.Array) -> jax.Array:
        accr = jnp.zeros_like(tc[0])
        acci = jnp.zeros_like(tc[1])
        for m in (range(1 << k) if patterns is None else patterns):
            lane_mask = sum(1 << q for j, q in enumerate(targets)
                            if (m >> j) & 1 and q < l)
            sub_mask = sum(1 << (q - l) for j, q in enumerate(targets)
                           if (m >> j) & 1 and l <= q < l + s)
            g = tc
            if lane_mask:
                g = g[..., np.arange(1 << l) ^ lane_mask]
            if sub_mask:
                g = jnp.take(g, np.arange(1 << s) ^ sub_mask,
                             axis=1 + sub_axis)
            for j, q in enumerate(targets):
                if (m >> j) & 1 and q >= l + s:
                    g = jnp.flip(g, axis=1 + axis_of[q])
            cr = ur[bidx, bidx ^ m]
            ci = ui[bidx, bidx ^ m]
            accr = accr + cr * g[0] - ci * g[1]
            acci = acci + cr * g[1] + ci * g[0]
        if chi is not None:
            accr = jnp.where(chi, accr, tc[0])
            acci = jnp.where(chi, acci, tc[1])
        return jnp.stack([accr, acci])

    spec = _gather_chunk_spec(t.shape, state.dtype.itemsize, axis_of,
                              sub_axis, lane_axis, tuple(sorted(
                                  {*targets, *controls})), l, s)
    if spec is None:
        return run(t).reshape(2, -1)
    axis, chunks = spec
    w = t.shape[1 + axis] // chunks

    def body(i, out):
        piece = jax.lax.dynamic_slice_in_dim(t, i * w, w, 1 + axis)
        return jax.lax.dynamic_update_slice_in_dim(out, run(piece),
                                                   i * w, 1 + axis)

    return jax.lax.fori_loop(0, chunks, body,
                             jnp.zeros_like(t)).reshape(2, -1)


def _gather_chunk_spec(shape: tuple, itemsize: int, axis_of, sub_axis,
                       lane_axis, wires: tuple, l: int, s: int):
    """(axis, chunks) for chunked gather application, or None.

    Candidate axes are merged runs no wire lives on.  The lane/sublane axes
    are never chunked (a narrow minor slice breaks the (8, 128) tile), and
    the LEADING axis — where the amplitude sharding lives, so a loop-varying
    dynamic slice over it would gather cross-shard every iteration — is used
    only as a last resort when nothing else is wide enough."""
    total = itemsize
    for d in shape:
        total *= int(d)
    if total <= 4 * _CHUNK_TARGET_BYTES:
        return None
    wire_axes = {axis_of[q] for q in wires if q >= l + s}
    wire_axes.add(sub_axis)
    wire_axes.add(lane_axis)
    rank = len(shape) - 1
    cands = [a for a in range(1, rank) if a not in wire_axes]
    want = 1
    while total // want > 2 * _CHUNK_TARGET_BYTES:
        want *= 2
    for axis in reversed(cands):
        if int(shape[1 + axis]) >= want:
            return axis, want
    if 0 not in wire_axes and int(shape[1]) >= want:
        return 0, want
    cands = [a for a in cands + ([0] if 0 not in wire_axes else [])
             if int(shape[1 + a]) > 1]
    if not cands:
        return None
    axis = max(cands, key=lambda a: shape[1 + a])
    return axis, int(shape[1 + axis])


def apply_matrix(state: jax.Array, u: jax.Array, targets: tuple,
                 controls: tuple = (), control_states: tuple = ()) -> jax.Array:
    """The universal dense gate (ref analogue:
    statevec_multiControlledMultiQubitUnitary, QuEST_cpu.c:1846).

    ``u`` is a (2, 2^k, 2^k) real pair and may represent a non-unitary matrix
    (used by applyMatrixN / Kraus superoperators).

    Eager f32 lane-block gates may route through the hand-written Pallas
    kernel (ops/pallas_kernels.py, QUEST_TPU_PALLAS=1); traced calls (whole-
    circuit programs) always take the XLA engine below, whose lowering is
    x64-compatible."""
    from . import pallas_kernels as _pk
    if _pk.pallas_enabled() and not isinstance(state, jax.core.Tracer):
        n = num_qubits_of(state)
        t = tuple(int(x) for x in targets)
        c = tuple(int(x) for x in controls)
        cs = tuple(int(s) for s in control_states) or (1,) * len(c)
        plan = _gate_plan(n, t, c, cs, False)
        if _pk.eligible(plan, n) and state.dtype == jnp.float32:
            return _pk.apply_lane_matrix_eager(state, u, plan)
    return _apply_matrix_xla(state, u, tuple(targets), tuple(controls),
                             tuple(control_states))


@partial(jax.jit, static_argnames=("fn", "statics", "out_sharding"))
def constrained_op(state: jax.Array, dyn: tuple, fn, statics: tuple,
                   out_sharding) -> jax.Array:
    """Run ``fn(state, *dyn, *statics)`` with the result PINNED to
    ``out_sharding`` inside the same compiled program.

    The eager multi-device dispatch path: op programs jitted without output
    constraints let GSPMD hand back a drifted layout (measured: cross-shard
    gates and channels return replicated or re-partitioned states), which
    the Qureg then corrected with a separate full-state resharding pass
    (`qureg._repin`).  Folding a `with_sharding_constraint` into the op's
    own program removes that corrective pass — the partitioner produces the
    env layout directly.  Cached per (fn, statics, sharding, shapes)."""
    out = fn(state, *dyn, *statics)
    return jax.lax.with_sharding_constraint(out, out_sharding)


def _dense_1q_f64_shadow(state: jax.Array, u: jax.Array, q: int,
                         num_qubits: int) -> jax.Array:
    """Fused f64 density-matrix 1q gate: U on row bit ``q`` AND conj(U) on
    column bit ``q + num_qubits`` in ONE pass over the Choi vector.

    The two-pass form reads and writes the 4 GiB state twice (plus chunk
    overhead); the fused form is the 2-target superoperator conj(U) ⊗ U on
    (q, q+n) through the GATHER engine — the exact structure every
    decoherence channel already runs (ops/decoherence.py), which matters:
    a hand-rolled 4-pattern elementwise variant of this op computed a wrong
    trace on-chip for sublane row bits (the X64-rewriter miscompile family,
    docs/DESIGN.md "f64 on TPU") while the gather formulation is
    TPU-proven."""
    from .pallas_layer import _kron_pair  # lazy: avoids an import cycle

    q = int(q)
    qc = q + int(num_qubits)
    # conj(U) ⊗ U as a (2, 4, 4) real pair: matrix bit 0 = q, bit 1 = qc
    # (kron's first factor is the high bit)
    sp = _kron_pair(jnp.stack([u[0], -u[1]]), u)
    return _dense_gather(state, sp, (q, qc), (), ())


def apply_matrix_routed(state: jax.Array, u: jax.Array, targets: tuple,
                        controls: tuple, control_states: tuple, perm: tuple):
    """Deferred-layout dense gate for compiled circuit programs: like
    :func:`_apply_matrix_xla` but WITHOUT the post-gate swap-back — any
    reroute swaps stay in place and update ``perm`` (logical->physical bit
    positions), so consecutive wide gates share one routing instead of
    paying the reference's swap-in/swap-out per gate (the TODO at
    QuEST_cpu_distributed.c:1376-1379; SURVEY §7.5).  ``targets``/
    ``controls`` are LOGICAL; returns (state, perm)."""
    n = num_qubits_of(state)
    targets = tuple(int(t) for t in targets)
    controls = tuple(int(c) for c in controls)
    if not control_states:
        control_states = (1,) * len(controls)
    control_states = tuple(int(s) for s in control_states)
    phys_t = tuple(perm[q] for q in targets)
    phys_c = tuple(perm[c] for c in controls)
    if _use_gather(state.dtype, len(targets), None):
        # the gather engine moves partners directly at any gate width — no
        # reroute (and so nothing to defer); mirror _apply_matrix_xla's
        # dispatch order, which checks this before planning
        return (_apply_matrix_xla(state, u, phys_t, phys_c, control_states),
                perm)
    plan = _gate_plan(n, phys_t, phys_c, control_states, False)
    if not plan.reroute:
        return (_apply_matrix_xla(state, u, phys_t, phys_c, control_states),
                perm)
    mapping = dict(plan.reroute)
    new_perm = list(perm)
    for a, b in plan.reroute:
        state = swap_qubit_amps(state, a, b)
        for logical, p in enumerate(new_perm):
            if p == a:
                new_perm[logical] = b
            elif p == b:
                new_perm[logical] = a
    state = _apply_matrix_xla(
        state, u, tuple(mapping.get(q, q) for q in phys_t),
        tuple(mapping.get(c, c) for c in phys_c), control_states)
    return state, tuple(new_perm)


def _perm_cycles(mapping: dict) -> list:
    """Cycle decomposition of a content map ``{src: dst}`` (a permutation on
    its support): each cycle ``[a1, a2, ..., ak]`` means content a1 -> a2,
    ..., ak -> a1.  Host-side helper shared by the permutation kernels and
    the scheduler (parallel/scheduler.py)."""
    seen: set = set()
    cycles = []
    for start in sorted(mapping):
        if start in seen or mapping[start] == start:
            continue
        cyc = [start]
        seen.add(start)
        cur = mapping[start]
        while cur != start:
            cyc.append(cur)
            seen.add(cur)
            cur = mapping[cur]
        cycles.append(cyc)
    return cycles


@partial(jax.jit, static_argnames=("wires", "dests", "allow_minor"))
def apply_bit_permutation(state: jax.Array, wires: tuple,
                          dests: tuple, allow_minor: bool = False) -> jax.Array:
    """Move the amplitude-index bit at position ``wires[i]`` to position
    ``dests[i]`` — the scheduler's fused permutation op (epoch boundaries,
    fused swap networks, placement boundaries; parallel/scheduler.py).

    When every involved position is a prefix qubit this is ONE grouped-view
    axis transpose: zero arithmetic, and on a sharded state GSPMD lowers
    every cross-shard move of the single transpose into one all-to-all —
    where the equivalent pairwise ``swap_qubit_amps`` chain pays one
    collective per swap (the comm the scheduler exists to save).  Positions
    inside the minor (lane/sublane) blocks cannot be transposed without
    breaking the (8, 128) tile, so such permutations fall back to pairwise
    swaps through the matrix engine — unless ``allow_minor``, which forces
    the single-transpose form at any position (the overlapped executor's
    chunk programs run on sub-tile-sized slices already, where a per-swap
    collective chain would multiply the very comm the chunking pipelines;
    parallel/executor.py)."""
    n = num_qubits_of(state)
    wires = tuple(int(w) for w in wires)
    dests = tuple(int(d) for d in dests)
    assert sorted(wires) == sorted(dests), \
        f"bit permutation {wires} -> {dests} is not a permutation"
    mapping = {w: d for w, d in zip(wires, dests) if w != d}
    if not mapping:
        return state
    l, s = _blocks(n)
    if min(mapping) >= l + s:
        support = tuple(sorted(mapping))
        dims, axis_of, _, _ = grouped_shape(n, tuple((q, 1) for q in support))
        t = state.reshape((2,) + dims)
        axes = list(range(t.ndim))
        for w, d in mapping.items():
            # the output axis indexing bit d carries the input axis of bit w
            axes[1 + axis_of[d]] = 1 + axis_of[w]
        return jnp.transpose(t, axes).reshape(2, -1)
    if allow_minor:
        # fully-factorised view: bit b is axis 1 + (n - 1 - b)
        t = state.reshape((2,) + (2,) * n)
        axes = list(range(t.ndim))
        for w, d in mapping.items():
            axes[1 + (n - 1 - d)] = 1 + (n - 1 - w)
        return jnp.transpose(t, axes).reshape(2, -1)
    for cyc in _perm_cycles(mapping):
        # content a1 -> a2 -> ... -> ak -> a1 via swaps (a1,a2),(a1,a3),...
        for x in cyc[1:]:
            state = swap_qubit_amps(state, cyc[0], x)
    return state


def split_prefix_cycles(mapping: dict, lo: int) -> tuple:
    """Split a content map into ``(fused, rest)``: cycles living entirely on
    prefix wires (``>= lo``) merge into one transposable map (the fused
    ``bitperm`` form), everything else stays for pairwise swaps.  The ONE
    definition of that split — shared by :func:`reconcile_perm` and the
    scheduler's static lowering (parallel/scheduler.py), so the two can
    never diverge on what fuses."""
    fused: dict = {}
    rest: dict = {}
    for cyc in _perm_cycles(mapping):
        tgt = fused if min(cyc) >= lo else rest
        for i, x in enumerate(cyc):
            tgt[x] = cyc[(i + 1) % len(cyc)]
    return fused, rest


def reconcile_perm(state: jax.Array, perm: tuple) -> jax.Array:
    """Physically restore logical == physical bit order (the lazy
    reconciliation at the end of a compiled program).  Cycles living
    entirely on prefix qubits are fused into one bit-permutation transpose
    (one collective on a sharded state — see :func:`apply_bit_permutation`);
    cycles touching the minor blocks keep the pairwise-swap form."""
    n = len(perm)
    # logical bit q sits at physical position perm[q] and must return to q
    mapping = {p: q for q, p in enumerate(perm) if p != q}
    if not mapping:
        return state
    fused, rest = split_prefix_cycles(mapping, sum(_blocks(n)))
    if fused:
        state = apply_bit_permutation(state, tuple(sorted(fused)),
                                      tuple(fused[w] for w in sorted(fused)))
    for cyc in _perm_cycles(rest):
        for x in cyc[1:]:
            state = swap_qubit_amps(state, cyc[0], x)
    return state


def permute_plane_bits(plane: jax.Array, mapping: dict) -> jax.Array:
    """Apply a content map ``{src_position: dst_position}`` of amplitude-
    index bits to ONE flat plane: the content of index bit ``src`` moves to
    position ``dst``.  A bit permutation is real, so the re and im planes
    transform independently — this is the plane-pair twin of
    :func:`apply_bit_permutation` the epoch executor's donated plane
    programs reconcile through (ops/epoch_pallas.py ``jit_program_planes``).

    Lowered as ONE transpose of the minimal factorised view: every involved
    bit is isolated as its own axis, untouched runs merge (so the rank stays
    bounded by 2*|support| + 1 rather than n).  Minor-bit cycles pay a
    relayout on TPU — the same cost the stacked path's pairwise-swap engine
    pays, without the (2, N) stack."""
    if not mapping:
        return plane
    n = int(plane.shape[0]).bit_length() - 1
    support = set(mapping)
    dims: list = []
    axis_of: dict = {}
    run = 0
    q = n - 1
    while q >= 0:
        if q in support:
            if run:
                dims.append(1 << run)
                run = 0
            axis_of[q] = len(dims)
            dims.append(2)
        else:
            run += 1
        q -= 1
    if run:
        dims.append(1 << run)
    t = plane.reshape(tuple(dims))
    axes = list(range(t.ndim))
    for src, dst in mapping.items():
        # the output axis indexing bit dst carries the input axis of bit src
        axes[axis_of[dst]] = axis_of[src]
    return jnp.transpose(t, axes).reshape(-1)


def reconcile_perm_planes(re: jax.Array, im: jax.Array, perm: tuple):
    """Plane-pair twin of :func:`reconcile_perm`: restore logical ==
    physical bit order on (re, im) storage without ever stacking the
    planes (which would break the epoch engines' donation/aliasing chain).
    The planes are permuted strictly one after the other — the
    optimization barrier pins im's transpose behind re's completion, so at
    most one transpose temp is in flight (the qft_inplace discipline)."""
    mapping = {p: q for q, p in enumerate(perm) if p != q}
    if not mapping:
        return re, im
    re = permute_plane_bits(re, mapping)
    re, im = jax.lax.optimization_barrier((re, im))
    return re, permute_plane_bits(im, mapping)


@partial(jax.jit, static_argnames=("targets", "controls", "control_states"))
def _apply_matrix_xla(state: jax.Array, u: jax.Array, targets: tuple,
                      controls: tuple = (), control_states: tuple = ()) -> jax.Array:
    n = num_qubits_of(state)
    targets = tuple(int(t) for t in targets)
    controls = tuple(int(c) for c in controls)
    if not control_states:
        control_states = (1,) * len(controls)
    control_states = tuple(int(s) for s in control_states)
    if _use_gather(state.dtype, len(targets), None):
        if len(targets) == 1 and not controls:
            return _dense_1q_f64(state, u, targets[0])
        return _dense_gather(state, u, targets, controls, control_states)
    plan = _gate_plan(n, targets, controls, control_states, False)
    if plan.reroute:
        mapping = dict(plan.reroute)
        for a, b in plan.reroute:
            state = swap_qubit_amps(state, a, b)
        state = _apply_matrix_xla(state, u,
                                  tuple(mapping.get(q, q) for q in targets),
                                  tuple(mapping.get(c, c) for c in controls),
                                  control_states)
        for a, b in reversed(plan.reroute):
            state = swap_qubit_amps(state, a, b)
        return state
    if plan.slice_idx is not None and _control_style() == "select":
        # comm-free controlled form: gate the whole state, keep it only
        # where every prefix control matches (see _control_style)
        lo = sum(_blocks(n))
        minor = [(c, st) for c, st in zip(controls, control_states) if c < lo]
        gated = _apply_matrix_xla(state, u, targets,
                                  tuple(c for c, _ in minor),
                                  tuple(st for _, st in minor))
        t = state.reshape((2,) + plan.dims)
        g = gated.reshape((2,) + plan.dims)
        cond = None
        for axis, idx in enumerate(plan.slice_idx):
            if isinstance(idx, int):
                shape = [1] * t.ndim
                shape[axis] = t.shape[axis]
                bit = (jnp.arange(t.shape[axis]) == idx).reshape(shape)
                cond = bit if cond is None else cond & bit
        return jnp.where(cond, g, t).reshape(2, -1)
    u = _expand_matrix(u, plan, state.dtype)
    t = state.reshape((2,) + plan.dims)
    if plan.slice_idx is not None:
        t = t.at[plan.slice_idx].set(_dense_chunked(t[plan.slice_idx], u, plan))
    else:
        t = _dense_chunked(t, u, plan)
    return t.reshape(2, -1)


@partial(jax.jit, static_argnames=("targets", "controls", "control_states",
                                   "num_qubits"))
def apply_matrix_density(state: jax.Array, u: jax.Array, targets: tuple,
                         controls: tuple, control_states: tuple,
                         num_qubits: int) -> jax.Array:
    """Gate + conjugated column-side shadow on a density matrix in ONE
    compiled program (the reference dispatches these as two kernel calls,
    ref: QuEST.c:8-10 + the densityMatrix branches of each API fn; fusing
    them halves the per-gate dispatch overhead of the eager density path and
    lets XLA schedule the two passes together).

    Note: this fusion supersedes the opt-in eager Pallas kernel
    (QUEST_TPU_PALLAS=1) for density matrices — inside the jitted program
    the state is a tracer, so apply_matrix's eager-kernel branch cannot
    engage.  That is the better trade: the flag's measured win was over
    per-gate EAGER dispatch, and the fused program removes one of the two
    dispatches outright.  The flag still applies to statevector gates."""
    if not control_states:
        control_states = (1,) * len(controls)
    if (len(targets) == 1 and not controls
            and _use_gather(state.dtype, 2, None)):  # dispatches a 2-target gather
        # f64 accelerator path: gate + shadow share ONE read and write of
        # the 4 GiB Choi vector (four partner patterns) instead of two full
        # passes — the dominant cost of the f64 density workload
        return _dense_1q_f64_shadow(state, u, targets[0], num_qubits)
    state = _apply_matrix_xla(state, u, targets, controls, control_states)
    conj = jnp.stack([u[0], -u[1]])
    return _apply_matrix_xla(state, conj,
                             tuple(t + num_qubits for t in targets),
                             tuple(c + num_qubits for c in controls),
                             control_states)


@partial(jax.jit, static_argnames=("targets", "controls", "control_states",
                                   "num_qubits"))
def apply_diagonal_density(state: jax.Array, diag: jax.Array, targets: tuple,
                           controls: tuple, control_states: tuple,
                           num_qubits: int) -> jax.Array:
    """Diagonal analogue of :func:`apply_matrix_density` — one program for
    the row-side factor and its column-side conjugate."""
    if not control_states:
        control_states = (1,) * len(controls)
    state = apply_diagonal(state, diag, targets, controls, control_states)
    conj = jnp.stack([diag[0], -diag[1]])
    return apply_diagonal(state, conj,
                          tuple(t + num_qubits for t in targets),
                          tuple(c + num_qubits for c in controls),
                          control_states)


@partial(jax.jit, static_argnames=("targets", "controls", "control_states"))
def apply_diagonal(state: jax.Array, diag: jax.Array, targets: tuple,
                   controls: tuple = (), control_states: tuple = ()) -> jax.Array:
    """Diagonal gate: amplitudes multiplied by ``diag[bits(targets)]``, given
    as a (2, 2^k) real pair.  Never moves data — a pure broadcast multiply by
    a block-expanded factor whose trailing dims match the (8, 128) tile,
    embarrassingly parallel on a sharded state (the reference's diagonal
    kernels are likewise comm-free, ref: QuEST_cpu.c:2978-3109)."""
    n = num_qubits_of(state)
    targets = tuple(int(t) for t in targets)
    controls = tuple(int(c) for c in controls)
    if not control_states:
        control_states = (1,) * len(controls)
    control_states = tuple(int(s) for s in control_states)
    if controls and len(targets) + len(controls) <= 16:
        # absorb ALL controls into the factor (entries are 1 where a control
        # bit mismatches): the gate becomes a pure broadcast multiply with no
        # control slice — in particular a control on a SHARDED qubit stays
        # comm-free, where a slice-update would make GSPMD communicate
        dr, di = diag[0], diag[1]
        for st in control_states:  # each control becomes the next-higher bit
            one = jnp.ones_like(dr)
            zero = jnp.zeros_like(di)
            if st:
                dr = jnp.concatenate([one, dr])
                di = jnp.concatenate([zero, di])
            else:
                dr = jnp.concatenate([dr, one])
                di = jnp.concatenate([di, zero])
        diag = jnp.stack([dr, di])
        targets = targets + controls
        controls = ()
        control_states = ()
    plan = _gate_plan(n, targets, controls, control_states, True)
    d = _expand_diag(diag, plan, state.dtype)
    t = state.reshape((2,) + plan.dims)

    def mul(sub):
        rank = sub.ndim - 1
        shape = [1] * rank
        for a, dim in zip(plan.slot_axes, plan.slot_dims):
            shape[a] = dim
        f = d.reshape((2,) + tuple(shape))
        out_re, out_im = _cmul(sub[0], sub[1], f[0], f[1])
        return jnp.stack([out_re, out_im])

    if plan.slice_idx is not None:
        t = t.at[plan.slice_idx].set(mul(t[plan.slice_idx]))
    else:
        t = mul(t)
    return t.reshape(2, -1)


_X_PAIR = np.stack([np.array([[0.0, 1.0], [1.0, 0.0]]), np.zeros((2, 2))])
_SWAP_PAIR = np.stack([np.array([[1, 0, 0, 0], [0, 0, 1, 0],
                                 [0, 1, 0, 0], [0, 0, 0, 1]], dtype=np.float64),
                       np.zeros((4, 4))])


@partial(jax.jit, static_argnames=("target", "controls", "control_states"))
def apply_pauli_x(state: jax.Array, target: int,
                  controls: tuple = (), control_states: tuple = ()) -> jax.Array:
    """X / CNOT / Toffoli (ref analogue: pauliXLocal QuEST_cpu.c:2498,
    controlledNotLocal :2584).  On prefix qubits a pure axis flip — no
    arithmetic; inside the minor blocks it routes through the expanded-matrix
    engine (a 128-wide permutation matmul)."""
    n = num_qubits_of(state)
    target = int(target)
    controls = tuple(int(c) for c in controls)
    if not control_states:
        control_states = (1,) * len(controls)
    l, s = _blocks(n)
    lo = l + s
    if (target >= lo and all(c >= lo for c in controls)
            and (not controls or _control_style() == "slice")):
        groups = tuple(sorted((q, 1) for q in {target, *controls}))
        dims, axis_of, _, _ = grouped_shape(n, groups)
        t = state.reshape((2,) + dims)
        if controls:
            idx = [slice(None)] * t.ndim
            for c, st in zip(controls, control_states):
                idx[1 + axis_of[c]] = int(st)
            removed = sorted(axis_of[c] for c in controls)
            a = 1 + axis_of[target] - sum(1 for r in removed if r < axis_of[target])
            t = t.at[tuple(idx)].set(jnp.flip(t[tuple(idx)], axis=a))
        else:
            t = jnp.flip(t, axis=1 + axis_of[target])
        return t.reshape(2, -1)
    u = jnp.asarray(_X_PAIR, dtype=state.dtype)
    return apply_matrix(state, u, (target,), controls, control_states)


@partial(jax.jit, static_argnames=("target", "controls", "control_states", "conj_fac"))
def apply_pauli_y(state: jax.Array, target: int,
                  controls: tuple = (), control_states: tuple = (),
                  conj_fac: int = 1) -> jax.Array:
    """Y gate; ``conj_fac=-1`` gives Y* for density-matrix shadow ops
    (ref analogue: pauliYLocal(conjFac), QuEST_cpu.c:2682)."""
    y = np.stack([np.zeros((2, 2)),
                  np.array([[0.0, -conj_fac], [conj_fac, 0.0]])])
    u = jnp.asarray(y, dtype=state.dtype)
    return apply_matrix(state, u, (int(target),), controls, control_states)


@partial(jax.jit, static_argnames=("q1", "q2"))
def swap_qubit_amps(state: jax.Array, q1: int, q2: int) -> jax.Array:
    """SWAP gate (ref analogue: swapQubitAmpsLocal/Distributed,
    QuEST_cpu.c:3536/:3579).  Prefix-prefix swaps are pure axis transposes
    (an all-to-all reshard when the axes straddle the mesh); swaps touching
    the minor blocks route through the expanded-matrix engine."""
    n = num_qubits_of(state)
    q1, q2 = int(q1), int(q2)
    l, s = _blocks(n)
    lo = l + s
    if q1 >= lo and q2 >= lo:
        dims, axis_of, _, _ = grouped_shape(n, tuple(sorted((q, 1) for q in {q1, q2})))
        t = state.reshape((2,) + dims)
        t = jnp.swapaxes(t, 1 + axis_of[q1], 1 + axis_of[q2])
        return t.reshape(2, -1)
    u = jnp.asarray(_SWAP_PAIR, dtype=state.dtype)
    return apply_matrix(state, u, (q1, q2))


@partial(jax.jit, static_argnames=("targets",))
def apply_multi_rotate_z(state: jax.Array, angle: jax.Array, targets: tuple) -> jax.Array:
    """exp(-i angle/2 Z⊗..⊗Z): phase by ±angle/2 keyed on bit-parity of the
    target mask (ref analogue: multiRotateZ, QuEST_cpu.c:3109).

    One fused flat pass: iota + population_count gives the ±1 parity sign —
    no reshape, no gather, no data movement."""
    n = num_qubits_of(state)
    mask = 0
    for q in targets:
        mask |= 1 << int(q)
    k = jax.lax.iota(jnp.uint32, 1 << n) if n <= 32 else jax.lax.iota(jnp.uint64, 1 << n)
    par = jax.lax.population_count(k & jnp.asarray(mask, k.dtype)) & 1
    z = (1.0 - 2.0 * par.astype(state.dtype))
    half = angle.astype(state.dtype) / 2
    fr = jnp.cos(half)
    fi = -jnp.sin(half) * z
    out_re, out_im = _cmul(state[0], state[1], fr, fi)
    return jnp.stack([out_re, out_im])


@jax.jit
def apply_full_diagonal(state: jax.Array, diag: jax.Array) -> jax.Array:
    """Elementwise multiply by a full (2, 2^n) diagonal operator (ref:
    statevec_applyDiagonalOp, QuEST_cpu.c:3661)."""
    dr, di = diag[0].astype(state.dtype), diag[1].astype(state.dtype)
    out_re, out_im = _cmul(state[0], state[1], dr, di)
    return jnp.stack([out_re, out_im])


@partial(jax.jit, static_argnames=("num_qubits",))
def densmatr_apply_diagonal(state: jax.Array, diag: jax.Array, num_qubits: int) -> jax.Array:
    """ρ(r,c) *= op_r — the diagonal op multiplies along the row (ket) index
    (ref analogue: densmatr_applyDiagonalOpLocal, QuEST_cpu.c:3696)."""
    dim = 1 << num_qubits
    m = state.reshape(2, dim, dim)  # [re/im, col, row]
    dr = diag[0].astype(state.dtype)[None, :]
    di = diag[1].astype(state.dtype)[None, :]
    out_re, out_im = _cmul(m[0], m[1], dr, di)
    return jnp.stack([out_re, out_im]).reshape(2, -1)
