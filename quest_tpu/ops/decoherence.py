"""Decoherence channels on Choi-flattened density matrices.

A density matrix of N qubits lives as a 2N-qubit amplitude pair with the
row (ket) index in qubits 0..N-1 and the column (bra) index in N..2N-1
(ref: getDensityAmp, QuEST.c:709-719).  A channel touching target q acts on
the two qubits (q, q+N) of the doubled space, so every channel here is a
superoperator routed through the universal gate engine: dephasing-type
channels are *diagonal* superoperators (pure broadcast multiplies, never any
data movement — matching the reference's observation that its dephasing
kernels are comm-free, ref: densmatr_oneQubitDegradeOffDiagonal,
QuEST_cpu.c:48), while population-mixing channels (depolarising, damping)
are small dense superoperators — one block-expanded matmul.  General Kraus
maps become one dense superoperator matrix on the doubled targets
(ref: populateKrausSuperOperator path, QuEST_common.c:541-605).

Superoperator index convention: for targets (q, q+N) the 4-dim gate index is
``row_bit + 2*col_bit``, i.e. [ρ00, ρ10, ρ01, ρ11].
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .apply import _dense_gather, _use_gather, apply_diagonal, apply_matrix, mat_pair

_F = jnp.float64


def _superop_apply(state: jax.Array, sp, doubled: tuple,
                   patterns: tuple | None) -> jax.Array:
    """Apply a superoperator matrix on the doubled targets, routing through
    the f64 gather engine with its XOR-pattern sparsity hint when eligible.

    The reference reaches the same goal with hand-specialised masked kernels
    per channel (ref: densmatr_mixDepolarising/mixDamping/
    mixTwoQubitDepolarising, QuEST_cpu.c:125-695): here the specialisation is
    the static set of XOR shift patterns with nonzero coefficients — a
    depolarising channel moves data only between amplitudes whose doubled
    target bits agree (m=0) or both flip (m=3), so 2 partner terms replace a
    dense 4x4 superoperator contraction."""
    if _use_gather(state.dtype, len(doubled), patterns):
        # the jitted wrapper matters for EAGER callers (apply_kraus_map):
        # without it the XOR-shift sum dispatches op-by-op with state-size
        # intermediates; inside an outer jit it simply inlines
        return _dense_gather_jit(state, sp, doubled, (), (), patterns)
    return apply_matrix(state, sp, doubled)


_dense_gather_jit = jax.jit(_dense_gather, static_argnums=(2, 3, 4, 5))


@partial(jax.jit, static_argnames=("target", "num_qubits"))
def mix_dephasing(state: jax.Array, prob: jax.Array, target: int, num_qubits: int) -> jax.Array:
    """ρ → (1-p)ρ + p ZρZ: off-diagonals (in q) scale by 1-2p
    (ref: densmatr_mixDephasing, QuEST_cpu.c:79)."""
    f = 1.0 - 2.0 * prob.astype(_F)
    dr = jnp.ones(4, dtype=_F).at[1].set(f).at[2].set(f)
    d = jnp.stack([dr, jnp.zeros_like(dr)])
    return apply_diagonal(state, d, (int(target), int(target) + num_qubits))


# off-diagonal pattern for two qubits: 1 where r1 != c1 or r2 != c2
# (bit order of the 16-dim diagonal: r1, r2, c1, c2)
_OFF2 = np.array([1.0 if (((i >> 0) & 1) != ((i >> 2) & 1)
                          or ((i >> 1) & 1) != ((i >> 3) & 1)) else 0.0
                  for i in range(16)])


@partial(jax.jit, static_argnames=("q1", "q2", "num_qubits"))
def mix_two_qubit_dephasing(state: jax.Array, prob: jax.Array, q1: int, q2: int,
                            num_qubits: int) -> jax.Array:
    """ρ → (1-p)ρ + p/3 (Z1ρZ1 + Z2ρZ2 + Z1Z2ρZ1Z2): every element that is
    off-diagonal in either qubit scales by 1-4p/3
    (ref: densmatr_mixTwoQubitDephasing, QuEST_cpu.c:84)."""
    dr = 1.0 - (4.0 * prob.astype(_F) / 3.0) * jnp.asarray(_OFF2, dtype=_F)
    d = jnp.stack([dr, jnp.zeros_like(dr)])
    return apply_diagonal(state, d, (int(q1), int(q2),
                                     int(q1) + num_qubits, int(q2) + num_qubits))


@partial(jax.jit, static_argnames=("target", "num_qubits"))
def mix_depolarising(state: jax.Array, prob: jax.Array, target: int,
                     num_qubits: int) -> jax.Array:
    """ρ → (1-p)ρ + p/3 (XρX + YρY + ZρZ)
    (ref: densmatr_mixDepolarisingLocal, QuEST_cpu.c:125, with its
    depolLevel = 4p/3 re-parametrisation resolved analytically):
    off-diag *= 1-4p/3; populations mix as a00' = (1-2p/3)a00 + (2p/3)a11.

    A dense 4x4 superoperator through the gate engine, whose chunked f64
    path (apply.py _dense_chunked) bounds the emulated-f64 matmul temps —
    a 14-qubit f64 density matrix fits a 16 GiB chip."""
    p = prob.astype(_F)
    mix = 2.0 * p / 3.0
    off = 1.0 - 4.0 * p / 3.0
    sr = (jnp.zeros((4, 4), dtype=_F)
          .at[0, 0].set(1.0 - mix).at[0, 3].set(mix)
          .at[3, 3].set(1.0 - mix).at[3, 0].set(mix)
          .at[1, 1].set(off).at[2, 2].set(off))
    s = jnp.stack([sr, jnp.zeros_like(sr)])
    return _superop_apply(state, s, (int(target), int(target) + num_qubits),
                          patterns=(0, 3))


@partial(jax.jit, static_argnames=("target", "num_qubits"))
def mix_damping(state: jax.Array, prob: jax.Array, target: int,
                num_qubits: int) -> jax.Array:
    """Amplitude damping |1><1| → |0><0| with probability p
    (ref: densmatr_mixDampingLocal, QuEST_cpu.c:174):
    a00' = a00 + p·a11, a11' = (1-p)a11, off-diag *= sqrt(1-p)."""
    p = prob.astype(_F)
    keep = jnp.sqrt(1.0 - p)
    sr = (jnp.zeros((4, 4), dtype=_F)
          .at[0, 0].set(1.0).at[0, 3].set(p)
          .at[3, 3].set(1.0 - p)
          .at[1, 1].set(keep).at[2, 2].set(keep))
    s = jnp.stack([sr, jnp.zeros_like(sr)])
    return _superop_apply(state, s, (int(target), int(target) + num_qubits),
                          patterns=(0, 3))


def kraus_superoperator(ops) -> np.ndarray:
    """S = Σ_i conj(K_i) ⊗ K_i in the (column ⊗ row) index convention of the
    flattened density matrix: vec(K ρ K†) = (K̄ ⊗ K) vec(ρ), returned as a
    (2, 4^k, 4^k) real pair
    (ref analogue: populateKrausSuperOperator2/4/N, QuEST_common.c:541-574)."""
    mats = [np.asarray(k, dtype=np.complex128) for k in ops]
    dim = mats[0].shape[0]
    s = np.zeros((dim * dim, dim * dim), dtype=np.complex128)
    for k in mats:
        s += np.kron(np.conj(k), k)
    return mat_pair(s)


# ---------------------------------------------------------------------------
# host-side superoperator builders — the STATIC twins of the traced mix_*
# channels above, consumed by the circuit IR (circuit.DensityCircuit records
# channels as concrete superoperator payloads so the Pallas epoch executor
# and the serve cache's parameter lift see ordinary matrix/diagonal ops).
# The formulas are byte-for-byte the same expressions as the jitted
# channels; tests/test_density_epoch.py pins host == traced.
# ---------------------------------------------------------------------------

def dephasing_diag(prob: float) -> np.ndarray:
    """(2, 4) real pair of the dephasing channel's DIAGONAL superoperator on
    the doubled pair (q, q+N): off-diagonals (index bits differ) scale by
    1 - 2p (the static twin of :func:`mix_dephasing`)."""
    f = 1.0 - 2.0 * float(prob)
    d = np.ones(4, np.float64)
    d[1] = d[2] = f
    return np.stack([d, np.zeros_like(d)])


def two_qubit_dephasing_diag(prob: float) -> np.ndarray:
    """(2, 16) diagonal superoperator of the two-qubit dephasing channel on
    (q1, q2, q1+N, q2+N) (static twin of :func:`mix_two_qubit_dephasing`)."""
    d = 1.0 - (4.0 * float(prob) / 3.0) * _OFF2
    return np.stack([d, np.zeros_like(d)])


def depolarising_superop(prob: float) -> np.ndarray:
    """(2, 4, 4) dense superoperator of the one-qubit depolarising channel
    on (q, q+N) (static twin of :func:`mix_depolarising`)."""
    p = float(prob)
    mix = 2.0 * p / 3.0
    off = 1.0 - 4.0 * p / 3.0
    s = np.zeros((4, 4), np.float64)
    s[0, 0] = s[3, 3] = 1.0 - mix
    s[0, 3] = s[3, 0] = mix
    s[1, 1] = s[2, 2] = off
    return np.stack([s, np.zeros_like(s)])


def damping_superop(prob: float) -> np.ndarray:
    """(2, 4, 4) dense superoperator of amplitude damping on (q, q+N)
    (static twin of :func:`mix_damping`)."""
    p = float(prob)
    keep = math.sqrt(max(0.0, 1.0 - p))
    s = np.zeros((4, 4), np.float64)
    s[0, 0] = 1.0
    s[0, 3] = p
    s[3, 3] = 1.0 - p
    s[1, 1] = s[2, 2] = keep
    return np.stack([s, np.zeros_like(s)])


def channel_kraus(kind: str, *args) -> list:
    """The defining Kraus operators of a named channel — the INDEPENDENT
    oracle ``analysis.check_density_lowering`` verifies recorded
    superoperator payloads against (it never reads the superop builders
    above, so a corrupted payload cannot self-certify)."""
    if kind == "dephase":
        (p,) = args
        return [math.sqrt(1.0 - p) * np.eye(2),
                math.sqrt(p) * np.diag([1.0, -1.0])]
    if kind == "dephase2":
        (p,) = args
        z = np.diag([1.0, -1.0])
        i2 = np.eye(2)
        f = math.sqrt(p / 3.0)
        return [math.sqrt(1.0 - p) * np.eye(4), f * np.kron(i2, z),
                f * np.kron(z, i2), f * np.kron(z, z)]
    if kind == "depol":
        (p,) = args
        f = math.sqrt(p / 3.0)
        return [math.sqrt(1.0 - p) * np.eye(2),
                f * np.array([[0.0, 1.0], [1.0, 0.0]]),
                f * np.array([[0.0, -1.0j], [1.0j, 0.0]]),
                f * np.diag([1.0, -1.0])]
    if kind == "damp":
        (p,) = args
        return [np.diag([1.0, math.sqrt(1.0 - p)]),
                np.array([[0.0, math.sqrt(p)], [0.0, 0.0]])]
    if kind == "kraus":
        return [np.asarray(k, np.complex128) for k in args[0]]
    raise ValueError(f"unknown channel kind {kind!r}")


def superop_trace_preserving(sp, num_targets: int, eps: float = 1e-8) -> bool:
    """Whether a (2, 4^k, 4^k) superoperator pair preserves Tr(rho): with
    the flat index = row_bits + (col_bits << k), summing the rows whose row
    and column target bits agree must reproduce the identity's vec — the
    admission check serve submit runs on channel operand slices (a lifted
    probability sweep must not be able to smuggle in a non-trace-preserving
    map the record-time Kraus validation never saw)."""
    sp = np.asarray(sp, np.float64)
    dim = sp.shape[1]
    k = num_targets
    diag_rows = np.array([r for r in range(dim)
                          if (r & ((1 << k) - 1)) == (r >> k)])
    want = np.zeros(dim)
    want[diag_rows] = 1.0
    got_r = sp[0][diag_rows].sum(axis=0)
    got_i = sp[1][diag_rows].sum(axis=0)
    return bool(np.all(np.abs(got_r - want) < eps)
                and np.all(np.abs(got_i) < eps))


def apply_kraus_map(state: jax.Array, ops, targets, num_qubits: int,
                    validate: bool = True) -> jax.Array:
    """Apply a Kraus channel by one dense superoperator matrix on the doubled
    targets (ts..., ts+N...) — the same engine path as a 2k-qubit gate, which
    is exactly how the reference routes Kraus maps
    (ref: densmatr_applyKrausSuperoperator, QuEST_common.c:576-605).

    The operator list is validated trace-preserving HERE (sum Kᵢ†Kᵢ = I
    within the state dtype's tolerance, ``E_INVALID_KRAUS_OPS``) — direct
    callers used to get silent trace drift from a malformed map, which no
    downstream check ever attributed back.  API entry points that already
    validated (``_mix_kraus``) or construct provably-CPTP maps
    (``mixPauli``, ``mixTwoQubitDepolarising``) pass ``validate=False``
    so the check runs once, in one place.

    The superoperator is built host-side, so its XOR sparsity pattern is
    detected numerically and handed to the gather engine: structured channels
    (Pauli mixtures, two-qubit depolarising) shrink from a dense 4^k
    contraction to their few nonzero shift patterns automatically."""
    if validate:
        from ..precision import real_eps
        from ..validation import validate_kraus_cptp
        validate_kraus_cptp(ops, "apply_kraus_map",
                            eps=real_eps(state.dtype))
    s = kraus_superoperator(ops)
    doubled = tuple(targets) + tuple(t + num_qubits for t in targets)
    dim = s.shape[1]
    nz_r, nz_c = np.nonzero((s[0] != 0.0) | (s[1] != 0.0))
    ms = sorted({int(b ^ c) for b, c in zip(nz_r, nz_c)})
    patterns = tuple(ms) if 0 < len(ms) < dim else None
    return _superop_apply(state, jnp.asarray(s), doubled, patterns)


@jax.jit
def mix_density_matrix(combine: jax.Array, prob: jax.Array, other: jax.Array) -> jax.Array:
    """out = (1-p)·out + p·other (ref: densmatr_mixDensityMatrix, QuEST_cpu.c:890)."""
    p = prob.astype(combine.dtype)
    return (1.0 - p) * combine + p * other
