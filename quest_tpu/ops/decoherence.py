"""Decoherence channels on Choi-flattened density matrices.

A density matrix of N qubits lives as a 2N-qubit amplitude pair with the
row (ket) index in qubits 0..N-1 and the column (bra) index in N..2N-1
(ref: getDensityAmp, QuEST.c:709-719).  A channel touching target q acts on
the two axes (q, q+N).

Dephasing-type channels are *diagonal* in this basis — pure broadcast
multiplies by real factors, never any data movement, matching the reference's
observation that its dephasing kernels are comm-free
(ref: densmatr_oneQubitDegradeOffDiagonal, QuEST_cpu.c:48).  Population-mixing
channels (depolarising, damping) combine the four (row-bit, col-bit)
sub-blocks with static slices and real coefficients.  General Kraus maps
become one dense superoperator matrix applied on the doubled axes via the
universal gate engine (ref: populateKrausSuperOperator path,
QuEST_common.c:541-605).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .apply import _axis, apply_matrix, mat_pair


def _rc_axes(target: int, num_qubits: int):
    n = 2 * num_qubits
    return _axis(target, n), _axis(target + num_qubits, n)


def _block_idx(n: int, axes_bits):
    """Index tuple over a (2,)+(2,)*n tensor fixing given (axis, bit) pairs."""
    idx = [slice(None)] * (n + 1)
    for a, b in axes_bits:
        idx[1 + a] = b
    return tuple(idx)


def _xor_pattern(n: int, ar: int, ac: int, dtype):
    """Broadcastable {0,1} tensor (over a single-part (2,)*n view): 1 where
    row bit != col bit of one qubit."""
    m = jnp.array([[0.0, 1.0], [1.0, 0.0]], dtype=dtype)
    return m.reshape([2 if i in (ar, ac) else 1 for i in range(n)])


@partial(jax.jit, static_argnames=("target", "num_qubits"))
def mix_dephasing(state: jax.Array, prob: jax.Array, target: int, num_qubits: int) -> jax.Array:
    """ρ → (1-p)ρ + p ZρZ: off-diagonals (in q) scale by 1-2p
    (ref: densmatr_mixDephasing, QuEST_cpu.c:79)."""
    n = 2 * num_qubits
    t = state.reshape((2,) + (2,) * n)
    ar, ac = _rc_axes(target, num_qubits)
    d = _xor_pattern(n, ar, ac, state.dtype)
    factor = (1.0 - (2.0 * prob).astype(state.dtype) * d)[None]
    return (t * factor).reshape(2, -1)


@partial(jax.jit, static_argnames=("q1", "q2", "num_qubits"))
def mix_two_qubit_dephasing(state: jax.Array, prob: jax.Array, q1: int, q2: int,
                            num_qubits: int) -> jax.Array:
    """ρ → (1-p)ρ + p/3 (Z1ρZ1 + Z2ρZ2 + Z1Z2ρZ1Z2): every element that is
    off-diagonal in either qubit scales by 1-4p/3
    (ref: densmatr_mixTwoQubitDephasing, QuEST_cpu.c:84)."""
    n = 2 * num_qubits
    t = state.reshape((2,) + (2,) * n)
    r1, c1 = _rc_axes(q1, num_qubits)
    r2, c2 = _rc_axes(q2, num_qubits)
    d1 = _xor_pattern(n, r1, c1, state.dtype)
    d2 = _xor_pattern(n, r2, c2, state.dtype)
    off = 1.0 - (1.0 - d1) * (1.0 - d2)  # 1 where off-diagonal in q1 or q2
    factor = (1.0 - (4.0 * prob / 3.0).astype(state.dtype) * off)[None]
    return (t * factor).reshape(2, -1)


@partial(jax.jit, static_argnames=("target", "num_qubits"))
def mix_depolarising(state: jax.Array, prob: jax.Array, target: int,
                     num_qubits: int) -> jax.Array:
    """ρ → (1-p)ρ + p/3 (XρX + YρY + ZρZ)
    (ref: densmatr_mixDepolarisingLocal, QuEST_cpu.c:125, with its
    depolLevel = 4p/3 re-parametrisation resolved analytically):
    off-diag *= 1-4p/3; populations mix as a00' = (1-2p/3)a00 + (2p/3)a11."""
    n = 2 * num_qubits
    t = state.reshape((2,) + (2,) * n)
    ar, ac = _rc_axes(target, num_qubits)
    i00 = _block_idx(n, [(ar, 0), (ac, 0)])
    i11 = _block_idx(n, [(ar, 1), (ac, 1)])
    i01 = _block_idx(n, [(ar, 0), (ac, 1)])
    i10 = _block_idx(n, [(ar, 1), (ac, 0)])
    a00, a11 = t[i00], t[i11]
    mix = (2.0 * prob / 3.0).astype(state.dtype)
    off = (1.0 - 4.0 * prob / 3.0).astype(state.dtype)
    t = t.at[i00].set((1.0 - mix) * a00 + mix * a11)
    t = t.at[i11].set((1.0 - mix) * a11 + mix * a00)
    t = t.at[i01].set(off * t[i01])
    t = t.at[i10].set(off * t[i10])
    return t.reshape(2, -1)


@partial(jax.jit, static_argnames=("target", "num_qubits"))
def mix_damping(state: jax.Array, prob: jax.Array, target: int,
                num_qubits: int) -> jax.Array:
    """Amplitude damping |1><1| → |0><0| with probability p
    (ref: densmatr_mixDampingLocal, QuEST_cpu.c:174):
    a00' = a00 + p·a11, a11' = (1-p)a11, off-diag *= sqrt(1-p)."""
    n = 2 * num_qubits
    t = state.reshape((2,) + (2,) * n)
    ar, ac = _rc_axes(target, num_qubits)
    i00 = _block_idx(n, [(ar, 0), (ac, 0)])
    i11 = _block_idx(n, [(ar, 1), (ac, 1)])
    i01 = _block_idx(n, [(ar, 0), (ac, 1)])
    i10 = _block_idx(n, [(ar, 1), (ac, 0)])
    a00, a11 = t[i00], t[i11]
    p = prob.astype(state.dtype)
    keep = jnp.sqrt(1.0 - p)
    t = t.at[i00].set(a00 + p * a11)
    t = t.at[i11].set((1.0 - p) * a11)
    t = t.at[i01].set(keep * t[i01])
    t = t.at[i10].set(keep * t[i10])
    return t.reshape(2, -1)


def kraus_superoperator(ops) -> np.ndarray:
    """S = Σ_i conj(K_i) ⊗ K_i in the (column ⊗ row) index convention of the
    flattened density matrix: vec(K ρ K†) = (K̄ ⊗ K) vec(ρ), returned as a
    (2, 4^k, 4^k) real pair
    (ref analogue: populateKrausSuperOperator2/4/N, QuEST_common.c:541-574)."""
    mats = [np.asarray(k, dtype=np.complex128) for k in ops]
    dim = mats[0].shape[0]
    s = np.zeros((dim * dim, dim * dim), dtype=np.complex128)
    for k in mats:
        s += np.kron(np.conj(k), k)
    return mat_pair(s)


def apply_kraus_map(state: jax.Array, ops, targets, num_qubits: int) -> jax.Array:
    """Apply a Kraus channel by one dense superoperator matrix on the doubled
    targets (ts..., ts+N...) — the same engine path as a 2k-qubit gate, which
    is exactly how the reference routes Kraus maps
    (ref: densmatr_applyKrausSuperoperator, QuEST_common.c:576-605)."""
    s = kraus_superoperator(ops)
    doubled = tuple(targets) + tuple(t + num_qubits for t in targets)
    return apply_matrix(state, s, doubled)


@jax.jit
def mix_density_matrix(combine: jax.Array, prob: jax.Array, other: jax.Array) -> jax.Array:
    """out = (1-p)·out + p·other (ref: densmatr_mixDensityMatrix, QuEST_cpu.c:890)."""
    p = prob.astype(combine.dtype)
    return (1.0 - p) * combine + p * other
