"""Whole-layer Pallas kernel: one pass applies every 1-qubit gate of a
circuit layer.

The XLA path (circuit.py + native fusion) compiles a 1q-gate layer into
~4-5 kron-packed matmul ops — each one a full HBM read+write of the state.
But a layer of single-qubit gates IS one big tensor product
U_{n-1} ⊗ … ⊗ U_0, and the tile-aligned grouped view (SURVEY-driven design
in ops/apply.py) factors the state as (top, fiber=128, sublane=8, lane=128).
This kernel exploits that: ONE grid pass contracts the lane (128-wide),
sublane (8-wide) and fiber (128-wide) axes — 17 qubits of gates — against a
block held in VMEM, then a second fiber-style pass covers each remaining
7-qubit group of top qubits.  A 24-qubit layer is 2 HBM passes instead of 5.

This has no analogue in the reference (its per-gate kernels are one pass
PER GATE, ref QuEST_cpu.c:1688) and is the hand-scheduled alternative to
XLA's fusion.  f32 only (Mosaic path; CPU uses the interpreter for tests).

Measured (v5e, 24 qubits, 24 Haar gates/layer): 2.5e10 amps/s — correct
but ~1.6x SLOWER than the XLA engine's kron-packed programs (3.9e10 in
the identical harness), chiefly the sublane transposes and Pallas's
fixed double-buffer pipeline vs XLA's tuned fusion schedule.  XLA stays
the default path; this module is the measured baseline for future
hand-tuning (callers opt in by invoking :func:`apply_1q_layer` directly).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from .. import _compat

LANE = 128
SUB = 8


def _kron_pair(a, b):
    """Complex kron on (2, d, d) real pairs."""
    re = jnp.kron(a[0], b[0]) - jnp.kron(a[1], b[1])
    im = jnp.kron(a[0], b[1]) + jnp.kron(a[1], b[0])
    return jnp.stack([re, im])


def _kron_gates(gates):
    """kron over a list of (2, 2, 2) pairs, first gate = least-significant
    qubit (matching the engine's bit order: qubit 0 is the LSB)."""
    out = gates[-1]
    for g in reversed(gates[:-1]):
        out = _kron_pair(out, g)
    return out


def _layer17_kernel(ul_r, ul_i, us_r, us_i, uf_r, uf_i,
                    re_ref, im_ref, ore_ref, oim_ref):
    """Contract lane (last axis), sublane (axis 1) and fiber (axis 0) of a
    (F=128, S=8, L=128) block with the three kron-packed gate matrices.
    Complex products in the 4-multiplication form (f32: fuses/performs best,
    see apply.py _gauss_mode)."""
    hp = jax.lax.Precision.HIGHEST

    def cmatmul(xr, xi, mr, mi, contract):
        dot = partial(jax.lax.dot_general,
                      dimension_numbers=((contract, (1,)), ((), ())),
                      precision=hp, preferred_element_type=xr.dtype)
        return (dot(xr, mr) - dot(xi, mi)), (dot(xr, mi) + dot(xi, mr))

    xr = re_ref[...]
    xi = im_ref[...]
    f, s, l = xr.shape

    # lane: out[f, s, j] = sum_l x[f, s, l] UL[j, l]
    xr2 = xr.reshape(f * s, l)
    xi2 = xi.reshape(f * s, l)
    xr2, xi2 = cmatmul(xr2, xi2, ul_r[...], ul_i[...], (1,))
    xr = xr2.reshape(f, s, l)
    xi = xi2.reshape(f, s, l)

    # sublane: out[f, j, l] = sum_s US[j, s] x[f, s, l] — left-multiply with
    # S leading (Mosaic rejects the tall-narrow right-multiplication form;
    # a statically-unrolled VPU variant exceeded the 16 MiB scoped VMEM)
    def csub(xr_, xi_):
        a = xr_.transpose(1, 0, 2).reshape(s, f * l)
        b = xi_.transpose(1, 0, 2).reshape(s, f * l)
        dot = partial(jax.lax.dot_general,
                      dimension_numbers=(((1,), (0,)), ((), ())),
                      precision=hp, preferred_element_type=a.dtype)
        rr = dot(us_r[...], a) - dot(us_i[...], b)
        ri = dot(us_r[...], b) + dot(us_i[...], a)
        return (rr.reshape(s, f, l).transpose(1, 0, 2),
                ri.reshape(s, f, l).transpose(1, 0, 2))

    xr, xi = csub(xr, xi)

    # fiber: out[j, s, l] = sum_f UF[j, f] x[f, s, l] — left-multiply, no
    # output transpose
    xr2 = xr.reshape(f, s * l)
    xi2 = xi.reshape(f, s * l)

    def dotl(m, x):
        return jax.lax.dot_general(
            m, x, dimension_numbers=(((1,), (0,)), ((), ())),
            precision=hp, preferred_element_type=x.dtype)

    ore_ref[...] = (dotl(uf_r[...], xr2) - dotl(uf_i[...], xi2)).reshape(f, s, l)
    oim_ref[...] = (dotl(uf_r[...], xi2) + dotl(uf_i[...], xr2)).reshape(f, s, l)


def _fiber_kernel(uf_r, uf_i, re_ref, im_ref, ore_ref, oim_ref):
    """Contract a W-wide fiber axis: blocks are (W, B); out[j, b] =
    sum_f U[j, f] x[f, b]."""
    hp = jax.lax.Precision.HIGHEST
    xr = re_ref[...]
    xi = im_ref[...]

    def dotl(m, x):
        return jax.lax.dot_general(
            m, x, dimension_numbers=(((1,), (0,)), ((), ())),
            precision=hp, preferred_element_type=x.dtype)

    ore_ref[...] = dotl(uf_r[...], xr) - dotl(uf_i[...], xi)
    oim_ref[...] = dotl(uf_r[...], xi) + dotl(uf_i[...], xr)


def _interpret() -> bool:
    return jax.default_backend() == "cpu"  # no Mosaic on CPU


def _shape3(n_amps: int):
    """(grid_size, 3-D view shape) of the (F=128, S=8, L=128) block walk —
    byte-identical to the flat layout, so the reshape is a free bitcast."""
    top = n_amps // (LANE * SUB * LANE)
    return top, (top * LANE, SUB, LANE)


def _state_spec():
    """BlockSpec of one (F, S, L) state block, indexed by the 1-D grid."""
    return pl.BlockSpec((LANE, SUB, LANE), lambda i: (i, 0, 0))


def _apply_layer17_p(re, im, ul, us, uf):
    """Apply UL(lane) ⊗ US(sublane) ⊗ UF(fiber: qubits 10..17) in one pass.
    Plane-pair form: takes/returns the re and im planes as separate flat
    arrays so the in-place aliasing chain is never broken by a slice or
    stack of the (2, N) pair."""
    top, shape3 = _shape3(re.shape[0])

    def mat_spec(d1, d2):
        return pl.BlockSpec((d1, d2), lambda i: (0, 0))

    run = pl.pallas_call(
        _layer17_kernel,
        interpret=_interpret(),
        grid=(top,),
        in_specs=[
            mat_spec(LANE, LANE), mat_spec(LANE, LANE),   # UL
            mat_spec(SUB, SUB), mat_spec(SUB, SUB),       # US
            mat_spec(LANE, LANE), mat_spec(LANE, LANE),   # UF
            pl.BlockSpec((LANE, SUB, LANE), lambda i: (i, 0, 0)),  # re
            pl.BlockSpec((LANE, SUB, LANE), lambda i: (i, 0, 0)),  # im
        ],
        out_specs=[
            pl.BlockSpec((LANE, SUB, LANE), lambda i: (i, 0, 0)),
            pl.BlockSpec((LANE, SUB, LANE), lambda i: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(shape3, re.dtype),
            jax.ShapeDtypeStruct(shape3, re.dtype),
        ],
        # true in-place: output block (i) depends only on input block (i),
        # so the state planes alias their outputs — with the caller's
        # donation this makes the whole pass run in ~state-size HBM (the
        # aliasing a 30-qubit 8 GiB f32 state needs on a 15.75 GiB chip)
        input_output_aliases={6: 0, 7: 1},
    )
    out_re, out_im = run(ul[0], ul[1], us[0], us[1], uf[0], uf[1],
                         re.reshape(shape3), im.reshape(shape3))
    return out_re.reshape(-1), out_im.reshape(-1)


_FIBER_COLS = 1024  # 128x1024 f32 block = 512 KiB per plane; larger blocks
                    # exceed VMEM under Pallas double-buffering (measured:
                    # 2048 fails to compile at 24q, 1024 works)


def _apply_fiber_p(re, im, uf, lo: int, width: int):
    """Apply a W-wide kron pack to qubits [lo, lo+log2(W)) — viewed as the
    contraction axis of a (left, W, right) factorisation of the state.
    Plane-pair form (see _apply_layer17_p)."""
    n_amps = re.shape[0]
    right = 1 << lo
    w = width
    left = n_amps // (right * w)
    cols = min(_FIBER_COLS, right)
    shape = (left * w, right)  # rank-2: rows a*w+f, block rows = one fiber

    run = pl.pallas_call(
        _fiber_kernel,
        interpret=_interpret(),
        grid=(left, right // cols),
        in_specs=[
            pl.BlockSpec((w, w), lambda i, j: (0, 0)),
            pl.BlockSpec((w, w), lambda i, j: (0, 0)),
            pl.BlockSpec((w, cols), lambda i, j: (i, j)),
            pl.BlockSpec((w, cols), lambda i, j: (i, j)),
        ],
        out_specs=[
            pl.BlockSpec((w, cols), lambda i, j: (i, j)),
            pl.BlockSpec((w, cols), lambda i, j: (i, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(shape, re.dtype),
            jax.ShapeDtypeStruct(shape, re.dtype),
        ],
        # in-place (see _apply_layer17_p): out block (i, j) reads only
        # in block (i, j)
        input_output_aliases={2: 0, 3: 1},
    )
    out_re, out_im = run(uf[0], uf[1], re.reshape(shape), im.reshape(shape))
    return out_re.reshape(-1), out_im.reshape(-1)


def layer_supported(n: int) -> bool:
    return n >= 17


def _fiber_group(q: int, n: int):
    """The 7-qubit-aligned fiber group [lo, hi) covering qubit q >= 17, with
    the Mosaic width floor applied: a group narrower than 3 qubits would
    give a fiber block width below the f32 sublane multiple of 8, which
    Mosaic tiling rejects, so narrow remainder groups are widened DOWN over
    lower qubits (callers put identity factors there — harmless
    re-application).  Returns (base, hi): the pack spans [base, hi).

    Single-sourced for _layer_all_p and _gate1_body: ad-hoc geometries
    (e.g. an (8, 2^27) view at n=30) force XLA into state-sized relayout
    loops that break aliasing — this alignment is the one proven to compile
    in place at the 30q ceiling."""
    lo = 17 + 7 * ((q - 17) // 7)
    hi = min(lo + 7, n)
    base = lo if hi - lo >= 3 else lo - (3 - (hi - lo))
    return base, hi


def _layer_all_p(re, im, gates):
    """Plane-pair body: build the kron packs (tiny in-trace matmuls) and run
    every Pallas pass.  ``gates`` is an (n, 2, 2, 2) stacked pair array."""
    n = int(re.shape[0]).bit_length() - 1
    gp = [gates[q] for q in range(n)]
    ul = _kron_gates(gp[0:7])
    us = _kron_gates(gp[7:10])
    uf = _kron_gates(gp[10:17])
    re, im = _apply_layer17_p(re, im, ul, us, uf)
    eye = jnp.asarray(np.stack([np.eye(2), np.zeros((2, 2))]),
                      dtype=re.dtype)
    lo = 17
    while lo < n:
        base, hi = _fiber_group(lo, n)
        # already-applied qubits below lo get identity factors (the widened
        # remainder-group case — see _fiber_group)
        pack = [eye] * (lo - base) + gp[lo:hi]
        re, im = _apply_fiber_p(re, im, _kron_gates(pack), base,
                                1 << (hi - base))
        lo = hi
    return re, im


@partial(jax.jit, donate_argnums=(0, 1))
def _layer_all_planes(re, im, gates):
    """The in-place whole-layer program: peak HBM is ONE state copy plus
    block buffers — this is what lets a 30-qubit (8 GiB) f32 state run on a
    15.75 GiB chip, where any path that stacks planes or breaks aliasing
    needs two copies."""
    return _layer_all_p(re, im, gates)


@partial(jax.jit, donate_argnums=(0,))
def _layer_all(state, gates):
    """(2, N) compatibility entry; the plane slice/stack at the boundary
    costs a second state copy, fine up to 29 qubits."""
    re, im = _layer_all_p(state[0], state[1], gates)
    return jnp.stack([re, im])


def apply_1q_layer(state: jax.Array, gate_pairs) -> jax.Array:
    """Apply one single-qubit gate per qubit (gate_pairs[q] is a (2, 2, 2)
    real pair for qubit q) to an n>=17-qubit f32 state in ceil((n-10)/7)
    HBM passes.  CONSUMES the input state (donated buffers)."""
    n = int(state.shape[1]).bit_length() - 1
    if not layer_supported(n):
        raise ValueError(f"layer kernel needs n >= 17, got {n}")
    if len(gate_pairs) != n:
        raise ValueError(f"need exactly {n} gate pairs, got {len(gate_pairs)}")
    if state.dtype != jnp.float32:
        raise ValueError(f"layer kernel is f32-only, got {state.dtype}")
    gates = jnp.stack([jnp.asarray(g, dtype=state.dtype) for g in gate_pairs])
    # Mosaic lowering on this stack requires x64 off (same constraint as
    # pallas_kernels.apply_lane_matrix_eager); f32 operands are unaffected
    with _compat.enable_x64(False):
        return _layer_all(state, gates)


def _gate1_body(re, im, gate, q: int):
    """Traceable single-gate pass body (one Pallas pass); see
    apply_1q_gate_planes for the jitted entry.  Note the layout caveat: the
    fiber passes' banded 2-D block views get their own tiled layouts, so a
    caller chaining many of these (or mixing them with flat elementwise
    passes) pays a state-sized relayout copy per plane at each layout
    boundary — at the 30q ceiling that breaks in-place execution, which is
    why ops/qft_inplace.py applies its high-qubit H's as flat-layout XLA
    flip passes instead of through this path."""
    n = int(re.shape[0]).bit_length() - 1
    eye = jnp.asarray(np.stack([np.eye(2), np.zeros((2, 2))]), dtype=re.dtype)
    if q < 17:
        gp = [eye] * 17
        gp[q] = gate
        return _apply_layer17_p(re, im, _kron_gates(gp[0:7]),
                                _kron_gates(gp[7:10]), _kron_gates(gp[10:17]))
    base, hi = _fiber_group(q, n)
    pack = [eye] * (hi - base)
    pack[q - base] = gate
    return _apply_fiber_p(re, im, _kron_gates(pack), base, 1 << (hi - base))


@partial(jax.jit, donate_argnums=(0, 1), static_argnames=("q",))
def _gate1_planes(re, im, gate, q: int):
    return _gate1_body(re, im, gate, q)


def apply_1q_gate_planes(re: jax.Array, im: jax.Array, gate, q: int):
    """Apply ONE single-qubit gate to qubit ``q`` in a single in-place HBM
    pass (identity factors elsewhere in the pack).  CONSUMES both planes.
    The building block for algorithms that interleave 1q gates with
    elementwise passes at the 30-qubit single-chip ceiling (see
    ops/qft_inplace.py), where any two-copy path exceeds HBM."""
    n = int(re.shape[0]).bit_length() - 1
    if not layer_supported(n):
        raise ValueError(f"layer kernel needs n >= 17, got {n}")
    if not 0 <= q < n:
        raise ValueError(f"qubit {q} out of range for {n} qubits")
    if re.dtype != jnp.float32 or im.dtype != jnp.float32:
        raise ValueError(f"layer kernel is f32-only, got {re.dtype}/{im.dtype}")
    gate = jnp.asarray(gate, dtype=re.dtype)
    with _compat.enable_x64(False):
        return _gate1_planes(re, im, gate, q)


def apply_1q_layer_planes(re: jax.Array, im: jax.Array, gate_pairs):
    """Plane-pair variant of :func:`apply_1q_layer`: CONSUMES both planes and
    runs fully in place (one state copy of peak HBM) — required for the
    largest single-chip states (30 qubits f32 = 8 GiB on a 15.75 GiB chip).
    """
    n = int(re.shape[0]).bit_length() - 1
    if not layer_supported(n):
        raise ValueError(f"layer kernel needs n >= 17, got {n}")
    if len(gate_pairs) != n:
        raise ValueError(f"need exactly {n} gate pairs, got {len(gate_pairs)}")
    if re.dtype != jnp.float32 or im.dtype != jnp.float32:
        raise ValueError(f"layer kernel is f32-only, got {re.dtype}/{im.dtype}")
    gates = jnp.stack([jnp.asarray(g, dtype=re.dtype) for g in gate_pairs])
    with _compat.enable_x64(False):
        return _layer_all_planes(re, im, gates)
