"""State initialisers (ref analogues: QuEST_cpu.c:1398-1673 init* family).

All produce (2, 2^n) SoA real-pair amplitude arrays.  Pure jitted functions:
under a sharded output sharding each device generates only its own window
(no initialiser materialises the full state on one device)."""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("num_amps", "dtype"), inline=True)
def blank_state(num_amps: int, dtype) -> jax.Array:
    """Ref: initBlankState (QuEST_cpu.c:1398) — all zeros."""
    return jnp.zeros((2, num_amps), dtype=dtype)


@partial(jax.jit, static_argnames=("num_amps", "dtype"))
def zero_state(num_amps: int, dtype) -> jax.Array:
    """Ref: initZeroState (QuEST_cpu.c:1428) — |00..0>."""
    return jnp.zeros((2, num_amps), dtype=dtype).at[0, 0].set(1.0)


@partial(jax.jit, static_argnames=("num_amps", "dtype"))
def plus_state(num_amps: int, dtype) -> jax.Array:
    """Ref: initPlusState (QuEST_cpu.c:1438) — uniform 1/sqrt(2^n)."""
    norm = 1.0 / jnp.sqrt(jnp.asarray(num_amps, dtype=dtype))
    re = jnp.full((num_amps,), norm, dtype=dtype)
    return jnp.stack([re, jnp.zeros_like(re)])


@partial(jax.jit, static_argnames=("num_amps", "dtype"))
def classical_state(num_amps: int, state_ind, dtype) -> jax.Array:
    """Ref: initClassicalState (QuEST_cpu.c:1470) — basis state |s>."""
    return jnp.zeros((2, num_amps), dtype=dtype).at[0, state_ind].set(1.0)


@partial(jax.jit, static_argnames=("num_amps", "dtype"))
def debug_state(num_amps: int, dtype) -> jax.Array:
    """Ref: initDebugState (QuEST_cpu.c:1591) — amp k = (2k + i(2k+1))/10."""
    k = jnp.arange(num_amps, dtype=dtype)
    return jnp.stack([(2 * k) / 10.0, (2 * k + 1) / 10.0])


@partial(jax.jit, static_argnames=("num_qubits", "qubit_id", "outcome", "dtype"))
def state_of_single_qubit(num_qubits: int, qubit_id: int, outcome: int, dtype) -> jax.Array:
    """Ref: initStateOfSingleQubit (QuEST_cpu.c:1545) — uniform over basis
    states whose ``qubit_id`` bit equals ``outcome``."""
    num_amps = 1 << num_qubits
    k = jnp.arange(num_amps)
    bit = (k >> qubit_id) & 1
    norm = 1.0 / jnp.sqrt(jnp.asarray(num_amps // 2, dtype=dtype))
    re = jnp.where(bit == outcome, norm, 0.0).astype(dtype)
    return jnp.stack([re, jnp.zeros_like(re)])


@partial(jax.jit, static_argnames=("num_qubits",))
def densmatr_pure_state(pure: jax.Array, num_qubits: int) -> jax.Array:
    """Ref: densmatr_initPureStateLocal (QuEST_cpu.c:1184) — ρ = |ψ><ψ|,
    flattened column-major (row index in the low qubits).

    The reference broadcasts ψ into every rank's pairStateVec then forms the
    outer product per-chunk; here it is one outer product whose row axis
    GSPMD keeps local and whose column axis follows the Qureg sharding."""
    pr, pi = pure[0], pure[1]
    # ρ(r,c) = ψ_r ψ_c*; storage [c, r] (flat index = r + c·2^N)
    re = pi[:, None] * pi[None, :] + pr[:, None] * pr[None, :]
    im = pi[:, None] * pr[None, :] * (-1.0) + pr[:, None] * pi[None, :]
    return jnp.stack([re.reshape(-1), im.reshape(-1)])


@partial(jax.jit, static_argnames=("num_qubits", "dtype"))
def densmatr_classical_state(num_qubits: int, state_ind, dtype) -> jax.Array:
    """Ref: densmatr_initClassicalState (QuEST_cpu.c:1115) — ρ = |s><s|."""
    dim = 1 << num_qubits
    ind = state_ind * dim + state_ind
    return jnp.zeros((2, dim * dim), dtype=dtype).at[0, ind].set(1.0)


@partial(jax.jit, static_argnames=("num_qubits", "dtype"))
def densmatr_plus_state(num_qubits: int, dtype) -> jax.Array:
    """Ref: densmatr_initPlusState (QuEST_cpu.c:1154) — every element 2^-N."""
    dim = 1 << num_qubits
    re = jnp.full((dim * dim,), 1.0 / dim, dtype=dtype)
    return jnp.stack([re, jnp.zeros_like(re)])


@jax.jit
def weighted_qureg(fac1, state1, fac2, state2, fac_out, state_out) -> jax.Array:
    """Ref: setWeightedQureg (QuEST_cpu.c:3619): out = f1·q1 + f2·q2 + fo·out.
    Factors are (re, im) pairs of shape (2,)."""
    def term(f, s):
        fr, fi = f[0].astype(s.dtype), f[1].astype(s.dtype)
        return jnp.stack([fr * s[0] - fi * s[1], fr * s[1] + fi * s[0]])
    return term(fac1, state1) + term(fac2, state2) + term(fac_out, state_out)


# --- plane-pair initialisers (huge single-device registers; qureg.py) ------

@partial(jax.jit, static_argnames=("num_amps", "dtype"))
def zero_state_planes(num_amps: int, dtype):
    return (jnp.zeros((num_amps,), dtype=dtype).at[0].set(1.0),
            jnp.zeros((num_amps,), dtype=dtype))


@partial(jax.jit, static_argnames=("num_amps", "dtype"))
def blank_state_planes(num_amps: int, dtype):
    return (jnp.zeros((num_amps,), dtype=dtype),
            jnp.zeros((num_amps,), dtype=dtype))


@partial(jax.jit, static_argnames=("num_amps", "dtype"))
def plus_state_planes(num_amps: int, dtype):
    norm = 1.0 / jnp.sqrt(jnp.asarray(num_amps, dtype=dtype))
    return (jnp.full((num_amps,), norm, dtype=dtype),
            jnp.zeros((num_amps,), dtype=dtype))


@partial(jax.jit, static_argnames=("num_amps", "dtype"))
def classical_state_planes(num_amps: int, state_ind, dtype):
    return (jnp.zeros((num_amps,), dtype=dtype).at[state_ind].set(1.0),
            jnp.zeros((num_amps,), dtype=dtype))


def build_state(fn, statics: tuple, sharding=None) -> jax.Array:
    """One dispatch point for initial-state construction: plain call on a
    single device, sharding-pinned program on a mesh (each device generates
    only its own window)."""
    if sharding is None:
        return fn(*statics)
    return constrained_init(fn, tuple(statics), sharding)


@partial(jax.jit, static_argnames=("fn", "statics", "out_sharding"))
def constrained_init(fn, statics: tuple, out_sharding) -> jax.Array:
    """Build an initial state directly IN the env sharding: each device
    generates only its own window (the module docstring's claim, now true
    for the eager create/init path too — unconstrained, the init programs
    produce a single-device array that the Qureg then redistributed with a
    separate placement pass)."""
    return jax.lax.with_sharding_constraint(fn(*statics), out_sharding)
