"""Scalar calculations: norms, inner products, purity, fidelity, expectations.

Ref analogues: calcTotalProb (QuEST_cpu_local.c:118-167),
statevec_calcInnerProductLocal (QuEST_cpu.c:1071), densmatr_calcPurityLocal
(:861), densmatr_calcFidelityLocal (:990), calcHilbertSchmidtDistanceSquaredLocal
(:923), densmatr_calcInnerProductLocal (:958), calcExpecDiagonalOp (:3738/:3781).

All reductions accumulate in float64 regardless of state dtype (the reference
uses double + Kahan); under a sharded state GSPMD turns these into local
partial sums + psum, exactly the reference's MPI_Allreduce pattern
(QuEST_cpu_distributed.c:35-117).  Results are (re, im) pairs or real scalars
— never complex dtypes (unsupported at TPU program boundaries)."""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from .measure import densmatr_diagonal

_ACC = jnp.float64


def _mag2(state: jax.Array) -> jax.Array:
    re, im = state[0].astype(_ACC), state[1].astype(_ACC)
    return re * re + im * im


@jax.jit
def total_prob_statevec(state: jax.Array) -> jax.Array:
    return jnp.sum(_mag2(state))


@partial(jax.jit, static_argnames=("num_qubits",))
def total_prob_densmatr(state: jax.Array, num_qubits: int) -> jax.Array:
    """Trace of ρ — sum of real diagonal parts."""
    return jnp.sum(densmatr_diagonal(state, num_qubits)[0].astype(_ACC))


@jax.jit
def inner_product(bra: jax.Array, ket: jax.Array) -> jax.Array:
    """<bra|ket> = Σ conj(a)·b, returned as a (re, im) pair."""
    ar, ai = bra[0].astype(_ACC), bra[1].astype(_ACC)
    br, bi = ket[0].astype(_ACC), ket[1].astype(_ACC)
    return jnp.stack([jnp.sum(ar * br + ai * bi), jnp.sum(ar * bi - ai * br)])


@jax.jit
def densmatr_inner_product(rho1: jax.Array, rho2: jax.Array) -> jax.Array:
    """Re Tr(ρ1† ρ2) = Σ Re(ρ1*_ij ρ2_ij) (ref: densmatr_calcInnerProductLocal,
    QuEST_cpu.c:958 — equals Tr(ρ1 ρ2) for Hermitian inputs)."""
    return jnp.sum(rho1[0].astype(_ACC) * rho2[0].astype(_ACC)
                   + rho1[1].astype(_ACC) * rho2[1].astype(_ACC))


@jax.jit
def purity(state: jax.Array) -> jax.Array:
    """Tr(ρ²) = Σ|ρ_ij|² for Hermitian ρ (ref: densmatr_calcPurityLocal :861)."""
    return jnp.sum(_mag2(state))


@jax.jit
def hilbert_schmidt_distance_squared(a: jax.Array, b: jax.Array) -> jax.Array:
    d0 = a[0].astype(_ACC) - b[0].astype(_ACC)
    d1 = a[1].astype(_ACC) - b[1].astype(_ACC)
    return jnp.sum(d0 * d0 + d1 * d1)


@partial(jax.jit, static_argnames=("num_qubits",))
def densmatr_fidelity(rho: jax.Array, pure: jax.Array, num_qubits: int) -> jax.Array:
    """<ψ|ρ|ψ> = Σ_rc ψ_r* ρ(r,c) ψ_c (ref: densmatr_calcFidelityLocal :990).

    Two real matvecs on the flattened matrix — MXU work when large."""
    dim = 1 << num_qubits
    mr = rho[0].reshape(dim, dim).astype(_ACC)  # [col, row]
    mi = rho[1].reshape(dim, dim).astype(_ACC)
    pr, pi = pure[0].astype(_ACC), pure[1].astype(_ACC)
    # v_c = Σ_r conj(ψ)_r M[c, r]  (complex matvec in real parts)
    vr = mr @ pr + mi @ pi
    vi = mi @ pr - mr @ pi
    # Re Σ_c ψ_c v_c
    return jnp.sum(pr * vr - pi * vi)


@jax.jit
def expec_diagonal_op_statevec(state: jax.Array, diag: jax.Array) -> jax.Array:
    """Σ |ψ_k|² op_k as (re, im) (ref: statevec_calcExpecDiagonalOpLocal :3738)."""
    mag2 = _mag2(state)
    return jnp.stack([jnp.sum(mag2 * diag[0].astype(_ACC)),
                      jnp.sum(mag2 * diag[1].astype(_ACC))])


@partial(jax.jit, static_argnames=("num_qubits",))
def expec_diagonal_op_densmatr(state: jax.Array, diag: jax.Array, num_qubits: int) -> jax.Array:
    """Σ ρ_kk op_k as (re, im) (ref: densmatr_calcExpecDiagonalOpLocal :3781)."""
    d = densmatr_diagonal(state, num_qubits).astype(_ACC)
    dr, di = diag[0].astype(_ACC), diag[1].astype(_ACC)
    return jnp.stack([jnp.sum(d[0] * dr - d[1] * di),
                      jnp.sum(d[0] * di + d[1] * dr)])


# ---------------------------------------------------------------------------
# fused Pauli-sum kernels (SURVEY §3.5)
#
# The reference evaluates a Pauli sum as O(terms · n) full-state kernel calls
# with a workspace clone per term (ref: statevec_calcExpecPauliSum,
# QuEST_common.c:480-515).  Here each term is ONE pass: a Pauli product
# P = ⊗ P_q maps |k> -> i^{#Y} (-1)^{popcount((k^x) & zy)} |k ^ x| with
# x = mask(X|Y), zy = mask(Z|Y).  The statevector kernels unroll over STATIC
# term masks so each term's |k ^ x> movement lowers to structured layout ops
# (a static lane permutation / sublane take / prefix-axis flips — the same
# moves as the f64 gather engine, apply.py _dense_gather) and the parity
# phase to tiny broadcast sign vectors.  A dynamic (traced-mask) gather is
# NOT an option at scale: one 2^25-amp dynamic gather measured ~1.5 s on the
# v5e, and a 49-term scan of them blew the remote worker's program watchdog
# (observed as a "TPU worker crashed" kernel fault).  The density kernel
# keeps traced masks — its per-term gather touches only the 2^n diagonal
# band, far below the hazard size.
# ---------------------------------------------------------------------------

_PHASE_RE = jnp.asarray([1.0, 0.0, -1.0, 0.0])   # Re(i^yc)
_PHASE_IM = jnp.asarray([0.0, 1.0, 0.0, -1.0])   # Im(i^yc)

_I_POW = ((1.0, 0.0), (0.0, 1.0), (-1.0, 0.0), (0.0, -1.0))  # i^yc


@lru_cache(maxsize=None)
def _parity_sign_np(width: int, mask: int):
    """(-1)^popcount(k & mask) over k in [0, 2^width) as a host vector."""
    v = np.arange(1 << width) & mask
    p = np.zeros_like(v)
    while v.any():
        p ^= v & 1
        v >>= 1
    return 1.0 - 2.0 * p.astype(np.float64)


def _structured_term(state: jax.Array, x: int, zy: int, yc: int):
    """One static Pauli-product pass: returns the state view t and the moved,
    signed, i^yc-phased term amplitudes (tr, ti) in the same view shape."""
    from .apply import _blocks, _gather_plan, num_qubits_of

    n = num_qubits_of(state)
    lane_w = _blocks(n)[0]  # lane bits need no axis of their own
    wires = tuple(q for q in range(n) if ((x | zy) >> q) & 1 and q >= lane_w)
    dims, axis_of, sub_axis, lane_axis, l, s = _gather_plan(n, wires)
    t = state.reshape((2,) + dims)
    g = t
    lane_x = x & ((1 << l) - 1)
    sub_x = (x >> l) & ((1 << s) - 1) if s else 0
    if lane_x:
        g = g[..., np.arange(1 << l) ^ lane_x]
    if sub_x:
        g = jnp.take(g, np.arange(1 << s) ^ sub_x, axis=1 + sub_axis)
    for q in range(l + s, n):
        if (x >> q) & 1:
            g = jnp.flip(g, axis=1 + axis_of[q])
    # parity sign over OUTPUT bits in zy; par((k^x)&zy) = par(k&zy) ^ par(x&zy)
    body_rank = len(dims)
    const = 1.0 - 2.0 * (bin(x & zy).count("1") & 1)
    pr, pi = _I_POW[yc % 4]
    pr *= const
    pi *= const
    sign = None

    def factor(vec, axis):
        shape = [1] * body_rank
        shape[axis] = len(vec)
        return jnp.asarray(vec.reshape(shape), dtype=state.dtype)

    lane_z = zy & ((1 << l) - 1)
    if lane_z:
        sign = factor(_parity_sign_np(l, lane_z), lane_axis)
    sub_z = (zy >> l) & ((1 << s) - 1) if s else 0
    if sub_z:
        f = factor(_parity_sign_np(s, sub_z), sub_axis)
        sign = f if sign is None else sign * f
    for q in range(l + s, n):
        if (zy >> q) & 1:
            f = factor(np.array([1.0, -1.0]), axis_of[q])
            sign = f if sign is None else sign * f
    tr = pr * g[0] - pi * g[1]
    ti = pr * g[1] + pi * g[0]
    if sign is not None:
        tr = tr * sign
        ti = ti * sign
    return t, tr, ti


@partial(jax.jit, static_argnames=("terms",))
def _expec_pauli_sum_statevec_unrolled(state: jax.Array, terms: tuple,
                                       coeffs: jax.Array) -> jax.Array:
    coeffs = coeffs.astype(_ACC)
    acc = jnp.zeros((), _ACC)
    for i, (x, zy, yc) in enumerate(terms):
        t, tr, ti = _structured_term(state, x, zy, yc)
        acc = acc + coeffs[i] * jnp.sum(t[0].astype(_ACC) * tr.astype(_ACC)
                                        + t[1].astype(_ACC) * ti.astype(_ACC))
    return acc


# Above this many terms the unrolled structured path's compile time and
# program size (one pass per term, retraced per distinct term tuple) swamp
# its runtime win; the traced-mask scan is O(1)-trace.  The scan's dynamic
# k^x gather is only safe BELOW the measured hazard size (a single 2^25-amp
# dynamic gather ran ~1.5 s on v5e and a 49-term scan of them killed the
# worker), so huge many-term states stay on the unrolled path.
_SCAN_TERM_LIMIT = 32
_SCAN_AMPS_LIMIT = 1 << 24


def _term_mask_arrays(terms: tuple):
    x = jnp.asarray([t[0] for t in terms], jnp.uint64)
    zy = jnp.asarray([t[1] for t in terms], jnp.uint64)
    yc = jnp.asarray([t[2] % 4 for t in terms], jnp.int32)
    return x, zy, yc


@jax.jit
def _expec_pauli_sum_statevec_scan(state: jax.Array, x_masks: jax.Array,
                                   zy_masks: jax.Array, y_phases: jax.Array,
                                   coeffs: jax.Array) -> jax.Array:
    """Traced-mask twin of the unrolled kernel: one lax.scan over the term
    masks, so trace/compile cost is O(1) in term count (the molecular-
    Hamiltonian regime: thousands of terms on a moderate state)."""
    n_amps = state.shape[1]
    dt = jnp.uint32 if n_amps <= (1 << 31) else jnp.uint64
    k = jax.lax.iota(dt, n_amps)
    re, im = state[0].astype(_ACC), state[1].astype(_ACC)

    def body(acc, term):
        xm, zym, yc, c = term
        xm = xm.astype(dt)
        zym = zym.astype(dt)
        sign = 1.0 - 2.0 * (jax.lax.population_count(k & zym) & 1).astype(_ACC)
        sx = 1.0 - 2.0 * (jax.lax.population_count(xm & zym) & 1).astype(_ACC)
        pr = _PHASE_RE.astype(_ACC)[yc] * sx
        pi = _PHASE_IM.astype(_ACC)[yc] * sx
        flat = k ^ xm
        gr = state[0][flat].astype(_ACC) * sign
        gi = state[1][flat].astype(_ACC) * sign
        t = jnp.sum(re * (pr * gr - pi * gi) + im * (pr * gi + pi * gr))
        return acc + c * t, None

    acc, _ = jax.lax.scan(body, jnp.zeros((), _ACC),
                          (x_masks, zy_masks, y_phases, coeffs.astype(_ACC)))
    return acc


def expec_pauli_sum_statevec(state: jax.Array, terms: tuple,
                             coeffs: jax.Array) -> jax.Array:
    """Re Σ_t c_t <ψ|P_t|ψ> (``terms`` = ((x, zy, yc), ...)); accumulation in
    float64.  Few terms: one fused structured pass per static term.  Many
    terms on a below-hazard state: a traced-mask scan (O(1) trace size)."""
    if len(terms) > _SCAN_TERM_LIMIT and state.shape[1] <= _SCAN_AMPS_LIMIT:
        x, zy, yc = _term_mask_arrays(terms)
        return _expec_pauli_sum_statevec_scan(state, x, zy, yc, coeffs)
    return _expec_pauli_sum_statevec_unrolled(state, terms, coeffs)


@partial(jax.jit, static_argnames=("num_qubits",))
def expec_pauli_sum_densmatr(state: jax.Array, x_masks: jax.Array,
                             zy_masks: jax.Array, y_phases: jax.Array,
                             coeffs: jax.Array, num_qubits: int) -> jax.Array:
    """Σ_t c_t Re Tr(P_t ρ) on the Choi-flattened density matrix: the trace of
    the row-side product needs only the 2^n amplitudes at (k^x) + k·2^n."""
    dim = 1 << num_qubits
    dt = jnp.uint32 if 2 * num_qubits <= 32 else jnp.uint64
    k = jax.lax.iota(dt, dim)

    def body(acc, term):
        xm, zym, yc, c = term
        m = k ^ xm.astype(dt)
        par = (jax.lax.population_count(m & zym.astype(dt)) & 1).astype(_ACC)
        sign = 1.0 - 2.0 * par
        flat = m + (k << num_qubits)
        rr = state[0][flat].astype(_ACC) * sign
        ri = state[1][flat].astype(_ACC) * sign
        pr = _PHASE_RE.astype(_ACC)[yc]
        pi = _PHASE_IM.astype(_ACC)[yc]
        return acc + c * jnp.sum(rr * pr - ri * pi), None

    acc, _ = jax.lax.scan(body, jnp.zeros((), _ACC),
                          (x_masks, zy_masks, y_phases, coeffs.astype(_ACC)))
    return acc


# ---------------------------------------------------------------------------
# partial trace (TPU-native extension; no v3.2 analogue — QuEST added
# calcPartialTrace in a later major version)
# ---------------------------------------------------------------------------

def _route_bits(state: jax.Array, desired: dict) -> jax.Array:
    """Permute amplitude-index bits with tracked pair swaps (existing
    sharded swap kernels): ``desired[q] = target position``; unspecified
    bits end up wherever the routing leaves them."""
    from .apply import swap_qubit_amps

    nbits = int(state.shape[1]).bit_length() - 1
    at = list(range(nbits))
    pos = {q: q for q in range(nbits)}
    for q in sorted(desired, key=lambda q: desired[q]):
        tgt = desired[q]
        p = pos[q]
        if p != tgt:
            other = at[tgt]
            state = swap_qubit_amps(state, p, tgt)
            at[p], at[tgt] = other, q
            pos[other], pos[q] = p, tgt
    return state


@partial(jax.jit, static_argnames=("keep", "num_qubits"))
def densmatr_partial_trace(state: jax.Array, keep: tuple,
                           num_qubits: int) -> jax.Array:
    """Tr_S ρ over the non-kept qubits of a Choi-flattened density matrix.
    Output is the (2, 4^m) flattened reduced matrix with kept qubit
    ``keep[i]`` as qubit i, element (r, c) at r + c·2^m (the getDensityAmp
    convention).

    Scatter-free: index bits are routed by pair swaps so traced row/col bits
    become the two minor blocks, then the block trace is either ONE
    contraction against the 2^t identity (t >= 7: the traced axes are
    tile-wide) or a sum of 2^t static diagonal-block slices (small t).  A
    segment-sum spelling measured 94 s for a 14-qubit density matrix on the
    v5e (the 2^25+ dynamic-scatter cliff); this form is a handful of
    bandwidth-bound passes."""
    n = num_qubits
    m = len(keep)
    t = n - m
    traced = tuple(q for q in range(n) if q not in keep)
    if t >= 7:
        # layout (msf): a | b | s_c | s_r  ->  dims (2^m, 2^m, 2^t, 2^t)
        desired = {}
        for j, q in enumerate(traced):
            desired[q] = j                   # s_r
            desired[q + n] = t + j           # s_c
        for j, q in enumerate(keep):
            desired[q + n] = 2 * t + j       # b (result column)
            desired[q] = 2 * t + m + j       # a (result row)
        state = _route_bits(state, desired)
        v = state.reshape(2, 1 << m, 1 << m, 1 << t, 1 << t)
        eye = jnp.eye(1 << t, dtype=state.dtype)
        out = jnp.tensordot(v, eye, axes=[[3, 4], [0, 1]])  # (2, a, b)
        return jnp.transpose(out, (0, 2, 1)).reshape(2, -1)
    # small traced block: layout (msf) s_c | b | s_r | a, then sum the
    # 2^t static diagonal (s, s) slices
    desired = {}
    for j, q in enumerate(keep):
        desired[q] = j                       # a
        desired[q + n] = n + j               # b
    for j, q in enumerate(traced):
        desired[q] = m + j                   # s_r
        desired[q + n] = n + m + j           # s_c
    state = _route_bits(state, desired)
    v = state.reshape(2, 1 << t, 1 << m, 1 << t, 1 << m)
    out = None
    for s_ in range(1 << t):
        piece = v[:, s_, :, s_, :]           # (2, b, a)
        out = piece if out is None else out + piece
    return out.reshape(2, -1)


@partial(jax.jit, static_argnames=("keep",))
def statevec_partial_trace(state: jax.Array, keep: tuple) -> jax.Array:
    """Reduced density matrix of a pure state: Tr_S |ψ⟩⟨ψ| without ever
    materialising the 4^n outer product.  The kept qubits are swapped to the
    top of the index (existing sharded swap kernels), making each reduced
    element a dot of two contiguous 2^t-amp slices.  When the (2^m, 2^t)
    slice view is tile-aligned (both dims at/above the (8, 128) f32 tile) —
    or the whole state is small enough that padding is bounded by a few MB —
    the reduction is ONE pair of MXU matmuls (the Gram matrix of the slice
    family); otherwise 4^m explicit slice dots avoid materialising a padded
    view of a large state (that fallback is only hit with small m, or in
    the impractical corner of keeping nearly all qubits of a large state,
    where the 2^m-dim output is itself exponential)."""
    from .apply import num_qubits_of

    n = num_qubits_of(state)
    m = len(keep)
    t = n - m
    state = _route_bits(state, {q: t + i for i, q in enumerate(keep)})
    t_dim, m_dim = 1 << t, 1 << m
    if m >= 3 and (t >= 7 or n <= 14):
        x = state.reshape(2, m_dim, t_dim).astype(_ACC)  # trailing >= (8,128)
        xr, xi = x[0], x[1]
        rr = xr @ xr.T + xi @ xi.T            # Re Σ_s x[a,s] conj-pair x[b,s]
        ri = xi @ xr.T - xr @ xi.T
    else:
        rows_r, rows_i = [], []
        for a in range(m_dim):
            sl = jax.lax.slice_in_dim(state, a * t_dim, (a + 1) * t_dim, axis=1)
            ar, ai = sl[0].astype(_ACC), sl[1].astype(_ACC)
            er, ei = [], []
            for b in range(m_dim):
                sb = jax.lax.slice_in_dim(state, b * t_dim, (b + 1) * t_dim, axis=1)
                br, bi = sb[0].astype(_ACC), sb[1].astype(_ACC)
                er.append(jnp.sum(ar * br + ai * bi))
                ei.append(jnp.sum(ai * br - ar * bi))
            rows_r.append(jnp.stack(er))
            rows_i.append(jnp.stack(ei))
        rr = jnp.stack(rows_r)
        ri = jnp.stack(rows_i)
    # flatten to the column-major (r + c·2^m) Qureg layout
    return jnp.stack([rr.T.reshape(-1), ri.T.reshape(-1)]).astype(state.dtype)


@jax.jit
def _apply_pauli_sum_scan(state: jax.Array, x_masks: jax.Array,
                          zy_masks: jax.Array, y_phases: jax.Array,
                          coeffs: jax.Array) -> jax.Array:
    """Traced-mask twin of apply_pauli_sum for many-term sums on
    below-hazard states (see _SCAN_TERM_LIMIT)."""
    n_amps = state.shape[1]
    dt = jnp.uint32 if n_amps <= (1 << 31) else jnp.uint64
    k = jax.lax.iota(dt, n_amps)
    sdt = state.dtype

    def body(acc, term):
        xm, zym, yc, c = term
        xm = xm.astype(dt)
        zym = zym.astype(dt)
        sign = (1.0 - 2.0 * (jax.lax.population_count(k & zym) & 1)).astype(sdt)
        sx = (1.0 - 2.0 * (jax.lax.population_count(xm & zym) & 1)).astype(sdt)
        pr = _PHASE_RE.astype(sdt)[yc] * sx
        pi = _PHASE_IM.astype(sdt)[yc] * sx
        gr = state[0][k ^ xm] * sign
        gi = state[1][k ^ xm] * sign
        piece = c.astype(sdt) * jnp.stack([pr * gr - pi * gi,
                                           pr * gi + pi * gr])
        return acc + piece, None

    out, _ = jax.lax.scan(body, jnp.zeros_like(state),
                          (x_masks, zy_masks, y_phases, coeffs))
    return out


def apply_pauli_sum(state: jax.Array, terms: tuple,
                    coeffs: jax.Array) -> jax.Array:
    """out = Σ_t c_t P_t ψ — dispatcher twin of expec_pauli_sum_statevec:
    traced-mask scan for many terms on below-hazard states, unrolled
    structured passes otherwise."""
    if len(terms) > _SCAN_TERM_LIMIT and state.shape[1] <= _SCAN_AMPS_LIMIT:
        x, zy, yc = _term_mask_arrays(terms)
        return _apply_pauli_sum_scan(state, x, zy, yc, coeffs)
    return _apply_pauli_sum_unrolled(state, terms, coeffs)


@partial(jax.jit, static_argnames=("terms",))
def _apply_pauli_sum_unrolled(state: jax.Array, terms: tuple,
                              coeffs: jax.Array) -> jax.Array:
    """one fused structured pass per static term
    (ref: statevec_applyPauliSum, QuEST_common.c:493-515, which clones +
    applies + accumulates per term).  The accumulator stays in the state
    dtype: a state-sized f64 carry costs 4x HBM traffic on an f32 state, and
    the sum has only `terms` addends."""
    out = None
    coeffs = coeffs.astype(state.dtype)
    for i, (x, zy, yc) in enumerate(terms):
        _, tr, ti = _structured_term(state, x, zy, yc)
        piece = coeffs[i] * jnp.stack([tr, ti]).reshape(2, -1)
        out = piece if out is None else out + piece
        # without the barrier XLA is free to materialise many terms' moved
        # copies concurrently — observed RESOURCE_EXHAUSTED at 26q f32
        out = jax.lax.optimization_barrier(out)
    return out.astype(state.dtype)
