"""Scalar calculations: norms, inner products, purity, fidelity, expectations.

Ref analogues: calcTotalProb (QuEST_cpu_local.c:118-167),
statevec_calcInnerProductLocal (QuEST_cpu.c:1071), densmatr_calcPurityLocal
(:861), densmatr_calcFidelityLocal (:990), calcHilbertSchmidtDistanceSquaredLocal
(:923), densmatr_calcInnerProductLocal (:958), calcExpecDiagonalOp (:3738/:3781).

All reductions accumulate in float64 regardless of state dtype (the reference
uses double + Kahan); under a sharded state GSPMD turns these into local
partial sums + psum, exactly the reference's MPI_Allreduce pattern
(QuEST_cpu_distributed.c:35-117).  Results are (re, im) pairs or real scalars
— never complex dtypes (unsupported at TPU program boundaries)."""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .measure import densmatr_diagonal

_ACC = jnp.float64


def _mag2(state: jax.Array) -> jax.Array:
    re, im = state[0].astype(_ACC), state[1].astype(_ACC)
    return re * re + im * im


@jax.jit
def total_prob_statevec(state: jax.Array) -> jax.Array:
    return jnp.sum(_mag2(state))


@partial(jax.jit, static_argnames=("num_qubits",))
def total_prob_densmatr(state: jax.Array, num_qubits: int) -> jax.Array:
    """Trace of ρ — sum of real diagonal parts."""
    return jnp.sum(densmatr_diagonal(state, num_qubits)[0].astype(_ACC))


@jax.jit
def inner_product(bra: jax.Array, ket: jax.Array) -> jax.Array:
    """<bra|ket> = Σ conj(a)·b, returned as a (re, im) pair."""
    ar, ai = bra[0].astype(_ACC), bra[1].astype(_ACC)
    br, bi = ket[0].astype(_ACC), ket[1].astype(_ACC)
    return jnp.stack([jnp.sum(ar * br + ai * bi), jnp.sum(ar * bi - ai * br)])


@jax.jit
def densmatr_inner_product(rho1: jax.Array, rho2: jax.Array) -> jax.Array:
    """Re Tr(ρ1† ρ2) = Σ Re(ρ1*_ij ρ2_ij) (ref: densmatr_calcInnerProductLocal,
    QuEST_cpu.c:958 — equals Tr(ρ1 ρ2) for Hermitian inputs)."""
    return jnp.sum(rho1[0].astype(_ACC) * rho2[0].astype(_ACC)
                   + rho1[1].astype(_ACC) * rho2[1].astype(_ACC))


@jax.jit
def purity(state: jax.Array) -> jax.Array:
    """Tr(ρ²) = Σ|ρ_ij|² for Hermitian ρ (ref: densmatr_calcPurityLocal :861)."""
    return jnp.sum(_mag2(state))


@jax.jit
def hilbert_schmidt_distance_squared(a: jax.Array, b: jax.Array) -> jax.Array:
    d0 = a[0].astype(_ACC) - b[0].astype(_ACC)
    d1 = a[1].astype(_ACC) - b[1].astype(_ACC)
    return jnp.sum(d0 * d0 + d1 * d1)


@partial(jax.jit, static_argnames=("num_qubits",))
def densmatr_fidelity(rho: jax.Array, pure: jax.Array, num_qubits: int) -> jax.Array:
    """<ψ|ρ|ψ> = Σ_rc ψ_r* ρ(r,c) ψ_c (ref: densmatr_calcFidelityLocal :990).

    Two real matvecs on the flattened matrix — MXU work when large."""
    dim = 1 << num_qubits
    mr = rho[0].reshape(dim, dim).astype(_ACC)  # [col, row]
    mi = rho[1].reshape(dim, dim).astype(_ACC)
    pr, pi = pure[0].astype(_ACC), pure[1].astype(_ACC)
    # v_c = Σ_r conj(ψ)_r M[c, r]  (complex matvec in real parts)
    vr = mr @ pr + mi @ pi
    vi = mi @ pr - mr @ pi
    # Re Σ_c ψ_c v_c
    return jnp.sum(pr * vr - pi * vi)


@jax.jit
def expec_diagonal_op_statevec(state: jax.Array, diag: jax.Array) -> jax.Array:
    """Σ |ψ_k|² op_k as (re, im) (ref: statevec_calcExpecDiagonalOpLocal :3738)."""
    mag2 = _mag2(state)
    return jnp.stack([jnp.sum(mag2 * diag[0].astype(_ACC)),
                      jnp.sum(mag2 * diag[1].astype(_ACC))])


@partial(jax.jit, static_argnames=("num_qubits",))
def expec_diagonal_op_densmatr(state: jax.Array, diag: jax.Array, num_qubits: int) -> jax.Array:
    """Σ ρ_kk op_k as (re, im) (ref: densmatr_calcExpecDiagonalOpLocal :3781)."""
    d = densmatr_diagonal(state, num_qubits).astype(_ACC)
    dr, di = diag[0].astype(_ACC), diag[1].astype(_ACC)
    return jnp.stack([jnp.sum(d[0] * dr - d[1] * di),
                      jnp.sum(d[0] * di + d[1] * dr)])
