"""Measurement probabilities and state collapse.

Ref analogues: findProbabilityOfZeroLocal (QuEST_cpu.c:3206),
collapseToKnownProbOutcomeLocal (:3380), densmatr variants (:3151, :785).
Reductions are plain jnp sums: under a sharded state GSPMD emits the psum the
reference performed with MPI_Allreduce (QuEST_cpu_distributed.c:1260-1274).
Accumulation is promoted to float64 to match the reference's double-precision
Kahan accuracy (QuEST_cpu_local.c:118-167); on TPU f64 is compiler-emulated,
costing a few extra vector ops on an already bandwidth-bound reduction."""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .apply import _axis, num_qubits_of

_ACC = jnp.float64  # reduction accumulator (f64 even for f32 states)


@partial(jax.jit, static_argnames=("target",))
def prob_of_zero(state: jax.Array, target: int) -> jax.Array:
    """P(qubit ``target`` = 0) for a statevector."""
    n = num_qubits_of(state)
    t = state.reshape((2,) + (2,) * n)
    idx = [slice(None)] * (n + 1)
    idx[1 + _axis(target, n)] = 0
    sub = t[tuple(idx)].astype(_ACC)
    return jnp.sum(sub[0] ** 2 + sub[1] ** 2)


@partial(jax.jit, static_argnames=("num_qubits",))
def densmatr_diagonal(state: jax.Array, num_qubits: int) -> jax.Array:
    """The 2^N diagonal elements ρ_kk, as a (2, 2^N) pair."""
    dim = 1 << num_qubits
    m = state.reshape(2, dim, dim)  # [re/im, col, row]
    return jnp.stack([jnp.diagonal(m[0]), jnp.diagonal(m[1])])


@partial(jax.jit, static_argnames=("target", "num_qubits"))
def densmatr_prob_of_zero(state: jax.Array, target: int, num_qubits: int) -> jax.Array:
    """P(target=0) = sum of diagonal elements with bit ``target`` clear
    (ref: densmatr_findProbabilityOfZeroLocal, QuEST_cpu.c:3151)."""
    diag = densmatr_diagonal(state, num_qubits)[0].astype(_ACC)
    t = diag.reshape((2,) * num_qubits)
    idx = [slice(None)] * num_qubits
    idx[_axis(target, num_qubits)] = 0
    return jnp.sum(t[tuple(idx)])


@partial(jax.jit, static_argnames=("target", "outcome"))
def collapse_to_outcome(state: jax.Array, target: int, outcome: int,
                        outcome_prob: jax.Array) -> jax.Array:
    """Zero the non-outcome half, renormalise the kept half by 1/sqrt(p)
    (ref: collapseToKnownProbOutcomeLocal, QuEST_cpu.c:3380)."""
    n = num_qubits_of(state)
    t = state.reshape((2,) + (2,) * n)
    a = _axis(target, n)
    renorm = 1.0 / jnp.sqrt(outcome_prob.astype(_ACC))
    keep = jnp.zeros(2, dtype=_ACC).at[outcome].set(1.0)
    factor = (keep * renorm).astype(state.dtype)
    shape = [1] * (n + 1)
    shape[1 + a] = 2
    t = t * factor.reshape(shape)
    return t.reshape(2, -1)


@partial(jax.jit, static_argnames=("target", "outcome", "num_qubits"))
def densmatr_collapse_to_outcome(state: jax.Array, target: int, outcome: int,
                                 outcome_prob: jax.Array, num_qubits: int) -> jax.Array:
    """Zero every element whose row OR column bit differs from the outcome,
    renormalise survivors by 1/p (ref: densmatr_collapseToKnownProbOutcome,
    QuEST_cpu.c:785)."""
    n = 2 * num_qubits
    t = state.reshape((2,) + (2,) * n)
    row_axis = _axis(target, n)
    col_axis = _axis(target + num_qubits, n)
    keep = jnp.zeros(2, dtype=_ACC).at[outcome].set(1.0)
    shape_r = [1] * (n + 1)
    shape_r[1 + row_axis] = 2
    shape_c = [1] * (n + 1)
    shape_c[1 + col_axis] = 2
    mask = (keep.reshape(shape_r) * keep.reshape(shape_c)) / outcome_prob.astype(_ACC)
    t = t * mask.astype(state.dtype)
    return t.reshape(2, -1)
