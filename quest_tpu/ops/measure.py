"""Measurement probabilities and state collapse.

Ref analogues: findProbabilityOfZeroLocal (QuEST_cpu.c:3206),
collapseToKnownProbOutcomeLocal (:3380), densmatr variants (:3151, :785).
Reductions are plain jnp sums: under a sharded state GSPMD emits the psum the
reference performed with MPI_Allreduce (QuEST_cpu_distributed.c:1260-1274).
Accumulation is promoted to float64 to match the reference's double-precision
Kahan accuracy (QuEST_cpu_local.c:118-167); on TPU f64 is compiler-emulated,
costing a few extra vector ops on an already bandwidth-bound reduction.

Probabilities are single fused flat passes (iota bit-mask + multiply +
reduce — no reshape, so no tile-padding hazards); collapses are diagonal
multiplies routed through the universal engine's block-expanded broadcast
path (apply.apply_diagonal)."""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .apply import apply_diagonal, num_qubits_of

_ACC = jnp.float64  # reduction accumulator (f64 even for f32 states)


def _bit_mask(num_amps_log2: int, target: int, outcome: int):
    """Flat {0,1} mask over 2^n amplitudes: 1 where bit ``target`` equals
    ``outcome``.  A fused iota — never materialised."""
    dt = jnp.uint32 if num_amps_log2 <= 32 else jnp.uint64
    k = jax.lax.iota(dt, 1 << num_amps_log2)
    return ((k >> target) & 1) == outcome


@partial(jax.jit, static_argnames=("target",))
def prob_of_zero(state: jax.Array, target: int) -> jax.Array:
    """P(qubit ``target`` = 0) for a statevector."""
    n = num_qubits_of(state)
    mask = _bit_mask(n, int(target), 0)
    re, im = state[0].astype(_ACC), state[1].astype(_ACC)
    return jnp.sum(jnp.where(mask, re * re + im * im, 0.0))


@partial(jax.jit, static_argnames=("num_qubits",))
def densmatr_diagonal(state: jax.Array, num_qubits: int) -> jax.Array:
    """The 2^N diagonal elements ρ_kk, as a (2, 2^N) pair."""
    dim = 1 << num_qubits
    m = state.reshape(2, dim, dim)  # [re/im, col, row]
    return jnp.stack([jnp.diagonal(m[0]), jnp.diagonal(m[1])])


@partial(jax.jit, static_argnames=("target", "num_qubits"))
def densmatr_prob_of_zero(state: jax.Array, target: int, num_qubits: int) -> jax.Array:
    """P(target=0) = sum of diagonal elements with bit ``target`` clear
    (ref: densmatr_findProbabilityOfZeroLocal, QuEST_cpu.c:3151)."""
    diag = densmatr_diagonal(state, num_qubits)[0].astype(_ACC)
    mask = _bit_mask(num_qubits, int(target), 0)
    return jnp.sum(jnp.where(mask, diag, 0.0))


@partial(jax.jit, static_argnames=("target", "outcome"))
def collapse_to_outcome(state: jax.Array, target: int, outcome: int,
                        outcome_prob: jax.Array) -> jax.Array:
    """Zero the non-outcome half, renormalise the kept half by 1/sqrt(p)
    (ref: collapseToKnownProbOutcomeLocal, QuEST_cpu.c:3380) — a real
    diagonal multiply through the universal engine."""
    renorm = 1.0 / jnp.sqrt(outcome_prob.astype(_ACC))
    dr = jnp.zeros(2, dtype=_ACC).at[outcome].set(renorm)
    d = jnp.stack([dr, jnp.zeros_like(dr)])
    return apply_diagonal(state, d, (int(target),))


@partial(jax.jit, static_argnames=("target", "outcome", "num_qubits"))
def densmatr_collapse_to_outcome(state: jax.Array, target: int, outcome: int,
                                 outcome_prob: jax.Array, num_qubits: int) -> jax.Array:
    """Zero every element whose row OR column bit differs from the outcome,
    renormalise survivors by 1/p (ref: densmatr_collapseToKnownProbOutcome,
    QuEST_cpu.c:785) — a diagonal multiply on the (row, col) qubit pair of
    the Choi-flattened matrix."""
    # targets (q, q+N): index = row_bit + 2*col_bit; survivor at 3*outcome
    dr = jnp.zeros(4, dtype=_ACC).at[3 * outcome].set(1.0 / outcome_prob.astype(_ACC))
    d = jnp.stack([dr, jnp.zeros_like(dr)])
    return apply_diagonal(state, d, (int(target), int(target) + num_qubits))


# ---------------------------------------------------------------------------
# joint outcome distributions (TPU-native extension; the reference can only
# query one qubit at a time — calcProbOfOutcome)
# ---------------------------------------------------------------------------

def _group_probs(weights: jax.Array, n: int, targets: tuple) -> jax.Array:
    """Sum ``weights`` (2^n, f64) into the 2^k joint-outcome histogram of the
    ``targets`` bits: outcome index bit i = state bit targets[i].  One fused
    iota keys a segment-sum — a single scatter-add pass, no reshape (so no
    tile-padding hazard at any n, and GSPMD turns the segment ids into a
    shard-local scatter + psum under a sharded state)."""
    if tuple(targets) == tuple(range(n)):
        return weights  # identity grouping: the histogram IS the weight vector
    dt = jnp.uint32 if n <= 32 else jnp.uint64
    k = jax.lax.iota(dt, 1 << n)
    idx = jnp.zeros_like(k)
    for i, q in enumerate(targets):
        idx = idx | (((k >> int(q)) & 1) << i)
    return jax.ops.segment_sum(weights, idx.astype(jnp.int32),
                               num_segments=1 << len(targets))


@partial(jax.jit, static_argnames=("targets",))
def prob_all_outcomes(state: jax.Array, targets: tuple) -> jax.Array:
    """Joint probability of every outcome of the ``targets`` qubits of a
    statevector, as a 2^k f64 vector."""
    n = num_qubits_of(state)
    re, im = state[0].astype(_ACC), state[1].astype(_ACC)
    return _group_probs(re * re + im * im, n, targets)


@partial(jax.jit, static_argnames=("targets", "num_qubits"))
def densmatr_prob_all_outcomes(state: jax.Array, targets: tuple,
                               num_qubits: int) -> jax.Array:
    """Joint outcome distribution from the density-matrix diagonal."""
    diag = densmatr_diagonal(state, num_qubits)[0].astype(_ACC)
    return _group_probs(diag, num_qubits, targets)
