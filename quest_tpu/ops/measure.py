"""Measurement probabilities and state collapse.

Ref analogues: findProbabilityOfZeroLocal (QuEST_cpu.c:3206),
collapseToKnownProbOutcomeLocal (:3380), densmatr variants (:3151, :785).
Reductions are plain jnp sums: under a sharded state GSPMD emits the psum the
reference performed with MPI_Allreduce (QuEST_cpu_distributed.c:1260-1274).
Accumulation is promoted to float64 to match the reference's double-precision
Kahan accuracy (QuEST_cpu_local.c:118-167); on TPU f64 is compiler-emulated,
costing a few extra vector ops on an already bandwidth-bound reduction.

Probabilities are single fused flat passes (iota bit-mask + multiply +
reduce — no reshape, so no tile-padding hazards); collapses are diagonal
multiplies routed through the universal engine's block-expanded broadcast
path (apply.apply_diagonal)."""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp

from .apply import apply_diagonal, num_qubits_of

_ACC = jnp.float64  # reduction accumulator (f64 even for f32 states)


def _bit_mask(num_amps_log2: int, target: int, outcome: int):
    """Flat {0,1} mask over 2^n amplitudes: 1 where bit ``target`` equals
    ``outcome``.  A fused iota — never materialised."""
    dt = jnp.uint32 if num_amps_log2 <= 32 else jnp.uint64
    k = jax.lax.iota(dt, 1 << num_amps_log2)
    return ((k >> target) & 1) == outcome


@partial(jax.jit, static_argnames=("target",))
def prob_of_zero(state: jax.Array, target: int) -> jax.Array:
    """P(qubit ``target`` = 0) for a statevector."""
    n = num_qubits_of(state)
    mask = _bit_mask(n, int(target), 0)
    re, im = state[0].astype(_ACC), state[1].astype(_ACC)
    return jnp.sum(jnp.where(mask, re * re + im * im, 0.0))


@partial(jax.jit, static_argnames=("num_qubits",))
def densmatr_diagonal(state: jax.Array, num_qubits: int) -> jax.Array:
    """The 2^N diagonal elements ρ_kk, as a (2, 2^N) pair."""
    dim = 1 << num_qubits
    m = state.reshape(2, dim, dim)  # [re/im, col, row]
    return jnp.stack([jnp.diagonal(m[0]), jnp.diagonal(m[1])])


@partial(jax.jit, static_argnames=("target", "num_qubits"))
def densmatr_prob_of_zero(state: jax.Array, target: int, num_qubits: int) -> jax.Array:
    """P(target=0) = sum of diagonal elements with bit ``target`` clear
    (ref: densmatr_findProbabilityOfZeroLocal, QuEST_cpu.c:3151)."""
    diag = densmatr_diagonal(state, num_qubits)[0].astype(_ACC)
    mask = _bit_mask(num_qubits, int(target), 0)
    return jnp.sum(jnp.where(mask, diag, 0.0))


@partial(jax.jit, static_argnames=("target", "outcome"))
def collapse_to_outcome(state: jax.Array, target: int, outcome: int,
                        outcome_prob: jax.Array) -> jax.Array:
    """Zero the non-outcome half, renormalise the kept half by 1/sqrt(p)
    (ref: collapseToKnownProbOutcomeLocal, QuEST_cpu.c:3380) — a real
    diagonal multiply through the universal engine."""
    renorm = 1.0 / jnp.sqrt(outcome_prob.astype(_ACC))
    dr = jnp.zeros(2, dtype=_ACC).at[outcome].set(renorm)
    d = jnp.stack([dr, jnp.zeros_like(dr)])
    return apply_diagonal(state, d, (int(target),))


@partial(jax.jit, static_argnames=("target", "outcome", "num_qubits"))
def densmatr_collapse_to_outcome(state: jax.Array, target: int, outcome: int,
                                 outcome_prob: jax.Array, num_qubits: int) -> jax.Array:
    """Zero every element whose row OR column bit differs from the outcome,
    renormalise survivors by 1/p (ref: densmatr_collapseToKnownProbOutcome,
    QuEST_cpu.c:785) — a diagonal multiply on the (row, col) qubit pair of
    the Choi-flattened matrix."""
    # targets (q, q+N): index = row_bit + 2*col_bit; survivor at 3*outcome
    dr = jnp.zeros(4, dtype=_ACC).at[3 * outcome].set(1.0 / outcome_prob.astype(_ACC))
    d = jnp.stack([dr, jnp.zeros_like(dr)])
    return apply_diagonal(state, d, (int(target), int(target) + num_qubits))


# ---------------------------------------------------------------------------
# joint outcome distributions (TPU-native extension; the reference can only
# query one qubit at a time — calcProbOfOutcome)
# ---------------------------------------------------------------------------

@lru_cache(maxsize=None)
def _indicator_np(width: int, bit_positions: tuple):
    """(2^width, 2^k) 0/1 matrix: M[v, o] = 1 iff bit_positions[j] of v
    equals bit j of o for every j — the grouping contraction for one
    tile-block axis."""
    import numpy as np

    v = np.arange(1 << width)
    m = np.ones((1 << width, 1 << len(bit_positions)))
    for j, p in enumerate(bit_positions):
        vb = (v >> p) & 1
        for o in range(1 << len(bit_positions)):
            m[:, o] *= (vb == ((o >> j) & 1))
    return m


def _group_probs(weights: jax.Array, n: int, targets: tuple) -> jax.Array:
    """Sum ``weights`` (2^n, f64) into the 2^k joint-outcome histogram of the
    ``targets`` bits: outcome index bit i = state bit targets[i].

    Structured, scatter-free: the grouped tile-safe view gives every prefix
    target its own axis; non-target axes are plain sums, and the lane /
    sublane blocks contract against tiny host-built 0/1 indicator matrices
    (an MXU matmul).  A segment-sum spelling was measured falling off a
    cliff at 2^25 amps on the v5e (6-12 s dynamic scatter — the same hazard
    family as the traced-mask Pauli gathers); this form is a bandwidth-bound
    reduction at any size."""
    if tuple(targets) == tuple(range(n)):
        return weights  # identity grouping: the histogram IS the weight vector
    from .apply import _blocks, _gather_plan

    k = len(targets)
    lane_w = _blocks(n)[0]  # lane bits need no axis of their own
    dims, axis_of, sub_axis, lane_axis, l, s = _gather_plan(
        n, tuple(sorted(q for q in targets if q >= lane_w)))
    lane_ts = tuple((i, q) for i, q in enumerate(targets) if q < l)
    sub_ts = tuple((i, q) for i, q in enumerate(targets) if l <= q < l + s)
    pre_ts = tuple((i, q) for i, q in enumerate(targets) if q >= l + s)
    w = weights.reshape(dims)
    keep = {axis_of[q] for _, q in pre_ts}
    keep.add(lane_axis)
    if sub_ts:
        keep.add(sub_axis)
    summed = tuple(a for a in range(len(dims)) if a not in keep)
    if summed:
        w = jnp.sum(w, axis=summed)
    # remaining axes, in order: prefix target axes (most-significant qubit
    # first), then the sublane axis (when isolated), then the lane axis
    pre_dim = 1 << len(pre_ts)
    sub_dim = (1 << s) if sub_ts else 1
    w = w.reshape(pre_dim, sub_dim, 1 << l)
    msub = (jnp.asarray(_indicator_np(s, tuple(q - l for _, q in sub_ts)),
                        dtype=w.dtype) if sub_ts
            else jnp.ones((sub_dim, 1), dtype=w.dtype))
    mlan = (jnp.asarray(_indicator_np(l, tuple(q for _, q in lane_ts)),
                        dtype=w.dtype) if lane_ts
            else jnp.ones((1 << l, 1), dtype=w.dtype))
    res = jnp.einsum("psl,sa,lb->pab", w, msub, mlan).reshape(-1)
    # host-side permutation from the (pre desc-q, sub, lane) flat order to
    # the outcome order (bit i = targets[i]) — 2^k entries, trivial
    import numpy as np

    a_w, b_w = msub.shape[1], mlan.shape[1]
    pre_desc = sorted(pre_ts, key=lambda t: -t[1])  # view axis order
    perm = np.empty(1 << k, dtype=np.int32)
    for o in range(1 << k):
        p = 0
        for j, (i, _q) in enumerate(pre_desc):
            p |= ((o >> i) & 1) << (len(pre_desc) - 1 - j)
        a = 0
        for j, (i, _q) in enumerate(sub_ts):
            a |= ((o >> i) & 1) << j
        b = 0
        for j, (i, _q) in enumerate(lane_ts):
            b |= ((o >> i) & 1) << j
        perm[o] = (p * a_w + a) * b_w + b
    return res[jnp.asarray(perm)]



@partial(jax.jit, static_argnames=("targets",))
def prob_all_outcomes(state: jax.Array, targets: tuple) -> jax.Array:
    """Joint probability of every outcome of the ``targets`` qubits of a
    statevector, as a 2^k f64 vector."""
    n = num_qubits_of(state)
    re, im = state[0].astype(_ACC), state[1].astype(_ACC)
    return _group_probs(re * re + im * im, n, targets)


@partial(jax.jit, static_argnames=("targets", "num_qubits"))
def densmatr_prob_all_outcomes(state: jax.Array, targets: tuple,
                               num_qubits: int) -> jax.Array:
    """Joint outcome distribution from the density-matrix diagonal."""
    diag = densmatr_diagonal(state, num_qubits)[0].astype(_ACC)
    return _group_probs(diag, num_qubits, targets)


# --- plane-pair twins (huge single-device registers; qureg.py) -------------

@partial(jax.jit, static_argnames=("target",))
def prob_of_zero_planes(re: jax.Array, im: jax.Array, target: int) -> jax.Array:
    """P(bit ``target`` = 0) on plane-pair storage.  Products stay in the
    plane dtype and only the REDUCTION accumulates in f64: an .astype(f64)
    of a 4 GiB f32 plane would materialise the one extra state copy this
    regime cannot hold."""
    n = int(re.shape[0]).bit_length() - 1
    mask = _bit_mask(n, int(target), 0)
    return (jnp.sum(jnp.where(mask, re * re, 0), dtype=jnp.float64)
            + jnp.sum(jnp.where(mask, im * im, 0), dtype=jnp.float64))


@partial(jax.jit, static_argnames=("target", "outcome"), donate_argnums=(0, 1))
def collapse_planes(re: jax.Array, im: jax.Array, target: int, outcome: int,
                    outcome_prob: jax.Array):
    """Collapse + renormalise on plane-pair storage — elementwise, so the
    donated planes alias their outputs (in-place at the memory ceiling)."""
    n = int(re.shape[0]).bit_length() - 1
    mask = _bit_mask(n, int(target), int(outcome))
    s = (1.0 / jnp.sqrt(outcome_prob)).astype(re.dtype)
    zero = jnp.zeros((), re.dtype)
    return jnp.where(mask, re * s, zero), jnp.where(mask, im * s, zero)


@jax.jit
def total_prob_planes(re: jax.Array, im: jax.Array) -> jax.Array:
    return (jnp.sum(re * re, dtype=jnp.float64)
            + jnp.sum(im * im, dtype=jnp.float64))
