"""Version shims over the moving parts of the jax API surface.

The codebase targets current jax, where ``jax.shard_map`` and
``jax.enable_x64`` are top-level; on the 0.4.x series both still live under
``jax.experimental``.  Every internal user imports the symbol from here so
the compatibility decision is made exactly once.
"""

from __future__ import annotations

import jax

# complex128 support requires x64 mode; enable it once, here.  float32
# quregs are still first-class (dtype is per-Qureg), x64 only widens what
# JAX *allows*, not what we allocate.  This module is the ONE allowlisted
# site for import-time jax.config mutation — the purity lint
# (analysis/purity.py P_IMPORT_TIME_STATE_MUTATION) flags it anywhere else
# in the package, so the compatibility decision cannot quietly spread.
jax.config.update("jax_enable_x64", True)

try:  # jax >= 0.5
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map

import inspect

_SHARD_MAP_PARAMS = frozenset(inspect.signature(_shard_map).parameters)


def shard_map(f, **kwargs):
    """shard_map with the check_vma/check_rep kwarg rename papered over
    (new jax renamed check_rep -> check_vma; the semantics are the same)."""
    if "check_vma" in kwargs and "check_vma" not in _SHARD_MAP_PARAMS:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    return _shard_map(f, **kwargs)

try:  # jax >= 0.4.26 top-level export
    enable_x64 = jax.enable_x64
except AttributeError:  # pragma: no cover - 0.4.x
    from jax.experimental import enable_x64  # noqa: F401

# jax 0.4.x ships optimization_barrier without a batching rule, so any
# jax.vmap over a program containing one (the serve layer's mode='vmap'
# gradient lowering; ops/calc.py's per-term accumulator barrier) dies with
# NotImplementedError.  The rule is trivial — a barrier is shape-preserving
# and elementwise-transparent, so binding the batched operands and passing
# the batch dims through IS the batched barrier (newer jax implements
# exactly this).  Registered only when missing.
try:  # pragma: no cover - presence depends on jax version
    from jax._src.interpreters import batching as _batching
    from jax._src.lax.lax import optimization_barrier_p as _opt_barrier_p

    if _opt_barrier_p not in _batching.primitive_batchers:
        def _optimization_barrier_batcher(args, dims, **params):
            return _opt_barrier_p.bind(*args, **params), dims

        _batching.primitive_batchers[_opt_barrier_p] = \
            _optimization_barrier_batcher
except (ImportError, AttributeError):
    pass
