"""Quantum-trajectory (Monte-Carlo wavefunction) noise simulation.

No reference analogue: the reference simulates noise only as density
matrices (4^n amplitudes).  The trajectory unraveling runs noisy circuits
as STOCHASTIC PURE STATES (2^n amplitudes): at each channel, one Kraus
branch is sampled and applied, and averaging over trajectories converges to
the density-matrix result — E_traj[⟨ψ_k|H|ψ_k⟩] → Tr(Hρ).  That halves the
exponent of the memory/compute cost, so a 20-qubit noisy circuit costs a
20-qubit statevector per trajectory instead of a 40-qubit Choi vector, and
`jax.vmap` over trajectory keys batches the whole ensemble into one device
program (the batching capability measured at ~29x device utilisation gain
for small states — bench `vmap_batch32_16q_f32`).

TPU-first design: branch selection must be traced (no data-dependent Python
control flow), so each channel draws a uniform from a per-trajectory
`jax.random` key and selects its Kraus branch with `lax.switch` over the
statically-known branch set.  The mixing channels (dephasing, depolarising)
have UNITARY Kraus branches with state-independent probabilities — selection
is a constant-probability switch and needs no renormalisation; amplitude
damping is the state-dependent case (jump probability p·P(|1⟩)) and
renormalises the chosen branch, the standard MCWF step.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .autodiff import (GateOp, ParamCircuit, ParamOp, _NOISE_KINDS, _Z_DIAG,
                       _angle, _apply_one, _apply_param_op, _resolve_init,
                       _zero_state)
from .ops import apply as _ap
from .ops import calc as _calc
from .ops import measure as _meas
from . import precision as _prec

__all__ = ["trajectory_state_fn", "trajectory_expectation_fn"]


def _mix3_edges(prob):
    """Cumulative branch edges for a {1-p, p/3, p/3, p/3} Kraus mixture."""
    return jnp.stack([1.0 - prob, 1.0 - 2.0 * prob / 3.0, 1.0 - prob / 3.0])


def _resolve_pure_init(pc, init):
    """Unwrap an init array or statevector Qureg (trajectories are pure)."""
    init, density = _resolve_init(pc, init, False)
    if density:
        raise ValueError("trajectory simulation runs pure states; pass a "
                         "statevector init (noise enters via the channels)")
    return init


def _apply_noise_trajectory(state, op: ParamOp, params, u):
    """One sampled Kraus branch of a channel, chosen by uniform ``u``."""
    prob = _angle(op.param, params)
    t = op.targets

    if op.kind == "dephase":
        # {sqrt(1-p) I, sqrt(p) Z}: unitary branches, fixed probabilities
        branches = [lambda s: s,
                    lambda s: _ap.apply_diagonal(
                        s, jnp.asarray(_Z_DIAG, dtype=s.dtype), (t[0],))]
        edges = jnp.stack([1.0 - prob])
    elif op.kind == "dephase2":
        # {sqrt(1-p) I, sqrt(p/3) Z1, sqrt(p/3) Z2, sqrt(p/3) Z1Z2}
        def z_on(*qs):
            def f(s):
                for q in qs:
                    s = _ap.apply_diagonal(s, jnp.asarray(_Z_DIAG, dtype=s.dtype),
                                           (q,))
                return s
            return f
        branches = [lambda s: s, z_on(t[0]), z_on(t[1]), z_on(t[0], t[1])]
        edges = _mix3_edges(prob)
    elif op.kind == "depolarise":
        # {sqrt(1-p) I, sqrt(p/3) X, sqrt(p/3) Y, sqrt(p/3) Z}
        branches = [
            lambda s: s,
            lambda s: _ap.apply_pauli_x(s, t[0], (), ()),
            lambda s: _ap.apply_pauli_y(s, t[0], (), ()),
            lambda s: _ap.apply_diagonal(s, jnp.asarray(_Z_DIAG, dtype=s.dtype),
                                         (t[0],)),
        ]
        edges = _mix3_edges(prob)
    elif op.kind == "damp":
        # state-dependent jump: P(jump) = p * P(|1>).  no-jump branch applies
        # K0 = diag(1, sqrt(1-p)) / sqrt(p0); jump branch K1 = sqrt(p)|0><1|
        # / sqrt(p1) — the canonical MCWF step
        p1_state = 1.0 - _meas.prob_of_zero(state, t[0]).astype(state.dtype)
        p_jump = prob.astype(state.dtype) * p1_state

        def no_jump(s):
            keep = jnp.sqrt(1.0 - prob.astype(s.dtype))
            d = jnp.stack([jnp.stack([jnp.ones((), s.dtype), keep]),
                           jnp.zeros(2, s.dtype)])
            s = _ap.apply_diagonal(s, d, (t[0],))
            norm = jnp.sqrt(jnp.maximum(1.0 - p_jump, 1e-30))
            return s / norm

        def jump(s):
            # sqrt(p)|0><1|: project onto |1>, flip to |0>, renormalise
            proj = jnp.stack([jnp.stack([jnp.zeros((), s.dtype),
                                         jnp.ones((), s.dtype)]),
                              jnp.zeros(2, s.dtype)])
            s = _ap.apply_diagonal(s, proj, (t[0],))
            s = _ap.apply_pauli_x(s, t[0], (), ())
            norm = jnp.sqrt(jnp.maximum(p_jump / prob.astype(s.dtype), 1e-30))
            return s / norm

        return jax.lax.cond(u < p_jump, jump, no_jump, state)
    else:
        raise ValueError(f"unknown noise kind {op.kind!r}")

    idx = jnp.searchsorted(edges, u.astype(edges.dtype), side="right")
    return jax.lax.switch(idx, branches, state)


def _trajectory_runner(pc: ParamCircuit):
    ops = tuple(pc.ops)
    n = pc.num_qubits
    noise_count = sum(1 for op in ops
                      if isinstance(op, ParamOp) and op.kind in _NOISE_KINDS)

    def run(key, params, state):
        params = jnp.asarray(params)
        if not jnp.issubdtype(params.dtype, jnp.floating):
            params = params.astype(_prec.CONFIG.real_dtype)
        draws = jax.random.uniform(key, (max(noise_count, 1),),
                                   dtype=jnp.float32)
        d = 0
        for op in ops:
            if isinstance(op, GateOp):
                state = _apply_one(state, op)
            elif op.kind in _NOISE_KINDS:
                state = _apply_noise_trajectory(state, op, params, draws[d])
                d += 1
            else:
                state = _apply_param_op(state, op, params, None)
        return state

    return run, n


def _initial(n, init):
    return (_zero_state(n, False, _prec.CONFIG.real_dtype)
            if init is None else init)


def trajectory_state_fn(pc: ParamCircuit, init=None):
    """Jitted ``(key, params) -> state``: ONE stochastic trajectory of the
    noisy circuit as a pure 2^n statevector.  ``jax.vmap`` over split keys
    runs an ensemble in one batched program; averaging outer products (or
    any observable) over trajectories converges to the density-matrix
    result at statevector cost."""
    run, n = _trajectory_runner(pc)
    init = _resolve_pure_init(pc, init)

    @jax.jit
    def fn(key, params):
        return run(key, params, _initial(n, init))

    return fn


def trajectory_expectation_fn(pc: ParamCircuit, hamil, trajectories: int,
                              init=None, batch: int | None = None):
    """Jitted ``(key, params) -> <H>`` averaged over ``trajectories``
    stochastic unravelings — the statevector-cost estimator of the
    density-matrix expectation (standard error ~ 1/sqrt(trajectories)).

    The ensemble runs as ``lax.map`` over chunks of ``batch`` vmapped
    trajectories (default 32, clipped to the total): a single full-width
    vmap batches every intermediate of every trajectory, which at 20 qubits
    x 256 trajectories measured a 56 GiB compile-time footprint — chunking
    caps live memory at one batch while keeping the device filled.
    ``trajectories`` is rounded UP to a whole number of chunks."""
    from .api import _pauli_sum_terms

    terms = _pauli_sum_terms(np.asarray(hamil.pauli_codes))
    cf = jnp.asarray(np.asarray(hamil.term_coeffs, dtype=np.float64))
    run, n = _trajectory_runner(pc)
    init = _resolve_pure_init(pc, init)
    if batch is None:
        batch = min(32, trajectories)
    chunks = -(-trajectories // batch)  # ceil

    @jax.jit
    def fn(key, params):
        def one(k):
            state = run(k, params, _initial(n, init))
            return _calc.expec_pauli_sum_statevec(state, terms, cf)

        keys = jax.random.split(key, chunks * batch)
        keys = keys.reshape(chunks, batch, *keys.shape[1:])
        chunk_means = jax.lax.map(lambda ks: jnp.mean(jax.vmap(one)(ks)), keys)
        return jnp.mean(chunk_means)

    return fn
