"""Regenerate docs/API.md from the live quest_tpu module surface.

Usage: python docs/gen_api.py  (from the repo root)
"""
import inspect
import os

import jax

jax.config.update('jax_platforms', 'cpu')
import quest_tpu as qt  # noqa: E402
import quest_tpu.analysis  # noqa: E402,F401 (dotted-group resolution)

GROUPS = [
    ("Environment", ["createQuESTEnv", "destroyQuESTEnv", "syncQuESTEnv",
                     "syncQuESTSuccess", "reportQuESTEnv", "getEnvironmentString",
                     "seedQuEST", "seedQuESTDefault"]),
    ("Registers", ["createQureg", "createDensityQureg", "createCloneQureg",
                   "destroyQureg", "cloneQureg", "getNumQubits", "getNumAmps",
                   "reportQuregParams", "reportState", "reportStateToScreen",
                   "copyStateToGPU", "copyStateFromGPU"]),
    ("Data structures", ["createComplexMatrixN", "destroyComplexMatrixN",
                         "createPauliHamil", "destroyPauliHamil",
                         "createPauliHamilFromFile", "initPauliHamil",
                         "reportPauliHamil", "createDiagonalOp",
                         "destroyDiagonalOp", "syncDiagonalOp", "initDiagonalOp",
                         "setDiagonalOpElems", "fromComplex", "toComplex",
                         "getStaticComplexMatrixN"]),
    ("State initialisation", ["initBlankState", "initZeroState", "initPlusState",
                              "initClassicalState", "initPureState",
                              "initDebugState", "initStateFromAmps", "setAmps",
                              "setWeightedQureg"]),
    ("Unitaries", ["phaseShift", "controlledPhaseShift", "multiControlledPhaseShift",
                   "controlledPhaseFlip", "multiControlledPhaseFlip", "sGate", "tGate",
                   "unitary", "compactUnitary", "rotateX", "rotateY", "rotateZ",
                   "rotateAroundAxis", "controlledRotateX", "controlledRotateY",
                   "controlledRotateZ", "controlledRotateAroundAxis",
                   "controlledCompactUnitary", "controlledUnitary",
                   "multiControlledUnitary", "multiStateControlledUnitary",
                   "pauliX", "pauliY", "pauliZ", "hadamard", "controlledNot",
                   "controlledPauliY", "swapGate", "sqrtSwapGate", "multiRotateZ",
                   "multiRotatePauli", "twoQubitUnitary", "controlledTwoQubitUnitary",
                   "multiControlledTwoQubitUnitary", "multiQubitUnitary",
                   "controlledMultiQubitUnitary", "multiControlledMultiQubitUnitary"]),
    ("Operators", ["applyMatrix2", "applyMatrix4", "applyMatrixN",
                   "applyMultiControlledMatrixN", "applyPauliSum", "applyPauliHamil",
                   "applyTrotterCircuit", "applyDiagonalOp",
                   "applyQFT", "applyFullQFT"]),
    ("Decoherence", ["mixDephasing", "mixTwoQubitDephasing", "mixDepolarising",
                     "mixTwoQubitDepolarising", "mixDamping", "mixPauli",
                     "mixDensityMatrix", "mixKrausMap", "mixTwoQubitKrausMap",
                     "mixMultiQubitKrausMap"]),
    ("Measurement & calculations", ["measure", "measureWithStats", "collapseToOutcome",
                   "calcProbOfOutcome", "calcProbOfAllOutcomes", "sampleOutcomes",
                   "calcPartialTrace", "calcVonNeumannEntropy",
                   "calcTotalProb", "getAmp", "getRealAmp",
                   "getImagAmp", "getProbAmp", "getDensityAmp", "calcInnerProduct",
                   "calcDensityInnerProduct", "calcPurity", "calcFidelity",
                   "calcHilbertSchmidtDistance", "calcExpecPauliProd",
                   "calcExpecPauliSum", "calcExpecPauliHamil", "calcExpecDiagonalOp"]),
    ("Numeric health (QuEST calcTotalProb parity, snake-case)",
     ["calc_total_prob", "calc_purity", "calc_fidelity"]),
    ("QASM logging", ["startRecordingQASM", "stopRecordingQASM", "clearRecordedQASM",
                      "printRecordedQASM", "writeRecordedQASMToFile"]),
    ("Debug API", ["initStateDebug", "initStateOfSingleQubit",
                   "initStateFromSingleFile", "setDensityAmps", "compareStates",
                   "QuESTPrecision"]),
    ("TPU-native extensions", ["set_precision", "get_precision", "Circuit",
                               "compile_circuit", "apply_circuit", "random_circuit",
                               "qft_circuit"]),
    ("Density noise circuits (Choi-doubled)",
     ["DensityCircuit", "DensityCircuit.damp", "DensityCircuit.depolarise",
      "DensityCircuit.dephase", "DensityCircuit.two_qubit_dephase",
      "DensityCircuit.mix_pauli", "DensityCircuit.kraus",
      "validate_density_operands",
      "analysis.check_density_lowering", "analysis.check_density_plan"]),
    ("Differentiable simulation", ["Param", "ParamCircuit", "build_param_circuit",
                                   "state_fn", "expectation_fn",
                                   "adjoint_gradient_fn"]),
    ("Trajectory simulation", ["trajectory_state_fn",
                               "trajectory_expectation_fn"]),
    ("Serving (quest_tpu.serve)", ["QuESTService", "ServeResult",
                                   "CompileCache", "CacheOptions"]),
    ("Deployment (quest_tpu.deploy)", ["ReplicaPool", "Replica", "Router",
                                       "RouterConfig", "ExecutableStore",
                                       "process_replica",
                                       "broadcast_hot_keys"]),
    ("Gradient serving (quest_tpu.grad)",
     ["GradResult", "training_loop", "sgd", "TrainingResult",
      "QuESTService.submit_gradient", "ReplicaPool.submit_gradient",
      "Router.submit_gradient",
      "grad.adjoint_terms_fn", "grad.hamil_masks",
      "grad.validate_gradient_circuit", "grad.grad_group_signature",
      "CompileCache.grad_entry_for", "CompileCache.grad_single_program",
      "CompileCache.grad_batch_program"]),
    ("Observability (quest_tpu.obs)", ["TraceRecorder", "FlightRecorder",
                                       "Ledger", "enable_tracing",
                                       "disable_tracing", "tracing_enabled",
                                       "chrome_trace", "trace_report",
                                       "global_ledger",
                                       "validate_chrome_trace",
                                       "process_shard", "save_shard",
                                       "load_shard", "merge_shards",
                                       "merge_files",
                                       "SLOConfig", "SLOMonitor"]),
    ("Numeric-health telemetry (quest_tpu.obs.numerics)",
     ["obs.numerics.state_probe_vector", "obs.numerics.densmatr_probe_vector",
      "obs.numerics.ulp_band", "obs.numerics.epoch_pass_probes",
      "obs.numerics.NumericLedger", "obs.numerics.NumericRecord",
      "obs.numerics.global_numeric_ledger",
      "obs.numerics.corruption_selftest"]),
    ("Calibration & runtime counters (quest_tpu.obs)",
     ["CalibrationProfile", "run_calibration", "save_profile",
      "load_profile", "validate_profile", "activate_calibration",
      "deactivate_calibration", "active_profile", "use_profile",
      "RuntimeCounters", "global_counters", "hbm_watermark"]),
    ("Static analysis & concurrency audit (quest_tpu.analysis)",
     ["analysis.analyze_circuit", "analysis.check_abstract_eval",
      "analysis.lint_package", "analysis.lint_paths",
      "analysis.verify_schedule", "analysis.check_equivalence",
      "analysis.audit_concurrency_package",
      "analysis.audit_concurrency_paths",
      "analysis.audit_concurrency_source",
      "analysis.strip_first_lock_scope",
      "analysis.Interleaver", "analysis.run_schedule_fuzz_smoke"]),
]


def main() -> None:
    lines = ["# quest-tpu API reference",
             "",
             "The complete public surface, mirroring QuEST v3.2's nine documentation",
             "groups (ref: QuEST.h) plus the TPU-native extensions. Generated from the",
             "live module (`python docs/gen_api.py`); every function is importable as",
             "`quest_tpu.<name>` and, for the QuEST groups, callable from C through",
             "`native/capi/quest_tpu_c.h` with the reference's exact signatures.", ""]
    count = 0
    for title, names in GROUPS:
        lines.append(f"## {title}")
        lines.append("")
        for n in names:
            fn = qt
            for part in n.split("."):
                fn = getattr(fn, part)
            try:
                sig = str(inspect.signature(fn))
            except (TypeError, ValueError):
                sig = ""
            doc = (inspect.getdoc(fn) or "").split("\n")[0]
            lines.append(f"- **`{n}{sig}`**" + (f" — {doc}" if doc else ""))
            count += 1
        lines.append("")
    lines.append(f"*{count} public functions/classes documented.*")
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)), "API.md")
    with open(out, "w") as f:
        f.write("\n".join(lines) + "\n")
    print(f"wrote {out} with {count} entries")


if __name__ == "__main__":
    main()
