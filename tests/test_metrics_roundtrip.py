"""serve/metrics.py round-trip coverage (ISSUE 7 satellite):

- property test: ``parse_prometheus(export())`` reproduces every counter,
  gauge and histogram bucket EXACTLY for randomized registries (the
  ``_fmt`` encoding — int-form for integral floats, ``repr`` otherwise —
  must round-trip through ``float()`` bit-for-bit);
- the histogram reservoir's FIFO-halving boundary: percentiles beyond
  8192 observations follow the documented drop-the-oldest-half rule
  (recent-biased), while the bucket export and count stay exact over ALL
  observations.
"""

from __future__ import annotations

import math
import random

from quest_tpu.serve.metrics import (_RESERVOIR_CAP, BATCH_BUCKETS,
                                     LATENCY_BUCKETS, Metrics,
                                     parse_prometheus)


def _expected_hist_samples(prefix, name, values, buckets):
    """Cumulative bucket counts / sum / count the exposition format must
    carry for ``values`` observed against ``buckets``."""
    per_bucket = [0] * (len(buckets) + 1)
    total = 0.0
    for v in values:
        total += v            # same accumulation order as _Histogram
        for i, b in enumerate(buckets):
            if v <= b:
                per_bucket[i] += 1
                break
        else:
            per_bucket[-1] += 1
    out = {}
    cum = 0
    for b, c in zip(buckets, per_bucket[:-1]):
        cum += c
        out[(f"{prefix}_{name}_bucket", f'le="{_le(b)}"')] = float(cum)
    out[(f"{prefix}_{name}_bucket", 'le="+Inf"')] = float(cum + per_bucket[-1])
    out[(f"{prefix}_{name}_sum", "")] = total
    out[(f"{prefix}_{name}_count", "")] = float(len(values))
    return out


def _le(b: float) -> str:
    f = float(b)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def test_prometheus_roundtrip_property():
    """Randomized registries: every exported sample parses back to the
    exact recorded value — counters, gauges, and cumulative histogram
    buckets alike."""
    for seed in range(8):
        rng = random.Random(seed)
        m = Metrics()
        counters = {}
        for i in range(rng.randint(1, 5)):
            name = f"ctr{i}_total"
            # mix integral and fractional values: both _fmt forms covered
            v = (float(rng.randint(0, 10**6)) if rng.random() < 0.5
                 else rng.uniform(0, 1e6))
            m.inc(name, v)
            counters[name] = v
        gauges = {}
        for i in range(rng.randint(1, 5)):
            name = f"g{i}"
            v = rng.uniform(-1e3, 1e3) * (10 ** rng.randint(-6, 6))
            m.set_gauge(name, v)
            gauges[name] = v
        hists = {}
        for i, buckets in enumerate((LATENCY_BUCKETS, BATCH_BUCKETS)):
            name = f"h{i}"
            values = [rng.uniform(0, 2 * buckets[-1])
                      for _ in range(rng.randint(1, 200))]
            for v in values:
                m.observe(name, v, buckets=buckets)
            hists[name] = (values, buckets)

        parsed = parse_prometheus(m.to_prometheus())
        for name, v in counters.items():
            assert parsed[f"quest_serve_{name}"][""] == v
        for name, v in gauges.items():
            assert parsed[f"quest_serve_{name}"][""] == v
        for name, (values, buckets) in hists.items():
            expected = _expected_hist_samples("quest_serve", name, values,
                                              buckets)
            for (metric, label), want in expected.items():
                got = parsed[metric][label]
                assert got == want, (metric, label, got, want)


def test_roundtrip_with_extra_gauges_and_obs_splice():
    m = Metrics()
    m.inc("requests_total", 3)
    text = m.to_prometheus(extra_gauges={"cache_hits": 7,
                                         "obs_trace_spans": 12.5})
    parsed = parse_prometheus(text)
    assert parsed["quest_serve_cache_hits"][""] == 7
    assert parsed["quest_serve_obs_trace_spans"][""] == 12.5
    assert parsed["quest_serve_requests_total"][""] == 3


# ---------------------------------------------------------------------------
# labeled series (the deploy layer's per-replica contract)
# ---------------------------------------------------------------------------

def test_labeled_roundtrip_property():
    """Randomized LABELED counters/gauges: every (name, label set) sample
    renders as real Prometheus labels and parses back exactly — one TYPE
    line per family, N labeled samples under it."""
    for seed in range(8):
        rng = random.Random(100 + seed)
        m = Metrics()
        want = {}
        for i in range(rng.randint(1, 4)):
            name = f"ctr{i}_total"
            for r in range(rng.randint(1, 3)):
                labels = {"replica": str(r)}
                if rng.random() < 0.5:
                    labels["reason"] = rng.choice(["burn", "saturation"])
                v = float(rng.randint(1, 10**6))
                m.inc(name, v, labels=labels)
                label_str = ",".join(f'{k}="{labels[k]}"'
                                     for k in sorted(labels))
                want[(f"quest_serve_{name}", label_str)] = v
        # an unlabeled sample coexists with labeled ones in one family
        m.inc("ctr0_total", 2.0)
        want[("quest_serve_ctr0_total", "")] = 2.0
        m.set_gauge("depth", 4.0, labels={"replica": "0"})
        m.set_gauge("depth", 9.0, labels={"replica": "1"})
        want[("quest_serve_depth", 'replica="0"')] = 4.0
        want[("quest_serve_depth", 'replica="1"')] = 9.0
        text = m.to_prometheus()
        assert text.count("# TYPE quest_serve_depth gauge") == 1
        parsed = parse_prometheus(text)
        for (metric, label), v in want.items():
            assert parsed[metric][label] == v, (metric, label)


def test_labeled_view_shares_one_registry():
    m = Metrics()
    r0, r1 = m.labeled(replica="0"), m.labeled(replica="1")
    r0.inc("requests_total", 5)
    r1.inc("requests_total", 7)
    r1.inc("shed_total", labels={"reason": "burn"})
    assert m.counter("requests_total", labels={"replica": "0"}) == 5
    assert m.counter_total("requests_total") == 12
    assert r0.counter("requests_total") == 5       # view reads its own labels
    parsed = parse_prometheus(m.to_prometheus())
    assert parsed["quest_serve_requests_total"] == {
        'replica="0"': 5.0, 'replica="1"': 7.0}
    assert parsed["quest_serve_shed_total"] == {
        'reason="burn",replica="1"': 1.0}
    # histograms pass through unlabeled (deployment-level aggregation)
    r0.observe("lat", 0.5)
    r1.observe("lat", 1.5)
    assert m.as_dict()["histograms"]["lat"]["count"] == 2


def test_label_value_escaping_roundtrips():
    m = Metrics()
    tricky = 'a"b\\c\nd'
    m.set_gauge("g", 1.0, labels={"k": tricky})
    parsed = parse_prometheus(m.to_prometheus())
    assert parsed["quest_serve_g"] == {'k="a\\"b\\\\c\\nd"': 1.0}


def test_bad_label_name_rejected():
    import pytest
    m = Metrics()
    with pytest.raises(ValueError):
        m.inc("x", labels={"bad-name": "v"})
    with pytest.raises(ValueError):
        m.set_gauge("x", 1.0, labels={"9leading": "v"})


def test_reservoir_percentiles_across_fifo_halving_boundary():
    """> 8192 observations: the reservoir drops its oldest half at the cap
    (documented O(1)-amortised recency bias) while the histogram's bucket
    counts, sum and count keep describing EVERY observation."""
    n_obs = 10_000
    assert n_obs > _RESERVOIR_CAP
    m = Metrics()
    values = [float(i) for i in range(n_obs)]
    for v in values:
        m.observe("lat", v, buckets=(2000.0, 6000.0, 9000.0))
    h = m._hists["lat"]

    # the documented retention rule, simulated independently
    expected_window: list[float] = []
    for v in values:
        expected_window.append(v)
        if len(expected_window) > _RESERVOIR_CAP:
            del expected_window[:_RESERVOIR_CAP // 2]
    assert h.raw == expected_window
    assert len(h.raw) < n_obs                      # halving happened
    assert min(h.raw) >= _RESERVOIR_CAP // 2       # oldest half is gone

    xs = sorted(expected_window)
    for q in (50.0, 99.0):
        idx = min(len(xs) - 1, max(0, round(q / 100.0 * (len(xs) - 1))))
        assert h.percentile(q) == xs[idx]
    assert h.percentile(50.0) > n_obs / 2          # recent-biased by design

    # exports still cover all n_obs observations exactly
    summary = m.as_dict()["histograms"]["lat"]
    assert summary["count"] == n_obs
    assert summary["sum"] == math.fsum(values) == sum(values)
    parsed = parse_prometheus(m.to_prometheus())
    assert parsed["quest_serve_lat_count"][""] == n_obs
    assert parsed["quest_serve_lat_bucket"]['le="2000"'] == 2001
    assert parsed["quest_serve_lat_bucket"]['le="+Inf"'] == n_obs
