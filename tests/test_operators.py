"""Non-unitary operators, mirroring the reference's test_operators.cpp
(8 TEST_CASEs)."""

from __future__ import annotations

import numpy as np
import pytest

import quest_tpu as qt
from oracle import (DM_TOL, NUM_QUBITS, SV_TOL, apply_to_sv, assert_dm,
                    assert_sv, dm, full_operator, left_apply_to_dm,
                    pauli_sum_matrix, random_density_matrix,
                    random_statevector, random_unitary, set_dm, set_sv, sv)

N = NUM_QUBITS
DIM = 1 << N


@pytest.fixture
def loaded(env):
    vec = random_statevector(N)
    rho = random_density_matrix(N)
    psi = qt.createQureg(N, env)
    dq = qt.createDensityQureg(N, env)
    set_sv(psi, vec)
    set_dm(dq, rho)
    return psi, dq, vec, rho


def _random_matrix(k):
    d = 1 << k
    return np.random.randn(d, d) + 1j * np.random.randn(d, d)


def test_applyMatrix2(env, loaded):
    psi, dq, vec, rho = loaded
    m = _random_matrix(1)
    for t in (0, 2, N - 1):
        set_sv(psi, vec)
        qt.applyMatrix2(psi, t, m)
        assert_sv(psi, apply_to_sv(vec, N, [t], m))
        # on density matrices the matrix left-multiplies only (no dagger)
        set_dm(dq, rho)
        qt.applyMatrix2(dq, t, m)
        assert_dm(dq, left_apply_to_dm(rho, N, [t], m))


def test_applyMatrix4(env, loaded):
    psi, dq, vec, rho = loaded
    m = _random_matrix(2)
    for t1, t2 in [(0, 1), (3, 1), (2, 4)]:
        set_sv(psi, vec)
        qt.applyMatrix4(psi, t1, t2, m)
        assert_sv(psi, apply_to_sv(vec, N, [t1, t2], m))
        set_dm(dq, rho)
        qt.applyMatrix4(dq, t1, t2, m)
        assert_dm(dq, left_apply_to_dm(rho, N, [t1, t2], m))


def test_applyMatrixN(env, loaded):
    psi, dq, vec, rho = loaded
    shard_amps = DIM // env.num_ranks
    kmax = shard_amps.bit_length() - 1
    for targets in [(0,), (1, 3), (0, 2, 4)]:
        if len(targets) > kmax:
            continue
        m = _random_matrix(len(targets))
        set_sv(psi, vec)
        qt.applyMatrixN(psi, list(targets), len(targets), m)
        assert_sv(psi, apply_to_sv(vec, N, list(targets), m))
        set_dm(dq, rho)
        qt.applyMatrixN(dq, list(targets), len(targets), m)
        assert_dm(dq, left_apply_to_dm(rho, N, list(targets), m))
    with pytest.raises(qt.QuESTError, match="size does not match"):
        qt.applyMatrixN(psi, [0, 1], 2, _random_matrix(1))


def test_applyMultiControlledMatrixN(env, loaded):
    psi, dq, vec, rho = loaded
    for ctrls, targets in [((4,), (0, 1)), ((0, 3), (1,)), ((1,), (2, 0))]:
        m = _random_matrix(len(targets))
        set_sv(psi, vec)
        qt.applyMultiControlledMatrixN(psi, list(ctrls), len(ctrls),
                                       list(targets), len(targets), m)
        assert_sv(psi, apply_to_sv(vec, N, list(targets), m, list(ctrls)))
        set_dm(dq, rho)
        qt.applyMultiControlledMatrixN(dq, list(ctrls), len(ctrls),
                                       list(targets), len(targets), m)
        assert_dm(dq, left_apply_to_dm(rho, N, list(targets), m, list(ctrls)))
    with pytest.raises(qt.QuESTError, match="disjoint"):
        qt.applyMultiControlledMatrixN(psi, [0], 1, [0, 1], 2, _random_matrix(2))


def test_applyPauliSum(env, loaded):
    psi, dq, vec, rho = loaded
    np.random.seed(13)
    num_terms = 3
    codes = np.random.randint(0, 4, size=(num_terms, N))
    coeffs = np.random.randn(num_terms)
    op = pauli_sum_matrix(N, codes, coeffs)
    out = qt.createQureg(N, env)
    qt.applyPauliSum(psi, codes.ravel(), coeffs, num_terms, out)
    assert_sv(out, op @ vec)
    # input state is preserved
    assert_sv(psi, vec)
    # density version: rho -> H rho (left multiplication)
    out_d = qt.createDensityQureg(N, env)
    qt.applyPauliSum(dq, codes.ravel(), coeffs, num_terms, out_d)
    assert_dm(out_d, op @ rho)


def test_applyPauliHamil(env, loaded):
    psi, dq, vec, rho = loaded
    np.random.seed(17)
    num_terms = 4
    codes = np.random.randint(0, 4, size=(num_terms, N))
    coeffs = np.random.randn(num_terms)
    hamil = qt.createPauliHamil(N, num_terms)
    qt.initPauliHamil(hamil, coeffs, codes.ravel())
    op = pauli_sum_matrix(N, codes, coeffs)
    out = qt.createQureg(N, env)
    qt.applyPauliHamil(psi, hamil, out)
    assert_sv(out, op @ vec)


def test_applyTrotterCircuit(env, loaded):
    psi, dq, vec, rho = loaded
    np.random.seed(19)
    num_terms = 3
    codes = np.random.randint(0, 4, size=(num_terms, N))
    coeffs = np.random.randn(num_terms)
    hamil = qt.createPauliHamil(N, num_terms)
    qt.initPauliHamil(hamil, coeffs, codes.ravel())
    h = pauli_sum_matrix(N, codes, coeffs)
    w, v = np.linalg.eigh(h)
    time = 0.1

    def exact(t):
        return (v * np.exp(-1j * w * t)) @ v.conj().T

    # high-rep first-order Trotter converges to the exact evolution
    set_sv(psi, vec)
    qt.applyTrotterCircuit(psi, hamil, time, 1, 30)
    got = sv(psi)
    assert np.abs(got - exact(time) @ vec).max() < 2e-3
    # second order converges faster
    set_sv(psi, vec)
    qt.applyTrotterCircuit(psi, hamil, time, 2, 10)
    got2 = sv(psi)
    assert np.abs(got2 - exact(time) @ vec).max() < 2e-4
    # order must be 1 or even
    with pytest.raises(qt.QuESTError, match="Trotterisation order"):
        qt.applyTrotterCircuit(psi, hamil, time, 3, 1)
    with pytest.raises(qt.QuESTError, match="repetitions"):
        qt.applyTrotterCircuit(psi, hamil, time, 1, 0)


def test_applyDiagonalOp(env, loaded):
    psi, dq, vec, rho = loaded
    op = qt.createDiagonalOp(N, env)
    elems = np.random.randn(DIM) + 1j * np.random.randn(DIM)
    qt.initDiagonalOp(op, np.real(elems).copy(), np.imag(elems).copy())
    qt.applyDiagonalOp(psi, op)
    assert_sv(psi, elems * vec)
    # density: rho -> D rho (left multiplication by the diagonal)
    qt.applyDiagonalOp(dq, op)
    assert_dm(dq, np.diag(elems) @ rho)


# --- QFT API (TPU-native extension; names per QuEST v3.5) -------------------

def _dft(dim: int) -> np.ndarray:
    w = np.exp(2j * np.pi / dim)
    return np.array([[w ** (x * y) for x in range(dim)]
                     for y in range(dim)]) / np.sqrt(dim)


def test_apply_full_qft_statevector(env):
    vec = random_statevector(N)
    psi = qt.createQureg(N, env)
    set_sv(psi, vec)
    qt.applyFullQFT(psi)
    np.testing.assert_allclose(sv(psi), _dft(1 << N) @ vec, atol=SV_TOL)


@pytest.mark.parametrize("qubits", [[2], [0, 3], [4, 1, 2]])
def test_apply_qft_subset(env, qubits):
    """QFT on a sub-register equals the dense DFT embedded on those wires
    (qubits[0] least significant)."""
    vec = random_statevector(N)
    psi = qt.createQureg(N, env)
    set_sv(psi, vec)
    qt.applyQFT(psi, qubits)
    op = full_operator(N, qubits, _dft(1 << len(qubits)))
    np.testing.assert_allclose(sv(psi), op @ vec, atol=SV_TOL)


def test_apply_qft_density(env):
    rho = random_density_matrix(3)
    rho_q = qt.createDensityQureg(3, env)
    set_dm(rho_q, rho)
    qt.applyQFT(rho_q, [0, 1, 2])
    f = _dft(8)
    np.testing.assert_allclose(dm(rho_q), f @ rho @ f.conj().T, atol=DM_TOL)
    assert qt.calcTotalProb(rho_q) == pytest.approx(1.0, abs=DM_TOL)



def test_apply_qft_validation(env_local):
    psi = qt.createQureg(3, env_local)
    with pytest.raises(qt.QuESTError):
        qt.applyQFT(psi, [0, 3])
    with pytest.raises(qt.QuESTError):
        qt.applyQFT(psi, [1, 1])
