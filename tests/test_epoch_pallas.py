"""Pallas epoch executor (ops/epoch_pallas.py) — interpret mode on CPU; the
same kernel code runs Mosaic-compiled on a chip (the pallas_layer /
qft_inplace engines it generalizes are chip-validated at n=20..30).

Covers: random 1q/2q/diagonal windows vs the XLA gate engine, the deferred
qubit map carried across 2+ epoch segments, degenerate single-op windows
(bit-exact f32 for diagonal kinds), the QFT HBM-pass-count regression
(engine="auto" must NOT silently fall back to the per-gate XLA path), the
planner's engine selection, and the engine-tagged compile-cache keys.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax.numpy as jnp

from quest_tpu.circuit import (Circuit, compile_circuit, qft_circuit,
                               random_circuit)
from quest_tpu.ops import epoch_pallas as ep
from quest_tpu.parallel import planner
from quest_tpu.validation import QuESTError

N = 17  # the engine floor: one (128, 8, 128) block


def _haar(rng, k=1):
    d = 1 << k
    g = rng.normal(size=(d, d)) + 1j * rng.normal(size=(d, d))
    u, r = np.linalg.qr(g)
    return u * (np.diag(r) / np.abs(np.diag(r)))


def _rand_state(n, seed=0):
    rng = np.random.default_rng(seed)
    st = rng.normal(size=(2, 1 << n)).astype(np.float32)
    st /= np.sqrt((st ** 2).sum())
    return jnp.asarray(st)


def _assert_engines_agree(c, seed=0, atol=5e-6):
    st = _rand_state(c.num_qubits, seed)
    want = np.asarray(compile_circuit(c, engine="xla")(st))
    got = np.asarray(compile_circuit(c, engine="pallas")(st))
    np.testing.assert_allclose(got, want, atol=atol)
    return got, want


# ---------------------------------------------------------------------------
# property: random mixed windows vs the XLA engine
# ---------------------------------------------------------------------------

def _random_window(n, seed, length=14):
    """A window drawing from every supported class: 1q dense anywhere,
    same-group 2q dense, controlled 1q dense, diagonals (cz / phase / rz),
    wide mrz, and swaps (which must cost zero passes)."""
    rng = np.random.default_rng(seed)
    c = Circuit(n)
    for _ in range(length):
        kind = rng.integers(0, 8)
        if kind == 0:
            c.unitary(int(rng.integers(0, n)), _haar(rng))
        elif kind == 1:  # controlled 1q dense, block target
            t = int(rng.integers(0, 10))
            ctl = int(rng.choice([q for q in range(n) if q != t]))
            c.multi_qubit_unitary((t,), _haar(rng), controls=(ctl,))
        elif kind == 2:  # 2q dense inside one axis group
            lo, hi = [(0, 7), (7, 10), (10, 17)][rng.integers(0, 3)]
            a, b = rng.choice(np.arange(lo, hi), size=2, replace=False)
            c.multi_qubit_unitary((int(a), int(b)), _haar(rng, 2))
        elif kind == 3:
            a, b = rng.choice(n, size=2, replace=False)
            c.cz(int(a), int(b))
        elif kind == 4:
            t = int(rng.integers(0, n))
            ctl = int(rng.choice([q for q in range(n) if q != t]))
            c.phase_shift(t, float(rng.uniform(-np.pi, np.pi)),
                          controls=(ctl,) if rng.integers(0, 2) else ())
        elif kind == 5:
            c.rz(int(rng.integers(0, n)), float(rng.uniform(-np.pi, np.pi)))
        elif kind == 6:
            ts = rng.choice(n, size=12, replace=False)
            c.multi_rotate_z(tuple(int(t) for t in ts),
                             float(rng.uniform(-np.pi, np.pi)))
        else:
            a, b = rng.choice(n, size=2, replace=False)
            c.swap(int(a), int(b))
    return c


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_random_window_matches_xla(seed):
    c = _random_window(N, seed)
    _assert_engines_agree(c, seed)


def test_swaps_cost_zero_passes():
    c = Circuit(N)
    for q in range(N // 2):
        c.swap(q, N - 1 - q)
    plan = ep.plan_circuit(c.key(), N)
    assert plan.hbm_passes == 0
    assert plan.deferred_ops == N // 2
    assert plan.residual_perm != tuple(range(N))
    _assert_engines_agree(c)


def test_high_qubit_fiber_ops():
    """Dense 1q (incl. x/y kinds) on qubits >= 17: the fiber pack path,
    with consecutive same-group ops merged into one pass."""
    n = 19
    rng = np.random.default_rng(7)
    c = Circuit(n)
    c.unitary(17, _haar(rng))
    c.unitary(18, _haar(rng))
    c.h(17)
    c.y(18)
    c.x(17)
    plan = ep.plan_circuit(c.key(), n)
    assert plan.hbm_passes == 1  # one merged pack for the whole run
    _assert_engines_agree(c)


def test_control_across_block_boundary():
    """Controls above the block range select off the global amplitude
    index reconstructed from program_id."""
    n = 18
    rng = np.random.default_rng(3)
    c = Circuit(n)
    c.multi_qubit_unitary((2,), _haar(rng), controls=(17,))
    c.phase_shift(4, 0.7, controls=(17,))
    plan = ep.plan_circuit(c.key(), n)
    assert plan.xla_ops == 0
    _assert_engines_agree(c)


# ---------------------------------------------------------------------------
# deferred qubit map across 2+ epochs
# ---------------------------------------------------------------------------

def test_deferred_map_carries_across_epochs():
    """Swaps before, between and after two Pallas segments split by an
    unsupported op (cross-group 2q dense -> XLA fallback window): the
    residual permutation must be carried through ALL of it and reconciled
    once at the end."""
    rng = np.random.default_rng(11)
    c = Circuit(N)
    c.swap(0, 12)
    c.unitary(0, _haar(rng))          # physically lands on wire 12
    c.cz(0, 5)
    c.multi_qubit_unitary((5, 14), _haar(rng, 2))   # cross-group: XLA
    c.swap(3, 16)
    c.unitary(3, _haar(rng))
    c.t(16)
    c.swap(1, 2)
    plan = ep.plan_circuit(c.key(), N)
    engines = [s.engine for s in plan.segments]
    assert engines == ["pallas", "xla", "pallas"]
    assert plan.deferred_ops == 3
    assert plan.residual_perm != tuple(range(N))
    _assert_engines_agree(c)


def test_pure_permutation_circuit():
    c = Circuit(N)
    c.swap(0, 16)
    c.swap(3, 7)
    c.swap(0, 3)
    _assert_engines_agree(c, atol=0.0)  # pure data movement: exact


# ---------------------------------------------------------------------------
# degenerate single-op windows
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("build", [
    lambda c: c.cz(2, 13),
    lambda c: c.s(9),
    lambda c: c.rz(16, 0.37),
])
def test_single_diagonal_op_bit_exact(build):
    """A one-op diagonal window must be BIT-exact vs the XLA engine: both
    paths multiply each amplitude by the same f32-rounded factor with the
    same complex-product expression."""
    c = Circuit(N)
    build(c)
    got, want = _assert_engines_agree(c, atol=0.0)
    np.testing.assert_array_equal(got, want)


def test_single_mrz_window():
    """mrz phases precompute host-side in f64 (the angle-precision
    contract) — one rounding step apart from the XLA engine's in-device
    f64 trig, so the window agrees to f32 ulp, not bitwise."""
    c = Circuit(N)
    c.multi_rotate_z(tuple(range(12)), 1.1)
    _assert_engines_agree(c, atol=3e-7)


@pytest.mark.parametrize("q", [0, 5, 8, 12, 16])
def test_single_dense_op(q):
    rng = np.random.default_rng(100 + q)
    c = Circuit(N)
    c.unitary(q, _haar(rng))
    plan = ep.plan_circuit(c.key(), N)
    assert plan.hbm_passes == 1
    _assert_engines_agree(c, atol=5e-7)


# ---------------------------------------------------------------------------
# QFT pass-count regression: auto must not silently fall back
# ---------------------------------------------------------------------------

def test_qft_plan_reproduces_inplace_pass_count():
    """The general epoch lowering of the QFT must match (here: beat by one,
    the q=17 ladder fusing into the tail pass) the hand-written
    qft_inplace engine's ~2(n-17)+1 HBM passes, with the trailing swap
    network absorbed into the deferred map at zero passes."""
    for n in (22, 28):
        plan = ep.plan_circuit(qft_circuit(n).key(), n)
        assert plan.xla_ops == 0, "silent per-gate fallback"
        assert plan.hbm_passes <= 2 * (n - 17) + 1
        assert plan.hbm_passes == 2 * (n - 17)
        assert plan.deferred_ops == n // 2          # the swap network
        assert plan.residual_perm != tuple(range(n))


def test_compile_circuit_auto_selects_pallas_for_qft(monkeypatch):
    """compile_circuit(engine='auto') — the default path — must pick the
    epoch executor for the QFT factory on TPU-class specs (the backend
    guard lifted via QUEST_TPU_EPOCH_ENGINE=1, since tests run on CPU) and
    carry the full fused plan, not a per-gate fallback."""
    monkeypatch.setenv("QUEST_TPU_EPOCH_ENGINE", "1")
    run = compile_circuit(qft_circuit(28))
    assert run.engine == "pallas"
    assert run.engine_plan.hbm_passes <= 2 * (28 - 17) + 1
    assert run.engine_plan.xla_ops == 0
    run = compile_circuit(random_circuit(24, 4))
    assert run.engine == "pallas"


# ---------------------------------------------------------------------------
# engine selection
# ---------------------------------------------------------------------------

def test_select_engine_rules():
    qft = qft_circuit(28)
    # TPU-class spec: pallas for the factories
    assert planner.select_engine(qft, 1, backend="tpu")["engine"] == "pallas"
    assert planner.select_engine(random_circuit(24, 4), 1,
                                 backend="tpu")["engine"] == "pallas"
    # off-TPU, auto stays on the XLA engine (interpret mode is not a perf
    # engine); forcing still works
    assert planner.select_engine(qft, 1, backend="cpu")["engine"] == "xla"
    assert planner.select_engine(qft, 1, backend="cpu",
                                 requested="pallas")["engine"] == "pallas"
    # outside the envelope: f64, small registers, meshes
    assert planner.select_engine(qft, 1, precision=2,
                                 backend="tpu")["engine"] == "xla"
    assert planner.select_engine(qft_circuit(12), 1,
                                 backend="tpu")["engine"] == "xla"
    assert planner.select_engine(qft, 8, backend="tpu")["engine"] == "xla"
    with pytest.raises(QuESTError):
        planner.select_engine(qft, 8, requested="pallas")
    with pytest.raises(QuESTError):
        planner.select_engine(qft_circuit(12), 1, requested="pallas")
    with pytest.raises(ValueError):
        planner.select_engine(qft, 1, requested="mosaic")


def test_engine_summary_per_epoch():
    c = Circuit(N)
    rng = np.random.default_rng(5)
    c.h(0)
    c.multi_qubit_unitary((3, 12), _haar(rng, 2))   # cross-group: XLA epoch
    c.cz(1, 2)
    s = planner.engine_summary(c, 1, requested="pallas")
    assert s["engine"] == "pallas"
    assert [e["engine"] for e in s["epochs"]] == ["pallas", "xla", "pallas"]
    # infeasible forced engine is REPORTED, not raised
    s = planner.engine_summary(c, 8, requested="pallas")
    assert s["engine"] == "xla"


def test_f64_state_falls_back_at_call_time():
    c = Circuit(N)
    c.h(0)
    run = compile_circuit(c, engine="pallas")
    st = _rand_state(N).astype(jnp.float64)
    want = np.asarray(compile_circuit(c, engine="xla")(st))
    np.testing.assert_allclose(np.asarray(run(st)), want, atol=1e-12)


# ---------------------------------------------------------------------------
# engine-tagged program identity (serve compile cache / Circuit.key)
# ---------------------------------------------------------------------------

def test_circuit_key_records_engine():
    c = qft_circuit(N)
    assert c.key(engine="xla") == c.key()        # backward compatible
    assert c.key(engine=None) == c.key()
    assert c.key(engine="pallas") != c.key()
    assert c.key(engine="pallas")[0] == ("engine", "pallas")


def test_cache_class_key_separates_engines():
    """A class compiled under engine='xla' must never be served to a
    request planned for engine='pallas': distinct entries, truthful
    hit/miss counters, and distinct executables."""
    from quest_tpu.serve.cache import CacheOptions, CompileCache
    cache = CompileCache(max_bytes=1 << 30)
    c = Circuit(N)
    c.h(0)
    ops = c.key()
    e_xla = cache.entry_for(ops, options=CacheOptions())
    e_pal = cache.entry_for(ops, options=CacheOptions(engine="pallas"))
    assert e_xla is not e_pal
    assert cache.stats["misses"] == 2 and cache.stats["hits"] == 0
    assert e_pal.skeleton is None      # opaque: payloads live in kernels
    assert cache.entry_for(ops, options=CacheOptions(engine="pallas")) is e_pal
    assert cache.stats["hits"] == 1

    st = _rand_state(N)
    want = np.asarray(compile_circuit(c, engine="xla")(st))
    got = np.asarray(
        cache.epoch_program(e_pal, ops).call(st))
    np.testing.assert_allclose(got, want, atol=5e-7)


def test_donating_runner_engine_dimension():
    from quest_tpu.serve.cache import CompileCache
    cache = CompileCache(max_bytes=1 << 30)
    c = Circuit(N)
    c.s(4)
    run_x = cache.donating_runner(c.key())
    run_p = cache.donating_runner(c.key(), engine="pallas")
    a = np.asarray(run_x(_rand_state(N, 1)))
    b = np.asarray(run_p(_rand_state(N, 1)))
    np.testing.assert_array_equal(a, b)   # diagonal window: bit-exact


# ---------------------------------------------------------------------------
# envelope validation
# ---------------------------------------------------------------------------

def test_envelope_rejections():
    with pytest.raises(ValueError):
        ep.plan_circuit(qft_circuit(12).key(), 12)
    st = jnp.zeros((2, 1 << 12), jnp.float32)
    with pytest.raises(ValueError):
        ep.run_ops_planes(st, qft_circuit(12).key())
    assert not ep.epoch_supported(12)
    assert not ep.epoch_supported(31)
    assert not ep.epoch_supported(20, precision=2)
    assert ep.epoch_supported(20)
