"""Pallas epoch executor (ops/epoch_pallas.py) — interpret mode on CPU; the
same kernel code runs Mosaic-compiled on a chip (the pallas_layer /
qft_inplace engines it generalizes are chip-validated at n=20..30).

Covers: random 1q/2q/diagonal windows vs the XLA gate engine, the WIDENED
envelope's four lowerings (cross-group 2q dense via the odd-bit block
decomposition, controlled dense on high qubits through the staged pack
predicate, the 10-16 qubit degenerate single-block geometry, and plane-pair
donation end-to-end), the deferred qubit map carried across 2+ epoch
segments, degenerate single-op windows (bit-exact f32 for diagonal kinds),
the QFT HBM-pass-count regression (engine="auto" must NOT silently fall
back to the per-gate XLA path), the planner's engine selection with the
remaining-cases-only rejection messages, an adversarial corrupted
cross-group decomposition caught by check_epoch_plan, and the
engine-tagged compile-cache keys.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax.numpy as jnp

from quest_tpu.circuit import (Circuit, compile_circuit, qft_circuit,
                               random_circuit)
from quest_tpu.ops import epoch_pallas as ep
from quest_tpu.parallel import planner
from quest_tpu.validation import QuESTError

N = 17  # the full block-walk floor: one (128, 8, 128) block per grid step


def _haar(rng, k=1):
    d = 1 << k
    g = rng.normal(size=(d, d)) + 1j * rng.normal(size=(d, d))
    u, r = np.linalg.qr(g)
    return u * (np.diag(r) / np.abs(np.diag(r)))


def _rand_state(n, seed=0):
    rng = np.random.default_rng(seed)
    st = rng.normal(size=(2, 1 << n)).astype(np.float32)
    st /= np.sqrt((st ** 2).sum())
    return jnp.asarray(st)


def _assert_engines_agree(c, seed=0, atol=5e-6):
    st = _rand_state(c.num_qubits, seed)
    want = np.asarray(compile_circuit(c, engine="xla")(st))
    got = np.asarray(compile_circuit(c, engine="pallas")(st))
    np.testing.assert_allclose(got, want, atol=atol)
    return got, want


# ---------------------------------------------------------------------------
# property: random mixed windows vs the XLA engine
# ---------------------------------------------------------------------------

def _random_window(n, seed, length=14):
    """A window drawing from every supported class: 1q dense anywhere,
    same-group 2q dense, controlled 1q dense, diagonals (cz / phase / rz),
    wide mrz, and swaps (which must cost zero passes)."""
    rng = np.random.default_rng(seed)
    c = Circuit(n)
    for _ in range(length):
        kind = rng.integers(0, 8)
        if kind == 0:
            c.unitary(int(rng.integers(0, n)), _haar(rng))
        elif kind == 1:  # controlled 1q dense, block target
            t = int(rng.integers(0, 10))
            ctl = int(rng.choice([q for q in range(n) if q != t]))
            c.multi_qubit_unitary((t,), _haar(rng), controls=(ctl,))
        elif kind == 2:  # 2q dense inside one axis group
            lo, hi = [(0, 7), (7, 10), (10, 17)][rng.integers(0, 3)]
            a, b = rng.choice(np.arange(lo, hi), size=2, replace=False)
            c.multi_qubit_unitary((int(a), int(b)), _haar(rng, 2))
        elif kind == 3:
            a, b = rng.choice(n, size=2, replace=False)
            c.cz(int(a), int(b))
        elif kind == 4:
            t = int(rng.integers(0, n))
            ctl = int(rng.choice([q for q in range(n) if q != t]))
            c.phase_shift(t, float(rng.uniform(-np.pi, np.pi)),
                          controls=(ctl,) if rng.integers(0, 2) else ())
        elif kind == 5:
            c.rz(int(rng.integers(0, n)), float(rng.uniform(-np.pi, np.pi)))
        elif kind == 6:
            ts = rng.choice(n, size=12, replace=False)
            c.multi_rotate_z(tuple(int(t) for t in ts),
                             float(rng.uniform(-np.pi, np.pi)))
        else:
            a, b = rng.choice(n, size=2, replace=False)
            c.swap(int(a), int(b))
    return c


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_random_window_matches_xla(seed):
    c = _random_window(N, seed)
    _assert_engines_agree(c, seed)


def test_swaps_cost_zero_passes():
    c = Circuit(N)
    for q in range(N // 2):
        c.swap(q, N - 1 - q)
    plan = ep.plan_circuit(c.key(), N)
    assert plan.hbm_passes == 0
    assert plan.deferred_ops == N // 2
    assert plan.residual_perm != tuple(range(N))
    _assert_engines_agree(c)


def test_high_qubit_fiber_ops():
    """Dense 1q (incl. x/y kinds) on qubits >= 17: the fiber pack path,
    with consecutive same-group ops merged into one pass."""
    n = 19
    rng = np.random.default_rng(7)
    c = Circuit(n)
    c.unitary(17, _haar(rng))
    c.unitary(18, _haar(rng))
    c.h(17)
    c.y(18)
    c.x(17)
    plan = ep.plan_circuit(c.key(), n)
    assert plan.hbm_passes == 1  # one merged pack for the whole run
    _assert_engines_agree(c)


def test_control_across_block_boundary():
    """Controls above the block range select off the global amplitude
    index reconstructed from program_id."""
    n = 18
    rng = np.random.default_rng(3)
    c = Circuit(n)
    c.multi_qubit_unitary((2,), _haar(rng), controls=(17,))
    c.phase_shift(4, 0.7, controls=(17,))
    plan = ep.plan_circuit(c.key(), n)
    assert plan.xla_ops == 0
    _assert_engines_agree(c)


# ---------------------------------------------------------------------------
# widened envelope 1: cross-group 2q dense (odd-bit block decomposition)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("pair", [(3, 8), (2, 14), (8, 12)])
def test_cross_group_2q_dense_minor_minor(pair):
    """A 2q dense gate straddling two MINOR axis groups (lane-sub,
    lane-fiber, sub-fiber) decomposes into single-target controlled dense
    factors that fuse into the SAME block pass: zero XLA fallback, zero
    extra passes."""
    rng = np.random.default_rng(sum(pair))
    c = Circuit(N)
    c.h(0)
    c.multi_qubit_unitary(pair, _haar(rng, 2))
    c.cz(1, 2)
    plan = ep.plan_circuit(c.key(), N)
    assert plan.xla_ops == 0
    assert plan.hbm_passes == 1      # the whole window is one block pass
    _assert_engines_agree(c, atol=5e-6)


def test_cross_group_2q_dense_minor_high():
    """Targets straddling a minor group and the high range: the
    block-diagonal factors land in the minor stream, the middle Givens
    rotations in the pack stream — still zero XLA fallback."""
    n = 19
    rng = np.random.default_rng(21)
    c = Circuit(n)
    c.multi_qubit_unitary((5, 18), _haar(rng, 2))
    plan = ep.plan_circuit(c.key(), n)
    assert plan.xla_ops == 0
    assert plan.pack_passes >= 1
    _assert_engines_agree(c, atol=5e-6)


def test_cross_group_2q_dense_reversed_target_order():
    """targets=(hi, lo): payload index bit 0 is the odd bit — the
    decomposition must reorder through the bit-swap conjugation."""
    rng = np.random.default_rng(31)
    c = Circuit(N)
    c.multi_qubit_unitary((14, 3), _haar(rng, 2))
    plan = ep.plan_circuit(c.key(), N)
    assert plan.xla_ops == 0
    _assert_engines_agree(c, atol=5e-6)


def test_cross_group_2q_dense_controlled():
    """A CONTROLLED cross-group 2q dense: the original controls ride on
    every factor alongside the decomposition's own odd-bit control."""
    rng = np.random.default_rng(41)
    c = Circuit(N)
    c.multi_qubit_unitary((4, 12), _haar(rng, 2), controls=(9,),
                          control_states=(0,))
    plan = ep.plan_circuit(c.key(), N)
    assert plan.xla_ops == 0
    _assert_engines_agree(c, atol=5e-6)


def test_cross_group_2q_degenerate_payloads():
    """Block-diagonal, anti-diagonal and singular-CS payloads (a dense
    SWAP matrix has c = (1, 0): the degenerate-column completion path):
    the shortcut and fill-in routes must all reconstruct exactly."""
    swap_mat = np.array([[1, 0, 0, 0], [0, 0, 1, 0],
                         [0, 1, 0, 0], [0, 0, 0, 1]], complex)
    rng = np.random.default_rng(51)
    u1, u2 = _haar(rng), _haar(rng)
    zero = np.zeros((2, 2))
    blockdiag = np.block([[u1, zero], [zero, u2]])
    antidiag = np.block([[zero, u1], [u2, zero]])
    for mat in (swap_mat, blockdiag, antidiag):
        c = Circuit(N)
        c.multi_qubit_unitary((5, 14), mat)
        plan = ep.plan_circuit(c.key(), N)
        assert plan.xla_ops == 0, mat
        _assert_engines_agree(c, atol=5e-6)


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_cross_group_random_mixed_window(seed):
    """Randomized mixed windows with cross-group 2q dense gates
    interleaved among every other supported class (the satellite coverage
    case): zero XLA fallback, engines agree."""
    rng = np.random.default_rng(1000 + seed)
    c = _random_window(N, seed, length=10)
    groups = [(0, 7), (7, 10), (10, 17)]
    for _ in range(3):
        ga, gb = rng.choice(3, size=2, replace=False)
        a = int(rng.integers(*groups[ga]))
        b = int(rng.integers(*groups[gb]))
        c.multi_qubit_unitary((a, b), _haar(rng, 2))
    plan = ep.plan_circuit(c.key(), N)
    assert plan.xla_ops == 0
    _assert_engines_agree(c, seed, atol=1e-5)


# ---------------------------------------------------------------------------
# widened envelope 2: controlled dense on high qubits (pack predicate)
# ---------------------------------------------------------------------------

def test_controlled_dense_high_qubits():
    """Controlled dense ops with targets >= 17 run through the staged pack
    engine — the control predicate computed off the reconstructed global
    amplitude index — instead of forcing an XLA segment."""
    n = 19
    rng = np.random.default_rng(61)
    c = Circuit(n)
    c.multi_qubit_unitary((18,), _haar(rng), controls=(2,))
    c.multi_qubit_unitary((17,), _haar(rng), controls=(18,),
                          control_states=(0,))
    c.multi_qubit_unitary((18,), _haar(rng), controls=(3, 17))
    plan = ep.plan_circuit(c.key(), n)
    assert plan.xla_ops == 0
    assert plan.pack_passes >= 1
    _assert_engines_agree(c, atol=5e-6)


def test_controlled_dense_high_identical_controls_compose():
    """Adjacent dense stages with IDENTICAL control predicates compose
    host-side into one pack; differing predicates stay separate stages in
    the same pass."""
    n = 18
    rng = np.random.default_rng(71)
    c = Circuit(n)
    c.multi_qubit_unitary((17,), _haar(rng), controls=(4,))
    c.multi_qubit_unitary((17,), _haar(rng), controls=(4,))
    c.multi_qubit_unitary((17,), _haar(rng), controls=(5,))
    plan = ep.plan_circuit(c.key(), n)
    assert plan.pack_passes == 1
    assert plan.xla_ops == 0
    [seg] = plan.segments
    [pp] = seg.passes
    dense_stages = [s for s in pp.specs if s[0] == "dense"]
    assert len(dense_stages) == 2    # first two composed, third separate
    _assert_engines_agree(c, atol=5e-6)


# ---------------------------------------------------------------------------
# widened envelope 3: 10-16 qubit degenerate single-block geometry
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("n", [10, 12, 16])
def test_small_n_random_window_matches_xla(n):
    """Registers below the full block-walk floor run the degenerate
    geometry: the whole state is one VMEM tile, every supported op is
    block-local, and mixed windows lower to ONE fused pass."""
    rng = np.random.default_rng(n)
    c = Circuit(n)
    for _ in range(10):
        kind = rng.integers(0, 5)
        if kind == 0:
            c.unitary(int(rng.integers(0, n)), _haar(rng))
        elif kind == 1:
            t = int(rng.integers(0, n))
            ctl = int(rng.choice([q for q in range(n) if q != t]))
            c.multi_qubit_unitary((t,), _haar(rng), controls=(ctl,))
        elif kind == 2:
            a, b = rng.choice(n, size=2, replace=False)
            c.cz(int(a), int(b))
        elif kind == 3:
            c.rz(int(rng.integers(0, n)), float(rng.uniform(-np.pi, np.pi)))
        else:
            a, b = rng.choice(n, size=2, replace=False)
            c.swap(int(a), int(b))
    plan = ep.plan_circuit(c.key(), n)
    assert plan.xla_ops == 0
    assert plan.hbm_passes <= 1
    assert plan.summary()["degenerate_geometry"]
    _assert_engines_agree(c, seed=n, atol=5e-6)


def test_small_n_cross_group_window():
    """Cross-group 2q dense in the degenerate geometry (the axis groups
    still partition the minor bits; the fiber axis is just narrower)."""
    rng = np.random.default_rng(81)
    c = Circuit(12)
    c.h(0)
    c.multi_qubit_unitary((3, 11), _haar(rng, 2))
    c.cz(2, 8)
    plan = ep.plan_circuit(c.key(), 12)
    assert plan.xla_ops == 0
    assert plan.hbm_passes == 1
    _assert_engines_agree(c, atol=5e-6)


def test_small_n_diagonal_bit_exact():
    """Diagonal windows stay BIT-exact in the degenerate geometry too."""
    c = Circuit(12)
    c.cz(2, 11)
    c.s(9)
    c.rz(0, 0.37)
    got, want = _assert_engines_agree(c, atol=0.0)
    np.testing.assert_array_equal(got, want)


def test_small_n_qft_one_pass():
    for n in (10, 16):
        plan = ep.plan_circuit(qft_circuit(n).key(), n)
        assert plan.xla_ops == 0
        assert plan.hbm_passes == 1
        assert plan.deferred_ops == n // 2


def test_vqe16_resolves_to_pallas_on_tpu_spec():
    """The 16q VQE ansatz — the circuit the old envelope rejected with the
    'n >= 17 floor' note — must now resolve to the Pallas engine on
    TPU-class specs as ONE fused pass; registers below the 10-qubit floor
    keep the old XLA behaviour."""
    from quest_tpu.serve.selftest import vqe_ansatz
    c = vqe_ansatz(16, 2, seed=0)
    choice = planner.select_engine(c, 1, backend="tpu")
    assert choice["engine"] == "pallas"
    assert choice["plan"].hbm_passes == 1
    assert choice["plan"].summary()["degenerate_geometry"]
    small = vqe_ansatz(8, 2, seed=0)
    assert planner.select_engine(small, 1, backend="tpu")["engine"] == "xla"
    assert not ep.epoch_supported(8)


def test_random24_plan_beats_committed_r05_pass_count():
    """Acceptance: the random24 auto-engine row's plan pass count must
    strictly decrease vs the committed r05 figure (9 passes, PR 6's
    narrow-envelope lowering — cross-group 2q gates split epochs then)."""
    plan = ep.plan_circuit(random_circuit(24, 4, seed=11).key(), 24)
    assert plan.xla_ops == 0
    assert plan.hbm_passes < 9
    assert plan.hbm_passes == 6


# ---------------------------------------------------------------------------
# widened envelope 4: plane-pair donation end-to-end
# ---------------------------------------------------------------------------

def test_plane_pair_program_matches_stacked():
    """jit_program_planes (the donated (re, im) -> (re, im) program) must
    agree with the (2, N) compat entry on every lowering, including a
    nontrivial residual permutation reconciled PER PLANE."""
    rng = np.random.default_rng(91)
    c = _random_window(N, 3, length=8)
    c.multi_qubit_unitary((5, 14), _haar(rng, 2))
    c.swap(0, 16)
    st = _rand_state(N, 5)
    want = np.asarray(ep.jit_program(c.key())(st))
    re, im = jnp.array(st[0]), jnp.array(st[1])
    out_re, out_im = ep.jit_program_planes(c.key(), donate=True)(re, im)
    np.testing.assert_allclose(np.asarray(out_re), want[0], atol=1e-6)
    np.testing.assert_allclose(np.asarray(out_im), want[1], atol=1e-6)


def test_plane_pair_program_rejects_non_f32():
    """The planes entry has no XLA fallback (plane callers are f32 by
    construction) — non-f32 planes must get the clean envelope error, not
    an internal Pallas dtype failure."""
    c = Circuit(12)
    c.h(0)
    call = ep.jit_program_planes(c.key(), donate=False)
    re = jnp.zeros(1 << 12, jnp.float64)
    with pytest.raises(ValueError, match="f32-only"):
        call(re, re)


def test_reconcile_perm_planes_matches_stacked():
    """The plane-pair residual reconciliation is the same bit permutation
    as the stacked reconcile_perm: EXACT equality on both planes."""
    from quest_tpu.ops.apply import reconcile_perm, reconcile_perm_planes
    rng = np.random.default_rng(13)
    n = 12
    perm = tuple(rng.permutation(n).tolist())
    st = _rand_state(n, 7)
    want = np.asarray(reconcile_perm(st, perm))
    re, im = reconcile_perm_planes(st[0], st[1], perm)
    np.testing.assert_array_equal(np.asarray(re), want[0])
    np.testing.assert_array_equal(np.asarray(im), want[1])


def test_compile_circuit_exposes_plane_runner(monkeypatch):
    """compile_circuit on the epoch engine carries run.planes — the
    donated plane-pair entry — and run.planes is None on the XLA engine."""
    c = qft_circuit(N)
    run_x = compile_circuit(c, engine="xla")
    assert run_x.planes is None
    run_p = compile_circuit(c, engine="pallas")
    assert run_p.planes is not None
    st = _rand_state(N, 9)
    want = np.asarray(run_p(st))
    re, im = run_p.planes(jnp.array(st[0]), jnp.array(st[1]))
    np.testing.assert_allclose(np.asarray(re), want[0], atol=1e-6)
    np.testing.assert_allclose(np.asarray(im), want[1], atol=1e-6)


def test_audit_epoch_donation_aliases_planes():
    """The donated plane-pair program must compile with input_output_alias
    entries on THIS backend (the machine check behind the 'truly in place'
    claim) and the audit must report the plan's pass counts."""
    from quest_tpu.analysis.jaxpr_audit import audit_epoch_donation
    c = qft_circuit(N)
    report, diags = audit_epoch_donation(c, label="qft17")
    assert report["donation_aliased"], diags
    assert report["pallas_passes"] == 1
    assert diags == []


def test_compat_entry_stack_aliases_under_donation():
    """The (2, N) compat entry reconciles the residual map PER PLANE and
    stacks once at the boundary: under a donating jit that stack must
    alias into the donated input buffer (no extra state copy)."""
    import jax
    from functools import partial
    from quest_tpu import _compat
    ops = qft_circuit(N).key()
    spec = jax.ShapeDtypeStruct((2, 1 << N), jnp.float32)

    @partial(jax.jit, donate_argnums=(0,))
    def run(state):
        return ep.run_ops_planes(state, ops)

    with _compat.enable_x64(False):
        text = run.lower(spec).compile().as_text()
    assert "input_output_alias" in text


# ---------------------------------------------------------------------------
# adversarial: corrupted cross-group decomposition caught by the IR proof
# ---------------------------------------------------------------------------

def test_check_epoch_plan_catches_corrupted_decomposition():
    """Tamper with the cross-group decomposition's middle factor (the
    controlled Givens rotation — the 'diagonal correction' of the odd-bit
    block form) inside an otherwise-valid plan: check_epoch_plan must
    refuse to certify it (V_SEMANTICS_CHANGED)."""
    from quest_tpu.analysis.equivalence import check_epoch_plan
    from quest_tpu.circuit import GateOp
    rng = np.random.default_rng(17)
    c = Circuit(12)
    c.multi_qubit_unitary((3, 11), _haar(rng, 2))
    plan = ep.plan_circuit(c.key(), 12)
    assert check_epoch_plan(c, plan=plan) == []   # the honest plan proves
    [seg] = plan.segments
    # the middle rotations are the factors TARGETING the odd (higher) bit
    idx = next(i for i, o in enumerate(seg.ops) if o.targets == (11,))
    victim = seg.ops[idx]
    theta = 0.31
    bad_mat = np.stack([np.array([[np.cos(theta), -np.sin(theta)],
                                  [np.sin(theta), np.cos(theta)]]),
                        np.zeros((2, 2))])
    bad = GateOp(victim.kind, victim.targets, victim.controls,
                 victim.control_states, tuple(bad_mat.ravel()), (2, 2, 2))
    tampered_ops = list(seg.ops)
    tampered_ops[idx] = bad
    tampered = ep.EnginePlan(
        12, [ep.Segment(seg.engine, tampered_ops, seg.passes)],
        plan.residual_perm, plan.deferred_ops)
    diags = check_epoch_plan(c, plan=tampered)
    assert any(d.code == "V_SEMANTICS_CHANGED" for d in diags), diags


# ---------------------------------------------------------------------------
# deferred qubit map across 2+ epochs
# ---------------------------------------------------------------------------

def test_deferred_map_carries_across_epochs():
    """Swaps before, between and after two Pallas segments split by an
    unsupported op (a >= 3-target dense gate straddling axis groups — the
    only dense shape still outside the widened envelope -> XLA fallback
    window): the residual permutation must be carried through ALL of it
    and reconciled once at the end.  A cross-group 2q dense no longer
    splits (it decomposes — test_cross_group_* below)."""
    rng = np.random.default_rng(11)
    c = Circuit(N)
    c.swap(0, 12)
    c.unitary(0, _haar(rng))          # physically lands on wire 12
    c.cz(0, 5)
    c.multi_qubit_unitary((5, 8, 14), _haar(rng, 3))   # 3q cross-group: XLA
    c.swap(3, 16)
    c.unitary(3, _haar(rng))
    c.t(16)
    c.swap(1, 2)
    plan = ep.plan_circuit(c.key(), N)
    engines = [s.engine for s in plan.segments]
    assert engines == ["pallas", "xla", "pallas"]
    assert plan.deferred_ops == 3
    assert plan.residual_perm != tuple(range(N))
    _assert_engines_agree(c)


def test_pure_permutation_circuit():
    c = Circuit(N)
    c.swap(0, 16)
    c.swap(3, 7)
    c.swap(0, 3)
    _assert_engines_agree(c, atol=0.0)  # pure data movement: exact


# ---------------------------------------------------------------------------
# degenerate single-op windows
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("build", [
    lambda c: c.cz(2, 13),
    lambda c: c.s(9),
    lambda c: c.rz(16, 0.37),
])
def test_single_diagonal_op_bit_exact(build):
    """A one-op diagonal window must be BIT-exact vs the XLA engine: both
    paths multiply each amplitude by the same f32-rounded factor with the
    same complex-product expression."""
    c = Circuit(N)
    build(c)
    got, want = _assert_engines_agree(c, atol=0.0)
    np.testing.assert_array_equal(got, want)


def test_single_mrz_window():
    """mrz phases precompute host-side in f64 (the angle-precision
    contract) — one rounding step apart from the XLA engine's in-device
    f64 trig, so the window agrees to f32 ulp, not bitwise."""
    c = Circuit(N)
    c.multi_rotate_z(tuple(range(12)), 1.1)
    _assert_engines_agree(c, atol=3e-7)


@pytest.mark.parametrize("q", [0, 5, 8, 12, 16])
def test_single_dense_op(q):
    rng = np.random.default_rng(100 + q)
    c = Circuit(N)
    c.unitary(q, _haar(rng))
    plan = ep.plan_circuit(c.key(), N)
    assert plan.hbm_passes == 1
    _assert_engines_agree(c, atol=5e-7)


# ---------------------------------------------------------------------------
# QFT pass-count regression: auto must not silently fall back
# ---------------------------------------------------------------------------

def test_qft_plan_reproduces_inplace_pass_count():
    """The general epoch lowering of the QFT must beat the hand-written
    qft_inplace engine's ~2(n-17)+1 HBM passes, with the trailing swap
    network absorbed into the deferred map at zero passes.  Since the
    two-stream lowering (controlled dense on high qubits rides the staged
    pack predicate; diagonals interleave as elementwise stages) the whole
    high ladder fuses into ONE pack pass per 7-qubit fiber group: one
    block pass + ceil((n-17)/7) packs — 2 passes at 22q, 3 at 28q, down
    from the per-stage 10/22 of the narrow envelope."""
    for n, want in ((22, 2), (28, 3)):
        plan = ep.plan_circuit(qft_circuit(n).key(), n)
        assert plan.xla_ops == 0, "silent per-gate fallback"
        assert plan.hbm_passes <= 2 * (n - 17) + 1  # the historical bound
        assert plan.hbm_passes == want
        assert plan.block_passes == 1
        assert plan.pack_passes == want - 1
        assert plan.deferred_ops == n // 2          # the swap network
        assert plan.residual_perm != tuple(range(n))


def test_compile_circuit_auto_selects_pallas_for_qft(monkeypatch):
    """compile_circuit(engine='auto') — the default path — must pick the
    epoch executor for the QFT factory on TPU-class specs (the backend
    guard lifted via QUEST_TPU_EPOCH_ENGINE=1, since tests run on CPU) and
    carry the full fused plan, not a per-gate fallback."""
    monkeypatch.setenv("QUEST_TPU_EPOCH_ENGINE", "1")
    run = compile_circuit(qft_circuit(28))
    assert run.engine == "pallas"
    assert run.engine_plan.hbm_passes <= 2 * (28 - 17) + 1
    assert run.engine_plan.xla_ops == 0
    run = compile_circuit(random_circuit(24, 4))
    assert run.engine == "pallas"


# ---------------------------------------------------------------------------
# engine selection
# ---------------------------------------------------------------------------

def test_select_engine_rules():
    qft = qft_circuit(28)
    # TPU-class spec: pallas for the factories
    assert planner.select_engine(qft, 1, backend="tpu")["engine"] == "pallas"
    assert planner.select_engine(random_circuit(24, 4), 1,
                                 backend="tpu")["engine"] == "pallas"
    # 10-16 qubit registers are now IN-envelope (degenerate single-block
    # geometry): the 12q QFT is one fused pass, a clear pallas win
    assert planner.select_engine(qft_circuit(12), 1,
                                 backend="tpu")["engine"] == "pallas"
    # off-TPU, auto stays on the XLA engine (interpret mode is not a perf
    # engine); forcing still works
    assert planner.select_engine(qft, 1, backend="cpu")["engine"] == "xla"
    assert planner.select_engine(qft, 1, backend="cpu",
                                 requested="pallas")["engine"] == "pallas"
    # the REMAINING out-of-envelope cases: f64, n < 10, meshes
    assert planner.select_engine(qft, 1, precision=2,
                                 backend="tpu")["engine"] == "xla"
    assert planner.select_engine(qft_circuit(8), 1,
                                 backend="tpu")["engine"] == "xla"
    assert planner.select_engine(qft, 8, backend="tpu")["engine"] == "xla"
    with pytest.raises(QuESTError):
        planner.select_engine(qft, 8, requested="pallas")
    with pytest.raises(QuESTError):
        planner.select_engine(qft_circuit(8), 1, requested="pallas")
    with pytest.raises(ValueError):
        planner.select_engine(qft, 1, requested="mosaic")


def test_envelope_rejection_messages_name_remaining_cases():
    """Forcing engine='pallas' outside the envelope raises
    E_INVALID_SCHEDULE_OPTION whose message names the SPECIFIC remaining
    unsupported case — meshes, f64 states, the n range — not the
    pre-widening blanket '17 <= n' envelope (cross-group 2q windows,
    controlled dense on high qubits and 10-16 qubit registers are now
    in-envelope; >= 3-target cross-group dense gates fall back PER OP
    inside the plan and never reject the circuit)."""
    qft = qft_circuit(22)
    with pytest.raises(QuESTError) as err:
        planner.select_engine(qft, 8, requested="pallas")
    assert err.value.code == "E_INVALID_SCHEDULE_OPTION"
    assert "multi-device mesh" in str(err.value)
    with pytest.raises(QuESTError) as err:
        planner.select_engine(qft, 1, precision=2, requested="pallas")
    assert err.value.code == "E_INVALID_SCHEDULE_OPTION"
    assert "f64" in str(err.value)
    with pytest.raises(QuESTError) as err:
        planner.select_engine(qft_circuit(8), 1, requested="pallas")
    assert err.value.code == "E_INVALID_SCHEDULE_OPTION"
    assert f"{ep.MIN_QUBITS} <= n <= {ep.MAX_QUBITS}" in str(err.value)
    # compile_circuit(engine="pallas") surfaces the same contract
    with pytest.raises(QuESTError) as err:
        compile_circuit(qft_circuit(8), engine="pallas")
    assert err.value.code == "E_INVALID_SCHEDULE_OPTION"
    # a >= 3-target cross-group dense op does NOT reject: it is planned as
    # a per-op XLA fallback window inside an accepted pallas program
    rng = np.random.default_rng(0)
    c = Circuit(N)
    c.h(0)
    c.multi_qubit_unitary((2, 8, 14), _haar(rng, 3))
    choice = planner.select_engine(c, 1, requested="pallas")
    assert choice["engine"] == "pallas"
    assert choice["plan"].xla_ops == 1


def test_engine_summary_per_epoch():
    c = Circuit(N)
    rng = np.random.default_rng(5)
    c.h(0)
    # a 3-target cross-group dense still splits the epoch (a 2-target one
    # now decomposes — see test_cross_group_2q_dense_minor_minor)
    c.multi_qubit_unitary((3, 8, 12), _haar(rng, 3))
    c.cz(1, 2)
    s = planner.engine_summary(c, 1, requested="pallas")
    assert s["engine"] == "pallas"
    assert [e["engine"] for e in s["epochs"]] == ["pallas", "xla", "pallas"]
    # infeasible forced engine is REPORTED, not raised
    s = planner.engine_summary(c, 8, requested="pallas")
    assert s["engine"] == "xla"


def test_f64_state_falls_back_at_call_time():
    c = Circuit(N)
    c.h(0)
    run = compile_circuit(c, engine="pallas")
    st = _rand_state(N).astype(jnp.float64)
    want = np.asarray(compile_circuit(c, engine="xla")(st))
    np.testing.assert_allclose(np.asarray(run(st)), want, atol=1e-12)


# ---------------------------------------------------------------------------
# engine-tagged program identity (serve compile cache / Circuit.key)
# ---------------------------------------------------------------------------

def test_circuit_key_records_engine():
    c = qft_circuit(N)
    assert c.key(engine="xla") == c.key()        # backward compatible
    assert c.key(engine=None) == c.key()
    assert c.key(engine="pallas") != c.key()
    assert c.key(engine="pallas")[0] == ("engine", "pallas")


def test_cache_class_key_separates_engines():
    """A class compiled under engine='xla' must never be served to a
    request planned for engine='pallas': distinct entries, truthful
    hit/miss counters, and distinct executables."""
    from quest_tpu.serve.cache import CacheOptions, CompileCache
    cache = CompileCache(max_bytes=1 << 30)
    c = Circuit(N)
    c.h(0)
    ops = c.key()
    e_xla = cache.entry_for(ops, options=CacheOptions())
    e_pal = cache.entry_for(ops, options=CacheOptions(engine="pallas"))
    assert e_xla is not e_pal
    assert cache.stats["misses"] == 2 and cache.stats["hits"] == 0
    assert e_pal.skeleton is None      # opaque: payloads live in kernels
    assert cache.entry_for(ops, options=CacheOptions(engine="pallas")) is e_pal
    assert cache.stats["hits"] == 1

    st = _rand_state(N)
    want = np.asarray(compile_circuit(c, engine="xla")(st))
    got = np.asarray(
        cache.epoch_program(e_pal, ops).call(st))
    np.testing.assert_allclose(got, want, atol=5e-7)


def test_donating_runner_engine_dimension():
    from quest_tpu.serve.cache import CompileCache
    cache = CompileCache(max_bytes=1 << 30)
    c = Circuit(N)
    c.s(4)
    run_x = cache.donating_runner(c.key())
    run_p = cache.donating_runner(c.key(), engine="pallas")
    a = np.asarray(run_x(_rand_state(N, 1)))
    b = np.asarray(run_p(_rand_state(N, 1)))
    np.testing.assert_array_equal(a, b)   # diagonal window: bit-exact


# ---------------------------------------------------------------------------
# envelope validation
# ---------------------------------------------------------------------------

def test_envelope_rejections():
    """The remaining out-of-envelope registers: below the 10-qubit
    degenerate-geometry floor, above the 30-qubit int32-index ceiling,
    f64.  10-16 qubit registers are IN (the widened envelope)."""
    with pytest.raises(ValueError):
        ep.plan_circuit(qft_circuit(8).key(), 8)
    st = jnp.zeros((2, 1 << 8), jnp.float32)
    with pytest.raises(ValueError):
        ep.run_ops_planes(st, qft_circuit(8).key())
    assert not ep.epoch_supported(9)
    assert not ep.epoch_supported(31)
    assert not ep.epoch_supported(20, precision=2)
    for n in (10, 12, 16, 17, 20, 30):
        assert ep.epoch_supported(n), n
