"""Plane-pair Qureg storage + deferred qubit-map (the 30q single-chip path).

At the memory ceiling (PLANE_STORAGE_MIN_BYTES, default 8 GiB = 30 qubits
f32) a Qureg holds separate (re, im) planes so the in-place Pallas engines
can consume its buffers directly, and an unordered applyFullQFT records its
trailing bit-reversal in ``qubit_map`` instead of paying the data movement.
These tests patch the thresholds down to exercise the whole plane regime at
18 qubits on CPU (Pallas interpret mode), comparing every operation against
an ordinary stacked register driven through the same public API.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest

import quest_tpu as qt
from quest_tpu import api as qapi
from quest_tpu import qureg as qmod

N = 18  # >= 17: the Pallas layer/QFT engine floor
ATOL = 5e-6  # f32 engine-vs-engine tolerance (matches test_pallas_layer)


@pytest.fixture
def plane_env(monkeypatch):
    """Single-device env with the plane threshold lowered so an 18q f32
    register uses plane storage.  Plane mode is normally accelerator-only
    (the byte ceiling is an HBM property); the env var forces it on so the
    CPU suite can exercise the engines in Pallas interpret mode."""
    monkeypatch.setenv(qmod.PLANE_STORAGE_ENV, "1")
    monkeypatch.setattr(qmod, "PLANE_STORAGE_MIN_BYTES", 2 * 4 * (1 << N))
    return qt.createQuESTEnv(num_devices=1)


def test_plane_storage_is_accelerator_only_by_default(monkeypatch):
    """A plane-sized f32 register on a CPU backend keeps the FULL gate set:
    the plane-only gate restriction is an accelerator-memory property, so on
    CPU (no env var) the register stays on stacked storage."""
    monkeypatch.delenv(qmod.PLANE_STORAGE_ENV, raising=False)
    monkeypatch.setattr(qmod, "PLANE_STORAGE_MIN_BYTES", 2 * 4 * (1 << 6))
    env = qt.createQuESTEnv(num_devices=1)
    q = qt.createQureg(6, env, dtype=jnp.float32)
    assert not q.uses_plane_storage()
    assert q._amps is not None and q._planes is None
    qt.controlledNot(q, 0, 1)  # would raise E_PLANE_ONLY_1Q in plane mode
    assert qt.calcTotalProb(q) == pytest.approx(1.0, abs=1e-6)
    # the env var force-enables plane mode on CPU (what the suite does)
    monkeypatch.setenv(qmod.PLANE_STORAGE_ENV, "1")
    assert qt.createQureg(6, env, dtype=jnp.float32).uses_plane_storage()
    # and "0" disables it regardless of backend
    monkeypatch.setenv(qmod.PLANE_STORAGE_ENV, "0")
    assert not qt.createQureg(6, env, dtype=jnp.float32).uses_plane_storage()


def test_take_planes_on_destroyed_register_raises(monkeypatch):
    """Donating buffers out of a destroyed register is an API error
    (E_QUREG_NOT_INITIALISED), not a bare TypeError."""
    env = qt.createQuESTEnv(num_devices=1)
    q = qt.createQureg(4, env)
    qt.destroyQureg(q, env)
    with pytest.raises(qt.QuESTError, match="destroyed") as exc:
        q.take_planes()
    assert exc.value.code == "E_QUREG_NOT_INITIALISED"


def _pair(q):
    """(2, 2^n) numpy view of a register's state: direct plane reads when
    the map is identity, explicit materialisation (reconciling a deferred
    map) otherwise."""
    if q._planes is not None and q.qubit_map is None:
        re, im = q.planes
        return np.stack([np.asarray(re), np.asarray(im)])
    if q._planes is not None:
        return np.asarray(q.materialize_stacked())
    return np.asarray(q.amps)


def test_plane_register_creation_and_init(plane_env):
    q = qt.createQureg(N, plane_env, dtype=jnp.float32)
    assert q.uses_plane_storage()
    assert q._planes is not None and q._amps is None
    assert qt.calcTotalProb(q) == pytest.approx(1.0, abs=1e-6)
    assert qt.getAmp(q, 0) == pytest.approx(1.0)
    qt.initPlusState(q)
    assert q._planes is not None
    assert qt.calcTotalProb(q) == pytest.approx(1.0, abs=1e-5)
    assert qt.getAmp(q, 3).real == pytest.approx(1.0 / np.sqrt(1 << N), rel=1e-5)
    qt.initClassicalState(q, 5)
    assert qt.getAmp(q, 5) == pytest.approx(1.0)
    qt.initBlankState(q)
    assert qt.calcTotalProb(q) == pytest.approx(0.0, abs=1e-7)


def test_plane_1q_gates_match_stacked_register(plane_env):
    qp = qt.createQureg(N, plane_env, dtype=jnp.float32)
    qs = qt.createQureg(N, plane_env, dtype=jnp.float32)
    assert qp.uses_plane_storage() and qs._amps is None  # both plane-eligible
    # force the reference register onto STACKED storage explicitly
    qs.materialize_stacked()
    assert qs._planes is None and qs._amps is not None

    for f, args in [(qt.hadamard, (0,)), (qt.hadamard, (N - 1,)),
                    (qt.pauliX, (3,)), (qt.pauliY, (8,)), (qt.pauliZ, (11,)),
                    (qt.rotateX, (5, 0.3)), (qt.rotateY, (12, -0.7)),
                    (qt.rotateZ, (N - 2, 1.1)), (qt.tGate, (2,)),
                    (qt.sGate, (9,)), (qt.phaseShift, (4, 0.37))]:
        f(qp, *args)
        f(qs, *args)
    assert qp._planes is not None  # never silently fell back to stacked
    np.testing.assert_allclose(_pair(qp), _pair(qs), atol=ATOL)
    # probabilities agree through the API
    for t in (0, 5, N - 1):
        assert qt.calcProbOfOutcome(qp, t, 1) == pytest.approx(
            qt.calcProbOfOutcome(qs, t, 1), abs=1e-5)


def test_plane_multi_qubit_gate_refused(plane_env):
    q = qt.createQureg(N, plane_env, dtype=jnp.float32)
    with pytest.raises(qt.QuESTError, match="plane-pair"):
        qt.controlledNot(q, 0, 1)
    with pytest.raises(qt.QuESTError, match="plane-pair"):
        qt.twoQubitUnitary(q, 0, 1, np.eye(4))
    # the register is still usable afterwards
    qt.hadamard(q, 0)
    assert qt.calcTotalProb(q) == pytest.approx(1.0, abs=1e-5)


def test_plane_full_qft_ordered(plane_env):
    qp = qt.createQureg(N, plane_env, dtype=jnp.float32)
    qs = qt.createQureg(N, plane_env, dtype=jnp.float32)
    qs.materialize_stacked()
    for t in (0, 4, N - 1):
        qt.hadamard(qp, t)
        qt.hadamard(qs, t)
    qt.rotateY(qp, 7, 0.4)
    qt.rotateY(qs, 7, 0.4)
    qt.applyFullQFT(qp)  # in-place engine, donated planes, ordered
    qt.applyQFT(qs, list(range(N)))  # circuit program on the stacked twin
    assert qp.qubit_map is None
    np.testing.assert_allclose(_pair(qp), _pair(qs), atol=ATOL)


def test_plane_full_qft_deferred_bit_reversal(plane_env, monkeypatch):
    """The >=30q mode at test size: unordered engine + qubit_map records the
    reversal; reads, gates, measurement and materialisation all translate
    through the map."""
    monkeypatch.setattr(qapi, "_QFT_UNORDERED_MIN_QUBITS", N)
    qp = qt.createQureg(N, plane_env, dtype=jnp.float32)
    qs = qt.createQureg(N, plane_env, dtype=jnp.float32)
    qs.materialize_stacked()
    qt.hadamard(qp, 2)
    qt.hadamard(qs, 2)
    qt.rotateZ(qp, 9, 0.21)
    qt.rotateZ(qs, 9, 0.21)
    qt.applyFullQFT(qp)
    qt.applyQFT(qs, list(range(N)))
    assert qp.qubit_map == tuple(range(N - 1, -1, -1))

    # amplitude reads translate through the map
    for idx in (0, 1, 5, (1 << N) - 1, 12345):
        a, b = qt.getAmp(qp, idx), qt.getAmp(qs, idx)
        assert a == pytest.approx(b, abs=ATOL), idx
    # probabilities on LOGICAL targets
    for t in (0, 3, N - 1):
        assert qt.calcProbOfOutcome(qp, t, 1) == pytest.approx(
            qt.calcProbOfOutcome(qs, t, 1), abs=1e-5)

    # gates on logical targets route to the mapped physical bit
    qt.hadamard(qp, 1)
    qt.hadamard(qs, 1)
    qt.phaseShift(qp, N - 3, 0.5)
    qt.phaseShift(qs, N - 3, 0.5)
    for idx in (7, 99, 54321):
        assert qt.getAmp(qp, idx) == pytest.approx(qt.getAmp(qs, idx),
                                                   abs=ATOL)

    # a second QFT forces map reconciliation (fits below the ceiling) and
    # still matches the circuit result
    qt.applyFullQFT(qp)
    qt.applyQFT(qs, list(range(N)))
    np.testing.assert_allclose(_pair(qp), _pair(qs), atol=5 * ATOL)


def test_plane_measure_collapse(plane_env):
    qp = qt.createQureg(N, plane_env, dtype=jnp.float32)
    qs = qt.createQureg(N, plane_env, dtype=jnp.float32)
    qs.materialize_stacked()
    qt.initPlusState(qp)
    qt.initPlusState(qs)
    qt.seedQuEST([42])
    op = qt.measure(qp, 4)
    qt.seedQuEST([42])
    os_ = qt.measure(qs, 4)
    assert op == os_
    assert qp._planes is not None
    assert qt.calcTotalProb(qp) == pytest.approx(1.0, abs=1e-5)
    np.testing.assert_allclose(_pair(qp), _pair(qs), atol=ATOL)
    # collapseToOutcome through the API
    p = qt.collapseToOutcome(qp, 6, 1)
    ps = qt.collapseToOutcome(qs, 6, 1)
    assert p == pytest.approx(ps, abs=1e-6)
    np.testing.assert_allclose(_pair(qp), _pair(qs), atol=ATOL)


def test_plane_materialisation_reconciles_map(plane_env, monkeypatch):
    """Asking for the stacked array on a mapped register applies the
    deferred permutation physically (below the ceiling)."""
    monkeypatch.setattr(qapi, "_QFT_UNORDERED_MIN_QUBITS", N)
    qp = qt.createQureg(N, plane_env, dtype=jnp.float32)
    qs = qt.createQureg(N, plane_env, dtype=jnp.float32)
    qs.materialize_stacked()
    qt.hadamard(qp, 0)
    qt.hadamard(qs, 0)
    qt.applyFullQFT(qp)
    qt.applyQFT(qs, list(range(N)))
    assert qp.qubit_map is not None
    st = np.asarray(qp.materialize_stacked())  # reconciles the map
    assert qp.qubit_map is None
    np.testing.assert_allclose(st, _pair(qs), atol=ATOL)
