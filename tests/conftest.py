"""Test configuration: deterministic CPU backend with 8 virtual devices, or
the real TPU chip when QUEST_TEST_PLATFORM=tpu.

The reference runs ONE test suite against whichever backend was compiled in
(serial / OpenMP / MPI / GPU — ref: tests/CMakeLists.txt:6-17).  Here the same
idea is expressed as a pytest parametrisation: every correctness test runs
twice, once on a single (unsharded) device and once sharded over an 8-device
mesh, exercising the GSPMD collective paths the reference exercised with real
MPI under SLURM (ref: examples/submissionScripts/mpi_SLURM_unit_tests.sh).

Platforms:
- default: CPU with 8 virtual devices at float64 (reference PRECISION=2) —
  deterministic, runs anywhere.
- QUEST_TEST_PLATFORM=tpu: the real chip at float32 (TPU-native precision 1,
  reference PRECISION=1 tolerances) — the accelerator numerics validation.
  The dist8 parametrisation skips (one physical chip); precision-2 anchors
  still run (f64 is emulated on TPU).

The container may boot JAX with a TPU platform plugin pre-registered from
sitecustomize; unless the TPU run is requested, tests must run on CPU with 8
virtual devices, so before any backend is initialised we inject the XLA
host-device-count flag and switch the platform config to cpu (this works even
after plugin registration, as long as no backend has been *used* yet).
"""

from __future__ import annotations

import os

TEST_PLATFORM = os.environ.get("QUEST_TEST_PLATFORM", "cpu").lower()

# Must happen before the first jax backend initialisation.
_FLAGS = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _FLAGS:
    os.environ["XLA_FLAGS"] = (_FLAGS + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

if TEST_PLATFORM == "cpu":
    jax.config.update("jax_platforms", "cpu")
# else: leave whatever accelerator platform the container provides (axon/tpu)

import numpy as np  # noqa: E402
import pytest  # noqa: E402

import quest_tpu as qt  # noqa: E402

ON_ACCELERATOR = TEST_PLATFORM != "cpu"


@pytest.fixture(scope="session", autouse=True)
def _precision():
    # CPU: float64, matching the reference's default PRECISION=2.
    # TPU: float32 (precision 1) — the chip's native width; f64 is emulated
    # and reserved for the precision-2 anchor tests that ask for it.
    qt.set_precision(1 if ON_ACCELERATOR else 2)


@pytest.fixture(scope="session")
def env_local():
    return qt.createQuESTEnv(1)


@pytest.fixture(scope="session")
def env_dist():
    if ON_ACCELERATOR:
        pytest.skip("single physical chip: dist8 runs on the CPU platform")
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return qt.createQuESTEnv(8)


@pytest.fixture(scope="session", params=["local", "dist8"])
def env(request, env_local):
    """Backend-parametrized environment: unsharded, and sharded over 8 devices."""
    if request.param == "local":
        return env_local
    return request.getfixturevalue("env_dist")


@pytest.fixture(autouse=True)
def _seed_rng():
    """Deterministic MT19937 stream per test (ref: seedQuEST semantics)."""
    qt.seedQuEST([12345, 678], 2)
    np.random.seed(7)
    yield
