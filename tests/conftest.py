"""Test configuration: force a deterministic CPU backend with 8 virtual devices.

The reference runs ONE test suite against whichever backend was compiled in
(serial / OpenMP / MPI / GPU — ref: tests/CMakeLists.txt:6-17).  Here the same
idea is expressed as a pytest parametrisation: every correctness test runs
twice, once on a single (unsharded) device and once sharded over an 8-device
mesh, exercising the GSPMD collective paths the reference exercised with real
MPI under SLURM (ref: examples/submissionScripts/mpi_SLURM_unit_tests.sh).

The container may boot JAX with a TPU platform plugin pre-registered from
sitecustomize; tests must nevertheless run on CPU with 8 virtual devices, so
before any backend is initialised we inject the XLA host-device-count flag and
switch the platform config to cpu (this works even after plugin registration,
as long as no backend has been *used* yet).
"""

from __future__ import annotations

import os

# Must happen before the first jax backend initialisation.
_FLAGS = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _FLAGS:
    os.environ["XLA_FLAGS"] = (_FLAGS + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402

import quest_tpu as qt  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _precision():
    qt.set_precision(2)  # float64: matches the reference's default PRECISION=2


@pytest.fixture(scope="session")
def env_local():
    return qt.createQuESTEnv(1)


@pytest.fixture(scope="session")
def env_dist():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return qt.createQuESTEnv(8)


@pytest.fixture(scope="session", params=["local", "dist8"])
def env(request, env_local):
    """Backend-parametrized environment: unsharded, and sharded over 8 devices."""
    if request.param == "local":
        return env_local
    return request.getfixturevalue("env_dist")


@pytest.fixture(autouse=True)
def _seed_rng():
    """Deterministic MT19937 stream per test (ref: seedQuEST semantics)."""
    qt.seedQuEST([12345, 678], 2)
    np.random.seed(7)
    yield
