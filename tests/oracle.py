"""Analytic linear-algebra oracle for the test suite.

An independent, deliberately unoptimised dense-numpy model of every operation,
mirroring the role of the reference's QVector/QMatrix utilities
(ref: tests/utilities.hpp:49-60, getFullOperatorMatrix :273-287,
applyReferenceOp overloads :403-703): tests apply an operation through
quest_tpu AND through this oracle and compare all amplitudes.

Conventions (identical to the framework and the reference):
- qubit q is bit q of the basis index (qubit 0 = least significant);
- a k-qubit gate matrix has targets[0] as the least significant row bit;
- a density matrix rho of N qubits is held as rho[r, c], and the flattened
  Choi vector has element (r, c) at index r + c*2^N.
"""

from __future__ import annotations

import numpy as np

NUM_QUBITS = 5  # matches the reference suite (tests/utilities.hpp:36)

# tolerance: tests accept <=10x REAL_EPS like the reference
# (test_unitaries.cpp:70); REAL_EPS is 1e-13 at precision 2 and 1e-5 at
# precision 1 (ref: QuEST_precision.h:35,49), so the f32 TPU run
# (QUEST_TEST_PLATFORM=tpu) uses the looser pair.
import os as _os

if _os.environ.get("QUEST_TEST_PLATFORM", "cpu").lower() == "cpu":
    SV_TOL = 1e-12
    DM_TOL = 1e-11
else:
    SV_TOL = 1e-4
    DM_TOL = 1e-3


# ---------------------------------------------------------------------------
# state extraction
# ---------------------------------------------------------------------------

def sv(qureg) -> np.ndarray:
    """Complex statevector of a quest_tpu Qureg (gathers shards)."""
    a = np.asarray(qureg.amps)
    return a[0] + 1j * a[1]


def dm(qureg) -> np.ndarray:
    """Density matrix rho[r, c] of a density Qureg."""
    v = sv(qureg)
    dim = 1 << qureg.num_qubits_represented
    return v.reshape(dim, dim).T  # flat index r + c*dim -> [r, c]


def dm_to_flat(rho: np.ndarray) -> np.ndarray:
    """Inverse of ``dm``: rho[r, c] -> flattened Choi vector."""
    return rho.T.reshape(-1)


# ---------------------------------------------------------------------------
# full-operator construction
# ---------------------------------------------------------------------------

def full_operator(n: int, targets, u, controls=(), control_states=None) -> np.ndarray:
    """Build the full 2^n x 2^n matrix of a (multi-)controlled k-qubit gate
    (oracle analogue of getFullOperatorMatrix, ref tests/utilities.hpp:273-287)."""
    targets = list(targets)
    controls = list(controls)
    if control_states is None:
        control_states = [1] * len(controls)
    u = np.asarray(u, dtype=complex)
    dim = 1 << n
    k = len(targets)
    op = np.zeros((dim, dim), dtype=complex)
    for col in range(dim):
        if all(((col >> c) & 1) == s for c, s in zip(controls, control_states)):
            in_sub = 0
            for j, t in enumerate(targets):
                in_sub |= ((col >> t) & 1) << j
            rest = col
            for t in targets:
                rest &= ~(1 << t)
            for out_sub in range(1 << k):
                row = rest
                for j, t in enumerate(targets):
                    row |= ((out_sub >> j) & 1) << t
                op[row, col] = u[out_sub, in_sub]
        else:
            op[col, col] = 1.0
    return op


def apply_to_sv(vec: np.ndarray, n, targets, u, controls=(), control_states=None):
    return full_operator(n, targets, u, controls, control_states) @ vec


def apply_to_dm(rho: np.ndarray, n, targets, u, controls=(), control_states=None):
    """rho -> U rho U^dagger (the reference's density applyReferenceOp)."""
    op = full_operator(n, targets, u, controls, control_states)
    return op @ rho @ op.conj().T


def left_apply_to_dm(rho: np.ndarray, n, targets, u, controls=()):
    """rho -> U rho (the reference's applyReferenceMatrix for density inputs)."""
    return full_operator(n, targets, u, controls) @ rho


def apply_channel(rho: np.ndarray, n, targets, kraus_ops) -> np.ndarray:
    """rho -> sum_i K_i rho K_i^dagger with k-qubit Kraus operators."""
    out = np.zeros_like(rho)
    for k in kraus_ops:
        op = full_operator(n, targets, k)
        out += op @ rho @ op.conj().T
    return out


# ---------------------------------------------------------------------------
# fixed matrices
# ---------------------------------------------------------------------------

I2 = np.eye(2, dtype=complex)
X = np.array([[0, 1], [1, 0]], dtype=complex)
Y = np.array([[0, -1j], [1j, 0]], dtype=complex)
Z = np.array([[1, 0], [0, -1]], dtype=complex)
H = np.array([[1, 1], [1, -1]], dtype=complex) / np.sqrt(2)
PAULIS = [I2, X, Y, Z]


def rot(axis: np.ndarray, angle: float) -> np.ndarray:
    """exp(-i angle/2 (axis . sigma)), axis normalised."""
    axis = np.asarray(axis, dtype=float)
    axis = axis / np.linalg.norm(axis)
    g = axis[0] * X + axis[1] * Y + axis[2] * Z
    return np.cos(angle / 2) * I2 - 1j * np.sin(angle / 2) * g


def phase_shift(angle: float) -> np.ndarray:
    return np.diag([1.0, np.exp(1j * angle)])


def pauli_string_matrix(n: int, targets, codes) -> np.ndarray:
    """Full-space product of single-qubit Paulis at the given targets."""
    op = np.eye(1 << n, dtype=complex)
    for t, c in zip(targets, codes):
        op = full_operator(n, [t], PAULIS[int(c)]) @ op
    return op


def pauli_sum_matrix(n: int, codes: np.ndarray, coeffs) -> np.ndarray:
    """sum_t coeffs[t] * prod_q pauli(codes[t, q]) on qubit q."""
    codes = np.asarray(codes).reshape(len(coeffs), n)
    dim = 1 << n
    out = np.zeros((dim, dim), dtype=complex)
    for t, c in enumerate(np.asarray(coeffs, dtype=float)):
        out += c * pauli_string_matrix(n, range(n), codes[t])
    return out


# ---------------------------------------------------------------------------
# random fixtures (oracle analogues of tests/utilities.hpp:342-384)
# ---------------------------------------------------------------------------

def random_unitary(k_qubits: int) -> np.ndarray:
    """Haar-ish random unitary via QR of a complex Gaussian."""
    dim = 1 << k_qubits
    g = np.random.randn(dim, dim) + 1j * np.random.randn(dim, dim)
    q, r = np.linalg.qr(g)
    return q * (np.diag(r) / np.abs(np.diag(r)))


def random_statevector(n: int) -> np.ndarray:
    v = np.random.randn(1 << n) + 1j * np.random.randn(1 << n)
    return v / np.linalg.norm(v)


def random_density_matrix(n: int) -> np.ndarray:
    dim = 1 << n
    a = np.random.randn(dim, dim) + 1j * np.random.randn(dim, dim)
    rho = a @ a.conj().T
    return rho / np.trace(rho)


def random_kraus_map(k_qubits: int, num_ops: int) -> list:
    """A random CPTP map: random matrices normalised so sum K^dag K = I."""
    dim = 1 << k_qubits
    mats = [np.random.randn(dim, dim) + 1j * np.random.randn(dim, dim)
            for _ in range(num_ops)]
    s = sum(k.conj().T @ k for k in mats)
    # s is positive-definite; its inverse square root normalises the map
    w, v = np.linalg.eigh(s)
    s_inv_sqrt = v @ np.diag(w ** -0.5) @ v.conj().T
    return [k @ s_inv_sqrt for k in mats]


# ---------------------------------------------------------------------------
# state loading & comparison
# ---------------------------------------------------------------------------

def set_sv(qureg, vec: np.ndarray) -> None:
    import quest_tpu as qt
    qt.initStateFromAmps(qureg, np.real(vec).copy(), np.imag(vec).copy())


def set_dm(qureg, rho: np.ndarray) -> None:
    import quest_tpu as qt
    flat = dm_to_flat(rho)
    qt.setDensityAmps(qureg, np.real(flat).copy(), np.imag(flat).copy())


def assert_sv(qureg, expected: np.ndarray, tol: float = SV_TOL) -> None:
    got = sv(qureg)
    np.testing.assert_allclose(got, expected, atol=tol, rtol=0)


def assert_dm(qureg, expected: np.ndarray, tol: float = DM_TOL) -> None:
    got = dm(qureg)
    np.testing.assert_allclose(got, expected, atol=tol, rtol=0)
