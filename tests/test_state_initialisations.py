"""State initialisation & amplitude injection, mirroring the reference's
test_state_initialisations.cpp (9 TEST_CASEs)."""

from __future__ import annotations

import numpy as np
import pytest

import quest_tpu as qt
from oracle import (NUM_QUBITS, assert_dm, assert_sv, dm, random_density_matrix,
                    random_statevector, set_dm, set_sv, sv)

N = NUM_QUBITS
DIM = 1 << N


def test_initBlankState(env):
    psi = qt.createQureg(N, env)
    qt.initBlankState(psi)
    assert_sv(psi, np.zeros(DIM))
    rho = qt.createDensityQureg(N, env)
    qt.initBlankState(rho)
    assert_dm(rho, np.zeros((DIM, DIM)))


def test_initZeroState(env):
    psi = qt.createQureg(N, env)
    qt.hadamard(psi, 0)
    qt.initZeroState(psi)
    expected = np.zeros(DIM)
    expected[0] = 1.0
    assert_sv(psi, expected)
    rho = qt.createDensityQureg(N, env)
    qt.initZeroState(rho)
    exp_rho = np.zeros((DIM, DIM))
    exp_rho[0, 0] = 1.0
    assert_dm(rho, exp_rho)


def test_initPlusState(env):
    psi = qt.createQureg(N, env)
    qt.initPlusState(psi)
    assert_sv(psi, np.full(DIM, 1.0 / np.sqrt(DIM)))
    rho = qt.createDensityQureg(N, env)
    qt.initPlusState(rho)
    assert_dm(rho, np.full((DIM, DIM), 1.0 / DIM))


def test_initClassicalState(env):
    for ind in (0, 5, DIM - 1):
        psi = qt.createQureg(N, env)
        qt.initClassicalState(psi, ind)
        expected = np.zeros(DIM)
        expected[ind] = 1.0
        assert_sv(psi, expected)
        rho = qt.createDensityQureg(N, env)
        qt.initClassicalState(rho, ind)
        exp_rho = np.zeros((DIM, DIM))
        exp_rho[ind, ind] = 1.0
        assert_dm(rho, exp_rho)
    psi = qt.createQureg(N, env)
    with pytest.raises(qt.QuESTError, match="Invalid state index"):
        qt.initClassicalState(psi, DIM)


def test_initPureState(env):
    vec = random_statevector(N)
    source = qt.createQureg(N, env)
    set_sv(source, vec)
    # statevector <- statevector copy
    psi = qt.createQureg(N, env)
    qt.initPureState(psi, source)
    assert_sv(psi, vec)
    # density matrix <- |psi><psi|
    rho = qt.createDensityQureg(N, env)
    qt.initPureState(rho, source)
    assert_dm(rho, np.outer(vec, np.conj(vec)))
    # validation: second arg must be a statevector; dims must match
    with pytest.raises(qt.QuESTError, match="state-vector"):
        qt.initPureState(psi, rho)
    small = qt.createQureg(N - 1, env)
    with pytest.raises(qt.QuESTError, match="Dimensions"):
        qt.initPureState(psi, small)


def test_initStateFromAmps(env):
    vec = random_statevector(N)
    psi = qt.createQureg(N, env)
    qt.initStateFromAmps(psi, np.real(vec).copy(), np.imag(vec).copy())
    assert_sv(psi, vec)


def test_setAmps(env):
    vec = random_statevector(N)
    psi = qt.createQureg(N, env)
    set_sv(psi, vec)
    # overwrite a window [start, start+num)
    start, num = 3, 7
    re = np.arange(num, dtype=float)
    im = -np.arange(num, dtype=float)
    qt.setAmps(psi, start, re, im, num)
    expected = vec.copy()
    expected[start:start + num] = re + 1j * im
    assert_sv(psi, expected)
    with pytest.raises(qt.QuESTError, match="More amplitudes"):
        qt.setAmps(psi, DIM - 1, re, im, num)
    with pytest.raises(qt.QuESTError, match="Invalid amplitude index"):
        qt.setAmps(psi, -1, re, im, num)
    rho = qt.createDensityQureg(N, env)
    with pytest.raises(qt.QuESTError, match="state-vector"):
        qt.setAmps(rho, 0, re, im, num)


def test_cloneQureg(env):
    vec = random_statevector(N)
    source = qt.createQureg(N, env)
    set_sv(source, vec)
    target = qt.createQureg(N, env)
    qt.cloneQureg(target, source)
    assert_sv(target, vec)
    # density
    rho_in = random_density_matrix(N)
    src_d = qt.createDensityQureg(N, env)
    set_dm(src_d, rho_in)
    tgt_d = qt.createDensityQureg(N, env)
    qt.cloneQureg(tgt_d, src_d)
    assert_dm(tgt_d, rho_in)
    with pytest.raises(qt.QuESTError, match="both be state-vectors or both"):
        qt.cloneQureg(target, src_d)
    small = qt.createQureg(N - 1, env)
    with pytest.raises(qt.QuESTError, match="Dimensions"):
        qt.cloneQureg(small, source)


def test_setWeightedQureg(env):
    v1, v2, v3 = (random_statevector(N) for _ in range(3))
    f1, f2, fo = 0.3 - 0.1j, -0.5 + 0.2j, 1.1 + 0.4j
    q1 = qt.createQureg(N, env)
    q2 = qt.createQureg(N, env)
    out = qt.createQureg(N, env)
    set_sv(q1, v1)
    set_sv(q2, v2)
    set_sv(out, v3)
    qt.setWeightedQureg(f1, q1, f2, q2, fo, out)
    assert_sv(out, f1 * v1 + f2 * v2 + fo * v3)
    # density-matrix version
    r1, r2, r3 = (random_density_matrix(N) for _ in range(3))
    d1 = qt.createDensityQureg(N, env)
    d2 = qt.createDensityQureg(N, env)
    do = qt.createDensityQureg(N, env)
    set_dm(d1, r1)
    set_dm(d2, r2)
    set_dm(do, r3)
    qt.setWeightedQureg(f1, d1, f2, d2, fo, do)
    assert_dm(do, f1 * r1 + f2 * r2 + fo * r3)
    # validation: mixed types
    with pytest.raises(qt.QuESTError, match="both be state-vectors or both"):
        qt.setWeightedQureg(f1, q1, f2, d2, fo, out)
