"""The whole-circuit compilation layer (no reference analogue — the TPU-native
fast path).  Checks the fused program agrees with the eager per-gate API and
with analytic results."""

from __future__ import annotations

import numpy as np
import pytest

import quest_tpu as qt
from oracle import NUM_QUBITS, assert_dm, assert_sv, dm, random_statevector, set_sv, sv, SV_TOL

N = NUM_QUBITS


def test_compiled_random_circuit_matches_eager(env):
    c = qt.random_circuit(N, depth=3, seed=42)
    psi = qt.createQureg(N, env)
    qt.initPlusState(psi)
    ref = qt.createCloneQureg(psi, env)
    qt.apply_circuit(psi, c)
    # replay through the eager API
    from quest_tpu.circuit import GateOp  # noqa: F401
    for op in c.ops:
        if op.kind == "matrix":
            p = op.payload()
            u = p[0] + 1j * p[1]
            qt.multiQubitUnitary(ref, list(op.targets), len(op.targets), u)
        elif op.kind == "diagonal":
            p = op.payload()
            d = p[0] + 1j * p[1]
            if op.controls:
                qt.controlledPhaseShift(ref, op.controls[0], op.targets[0],
                                        float(np.angle(d[1])))
            else:
                diag_u = np.diag(d)
                qt.multiQubitUnitary(ref, list(op.targets), len(op.targets), diag_u)
        elif op.kind == "x":
            if op.controls:
                qt.controlledNot(ref, op.controls[0], op.targets[0])
            else:
                qt.pauliX(ref, op.targets[0])
        elif op.kind == "swap":
            qt.swapGate(ref, op.targets[0], op.targets[1])
    np.testing.assert_allclose(sv(psi), sv(ref), atol=SV_TOL)


def test_compiled_circuit_on_density_matrix(env):
    c = qt.Circuit(N).h(0).cnot(0, 1).rz(1, 0.3).ry(2, -0.7).y(3).x(4, (3,))
    rho = qt.createDensityQureg(N, env)
    ref = qt.createDensityQureg(N, env)
    qt.apply_circuit(rho, c)
    qt.hadamard(ref, 0)
    qt.controlledNot(ref, 0, 1)
    qt.rotateZ(ref, 1, 0.3)
    qt.rotateY(ref, 2, -0.7)
    qt.pauliY(ref, 3)
    qt.controlledNot(ref, 3, 4)
    np.testing.assert_allclose(sv(rho), sv(ref), atol=SV_TOL)
    assert qt.calcTotalProb(rho) == pytest.approx(1.0, abs=SV_TOL)


def test_qft_matches_dft_matrix(env):
    n = 4
    dim = 1 << n
    vec = random_statevector(n)
    psi = qt.createQureg(n, env)
    set_sv(psi, vec)
    qt.apply_circuit(psi, qt.qft_circuit(n))
    # DFT with positive phase convention: F[y, x] = w^(xy)/sqrt(dim)
    w = np.exp(2j * np.pi / dim)
    f = np.array([[w ** (x * y) for x in range(dim)] for y in range(dim)]) / np.sqrt(dim)
    np.testing.assert_allclose(sv(psi), f @ vec, atol=SV_TOL)


def test_compile_circuit_pure_function(env_local):
    c = qt.random_circuit(4, depth=2, seed=1)
    run = qt.compile_circuit(c)
    psi = qt.createQureg(4, env_local)
    qt.initZeroState(psi)
    out = run(psi.amps)
    assert out.shape == (2, 16)
    norm = float(np.sum(np.asarray(out) ** 2))
    assert norm == pytest.approx(1.0, abs=SV_TOL)


def test_density_shadow_cache_invalidated_on_append(env):
    """Regression (r2 verdict): gates appended to a Circuit after a
    density-matrix application must not be dropped by the shadow-op cache."""
    c = qt.Circuit(3).h(0)
    rho = qt.createDensityQureg(3, env)
    qt.apply_circuit(rho, c)          # primes the shadow cache
    np.testing.assert_allclose(np.diag(dm(rho))[:2], [0.5, 0.5], atol=SV_TOL)

    c.x(0)                            # append AFTER the cache was built
    qt.initZeroState(rho)
    qt.apply_circuit(rho, c)          # must include the appended X
    ref = qt.createDensityQureg(3, env)
    qt.hadamard(ref, 0)
    qt.pauliX(ref, 0)
    np.testing.assert_allclose(dm(rho), dm(ref), atol=SV_TOL)

    # same circuit object re-applied unchanged: cache hit must still be right
    qt.initZeroState(rho)
    qt.apply_circuit(rho, c)
    np.testing.assert_allclose(dm(rho), dm(ref), atol=SV_TOL)


def test_deferred_reroute_matches_eager_engine(env_local):
    """Wide minor-block gates in a compiled circuit defer their reroute
    swap-backs (one shared routing + one reconcile); the result must equal
    the eager engine's per-gate swap-in/swap-out semantics, including for
    gates APPLIED AFTER the deferral (their wires are translated)."""
    import jax.numpy as jnp
    from quest_tpu.circuit import Circuit, compile_circuit
    from quest_tpu.ops import apply as ap
    from oracle import random_unitary

    n = 14
    np.random.seed(5)
    u3 = random_unitary(3)
    u1 = random_unitary(1)
    c = Circuit(n)
    c.multi_qubit_unitary((0, 8, 10), u3)   # triggers reroute (m=11 > cap)
    c.h(2)                                  # applied while perm non-identity
    c.rz(13, 0.31)
    c.multi_qubit_unitary((0, 8, 10), u3)   # shares the routing
    c.unitary(5, u1)
    c.cnot(1, 11)

    rs = np.random.RandomState(3)
    st = rs.randn(2, 1 << n)
    st /= np.sqrt((st ** 2).sum())
    sj = jnp.asarray(st, jnp.float64)

    got = np.asarray(compile_circuit(c)(sj))

    want = sj
    for op in c.key():
        u = jnp.asarray(op.payload(), dtype=want.dtype) if op.kind == "matrix" else None
        if op.kind == "matrix":
            want = ap.apply_matrix(want, u, op.targets, op.controls,
                                   op.control_states)
        else:
            from quest_tpu.circuit import _apply_one
            want = _apply_one(want, op)
    np.testing.assert_allclose(got, np.asarray(want), atol=1e-12)
