"""Driver-integration regression tests.

The driver validates multi-chip sharding by calling
``__graft_entry__.dryrun_multichip(n)`` in an environment that may have a
broken or absent accelerator runtime (r03: a libtpu client/terminal version
mismatch made *any* touch of the default backend fatal).  These tests pin the
property that the dryrun is accelerator-independent: it must run entirely on
the virtual-device CPU platform and never initialise any other backend.

Ref analogue: the reference proves its distributed backend by running under
real MPI (examples/submissionScripts/mpi_SLURM_unit_tests.sh:1-17); here the
equivalent proof artifact is the dryrun, so its environment-robustness is a
first-class correctness property.
"""

from __future__ import annotations

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_DRYRUN_DRIVER = """
import os, sys
# Mimic the driver: virtual CPU devices via XLA_FLAGS, nothing else.  Any
# JAX_PLATFORMS pin is removed so the default platform resolution (which may
# prefer a site-registered accelerator plugin) is in effect — the dryrun
# itself must neutralise it.
os.environ.pop("JAX_PLATFORMS", None)
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
sys.path.insert(0, @REPO@)

import __graft_entry__
__graft_entry__.dryrun_multichip(8)

# The pinned property: after a full dryrun, the ONLY initialised backend is
# the host CPU platform.  If any eager op had touched the default backend,
# the accelerator plugin would appear here (and in the driver's environment
# it would have crashed the process before this point).  The registry of
# already-initialised backends has no public accessor, so fall back to the
# public default-platform signal if the private one moves in a jax upgrade.
import jax
from jax._src import xla_bridge
registry = getattr(xla_bridge, "_backends", None)
if registry is not None:
    initialised = set(registry)
    assert initialised == {"cpu"}, f"non-CPU backend initialised: {initialised}"
else:
    initialised = {d.platform for d in jax.devices()}
    assert initialised == {"cpu"}, f"non-CPU default platform: {initialised}"
print("BACKENDS_OK", sorted(initialised))
"""


def _driver_source() -> str:
    return _DRYRUN_DRIVER.replace("@REPO@", repr(REPO))


def test_dryrun_multichip_never_touches_accelerator_backend():
    """dryrun_multichip(8) must complete using only the CPU backend, even
    when an accelerator plugin is registered as the default platform."""
    proc = subprocess.run(
        [sys.executable, "-c", _driver_source()],
        capture_output=True, text=True, timeout=600,
        cwd=REPO,
    )
    assert proc.returncode == 0, (
        f"dryrun subprocess failed\nstdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    )
    assert "BACKENDS_OK ['cpu']" in proc.stdout
    assert "dryrun_multichip(8): OK" in proc.stdout


def test_dryrun_multichip_with_poisoned_accelerator_runtime():
    """Simulate the r03 driver failure mode: point the TPU runtime library at
    a nonexistent file so that *any* TPU-plugin initialisation would crash,
    and verify the dryrun still completes on CPU.

    Note: the poison only bites in environments where a TPU PJRT plugin is
    registered (like this repo's axon container); elsewhere this reduces to
    the backend-registry check of the previous test — the registry assertion
    there is the environment-independent guard."""
    poisoned = (
        "import os\n"
        "os.environ['TPU_LIBRARY_PATH'] = '/nonexistent/libtpu.so'\n"
        + _driver_source()
    )
    proc = subprocess.run(
        [sys.executable, "-c", poisoned],
        capture_output=True, text=True, timeout=600,
        cwd=REPO,
    )
    assert proc.returncode == 0, (
        f"dryrun subprocess failed under poisoned runtime\n"
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    )
    assert "dryrun_multichip(8): OK" in proc.stdout


@pytest.mark.parametrize("n_devices", [2])
def test_dryrun_multichip_device_counts(n_devices):
    """The dryrun must work for any power-of-two device count the driver
    picks, not just the 8 the other tests cover."""
    body = _driver_source().replace(
        "dryrun_multichip(8)", f"dryrun_multichip({n_devices})").replace(
        "device_count=8", f"device_count={n_devices}")
    proc = subprocess.run(
        [sys.executable, "-c", body],
        capture_output=True, text=True, timeout=600,
        cwd=REPO,
    )
    assert proc.returncode == 0, (
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    )
    assert f"dryrun_multichip({n_devices}): OK" in proc.stdout
