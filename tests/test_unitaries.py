"""Unitary-gate correctness vs the analytic oracle, mirroring the reference's
test_unitaries.cpp (37 TEST_CASEs).  Every test runs on a 5-qubit statevector
AND a 5-qubit density matrix (debug-state initialised), on an unsharded and an
8-device-sharded backend (see conftest), comparing all amplitudes within
10x REAL_EPS — the reference's exact pattern (tests/test_unitaries.cpp:46-89).
"""

from __future__ import annotations

import itertools

import numpy as np
import pytest

import quest_tpu as qt
from generators import (bitsets, pauliseqs, sublists, subsets,
                        target_control_cases)
from oracle import (DM_TOL, NUM_QUBITS, H, I2, X, Y, Z, apply_to_dm,
                    apply_to_sv, assert_dm, assert_sv, dm, full_operator,
                    phase_shift, random_unitary, rot, sv)

N = NUM_QUBITS


def _prepared(env):
    psi = qt.createQureg(N, env)
    rho = qt.createDensityQureg(N, env)
    qt.initDebugState(psi)
    qt.initDebugState(rho)
    return psi, rho, sv(psi), dm(rho)


def _check(env, apply_quest, targets, u, controls=(), control_states=None,
           kind="both"):
    """Apply through quest_tpu and the oracle on both register kinds (or one,
    for exhaustive sweeps that alternate kinds to halve runtime)."""
    if kind in ("both", "sv"):
        psi = qt.createQureg(N, env)
        qt.initDebugState(psi)
        ref_psi = sv(psi)
        apply_quest(psi)
        assert_sv(psi, apply_to_sv(ref_psi, N, targets, u, controls, control_states))
    if kind in ("both", "dm"):
        rho = qt.createDensityQureg(N, env)
        qt.initDebugState(rho)
        ref_rho = dm(rho)
        apply_quest(rho)
        assert_dm(rho, apply_to_dm(ref_rho, N, targets, u, controls, control_states))


def _all_pairs():
    return [(a, b) for a in range(N) for b in range(N) if a != b]


# exhaustive generator-driven arrangements (ref: utilities.hpp sublists —
# every ordered arrangement at 5 qubits); replaces the old hand-picked tuples
_ALL_PAIRS = sublists(range(N), 2)            # all 20 ordered (a, b)
_ALL_TRIPLES = sublists(range(N), 3)          # all 60 ordered (a, b, c)


# ---------------------------------------------------------------------------
# single-qubit dense gates
# ---------------------------------------------------------------------------

def test_compactUnitary(env):
    alpha, beta = 0.3 - 0.4j, 0.74 + 0.46j
    norm = np.sqrt(abs(alpha) ** 2 + abs(beta) ** 2)
    alpha, beta = alpha / norm, beta / norm
    u = np.array([[alpha, -np.conj(beta)], [beta, np.conj(alpha)]])
    for t in range(N):
        _check(env, lambda q, t=t: qt.compactUnitary(q, t, alpha, beta), [t], u)
    psi = qt.createQureg(N, env)
    with pytest.raises(qt.QuESTError, match="Invalid target"):
        qt.compactUnitary(psi, N, alpha, beta)
    with pytest.raises(qt.QuESTError, match="not unitary"):
        qt.compactUnitary(psi, 0, 1.0, 1.0)


def test_unitary(env):
    u = random_unitary(1)
    for t in range(N):
        _check(env, lambda q, t=t: qt.unitary(q, t, u), [t], u)
    psi = qt.createQureg(N, env)
    with pytest.raises(qt.QuESTError, match="Invalid target"):
        qt.unitary(psi, -1, u)
    with pytest.raises(qt.QuESTError, match="not unitary"):
        qt.unitary(psi, 0, u + 1.0)


def test_rotateX(env):
    theta = 0.6
    for t in range(N):
        _check(env, lambda q, t=t: qt.rotateX(q, t, theta), [t], rot([1, 0, 0], theta))


def test_rotateY(env):
    theta = -1.2
    for t in range(N):
        _check(env, lambda q, t=t: qt.rotateY(q, t, theta), [t], rot([0, 1, 0], theta))


def test_rotateZ(env):
    theta = 2.1
    for t in range(N):
        _check(env, lambda q, t=t: qt.rotateZ(q, t, theta), [t], rot([0, 0, 1], theta))


def test_rotateAroundAxis(env):
    theta, axis = 0.9, (1.0, -2.0, 0.5)
    for t in range(N):
        _check(env, lambda q, t=t: qt.rotateAroundAxis(q, t, theta, axis),
               [t], rot(axis, theta))
    psi = qt.createQureg(N, env)
    with pytest.raises(qt.QuESTError, match="non-zero"):
        qt.rotateAroundAxis(psi, 0, theta, (0.0, 0.0, 0.0))


def test_pauliX(env):
    for t in range(N):
        _check(env, lambda q, t=t: qt.pauliX(q, t), [t], X)


def test_pauliY(env):
    for t in range(N):
        _check(env, lambda q, t=t: qt.pauliY(q, t), [t], Y)


def test_pauliZ(env):
    for t in range(N):
        _check(env, lambda q, t=t: qt.pauliZ(q, t), [t], Z)


def test_hadamard(env):
    for t in range(N):
        _check(env, lambda q, t=t: qt.hadamard(q, t), [t], H)


def test_sGate(env):
    for t in range(N):
        _check(env, lambda q, t=t: qt.sGate(q, t), [t], np.diag([1, 1j]))


def test_tGate(env):
    for t in range(N):
        _check(env, lambda q, t=t: qt.tGate(q, t), [t],
               np.diag([1, np.exp(1j * np.pi / 4)]))


def test_phaseShift(env):
    theta = 0.8
    for t in range(N):
        _check(env, lambda q, t=t: qt.phaseShift(q, t, theta), [t], phase_shift(theta))


# ---------------------------------------------------------------------------
# controlled single-qubit gates
# ---------------------------------------------------------------------------

def test_controlledNot(env):
    for c, t in _ALL_PAIRS:
        _check(env, lambda q, c=c, t=t: qt.controlledNot(q, c, t), [t], X, [c])
    psi = qt.createQureg(N, env)
    with pytest.raises(qt.QuESTError, match="equal target"):
        qt.controlledNot(psi, 1, 1)
    with pytest.raises(qt.QuESTError, match="Invalid control"):
        qt.controlledNot(psi, N, 0)


def test_controlledPauliY(env):
    for c, t in _ALL_PAIRS:
        _check(env, lambda q, c=c, t=t: qt.controlledPauliY(q, c, t), [t], Y, [c])


def test_controlledPhaseFlip(env):
    for c, t in _ALL_PAIRS:
        _check(env, lambda q, c=c, t=t: qt.controlledPhaseFlip(q, c, t), [t], Z, [c])


def test_controlledPhaseShift(env):
    theta = 1.7
    for c, t in _ALL_PAIRS:
        _check(env, lambda q, c=c, t=t: qt.controlledPhaseShift(q, c, t, theta),
               [t], phase_shift(theta), [c])


def test_controlledCompactUnitary(env):
    alpha, beta = (0.6 + 0.1j), (-0.2 + 0.77j)
    norm = np.sqrt(abs(alpha) ** 2 + abs(beta) ** 2)
    alpha, beta = alpha / norm, beta / norm
    u = np.array([[alpha, -np.conj(beta)], [beta, np.conj(alpha)]])
    for c, t in _ALL_PAIRS:
        _check(env, lambda q, c=c, t=t: qt.controlledCompactUnitary(q, c, t, alpha, beta),
               [t], u, [c])


def test_controlledUnitary(env):
    u = random_unitary(1)
    for c, t in _ALL_PAIRS:
        _check(env, lambda q, c=c, t=t: qt.controlledUnitary(q, c, t, u), [t], u, [c])


def test_controlledRotateX(env):
    theta = 0.4
    for c, t in _ALL_PAIRS:
        _check(env, lambda q, c=c, t=t: qt.controlledRotateX(q, c, t, theta),
               [t], rot([1, 0, 0], theta), [c])


def test_controlledRotateY(env):
    theta = 1.1
    for c, t in _ALL_PAIRS:
        _check(env, lambda q, c=c, t=t: qt.controlledRotateY(q, c, t, theta),
               [t], rot([0, 1, 0], theta), [c])


def test_controlledRotateZ(env):
    theta = -0.9
    for c, t in _ALL_PAIRS:
        _check(env, lambda q, c=c, t=t: qt.controlledRotateZ(q, c, t, theta),
               [t], rot([0, 0, 1], theta), [c])


def test_controlledRotateAroundAxis(env):
    theta, axis = -2.0, (0.5, 1.0, -1.5)
    for c, t in _ALL_PAIRS:
        _check(env,
               lambda q, c=c, t=t: qt.controlledRotateAroundAxis(q, c, t, theta, axis),
               [t], rot(axis, theta), [c])


def test_multiControlledUnitary(env):
    u = random_unitary(1)
    cases = [(cs, t) for t in range(N)
             for k in range(1, N) for cs in subsets(range(N), k, exclude=(t,))]
    for i, (ctrls, t) in enumerate(cases):
        _check(env,
               lambda q, cs=ctrls, t=t: qt.multiControlledUnitary(q, list(cs), len(cs), t, u),
               [t], u, list(ctrls), kind="sv" if i % 2 else "dm")
    psi = qt.createQureg(N, env)
    with pytest.raises(qt.QuESTError, match="unique"):
        qt.multiControlledUnitary(psi, [0, 0], 2, 1, u)
    with pytest.raises(qt.QuESTError, match="include target"):
        qt.multiControlledUnitary(psi, [0, 1], 2, 0, u)


def test_multiStateControlledUnitary(env):
    u = random_unitary(1)
    cases = []
    for c, t in sublists(range(N), 2):
        for states in bitsets(1):
            cases.append(((c,), states, t))
    for i, (targs, _) in enumerate(target_control_cases(N, 1, max_ctrls=0)):
        pats = bitsets(2)
        cs = sublists(range(N), 2, exclude=targs)
        cases.append((cs[i % len(cs)], pats[i % len(pats)], targs[0]))
    for i, (ctrls, states, t) in enumerate(cases):
        _check(env,
               lambda q, cs=ctrls, ss=states, t=t:
                   qt.multiStateControlledUnitary(q, list(cs), list(ss), len(cs), t, u),
               [t], u, list(ctrls), list(states), kind="sv" if i % 2 else "dm")


# ---------------------------------------------------------------------------
# swaps
# ---------------------------------------------------------------------------

_SWAP = np.array([[1, 0, 0, 0], [0, 0, 1, 0], [0, 1, 0, 0], [0, 0, 0, 1]],
                 dtype=complex)
_SQRT_SWAP = np.array([[1, 0, 0, 0],
                       [0, (1 + 1j) / 2, (1 - 1j) / 2, 0],
                       [0, (1 - 1j) / 2, (1 + 1j) / 2, 0],
                       [0, 0, 0, 1]], dtype=complex)


def test_swapGate(env):
    for a, b in _ALL_PAIRS:
        _check(env, lambda q, a=a, b=b: qt.swapGate(q, a, b), [a, b], _SWAP)
    psi = qt.createQureg(N, env)
    with pytest.raises(qt.QuESTError, match="unique"):
        qt.swapGate(psi, 0, 0)


def test_sqrtSwapGate(env):
    for a, b in _ALL_PAIRS:
        _check(env, lambda q, a=a, b=b: qt.sqrtSwapGate(q, a, b), [a, b], _SQRT_SWAP)


# ---------------------------------------------------------------------------
# multi-qubit phase gates
# ---------------------------------------------------------------------------

def test_multiControlledPhaseFlip(env):
    for qs in [qs for k in range(2, N + 1) for qs in subsets(range(N), k)]:
        # a phase flip on all-1s of the group: diag with -1 at the last entry
        u = np.eye(1 << len(qs), dtype=complex)
        u[-1, -1] = -1
        _check(env, lambda q, qs=qs: qt.multiControlledPhaseFlip(q, list(qs), len(qs)),
               list(qs), u)


def test_multiControlledPhaseShift(env):
    theta = 0.77
    for qs in [qs for k in range(2, N + 1) for qs in subsets(range(N), k)]:
        u = np.eye(1 << len(qs), dtype=complex)
        u[-1, -1] = np.exp(1j * theta)
        _check(env,
               lambda q, qs=qs: qt.multiControlledPhaseShift(q, list(qs), len(qs), theta),
               list(qs), u)


def test_multiRotateZ(env):
    theta = 1.3
    for qs in [qs for k in range(1, N + 1) for qs in subsets(range(N), k)]:
        # exp(-i theta/2 Z x..x Z): diagonal phase by parity of the group bits
        dim = 1 << len(qs)
        diag = np.array([np.exp(-1j * theta / 2 * (1 - 2 * (bin(i).count("1") % 2)))
                         for i in range(dim)])
        _check(env, lambda q, qs=qs: qt.multiRotateZ(q, list(qs), len(qs), theta),
               list(qs), np.diag(diag))


def test_multiRotatePauli(env):
    theta = 0.67
    paulis = [I2, X, Y, Z]
    cases = [(qs, (1, 3)) for qs in sublists(range(N), 2)]
    cases += [((1, 3), codes) for codes in pauliseqs(2)]
    cases += [((1, 3, 4), (1, 2, 3)), ((0, 1, 2), (3, 3, 1)), ((0,), (2,))]
    for qs, codes in cases:
        # exp(-i theta/2 sigma_1 x .. x sigma_k), with codes[j] acting on
        # qs[j]; an ALL-identity string applies nothing (the reference skips
        # the empty rotation mask, omitting the global phase —
        # QuEST_common.c:436-437)
        if all(c == 0 for c in codes):
            u = np.eye(1 << len(qs), dtype=complex)
        else:
            op = np.array([[1.0]], dtype=complex)
            for c in reversed(codes):  # qs[0] = least significant row bit
                op = np.kron(op, paulis[c])
            u = (np.cos(theta / 2) * np.eye(1 << len(qs))
                 - 1j * np.sin(theta / 2) * op)
        _check(env,
               lambda q, qs=qs, cs=codes: qt.multiRotatePauli(q, list(qs), list(cs),
                                                              len(qs), theta),
               list(qs), u)


# ---------------------------------------------------------------------------
# multi-qubit dense gates
# ---------------------------------------------------------------------------

def test_twoQubitUnitary(env):
    u = random_unitary(2)
    for t1, t2 in _ALL_PAIRS:
        _check(env, lambda q, a=t1, b=t2: qt.twoQubitUnitary(q, a, b, u), [t1, t2], u)
    psi = qt.createQureg(N, env)
    with pytest.raises(qt.QuESTError, match="not unitary"):
        qt.twoQubitUnitary(psi, 0, 1, np.ones((4, 4)))


def test_controlledTwoQubitUnitary(env):
    u = random_unitary(2)
    cases = []
    for i, (t1, t2) in enumerate(sublists(range(N), 2)):
        rest = [q for q in range(N) if q not in (t1, t2)]
        cases.append((rest[i % len(rest)], (t1, t2)))
    for c, (t1, t2) in cases:
        _check(env, lambda q, c=c, a=t1, b=t2: qt.controlledTwoQubitUnitary(q, c, a, b, u),
               [t1, t2], u, [c])


def test_multiControlledTwoQubitUnitary(env):
    u = random_unitary(2)
    for (t1, t2), cs in target_control_cases(N, 2, max_ctrls=3):
        if not cs:
            continue
        _check(env,
               lambda q, cs=cs, a=t1, b=t2:
                   qt.multiControlledTwoQubitUnitary(q, list(cs), len(cs), a, b, u),
               [t1, t2], u, list(cs))


def _max_dense_targets(env):
    """Like the reference, dense-matrix batches must fit in one device's shard
    (ref: validateMultiQubitMatrixFitsInNode, QuEST_validation.c:437)."""
    shard_amps = (1 << N) // env.num_ranks
    return shard_amps.bit_length() - 1


def test_multiQubitUnitary(env):
    kmax = _max_dense_targets(env)
    all_targs = [t for k in range(1, 4) for t in sublists(range(N), k)]
    all_targs.append((1, 3, 4, 0))
    for i, targs in enumerate(all_targs):
        if len(targs) > kmax:
            continue
        u = random_unitary(len(targs))
        _check(env, lambda q, ts=targs, u=u: qt.multiQubitUnitary(q, list(ts), len(ts), u),
               list(targs), u, kind="sv" if i % 2 else "dm")
    psi = qt.createQureg(N, env)
    with pytest.raises(qt.QuESTError, match="unique"):
        qt.multiQubitUnitary(psi, [0, 0], 2, random_unitary(2))
    if kmax < N:
        with pytest.raises(qt.QuESTError, match="cannot all fit"):
            qt.multiQubitUnitary(psi, list(range(kmax + 1)), kmax + 1,
                                 random_unitary(kmax + 1))


def test_controlledMultiQubitUnitary(env):
    kmax = _max_dense_targets(env)
    cases = []
    for k in (1, 2):
        for i, targs in enumerate(sublists(range(N), k)):
            rest = [q for q in range(N) if q not in targs]
            cases.append((rest[i % len(rest)], targs))
    cases.append((0, (2, 3, 4)))
    for c, targs in cases:
        if len(targs) > kmax:
            continue
        u = random_unitary(len(targs))
        _check(env,
               lambda q, c=c, ts=targs, u=u:
                   qt.controlledMultiQubitUnitary(q, c, list(ts), len(ts), u),
               list(targs), u, [c])


def test_multiControlledMultiQubitUnitary(env):
    kmax = _max_dense_targets(env)
    cases = [(cs, ts) for k in (1, 2)
             for ts, cs in target_control_cases(N, k, max_ctrls=3) if cs]
    cases.append(((0,), (1, 2, 3)))
    for cs, targs in cases:
        if len(targs) > kmax:
            continue
        u = random_unitary(len(targs))
        _check(env,
               lambda q, cs=cs, ts=targs, u=u:
                   qt.multiControlledMultiQubitUnitary(q, list(cs), len(cs),
                                                       list(ts), len(ts), u),
               list(targs), u, list(cs))
    psi = qt.createQureg(N, env)
    with pytest.raises(qt.QuESTError, match="disjoint"):
        qt.multiControlledMultiQubitUnitary(psi, [0, 1], 2, [1, 2], 2, random_unitary(2))


def test_wide_minor_gate_refuses_oversized_expansion(env_local):
    """A dense gate too wide to expand and with no free prefix qubits to
    reroute onto must raise the reference's fits-in-node error
    (ref: QuEST_validation.c:144) rather than build an oversized matrix."""
    import jax.numpy as jnp
    from quest_tpu.ops.apply import apply_matrix

    n = 12
    k = 11  # slots = 7 lane + 3 sublane + 1 prefix = 11 > _EXPAND_CAP
    state = jnp.zeros((2, 1 << n), dtype=jnp.float32).at[0, 0].set(1.0)
    mat = jnp.zeros((2, 1 << k, 1 << k), dtype=jnp.float32)
    with pytest.raises(qt.QuESTError, match="cannot all fit"):
        apply_matrix(state, mat, tuple(range(k)))


def test_pallas_lane_kernel_matches_xla(env_local):
    """The hand-written Pallas lane-block kernel (QUEST_TPU_PALLAS=1 eager
    path) agrees with the XLA engine (interpret mode on CPU, Mosaic on TPU)."""
    import jax.numpy as jnp
    from quest_tpu.ops import apply as ap
    from quest_tpu.ops import pallas_kernels as pk

    n = 11
    u = random_unitary(2)  # applied at lane-block targets (2, 3)
    rng = np.random.default_rng(3)
    state = jnp.asarray(rng.normal(size=(2, 1 << n)), dtype=jnp.float32)
    ref = ap.apply_matrix(state, jnp.asarray(ap.mat_pair(u), jnp.float32), (2, 3))
    pk.use_pallas(True)
    try:
        out = ap.apply_matrix(state, jnp.asarray(ap.mat_pair(u), jnp.float32), (2, 3))
    finally:
        pk.use_pallas(False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
