"""REAL multi-process distribution: 2 OS processes, one global device mesh.

The reference's distribution is multi-process by construction
(ref: QuEST_cpu_distributed.c:129-160 MPI_Init; run under SLURM by
examples/submissionScripts/mpi_SLURM_unit_tests.sh).  The JAX equivalent is
``jax.distributed.initialize``: every process contributes its local CPU
devices to one global mesh and executes the same SPMD program.  This test
launches 2 local processes (4 virtual CPU devices each — an 8-device global
mesh), runs a sharded circuit with cross-shard gates and a global reduction,
and round-trips the state through utils/checkpoint.py — executing its
``jax.process_count() > 1`` branches (lowest-owner dedup + the two
sync_global_devices barriers), which no single-process test can reach.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import os, sys
pid = int(sys.argv[1]); port = sys.argv[2]; ckpt = sys.argv[3]
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ.pop("JAX_PLATFORMS", None)
sys.path.insert(0, @REPO@)

import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(coordinator_address="127.0.0.1:" + port,
                           num_processes=2, process_id=pid)
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8, len(jax.devices())
assert len(jax.local_devices()) == 4

import numpy as np
import quest_tpu as qt
from quest_tpu.utils.checkpoint import load_qureg, save_qureg

env = qt.createQuESTEnv(num_devices=8)
n = 10
q = qt.createQureg(n, env)
qt.initPlusState(q)
# cross-shard work: the top 3 qubits are sharded on an 8-device mesh
qt.hadamard(q, n - 1)
qt.controlledNot(q, 0, n - 1)
qt.rotateY(q, n - 2, 0.37)
total = qt.calcTotalProb(q)
assert abs(total - 1.0) < 1e-10, total

# the eager sequence above must not have taken ANY corrective resharding
# pass: ops pin the env sharding inside their own programs (api._pinned)
from quest_tpu import qureg as qmod
assert qmod.REPIN_COUNT == 0, f"corrective reshards fired: {qmod.REPIN_COUNT}"

save_qureg(q, ckpt)
q2 = load_qureg(ckpt, env)

# the Qureg re-pins the env sharding after every op, so the state must
# still be distributed 8 ways (one window per device, 4 addressable here)
assert q.amps.sharding == q.env.sharding, q.amps.sharding
assert len(q.amps.addressable_shards) == 4

# verify the round-trip GLOBALLY with collective probes (both unit-norm +
# inner product 1 <=> identical states)
assert abs(qt.calcTotalProb(q2) - 1.0) < 1e-10
ip = qt.calcInnerProduct(q, q2)
assert abs(ip.real - 1.0) < 1e-12 and abs(ip.imag) < 1e-12, ip
for t in (0, n - 2, n - 1):
    a = qt.calcProbOfOutcome(q, t, 1)
    b = qt.calcProbOfOutcome(q2, t, 1)
    assert abs(a - b) < 1e-12, (t, a, b)

nshards = len(q2.amps.addressable_shards)
print("WORKER" + str(pid) + " OK local_shards=" + str(nshards))
"""


SEED_WORKER = r"""
import os, sys
pid = int(sys.argv[1]); port = sys.argv[2]
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ.pop("JAX_PLATFORMS", None)
sys.path.insert(0, @REPO@)

import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(coordinator_address="127.0.0.1:" + port,
                           num_processes=2, process_id=pid)

import quest_tpu as qt

# DEFAULT seeding only: createQuESTEnv broadcasts process 0's [msec, pid]
# seed to every process (ref: MPI_Bcast, QuEST_cpu_distributed.c:1318-1329).
# Neither worker calls seedQuEST.  Without the broadcast the two processes
# would seed from their own distinct PIDs and diverge.
env = qt.createQuESTEnv(num_devices=8)
n = 8
q = qt.createQureg(n, env)
qt.initPlusState(q)
outcomes = [qt.measure(q, t) for t in range(n)]
assert abs(qt.calcTotalProb(q) - 1.0) < 1e-10

# a fresh register and more draws: streams must stay in lockstep
q2 = qt.createQureg(n, env)
qt.initPlusState(q2)
for t in range(n):
    qt.hadamard(q2, t)
qt.initPlusState(q2)
outcomes += [qt.measure(q2, t) for t in range(0, n, 2)]
assert abs(qt.calcTotalProb(q2) - 1.0) < 1e-10
print("SEEDWORKER" + str(pid) + " OUTCOMES=" + "".join(map(str, outcomes)))
"""


#: jaxlib 0.4.36 cannot run cross-process computations on the CPU backend
#: ("Multiprocess computations aren't implemented on the CPU backend"):
#: both workers execute sharded programs over the 2-process global mesh,
#: so the whole scenario is stack-blocked — see docs/DESIGN.md "Known
#: stack regressions".  strict=False: a jaxlib restoring multi-process
#: CPU collectives turns these back into plain passes.
_MULTIPROC_CPU_XFAIL = pytest.mark.xfail(
    reason="multi-process CPU collectives unimplemented in jaxlib 0.4.36 "
           "— see docs/DESIGN.md 'Known stack regressions'",
    strict=False)


@_MULTIPROC_CPU_XFAIL
@pytest.mark.skipif(sys.platform != "linux", reason="needs local TCP coordinator")
def test_two_process_default_seed_broadcast(tmp_path):
    """Both processes, seeded only by the DEFAULT path, must draw identical
    measurement outcomes — the reference's seed-broadcast contract."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    src = tmp_path / "seed_worker.py"
    src.write_text(SEED_WORKER.replace("@REPO@", repr(REPO)))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen([sys.executable, str(src), str(pid), str(port)],
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                         text=True, cwd=REPO, env=env)
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-process workers timed out (coordinator hang?)")
        outs.append((p.returncode, out, err))
    seqs = []
    for pid, (rc, out, err) in enumerate(outs):
        assert rc == 0, f"worker {pid} failed\nstdout:\n{out}\nstderr:\n{err[-2000:]}"
        line = [l for l in out.splitlines() if l.startswith(f"SEEDWORKER{pid}")]
        assert line, out
        seqs.append(line[0].split("OUTCOMES=")[1])
    assert seqs[0] == seqs[1], f"divergent outcome streams: {seqs}"


@_MULTIPROC_CPU_XFAIL
@pytest.mark.skipif(sys.platform != "linux", reason="needs local TCP coordinator")
def test_two_process_distributed_checkpoint(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    ckpt = tmp_path / "ckpt"
    src = tmp_path / "worker.py"
    src.write_text(WORKER.replace("@REPO@", repr(REPO)))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen([sys.executable, str(src), str(pid), str(port), str(ckpt)],
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                         text=True, cwd=REPO, env=env)
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-process workers timed out (coordinator hang?)")
        outs.append((p.returncode, out, err))
    for pid, (rc, out, err) in enumerate(outs):
        assert rc == 0, f"worker {pid} failed\nstdout:\n{out}\nstderr:\n{err[-2000:]}"
        assert f"WORKER{pid} OK" in out

    # the checkpoint on disk is complete and process-0-authored where shared
    manifest = ckpt / "manifest.json"
    assert manifest.exists()
    import json
    meta = json.loads(manifest.read_text())
    assert meta["num_shards"] == 8
    files = sorted(f.name for f in ckpt.glob("shard_*.npy"))
    assert len(files) == 8
