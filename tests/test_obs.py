"""Observability-layer tests (quest_tpu/obs + its integration points):

- span recorder semantics: nesting/parent links, request-id propagation,
  the notes side channel, retroactive emission, and the disabled-path
  overhead contract (the serve bench row's <1% budget);
- end-to-end serve tracing: a drained workload exports Chrome-trace JSON
  that validates — every execution span linked to its request_id with class
  key / engine / cache outcome, zero orphans — with the obs counters
  re-exported through the service's Prometheus scrape;
- the flight recorder: ring bounds, E_QUEUE_FULL and execution-error dumps;
- the model-vs-measured ledger: collective-bound and wall-band drift rules
  (wall only judged on calibrated platforms) with O_MODEL_DRIFT warnings;
- the re-routed ``utils/profiling.circuit_stats`` (engine-aware fused pass
  counts; the 22-vs-420 QFT regression) and the purity lint's import-time
  atexit rule with its obs/trace.py allowlist.
"""

from __future__ import annotations

import json
import time

import numpy as np
import pytest

import quest_tpu as qt
from quest_tpu import obs


@pytest.fixture
def traced():
    """Tracing on around one test, reset and off afterwards (the recorder
    is the process singleton — leaks would couple tests)."""
    obs.enable_tracing()
    obs.reset_tracing()
    yield obs.recorder()
    obs.disable_tracing()
    obs.reset_tracing()


def _small_service(**kw):
    from quest_tpu.serve import CompileCache, QuESTService
    kw.setdefault("max_batch", 8)
    kw.setdefault("max_delay_ms", 5)
    kw.setdefault("cache", CompileCache())
    kw.setdefault("start", False)
    return QuESTService(**kw)


def _vqe(n=5, layers=1, seed=0):
    from quest_tpu.serve.selftest import vqe_ansatz
    return vqe_ansatz(n, layers, seed=seed)


# ---------------------------------------------------------------------------
# span recorder semantics
# ---------------------------------------------------------------------------

def test_span_nesting_and_parent_links(traced):
    with obs.span("outer", phase="a") as outer:
        with obs.span("inner") as inner:
            inner.attrs["found"] = 3
        assert inner.parent_id == outer.span_id
    spans = {s.name: s for s in traced.spans()}
    assert spans["outer"].parent_id is None
    assert spans["inner"].parent_id == spans["outer"].span_id
    assert spans["inner"].attrs["found"] == 3
    assert spans["outer"].attrs == {"phase": "a"}
    # children complete (and append) before their parents
    assert [s.name for s in traced.spans()] == ["inner", "outer"]
    assert spans["outer"].dur >= spans["inner"].dur >= 0.0


def test_request_id_propagation(traced):
    with obs.request(42):
        assert obs.current_request_id() == 42
        with obs.span("inside"):
            pass
    with obs.span("outside"):
        pass
    spans = {s.name: s for s in traced.spans()}
    assert spans["inside"].request_id == 42
    assert spans["outside"].request_id is None


def test_notes_side_channel(traced):
    obs.note("orphaned", 1)  # no scope open: silently dropped
    with obs.collect_notes() as notes:
        obs.note("cache_outcome", "hit")
        with obs.collect_notes() as inner:
            obs.note("cache_outcome", "miss")
        assert inner == {"cache_outcome": "miss"}
    assert notes == {"cache_outcome": "hit"}


def test_emit_span_retroactive(traced):
    t0 = time.perf_counter()
    sid = obs.emit_span("retro", t0=t0, dur=0.5, request_id=7, batch=3)
    sp = traced.spans()[0]
    assert sp.span_id == sid and sp.request_id == 7
    assert sp.dur == 0.5 and sp.attrs == {"batch": 3}


def test_recorder_bounded_drops_not_evicts():
    rec = obs.TraceRecorder(max_spans=4, enabled=True)
    for i in range(6):
        with rec.span(f"s{i}"):
            pass
    snap = rec.snapshot()
    assert snap["spans"] == 4 and snap["dropped"] == 2
    assert [s.name for s in rec.spans()] == ["s0", "s1", "s2", "s3"]


def test_overflow_never_orphans_recorded_children():
    """Children append before their parents; a full buffer must still
    admit a parent some recorded child references, and retroactive emits
    against a dropped parent are recorded as roots — the export stays
    orphan-free under any overflow."""
    rec = obs.TraceRecorder(max_spans=3, enabled=True)
    with rec.span("root"):
        with rec.span("mid"):
            for i in range(3):
                with rec.span(f"leaf{i}"):
                    pass
    # 3 leaves fill the buffer; mid and root are admitted anyway because
    # recorded spans reference them (bounded overshoot), and nothing
    # recorded points at a missing span
    names = [s.name for s in rec.spans()]
    assert "mid" in names and "root" in names
    assert rec.snapshot()["dropped"] == 0
    from quest_tpu.obs.export import chrome_trace, validate_chrome_trace
    assert validate_chrome_trace(chrome_trace(recorder=rec)) == []
    # an unreferenced span past the bound still drops...
    with rec.span("extra_root"):
        pass
    assert rec.snapshot()["dropped"] == 1
    assert validate_chrome_trace(chrome_trace(recorder=rec)) == []
    # ...and an emit naming a never-recorded parent is recorded as a ROOT
    # (unknown parents are rewritten, so no export can carry an orphan)
    rec2 = obs.TraceRecorder(max_spans=10, enabled=True)
    sid = rec2.emit("late", t0=0.0, dur=0.1, parent_id=99999)
    late = [s for s in rec2.spans() if s.span_id == sid][0]
    assert late.parent_id is None
    assert validate_chrome_trace(chrome_trace(recorder=rec2)) == []


def test_disabled_span_overhead_under_one_percent():
    """The serve bench row's contract: tracing DISABLED must cost < 1% of
    wall.  A request's serve path records ~10 spans; at 64 requests that is
    640 no-op entries against a >= 1 s CPU batch wall, so the per-call
    budget is generous — we assert each disabled span() costs < 5 us
    (measured typically ~0.3 us), i.e. < 3.2 ms per 64-request wave."""
    assert not obs.tracing_enabled()
    reps = 200_000
    t0 = time.perf_counter()
    for _ in range(reps):
        with obs.span("hot", attr=1):
            pass
    per_call = (time.perf_counter() - t0) / reps
    assert per_call < 5e-6, f"disabled span costs {per_call * 1e6:.2f}us"
    spans_per_request = 10
    assert per_call * spans_per_request * 64 < 0.01 * 1.0
    assert obs.recorder().snapshot()["spans"] == 0  # nothing recorded


# ---------------------------------------------------------------------------
# end-to-end serve tracing
# ---------------------------------------------------------------------------

def test_service_trace_end_to_end(traced):
    svc = _small_service()
    futs = [svc.submit(_vqe(seed=s)) for s in range(4)]
    futs += [svc.submit(qt.qft_circuit(4)) for _ in range(2)]
    svc.start()
    assert svc.drain(timeout=300)
    for f in futs:
        f.result(timeout=60)

    doc = obs.chrome_trace()
    assert obs.validate_chrome_trace(doc) == []
    events = doc["traceEvents"]
    execs = [e for e in events if e.get("name") == "serve.request"]
    assert len(execs) == 6
    by_rid = {e["args"]["request_id"]: e for e in execs}
    assert set(by_rid) == {0, 1, 2, 3, 4, 5}
    for e in execs:
        args = e["args"]
        assert args["engine"] == "xla"
        assert args["cache"] in ("hit", "miss")
        assert args["class_key"]
        assert args["batch"] >= 1
        assert e["ts"] >= 0 and e["dur"] > 0
    # exactly one miss per structural class, hits for the rest — the trace
    # agrees with the cache counters
    assert sum(1 for e in execs if e["args"]["cache"] == "miss") == 2
    # cache lookups and submits correlate to the same request ids
    lookups = [e for e in events if e.get("name") == "cache.lookup"
               and e["args"]["request_id"] is not None]
    assert {e["args"]["request_id"] for e in lookups} == set(by_rid)
    # every execution span parents into a serve.execute_batch span
    batches = {e["args"]["span_id"] for e in events
               if e.get("name") == "serve.execute_batch"}
    assert batches and all(e["args"]["parent_id"] in batches for e in execs)

    # flight recorder: every request resolved ok with its batch id
    flight = svc.flight_recorder.snapshot()
    assert flight["depth"] == 6 and flight["dumps"] == 0
    assert all(r["outcome"] == "ok" and r["batch_id"] >= 1
               and r["wait_s"] >= 0 for r in flight["records"])

    # the human report names every request
    report = obs.trace_report()
    for rid in by_rid:
        assert f"request {rid}" in report

    # one Prometheus scrape covers service metrics AND the obs counters
    from quest_tpu.serve.metrics import parse_prometheus
    parsed = parse_prometheus(svc.prometheus())
    assert "quest_serve_obs_trace_spans" in parsed
    assert "quest_serve_obs_flight_depth" in parsed
    assert parsed["quest_serve_obs_trace_enabled"][""] == 1
    assert svc.metrics_dict()["obs"]["flight_depth"] == 6
    svc.shutdown()


def test_orphan_and_missing_attr_detection():
    # the recorder itself can no longer produce an orphan (overflow keeps
    # referenced parents, emit rewrites unknown ones) — so feed the
    # validator a hand-built document, the shape an external producer or a
    # truncated file could present
    doc = {"traceEvents": [
        {"name": "serve.request", "ph": "X", "pid": 1, "tid": 1,
         "ts": 0.0, "dur": 1.0,
         "args": {"span_id": 1, "parent_id": 9999, "request_id": None}},
    ]}
    problems = obs.validate_chrome_trace(doc)
    assert any("orphan" in p for p in problems)
    assert any("request_id" in p for p in problems)
    assert any("class_key" in p.lower() for p in problems)


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

def test_flight_ring_is_bounded():
    from quest_tpu.obs import FlightRecorder
    fr = FlightRecorder(capacity=4)
    for i in range(10):
        fr.admit(i, "ck", queue_depth=i)
    recs = fr.records()
    assert len(recs) == 4
    assert [r.request_id for r in recs] == [6, 7, 8, 9]
    fr.resolve(2, "ok")            # rung out: ignored, no crash
    fr.resolve(9, "ok", batch_id=1, wait_s=0.1, exec_s=0.2)
    assert fr.records()[-1].outcome == "ok"
    dump = fr.dump("test")
    assert dump["reason"] == "test" and len(dump["records"]) == 4
    assert fr.last_dump is dump and fr.dumps == 1


def test_queue_full_dumps_flight_ring():
    from quest_tpu.validation import QuESTError
    svc = _small_service(max_queue=2)
    svc.submit(_vqe(seed=0))
    svc.submit(_vqe(seed=1))
    with pytest.raises(QuESTError) as err:
        svc.submit(_vqe(seed=2))
    assert err.value.code == "E_QUEUE_FULL"
    dump = svc.flight_recorder.last_dump
    assert dump is not None and dump["reason"] == "E_QUEUE_FULL"
    outcomes = [r["outcome"] for r in dump["records"]]
    assert outcomes.count("queue_full") == 1
    assert json.dumps(dump)  # dumps are JSON-serializable as-is
    # a bounce carries a distinct NEGATIVE id: it can never alias the
    # admitted request that gets the next real id
    bounced = [r for r in dump["records"] if r["outcome"] == "queue_full"]
    assert bounced[0]["request_id"] < 0
    admitted_ids = {r["request_id"] for r in dump["records"]
                    if r["admitted"]}
    assert bounced[0]["request_id"] not in admitted_ids
    svc.shutdown(drain=False)


def test_execution_error_resolves_and_dumps():
    n = 4
    svc = _small_service()
    # a zero initial state is unnormalisable: sampling raises inside the
    # worker — the error must reach the future AND the flight recorder
    fut = svc.submit(_vqe(n=n), shots=4,
                     initial_state=np.zeros((2, 1 << n)))
    svc.start()
    assert svc.drain(timeout=120)
    assert isinstance(fut.exception(timeout=60), ValueError)
    rec = svc.flight_recorder.records()[0]
    assert rec.outcome == "error:ValueError"
    assert svc.flight_recorder.last_dump["reason"] == "error:ValueError"
    svc.shutdown()


def test_partial_batch_failure_keeps_completed_outcomes():
    """A mid-batch sampling failure must not rewrite the flight outcome of
    requests whose results were already delivered: completed stays 'ok',
    only the failing request records the error."""
    n = 4
    good_state = np.zeros((2, 1 << n))
    good_state[0, 0] = 1.0
    svc = _small_service(max_delay_ms=200)
    ok_fut = svc.submit(_vqe(n=n), shots=0, initial_state=good_state)
    bad_fut = svc.submit(_vqe(n=n), shots=4,
                         initial_state=np.zeros((2, 1 << n)))
    svc.start()
    assert svc.drain(timeout=120)
    assert ok_fut.result(timeout=60) is not None
    assert isinstance(bad_fut.exception(timeout=60), ValueError)
    by_rid = {r.request_id: r for r in svc.flight_recorder.records()}
    assert by_rid[0].outcome == "ok"
    assert by_rid[1].outcome == "error:ValueError"
    assert svc.metrics.counter("requests_failed_total") == 1
    assert svc.metrics.counter("requests_completed_total") == 1
    svc.shutdown()


# ---------------------------------------------------------------------------
# model-vs-measured ledger
# ---------------------------------------------------------------------------

def test_ledger_collective_drift():
    led = obs.Ledger()
    with pytest.warns(RuntimeWarning, match="O_MODEL_DRIFT"):
        rec = led.record("r", predicted_collectives=2,
                         measured_hlo_collectives=13)
    assert len(rec.findings) == 1 and "undercosts" in rec.findings[0]
    ok = led.record("r2", predicted_collectives=2,
                    measured_hlo_collectives=12)   # at the 6x bound: fine
    assert ok.findings == ()
    with pytest.warns(RuntimeWarning):
        lost = led.record("r3", predicted_collectives=0,
                          measured_hlo_collectives=1)
    assert "comm-free" in lost.findings[0]
    assert led.snapshot() == {"records": 3, "drift_total": 2}


def test_ledger_wall_band_is_platform_gated():
    led = obs.Ledger()
    # CPU wall vs the TPU roofline: recorded, ratio computed, NOT judged
    rec = led.record("cpu", platform="cpu", predicted_seconds=1e-3,
                     measured_seconds=10.0)
    assert rec.wall_ratio == pytest.approx(1e4)
    assert not rec.wall_checked and rec.findings == ()
    # a TPU run out of band IS drift
    with pytest.warns(RuntimeWarning, match="re-calibrate"):
        bad = led.record("tpu", platform="tpu", predicted_seconds=1e-3,
                         measured_seconds=10.0)
    assert bad.wall_checked and len(bad.findings) == 1
    # calibrated=True opts any platform in; in-band stays clean
    good = led.record("calib", platform="cpu", calibrated=True,
                      predicted_seconds=1.0, measured_seconds=2.0)
    assert good.wall_checked and good.findings == ()


def test_trace_report_cli_17q_epoch_engine(capsys):
    """The obs-selftest CI contract in-process: the 17q QFT through the
    forced epoch engine records a clean ledger row (zero O_MODEL_DRIFT on
    CPU), >0 spans, and a valid Chrome-trace export."""
    from quest_tpu.analysis.__main__ import main
    assert main(["--qft", "17", "--engine", "pallas", "--trace-report",
                 "--no-hints", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert not any(d["code"] == "O_MODEL_DRIFT" for d in doc["diagnostics"])
    rep = doc["trace_report"][0]
    assert rep["engine"] == "pallas"
    assert rep["spans"] > 0
    assert rep["ledger"]["findings"] == []
    assert rep["ledger"]["predicted_hbm_passes"] == 1  # one fused pass
    assert obs.validate_chrome_trace(rep["chrome_trace"]) == []
    assert not obs.tracing_enabled()  # the CLI restored the prior state


@pytest.mark.slow
def test_ledger_22q_qft_x8_scheduled_row():
    """The acceptance row: bench's 22q QFT x8 scheduled pair records a
    model-vs-measured ledger entry (predicted model seconds + comm events
    vs measured wall + state-sized compiled collectives) with zero drift
    findings on the CPU mesh."""
    import jax

    import bench
    cpu = jax.devices("cpu")
    if len(cpu) < 8:
        pytest.skip("needs the 8-virtual-device CPU mesh")
    _value, cfg = bench.bench_sched_pair(qt.qft_circuit(22), cpu[:8])
    mvm = cfg["model_vs_measured"]
    assert mvm["label"] == "sched_pair_22q_x8"
    assert mvm["predicted_seconds"] > 0 and mvm["measured_seconds"] > 0
    assert mvm["predicted_collectives"] == cfg["predicted"][
        "comm_events_after"]
    assert mvm["measured_hlo_collectives"] is not None
    assert mvm["findings"] == ()           # collective bound holds; wall
    assert not mvm["wall_checked"]         # is not judged on a CPU mesh


# ---------------------------------------------------------------------------
# circuit_stats: engine-aware pass counts (the 22-vs-420 regression)
# ---------------------------------------------------------------------------

def test_circuit_stats_fused_qft28_matches_epoch_plan():
    from quest_tpu.ops.epoch_pallas import plan_circuit
    from quest_tpu.utils.profiling import circuit_stats
    c = qt.qft_circuit(28)
    st = circuit_stats(c)
    plan = plan_circuit(c.key(), 28)
    assert st.engine == "pallas"
    # the widened two-stream lowering: 1 block pass + 2 fiber-group packs
    # (was 22 under the narrow per-stage envelope, 420 per-op)
    assert st.hbm_passes == plan.hbm_passes == 3
    assert st.deferred_perm_ops == plan.deferred_ops == 14
    # the historical per-op model survives as the explicit fused=False mode
    old = circuit_stats(c, fused=False)
    assert old.hbm_passes == 420 and old.engine == "xla"
    # swaps are permutation traffic, not MXU contractions, in BOTH modes
    for stats in (st, old):
        assert stats.permutation_ops == 14
        assert stats.mxu_contractions == 28          # the H gates only
        assert stats.diagonal_ops == 378


def test_circuit_stats_widened_envelope_16q():
    """Satellite regression: a 16-qubit circuit must report the degenerate
    single-block geometry's fused count through the widened plan_circuit —
    ONE pass for the whole VQE ansatz — not the per-op model the old
    'n >= 17 floor' forced."""
    from quest_tpu.ops.epoch_pallas import plan_circuit
    from quest_tpu.serve.selftest import vqe_ansatz
    from quest_tpu.utils.profiling import circuit_stats
    c = vqe_ansatz(16, 2, seed=0)
    st = circuit_stats(c)
    plan = plan_circuit(c.key(), 16)
    assert st.engine == "pallas"
    assert st.hbm_passes == plan.hbm_passes == 1
    assert st.num_ops == len(c.ops) > 1


def test_circuit_stats_cross_group_mixed_window():
    """Satellite regression: cross-group 2q dense ops no longer inflate
    the stats with per-op XLA windows — the mixed window's fused count
    flows through the widened plan."""
    import numpy as np
    from quest_tpu.ops.epoch_pallas import plan_circuit
    from quest_tpu.utils.profiling import circuit_stats
    rng = np.random.default_rng(3)
    g = rng.normal(size=(4, 4)) + 1j * rng.normal(size=(4, 4))
    u, r = np.linalg.qr(g)
    u = u * (np.diag(r) / np.abs(np.diag(r)))
    c = qt.Circuit(20)
    c.h(0)
    c.multi_qubit_unitary((3, 12), u)    # straddles lane/fiber: decomposed
    c.cz(2, 8)
    c.h(18)                              # high qubit: pack stream
    st = circuit_stats(c)
    plan = plan_circuit(c.key(), 20)
    assert st.engine == "pallas"
    assert plan.xla_ops == 0
    assert st.hbm_passes == plan.hbm_passes < len(c.ops)


def test_circuit_stats_outside_envelope_and_mesh():
    from quest_tpu.utils.profiling import circuit_stats
    small = qt.qft_circuit(8)        # below the 10-qubit degenerate floor
    st = circuit_stats(small)
    assert st.engine == "xla" and st.hbm_passes == len(small.ops)
    sharded = circuit_stats(qt.qft_circuit(12), num_ranks=8)
    assert sharded.engine == "xla"   # meshes pin to the XLA engine
    assert sharded.hbm_passes == len(qt.qft_circuit(12).ops)
    assert sharded.cross_shard_ops > 0
    c = qt.Circuit(18)
    c.h(0).swap(0, 17)
    st2 = circuit_stats(c, fused=False)
    assert st2.permutation_ops == 1 and st2.mxu_contractions == 1


# ---------------------------------------------------------------------------
# purity lint: import-time atexit rule + the obs/trace.py allowlist
# ---------------------------------------------------------------------------

def test_purity_flags_import_time_atexit():
    from quest_tpu.analysis.purity import lint_source
    bad = "import atexit\n\ndef f():\n    pass\n\natexit.register(f)\n"
    found = lint_source(bad, "quest_tpu/somewhere.py")
    assert [d.code for d in found] == ["P_IMPORT_TIME_STATE_MUTATION"]
    ok = "import atexit\n\ndef install(f):\n    atexit.register(f)\n"
    assert lint_source(ok, "quest_tpu/somewhere.py") == []


def test_purity_allowlists_obs_trace_singleton():
    import os

    import quest_tpu.obs.trace as trace_mod
    from quest_tpu.analysis.purity import lint_paths
    path = trace_mod.__file__
    assert lint_paths([path]) == []
    # the allowlist is a path suffix: the same source elsewhere still trips
    from quest_tpu.analysis.purity import lint_source
    with open(path, encoding="utf-8") as fh:
        src = fh.read()
    found = lint_source(src, "other_pkg/trace.py")
    assert any(d.code == "P_IMPORT_TIME_STATE_MUTATION" for d in found)
    assert os.path.normpath(path).endswith(os.path.join("obs", "trace.py"))
