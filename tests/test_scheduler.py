"""The comm-aware circuit scheduler (parallel/scheduler.py) and its executor
hooks: commutation DAG soundness, scheduled-vs-unscheduled statevector
equivalence (the oracle the ISSUE demands), the QFT comm-savings acceptance
bar, bit-permutation kernels, reconcile cycle handling, the routed-executor
property test, and the compile/optimize contracts it rides along with."""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import quest_tpu as qt
from quest_tpu.circuit import (Circuit, compile_circuit, qft_circuit,
                               random_circuit)
from quest_tpu.ops import apply as ap
from quest_tpu.parallel import planner
from quest_tpu.parallel import scheduler as sched
from oracle import random_unitary


def _rand_state(n: int, seed: int = 0) -> jax.Array:
    rs = np.random.RandomState(seed)
    st = rs.randn(2, 1 << n)
    st /= np.sqrt((st ** 2).sum())
    return jnp.asarray(st, jnp.float64)


def _rich_circuit(n: int = 14, seed: int = 7) -> Circuit:
    """Every scheduler-relevant structure at once: wide reroute gates
    (shared and conflicting routings), diagonals/mrz sunk between them,
    controls, repeated cross-shard dense gates, and a trailing swap
    network."""
    rs = np.random.RandomState(seed)
    np.random.seed(seed)
    c = Circuit(n)
    c.multi_qubit_unitary((0, 8, 12), random_unitary(3))
    c.h(2)
    c.rz(n - 1, 0.31)
    c.multi_qubit_unitary((1, 9, 13), random_unitary(3))
    c.multi_qubit_unitary((0, 8, 12), random_unitary(3))
    c.multi_rotate_z(tuple(range(n - 2)), 0.7)
    c.x(3, (11,))
    c.y(5)
    for _ in range(3):
        c.multi_qubit_unitary((n - 2, n - 1), random_unitary(2))
    c.swap(0, n - 1)
    c.swap(1, n - 2)
    c.swap(2, n - 3)
    c.swap(3, n - 4)
    return c


# ---------------------------------------------------------------------------
# commutation DAG
# ---------------------------------------------------------------------------

def test_dag_diagonals_commute_through_controls():
    c = Circuit(4)
    c.h(0)                       # 0: dense on 0
    c.phase_shift(1, 0.3, controls=(0,))  # 1: diagonal on 0 and 1
    c.t(0)                       # 2: diagonal on 0
    c.z(1, controls=(0,))        # 3: diagonal on 0, 1
    c.h(0)                       # 4: dense on 0 again
    dag = sched.commutation_dag(c.ops)
    # diagonals depend only on the last dense op, not on each other
    assert dag.preds[1] == {0}
    assert dag.preds[2] == {0}
    assert dag.preds[3] == {0}
    # the closing dense op orders against every diagonal recorded since
    assert dag.preds[4] == {0, 1, 2, 3}


def test_dag_disjoint_wires_commute():
    c = Circuit(4).h(0).h(1).cnot(2, 3)
    dag = sched.commutation_dag(c.ops)
    assert all(not p for p in dag.preds)


def test_reorder_is_a_permutation_within_dag():
    c = _rich_circuit()
    out = sched.reorder_ops(c.ops, c.num_qubits, 8)
    assert sorted(map(id, out)) == sorted(map(id, c.ops))


# ---------------------------------------------------------------------------
# the equivalence oracle (ISSUE acceptance): scheduled == unscheduled
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("devices", [2, 4, 8])
def test_scheduled_random_circuits_equivalent(devices):
    for seed in range(3):
        c = random_circuit(10, depth=2, seed=seed)
        st = _rand_state(10, seed)
        want = np.asarray(compile_circuit(c)(st))
        got = np.asarray(compile_circuit(c, num_devices=devices)(st))
        np.testing.assert_allclose(got, want, atol=1e-12)


@pytest.mark.parametrize("devices", [2, 4, 8])
def test_scheduled_rich_circuit_equivalent(devices):
    c = _rich_circuit()
    st = _rand_state(c.num_qubits, devices)
    want = np.asarray(compile_circuit(c)(st))
    s = c.schedule(devices)
    assert s is not c and c.ops == _rich_circuit().ops  # input unmodified
    got = np.asarray(compile_circuit(s)(st))
    np.testing.assert_allclose(got, want, atol=1e-12)


def test_scheduled_qft_matches_unscheduled():
    c = qft_circuit(13)
    st = _rand_state(13, 3)
    want = np.asarray(compile_circuit(c)(st))
    got = np.asarray(compile_circuit(c.schedule(8))(st))
    np.testing.assert_allclose(got, want, atol=1e-12)


def test_bitperm_shadow_on_density_qureg(env_local):
    """bitperm ops must shadow correctly on the Choi-flattened density
    path: the column-side twin shifts the wires AND the dest payload by n
    (circuit.py _shadow_op's bitperm branch)."""
    from quest_tpu.circuit import GateOp
    n = 6
    c = Circuit(n).h(0).cnot(0, 2)
    # content 0 -> 2 -> 5 -> 0, as one fused permutation op
    c.ops.append(GateOp("bitperm", (0, 2, 5), (), (), (2.0, 5.0, 0.0), None))
    ref = Circuit(n).h(0).cnot(0, 2).swap(0, 2).swap(0, 5)  # same cycle
    rho = qt.createDensityQureg(n, env_local)
    want = qt.createDensityQureg(n, env_local)
    qt.apply_circuit(rho, c)
    qt.apply_circuit(want, ref)
    np.testing.assert_allclose(np.asarray(rho.amps), np.asarray(want.amps),
                               atol=1e-11)


def test_scheduled_swap_network_on_density_qureg(env_local):
    """A scheduled circuit whose swap network fused into bitperm + staging
    swaps must agree with the unscheduled circuit on a density register."""
    n = 6
    c = Circuit(n).h(0).cnot(0, n - 1)
    for q in range(3):
        c.swap(q, n - 1 - q)
    s = c.schedule(4)
    rho = qt.createDensityQureg(n, env_local)
    ref = qt.createDensityQureg(n, env_local)
    qt.apply_circuit(rho, s)
    qt.apply_circuit(ref, c)
    np.testing.assert_allclose(np.asarray(rho.amps), np.asarray(ref.amps),
                               atol=1e-11)


# ---------------------------------------------------------------------------
# comm savings (ISSUE acceptance bar)
# ---------------------------------------------------------------------------

def test_qft22_schedule_saves_20pct_collectives():
    """Acceptance: the scheduled 22q QFT over an 8-way mesh executes >= 20%
    fewer swap/reshard collectives than unscheduled, asserted via the
    comm_plan of the scheduled circuit."""
    c = qft_circuit(22)
    r = sched.schedule_savings(c, 8)
    assert r["comm_events_after"] <= 0.8 * r["comm_events_before"], r
    assert r["comm_bytes_after"] < r["comm_bytes_before"], r
    assert r["reshard_events_after"] < r["reshard_events_before"], r


def test_schedule_never_adds_comm_on_bench_workloads():
    for c in (qft_circuit(16), random_circuit(16, depth=2, seed=1)):
        for devices in (2, 8):
            r = sched.schedule_savings(c, devices)
            assert r["comm_events_after"] <= r["comm_events_before"], r
            assert r["comm_bytes_after"] <= r["comm_bytes_before"], r


def test_epoch_lowering_localises_repeated_cross_gates():
    """>= 3 dense gates on the same sharded targets get bracketed between
    two fused bitperms and run shard-local in between."""
    np.random.seed(0)
    n, devices = 14, 4  # local range [0, 12), prefix-local wires 10, 11
    c = Circuit(n)
    for _ in range(3):
        c.multi_qubit_unitary((n - 2, n - 1), random_unitary(2))
    s = c.schedule(devices)
    kinds = [op.kind for op in s.ops]
    assert kinds.count("bitperm") == 2, kinds
    plans = planner.comm_plan(s, devices)
    # the three dense gates are now comm-free; only the brackets communicate
    assert sum(p.comm != "none" for p in plans) == 2, plans
    st = _rand_state(n, 5)
    np.testing.assert_allclose(np.asarray(compile_circuit(s)(st)),
                               np.asarray(compile_circuit(c)(st)),
                               atol=1e-12)


@pytest.mark.parametrize("devices", [1, 2, 8])
def test_overlapping_swap_run_fusion_equivalent(devices):
    """Swap runs whose swaps SHARE wires compose into cycles (not just
    transpositions); the fused lowering must realise the exact net
    permutation."""
    n = 13
    c = Circuit(n).h(0)
    c.swap(0, 12)
    c.swap(12, 11)
    c.swap(11, 1)
    c.swap(2, 10)
    st = _rand_state(n, devices)
    want = np.asarray(compile_circuit(c)(st))
    got = np.asarray(compile_circuit(c.schedule(devices))(st))
    np.testing.assert_allclose(got, want, atol=1e-12)


def test_comm_summary_totals():
    c = qft_circuit(12)
    s = planner.comm_summary(c, 4)
    plans = planner.comm_plan(c, 4)
    assert s["ops"] == len(plans)
    assert s["comm_events"] == s["permute_events"] + s["reshard_events"]
    assert s["bytes_moved"] == sum(p.bytes_moved for p in plans)


# ---------------------------------------------------------------------------
# placement search
# ---------------------------------------------------------------------------

def test_placement_identity_when_uniform():
    """Uniformly hot wires (QFT: every qubit gets H + swap) must keep the
    identity placement — boundary permutations would be pure cost."""
    c = qft_circuit(14)
    assert sched.greedy_placement(c, 8) == tuple(range(14))


def test_placement_moves_hot_wire_off_the_sharded_range():
    """A circuit hammering one sharded wire with dense gates relabels it
    shard-local, and the placed circuit stays equivalent."""
    np.random.seed(1)
    n, devices = 13, 8  # sharded range: wires 10, 11, 12
    c = Circuit(n)
    for _ in range(12):
        c.unitary(n - 1, random_unitary(1))
    sigma = sched.greedy_placement(c, devices)
    assert sigma[n - 1] < planner.local_qubit_count(n, devices)
    s = c.schedule(devices)
    r = sched.schedule_savings(c, devices, scheduled=s)
    assert r["comm_events_after"] < r["comm_events_before"], r
    st = _rand_state(n, 2)
    np.testing.assert_allclose(np.asarray(compile_circuit(s)(st)),
                               np.asarray(compile_circuit(c)(st)),
                               atol=1e-12)


# ---------------------------------------------------------------------------
# bit-permutation kernel + reconcile cycles
# ---------------------------------------------------------------------------

def _apply_perm_oracle(st: np.ndarray, mapping: dict) -> np.ndarray:
    """numpy oracle: content of bit position w moves to mapping[w]."""
    n = int(st.shape[1]).bit_length() - 1
    idx = np.arange(1 << n)
    dst = np.zeros_like(idx)
    for b in range(n):
        dst |= ((idx >> b) & 1) << mapping.get(b, b)
    out = np.zeros_like(st)
    out[:, dst] = st
    return out


@pytest.mark.parametrize("mapping", [
    {10: 11, 11: 10},                      # prefix transposition
    {10: 11, 11: 12, 12: 10},              # prefix 3-cycle (transpose path)
    {0: 11, 11: 0},                        # minor<->prefix (swap fallback)
    {1: 3, 3: 8, 8: 11, 11: 1},            # mixed 4-cycle
])
def test_apply_bit_permutation_matches_oracle(mapping):
    n = 13
    st = np.asarray(_rand_state(n, sum(mapping)))
    wires = tuple(sorted(mapping))
    dests = tuple(mapping[w] for w in wires)
    got = np.asarray(ap.apply_bit_permutation(jnp.asarray(st), wires, dests))
    np.testing.assert_allclose(got, _apply_perm_oracle(st, mapping),
                               atol=1e-15)


@pytest.mark.parametrize("perm", [
    (1, 0, 2, 3, 4, 5, 6, 7, 8, 9, 11, 10, 12),        # 2-cycles
    (3, 1, 2, 0, 4, 5, 6, 7, 8, 9, 11, 12, 10),        # prefix 3-cycle
    (12, 1, 2, 3, 4, 5, 6, 7, 8, 0, 11, 10, 9),        # mixed 3+ cycles
])
def test_reconcile_perm_restores_logical_order(perm):
    """reconcile_perm on 3+ cycles (incl. the fused prefix-bitperm path):
    applying the permutation then reconciling is the identity."""
    n = len(perm)
    st = _rand_state(n, len(perm))
    # put logical bit q at physical position perm[q]
    moved = ap.apply_bit_permutation(
        st, tuple(range(n)), tuple(perm))
    got = np.asarray(ap.reconcile_perm(moved, tuple(perm)))
    np.testing.assert_allclose(got, np.asarray(st), atol=1e-15)


# ---------------------------------------------------------------------------
# routed-executor property test (ISSUE satellite): _run_ops_routed vs a
# non-routed per-gate reference, including non-identity trailing perms
# ---------------------------------------------------------------------------

def _eager_reference(st: jax.Array, ops) -> jax.Array:
    """Per-gate reference: every op through the eager engine (wide gates
    pay their swap-in/swap-out per gate — no routing deferral)."""
    from quest_tpu.circuit import _apply_one
    for op in ops:
        st = _apply_one(st, op)
    return st


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_routed_executor_property(seed):
    """Random circuits with wide minor-block gates: the deferred-routing
    whole-program path must equal the per-gate reference to f64 tolerance.
    The conflicting-routing gate pairs leave a non-identity perm with 3+
    cycles at the end of the op chain, exercising reconcile_perm's cycle
    handling."""
    np.random.seed(seed)
    rs = np.random.RandomState(seed)
    n = 14
    c = Circuit(n)
    wide = [(0, 8, 10), (1, 9, 11), (2, 8, 12)]
    for layer in range(3):
        c.multi_qubit_unitary(wide[layer % len(wide)], random_unitary(3))
        q = int(rs.randint(0, n))
        c.unitary(q, random_unitary(1))
        c.rz(int(rs.randint(0, n)), float(rs.randn()))
        if layer % 2:
            c.swap(int(rs.randint(0, n // 2)),
                   int(n // 2 + rs.randint(0, n // 2)))
    # end on a wide gate so the live perm is non-identity at reconcile time
    c.multi_qubit_unitary(wide[seed % len(wide)], random_unitary(3))
    st = _rand_state(n, 100 + seed)
    got = np.asarray(compile_circuit(c)(st))
    want = np.asarray(_eager_reference(st, c.key()))
    np.testing.assert_allclose(got, want, atol=1e-12)


def test_routed_perm_has_three_plus_cycle():
    """The conflicting wide gates in the property test really do leave a
    3+ cycle for reconcile_perm (not just transpositions)."""
    n = 14
    perm = tuple(range(n))
    st = _rand_state(n, 0)
    np.random.seed(0)
    u = random_unitary(3)
    for targets in ((0, 8, 10), (1, 9, 11), (2, 8, 12)):
        st, perm = ap.apply_matrix_routed(
            st, jnp.asarray(np.stack([u.real, u.imag])), targets, (), (),
            perm)
    mapping = {p: q for q, p in enumerate(perm) if p != q}
    cycles = ap._perm_cycles(mapping)
    assert any(len(cyc) >= 3 for cyc in cycles), (perm, cycles)


# ---------------------------------------------------------------------------
# schedule() input validation (ISSUE satellite): E_* codes, not silence
# ---------------------------------------------------------------------------

def test_schedule_rejects_unknown_kwargs():
    """Unknown kwargs raise the validation layer's E_INVALID_SCHEDULE_OPTION
    instead of silently proceeding (or a bare TypeError)."""
    from quest_tpu.validation import ErrorCode, QuESTError
    c = qft_circuit(6)
    with pytest.raises(QuESTError) as err:
        c.schedule(4, optimize_harder=True)
    assert err.value.code == ErrorCode.INVALID_SCHEDULE_OPTION
    assert "optimize_harder" in str(err.value)
    # the documented options still work
    assert c.schedule(4, placement=False, reorder=False).num_qubits == 6


@pytest.mark.parametrize("bad", [0, -1, 3, 12, True, 2.0, "8"])
def test_schedule_rejects_bad_num_devices(bad):
    """num_devices < 1, non-power-of-two, or non-integer raises
    E_INVALID_NUM_RANKS (the amplitude mesh halves the 2^n axis)."""
    from quest_tpu.validation import ErrorCode, QuESTError
    c = qft_circuit(6)
    with pytest.raises(QuESTError) as err:
        c.schedule(bad)
    assert err.value.code == ErrorCode.INVALID_NUM_RANKS


def test_schedule_accepts_valid_num_devices():
    c = qft_circuit(6)
    for devices in (1, 2, 4, 8):
        assert c.schedule(devices).num_qubits == 6


# ---------------------------------------------------------------------------
# ride-along contracts: donated-program cache, optimize() in-place fusion
# ---------------------------------------------------------------------------

def test_compile_donate_caches_program(monkeypatch):
    """compile_circuit(donate=True) must not rebuild its jitted program per
    call: two compiles of EQUAL circuits applied twice each trace once.
    Since PR 5 the donated program lives in the serve layer's structural
    compilation cache (quest_tpu/serve/cache.py), so the cache is cleared
    first — an equal-STRUCTURE circuit from another test would otherwise
    legitimately satisfy the trace with zero new traces."""
    import quest_tpu.circuit as circuit_mod
    from quest_tpu.serve.cache import global_cache

    global_cache().clear()
    circuit_mod._donated_program.cache_clear()
    traces = {"n": 0}
    real = circuit_mod._run_ops_routed

    def counting(state, ops, params=None, offsets=None):
        traces["n"] += 1
        return real(state, ops, params, offsets)

    monkeypatch.setattr(circuit_mod, "_run_ops_routed", counting)
    c1 = random_circuit(6, depth=2, seed=987_123)
    c2 = random_circuit(6, depth=2, seed=987_123)
    assert c1.key() == c2.key() and c1 is not c2
    run1 = compile_circuit(c1, donate=True)
    run2 = compile_circuit(c2, donate=True)

    def fresh():
        return jnp.zeros((2, 64), jnp.float64).at[0, 0].set(1.0)

    np.testing.assert_allclose(
        float(jnp.sum(np.asarray(run1(fresh())) ** 2)), 1.0, atol=1e-12)
    run1(fresh())
    run2(fresh())
    run2(fresh())
    assert traces["n"] == 1, f"donated program retraced {traces['n']} times"


def test_optimize_returns_self_and_invalidates_shadow(env_local):
    """optimize() mutates in place, returns self, and a density-matrix
    apply_circuit after fusion uses the FUSED ops (shadow cache rebuilt)."""
    n = 4
    c = Circuit(n).h(0).rz(0, 0.4).ry(0, -0.2).x(1).cnot(1, 2)
    rho = qt.createDensityQureg(n, env_local)
    qt.apply_circuit(rho, c)          # primes the shadow cache (pre-fusion)
    before_ops = list(c.ops)
    ret = c.optimize()
    assert ret is c                   # documented return-self contract
    assert getattr(c, "_shadow_cache", "unset") is None
    ref = qt.createDensityQureg(n, env_local)
    for op in before_ops:
        p = op.payload()
        if op.kind == "matrix":
            qt.multiQubitUnitary(ref, list(op.targets), len(op.targets),
                                 p[0] + 1j * p[1])
        elif op.kind == "diagonal":
            qt.multiQubitUnitary(ref, list(op.targets), len(op.targets),
                                 np.diag(p[0] + 1j * p[1]))
        elif op.kind == "x":
            if op.controls:
                qt.controlledNot(ref, op.controls[0], op.targets[0])
            else:
                qt.pauliX(ref, op.targets[0])
    rho2 = qt.createDensityQureg(n, env_local)
    qt.apply_circuit(rho2, c)         # must rebuild the shadow from fused ops
    assert c._shadow_cache is not None
    assert c._shadow_cache[1] == c.key()
    assert len(c._shadow_cache[2]) == 2 * len(c.ops)
    np.testing.assert_allclose(np.asarray(rho2.amps), np.asarray(ref.amps),
                               atol=1e-11)
