"""Translation validation of scheduler rewrites (analysis/equivalence.py).

Four layers, per the ISSUE:

1. Domain soundness: the dense-window evaluator matches the jax kernels
   bit-for-bit on every IR kind (the convention anchor), the Pauli domain
   conjugates Cliffords exactly, the phase-polynomial domain merges
   multiRotateZ symbolically at widths no dense check could touch.
2. Acceptance: every rewrite the SHIPPED scheduler performs — 22q QFT x8,
   randomized circuits, the rich scheduler-structure circuit, optimize()'s
   native fusion — verifies with zero diagnostics.
3. The adversarial mutation harness: seeded bugs injected into scheduler
   output (dropped op, swapped wire, wrong bitperm cycle, perturbed angle)
   are each flagged V_SEMANTICS_CHANGED.
4. The soundness oracle: across random scheduled+mutated circuits, the
   checker NEVER returns "proven equivalent" when an f64 statevector
   comparison disagrees (global-phase differences included).
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from quest_tpu.analysis import AnalysisCode, Severity, check_equivalence
from quest_tpu.analysis.equivalence import (_normalize_perms, _pauli_equiv,
                                            _window_unitary)
from quest_tpu.circuit import (Circuit, GateOp, compile_circuit, qft_circuit,
                               random_circuit)
from oracle import random_unitary


def codes(diags):
    return [d.code for d in diags]


def has_error(diags):
    return any(d.severity >= Severity.ERROR for d in diags)


def _rand_state(n: int, seed: int = 0) -> jax.Array:
    rs = np.random.RandomState(seed)
    st = rs.randn(2, 1 << n)
    st /= np.sqrt((st ** 2).sum())
    return jnp.asarray(st, jnp.float64)


def _states_agree(a: Circuit, b: Circuit, seed: int = 0,
                  atol: float = 1e-10) -> bool:
    st = _rand_state(a.num_qubits, seed)
    sa = np.asarray(compile_circuit(a)(st))
    sb = np.asarray(compile_circuit(b)(st))
    return bool(np.max(np.abs(sa - sb)) < atol)


# ---------------------------------------------------------------------------
# 1. domain soundness
# ---------------------------------------------------------------------------

def test_window_unitary_matches_kernels():
    """The dense-window evaluator and the jax kernels agree on every IR
    kind — the convention anchor the whole validator rests on."""
    from quest_tpu.circuit import _apply_one
    np.random.seed(0)
    n = 4
    st = np.asarray(_rand_state(n, 1))
    vec = st[0] + 1j * st[1]
    c = Circuit(n)
    c.multi_qubit_unitary((2, 0), random_unitary(2), controls=(3,),
                          control_states=(0,))
    c.h(1)
    c.x(0, controls=(2,))
    c.y(3)
    c.swap(1, 3)
    c.phase_shift(2, 0.7, controls=(0,))
    c.ops.append(GateOp("mrz", (0, 1, 3), (), (), (0.9,), None))
    c.ops.append(GateOp("y*", (2,), (1,), (1,)))
    for op in c.ops:
        got = np.asarray(_apply_one(jnp.asarray(st, jnp.float64), op))
        want = _window_unitary([op], list(range(n))) @ vec
        np.testing.assert_allclose(got[0] + 1j * got[1], want, atol=1e-12)


def test_normalize_perms_absorbs_swaps_and_bitperms():
    """bitperm cycle 0->2->5->0 equals swap(0,2);swap(0,5): both normalize
    to the same residual permutation with identical cores."""
    n = 6
    a = Circuit(n).h(0).cnot(0, 2)
    a.ops.append(GateOp("bitperm", (0, 2, 5), (), (), (2.0, 5.0, 0.0), None))
    b = Circuit(n).h(0).cnot(0, 2).swap(0, 2).swap(0, 5)
    core_a, perm_a = _normalize_perms(a.ops, n)
    core_b, perm_b = _normalize_perms(b.ops, n)
    assert perm_a == perm_b != tuple(range(n))
    assert [op for _, op in core_a] == [op for _, op in core_b]
    assert check_equivalence(a, b) == []


def test_ops_after_permutation_relabel():
    """An op recorded after a swap acts on post-swap positions: the
    normalizer must translate it — swap;H(0) == H(1);swap."""
    a = Circuit(3).swap(0, 1).h(0)
    b = Circuit(3).h(1)
    b.swap(0, 1)
    assert check_equivalence(a, b) == []
    # and the wrong translation is caught
    c = Circuit(3).h(0)
    c.swap(0, 1)
    assert has_error(check_equivalence(a, c))


def test_global_phase_is_not_dropped():
    """Z X = - X Z: same Pauli tableau, different unitary.  The dense
    window must refuse equivalence (the soundness case a sign-free
    stabilizer check would miss)."""
    a = Circuit(2).z(0).x(0)
    b = Circuit(2).x(0)
    b.z(0)
    diags = check_equivalence(a, b)
    assert AnalysisCode.SEMANTICS_CHANGED in codes(diags)
    assert not _states_agree(a, b)


def test_phase_polynomial_merges_wide_mrz():
    """Two multiRotateZ on 15 shared wires merge into one at the summed
    angle — provable ONLY in the phase-polynomial domain (2^15 dense is
    out of reach of the window limit)."""
    t = tuple(range(15))
    a = Circuit(16)
    a.ops.append(GateOp("mrz", t, (), (), (0.3,), None))
    a.ops.append(GateOp("mrz", t, (), (), (0.4,), None))
    b = Circuit(16)
    b.ops.append(GateOp("mrz", t, (), (), (0.7,), None))
    assert check_equivalence(a, b) == []
    bad = Circuit(16)
    bad.ops.append(GateOp("mrz", t, (), (), (0.8,), None))
    assert AnalysisCode.SEMANTICS_CHANGED in codes(check_equivalence(a, bad))


def test_phase_polynomial_commutes_rz_through_controls():
    """rz / controlled-phase reorderings verify through the diagonal
    domain without any dense work."""
    a = Circuit(4).rz(0, 0.3).phase_shift(1, 0.5, controls=(0,)).t(0)
    b = Circuit(4).t(0)
    b.phase_shift(1, 0.5, controls=(0,))
    b.rz(0, 0.3)
    assert check_equivalence(a, b) == []


def test_pauli_domain_decides_wide_clifford_window():
    """X(0) pushed through a 12-wire CNOT ladder becomes X on every wire:
    a connected, all-Clifford, wider-than-dense window.  The Pauli domain
    must prove the match (up to global phase -> V_UNVERIFIED_REGION
    warning, not an error) and refute a corrupted variant."""
    n = 12
    a = Circuit(n).x(0)
    for q in range(n - 1):
        a.cnot(q, q + 1)
    b = Circuit(n)
    for q in range(n - 1):
        b.cnot(q, q + 1)
    for q in range(n):
        b.x(q)
    ops_a = [(i, op) for i, op in enumerate(a.ops)]
    ops_b = [(i, op) for i, op in enumerate(b.ops)]
    assert _pauli_equiv([op for _, op in ops_a], [op for _, op in ops_b],
                        list(range(n))) is True
    diags = check_equivalence(a, b)
    assert not has_error(diags)
    assert codes(diags) in ([], [AnalysisCode.UNVERIFIED_REGION])
    # corrupt one wire of the image: tableau mismatch -> ERROR
    bad = Circuit(n)
    for q in range(n - 1):
        bad.cnot(q, q + 1)
    for q in range(n - 1):
        bad.x(q)
    assert AnalysisCode.SEMANTICS_CHANGED in codes(check_equivalence(a, bad))


# ---------------------------------------------------------------------------
# 2. acceptance: every shipped rewrite verifies
# ---------------------------------------------------------------------------

def test_shipped_scheduler_verifies_qft22_x8():
    """ISSUE acceptance: the scheduled 22q QFT x8 (the bench.py pair)
    verifies with ZERO diagnostics — proven equivalent, host-only."""
    c = qft_circuit(22)
    assert check_equivalence(c, c.schedule(8)) == []


@pytest.mark.parametrize("devices", [2, 4, 8])
def test_shipped_scheduler_verifies_random_circuits(devices):
    for seed in range(3):
        c = random_circuit(10, depth=2, seed=seed)
        assert check_equivalence(c, c.schedule(devices)) == []


def test_shipped_scheduler_verifies_rich_structure():
    """Every scheduler-relevant structure at once (the test_scheduler rich
    circuit): epoch lowering, placement, swap fusion, sunk diagonals."""
    from test_scheduler import _rich_circuit
    c = _rich_circuit()
    for devices in (2, 8):
        assert check_equivalence(c, c.schedule(devices)) == []


def test_optimize_fusion_verifies():
    """optimize()'s native gate fusion (merged payloads — nothing matches
    1:1) is proven by the dense-window domain."""
    pytest.importorskip("ctypes")
    c = random_circuit(6, depth=2, seed=3)
    before = Circuit(6)
    before.ops = list(c.ops)
    c.optimize()
    if len(c.ops) == len(before.ops):
        pytest.skip("native fusion library unavailable")
    assert check_equivalence(before, c) == []


def test_validate_schedule_env_hook(monkeypatch):
    """QUEST_TPU_VALIDATE_SCHEDULE=1 translation-validates inside
    schedule() and raises QuESTError V_SEMANTICS_CHANGED on a seeded
    scheduler bug."""
    from quest_tpu.parallel import scheduler as sched
    from quest_tpu.validation import QuESTError

    monkeypatch.setenv("QUEST_TPU_VALIDATE_SCHEDULE", "1")
    c = qft_circuit(12)
    s = c.schedule(8)  # clean pass validates silently
    assert len(s.ops) == len(c.ops)

    real = sched._fuse_swap_runs

    def buggy(ops, n, num_devices):
        out = real(ops, n, num_devices)
        return out[:-1]  # drop the last op: a classic rewrite bug

    monkeypatch.setattr(sched, "_fuse_swap_runs", buggy)
    with pytest.raises(QuESTError) as err:
        c.schedule(8)
    assert err.value.code == AnalysisCode.SEMANTICS_CHANGED


# ---------------------------------------------------------------------------
# 3. the adversarial mutation harness
# ---------------------------------------------------------------------------

def _mutate(ops: list, rng: np.random.RandomState, kind: str) -> list | None:
    """Inject one seeded scheduler bug into an op list; None if this op
    list has no site for the mutation kind."""
    ops = list(ops)
    if kind == "drop":
        victims = [i for i, op in enumerate(ops) if op.kind != "bitperm"]
        if not victims:
            return None
        del ops[victims[rng.randint(len(victims))]]
        return ops
    if kind == "wire":
        n = max(max(op.targets + op.controls, default=0) for op in ops) + 1
        for i in rng.permutation(len(ops)):
            op = ops[i]
            if op.kind == "bitperm" or not op.targets:
                continue
            used = set(op.targets) | set(op.controls)
            free = [q for q in range(n) if q not in used]
            if not free:
                continue
            j = rng.randint(len(op.targets))
            t = list(op.targets)
            t[j] = free[rng.randint(len(free))]
            ops[i] = GateOp(op.kind, tuple(t), op.controls,
                            op.control_states, op.matrix, op.shape)
            return ops
        return None
    if kind == "bitperm":
        for i, op in enumerate(ops):
            if op.kind == "bitperm" and len(op.targets) >= 2:
                dests = list(op.matrix)
                rolled = tuple(dests[1:] + dests[:1])  # wrong cycle
                if rolled == op.matrix:
                    continue
                ops[i] = GateOp(op.kind, op.targets, op.controls,
                                op.control_states, rolled, op.shape)
                return ops
        return None
    if kind == "angle":
        for i in rng.permutation(len(ops)):
            op = ops[i]
            if op.kind == "mrz":
                ops[i] = GateOp(op.kind, op.targets, op.controls,
                                op.control_states,
                                (float(op.matrix[0]) + 0.31,), op.shape)
                return ops
            if op.kind == "diagonal" and op.shape == (2, 2):
                p = op.payload()
                d = (p[0] + 1j * p[1]) * np.exp([0.0, 0.41j])
                dp = np.stack([d.real, d.imag])
                ops[i] = GateOp(op.kind, op.targets, op.controls,
                                op.control_states, tuple(dp.ravel()),
                                dp.shape)
                return ops
        return None
    raise AssertionError(kind)


@pytest.mark.parametrize("kind", ["drop", "wire", "bitperm", "angle"])
def test_mutation_harness_catches_injected_bugs(kind):
    """Every seeded bug class injected into real scheduler OUTPUT is
    flagged V_SEMANTICS_CHANGED, across circuits and seeds.  qft(16) x8 is
    the smallest QFT whose swap network fuses into a bitperm collective."""
    circuits = [qft_circuit(16), random_circuit(10, depth=2, seed=1)]
    caught = 0
    for ci, c in enumerate(circuits):
        s = c.schedule(8)
        for seed in range(3):
            rng = np.random.RandomState(100 * ci + seed)
            mutated_ops = _mutate(s.ops, rng, kind)
            if mutated_ops is None:
                continue
            bad = Circuit(c.num_qubits)
            bad.ops = mutated_ops
            diags = check_equivalence(c, bad)
            assert has_error(diags), (kind, ci, seed, codes(diags))
            caught += 1
    assert caught, f"no mutation site for {kind!r} in any test circuit"


def test_mutated_scheduler_pass_is_caught_end_to_end():
    """A bug injected into a scheduler PASS (not its output) is caught:
    placement relabeling applied without its entry permutation."""
    from quest_tpu.parallel import scheduler as sched
    c = Circuit(13)
    np.random.seed(1)
    for _ in range(12):
        c.unitary(12, random_unitary(1))
    s = sched.schedule(c, 8)
    assert check_equivalence(c, s) == []
    # strip the entry bitperm the placement search inserted
    assert s.ops[0].kind in ("bitperm", "swap")
    bad = Circuit(13)
    bad.ops = [op for op in s.ops[1:]]
    assert has_error(check_equivalence(c, bad))


# ---------------------------------------------------------------------------
# 4. the soundness oracle
# ---------------------------------------------------------------------------

def test_checker_never_passes_a_statevector_disagreement():
    """Across scheduled and randomly-mutated circuits: whenever the checker
    returns ZERO diagnostics ("proven equivalent"), the f64 statevectors
    agree.  The contrapositive — states differ => diagnostics — is the
    soundness contract; false ALARMS are allowed, silence is not."""
    n = 8
    kinds = ["drop", "wire", "angle", "bitperm", None]
    checked_equal = 0
    for seed in range(6):
        c = random_circuit(n, depth=2, seed=seed)
        s = c.schedule([2, 4, 8][seed % 3])
        rng = np.random.RandomState(seed)
        kind = kinds[seed % len(kinds)]
        ops = _mutate(s.ops, rng, kind) if kind else list(s.ops)
        if ops is None:
            ops = list(s.ops)
        cand = Circuit(n)
        cand.ops = ops
        diags = check_equivalence(c, cand)
        agree = _states_agree(c, cand, seed)
        assert not (diags == [] and not agree), \
            f"checker silently passed a semantic change (seed {seed})"
        if diags == []:
            checked_equal += 1
    assert checked_equal, "oracle never exercised the 'equivalent' verdict"
