"""syncQuESTEnv must be a REAL device barrier.

The reference's syncQuESTEnv is an MPI_Barrier + GPU sync
(ref: QuEST_cpu_distributed.c syncQuESTEnv, QuEST_gpu.cu:129).  On the JAX
stack the tempting implementation is ``block_until_ready()``, but through
remote-device tunnels that has been observed returning early; the
implementation therefore also performs a scalar readback per addressable
shard (the barrier bench.py trusts).  These tests pin that behaviour.
"""

from __future__ import annotations

import os
import time

import numpy as np
import pytest

import quest_tpu as qt

TEST_PLATFORM = os.environ.get("QUEST_TEST_PLATFORM", "cpu").lower()


def test_sync_covers_every_env_qureg():
    """sync walks every registered qureg and completes without error, and the
    readback path touches each shard of a sharded state."""
    env = qt.createQuESTEnv()
    a = qt.createQureg(6, env)
    b = qt.createDensityQureg(3, env)
    qt.hadamard(a, 0)
    qt.mixDephasing(b, 0, 0.1)
    qt.syncQuESTEnv(env)
    # after the barrier, host reads see the finished values (f32 on the
    # accelerator platform, f64 on the CPU test platform)
    tol = 1e-5 if TEST_PLATFORM == "tpu" else 1e-10
    assert abs(qt.calcTotalProb(a) - 1.0) < tol
    assert abs(qt.calcTotalProb(b) - 1.0) < tol


@pytest.mark.skipif(TEST_PLATFORM != "tpu",
                    reason="early-return behaviour only exists on the "
                           "tunneled accelerator stack")
def test_sync_actually_waits_on_accelerator():
    """Queue substantial device work, call syncQuESTEnv, and require that a
    subsequent scalar readback is near-instant: if sync returned early the
    pending work would still be draining and the readback would absorb it."""
    env = qt.createQuESTEnv()
    q = qt.createQureg(22, env)
    for d in range(3):
        for t in range(22):
            qt.rotateY(q, t, 0.01 * (d + 1))
    t0 = time.perf_counter()
    qt.syncQuESTEnv(env)
    sync_dt = time.perf_counter() - t0
    t0 = time.perf_counter()
    float(np.asarray(q.amps.addressable_shards[0].data.reshape(-1)[0]))
    readback_dt = time.perf_counter() - t0
    # the readback after a true barrier is one tiny RPC; if sync had
    # returned early it would inherit the queued gate work instead
    assert readback_dt < max(0.5, 0.25 * sync_dt), (
        f"post-sync readback took {readback_dt:.3f}s (sync {sync_dt:.3f}s) — "
        "syncQuESTEnv did not drain the device queue")
