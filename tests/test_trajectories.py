"""Quantum-trajectory noise simulation (quest_tpu/trajectories.py).

No reference analogue — the reference simulates noise only as density
matrices.  The independent check is exactly that: trajectory averages must
converge to the density-matrix result computed by the (oracle-validated)
density path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import quest_tpu as qt
from quest_tpu.models import tfim_hamiltonian
from quest_tpu.trajectories import (trajectory_expectation_fn,
                                    trajectory_state_fn)
from conftest import ON_ACCELERATOR


def _noisy_circuit():
    pc = qt.ParamCircuit(3)
    t = pc.params(3)
    pc.h(0).cnot(0, 1).rx(2, t[0])
    pc.dephase(0, 0.15)
    pc.depolarise(1, t[1])
    pc.damp(2, t[2])
    pc.two_qubit_dephase(0, 1, 0.1)
    pc.ry(1, 0.4)
    return pc


PARAMS = jnp.asarray([0.7, 0.2, 0.3])


def test_trajectory_average_matches_density(env_local):
    """E_traj[<psi|H|psi>] -> Tr(H rho): the statistical estimator agrees
    with the exact density evolution within a few standard errors."""
    pc = _noisy_circuit()
    h = tfim_hamiltonian(3)
    exact = float(qt.expectation_fn(pc, h, density=True)(PARAMS))
    est = float(trajectory_expectation_fn(pc, h, trajectories=4000)(
        jax.random.PRNGKey(0), PARAMS))
    assert est == pytest.approx(exact, abs=0.06)


def test_trajectory_density_reconstruction(env_local):
    """Averaged trajectory outer products reconstruct the full density
    matrix, not just one observable."""
    pc = _noisy_circuit()
    run = trajectory_state_fn(pc)
    shots = 3000
    keys = jax.random.split(jax.random.PRNGKey(7), shots)

    def outer(k):
        s = run(k, PARAMS)
        v_re, v_im = s[0], s[1]
        rr = jnp.outer(v_re, v_re) + jnp.outer(v_im, v_im)
        ri = jnp.outer(v_im, v_re) - jnp.outer(v_re, v_im)
        return rr, ri

    rr, ri = jax.vmap(outer)(keys)
    rho_est = np.asarray(jnp.mean(rr, 0)) + 1j * np.asarray(jnp.mean(ri, 0))

    rho_q = qt.createDensityQureg(3, qt.createQuESTEnv(1))
    state = qt.build_param_circuit(pc, density=True)(PARAMS, rho_q.amps)
    a = np.asarray(state)
    rho_exact = (a[0] + 1j * a[1]).reshape(8, 8).T
    assert np.abs(rho_est - rho_exact).max() < 0.05


def test_trajectory_norms_are_one(env_local):
    """Every sampled trajectory is a normalised pure state (the damping
    branches renormalise)."""
    pc = _noisy_circuit()
    run = trajectory_state_fn(pc)
    keys = jax.random.split(jax.random.PRNGKey(3), 64)
    states = jax.vmap(lambda k: run(k, PARAMS))(keys)
    norms = np.asarray(jnp.sum(states[:, 0] ** 2 + states[:, 1] ** 2, axis=1))
    np.testing.assert_allclose(norms, 1.0, atol=1e-4 if ON_ACCELERATOR else 1e-10)


def test_unitary_trajectories_are_deterministic(env_local):
    pc = qt.ParamCircuit(3)
    t = pc.param()
    pc.h(0).cnot(0, 1).rz(2, t)
    run = trajectory_state_fn(pc)
    p = jnp.asarray([0.3])
    s1 = np.asarray(run(jax.random.PRNGKey(1), p))
    s2 = np.asarray(qt.state_fn(pc)(p))
    np.testing.assert_allclose(s1, s2, atol=1e-4 if ON_ACCELERATOR else 1e-12)


def test_qureg_init_accepted_density_rejected(env_local):
    """init follows the sibling state_fn contract: a statevector Qureg's
    amplitudes are unwrapped; a density Qureg is rejected."""
    pc = qt.ParamCircuit(2)
    pc.dephase(0, 0.1)
    env = qt.createQuESTEnv(1)
    psi = qt.createQureg(2, env)
    qt.pauliX(psi, 1)
    run = trajectory_state_fn(pc, init=psi)
    s = np.asarray(run(jax.random.PRNGKey(0), jnp.zeros(0)))
    assert abs(s[0, 2]) == pytest.approx(1.0, abs=1e-6)  # still |10> up to phase
    with pytest.raises(ValueError, match="pure"):
        trajectory_state_fn(pc, init=qt.createDensityQureg(2, env))


def test_damping_jump_statistics(env_local):
    """Pure |1> under damping: the jump branch fires with probability p and
    leaves |0>; no-jump leaves |1>."""
    pc = qt.ParamCircuit(1)
    pc.x(0)
    pc.damp(0, 0.3)
    run = trajectory_state_fn(pc)
    keys = jax.random.split(jax.random.PRNGKey(11), 2000)
    states = jax.vmap(lambda k: run(k, jnp.zeros(0)))(keys)
    p0 = np.asarray(states[:, 0, 0] ** 2 + states[:, 1, 0] ** 2)
    # each trajectory is either |0> (jump) or |1>
    frac_jumped = float(np.mean(p0 > 0.5))
    assert frac_jumped == pytest.approx(0.3, abs=0.04)
