"""Data structures: registers, matrices, Hamiltonians, diagonal operators,
environments — mirroring the reference's test_data_structures.cpp
(21 TEST_CASEs)."""

from __future__ import annotations

import os

import numpy as np
import pytest

import quest_tpu as qt
from oracle import NUM_QUBITS, assert_sv, dm, sv

N = NUM_QUBITS
DIM = 1 << N


# ---------------------------------------------------------------------------
# complex scalar / matrix types
# ---------------------------------------------------------------------------

def test_fromComplex():
    c = qt.Complex(0.3, -0.5)
    assert qt.fromComplex(c) == 0.3 - 0.5j


def test_toComplex():
    assert qt.toComplex(1.5 + 2.5j) == 1.5 + 2.5j


def test_createComplexMatrixN():
    for k in (1, 3):
        m = qt.createComplexMatrixN(k)
        assert m.shape == (1 << k, 1 << k)
        assert np.all(m == 0)
    with pytest.raises(qt.QuESTError, match="Invalid number of qubits"):
        qt.createComplexMatrixN(0)


def test_initComplexMatrixN():
    m = qt.createComplexMatrixN(1)
    qt.initComplexMatrixN(m, [[1, 2], [3, 4]], [[5, 6], [7, 8]])
    assert m[0, 0] == 1 + 5j and m[1, 1] == 4 + 8j


def test_destroyComplexMatrixN():
    m = qt.createComplexMatrixN(2)
    qt.destroyComplexMatrixN(m)  # no-op for parity


def test_getStaticComplexMatrixN():
    m = qt.getStaticComplexMatrixN([[0, 1], [1, 0]], [[0, 0], [0, 0]])
    assert np.allclose(m, np.array([[0, 1], [1, 0]]))


# ---------------------------------------------------------------------------
# environment
# ---------------------------------------------------------------------------

def test_createQuESTEnv():
    env = qt.createQuESTEnv(1)
    assert env.num_ranks == 1
    with pytest.raises(qt.QuESTError, match="power-of-2"):
        qt.createQuESTEnv(3)


def test_destroyQuESTEnv():
    env = qt.createQuESTEnv(1)
    qt.destroyQuESTEnv(env)


def test_syncQuESTEnv():
    env = qt.createQuESTEnv(1)
    qt.syncQuESTEnv(env)


# ---------------------------------------------------------------------------
# quregs
# ---------------------------------------------------------------------------

def test_createQureg(env):
    q = qt.createQureg(N, env)
    assert not q.isDensityMatrix
    assert q.numQubitsRepresented == N
    assert q.num_amps_total == DIM
    expected = np.zeros(DIM)
    expected[0] = 1.0
    assert_sv(q, expected)
    with pytest.raises(qt.QuESTError, match="Invalid number of qubits"):
        qt.createQureg(0, env)
    if env.num_ranks > 1:
        with pytest.raises(qt.QuESTError, match="one amplitude per node"):
            qt.createQureg(1, env)


def test_createDensityQureg(env):
    q = qt.createDensityQureg(N, env)
    assert q.isDensityMatrix
    assert q.numQubitsRepresented == N
    assert q.num_amps_total == DIM * DIM
    rho = dm(q)
    assert rho[0, 0] == pytest.approx(1.0)
    assert np.abs(rho).sum() == pytest.approx(1.0)


def test_createCloneQureg(env):
    src = qt.createQureg(N, env)
    qt.hadamard(src, 0)
    qt.rotateY(src, 2, 0.4)
    clone = qt.createCloneQureg(src, env)
    assert np.allclose(sv(clone), sv(src))
    assert clone.numQubitsRepresented == src.numQubitsRepresented


def test_destroyQureg(env):
    q = qt.createQureg(N, env)
    qt.destroyQureg(q, env)
    assert q.amps is None


# ---------------------------------------------------------------------------
# PauliHamil
# ---------------------------------------------------------------------------

def test_createPauliHamil():
    h = qt.createPauliHamil(3, 4)
    assert h.num_qubits == 3 and h.num_sum_terms == 4
    assert h.pauli_codes.shape == (4, 3)
    assert np.all(h.term_coeffs == 0)
    with pytest.raises(qt.QuESTError, match="strictly positive"):
        qt.createPauliHamil(0, 1)
    with pytest.raises(qt.QuESTError, match="strictly positive"):
        qt.createPauliHamil(1, 0)


def test_destroyPauliHamil():
    h = qt.createPauliHamil(2, 2)
    qt.destroyPauliHamil(h)


def test_initPauliHamil():
    h = qt.createPauliHamil(2, 2)
    qt.initPauliHamil(h, [0.5, -1.5], [0, 1, 2, 3])
    assert np.allclose(h.term_coeffs, [0.5, -1.5])
    assert np.all(h.pauli_codes == [[0, 1], [2, 3]])
    with pytest.raises(qt.QuESTError, match="Invalid Pauli code"):
        qt.initPauliHamil(h, [1.0, 1.0], [0, 1, 2, 4])


def test_createPauliHamilFromFile(tmp_path):
    fn = tmp_path / "hamil.txt"
    fn.write_text("0.5 0 1 2\n-1.0 3 0 1\n")
    h = qt.createPauliHamilFromFile(str(fn))
    assert h.num_qubits == 3 and h.num_sum_terms == 2
    assert np.allclose(h.term_coeffs, [0.5, -1.0])
    assert np.all(h.pauli_codes == [[0, 1, 2], [3, 0, 1]])
    bad = tmp_path / "bad.txt"
    bad.write_text("0.5 0 1 9\n")
    with pytest.raises(qt.QuESTError, match="invalid pauli code"):
        qt.createPauliHamilFromFile(str(bad))
    with pytest.raises(qt.QuESTError, match="Could not open file"):
        qt.createPauliHamilFromFile(str(tmp_path / "missing.txt"))


# ---------------------------------------------------------------------------
# DiagonalOp
# ---------------------------------------------------------------------------

def test_createDiagonalOp(env):
    op = qt.createDiagonalOp(N, env)
    assert op.num_qubits == N
    assert np.asarray(op.amps).shape == (2, DIM)
    with pytest.raises(qt.QuESTError, match="Invalid number of qubits"):
        qt.createDiagonalOp(0, env)


def test_destroyDiagonalOp(env):
    op = qt.createDiagonalOp(N, env)
    qt.destroyDiagonalOp(op, env)
    assert op.amps is None


def test_initDiagonalOp(env):
    op = qt.createDiagonalOp(N, env)
    re = np.arange(DIM, dtype=float)
    im = -np.arange(DIM, dtype=float)
    qt.initDiagonalOp(op, re, im)
    a = np.asarray(op.amps)
    assert np.allclose(a[0], re) and np.allclose(a[1], im)
    with pytest.raises(qt.QuESTError, match="Invalid number of elements"):
        qt.initDiagonalOp(op, re[:3], im[:3])


def test_setDiagonalOpElems(env):
    op = qt.createDiagonalOp(N, env)
    qt.setDiagonalOpElems(op, 4, [1.0, 2.0], [3.0, 4.0], 2)
    a = np.asarray(op.amps)
    assert a[0][4] == 1.0 and a[1][5] == 4.0
    with pytest.raises(qt.QuESTError, match="More elements"):
        qt.setDiagonalOpElems(op, DIM - 1, [1.0, 2.0], [3.0, 4.0], 2)


def test_syncDiagonalOp(env):
    op = qt.createDiagonalOp(N, env)
    qt.syncDiagonalOp(op)  # device-resident already; must not fail
