"""Distribution building blocks, checkpointing, and the algorithm library."""

from __future__ import annotations

import numpy as np
import pytest

import quest_tpu as qt
from quest_tpu.models import (bernstein_vazirani_circuit, ghz_circuit,
                              grover_circuit, phase_estimation_circuit,
                              trotter_circuit)
from quest_tpu.parallel import (comm_plan, gather_full_state, global_sum,
                                is_shard_local, pairwise_exchange)
from quest_tpu.utils import load_qureg, save_qureg
from oracle import (NUM_QUBITS, SV_TOL, assert_sv, random_statevector,
                    set_sv, sv)

N = NUM_QUBITS


# ---------------------------------------------------------------------------
# parallel
# ---------------------------------------------------------------------------

def test_pairwise_exchange(env_dist):
    q = qt.createQureg(N, env_dist)
    qt.initDebugState(q)
    before = np.asarray(q.amps).copy()
    out = pairwise_exchange(q.amps, env_dist.mesh, distance=1)
    got = np.asarray(out)
    # device d's window now holds device d^1's window
    shard = before.shape[1] // 8
    for d in range(8):
        np.testing.assert_array_equal(
            got[:, d * shard:(d + 1) * shard],
            before[:, (d ^ 1) * shard:((d ^ 1) + 1) * shard])


def test_global_sum(env_dist):
    q = qt.createQureg(N, env_dist)
    qt.initPlusState(q)
    total = float(global_sum(q.amps ** 2, env_dist.mesh))
    assert total == pytest.approx(1.0, abs=1e-12)


def test_gather_full_state(env_dist):
    q = qt.createQureg(N, env_dist)
    qt.initDebugState(q)
    full = gather_full_state(q.amps, env_dist.mesh)
    np.testing.assert_allclose(np.asarray(full), np.asarray(q.amps))


def test_is_shard_local():
    # 10 qubits over 8 devices: 7 local qubits per shard
    assert is_shard_local(6, 10, 8)
    assert not is_shard_local(7, 10, 8)
    assert is_shard_local(9, 10, 1)


def test_comm_plan():
    c = qt.Circuit(10).h(0).h(9).phase_shift(9, 0.3).swap(0, 9)
    plans = comm_plan(c, num_devices=8)
    assert [p.comm for p in plans] == ["none", "permute", "none", "reshard"]
    assert plans[1].bytes_moved == (1 << 10) // 8 * 8


# ---------------------------------------------------------------------------
# checkpoint
# ---------------------------------------------------------------------------

def test_checkpoint_roundtrip(env, tmp_path):
    vec = random_statevector(N)
    q = qt.createQureg(N, env)
    set_sv(q, vec)
    save_qureg(q, str(tmp_path / "ckpt"))
    q2 = load_qureg(str(tmp_path / "ckpt"), env)
    assert_sv(q2, vec)
    assert not q2.is_density_matrix


def test_checkpoint_density(env, tmp_path):
    q = qt.createDensityQureg(3, env)
    qt.hadamard(q, 0)
    qt.mixDamping(q, 0, 0.2)
    ref = np.asarray(q.amps).copy()
    save_qureg(q, str(tmp_path / "dm"))
    q2 = load_qureg(str(tmp_path / "dm"), env)
    np.testing.assert_allclose(np.asarray(q2.amps), ref)
    assert q2.is_density_matrix


# ---------------------------------------------------------------------------
# models / algorithms
# ---------------------------------------------------------------------------

def test_ghz_circuit(env):
    q = qt.createQureg(N, env)
    qt.apply_circuit(q, ghz_circuit(N))
    v = sv(q)
    s = 1 / np.sqrt(2)
    expected = np.zeros(1 << N, dtype=complex)
    expected[0] = s
    expected[-1] = s
    np.testing.assert_allclose(v, expected, atol=1e-12)


def test_bernstein_vazirani(env):
    secret = 0b1011
    q = qt.createQureg(6, env)
    qt.apply_circuit(q, bernstein_vazirani_circuit(6, secret))
    prob = 1.0
    bits = secret
    for qb in range(1, 6):
        prob *= qt.calcProbOfOutcome(q, qb, bits & 1)
        bits >>= 1
    assert prob == pytest.approx(1.0, abs=1e-12)


def test_grover(env):
    n, marked = 4, 0b1010
    q = qt.createQureg(n, env)
    qt.apply_circuit(q, grover_circuit(n, marked))
    probs = np.abs(sv(q)) ** 2
    assert probs.argmax() == marked
    assert probs[marked] > 0.9


def test_phase_estimation(env):
    m, phase = 4, 5 / 16  # exactly representable in 4 bits
    q = qt.createQureg(m + 1, env)
    qt.apply_circuit(q, phase_estimation_circuit(m, phase))
    probs = np.abs(sv(q)) ** 2
    # eval register (qubits 0..m-1) should read the phase numerator; qubit m=1
    best = probs.argmax()
    assert (best >> m) & 1 == 1
    # the QFT convention may bit-reverse; accept the numerator either way
    read = best & ((1 << m) - 1)
    rev = int(format(read, f"0{m}b")[::-1], 2)
    assert 5 in (read, rev)


def test_trotter_circuit_matches_api(env):
    np.random.seed(23)
    num_terms = 3
    codes = np.random.randint(0, 4, size=(num_terms, N))
    coeffs = np.random.randn(num_terms)
    hamil = qt.createPauliHamil(N, num_terms)
    qt.initPauliHamil(hamil, coeffs, codes.ravel())
    vec = random_statevector(N)
    q1 = qt.createQureg(N, env)
    q2 = qt.createQureg(N, env)
    set_sv(q1, vec)
    set_sv(q2, vec)
    qt.applyTrotterCircuit(q1, hamil, 0.3, 2, 3)
    qt.apply_circuit(q2, trotter_circuit(hamil, 0.3, 2, 3))
    np.testing.assert_allclose(sv(q2), sv(q1), atol=1e-10)


def test_checkpoint_cross_mesh_restore(env_local, env_dist, tmp_path):
    """A checkpoint written under one sharding restores onto a different mesh
    (dist8 -> local and local -> dist8), shard-by-shard with no full-state
    host buffer (load_qureg assembles per-device slices from memory-mapped
    shard files)."""
    vec = random_statevector(8)
    q = qt.createQureg(8, env_dist)
    set_sv(q, vec)
    save_qureg(q, str(tmp_path / "a"))
    q2 = load_qureg(str(tmp_path / "a"), env_local)      # 8 shards -> 1
    np.testing.assert_allclose(sv(q2), vec, atol=1e-12)

    q3 = qt.createQureg(8, env_local)
    set_sv(q3, vec)
    save_qureg(q3, str(tmp_path / "b"))
    q4 = load_qureg(str(tmp_path / "b"), env_dist)       # 1 shard -> 8
    np.testing.assert_allclose(sv(q4), vec, atol=1e-12)
    assert len(q4.amps.sharding.device_set) == 8


def test_init_state_from_single_file(env, tmp_path):
    fn = tmp_path / "state.txt"
    fn.write_text("# comment line\n0.6, 0.0\n0.0, 0.8\n" + "0.0, 0.0\n" * 30)
    q = qt.createQureg(5, env)
    assert qt.initStateFromSingleFile(q, str(fn)) == 1
    np.testing.assert_allclose(sv(q)[:2], [0.6, 0.8j], atol=1e-12)
    assert qt.initStateFromSingleFile(q, str(tmp_path / "missing.txt")) == 0


def test_sync_quest_env_blocks_env_quregs(env):
    q = qt.createQureg(5, env)
    qt.hadamard(q, 0)
    qt.syncQuESTEnv(env)  # must not raise; blocks this env's quregs only
    assert qt.calcTotalProb(q) == pytest.approx(1.0, abs=10 * SV_TOL)


def test_circuit_stats():
    from quest_tpu.utils.profiling import circuit_stats
    c = qt.Circuit(6).h(0).cz(0, 1).s(5).x(4)
    st = circuit_stats(c, num_ranks=4)  # qubits 4,5 sharded
    assert st.num_ops == 4
    assert st.diagonal_ops == 2          # cz records as controlled diagonal, s
    assert st.mxu_contractions == 2      # h, x
    assert st.cross_shard_ops == 2       # s(5), x(4)


def test_distributed_qft_example_runs():
    """examples/distributed_qft.py — the TPU-native distributed showcase —
    runs end-to-end on the virtual mesh and concentrates QFT(|+..+>) on |0>."""
    import os
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env_vars = dict(os.environ)
    env_vars["PYTHONPATH"] = root
    env_vars.pop("QUEST_TEST_PLATFORM", None)
    env_vars.pop("QUEST_EXAMPLE_REAL_MESH", None)
    # pin the virtual mesh width regardless of ambient XLA_FLAGS
    env_vars["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    r = subprocess.run(
        [sys.executable, os.path.join(root, "examples", "distributed_qft.py")],
        capture_output=True, text=True, timeout=580, env=env_vars)
    assert r.returncode == 0, r.stderr[-500:]
    assert "amplitude of |0...0>: +1.000000" in r.stdout
    assert "8 x cpu devices" in r.stdout or "tpu devices" in r.stdout


@pytest.mark.xfail(
    reason="multi-process CPU collectives unimplemented in jaxlib 0.4.36 "
           "(the rehearsal's seed broadcast is the first to hit it) — see "
           "docs/DESIGN.md 'Known stack regressions'",
    strict=False)
def test_multihost_example_rehearsal():
    """examples/multihost_example.py --rehearse: the pod submission-script
    code path (jax.distributed.initialize + one env over the global mesh)
    as 2 local processes (ref analogue:
    examples/submissionScripts/mpi_SLURM_example.sh's mpirun launch)."""
    import os
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env_vars = dict(os.environ)
    env_vars.pop("XLA_FLAGS", None)  # workers pin their own device count
    env_vars.pop("QUEST_TEST_PLATFORM", None)
    r = subprocess.run(
        [sys.executable, os.path.join(root, "examples", "multihost_example.py"),
         "--rehearse"],
        capture_output=True, text=True, timeout=580, env=env_vars)
    assert r.returncode == 0, (r.stdout[-500:], r.stderr[-1000:])
    assert "processes=2 devices=8" in r.stdout
    assert "MODE=distributed NUMDEVICES=8" in r.stdout
    assert "OK" in r.stdout


# ---------------------------------------------------------------------------
# ICI time model (planner.time_model / project_random_circuit)
# ---------------------------------------------------------------------------

def test_time_model_gate_classes():
    """Local gates cost no comm; a cross-shard 1q gate's comm time equals
    one full shard over one ICI link; diagonals stay comm-free."""
    from quest_tpu.circuit import Circuit
    from quest_tpu.parallel.planner import V5E, time_model

    n, d = 20, 8
    c = Circuit(n)
    c.h(0)            # shard-local
    c.h(n - 1)        # cross-shard (top log2(8)=3 qubits sharded)
    c.z(n - 1)        # diagonal on a sharded qubit: comm-free
    times = time_model(c, d, V5E, precision=1)
    shard_bytes = (1 << n) // d * 8
    assert times[0].comm_s == 0.0
    assert times[1].comm_s == pytest.approx(
        shard_bytes / V5E.ici_link_bytes_per_sec)
    assert times[2].comm_s == 0.0
    assert all(t.compute_s > 0 for t in times)


def test_time_model_single_chip_matches_measured_rows():
    """The model's single-chip predictions reproduce the recorded bench
    rows within 25% (f32 is the calibration row; f64's efficiency comes
    from an independent config, so its agreement is a real check)."""
    from quest_tpu.circuit import random_circuit
    from quest_tpu.parallel.planner import V5E, time_model

    c = random_circuit(24, depth=1, seed=11)
    for precision, measured in ((1, 6.04e9), (2, 1.15e9)):
        t = sum(x.total_s for x in time_model(c, 1, V5E, precision))
        predicted = (1 << 24) * 24 / t
        assert predicted == pytest.approx(measured, rel=0.25), precision


def test_north_star_projection():
    """The BASELINE 34q/v5p-64/f64 north star clears 1e8 amps/s/chip in the
    calibrated model, and the published DESIGN.md numbers match the code."""
    from quest_tpu.parallel.planner import V5P, project_random_circuit

    p = project_random_circuit(34, 20, 64, V5P, precision=2)
    assert p["sharded_qubits"] == 6
    assert p["vs_1e8_target"] > 30  # DESIGN.md publishes 34x (serial model)
    assert p["layer_comm_seconds"] < p["layer_compute_seconds"]  # compute-bound
    f32 = project_random_circuit(34, 20, 64, V5P, precision=1)
    assert f32["amp_updates_per_sec_per_chip"] > p["amp_updates_per_sec_per_chip"]
