"""Compile-economics static checker (analysis/staticcheck.py + the
jaxpr_audit trace-diff helpers): every S_* rule gets its refutation-corpus
pair (the seeded bug must be flagged, the fixed twin must stay silent),
the waiver comments must waive only WITH a reason, the repo self-audit
must be clean, and the Layer-2 jaxpr diff must prove S_CLASS_NOT_CLOSED
on a deliberately payload-embedding (opaque/pallas) class while the
lifted equivalent of the SAME circuit passes.
"""

from __future__ import annotations

import pytest

from quest_tpu.analysis import staticcheck as sc
from quest_tpu.analysis.diagnostics import AnalysisCode, Severity
from quest_tpu.circuit import Circuit


def codes(diags):
    return [d.code for d in diags]


def audit(src):
    return sc.audit_source(src, "fixture.py")


# ---------------------------------------------------------------------------
# the refutation corpus: each rule flags its seeded bug, passes the twin
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("entry", sc.CORPUS, ids=[e["name"] for e in sc.CORPUS])
def test_corpus_bad_flagged(entry):
    found = audit(entry["bad"])
    assert entry["code"] in codes(found)
    assert all(d.severity == Severity.ERROR for d in found)


@pytest.mark.parametrize("entry", sc.CORPUS, ids=[e["name"] for e in sc.CORPUS])
def test_corpus_good_clean(entry):
    assert audit(entry["good"]) == []


def test_corpus_report_self_consistent():
    rows, diags = sc.corpus_report()
    assert diags == []
    assert len(rows) == len(sc.CORPUS)
    assert all(r["bad_flagged"] and r["good_clean"] for r in rows)


# ---------------------------------------------------------------------------
# waivers: a reasoned comment waives, an unreasoned one is refused
# ---------------------------------------------------------------------------

_LITERAL_ANGLE = (
    "def probe(c):\n"
    "    c.ry(0, 0.37){comment}\n"
)


def test_reasoned_waiver_silences():
    src = _LITERAL_ANGLE.format(
        comment="  # unlifted-ok: fixed probe angle, compiled once")
    assert audit(src) == []


def test_unreasoned_waiver_is_refused():
    src = _LITERAL_ANGLE.format(comment="  # unlifted-ok:")
    found = audit(src)
    assert codes(found) == [AnalysisCode.UNLIFTED_LITERAL]
    assert "UNREASONED" in found[0].message


def test_waiver_on_preceding_comment_line():
    src = ("def probe(c):\n"
           "    # unlifted-ok: fixed probe angle\n"
           "    c.ry(0, 0.37)\n")
    assert audit(src) == []


def test_wrong_family_waiver_does_not_waive():
    src = _LITERAL_ANGLE.format(comment="  # host-sync-ok: not this rule")
    assert codes(audit(src)) == [AnalysisCode.UNLIFTED_LITERAL]


# ---------------------------------------------------------------------------
# S_UNLIFTED_LITERAL edges
# ---------------------------------------------------------------------------

def test_int_literal_wire_args_not_flagged():
    # wires and control indices are structural ints, not payloads
    assert audit("def f(c):\n    c.cnot(0, 1)\n    c.rx(2, 1)\n") == []


def test_literal_arithmetic_flagged_but_names_exempt():
    flagged = audit("def f(c):\n    c.rz(0, 2.0 * 0.5)\n")
    assert codes(flagged) == [AnalysisCode.UNLIFTED_LITERAL]
    # an expression mentioning a NAME is data-bound: not provably literal
    assert audit("def f(c, theta):\n    c.rz(0, 2.0 * theta)\n") == []


def test_keyword_angle_flagged():
    found = audit("def f(c):\n    c.phase_shift(3, angle=0.25)\n")
    assert codes(found) == [AnalysisCode.UNLIFTED_LITERAL]


# ---------------------------------------------------------------------------
# S_RECOMPILE_HAZARD edges
# ---------------------------------------------------------------------------

def test_aot_lower_chain_not_flagged():
    src = ("import jax\n"
           "def build(spec):\n"
           "    return jax.jit(lambda s: s * 2.0).lower(spec).compile()\n")
    assert audit(src) == []


def test_int_static_arg_not_flagged():
    src = ("import jax\n"
           "from functools import partial\n"
           "@partial(jax.jit, static_argnames=('n',))\n"
           "def grow(state, n):\n"
           "    return state\n"
           "def use(state):\n"
           "    return grow(state, 4)\n")
    assert audit(src) == []


def test_unhashable_static_arg_flagged():
    src = ("import jax\n"
           "from functools import partial\n"
           "@partial(jax.jit, static_argnames=('wires',))\n"
           "def apply(state, wires):\n"
           "    return state\n"
           "def use(state):\n"
           "    return apply(state, [1, 2])\n")
    assert codes(audit(src)) == [AnalysisCode.RECOMPILE_HAZARD]


def test_static_argnums_resolved_to_float_arg():
    src = ("import jax\n"
           "from functools import partial\n"
           "@partial(jax.jit, static_argnums=(1,))\n"
           "def rot(state, angle):\n"
           "    return state\n"
           "def use(state):\n"
           "    return rot(state, 0.5)\n")
    assert codes(audit(src)) == [AnalysisCode.RECOMPILE_HAZARD]


# ---------------------------------------------------------------------------
# S_HOST_SYNC_IN_HOT_PATH edges
# ---------------------------------------------------------------------------

def test_hot_path_annotation_roots_custom_function():
    src = ("import numpy as np\n"
           "# hot-path\n"
           "def admit(req):\n"
           "    return np.asarray(req)\n")
    assert codes(audit(src)) == [AnalysisCode.HOST_SYNC_IN_HOT_PATH]


def test_worker_side_sync_not_flagged():
    src = ("import jax\n"
           "class Service:\n"
           "    def submit(self, req):\n"
           "        self._queue.append(req)\n"
           "    def _execute(self, req):\n"
           "        return jax.block_until_ready(req)\n")
    assert audit(src) == []


def test_item_call_on_hot_path_flagged():
    src = ("class Router:\n"
           "    def route(self, scores):\n"
           "        return scores.argmin().item()\n")
    assert codes(audit(src)) == [AnalysisCode.HOST_SYNC_IN_HOT_PATH]


# ---------------------------------------------------------------------------
# S_X64_PROMOTION edges
# ---------------------------------------------------------------------------

def test_np_pi_is_weak_and_exempt():
    src = ("import jax\n"
           "import numpy as np\n"
           "@jax.jit\n"
           "def phase(state):\n"
           "    return state * np.pi\n")
    assert audit(src) == []


def test_astype_float64_on_traced_param_flagged():
    src = ("import jax\n"
           "import jax.numpy as jnp\n"
           "@jax.jit\n"
           "def widen(state):\n"
           "    return state.astype(jnp.float64)\n")
    assert codes(audit(src)) == [AnalysisCode.X64_PROMOTION]


def test_np_call_outside_jit_not_flagged():
    src = ("import numpy as np\n"
           "def host_side(x):\n"
           "    return x * np.float64(2.0)\n")
    assert audit(src) == []


# ---------------------------------------------------------------------------
# the repo self-audit and the CLI wiring
# ---------------------------------------------------------------------------

def test_repo_self_audit_is_clean():
    report, found = sc.audit_package()
    errors = [d for d in found if d.severity >= Severity.ERROR]
    assert errors == [], "\n".join(d.format() for d in errors)
    # the known, deliberately-waived sites stay waived (examples demo
    # angles, calibration probes, submit-contract np.asarray casts)
    assert report["waived"] >= 13
    assert any("service.py" in h and "submit" in h
               for h in report["hot_path_functions"])


def test_cli_staticcheck_paths_gate(tmp_path):
    from quest_tpu.analysis.__main__ import main
    bad = tmp_path / "bad.py"
    bad.write_text(sc.CORPUS[0]["bad"])
    good = tmp_path / "good.py"
    good.write_text(sc.CORPUS[0]["good"])
    assert main(["--staticcheck-paths", str(bad)]) == 1
    assert main(["--staticcheck-paths", str(good)]) == 0


# ---------------------------------------------------------------------------
# Layer 2: the traced-served-class audit (jaxpr diff)
# ---------------------------------------------------------------------------

def _toy(angle: float) -> Circuit:
    c = Circuit(4)
    for q in range(4):
        c.ry(q, angle + 0.1 * q)
    c.cnot(0, 1)
    return c


def test_lifted_class_is_closed():
    reports, diags = sc.audit_served_classes(
        [("toy4", _toy(0.3), _toy(0.9))])
    assert diags == []
    (r,) = reports
    assert r["lifted"] and r["twin_shares_entry"]
    assert r["trace_differences"] == 0
    assert r["f32_output_dtypes"] == ["float32"]


def test_opaque_class_fires_class_not_closed():
    from quest_tpu.serve.cache import CacheOptions
    reports, diags = sc.audit_served_classes(
        [("toy4", _toy(0.3), _toy(0.9))],
        options=CacheOptions(engine="pallas"))
    assert AnalysisCode.CLASS_NOT_CLOSED in codes(diags)
    (r,) = reports
    assert not r["lifted"]
    assert r["trace_differences"] > 0


def test_structural_twin_mismatch_is_key_instability():
    twin = _toy(0.3)
    twin.h(3)  # a structurally DIFFERENT circuit posing as the twin
    reports, diags = sc.audit_served_classes([("toy4", _toy(0.3), twin)])
    assert AnalysisCode.CLASS_NOT_CLOSED in codes(diags)
    assert reports[0]["twin_shares_entry"] is False


def test_trace_diff_helpers_directly():
    import jax.numpy as jnp
    from quest_tpu.analysis.jaxpr_audit import (diff_trace_constants,
                                                scan_x64_promotion,
                                                trace_embedded_ops)
    j1 = trace_embedded_ops(4, _toy(0.3).key())
    j2 = trace_embedded_ops(4, _toy(0.9).key())
    assert diff_trace_constants(j1, j1) == []
    assert diff_trace_constants(j1, j2) != []
    events, out_dtypes = scan_x64_promotion(
        trace_embedded_ops(4, _toy(0.3).key(), dtype=jnp.float32))
    assert events == []
    assert all(str(d) == "float32" for d in out_dtypes)


def test_scan_x64_promotion_catches_promoted_program():
    import jax
    import numpy as np
    from quest_tpu.analysis.jaxpr_audit import scan_x64_promotion
    spec = jax.ShapeDtypeStruct((4,), "float32")
    promoted = jax.make_jaxpr(lambda s: s * np.float64(2.0))(spec)
    events, out_dtypes = scan_x64_promotion(promoted)
    assert events
    assert any(str(d) == "float64" for d in out_dtypes)
