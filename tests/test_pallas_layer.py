"""Whole-layer Pallas kernel vs the XLA gate engine (interpret mode on CPU;
the same code paths run Mosaic-compiled on a real chip — validated there at
n=20 and n=24, see ops/pallas_layer.py docstring)."""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from quest_tpu import _compat
from quest_tpu.ops import apply as ap
from quest_tpu.ops import pallas_layer as pll


def _haar(rng):
    g = rng.normal(size=(2, 2)) + 1j * rng.normal(size=(2, 2))
    u, r = np.linalg.qr(g)
    return u * (np.diag(r) / np.abs(np.diag(r)))


@pytest.mark.parametrize("n", [17, 18, 19, 20])
def test_layer_matches_engine(n):
    rng = np.random.default_rng(42 + n)
    gates = [_haar(rng) for _ in range(n)]
    amps = rng.normal(size=(2, 1 << n)).astype(np.float32)
    amps /= np.sqrt((amps ** 2).sum())

    want = jnp.asarray(amps)
    for q, u in enumerate(gates):
        want = ap.apply_matrix(want, jnp.asarray(ap.mat_pair(u), jnp.float32),
                               (q,))
    got = pll.apply_1q_layer(jnp.asarray(amps),
                             [ap.mat_pair(u) for u in gates])
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-6)


def test_layer_rejects_small_states():
    state = jnp.zeros((2, 1 << 10), jnp.float32)
    with pytest.raises(ValueError):
        pll.apply_1q_layer(state, [ap.mat_pair(np.eye(2))] * 10)


@pytest.mark.parametrize("n,q", [
    # n=19: lane/sublane/fiber positions + a widened (padded) high group
    (19, 0), (19, 5), (19, 8), (19, 12), (19, 16), (19, 17), (19, 18),
    # n=21: full-width unpadded group-0 arithmetic (q in [17, 21), no pad)
    (21, 17), (21, 20),
    # n=25: the SECOND fiber group (lo = 24) — pins the group-offset math
    (25, 24),
])
def test_single_gate_pass_matches_engine(n, q):
    rng = np.random.default_rng(100 * n + q)
    u = _haar(rng)
    amps = rng.normal(size=(2, 1 << n)).astype(np.float32)
    amps /= np.sqrt((amps ** 2).sum())

    want = ap.apply_matrix(jnp.asarray(amps),
                           jnp.asarray(ap.mat_pair(u), jnp.float32), (q,))
    re, im = pll.apply_1q_gate_planes(jnp.asarray(amps[0]),
                                      jnp.asarray(amps[1]),
                                      ap.mat_pair(u), q)
    got = np.stack([np.asarray(re), np.asarray(im)])
    np.testing.assert_allclose(got, np.asarray(want), atol=2e-6)


def test_qft_inplace_matches_circuit_engine():
    """The fused-ladder in-place QFT (ops/qft_inplace.py) must equal the
    circuit QFT (H + controlled phases + swaps) applied by the XLA engine."""
    from quest_tpu.circuit import _apply_one, qft_circuit
    from quest_tpu.ops.qft_inplace import qft_planes

    n = 18
    rng = np.random.default_rng(7)
    amps = rng.normal(size=(2, 1 << n)).astype(np.float32)
    amps /= np.sqrt((amps ** 2).sum())

    want = jnp.asarray(amps)
    for op in qft_circuit(n).key():
        want = _apply_one(want, op)

    re, im = qft_planes(jnp.asarray(amps[0]), jnp.asarray(amps[1]))
    got = np.stack([np.asarray(re), np.asarray(im)])
    np.testing.assert_allclose(got, np.asarray(want), atol=5e-6)


def test_qft_inplace_concentrates_plus_state():
    """QFT(|+...+>) = |0...0> — the same end-to-end check the distributed
    QFT example uses, here through the in-place engine."""
    from quest_tpu.ops.qft_inplace import qft_planes

    n = 17
    re = jnp.full((1 << n,), 1.0 / np.sqrt(1 << n), jnp.float32)
    im = jnp.zeros((1 << n,), jnp.float32)
    re, im = qft_planes(re, im)
    assert abs(float(re[0]) - 1.0) < 1e-4
    assert abs(float(im[0])) < 1e-4
    norm = float(jnp.sum(re ** 2 + im ** 2))
    assert abs(norm - 1.0) < 1e-3


def test_qft_inplace_unordered_mode():
    """bit_reversal=False (the 30q-ceiling mode) returns the transform in
    bit-reversed amplitude order: undoing the permutation on the host must
    reproduce the ordered transform."""
    from quest_tpu.ops.qft_inplace import _rev_perm, qft_planes

    n = 17
    rng = np.random.default_rng(3)
    amps = rng.normal(size=(2, 1 << n)).astype(np.float32)
    amps /= np.sqrt((amps ** 2).sum())

    re_o, im_o = qft_planes(jnp.asarray(amps[0]), jnp.asarray(amps[1]))
    re_u, im_u = qft_planes(jnp.asarray(amps[0]), jnp.asarray(amps[1]),
                            bit_reversal=False)
    perm = _rev_perm(n)
    np.testing.assert_allclose(np.asarray(re_u)[perm], np.asarray(re_o),
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(im_u)[perm], np.asarray(im_o),
                               atol=1e-6)


@pytest.mark.parametrize("q", [3, 16, 17, 18])
def test_ladder_pallas_matches_xla_form(q):
    """The in-place Pallas ladder kernel must equal the XLA reference form
    (_ladder_diag) — pins the kernel's global-index reconstruction."""
    from quest_tpu.ops.qft_inplace import _ladder_diag, _ladder_pallas

    n = 19
    rng = np.random.default_rng(q)
    amps = rng.normal(size=(2, 1 << n)).astype(np.float32)
    amps /= np.sqrt((amps ** 2).sum())
    re, im = jnp.asarray(amps[0]), jnp.asarray(amps[1])

    want_re, want_im = _ladder_diag(re, im, q)
    # Mosaic lowering requires x64 off (the qft_planes entry does the same;
    # see pallas_layer apply_1q_layer) — f32 operands are unaffected
    with _compat.enable_x64(False):
        got_re, got_im = jax.jit(_ladder_pallas,
                                 static_argnums=(2,))(re, im, q)
    np.testing.assert_allclose(np.asarray(got_re), np.asarray(want_re),
                               atol=2e-6)
    np.testing.assert_allclose(np.asarray(got_im), np.asarray(want_im),
                               atol=2e-6)


@pytest.mark.parametrize("bit_reversal", [True, False])
def test_qft_inverse_roundtrip(bit_reversal):
    """inverse=True undoes the forward transform of the same ordering mode
    (the phase-estimation primitive)."""
    from quest_tpu.ops.qft_inplace import qft_planes

    n = 17
    rng = np.random.default_rng(11)
    amps = rng.normal(size=(2, 1 << n)).astype(np.float32)
    amps /= np.sqrt((amps ** 2).sum())

    re, im = qft_planes(jnp.asarray(amps[0]), jnp.asarray(amps[1]),
                        bit_reversal=bit_reversal)
    re, im = qft_planes(re, im, bit_reversal=bit_reversal, inverse=True)
    np.testing.assert_allclose(np.asarray(re), amps[0], atol=2e-6)
    np.testing.assert_allclose(np.asarray(im), amps[1], atol=2e-6)
