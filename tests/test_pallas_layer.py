"""Whole-layer Pallas kernel vs the XLA gate engine (interpret mode on CPU;
the same code paths run Mosaic-compiled on a real chip — validated there at
n=20 and n=24, see ops/pallas_layer.py docstring)."""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from quest_tpu.ops import apply as ap
from quest_tpu.ops import pallas_layer as pll


def _haar(rng):
    g = rng.normal(size=(2, 2)) + 1j * rng.normal(size=(2, 2))
    u, r = np.linalg.qr(g)
    return u * (np.diag(r) / np.abs(np.diag(r)))


@pytest.mark.parametrize("n", [17, 18, 19, 20])
def test_layer_matches_engine(n):
    rng = np.random.default_rng(42 + n)
    gates = [_haar(rng) for _ in range(n)]
    amps = rng.normal(size=(2, 1 << n)).astype(np.float32)
    amps /= np.sqrt((amps ** 2).sum())

    want = jnp.asarray(amps)
    for q, u in enumerate(gates):
        want = ap.apply_matrix(want, jnp.asarray(ap.mat_pair(u), jnp.float32),
                               (q,))
    got = pll.apply_1q_layer(jnp.asarray(amps),
                             [ap.mat_pair(u) for u in gates])
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-6)


def test_layer_rejects_small_states():
    state = jnp.zeros((2, 1 << 10), jnp.float32)
    with pytest.raises(ValueError):
        pll.apply_1q_layer(state, [ap.mat_pair(np.eye(2))] * 10)
