"""Randomized differential fuzzing: long random API-call sequences checked
against the dense-numpy oracle after every step.

Neither the reference suite nor the per-op tests exercise cross-op
interactions (a Kraus channel after a collapse after a packed unitary…);
seeded random walks over the full op set do.  Any divergence >tolerance
fails with the seed and step for exact reproduction.
"""

from __future__ import annotations

import math

import numpy as np
import pytest

import quest_tpu as qt
from oracle import (DM_TOL, SV_TOL, apply_channel, apply_to_dm, apply_to_sv,
                    dm, phase_shift, random_kraus_map, random_unitary, rot, sv)

N = 5
STEPS = 40
SEEDS = range(4)


def _random_op(rng, kmax=3):
    """Draw one op as (apply_fn, (targets, matrix, controls)) or a collapse
    marker.  ``kmax`` caps dense-gate width to the per-shard limit (the
    reference's fits-in-node rule)."""
    kinds = ["h", "x", "y", "z", "s", "t", "rx", "ry", "rz", "rot_axis",
             "phase", "cnot", "cz", "cphase", "swap", "sqrt_swap", "unitary1",
             "mcu", "multi_rotate_z", "collapse"]
    if kmax >= 2:
        kinds += ["unitary2"]
    if kmax >= 3:
        kinds += ["multi3"]
    kind = rng.choice(kinds)
    q = int(rng.integers(N))
    q2 = int(rng.choice([x for x in range(N) if x != q]))
    angle = float(rng.uniform(-math.pi, math.pi))

    X = np.array([[0, 1], [1, 0]], dtype=complex)
    Y = np.array([[0, -1j], [1j, 0]])
    Z = np.diag([1, -1]).astype(complex)
    H = np.array([[1, 1], [1, -1]]) / math.sqrt(2)

    if kind == "h":
        return lambda p: qt.hadamard(p, q), ([q], H, [])
    if kind == "x":
        return lambda p: qt.pauliX(p, q), ([q], X, [])
    if kind == "y":
        return lambda p: qt.pauliY(p, q), ([q], Y, [])
    if kind == "z":
        return lambda p: qt.pauliZ(p, q), ([q], Z, [])
    if kind == "s":
        return lambda p: qt.sGate(p, q), ([q], np.diag([1, 1j]), [])
    if kind == "t":
        return lambda p: qt.tGate(p, q), ([q], phase_shift(math.pi / 4), [])
    if kind == "rx":
        return lambda p: qt.rotateX(p, q, angle), ([q], rot([1, 0, 0], angle), [])
    if kind == "ry":
        return lambda p: qt.rotateY(p, q, angle), ([q], rot([0, 1, 0], angle), [])
    if kind == "rz":
        return lambda p: qt.rotateZ(p, q, angle), ([q], rot([0, 0, 1], angle), [])
    if kind == "rot_axis":
        ax = rng.normal(size=3)
        return (lambda p: qt.rotateAroundAxis(p, q, angle, tuple(ax)),
                ([q], rot(ax, angle), []))
    if kind == "phase":
        return (lambda p: qt.phaseShift(p, q, angle),
                ([q], phase_shift(angle), []))
    if kind == "cnot":
        return lambda p: qt.controlledNot(p, q2, q), ([q], X, [q2])
    if kind == "cz":
        return lambda p: qt.controlledPhaseFlip(p, q2, q), ([q], Z, [q2])
    if kind == "cphase":
        return (lambda p: qt.controlledPhaseShift(p, q2, q, angle),
                ([q], phase_shift(angle), [q2]))
    if kind == "swap":
        SW = np.eye(4)[[0, 2, 1, 3]].astype(complex)
        return lambda p: qt.swapGate(p, q, q2), ([q, q2], SW, [])
    if kind == "sqrt_swap":
        SS = np.array([[1, 0, 0, 0],
                       [0, (1 + 1j) / 2, (1 - 1j) / 2, 0],
                       [0, (1 - 1j) / 2, (1 + 1j) / 2, 0],
                       [0, 0, 0, 1]])
        return lambda p: qt.sqrtSwapGate(p, q, q2), ([q, q2], SS, [])
    if kind == "unitary1":
        u = random_unitary(1)
        return lambda p: qt.unitary(p, q, u), ([q], u, [])
    if kind == "unitary2":
        u = random_unitary(2)
        return (lambda p: qt.twoQubitUnitary(p, q, q2, u), ([q, q2], u, []))
    if kind == "multi3":
        ts = list(rng.permutation(N)[:3])
        ts = [int(t) for t in ts]
        u = random_unitary(3)
        return (lambda p: qt.multiQubitUnitary(p, ts, 3, u), (ts, u, []))
    if kind == "mcu":
        cs = [q2]
        u = random_unitary(1)
        return (lambda p: qt.multiControlledUnitary(p, cs, 1, q, u),
                ([q], u, cs))
    if kind == "multi_rotate_z":
        ts = sorted(int(t) for t in rng.permutation(N)[:2])
        d = np.array([np.exp(-1j * angle / 2 * (1 - 2 * (bin(i).count("1") % 2)))
                      for i in range(4)])
        return (lambda p: qt.multiRotateZ(p, ts, 2, angle),
                (ts, np.diag(d), []))
    if kind == "collapse":
        return ("collapse", q), None
    raise AssertionError(kind)


@pytest.mark.parametrize("seed", SEEDS)
def test_fuzz_statevector(env, seed):
    rng = np.random.default_rng(1000 + seed)
    kmax = ((1 << N) // env.num_ranks).bit_length() - 1
    psi = qt.createQureg(N, env)
    qt.initPlusState(psi)
    ref = np.full(1 << N, 1 / math.sqrt(1 << N), dtype=complex)
    for step in range(STEPS):
        op, oracle = _random_op(rng, kmax)
        if oracle is None:  # collapse to the likelier outcome (never prob 0)
            _, q = op
            p1 = qt.calcProbOfOutcome(psi, q, 1)
            outcome = 1 if p1 >= 0.5 else 0
            qt.collapseToOutcome(psi, q, outcome)
            mask = np.array([(i >> q) & 1 == outcome for i in range(1 << N)])
            ref = np.where(mask, ref, 0)
            ref = ref / np.linalg.norm(ref)
        else:
            op(psi)
            ts, u, cs = oracle
            ref = apply_to_sv(ref, N, ts, u, cs)
        got = sv(psi)
        assert np.abs(got - ref).max() < 10 * SV_TOL, \
            f"seed {seed} diverged at step {step}"


@pytest.mark.parametrize("seed", SEEDS)
def test_fuzz_density_with_channels(env, seed):
    rng = np.random.default_rng(2000 + seed)
    kmax = ((1 << (2 * N)) // env.num_ranks).bit_length() - 1
    rho_q = qt.createDensityQureg(N, env)
    qt.initPlusState(rho_q)
    ref = np.full((1 << N, 1 << N), 1.0 / (1 << N), dtype=complex)
    for step in range(STEPS):
        roll = rng.uniform()
        if roll < 0.25:  # decoherence channel
            q = int(rng.integers(N))
            p = float(rng.uniform(0, 0.3))
            which = rng.choice(["damp", "dephase", "depol", "kraus"])
            if which == "damp":
                qt.mixDamping(rho_q, q, p)
                ks = [np.diag([1, math.sqrt(1 - p)]),
                      np.sqrt(p) * np.array([[0, 1], [0, 0]])]
            elif which == "dephase":
                qt.mixDephasing(rho_q, q, p)
                ks = [math.sqrt(1 - p) * np.eye(2),
                      math.sqrt(p) * np.diag([1, -1])]
            elif which == "depol":
                qt.mixDepolarising(rho_q, q, p)
                X = np.array([[0, 1], [1, 0]], dtype=complex)
                Y = np.array([[0, -1j], [1j, 0]])
                Z = np.diag([1, -1]).astype(complex)
                ks = [math.sqrt(1 - p) * np.eye(2)] + \
                     [math.sqrt(p / 3) * m for m in (X, Y, Z)]
            else:
                ks = random_kraus_map(1, 3)
                qt.mixKrausMap(rho_q, q, ks, 3)
            ref = apply_channel(ref, N, [q], ks)
        else:
            op, oracle = _random_op(rng, min(kmax, 3))
            if oracle is None:
                _, q = op
                p1 = qt.calcProbOfOutcome(rho_q, q, 1)
                outcome = 1 if p1 >= 0.5 else 0
                prob = qt.collapseToOutcome(rho_q, q, outcome)
                proj = np.diag([(1.0 if ((i >> q) & 1) == outcome else 0.0)
                                for i in range(1 << N)])
                ref = proj @ ref @ proj / prob
            else:
                op(rho_q)
                ts, u, cs = oracle
                ref = apply_to_dm(ref, N, ts, u, cs)
        got = dm(rho_q)
        assert np.abs(got - ref).max() < 10 * DM_TOL, \
            f"seed {seed} diverged at step {step}"
    assert qt.calcTotalProb(rho_q) == pytest.approx(1.0, abs=1e-6)


def test_fuzz_under_select_control_style():
    """The comm-free control style (QUEST_TPU_CONTROL_STYLE=select) survives
    a full differential fuzz walk on both backends — the style changes the
    compiled form of every controlled dense gate, so the walk re-validates
    the whole interaction surface under it (style is read at import, hence
    the subprocess)."""
    import os
    import subprocess
    import sys

    here = os.path.dirname(os.path.abspath(__file__))
    env_vars = dict(os.environ)
    env_vars["QUEST_TPU_CONTROL_STYLE"] = "select"
    # always a CPU run: under QUEST_TEST_PLATFORM=tpu the dist8 node would
    # silently skip and halve the claimed coverage
    env_vars.pop("QUEST_TEST_PLATFORM", None)
    fuzz = os.path.join(here, "test_fuzz.py")
    r = subprocess.run(
        [sys.executable, "-m", "pytest", "-q", "-x",
         f"{fuzz}::test_fuzz_statevector[local-0]",
         f"{fuzz}::test_fuzz_statevector[dist8-0]"],
        capture_output=True, text=True, timeout=580, env=env_vars,
        cwd=os.path.dirname(here))
    assert r.returncode == 0, r.stdout[-600:] + r.stderr[-600:]
