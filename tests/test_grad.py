"""Gradient serving (quest_tpu/grad + serve/cache.py gradient entries).

The adjoint-differentiation serving path: correctness against taped
reverse-mode and central finite differences, the O(1)-live-state claim,
bit-identity of batched vs serial gradients, the E_GRADIENT_* error
surface, router affinity/quarantine for gradient classes, the persistent
store round-trip, and the training-loop driver.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import quest_tpu as qt
from quest_tpu.grad import adjoint as gadj
from quest_tpu.grad import training_loop
from quest_tpu.models import (hardware_efficient_ansatz, maxcut_hamiltonian,
                              qaoa_maxcut_circuit, tfim_hamiltonian)
from quest_tpu.serve import CompileCache, GradResult, QuESTService
from quest_tpu.validation import ErrorCode, QuESTError
from conftest import ON_ACCELERATOR

TOL = 1e-3 if ON_ACCELERATOR else 1e-10
FD_EPS = 1e-2 if ON_ACCELERATOR else 1e-5
FD_TOL = 5e-2 if ON_ACCELERATOR else 1e-6


def _zero_state(n):
    dt = jnp.float32 if ON_ACCELERATOR else jnp.float64
    return jnp.zeros((2, 1 << n), dt).at[0, 0].set(1.0)


def _grad_via_cache(cache, pc, hamil, params):
    masks = gadj.hamil_masks(hamil)
    entry = cache.grad_entry_for(tuple(pc.ops), pc.num_qubits,
                                 pc.num_params, masks)
    st = _zero_state(pc.num_qubits)
    cf = jnp.asarray(np.asarray(hamil.term_coeffs, np.float64))
    prog = cache.grad_single_program(entry, st)
    e, g = prog.call(st, jnp.asarray(params), cf)
    return float(e), np.asarray(g), entry


# ---------------------------------------------------------------------------
# satellite 1: correctness oracles + the O(1)-state claim
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("n,layers", [(3, 1), (5, 2), (6, 2)])
def test_lifted_adjoint_matches_jax_grad_hea(env_local, n, layers):
    """The served (lifted) adjoint program must agree with taped
    reverse-mode through the unlifted program on the hardware-efficient
    ansatz at several sizes."""
    pc = hardware_efficient_ansatz(n, layers)
    h = tfim_hamiltonian(n, field=0.7)
    params = np.random.default_rng(n).uniform(-1.5, 1.5, pc.num_params)
    e, g, _ = _grad_via_cache(CompileCache(), pc, h, params)
    v0, g0 = jax.value_and_grad(qt.expectation_fn(pc, h))(jnp.asarray(params))
    assert abs(e - float(v0)) < TOL
    np.testing.assert_allclose(g, np.asarray(g0), atol=TOL)


@pytest.mark.parametrize("n,p", [(4, 1), (6, 3)])
def test_lifted_adjoint_matches_fd_qaoa(env_local, n, p):
    """QAOA (shared affine params through multiRotateZ/rx walls): energy
    gradient vs central finite differences, tolerance-banded."""
    edges = [(i, (i + 1) % n) for i in range(n)]
    pc = qaoa_maxcut_circuit(n, edges, p)
    h = maxcut_hamiltonian(n, edges)
    params = np.random.default_rng(p).uniform(-1.0, 1.0, pc.num_params)
    e, g, _ = _grad_via_cache(CompileCache(), pc, h, params)
    efn = qt.expectation_fn(pc, h)
    assert abs(e - float(efn(jnp.asarray(params)))) < TOL
    for i in range(pc.num_params):
        up = params.copy(); up[i] += FD_EPS
        dn = params.copy(); dn[i] -= FD_EPS
        fd = (float(efn(jnp.asarray(up))) - float(efn(jnp.asarray(dn)))) \
            / (2 * FD_EPS)
        assert abs(g[i] - fd) < FD_TOL, (i, g[i], fd)


def _max_live_state_vars(jaxpr, amps: int) -> int:
    """Liveness analysis over a jaxpr: the maximum number of
    state-sized (>= ``amps`` elements) variables simultaneously live at
    any program point — the honest form of the 'live buffers' question
    (backend memory_analysis on CPU reports allocation totals, not
    liveness)."""
    from jax.core import Var

    last_use, born = {}, {}
    for v in list(jaxpr.invars) + list(jaxpr.constvars):
        if isinstance(v, Var):
            born[v] = -1
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if isinstance(v, Var):
                last_use[v] = i
        for v in eqn.outvars:
            if isinstance(v, Var):
                born[v] = i
    for v in jaxpr.outvars:
        if isinstance(v, Var):
            last_use[v] = len(jaxpr.eqns)
    spans = [(b, last_use[v]) for v, b in born.items()
             if v in last_use and getattr(v.aval, "size", 0) >= amps]
    return max(sum(1 for b, d in spans if b < i <= d)
               for i in range(len(jaxpr.eqns) + 1))


def test_adjoint_is_o1_state_in_depth(env_local):
    """The live-buffer assertion behind the O(1)-state claim: at any
    point of the adjoint program only a HANDFUL of state-sized buffers
    are live (psi, lam, the generator scratch — independent of depth),
    while taped reverse-mode keeps a residual per gate live across the
    forward sweep, so its live set grows linearly with depth."""
    n = 6
    amps = 1 << n
    h = tfim_hamiltonian(n)
    st = _zero_state(n)

    def live_counts(layers):
        pc = hardware_efficient_ansatz(n, layers)
        body = gadj.adjoint_terms_fn(pc.ops, n, pc.num_params,
                                     gadj.hamil_masks(h))
        p = jnp.zeros(pc.num_params)
        cf = jnp.zeros(h.num_sum_terms)
        adjoint = _max_live_state_vars(
            jax.make_jaxpr(body)(st, p, cf).jaxpr, amps)
        taped = _max_live_state_vars(
            jax.make_jaxpr(jax.value_and_grad(
                qt.expectation_fn(pc, h)))(p).jaxpr, amps)
        return adjoint, taped

    a4, t4 = live_counts(4)
    a16, t16 = live_counts(16)
    # adjoint: a depth-independent handful (measured 5 -> 7 for 4x the
    # layers: the three statevectors plus barrier/scratch pairs)
    assert a16 <= a4 + 4 and a16 <= 16, (a4, a16)
    # taped reverse-mode: the live residual set grows with depth
    assert t16 > 2 * t4, (t4, t16)
    # and the adjoint's live set is orders of magnitude below the tape's
    assert a16 * 10 < t16, (a16, t16)


def test_deep_circuit_gradient_correct(env_local):
    """A deep circuit (where taped reverse-mode would hold depth+1
    states): the adjoint gradient still matches jax.grad."""
    pc = hardware_efficient_ansatz(4, 10)
    h = tfim_hamiltonian(4)
    params = np.random.default_rng(10).uniform(-1, 1, pc.num_params)
    e, g, _ = _grad_via_cache(CompileCache(), pc, h, params)
    v0, g0 = jax.value_and_grad(qt.expectation_fn(pc, h))(jnp.asarray(params))
    assert abs(e - float(v0)) < TOL
    np.testing.assert_allclose(g, np.asarray(g0), atol=10 * TOL)


# ---------------------------------------------------------------------------
# satellite 2: batching invariance + isolation
# ---------------------------------------------------------------------------

def test_gradient_storm_bit_identical_to_serial(env_local):
    """64 same-class gradient requests batched through one service are
    BIT-IDENTICAL to the one-at-a-time serial loop on a fresh service."""
    pc = hardware_efficient_ansatz(5, 2)
    h = tfim_hamiltonian(5)
    rng = np.random.default_rng(2)
    params = [rng.uniform(-np.pi, np.pi, pc.num_params) for _ in range(64)]
    with QuESTService(max_batch=16, max_delay_ms=20,
                      cache=CompileCache(), start=False) as svc:
        futs = [svc.submit_gradient(pc, p, h) for p in params]
        svc.start()
        assert svc.drain(timeout=600)
        batched = [f.result(timeout=60) for f in futs]
    with QuESTService(max_batch=16, max_delay_ms=0,
                      cache=CompileCache()) as svc2:
        serial = [svc2.submit_gradient(pc, p, h).result(timeout=120)
                  for p in params]
    assert any(r.batch_size > 1 for r in batched)
    for r, s in zip(batched, serial):
        assert isinstance(r, GradResult)
        assert r.energy == s.energy
        assert np.array_equal(r.gradient, s.gradient)


def test_gradient_forward_interleave_isolation(env_local):
    """Gradient and forward requests interleaved on ONE service: forward
    states stay bit-identical to serial execution and the per-request
    MT19937 sample streams match the oracle — and probed/unprobed
    gradient twins never co-batch yet return identical primaries."""
    from quest_tpu.ops import measure as _meas
    from quest_tpu.rng import MT19937
    from quest_tpu.serve.selftest import vqe_ansatz

    n, seed = 4, 11
    pc = hardware_efficient_ansatz(n, 1)
    h = tfim_hamiltonian(n)
    rng = np.random.default_rng(3)
    gparams = [rng.uniform(-1, 1, pc.num_params) for _ in range(6)]
    fwd = [vqe_ansatz(n, 1, seed=s) for s in range(6)]
    cache = CompileCache()
    with QuESTService(max_batch=8, max_delay_ms=10, seed=seed, cache=cache,
                      start=False) as svc:
        gf = [svc.submit_gradient(pc, p, h) for p in gparams]
        ff = [svc.submit(c, shots=16) for c in fwd]
        pf = [svc.submit_gradient(pc, p, h, probes=True) for p in gparams]
        svc.start()
        assert svc.drain(timeout=600)
        gres = [f.result(timeout=60) for f in gf]
        fres = [f.result(timeout=60) for f in ff]
        pres = [f.result(timeout=60) for f in pf]
    st = _zero_state(n)
    for c, r in zip(fwd, fres):
        want = np.asarray(cache.execute(c.key(), st, num_qubits=n))
        assert np.array_equal(r.state, want)
        probs = np.asarray(_meas.prob_all_outcomes(jnp.asarray(want),
                                                   tuple(range(n))))
        cdf = np.cumsum(probs)
        gen = MT19937()
        gen.init_by_array([seed, r.request_id])
        draws = gen.genrand_real1_batch(16)
        expect = np.minimum(np.searchsorted(cdf, draws * cdf[-1],
                                            side="right"),
                            np.nonzero(probs > 0)[0][-1])
        assert np.array_equal(r.samples, expect.astype(np.int64))
    for g, p in zip(gres, pres):
        # probed and unprobed groups executed separately (different
        # programs) but the primary outputs are bit-identical
        assert p.numeric_health is not None and g.numeric_health is None
        assert not p.numeric_health["findings"]
        assert g.energy == p.energy
        assert np.array_equal(g.gradient, p.gradient)


def test_gradient_batch_mode_vmap_close(env_local):
    """batch_mode='vmap' trades bit-identity for throughput: results stay
    within a few ulps of the map-mode contract."""
    pc = hardware_efficient_ansatz(4, 1)
    h = tfim_hamiltonian(4)
    rng = np.random.default_rng(4)
    params = [rng.uniform(-1, 1, pc.num_params) for _ in range(8)]
    with QuESTService(max_batch=8, max_delay_ms=20, batch_mode="vmap",
                      cache=CompileCache(), start=False) as svc:
        futs = [svc.submit_gradient(pc, p, h) for p in params]
        svc.start()
        assert svc.drain(timeout=300)
        vres = [f.result(timeout=60) for f in futs]
    for p, r in zip(params, vres):
        v0, g0 = jax.value_and_grad(qt.expectation_fn(pc, h))(jnp.asarray(p))
        assert abs(r.energy - float(v0)) < 1e-12
        np.testing.assert_allclose(r.gradient, np.asarray(g0), atol=1e-12)


# ---------------------------------------------------------------------------
# satellite 3: the error surface
# ---------------------------------------------------------------------------

def test_adjoint_gradient_fn_error_codes(env_local):
    pc = qt.ParamCircuit(2)
    pc.h(0).damp(0, pc.param())
    with pytest.raises(QuESTError, match="noise") as exc:
        qt.adjoint_gradient_fn(pc, tfim_hamiltonian(2))
    assert exc.value.code == ErrorCode.GRADIENT_NOT_UNITARY

    # a density-register init (Qureg carries the density flag) raises the
    # density-mode code at build time
    pc2 = qt.ParamCircuit(2)
    pc2.h(0).rx(0, pc2.param())
    env = qt.createQuESTEnv()
    dq = qt.createDensityQureg(2, env)
    with pytest.raises(QuESTError) as exc:
        qt.adjoint_gradient_fn(pc2, tfim_hamiltonian(2), init=dq)
    assert exc.value.code == ErrorCode.GRADIENT_DENSITY_MODE


def test_nonunitary_payloads_rejected(env_local):
    # non-unitary embedded matrix
    pc = qt.ParamCircuit(2)
    pc._mat([[2.0, 0.0], [0.0, 1.0]], (0,))
    pc.rx(1, pc.param())
    with pytest.raises(QuESTError) as exc:
        gadj.validate_gradient_circuit(pc)
    assert exc.value.code == ErrorCode.GRADIENT_NOT_UNITARY
    # non-unit-modulus diagonal
    pc2 = qt.ParamCircuit(2)
    pc2._diag([0.5, 1.0], (0,))
    pc2.rx(1, pc2.param())
    with pytest.raises(QuESTError) as exc:
        gadj.validate_gradient_circuit(pc2)
    assert exc.value.code == ErrorCode.GRADIENT_NOT_UNITARY


def test_submit_gradient_admission_rejections(env_local):
    """submit_gradient rejects bad circuits AT ADMISSION with the same
    codes adjoint_gradient_fn raises — the worker thread never sees
    them."""
    h2 = tfim_hamiltonian(2)
    with QuESTService(cache=CompileCache()) as svc:
        noisy = qt.ParamCircuit(2)
        noisy.h(0).depolarise(0, noisy.param())
        with pytest.raises(QuESTError) as exc:
            svc.submit_gradient(noisy, [0.1], h2)
        assert exc.value.code == ErrorCode.GRADIENT_NOT_UNITARY

        pc = qt.ParamCircuit(2)
        pc.h(0).ry(0, pc.param())
        # density-shaped initial state -> the density-mode code
        rho = np.zeros((2, 16))
        rho[0, 0] = 1.0
        with pytest.raises(QuESTError) as exc:
            svc.submit_gradient(pc, [0.1], h2, initial_state=rho)
        assert exc.value.code == ErrorCode.GRADIENT_DENSITY_MODE
        # Hamiltonian qubit-count mismatch
        with pytest.raises(QuESTError) as exc:
            svc.submit_gradient(pc, [0.1], tfim_hamiltonian(3))
        assert exc.value.code == \
            ErrorCode.MISMATCHING_PAULI_HAMIL_QUREG_NUM_QUBITS
        # wrong parameter count / missing pieces
        with pytest.raises(ValueError, match="takes 1"):
            svc.submit_gradient(pc, [0.1, 0.2], h2)
        with pytest.raises(TypeError, match="PauliHamil"):
            svc.submit_gradient(pc, [0.1])
        with pytest.raises(TypeError, match="ParamCircuit"):
            svc.submit_gradient(qt.qft_circuit(2), [0.1], h2)
        # and the forward door bounces traced-parameter circuits
        with pytest.raises(TypeError, match="submit_gradient"):
            svc.submit(pc)


# ---------------------------------------------------------------------------
# deploy: gradient classes are routable classes
# ---------------------------------------------------------------------------

def test_router_grad_affinity_and_quarantine(env_local):
    from quest_tpu.deploy import ReplicaPool, RouterConfig

    pc = hardware_efficient_ansatz(3, 1)
    h = tfim_hamiltonian(3)
    bad = tfim_hamiltonian(3)
    bad.term_coeffs[0] = float("nan")
    rng = np.random.default_rng(5)
    p = rng.uniform(-1, 1, pc.num_params)
    with ReplicaPool(num_replicas=2, probes=True, max_delay_ms=0,
                     router_config=RouterConfig(quarantine_nans=2)) as pool:
        # affinity: repeated same-class gradient requests stick to ONE
        # replica (exactly one structural miss across the deployment)
        res = [pool.submit_gradient(pc, p, h).result(timeout=300)
               for _ in range(4)]
        assert [r.cache_outcome for r in res].count("miss") == 1
        gck = pool.router.grad_class_key(pc, h)
        assert gck in pool.router.snapshot()["placements"]
        # distinct from the forward class key of the same circuit shape
        assert gck != pool.router.class_key(pc)
        # two consecutive NaN outcomes quarantine the placement (the
        # done-callback that reports them runs just after result() is
        # released, so poll briefly)
        import time
        for _ in range(2):
            r = pool.submit_gradient(pc, p, bad).result(timeout=300)
            assert r.numeric_health["nan_count"] > 0
        deadline = time.monotonic() + 5.0
        while (not pool.router.snapshot()["quarantined"]
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert len(pool.router.snapshot()["quarantined"]) >= 1
        # the clean class still serves (re-placed while the pair sits out)
        clean = pool.submit_gradient(pc, p, h).result(timeout=300)
        assert not clean.numeric_health["findings"]
        assert clean.energy == res[0].energy


# ---------------------------------------------------------------------------
# persistence + eviction: gradient entries are first-class cache citizens
# ---------------------------------------------------------------------------

def test_grad_program_persists_and_warms(env_local, tmp_path):
    from quest_tpu.deploy import ExecutableStore

    pc = hardware_efficient_ansatz(3, 1)
    h = tfim_hamiltonian(3)
    params = np.random.default_rng(6).uniform(-1, 1, pc.num_params)
    store = ExecutableStore(str(tmp_path))
    cache = CompileCache().attach_store(store)
    e0, g0, _ = _grad_via_cache(cache, pc, h, params)
    assert cache.snapshot()["persist_saves"] >= 1
    # a COLD cache warms from the store: the gradient entry (masks
    # included) re-materializes and the program loads with ZERO compiles
    cold = CompileCache().attach_store(store)
    summary = store.warm(cold)
    assert summary["loaded"] >= 1
    e1, g1, entry = _grad_via_cache(cold, pc, h, params)
    assert cold.snapshot()["compiles"] == 0
    assert entry.hamil == gadj.hamil_masks(h)
    assert e1 == e0 and np.array_equal(g1, g0)


# ---------------------------------------------------------------------------
# the training-loop driver
# ---------------------------------------------------------------------------

def test_training_loop_descends_and_compiles_once(env_local):
    pc = hardware_efficient_ansatz(4, 1)
    h = tfim_hamiltonian(4)
    rng = np.random.default_rng(8)
    cache = CompileCache()
    with QuESTService(max_batch=8, max_delay_ms=5, cache=cache) as svc:
        tr = training_loop(svc, pc, h, rng.uniform(-0.5, 0.5, (4, pc.num_params)),
                           steps=6, lr=0.1)
        single = training_loop(svc, pc, h, tr.params[0], steps=2, lr=0.05)
    assert tr.energies.shape == (4, 6) and tr.requests == 24
    # plain SGD on a smooth landscape: every chain ends below its start
    assert (tr.energies[:, -1] <= tr.energies[:, 0] + 1e-9).all()
    assert single.energies.shape == (2,)
    assert single.params.shape == (pc.num_params,)
    # the whole run hit ONE gradient class: a single structural miss
    assert cache.snapshot()["misses"] == 1
