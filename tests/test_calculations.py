"""Scalar calculations & amplitude access, mirroring the reference's
test_calculations.cpp (18 TEST_CASEs)."""

from __future__ import annotations

import numpy as np
import pytest

import quest_tpu as qt
from oracle import (NUM_QUBITS, pauli_string_matrix, pauli_sum_matrix, SV_TOL,
                    random_density_matrix, random_statevector, set_dm, set_sv)

N = NUM_QUBITS
DIM = 1 << N


@pytest.fixture
def loaded(env):
    vec = random_statevector(N)
    rho = random_density_matrix(N)
    psi = qt.createQureg(N, env)
    dq = qt.createDensityQureg(N, env)
    set_sv(psi, vec)
    set_dm(dq, rho)
    return psi, dq, vec, rho


def test_calcTotalProb(env, loaded):
    psi, dq, vec, rho = loaded
    assert qt.calcTotalProb(psi) == pytest.approx(1.0, abs=SV_TOL)
    assert qt.calcTotalProb(dq) == pytest.approx(1.0, abs=SV_TOL)
    qt.initBlankState(psi)
    assert qt.calcTotalProb(psi) == pytest.approx(0.0, abs=SV_TOL)


def test_calcProbOfOutcome(env, loaded):
    psi, dq, vec, rho = loaded
    for t in range(N):
        mask = np.array([((i >> t) & 1) for i in range(DIM)])
        p1 = float(np.sum(np.abs(vec) ** 2 * mask))
        assert qt.calcProbOfOutcome(psi, t, 1) == pytest.approx(p1, abs=SV_TOL)
        assert qt.calcProbOfOutcome(psi, t, 0) == pytest.approx(1 - p1, abs=SV_TOL)
        p1d = float(np.real(np.sum(np.diag(rho) * mask)))
        assert qt.calcProbOfOutcome(dq, t, 1) == pytest.approx(p1d, abs=SV_TOL)
        assert qt.calcProbOfOutcome(dq, t, 0) == pytest.approx(1 - p1d, abs=SV_TOL)
    with pytest.raises(qt.QuESTError, match="Invalid measurement outcome"):
        qt.calcProbOfOutcome(psi, 0, 3)


def test_calcInnerProduct(env):
    v1, v2 = random_statevector(N), random_statevector(N)
    q1, q2 = qt.createQureg(N, env), qt.createQureg(N, env)
    set_sv(q1, v1)
    set_sv(q2, v2)
    expected = np.vdot(v1, v2)  # <q1|q2>
    got = qt.calcInnerProduct(q1, q2)
    assert got == pytest.approx(expected, abs=SV_TOL)
    rho = qt.createDensityQureg(N, env)
    with pytest.raises(qt.QuESTError, match="state-vector"):
        qt.calcInnerProduct(q1, rho)


def test_calcDensityInnerProduct(env):
    r1, r2 = random_density_matrix(N), random_density_matrix(N)
    d1, d2 = qt.createDensityQureg(N, env), qt.createDensityQureg(N, env)
    set_dm(d1, r1)
    set_dm(d2, r2)
    expected = float(np.real(np.trace(r1.conj().T @ r2)))
    assert qt.calcDensityInnerProduct(d1, d2) == pytest.approx(expected, abs=SV_TOL)


def test_calcPurity(env, loaded):
    psi, dq, vec, rho = loaded
    expected = float(np.real(np.trace(rho @ rho)))
    assert qt.calcPurity(dq) == pytest.approx(expected, abs=SV_TOL)
    with pytest.raises(qt.QuESTError, match="density matrices"):
        qt.calcPurity(psi)


def test_calcFidelity(env, loaded):
    psi, dq, vec, rho = loaded
    pure_vec = random_statevector(N)
    pure = qt.createQureg(N, env)
    set_sv(pure, pure_vec)
    # statevector fidelity |<pure|psi>|^2
    expected_sv = float(np.abs(np.vdot(pure_vec, vec)) ** 2)
    assert qt.calcFidelity(psi, pure) == pytest.approx(expected_sv, abs=SV_TOL)
    # density fidelity <pure|rho|pure>
    expected_dm = float(np.real(np.vdot(pure_vec, rho @ pure_vec)))
    assert qt.calcFidelity(dq, pure) == pytest.approx(expected_dm, abs=SV_TOL)
    with pytest.raises(qt.QuESTError, match="state-vector"):
        qt.calcFidelity(psi, dq)


def test_calcHilbertSchmidtDistance(env):
    r1, r2 = random_density_matrix(N), random_density_matrix(N)
    d1, d2 = qt.createDensityQureg(N, env), qt.createDensityQureg(N, env)
    set_dm(d1, r1)
    set_dm(d2, r2)
    expected = float(np.sqrt(np.sum(np.abs(r1 - r2) ** 2)))
    assert qt.calcHilbertSchmidtDistance(d1, d2) == pytest.approx(expected, abs=SV_TOL)


def test_calcExpecPauliProd(env, loaded):
    psi, dq, vec, rho = loaded
    work = qt.createQureg(N, env)
    workd = qt.createDensityQureg(N, env)
    for targets, codes in [((0,), (1,)), ((1, 3), (2, 3)), ((0, 2, 4), (3, 1, 2))]:
        op = pauli_string_matrix(N, targets, codes)
        expected = float(np.real(np.vdot(vec, op @ vec)))
        got = qt.calcExpecPauliProd(psi, list(targets), list(codes), len(targets), work)
        assert got == pytest.approx(expected, abs=SV_TOL)
        expected_d = float(np.real(np.trace(op @ rho)))
        got_d = qt.calcExpecPauliProd(dq, list(targets), list(codes), len(targets), workd)
        assert got_d == pytest.approx(expected_d, abs=SV_TOL)
    with pytest.raises(qt.QuESTError, match="Invalid Pauli code"):
        qt.calcExpecPauliProd(psi, [0], [4], 1, work)


def test_calcExpecPauliSum(env, loaded):
    psi, dq, vec, rho = loaded
    work = qt.createQureg(N, env)
    np.random.seed(11)
    num_terms = 4
    codes = np.random.randint(0, 4, size=(num_terms, N))
    coeffs = np.random.randn(num_terms)
    op = pauli_sum_matrix(N, codes, coeffs)
    expected = float(np.real(np.vdot(vec, op @ vec)))
    got = qt.calcExpecPauliSum(psi, codes.ravel(), coeffs, num_terms, work)
    assert got == pytest.approx(expected, abs=SV_TOL)
    workd = qt.createDensityQureg(N, env)
    expected_d = float(np.real(np.trace(op @ rho)))
    got_d = qt.calcExpecPauliSum(dq, codes.ravel(), coeffs, num_terms, workd)
    assert got_d == pytest.approx(expected_d, abs=SV_TOL)


def test_calcExpecPauliHamil(env, loaded):
    psi, dq, vec, rho = loaded
    num_terms = 3
    np.random.seed(21)
    codes = np.random.randint(0, 4, size=(num_terms, N))
    coeffs = np.random.randn(num_terms)
    hamil = qt.createPauliHamil(N, num_terms)
    qt.initPauliHamil(hamil, coeffs, codes.ravel())
    op = pauli_sum_matrix(N, codes, coeffs)
    work = qt.createQureg(N, env)
    expected = float(np.real(np.vdot(vec, op @ vec)))
    assert qt.calcExpecPauliHamil(psi, hamil, work) == pytest.approx(expected, abs=SV_TOL)


def test_calcExpecDiagonalOp(env, loaded):
    psi, dq, vec, rho = loaded
    op = qt.createDiagonalOp(N, env)
    elems = np.random.randn(DIM) + 1j * np.random.randn(DIM)
    qt.initDiagonalOp(op, np.real(elems).copy(), np.imag(elems).copy())
    expected = complex(np.sum(np.abs(vec) ** 2 * elems))
    got = qt.calcExpecDiagonalOp(psi, op)
    assert got == pytest.approx(expected, abs=SV_TOL)
    expected_d = complex(np.sum(np.diag(rho) * elems))
    got_d = qt.calcExpecDiagonalOp(dq, op)
    assert got_d == pytest.approx(expected_d, abs=SV_TOL)


def test_getNumQubits(env):
    psi = qt.createQureg(N, env)
    assert qt.getNumQubits(psi) == N


def test_getNumAmps(env):
    psi = qt.createQureg(N, env)
    assert qt.getNumAmps(psi) == DIM


def test_getAmp(env, loaded):
    psi, dq, vec, rho = loaded
    for i in (0, 1, DIM - 1):
        assert qt.getAmp(psi, i) == pytest.approx(vec[i], abs=SV_TOL)
    with pytest.raises(qt.QuESTError, match="Invalid amplitude index"):
        qt.getAmp(psi, DIM)
    with pytest.raises(qt.QuESTError, match="state-vector"):
        qt.getAmp(dq, 0)


def test_getRealAmp(env, loaded):
    psi, _, vec, _ = loaded
    for i in (0, 7):
        assert qt.getRealAmp(psi, i) == pytest.approx(np.real(vec[i]), abs=SV_TOL)


def test_getImagAmp(env, loaded):
    psi, _, vec, _ = loaded
    for i in (0, 7):
        assert qt.getImagAmp(psi, i) == pytest.approx(np.imag(vec[i]), abs=SV_TOL)


def test_getProbAmp(env, loaded):
    psi, _, vec, _ = loaded
    for i in (0, 7):
        assert qt.getProbAmp(psi, i) == pytest.approx(abs(vec[i]) ** 2, abs=SV_TOL)


def test_getDensityAmp(env, loaded):
    _, dq, _, rho = loaded
    for r, c in [(0, 0), (1, 3), (DIM - 1, DIM - 1), (4, 0)]:
        assert qt.getDensityAmp(dq, r, c) == pytest.approx(rho[r, c], abs=SV_TOL)
    psi = qt.createQureg(N, env)
    with pytest.raises(qt.QuESTError, match="density matrices"):
        qt.getDensityAmp(psi, 0, 0)


def test_pauli_sum_scan_fallback_matches_unrolled(env_local):
    """Above _SCAN_TERM_LIMIT terms the dispatcher switches to the
    traced-mask lax.scan kernel; both paths must agree with the dense
    oracle draw-for-draw (ADVICE r4: many-term Hamiltonians must not
    retrace per term)."""
    from quest_tpu.ops import calc as _calc
    from quest_tpu.api import _pauli_sum_terms

    np.random.seed(33)
    num_terms = _calc._SCAN_TERM_LIMIT + 9
    codes = np.random.randint(0, 4, size=(num_terms, N))
    coeffs = np.random.randn(num_terms)

    psi = qt.createQureg(N, env_local)
    vec = random_statevector(N)
    set_sv(psi, vec)
    terms = _pauli_sum_terms(codes)
    assert len(terms) > _calc._SCAN_TERM_LIMIT

    import jax.numpy as jnp
    cf = jnp.asarray(coeffs)
    got_scan = float(_calc.expec_pauli_sum_statevec(psi.amps, terms, cf))
    got_unrolled = float(_calc._expec_pauli_sum_statevec_unrolled(
        psi.amps, terms, cf))
    op = pauli_sum_matrix(N, codes, coeffs)
    expected = float(np.real(np.vdot(vec, op @ vec)))
    # dtype-aware tolerances: the TPU-platform suite runs f32 registers
    # (f64 accumulation over f32 amplitudes lands near 1e-9 absolute)
    f64 = psi.dtype == np.float64
    oracle_tol = 1e-10 if f64 else 1e-7   # scalar expectation vs oracle
    twin_tol = 1e-12 if f64 else 1e-7     # scan vs unrolled scalar
    apply_tol = 1e-10 if f64 else 1e-5    # elementwise state comparisons
    assert got_scan == pytest.approx(expected, abs=oracle_tol)
    assert got_scan == pytest.approx(got_unrolled, abs=twin_tol)

    # apply_pauli_sum: scan vs unrolled vs dense oracle
    out_scan = np.asarray(_calc.apply_pauli_sum(psi.amps, terms, cf))
    out_unrolled = np.asarray(_calc._apply_pauli_sum_unrolled(psi.amps, terms, cf))
    want = op @ vec
    np.testing.assert_allclose(out_scan[0] + 1j * out_scan[1], want,
                               atol=apply_tol)
    np.testing.assert_allclose(out_scan, out_unrolled, atol=apply_tol)

    # work through the public API too (calcExpecPauliSum on a many-term sum)
    work = qt.createQureg(N, env_local)
    got_api = qt.calcExpecPauliSum(psi, codes.ravel(), coeffs, num_terms, work)
    assert got_api == pytest.approx(expected, abs=oracle_tol)
