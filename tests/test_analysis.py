"""quest_tpu.analysis: the static circuit analyzer + purity lint.

Every analyzer rule gets one known-bad circuit (asserting the stable
diagnostic code), plus clean-circuit no-false-positive cases, a purity-lint
self-test over the quest_tpu tree (the same gate
``python -m quest_tpu.analysis --self-lint`` enforces in CI), and the
precision-4 warning regression from the same review round.
"""

from __future__ import annotations

import warnings

import jax.numpy as jnp
import numpy as np
import pytest

import quest_tpu as qt
from quest_tpu import analysis as an
from quest_tpu import circuit as cmod
from quest_tpu import qureg as qmod
from quest_tpu.analysis import AnalysisCode, Severity
from quest_tpu.circuit import Circuit, GateOp
from quest_tpu.validation import ErrorCode


def codes(diags):
    return [d.code for d in diags]


def analyze(circuit, **kw):
    return an.analyze_circuit(circuit, **kw)


# ---------------------------------------------------------------------------
# pass 1: circuit IR analyzer — one bad circuit per diagnostic code
# ---------------------------------------------------------------------------

def test_ir_invalid_target():
    c = Circuit(3).x(7)
    assert ErrorCode.INVALID_TARGET_QUBIT in codes(analyze(c))


def test_ir_negative_target():
    c = Circuit(3)
    c.ops.append(GateOp("x", (-1,)))
    assert ErrorCode.INVALID_TARGET_QUBIT in codes(analyze(c))


def test_ir_invalid_control():
    c = Circuit(3).x(0, controls=(4,))
    assert ErrorCode.INVALID_CONTROL_QUBIT in codes(analyze(c))


def test_ir_duplicate_targets():
    c = Circuit(3)
    c.ops.append(GateOp("matrix", (1, 1), (), (),
                        tuple(np.stack([np.eye(4), np.zeros((4, 4))]).ravel()),
                        (2, 4, 4)))
    assert ErrorCode.TARGETS_NOT_UNIQUE in codes(analyze(c))


def test_ir_duplicate_controls():
    c = Circuit(4).x(0, controls=(1, 1))
    assert ErrorCode.CONTROLS_NOT_UNIQUE in codes(analyze(c))


def test_ir_control_target_collision():
    c = Circuit(3).x(0, controls=(0,))
    assert ErrorCode.CONTROL_TARGET_COLLISION in codes(analyze(c))


def test_ir_control_state_arity_and_bits():
    c = Circuit(3)
    c.multi_qubit_unitary((0,), np.eye(2), controls=(1, 2),
                          control_states=(1,))
    found = codes(analyze(c))
    assert ErrorCode.MISMATCHING_NUM_CONTROL_STATES in found
    c2 = Circuit(3)
    c2.multi_qubit_unitary((0,), np.eye(2), controls=(1,),
                           control_states=(2,))
    assert ErrorCode.INVALID_CONTROLS_BIT_STATE in codes(analyze(c2))


def test_ir_non_unitary_matrix():
    c = Circuit(2).unitary(0, [[1.0, 1.0], [0.0, 1.0]])
    diags = analyze(c)
    assert ErrorCode.NON_UNITARY_MATRIX in codes(diags)
    assert all(d.severity == Severity.ERROR for d in diags
               if d.code == ErrorCode.NON_UNITARY_MATRIX)


def test_ir_non_unitary_diagonal():
    c = Circuit(2)
    c._diag([1.0, 0.5], (0,))  # |d| != 1: not norm-preserving
    assert ErrorCode.NON_UNITARY_MATRIX in codes(analyze(c))


def test_ir_matrix_shape_mismatch():
    c = Circuit(3)
    c.ops.append(GateOp("matrix", (0, 1), (), (),
                        tuple(np.stack([np.eye(2), np.zeros((2, 2))]).ravel()),
                        (2, 2, 2)))  # 2x2 payload on 2 targets
    assert ErrorCode.INVALID_UNITARY_SIZE in codes(analyze(c))


def test_ir_unknown_kind():
    c = Circuit(2)
    c.ops.append(GateOp("frobnicate", (0,)))
    diags = [d for d in analyze(c)
             if d.code == AnalysisCode.UNKNOWN_GATE_KIND]
    assert len(diags) == 1 and diags[0].severity == Severity.ERROR


def test_ir_matrix_exceeds_shard():
    c = Circuit(3).multi_qubit_unitary((0, 1, 2), np.eye(8))
    assert ErrorCode.CANNOT_FIT_MULTI_QUBIT_MATRIX in codes(
        analyze(c, num_devices=4))
    assert ErrorCode.CANNOT_FIT_MULTI_QUBIT_MATRIX not in codes(
        analyze(c, num_devices=1))


def test_ir_memory_footprint_vs_mesh():
    big = Circuit(36).h(0)  # 2^36 f64 amps = 1 TiB state
    diags = analyze(big, num_devices=1, precision=2)
    assert AnalysisCode.STATE_EXCEEDS_MESH_MEMORY in codes(diags)
    # sharded wide enough, the same circuit fits (pass needs no devices)
    from quest_tpu.parallel.planner import V5P
    ok = analyze(big, num_devices=256, precision=2, chip=V5P)
    assert AnalysisCode.STATE_EXCEEDS_MESH_MEMORY not in codes(ok)


def test_ir_plane_storage_compat(monkeypatch):
    monkeypatch.setattr(qmod, "PLANE_STORAGE_MIN_BYTES", 2 * 4 * (1 << 4))
    c = Circuit(4).cnot(0, 1)
    c.h(2)
    diags = analyze(c, num_devices=1, precision=1)
    flagged = [d for d in diags if d.code == ErrorCode.PLANE_ONLY_1Q]
    assert len(flagged) == 1 and flagged[0].op_index == 0
    assert flagged[0].severity == Severity.WARNING
    # f64 registers never take plane storage: no warning
    assert ErrorCode.PLANE_ONLY_1Q not in codes(
        analyze(c, num_devices=1, precision=2))


def test_ir_hint_adjacent_inverse_pair():
    c = Circuit(2).h(0).h(0)
    assert AnalysisCode.ADJACENT_INVERSE_PAIR in codes(analyze(c))
    c2 = Circuit(2).x(1).x(1)
    assert AnalysisCode.ADJACENT_INVERSE_PAIR in codes(analyze(c2))
    c3 = Circuit(12)
    c3.multi_rotate_z(tuple(range(12)), 0.7)   # O(1)-payload mrz kind
    c3.multi_rotate_z(tuple(range(12)), -0.7)
    assert AnalysisCode.ADJACENT_INVERSE_PAIR in codes(analyze(c3))


def test_ir_hint_fusable_1q_run():
    c = Circuit(3).h(1).t(1)
    c.rx(1, 0.3)
    diags = [d for d in analyze(c) if d.code == AnalysisCode.FUSABLE_1Q_RUN]
    assert len(diags) == 1 and diags[0].severity == Severity.HINT


def test_ir_clean_circuits_have_no_findings():
    assert analyze(qt.qft_circuit(5)) == []
    assert analyze(qt.random_circuit(4, 3)) == []
    # a mesh deployment whose shards hold whole lane rows stays clean...
    assert analyze(qt.qft_circuit(12), num_devices=8, precision=2) == []
    # ...while a sub-lane-row shard (6q x 8 = 8 amps/shard) now warns: the
    # wire-position comm model is incomplete there (planner.sub_tile_shard)
    found = analyze(qt.qft_circuit(6), num_devices=8, precision=2)
    assert [d.code for d in found] == [AnalysisCode.SUBTILE_SHARD]


# ---------------------------------------------------------------------------
# pass 2: eager-vs-compiled abstract-eval consistency
# ---------------------------------------------------------------------------

def _mrz_circuit():
    c = Circuit(3)
    c.ops.append(GateOp("mrz", (0, 1, 2), (), (), (0.5,), None))
    return c


def test_abstract_eval_clean_on_real_circuits():
    for circuit in (qt.qft_circuit(4), qt.random_circuit(3, 2),
                    _mrz_circuit()):
        for dtype in (jnp.float32, jnp.float64):
            assert an.check_abstract_eval(circuit, dtype=dtype) == []


def test_abstract_eval_catches_angle_dtype_drift(monkeypatch):
    """The circuit.py:208 bug class re-seeded: compiled path casting the mrz
    angle to the state dtype must be flagged with a stable code."""
    orig = cmod.op_operands

    def buggy(op, state_dtype):
        if op.kind == "mrz":
            return {"angle": jnp.asarray(op.matrix[0], dtype=state_dtype)}
        return orig(op, state_dtype)

    monkeypatch.setattr(cmod, "op_operands", buggy)
    diags = an.check_abstract_eval(_mrz_circuit(), dtype=jnp.float32)
    assert codes(diags) == [AnalysisCode.OPERAND_DTYPE_DRIFT]
    assert diags[0].severity == Severity.ERROR and diags[0].op_index == 0
    # at f64 the buggy cast coincides with the contract: nothing to flag
    assert an.check_abstract_eval(_mrz_circuit(), dtype=jnp.float64) == []


def test_abstract_eval_catches_output_dtype_mismatch(monkeypatch):
    """A compiled path that promotes the state dtype (e.g. an f64 constant
    multiplied in without a cast) diverges from eager output dtype."""
    from quest_tpu.analysis import abstract_eval as ae

    monkeypatch.setitem(ae.EAGER_MIRROR, "mrz",
                        lambda state, op: state.astype(jnp.float64))
    diags = an.check_abstract_eval(_mrz_circuit(), dtype=jnp.float32)
    assert AnalysisCode.EAGER_COMPILED_DTYPE_MISMATCH in codes(diags)


def test_abstract_eval_catches_shape_mismatch(monkeypatch):
    from quest_tpu.analysis import abstract_eval as ae

    monkeypatch.setitem(ae.EAGER_MIRROR, "mrz",
                        lambda state, op: state[:, ::2])
    diags = an.check_abstract_eval(_mrz_circuit(), dtype=jnp.float32)
    assert AnalysisCode.EAGER_COMPILED_SHAPE_MISMATCH in codes(diags)


def test_abstract_eval_skips_semantically_invalid_ops():
    """Ops the IR pass rejects (bad wires) fail to trace on BOTH paths; the
    checker must skip them instead of crashing, leaving the finding to the
    IR pass — the CLI runs both passes together."""
    c = Circuit(3).x(7)
    c.unitary(0, [[1.0, 1.0], [0.0, 1.0]])  # traces fine, flagged by IR
    assert an.check_abstract_eval(c, dtype=jnp.float32) == []
    assert ErrorCode.INVALID_TARGET_QUBIT in codes(an.analyze_circuit(c))


def test_compiled_mrz_angle_is_float64():
    """The invariant itself, not just the checker: the compiled path builds
    the mrz angle wide (satellite fix for circuit.py:208)."""
    import jax

    op = _mrz_circuit().ops[0]
    operands = jax.eval_shape(lambda: cmod.op_operands(op, jnp.float32))
    assert operands["angle"].dtype == jnp.dtype(jnp.float64)


def test_eager_and_compiled_mrz_agree_numerically():
    """End-to-end: a wide multiRotateZ through the eager API and through a
    compiled Circuit produces the same f32 state."""
    env = qt.createQuESTEnv(1)
    targets = tuple(range(12))  # >10 targets: the mrz kernel path
    qe = qt.createQureg(12, env, dtype=jnp.float32)
    qt.multiRotateZ(qe, targets, 0.37)
    c = Circuit(12).multi_rotate_z(targets, 0.37)
    qc = qt.createQureg(12, env, dtype=jnp.float32)
    qt.apply_circuit(qc, c)
    np.testing.assert_array_equal(np.asarray(qe.amps), np.asarray(qc.amps))


# ---------------------------------------------------------------------------
# pass 3: source purity lint
# ---------------------------------------------------------------------------

def lint_codes(src):
    return codes(an.lint_source(src, "seed.py"))


def test_lint_traced_python_branch():
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    if x > 0:\n"
        "        return x\n"
        "    return -x\n")
    assert lint_codes(src) == [AnalysisCode.TRACED_PYTHON_BRANCH]


def test_lint_traced_while():
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    while x < 3:\n"
        "        x = x + 1\n"
        "    return x\n")
    assert lint_codes(src) == [AnalysisCode.TRACED_PYTHON_BRANCH]


def test_lint_host_cast_on_traced():
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return float(x)\n")
    assert lint_codes(src) == [AnalysisCode.HOST_CAST_ON_TRACED]


def test_lint_numpy_on_traced():
    src = (
        "import jax\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return np.sum(x)\n")
    assert lint_codes(src) == [AnalysisCode.NUMPY_ON_TRACED]


def test_lint_angle_not_f64():
    src = (
        "import jax.numpy as jnp\n"
        "def f(state, op):\n"
        "    return apply_multi_rotate_z(\n"
        "        state, jnp.asarray(op.matrix[0], dtype=state.dtype),\n"
        "        op.targets)\n")
    assert lint_codes(src) == [AnalysisCode.ANGLE_NOT_F64]
    ok = (
        "import jax.numpy as jnp\n"
        "def f(state, op):\n"
        "    return apply_multi_rotate_z(\n"
        "        state, jnp.asarray(op.matrix[0], dtype=jnp.float64),\n"
        "        op.targets)\n")
    assert lint_codes(ok) == []


def test_lint_callback_in_shard_map():
    src = (
        "import jax\n"
        "from functools import partial\n"
        "@partial(shard_map, mesh=None, in_specs=None, out_specs=None)\n"
        "def f(shard):\n"
        "    jax.debug.callback(print, shard)\n"
        "    return shard\n")
    assert lint_codes(src) == [AnalysisCode.CALLBACK_IN_SHARD_MAP]


def test_lint_statics_and_metadata_are_clean():
    """No false positives: static args, dtype/shape metadata branches, and
    host code outside jit are all fine."""
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "from functools import partial\n"
        "@partial(jax.jit, static_argnames=('k',))\n"
        "def f(x, k):\n"
        "    if k:\n"
        "        return x\n"
        "    if x.dtype == jnp.float32:\n"
        "        return x * 2\n"
        "    return x\n"
        "def host(y):\n"
        "    if y > 0:\n"
        "        return float(y) + np.sum(y)\n"
        "    return y\n")
    assert lint_codes(src) == []


def test_lint_import_time_config_mutation(tmp_path):
    """Module-import-time jax.config / RNG mutation is flagged; the same
    code inside a function is not; _compat.py is the allowlisted site."""
    bad = (
        "import jax\n"
        "import numpy as np\n"
        "jax.config.update('jax_enable_x64', True)\n"
        "np.random.seed(0)\n"
        "if True:\n"
        "    jax.config.update('jax_platforms', 'cpu')\n")
    found = lint_codes(bad)
    assert found == [AnalysisCode.IMPORT_TIME_STATE_MUTATION] * 3
    ok = (
        "import jax\n"
        "def configure():\n"
        "    jax.config.update('jax_enable_x64', True)\n")
    assert lint_codes(ok) == []
    # attribute assignment counts as mutation too
    assign = "import jax\njax.config.jax_enable_x64 = True\n"
    assert lint_codes(assign) == [AnalysisCode.IMPORT_TIME_STATE_MUTATION]
    # fixture at the allowlisted PATH quest_tpu/_compat.py: exempt
    pkg = tmp_path / "quest_tpu"
    pkg.mkdir()
    fixture = pkg / "_compat.py"
    fixture.write_text(bad)
    assert an.lint_paths([str(fixture)]) == []
    # a stray _compat.py elsewhere is NOT exempt (suffix match, not name)
    stray = tmp_path / "_compat.py"
    stray.write_text(bad)
    assert len(an.lint_paths([str(stray)])) == 3
    other = pkg / "other.py"
    other.write_text(bad)
    assert len(an.lint_paths([str(other)])) == 3


def test_import_time_mutation_allowlist_is_exactly_two_sites():
    """The satellite contract: the tree's only import-time process-state
    mutations are quest_tpu/_compat.py (the jax.config x64 default) and
    quest_tpu/obs/trace.py (the span recorder's atexit dump hook) — both
    allowlisted; the SAME sources renamed away from the allowlist trip
    the rule, so no third site can appear silently."""
    import os

    from quest_tpu.analysis import purity as pmod

    pkg_root = os.path.dirname(os.path.abspath(an.__file__))
    pkg_root = os.path.dirname(pkg_root)
    diags = [d for d in an.lint_paths([pkg_root])
             if d.code == AnalysisCode.IMPORT_TIME_STATE_MUTATION]
    assert diags == []
    for rel in ("_compat.py", os.path.join("obs", "trace.py")):
        src = os.path.join(pkg_root, rel)
        with open(src, encoding="utf-8") as fh:
            found = an.lint_source(fh.read(),
                                   "renamed_away_from_allowlist.py")
        hits = [d for d in found
                if d.code == AnalysisCode.IMPORT_TIME_STATE_MUTATION]
        assert len(hits) == 1, (rel, [d.format() for d in found])
    assert pmod._IMPORT_MUTATION_ALLOWLIST == ("quest_tpu/_compat.py",
                                               "quest_tpu/obs/trace.py")


def test_lint_self_clean():
    """The quest_tpu tree itself is clean under the purity rules — the CI
    gate (`python -m quest_tpu.analysis --self-lint`) stays green."""
    assert an.lint_package() == []


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def test_cli_self_lint_exits_zero(capsys):
    from quest_tpu.analysis.__main__ import main
    assert main(["--self-lint"]) == 0
    assert "0 at/above error" in capsys.readouterr().out


def test_cli_circuit_modes(capsys):
    from quest_tpu.analysis.__main__ import main
    assert main(["--qft", "4", "--random", "3", "2"]) == 0
    out = capsys.readouterr().out
    assert "qft(4)" in out and "random(3,2)" in out


def test_cli_lint_flags_bad_file(tmp_path, capsys):
    from quest_tpu.analysis.__main__ import main
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return float(x)\n")
    assert main(["--lint", str(bad)]) == 1
    assert AnalysisCode.HOST_CAST_ON_TRACED in capsys.readouterr().out


def test_cli_no_mode_is_usage_error():
    from quest_tpu.analysis.__main__ import main
    assert main([]) == 2


def test_cli_json_is_one_parseable_document(capsys):
    """--json emits ONE JSON document with diagnostics + summary — the
    machine-readable contract the CI gates parse (no text grepping)."""
    import json

    from quest_tpu.analysis.__main__ import main
    assert main(["--self-lint", "--qft", "4", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["summary"]["counts"]["ERROR"] == 0
    assert doc["summary"]["fail_at"] == "ERROR"
    assert any(c["label"] == "qft(4)" for c in doc["circuits"])
    assert isinstance(doc["diagnostics"], list)


def test_cli_json_carries_severities(tmp_path, capsys):
    import json

    from quest_tpu.analysis.__main__ import main
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return float(x)\n")
    assert main(["--lint", str(bad), "--json"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["summary"]["counts"]["ERROR"] == 1
    assert doc["diagnostics"][0]["code"] == AnalysisCode.HOST_CAST_ON_TRACED
    assert doc["diagnostics"][0]["severity"] == "ERROR"


def test_cli_concurrency_json_roundtrip(capsys):
    """``--concurrency`` honors the repo-wide ONE-JSON-document contract
    and the shared severity schema: the document parses, carries the
    ``concurrency`` section (classes, lock graph, fuzz placeholder), its
    findings land in the same ``diagnostics``/``summary`` sections every
    other mode uses, and the counts are internally consistent — the CI
    gate PARSES this, it does not grep."""
    import json

    from quest_tpu.analysis.__main__ import main
    assert main(["--concurrency", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    # one document, all standard sections present
    for key in ("circuits", "schedule", "verify", "serve_audit",
                "trace_report", "concurrency", "diagnostics", "summary"):
        assert key in doc, sorted(doc)
    c = doc["concurrency"]
    assert c["files"] > 0
    assert {"name", "file", "line", "locks", "attrs", "findings"} <= set(
        c["classes"][0])
    assert set(c["lock_graph"]) == {"edges", "cycles"}
    assert c["fuzz"] is None            # smoke not requested
    # severity schema identical to every other mode
    assert doc["summary"]["counts"]["ERROR"] == 0
    assert set(doc["summary"]["counts"]) == {"HINT", "WARNING", "ERROR"}
    assert doc["summary"]["diagnostics"] == len(doc["diagnostics"])
    # a tree with a seeded violation exits 1 through the same document
    import quest_tpu.deploy.router as router_mod
    from quest_tpu.analysis import concurrency as cc
    with open(router_mod.__file__, encoding="utf-8") as fh:
        mutated = cc.strip_first_lock_scope(fh.read())
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        bad = f"{td}/router_mutated.py"
        with open(bad, "w", encoding="utf-8") as fh:
            fh.write(mutated)
        assert main(["--concurrency-paths", bad, "--json"]) == 1
        doc = json.loads(capsys.readouterr().out)
    assert doc["summary"]["counts"]["ERROR"] >= 1
    assert any(d["code"] == AnalysisCode.UNGUARDED_SHARED_WRITE
               and d["severity"] == "ERROR" for d in doc["diagnostics"])


def test_cli_verify_schedule_mode(capsys):
    """--verify-schedule runs the translation validator + lowered audit and
    reports a proven-equivalent rewrite for the shipped scheduler."""
    import json

    from quest_tpu.analysis.__main__ import main
    assert main(["--qft", "10", "--devices", "4", "--verify-schedule",
                 "--no-hints", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert len(doc["verify"]) == 1
    v = doc["verify"][0]
    assert v["proven_equivalent"] is True
    assert v["equivalence_diagnostics"] == 0
    assert len(doc["schedule"]) == 1  # --verify-schedule implies scheduling


# ---------------------------------------------------------------------------
# satellite regression: the precision-4 warning tells the truth
# ---------------------------------------------------------------------------

def test_precision4_warning_matches_get_precision():
    from quest_tpu import precision as pmod

    prev = qt.get_precision()
    pmod._WARNED_PREC4 = False
    try:
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            qt.set_precision(4)
        assert qt.get_precision() == 4  # retained, exactly as the text says
        msgs = [str(w.message) for w in caught
                if issubclass(w.category, RuntimeWarning)]
        assert len(msgs) == 1
        assert "retained" in msgs[0] and "float64" in msgs[0]
        assert "mapping to precision 2" not in msgs[0]
        # storage really is float64
        assert pmod.CONFIG.real_dtype == jnp.float64
        assert qt.real_eps() == 1e-14
    finally:
        pmod._WARNED_PREC4 = False
        qt.set_precision(prev)
