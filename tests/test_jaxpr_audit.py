"""Lowered-jaxpr / compiled-HLO audit (analysis/jaxpr_audit.py): collective
counting at both levels, the planner cross-check, and the donation audit —
on the same 8-virtual-device CPU mesh the rest of the suite uses."""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from quest_tpu.analysis import AnalysisCode, Severity
from quest_tpu.analysis.jaxpr_audit import (audit_dispatch,
                                            audit_schedule_pair,
                                            count_hlo_collectives,
                                            count_jaxpr_collectives,
                                            donation_aliased)
from quest_tpu.circuit import Circuit, qft_circuit


def codes(diags):
    return [d.code for d in diags]


# ---------------------------------------------------------------------------
# jaxpr-level counting
# ---------------------------------------------------------------------------

def test_gspmd_dispatch_path_has_no_explicit_collectives():
    """The compiled gate path relies on the partitioner: its traced jaxpr
    must contain ZERO explicit collective primitives."""
    from quest_tpu.analysis.jaxpr_audit import make_dispatch_jaxpr
    c = qft_circuit(10)
    assert count_jaxpr_collectives(make_dispatch_jaxpr(c)) == {}


def test_shard_map_collectives_are_counted(env_dist):
    """The manual shard_map kernels (parallel/collectives.py) show exactly
    their documented primitives through the recursive eqn walk."""
    from quest_tpu.parallel import collectives as coll
    mesh = env_dist.mesh
    st = jnp.zeros((2, 1 << 12), jnp.float32)
    jx = jax.make_jaxpr(lambda s: coll.pairwise_exchange(s, mesh, 1))(st)
    assert count_jaxpr_collectives(jx) == {"ppermute": 1}
    jx = jax.make_jaxpr(lambda s: coll.global_sum(s, mesh))(st)
    counts = count_jaxpr_collectives(jx)
    # the psum primitive is spelled psum2 on some jax versions
    assert counts.get("psum", 0) + counts.get("psum2", 0) >= 1, counts


# ---------------------------------------------------------------------------
# HLO-level counting helpers (pure text parsing)
# ---------------------------------------------------------------------------

_FAKE_HLO = """\
HloModule m, input_output_alias={ {}: (0, {}, may-alias) }
%all-gather = f32[2,4096]{1,0} all-gather(f32[2,512]{1,0} %p0)
%all-reduce.1 = f32[8]{0} all-reduce(f32[8]{0} %small)
%collective-permute = f32[2,512]{1,0} collective-permute(f32[2,512]{1,0} %x)
"""


def test_hlo_collective_count_filters_small_ops():
    all_ops = count_hlo_collectives(_FAKE_HLO)
    assert all_ops == {"all-gather": 1, "all-reduce": 1,
                       "collective-permute": 1}
    big = count_hlo_collectives(_FAKE_HLO, min_elems=256)
    assert big == {"all-gather": 1, "collective-permute": 1}


def test_donation_alias_detection():
    assert donation_aliased(_FAKE_HLO)
    assert not donation_aliased("HloModule m\n%add = f32[2] add(...)")


# ---------------------------------------------------------------------------
# the audit against the planner model
# ---------------------------------------------------------------------------

def test_local_circuit_audits_clean(env_dist):
    """A circuit the planner models comm-free must compile with zero
    state-sized collectives — no A_UNEXPECTED_ALLGATHER."""
    c = Circuit(12).h(0).cnot(0, 1).t(2)
    report, diags = audit_dispatch(c, 8, label="local")
    assert report["predicted_comm_events"] == 0
    assert report["hlo_collectives"] == {}
    assert diags == []
    assert report["donation_aliased"]


def test_sharded_circuit_audit_within_model_bound(env_dist):
    """The scheduled QFT's compiled collective count stays within the
    per-event lowering bound of the planner prediction (the acceptance
    cross-check, at the 12q size the suite can afford to compile)."""
    from quest_tpu.analysis.jaxpr_audit import _HLO_OPS_PER_EVENT
    c = qft_circuit(12)
    s = c.schedule(8)
    report, diags = audit_dispatch(s, 8, label="qft12")
    measured = sum(report["hlo_collectives"].values())
    assert measured > 0  # the mesh really communicates
    assert measured <= _HLO_OPS_PER_EVENT * report["predicted_comm_events"], \
        report
    assert AnalysisCode.COLLECTIVE_COUNT_MISMATCH not in codes(diags)
    assert AnalysisCode.UNEXPECTED_ALLGATHER not in codes(diags)


def test_schedule_pair_audit_no_hlo_regression(env_dist):
    """HLO-level scheduler gate: the scheduled member of the 16q QFT pair
    (the smallest whose swap network fuses) compiles to no MORE state-sized
    collectives than the unscheduled one."""
    c = qft_circuit(16)
    s = c.schedule(8)
    report, diags = audit_schedule_pair(c, s, 8, label="qft16")
    assert diags == [], [d.format() for d in diags]
    assert (sum(report["scheduled_hlo"].values())
            <= sum(report["unscheduled_hlo"].values())), report


def test_audit_skips_hlo_when_mesh_too_small():
    """Requesting more devices than exist degrades to the host-only audit
    (jaxpr walk + predictions), not an error."""
    c = Circuit(10).h(9)
    report, diags = audit_dispatch(c, 1024, label="huge")
    assert report["hlo_collectives"] is None
    assert report["donation_aliased"] is None
    assert diags == []
