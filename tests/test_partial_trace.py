"""Partial trace and entanglement entropy (TPU-native extensions:
calcPartialTrace / calcVonNeumannEntropy — no v3.2 analogue)."""

from __future__ import annotations

import numpy as np
import pytest

import quest_tpu as qt
from conftest import ON_ACCELERATOR
from oracle import (DM_TOL, NUM_QUBITS, dm, random_density_matrix,
                    random_statevector, set_dm, set_sv)

N = NUM_QUBITS
# entropies pass through an eigendecomposition of f32-computed amplitudes on
# the accelerator platform; exact-arithmetic tolerances only hold at f64
ENT_TOL = 1e-4 if ON_ACCELERATOR else 1e-9


def _oracle_ptrace(rho: np.ndarray, n: int, keep) -> np.ndarray:
    """Independent dense reduction, elementwise over kept/traced bits."""
    m = len(keep)
    out = np.zeros((1 << m, 1 << m), dtype=complex)
    traced = [q for q in range(n) if q not in keep]
    for r in range(1 << n):
        for c in range(1 << n):
            if any(((r >> q) & 1) != ((c >> q) & 1) for q in traced):
                continue
            a = sum(((r >> q) & 1) << i for i, q in enumerate(keep))
            b = sum(((c >> q) & 1) << i for i, q in enumerate(keep))
            out[a, b] += rho[r, c]
    return out


@pytest.mark.parametrize("trace_out", [[0], [4], [1, 3], [0, 2, 4]])
def test_partial_trace_density(env, trace_out):
    rho_q = qt.createDensityQureg(N, env)
    rho = random_density_matrix(N)
    set_dm(rho_q, rho)
    red = qt.calcPartialTrace(rho_q, trace_out)
    keep = [q for q in range(N) if q not in trace_out]
    assert red.is_density_matrix and red.num_qubits_represented == len(keep)
    np.testing.assert_allclose(dm(red), _oracle_ptrace(rho, N, keep),
                               atol=10 * DM_TOL)
    assert qt.calcTotalProb(red) == pytest.approx(1.0, abs=10 * DM_TOL)


@pytest.mark.parametrize("trace_out", [[0], [2, 4], [1, 2, 3], [0, 1]])
def test_partial_trace_statevector(env, trace_out):
    psi = qt.createQureg(N, env)
    vec = random_statevector(N)
    set_sv(psi, vec)
    red = qt.calcPartialTrace(psi, trace_out)
    keep = [q for q in range(N) if q not in trace_out]
    np.testing.assert_allclose(dm(red), _oracle_ptrace(np.outer(vec, vec.conj()), N, keep),
                               atol=10 * DM_TOL)
    # input register untouched
    assert qt.calcTotalProb(psi) == pytest.approx(1.0, abs=DM_TOL)


def test_partial_trace_bell(env_local):
    """Tracing one side of a Bell pair leaves the maximally mixed qubit."""
    psi = qt.createQureg(2, env_local)
    qt.hadamard(psi, 0)
    qt.controlledNot(psi, 0, 1)
    red = qt.calcPartialTrace(psi, [1])
    np.testing.assert_allclose(dm(red), np.eye(2) / 2, atol=DM_TOL)


def test_partial_trace_product_state(env_local):
    """A product state reduces to the exact single-qubit factor."""
    psi = qt.createQureg(3, env_local)
    qt.rotateY(psi, 1, 0.8)
    red = qt.calcPartialTrace(psi, [0, 2])
    c, s = np.cos(0.4), np.sin(0.4)
    expect = np.outer([c, s], [c, s])
    np.testing.assert_allclose(dm(red), expect, atol=DM_TOL)


def test_partial_trace_wide_traced_block(env_local):
    """Tracing >= 7 qubits exercises the identity-contraction branch (the
    default-suite circuits only reach the small-t slice branch)."""
    n = 9
    psi = qt.createQureg(n, env_local)
    vec = random_statevector(n)
    set_sv(psi, vec)
    keep = [1, 8]
    red = qt.calcPartialTrace(psi, [q for q in range(n) if q not in keep])
    np.testing.assert_allclose(
        dm(red), _oracle_ptrace(np.outer(vec, vec.conj()), n, keep),
        atol=10 * DM_TOL)
    rho_q = qt.createDensityQureg(n, env_local)
    qt.hadamard(rho_q, 1)
    qt.controlledNot(rho_q, 1, 8)
    qt.mixDephasing(rho_q, 8, 0.2)
    red2 = qt.calcPartialTrace(rho_q, [q for q in range(n) if q not in keep])
    assert qt.calcTotalProb(red2) == pytest.approx(1.0, abs=10 * DM_TOL)
    # dephasing shrinks the off-diagonal Bell coherence by 1-2p
    amp = qt.getDensityAmp(red2, 0, 3)
    assert amp.real == pytest.approx(0.5 * (1 - 2 * 0.2), abs=10 * DM_TOL)


def test_partial_trace_validation(env_local):
    psi = qt.createQureg(3, env_local)
    with pytest.raises(qt.QuESTError):
        qt.calcPartialTrace(psi, [0, 1, 2])  # nothing left
    with pytest.raises(qt.QuESTError):
        qt.calcPartialTrace(psi, [3])
    with pytest.raises(qt.QuESTError):
        qt.calcPartialTrace(psi, [1, 1])


def test_entropy_bell_and_ghz(env_local):
    psi = qt.createQureg(2, env_local)
    qt.hadamard(psi, 0)
    qt.controlledNot(psi, 0, 1)
    # half a Bell pair carries exactly 1 bit of entanglement entropy
    assert qt.calcVonNeumannEntropy(psi, [0]) == pytest.approx(1.0, abs=max(1e-6, ENT_TOL))
    # the full pure state carries none
    assert qt.calcVonNeumannEntropy(psi) == pytest.approx(0.0, abs=ENT_TOL)

    ghz = qt.createQureg(4, env_local)
    qt.hadamard(ghz, 0)
    for i in range(3):
        qt.controlledNot(ghz, i, i + 1)
    # any bipartition of a GHZ state has entropy 1 bit
    assert qt.calcVonNeumannEntropy(ghz, [0, 1]) == pytest.approx(1.0, abs=max(1e-6, ENT_TOL))
    assert qt.calcVonNeumannEntropy(ghz, [2]) == pytest.approx(1.0, abs=max(1e-6, ENT_TOL))


def test_entropy_mixed_density(env_local):
    rho = qt.createDensityQureg(2, env_local)
    # maximally mixed 2-qubit state: entropy 2 bits; each qubit 1 bit
    set_dm(rho, np.eye(4) / 4)
    assert qt.calcVonNeumannEntropy(rho) == pytest.approx(2.0, abs=ENT_TOL)
    assert qt.calcVonNeumannEntropy(rho, [1]) == pytest.approx(1.0, abs=ENT_TOL)
    # natural-log units
    assert qt.calcVonNeumannEntropy(rho, base=np.e) == pytest.approx(
        2.0 * np.log(2.0), abs=ENT_TOL)


def test_entropy_pure_statevector_subsets_match_complement(env_local):
    """For a pure state, S(A) == S(complement of A)."""
    psi = qt.createQureg(4, env_local)
    vec = random_statevector(4)
    set_sv(psi, vec)
    sa = qt.calcVonNeumannEntropy(psi, [0, 3])
    sb = qt.calcVonNeumannEntropy(psi, [1, 2])
    assert sa == pytest.approx(sb, abs=max(1e-8, ENT_TOL))
