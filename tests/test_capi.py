"""The C front-end (native/capi): compile and run a C program against
libquest_tpu_c and check its output."""

from __future__ import annotations

import os
import shutil
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CAPI = os.path.join(ROOT, "native", "capi")
LIB = os.path.join(CAPI, "build", "libquest_tpu_c.so")

C_PROGRAM = r"""
#include <stdio.h>
#include "QuEST.h"

int main(void) {
    QuESTEnv env = createQuESTEnv();
    Qureg q = createQureg(3, env);
    initZeroState(q);
    hadamard(q, 0);
    controlledNot(q, 0, 1);
    rotateY(q, 2, 0.1);
    printf("amp0=%.10f\n", getRealAmp(q, 0));
    printf("total=%.10f\n", calcTotalProb(q));
    printf("p2=%.10f\n", calcProbOfOutcome(q, 2, 1));
    destroyQureg(q, env);
    destroyQuESTEnv(env);
    return 0;
}
"""


@pytest.fixture(scope="module")
def c_binary(tmp_path_factory):
    if shutil.which("gcc") is None:
        pytest.skip("no C compiler")
    if not os.path.exists(LIB):
        r = subprocess.run([os.path.join(CAPI, "build.sh")],
                           capture_output=True, text=True)
        if r.returncode != 0:
            pytest.skip(f"C shim build failed: {r.stderr[-500:]}")
    d = tmp_path_factory.mktemp("capi")
    src = d / "prog.c"
    src.write_text(C_PROGRAM)
    binary = d / "prog"
    subprocess.run(["gcc", str(src), "-I", CAPI,
                    "-L", os.path.dirname(LIB), "-lquest_tpu_c",
                    f"-Wl,-rpath,{os.path.dirname(LIB)}", "-o", str(binary)],
                   check=True, capture_output=True)
    return binary


def test_c_program_runs(c_binary):
    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    out = subprocess.run([str(c_binary)], capture_output=True, text=True,
                         env=env, timeout=300)
    assert out.returncode == 0, out.stderr[-500:]
    vals = dict(line.split("=") for line in out.stdout.strip().splitlines()
                if "=" in line)
    # H(0) CNOT(0,1) RY(2, .1): amp0 = cos(.05)/sqrt(2)
    import math
    assert abs(float(vals["amp0"]) - math.cos(0.05) / math.sqrt(2)) < 1e-9
    assert abs(float(vals["total"]) - 1.0) < 1e-9
    assert abs(float(vals["p2"]) - math.sin(0.05) ** 2) < 1e-9
