"""The C front-end (native/capi): compile and run a C program against
libquest_tpu_c and check its output."""

from __future__ import annotations

import os
import shutil
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CAPI = os.path.join(ROOT, "native", "capi")
LIB = os.path.join(CAPI, "build", "libquest_tpu_c.so")

C_PROGRAM = r"""
#include <stdio.h>
#include "QuEST.h"

int main(void) {
    QuESTEnv env = createQuESTEnv();
    Qureg q = createQureg(3, env);
    initZeroState(q);
    hadamard(q, 0);
    controlledNot(q, 0, 1);
    rotateY(q, 2, 0.1);
    printf("amp0=%.10f\n", getRealAmp(q, 0));
    printf("total=%.10f\n", calcTotalProb(q));
    printf("p2=%.10f\n", calcProbOfOutcome(q, 2, 1));
    destroyQureg(q, env);
    destroyQuESTEnv(env);
    return 0;
}
"""


@pytest.fixture(scope="module")
def c_binary(tmp_path_factory):
    if shutil.which("gcc") is None:
        pytest.skip("no C compiler")
    if not os.path.exists(LIB):
        r = subprocess.run([os.path.join(CAPI, "build.sh")],
                           capture_output=True, text=True)
        if r.returncode != 0:
            pytest.skip(f"C shim build failed: {r.stderr[-500:]}")
    d = tmp_path_factory.mktemp("capi")
    src = d / "prog.c"
    src.write_text(C_PROGRAM)
    binary = d / "prog"
    subprocess.run(["gcc", str(src), "-I", CAPI,
                    "-L", os.path.dirname(LIB), "-lquest_tpu_c",
                    f"-Wl,-rpath,{os.path.dirname(LIB)}", "-o", str(binary)],
                   check=True, capture_output=True)
    return binary


def test_c_program_runs(c_binary):
    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    out = subprocess.run([str(c_binary)], capture_output=True, text=True,
                         env=env, timeout=300)
    assert out.returncode == 0, out.stderr[-500:]
    vals = dict(line.split("=") for line in out.stdout.strip().splitlines()
                if "=" in line)
    # H(0) CNOT(0,1) RY(2, .1): amp0 = cos(.05)/sqrt(2)
    import math
    assert abs(float(vals["amp0"]) - math.cos(0.05) / math.sqrt(2)) < 1e-9
    assert abs(float(vals["total"]) - 1.0) < 1e-9
    assert abs(float(vals["p2"]) - math.sin(0.05) ** 2) < 1e-9


RUN_ENV = {"PYTHONPATH": ROOT, "JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""}


def _run(binary, timeout=300):
    env = dict(os.environ)
    env.update(RUN_ENV)
    # the C program must see a single-device environment (conftest exports
    # XLA_FLAGS for the 8-virtual-device mesh, under which a 3-qubit gate on
    # a 3-qubit state correctly fails the per-shard fits-in-node rule)
    env.pop("XLA_FLAGS", None)
    return subprocess.run([str(binary)], capture_output=True, text=True,
                          env=env, timeout=timeout)


REF_EXAMPLES = "/root/reference/examples"


@pytest.fixture(scope="module")
def example_binaries(tmp_path_factory, c_binary):
    """Compile the reference's own example .c files VERBATIM against the shim
    (c_binary dependency just ensures the shim library is built)."""
    d = tmp_path_factory.mktemp("ref_examples")
    out = {}
    for name in ["tutorial_example", "bernstein_vazirani_circuit",
                 "damping_example"]:
        src = os.path.join(REF_EXAMPLES, f"{name}.c")
        if not os.path.exists(src):
            pytest.skip("reference examples not mounted")
        binary = d / name
        subprocess.run(["gcc", src, "-I", CAPI,
                        "-L", os.path.dirname(LIB), "-lquest_tpu_c",
                        f"-Wl,-rpath,{os.path.dirname(LIB)}", "-lm",
                        "-o", str(binary)],
                       check=True, capture_output=True)
        out[name] = binary
    return out


def test_reference_tutorial_verbatim(example_binaries):
    """examples/tutorial_example.c compiled unchanged; deterministic output
    lines match the reference binary (measurement lines are RNG-seeded)."""
    r = _run(example_binaries["tutorial_example"])
    assert r.returncode == 0, r.stderr[-500:]
    assert "Probability amplitude of |111>: 0.112422" in r.stdout
    assert "Probability of qubit 2 being in state 1: 0.749178" in r.stdout
    assert "Number of amps per rank is 8." in r.stdout


def test_reference_bernstein_vazirani_verbatim(example_binaries):
    """examples/bernstein_vazirani_circuit.c: full stdout is byte-identical
    to the reference binary."""
    r = _run(example_binaries["bernstein_vazirani_circuit"])
    assert r.returncode == 0, r.stderr[-500:]
    assert r.stdout == "solution reached with probability 1.000000\n"


def test_reference_damping_verbatim(example_binaries):
    """examples/damping_example.c: full stdout is byte-identical to the
    reference binary (deterministic channel, %.14f report format)."""
    r = _run(example_binaries["damping_example"])
    assert r.returncode == 0, r.stderr[-500:]
    tail = r.stdout[r.stdout.rindex("Reporting state ["):]
    assert tail == ("Reporting state [\n"
                    "real, imag\n"
                    "0.82566077995000, 0.00000000000000\n"
                    "0.29524500000000, 0.00000000000000\n"
                    "0.29524500000000, 0.00000000000000\n"
                    "0.17433922005000, 0.00000000000000\n"
                    "]\n")


HOOK_PROGRAM = r"""
#include <stdio.h>
#include <stdexcept>
#include <string>
#include "QuEST.h"

// override the weak error hook, exactly like the reference test suite
// (ref: tests/main.cpp:27-29)
extern "C" void invalidQuESTInputError(const char* errMsg, const char* errFunc) {
    throw std::runtime_error(std::string(errFunc) + "|" + errMsg);
}

int main(void) {
    QuESTEnv env = createQuESTEnv();
    Qureg q = createQureg(3, env);
    try {
        hadamard(q, 7);
        printf("NO_THROW\n");
    } catch (const std::runtime_error& e) {
        printf("CAUGHT: %s\n", e.what());
    }
    // the qureg must still be usable after a caught validation error
    hadamard(q, 0);
    printf("total=%.10f\n", calcTotalProb(q));
    destroyQureg(q, env);
    destroyQuESTEnv(env);
    return 0;
}
"""


def test_error_hook_override(tmp_path, c_binary):
    """The invalidQuESTInputError weak symbol can be overridden to throw —
    the mechanism the reference's Catch2 suite relies on."""
    src = tmp_path / "hook.cpp"
    src.write_text(HOOK_PROGRAM)
    binary = tmp_path / "hook"
    subprocess.run(["g++", str(src), "-I", CAPI,
                    "-L", os.path.dirname(LIB), "-lquest_tpu_c",
                    f"-Wl,-rpath,{os.path.dirname(LIB)}", "-o", str(binary)],
                   check=True, capture_output=True)
    r = _run(binary)
    assert r.returncode == 0, r.stderr[-500:]
    assert "CAUGHT: hadamard|Invalid target qubit. Must be >=0 and <numQubits." \
        in r.stdout
    assert "NO_THROW" not in r.stdout
    assert "total=1.0000000000" in r.stdout


C_SURFACE_PROGRAM = r"""
#include <stdio.h>
#include <math.h>
#include "QuEST.h"

int main(void) {
    QuESTEnv env = createQuESTEnv();
    char envStr[200];

    Qureg q = createQureg(4, env);
    Qureg work = createQureg(4, env);
    getEnvironmentString(env, q, envStr);

    /* unitaries across the full surface */
    initPlusState(q);
    controlledRotateX(q, 0, 1, 0.3);
    controlledRotateAroundAxis(q, 1, 2, 0.4, (Vector){0, 0, 1});
    int ctrls[] = {0, 1};
    int states[] = {0, 1};
    ComplexMatrix2 u2 = {.real = {{0, 1}, {1, 0}}, .imag = {{0, 0}, {0, 0}}};
    multiStateControlledUnitary(q, ctrls, states, 2, 3, u2);
    ComplexMatrix4 u4 = {.real = {{1,0,0,0},{0,1,0,0},{0,0,0,1},{0,0,1,0}},
                         .imag = {{0}}};
    twoQubitUnitary(q, 0, 1, u4);
    controlledTwoQubitUnitary(q, 3, 0, 1, u4);
    multiControlledTwoQubitUnitary(q, ctrls + 1, 1, 2, 3, u4);
    sqrtSwapGate(q, 0, 1);
    int zq[] = {0, 2};
    multiRotateZ(q, zq, 2, 0.7);
    enum pauliOpType ps[] = {PAULI_X, PAULI_Y};
    multiRotatePauli(q, zq, ps, 2, 0.2);
    controlledPauliY(q, 0, 3);

    /* calculations */
    cloneQureg(work, q);
    Complex ip = calcInnerProduct(work, q);
    printf("ip=%.10f\n", ip.real);
    printf("fid=%.10f\n", calcFidelity(q, work));
    Complex a0 = getAmp(q, 0);
    printf("amp0=%.10f amp0i=%.10f\n", a0.real, a0.imag);
    printf("numAmps=%lld numQubits=%d\n", getNumAmps(q), getNumQubits(q));

    enum pauliOpType codes[] = {PAULI_X, PAULI_I, PAULI_I, PAULI_I,
                                PAULI_Z, PAULI_Z, PAULI_I, PAULI_I};
    qreal coeffs[] = {0.3, -0.7};
    printf("exps=%.10f\n", calcExpecPauliSum(q, codes, coeffs, 2, work));
    PauliHamil h = createPauliHamil(4, 2);
    initPauliHamil(h, coeffs, codes);
    printf("exph=%.10f\n", calcExpecPauliHamil(q, h, work));
    Qureg out = createQureg(4, env);
    applyPauliHamil(q, h, out);
    applyTrotterCircuit(q, h, 0.1, 2, 3);
    destroyPauliHamil(h);

    /* diagonal op */
    DiagonalOp op = createDiagonalOp(4, env);
    for (long long i = 0; i < 16; i++) { op.real[i] = 1.0; op.imag[i] = 0.0; }
    syncDiagonalOp(op);
    applyDiagonalOp(q, op);
    Complex ed = calcExpecDiagonalOp(q, op);
    printf("ed=%.10f\n", ed.real);
    destroyDiagonalOp(op, env);

    /* state mirrors */
    copyStateFromGPU(q);
    printf("mirror0=%.10f\n", q.stateVec.real[0] * q.stateVec.real[0]
                              + q.stateVec.imag[0] * q.stateVec.imag[0]);
    copyStateToGPU(q);

    /* setAmps + weighted combination */
    qreal res[2] = {0.6, 0.0}, ims[2] = {0.0, 0.8};
    Qureg w2 = createQureg(1, env);
    setAmps(w2, 0, res, ims, 2);
    printf("w2total=%.10f\n", calcTotalProb(w2));
    Complex one = {1, 0}, zero = {0, 0};
    Qureg w3 = createCloneQureg(w2, env);
    setWeightedQureg(one, w2, zero, w3, zero, w3);
    printf("w3amp=%.10f\n", getImagAmp(w3, 1));

    /* density operations */
    Qureg rho = createDensityQureg(2, env);
    initPlusState(rho);
    mixPauli(rho, 0, 0.05, 0.05, 0.05);
    ComplexMatrix2 k0 = {.real = {{1, 0}, {0, 0.8}}, .imag = {{0}}};
    ComplexMatrix2 k1 = {.real = {{0, 0.6}, {0, 0}}, .imag = {{0}}};
    ComplexMatrix2 kops[] = {k0, k1};
    mixKrausMap(rho, 0, kops, 2);
    mixTwoQubitDephasing(rho, 0, 1, 0.1);
    printf("rhototal=%.10f purity=%.10f\n", calcTotalProb(rho), calcPurity(rho));
    Qureg rho2 = createCloneQureg(rho, env);
    mixDensityMatrix(rho, 0.3, rho2);
    printf("dip=%.10f\n", calcDensityInnerProduct(rho, rho2));
    Complex da = getDensityAmp(rho, 1, 1);
    printf("da=%.10f\n", da.real);

    /* debug api */
    initStateDebug(q);
    printf("dbg=%.10f dbgi=%.10f\n", getRealAmp(q, 1), getImagAmp(q, 1));
    printf("prec=%d\n", QuESTPrecision());
    printf("cmp=%d\n", compareStates(w2, w2, 1e-10));

    destroyQureg(q, env); destroyQureg(work, env); destroyQureg(out, env);
    destroyQureg(w2, env); destroyQureg(w3, env);
    destroyQureg(rho, env); destroyQureg(rho2, env);
    destroyQuESTEnv(env);
    return 0;
}
"""


def test_c_api_full_surface(tmp_path, c_binary):
    """One C program touching every API family: gates, Pauli sums/Hamils,
    Trotter, diagonal ops, Kraus maps, clones, weighted quregs, state
    mirrors, debug calls."""
    src = tmp_path / "surface.c"
    src.write_text(C_SURFACE_PROGRAM)
    binary = tmp_path / "surface"
    subprocess.run(["gcc", str(src), "-I", CAPI,
                    "-L", os.path.dirname(LIB), "-lquest_tpu_c",
                    f"-Wl,-rpath,{os.path.dirname(LIB)}", "-lm",
                    "-o", str(binary)],
                   check=True, capture_output=True)
    r = _run(binary, timeout=600)
    assert r.returncode == 0, (r.stdout[-300:], r.stderr[-500:])
    vals = {}
    for line in r.stdout.strip().splitlines():
        parts = line.replace("=", " = ").split()
        for key, eq, val in zip(parts, parts[1:], parts[2:]):
            if eq == "=":
                vals[key] = val
    assert abs(float(vals["ip"]) - 1.0) < 1e-9           # <q|q> after clone
    assert abs(float(vals["fid"]) - 1.0) < 1e-9
    assert abs(float(vals["w2total"]) - 1.0) < 1e-9      # 0.6^2 + 0.8^2
    assert abs(float(vals["w3amp"]) - 0.8) < 1e-9
    assert abs(float(vals["rhototal"]) - 1.0) < 1e-9     # CPTP channels
    assert abs(float(vals["ed"]) - 1.0) < 1e-9           # identity diagonal
    assert vals["numAmps"] == "16"
    # initDebugState: amp k = (2k)/10 + i(2k+1)/10
    assert abs(float(vals["dbg"]) - 0.2) < 1e-12
    assert abs(float(vals["dbgi"]) - 0.3) < 1e-12
    assert vals["prec"] == "2"
    assert vals["cmp"] == "1"
    # host mirror holds |amp|^2 of the first amplitude after the circuit
    assert 0.0 <= float(vals["mirror0"]) <= 1.0


REF_ROOT = "/root/reference"


@pytest.fixture(scope="module")
def reference_lib(tmp_path_factory):
    """Build the reference's own libQuEST.so (PRECISION=2) if sources are
    mounted; skip otherwise."""
    if not os.path.exists(os.path.join(REF_ROOT, "CMakeLists.txt")):
        pytest.skip("reference sources not mounted")
    build = tmp_path_factory.mktemp("refbuild")
    r = subprocess.run(["cmake", REF_ROOT], cwd=build, capture_output=True)
    if r.returncode != 0:
        pytest.skip("reference cmake failed")
    r = subprocess.run(["make", "-j8", "QuEST"], cwd=build, capture_output=True)
    if r.returncode != 0:
        pytest.skip("reference build failed")
    return os.path.join(build, "QuEST")


AMP_DUMP = r"""
#include <stdio.h>
#include "QuEST.h"
int main(void) {
    QuESTEnv env = createQuESTEnv();
    Qureg q = createQureg(5, env);
    initZeroState(q);
    hadamard(q, 0); controlledNot(q, 0, 1);
    rotateY(q, 2, 0.1); rotateX(q, 3, -1.234); rotateZ(q, 4, 2.718);
    Complex a = {.real = 0.5, .imag = 0.5}, b = {.real = 0.5, .imag = -0.5};
    compactUnitary(q, 1, a, b);
    controlledCompactUnitary(q, 0, 3, a, b);
    int targs[] = {0, 1, 2};
    multiControlledPhaseFlip(q, targs, 3);
    ComplexMatrix2 u = {.real = {{0.6, 0.8}, {0.8, -0.6}}, .imag = {{0}}};
    unitary(q, 4, u);
    Vector v = {.x = 1, .y = 1, .z = 0};
    rotateAroundAxis(q, 2, 0.777, v);
    tGate(q, 0); sGate(q, 1);
    controlledPhaseShift(q, 2, 0, 0.321);
    for (long long i = 0; i < 32; i++)
        printf("%lld %.17e %.17e\n", i, getRealAmp(q, i), getImagAmp(q, i));
    return 0;
}
"""


def test_f64_amplitudes_match_reference_binary(tmp_path, c_binary, reference_lib):
    """Every amplitude of a 13-gate circuit agrees with the reference CPU
    binary at float64 to <1e-14 (last-ULP rounding differences only — the
    engine's matmul formulation reassociates sums, so exact bit-equality is
    not guaranteed and not claimed)."""
    src = tmp_path / "ampdump.c"
    src.write_text(AMP_DUMP)
    ref_bin = tmp_path / "dump_ref"
    subprocess.run(["gcc", str(src), "-I", os.path.join(REF_ROOT, "QuEST", "include"),
                    "-L", reference_lib, "-lQuEST",
                    f"-Wl,-rpath,{reference_lib}", "-lm", "-o", str(ref_bin)],
                   check=True, capture_output=True)
    tpu_bin = tmp_path / "dump_tpu"
    subprocess.run(["gcc", str(src), "-I", CAPI,
                    "-L", os.path.dirname(LIB), "-lquest_tpu_c",
                    f"-Wl,-rpath,{os.path.dirname(LIB)}", "-lm", "-o", str(tpu_bin)],
                   check=True, capture_output=True)
    ref_out = subprocess.run([str(ref_bin)], capture_output=True, text=True,
                             timeout=120).stdout
    tpu_out = _run(tpu_bin).stdout

    def parse(s):
        return {int(t[0]): (float(t[1]), float(t[2]))
                for t in (ln.split() for ln in s.strip().splitlines())
                if len(t) == 3}

    ref_amps, tpu_amps = parse(ref_out), parse(tpu_out)
    assert len(ref_amps) == len(tpu_amps) == 32
    for i in range(32):
        assert abs(ref_amps[i][0] - tpu_amps[i][0]) < 1e-14, (i, ref_amps[i], tpu_amps[i])
        assert abs(ref_amps[i][1] - tpu_amps[i][1]) < 1e-14, (i, ref_amps[i], tpu_amps[i])


REF_TESTS = "/root/reference/tests"


@pytest.fixture(scope="module")
def catch2_binary(tmp_path_factory, c_binary):
    """Compile the reference's own Catch2 test suite UNCHANGED against the
    shim (the SURVEY §7 north star)."""
    if not os.path.exists(os.path.join(REF_TESTS, "main.cpp")):
        pytest.skip("reference tests not mounted")
    d = tmp_path_factory.mktemp("catch2")
    objs = []
    for f in ["main", "utilities", "test_gates", "test_state_initialisations"]:
        obj = d / f"{f}.o"
        r = subprocess.run(
            ["g++", "-std=c++14", "-DCATCH_CONFIG_NO_POSIX_SIGNALS", "-c",
             os.path.join(REF_TESTS, f"{f}.cpp"), "-I", CAPI,
             "-I", REF_TESTS, "-I", os.path.join(REF_TESTS, "catch"),
             "-o", str(obj)], capture_output=True, text=True)
        assert r.returncode == 0, (f, r.stderr[-400:])
        objs.append(str(obj))
    binary = d / "quest_tests"
    subprocess.run(["g++"] + objs + ["-L", os.path.dirname(LIB),
                    "-lquest_tpu_c", f"-Wl,-rpath,{os.path.dirname(LIB)}",
                    "-o", str(binary)], check=True, capture_output=True)
    return binary


def test_reference_catch2_gates_tag(catch2_binary):
    """The reference's [gates] Catch2 cases (measure, measureWithStats,
    collapseToOutcome — 1000+ assertions) pass against the TPU runtime."""
    env = dict(os.environ)
    env.update(RUN_ENV)
    env.pop("XLA_FLAGS", None)
    r = subprocess.run([str(catch2_binary), "[gates]"], capture_output=True,
                       text=True, env=env, timeout=580)
    assert r.returncode == 0, r.stdout[-800:]
    assert "All tests passed" in r.stdout


def test_reference_catch2_state_init_tag(catch2_binary):
    """The reference's [state_initialisations] Catch2 cases pass against the
    TPU runtime."""
    env = dict(os.environ)
    env.update(RUN_ENV)
    env.pop("XLA_FLAGS", None)
    r = subprocess.run([str(catch2_binary), "[state_initialisations]"],
                       capture_output=True, text=True, env=env, timeout=580)
    assert r.returncode == 0, r.stdout[-800:]
    assert "All tests passed" in r.stdout


# ---------------------------------------------------------------------------
# FULL reference Catch2 parity (opt-in: the heavyweight tags dispatch
# thousands of distinct compiled programs and take tens of minutes).
#
# One committed command reproduces 106/106 from a fresh checkout:
#
#     QUEST_FULL_CATCH2=1 python -m pytest tests/test_capi.py -k full_suite -q
#
# Ref analogue: the reference registers every test file as a ctest target
# (tests/CMakeLists.txt:40-47) and runs the suite under MPI via
# examples/submissionScripts/mpi_SLURM_unit_tests.sh.
# ---------------------------------------------------------------------------

FULL_TAG_CASES = {
    "[data_structures]": 21,
    "[state_initialisations]": 9,
    "[unitaries]": 37,
    "[gates]": 3,
    "[operators]": 8,
    "[decoherence]": 10,
    "[calculations]": 18,
}
assert sum(FULL_TAG_CASES.values()) == 106


@pytest.fixture(scope="module")
def catch2_full_binary(tmp_path_factory, c_binary):
    """Compile ALL seven reference test files + utilities.cpp unchanged
    against the shim."""
    if not os.environ.get("QUEST_FULL_CATCH2"):
        pytest.skip("set QUEST_FULL_CATCH2=1 to run the full reference "
                    "Catch2 suite (tens of minutes)")
    if not os.path.exists(os.path.join(REF_TESTS, "main.cpp")):
        pytest.skip("reference tests not mounted")
    d = tmp_path_factory.mktemp("catch2full")
    srcs = ["main", "utilities", "test_calculations", "test_data_structures",
            "test_decoherence", "test_gates", "test_operators",
            "test_state_initialisations", "test_unitaries"]
    objs = []
    for f in srcs:
        obj = d / f"{f}.o"
        r = subprocess.run(
            ["g++", "-std=c++14", "-O1", "-DCATCH_CONFIG_NO_POSIX_SIGNALS",
             "-c", os.path.join(REF_TESTS, f"{f}.cpp"), "-I", CAPI,
             "-I", REF_TESTS, "-I", os.path.join(REF_TESTS, "catch"),
             "-o", str(obj)], capture_output=True, text=True)
        assert r.returncode == 0, (f, r.stderr[-400:])
        objs.append(str(obj))
    binary = d / "quest_tests_full"
    subprocess.run(["g++"] + objs + ["-L", os.path.dirname(LIB),
                    "-lquest_tpu_c", f"-Wl,-rpath,{os.path.dirname(LIB)}",
                    "-o", str(binary)], check=True, capture_output=True)
    return binary


@pytest.mark.parametrize("tag", list(FULL_TAG_CASES))
def test_reference_catch2_full_suite(catch2_full_binary, tag):
    """Run every test case of one reference Catch2 tag, EACH IN ITS OWN
    PROCESS — the reference's own granularity: ctest registers every case
    as a separate target (ref tests/CMakeLists.txt:40-47), so each starts
    with a fresh C rand() stream.  Running a whole tag in one process
    diverges from that: the reference's getRandomUnitary(2) is a single-pass
    classical Gram-Schmidt whose own unitarity DEMAND (utilities.cpp:527)
    deterministically fails on the ill-conditioned draw that appears at one
    particular mid-tag stream position — a latent flaw of the reference's
    generator, never observed under ctest because no case inherits another's
    stream.  QUEST_TPU_CLEAR_CACHES_EVERY bounds each process's mmap budget
    (see api.py _maybe_clear_caches)."""
    env = dict(os.environ)
    env.update(RUN_ENV)
    env.pop("XLA_FLAGS", None)
    env.setdefault("QUEST_TPU_CLEAR_CACHES_EVERY", "64")

    r = subprocess.run([str(catch2_full_binary), "--list-test-names-only",
                        tag], capture_output=True, text=True, env=env,
                       timeout=600)
    cases = [ln.strip() for ln in r.stdout.splitlines() if ln.strip()]
    assert len(cases) == FULL_TAG_CASES[tag], (
        f"{tag}: expected {FULL_TAG_CASES[tag]} cases, binary lists "
        f"{len(cases)} — the committed count table is stale")

    failures = []
    for case in cases:
        r = subprocess.run([str(catch2_full_binary), case],
                           capture_output=True, text=True, env=env,
                           timeout=5400)
        if r.returncode != 0 or "All tests passed" not in r.stdout:
            failures.append((case, r.stdout[-800:]))
    assert not failures, failures
