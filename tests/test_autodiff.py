"""Differentiable parametric circuits (quest_tpu/autodiff.py).

No reference analogue — this is the TPU-native capability layer: jax.grad
through the simulation, vmap-batched execution, trainable noise.  Gradients
are verified against central finite differences and the analytic
parameter-shift rule; energies against the independent dense oracle.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import quest_tpu as qt
from quest_tpu.models import (hardware_efficient_ansatz, maxcut_hamiltonian,
                              qaoa_maxcut_circuit, tfim_hamiltonian)
from conftest import ON_ACCELERATOR
from oracle import NUM_QUBITS, SV_TOL, pauli_sum_matrix, sv

N = NUM_QUBITS

# finite differencing needs wider steps (and wider tolerances) at float32
FD_EPS = 1e-2 if ON_ACCELERATOR else 1e-5
FD_TOL = 5e-2 if ON_ACCELERATOR else 1e-7
PS_TOL = 1e-3 if ON_ACCELERATOR else 1e-9


def _mixed_circuit():
    """One of every parametric kind, interleaved with static gates."""
    pc = qt.ParamCircuit(N)
    t = pc.params(7)
    pc.h(0).cnot(0, 1)
    pc.rx(1, t[0])
    pc.ry(2, t[1])
    pc.rz(3, t[2])
    pc.phase_shift(4, t[3], controls=(0,))
    pc.multi_rotate_z((1, 3), t[4])
    pc.multi_rotate_pauli((0, 2, 4), (1, 2, 3), t[5])
    pc.phase_shift(2, 2.0 * t[6] + 0.25)  # affine Param transform
    pc.h(3)
    return pc


def _hamil():
    return tfim_hamiltonian(N, field=0.7)


def test_grad_matches_finite_difference(env):
    pc = _mixed_circuit()
    psi = qt.createQureg(N, env)  # sharded init under dist8
    e = qt.expectation_fn(pc, _hamil(), init=psi)
    params = jnp.asarray(np.random.default_rng(3).uniform(-1.5, 1.5, pc.num_params))
    g = jax.grad(e)(params)
    for i in range(pc.num_params):
        fd = (e(params.at[i].add(FD_EPS)) - e(params.at[i].add(-FD_EPS))) / (2 * FD_EPS)
        assert abs(float(g[i] - fd)) < FD_TOL, (i, float(g[i]), float(fd))


def test_grad_matches_parameter_shift(env_local):
    """For gates exp(-iθP/2) (rx/ry/rz/mrz), dE/dθ_i is exactly
    [E(θ + π/2·e_i) − E(θ − π/2·e_i)] / 2."""
    pc = qt.ParamCircuit(4)
    t = pc.params(4)
    pc.h(0).cnot(0, 1)
    pc.rx(0, t[0]).ry(1, t[1]).rz(2, t[2])
    pc.multi_rotate_z((1, 2, 3), t[3])
    pc.cz(2, 3)
    e = qt.expectation_fn(pc, tfim_hamiltonian(4))
    params = jnp.asarray([0.3, -1.1, 0.8, 0.45])
    g = jax.grad(e)(params)
    s = np.pi / 2
    for i in range(4):
        shift = (e(params.at[i].add(s)) - e(params.at[i].add(-s))) / 2.0
        assert abs(float(g[i] - shift)) < PS_TOL, (i, float(g[i]), float(shift))


def test_energy_matches_dense_oracle(env):
    pc = _mixed_circuit()
    h = _hamil()
    params = jnp.asarray(np.random.default_rng(5).uniform(-1, 1, pc.num_params))
    e = float(qt.expectation_fn(pc, h)(params))
    # independent path: run the bound circuit through state_fn, contract with
    # the oracle's dense Hamiltonian matrix
    state = np.asarray(qt.state_fn(pc)(params))
    vec = state[0] + 1j * state[1]
    hm = pauli_sum_matrix(N, h.pauli_codes, h.term_coeffs)
    assert e == pytest.approx(float(np.real(vec.conj() @ hm @ vec)), abs=10 * SV_TOL)


def test_state_fn_matches_eager_api(env):
    pc = qt.ParamCircuit(N)
    t = pc.params(3)
    pc.h(0).cnot(0, 1).rx(2, t[0]).ry(3, t[1]).rz(4, t[2]).swap(0, 4)
    params = jnp.asarray([0.2, -0.4, 1.3])
    state = np.asarray(qt.state_fn(pc)(params))
    got = state[0] + 1j * state[1]

    ref = qt.createQureg(N, env)
    qt.hadamard(ref, 0)
    qt.controlledNot(ref, 0, 1)
    qt.rotateX(ref, 2, 0.2)
    qt.rotateY(ref, 3, -0.4)
    qt.rotateZ(ref, 4, 1.3)
    qt.swapGate(ref, 0, 4)
    np.testing.assert_allclose(got, sv(ref), atol=SV_TOL)


def test_vmap_batch_matches_loop(env_local):
    pc = _mixed_circuit()
    e = qt.expectation_fn(pc, _hamil())
    batch = jnp.asarray(np.random.default_rng(7).uniform(-2, 2, (6, pc.num_params)))
    vb = jax.vmap(e)(batch)
    lb = jnp.stack([e(b) for b in batch])
    np.testing.assert_allclose(np.asarray(vb), np.asarray(lb),
                               atol=1e-4 if ON_ACCELERATOR else 1e-12)


def test_vmap_grad_batches(env_local):
    pc = _mixed_circuit()
    e = qt.expectation_fn(pc, _hamil())
    batch = jnp.asarray(np.random.default_rng(8).uniform(-2, 2, (4, pc.num_params)))
    gv = jax.vmap(jax.grad(e))(batch)
    for k in range(batch.shape[0]):
        np.testing.assert_allclose(np.asarray(gv[k]), np.asarray(jax.grad(e)(batch[k])),
                                   atol=1e-4 if ON_ACCELERATOR else 1e-12)


def test_density_pure_matches_statevector(env):
    pc = qt.ParamCircuit(4)
    t = pc.params(2)
    pc.h(0).ry(1, t[0]).cnot(1, 2).rz(3, t[1]).multi_rotate_pauli((0, 3), (2, 1), t[0])
    h = tfim_hamiltonian(4)
    params = jnp.asarray([0.9, -0.3])
    ev_sv = float(qt.expectation_fn(pc, h)(params))
    ev_dm = float(qt.expectation_fn(pc, h, density=True)(params))
    assert ev_dm == pytest.approx(ev_sv, abs=10 * SV_TOL)


def test_density_noise_grad_finite_difference(env_local):
    """Gradients flow through channel probabilities (trainable noise)."""
    pc = qt.ParamCircuit(3)
    t = pc.params(5)
    pc.h(0).cnot(0, 1).rx(2, t[0])
    pc.damp(0, t[1])
    pc.depolarise(1, t[2])
    pc.dephase(2, t[3])
    pc.two_qubit_dephase(0, 2, 0.5 * t[4])
    e = qt.expectation_fn(pc, tfim_hamiltonian(3), density=True)
    params = jnp.asarray([0.7, 0.15, 0.2, 0.1, 0.3])
    g = jax.grad(e)(params)
    for i in range(5):
        fd = (e(params.at[i].add(FD_EPS)) - e(params.at[i].add(-FD_EPS))) / (2 * FD_EPS)
        assert abs(float(g[i] - fd)) < FD_TOL, (i, float(g[i]), float(fd))


def test_vqe_tfim_reaches_ground_energy(env_local):
    """End-to-end VQE: optax.adam on a hardware-efficient ansatz recovers the
    4-qubit TFIM ground energy."""
    import optax

    n = 4
    h = tfim_hamiltonian(n, field=1.0)
    pc = hardware_efficient_ansatz(n, layers=3)
    e = qt.expectation_fn(pc, h)
    vg = jax.jit(jax.value_and_grad(e))
    params = jnp.asarray(np.random.default_rng(11).normal(0, 0.1, pc.num_params))
    opt = optax.adam(0.1)
    st = opt.init(params)
    val = None
    for _ in range(300):
        val, g = vg(params)
        up, st = opt.update(g, st)
        params = optax.apply_updates(params, up)
    exact = np.linalg.eigvalsh(pauli_sum_matrix(n, h.pauli_codes, h.term_coeffs))[0]
    assert float(val) < exact + 0.05, (float(val), exact)
    assert float(val) > exact - 1e-6  # variational bound


def test_qaoa_maxcut(env_local):
    """QAOA p=2 on the 5-cycle reaches a high approximation ratio."""
    import optax

    edges = [(i, (i + 1) % 5) for i in range(5)]
    pc = qaoa_maxcut_circuit(5, edges, p=2)
    assert pc.num_params == 4
    h = maxcut_hamiltonian(5, edges)
    e = qt.expectation_fn(pc, h)
    vg = jax.jit(jax.value_and_grad(e))
    params = jnp.full(pc.num_params, 0.1)
    opt = optax.adam(0.1)
    st = opt.init(params)
    for _ in range(150):
        val, g = vg(params)
        up, st = opt.update(g, st)
        params = optax.apply_updates(params, up)
    # max cut of C5 is 4 -> minimum energy -4; p=2 QAOA reaches ~-3.85
    assert float(val) < -3.5, float(val)


def test_param_affine_transform(env_local):
    pc = qt.ParamCircuit(2)
    p = pc.param()
    pc.h(0).rx(0, 2.0 * p + 0.5)
    bound = qt.ParamCircuit(2)
    bound.h(0).rx(0, 2.0 * 0.3 + 0.5)
    sa = np.asarray(qt.state_fn(pc)(jnp.asarray([0.3])))
    sb = np.asarray(qt.state_fn(bound)(jnp.zeros(0)))
    np.testing.assert_allclose(sa, sb, atol=SV_TOL)


def test_adjoint_gradient_matches_jax_grad(env):
    """The O(1)-memory adjoint method must agree with taped reverse-mode to
    machine precision on every parametric kind, controls, shared affine
    params, and static gates."""
    from quest_tpu.autodiff import adjoint_gradient_fn

    pc = _mixed_circuit()
    pc.x(1, (3,)).swap(0, 4).s(2).z(3, (1,))
    # controlled parametric gates placed so their gradients are NONZERO (a
    # zero-gradient controlled gate once masked an inverted control
    # projector in the generator path)
    ex = pc.params(3)
    pc.ry(0, 0.7)
    pc.phase_shift(1, ex[0], controls=(0,))
    pc.rz(2, ex[1])  # rz after entanglement: generator test via Z
    pc.ry(3, ex[2])
    pc.h(1)
    h = _hamil()
    psi = qt.createQureg(N, env)  # sharded init under dist8
    params = jnp.asarray(np.random.default_rng(21).uniform(-1.5, 1.5, pc.num_params))
    v0, g0 = jax.value_and_grad(qt.expectation_fn(pc, h, init=psi))(params)
    v1, g1 = adjoint_gradient_fn(pc, h, init=psi)(params)
    tol = 1e-3 if ON_ACCELERATOR else 1e-10
    assert abs(float(v0 - v1)) < tol
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g0), atol=tol)
    # guard the guard: the controlled-phase parameter really contributes
    assert abs(float(g0[ex[0].index])) > 1e-4, float(g0[ex[0].index])


def test_adjoint_gradient_qaoa_shared_params(env_local):
    from quest_tpu.autodiff import adjoint_gradient_fn

    edges = [(i, (i + 1) % 5) for i in range(5)]
    pc = qaoa_maxcut_circuit(5, edges, p=2)
    h = maxcut_hamiltonian(5, edges)
    params = jnp.asarray([0.3, -0.2, 0.5, 0.1])
    v0, g0 = jax.value_and_grad(qt.expectation_fn(pc, h))(params)
    v1, g1 = adjoint_gradient_fn(pc, h)(params)
    tol = 1e-3 if ON_ACCELERATOR else 1e-10
    assert abs(float(v0 - v1)) < tol
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g0), atol=tol)


def test_remat_gradient_matches_plain(env_local):
    """remat_every blocks must not change values or gradients — only the
    taping schedule (one checkpoint per block, forward recompute in the
    backward sweep)."""
    pc = qt.ParamCircuit(3)
    t = pc.params(3)
    pc.h(0).cnot(0, 1).rx(1, t[0])
    pc.damp(0, t[1])
    pc.ry(2, t[2]).depolarise(2, 0.1)
    h = tfim_hamiltonian(3)
    params = jnp.asarray([0.4, 0.12, -0.8])
    e_plain = qt.expectation_fn(pc, h, density=True)
    e_remat = qt.expectation_fn(pc, h, density=True, remat_every=2)
    assert float(e_plain(params)) == pytest.approx(float(e_remat(params)), abs=1e-12)
    g0 = jax.grad(e_plain)(params)
    g1 = jax.grad(e_remat)(params)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g0), atol=1e-11)


def test_coeffs_gradient_is_per_term_expectation(env_local):
    """With coeffs_arg=True, d<H>/dc_t must equal <P_t> by linearity."""
    pc = _mixed_circuit()
    h = _hamil()
    e2 = qt.expectation_fn(pc, h, coeffs_arg=True)
    params = jnp.asarray(np.random.default_rng(31).uniform(-1, 1, pc.num_params))
    coeffs = jnp.asarray(np.asarray(h.term_coeffs))
    gc = jax.grad(e2, argnums=1)(params, coeffs)
    # independent per-term check through the eager API
    psi = qt.createQureg(N, env_local)
    state = qt.state_fn(pc)(params)
    psi.set_amps_array(state)
    for t in range(h.num_sum_terms):
        want = qt.calcExpecPauliProd(psi, list(range(N)), list(h.pauli_codes[t]), N,
                                     qt.createQureg(N, env_local))
        assert float(gc[t]) == pytest.approx(want, abs=1e-10)


def test_adjoint_gradient_rejects_noise(env_local):
    from quest_tpu.autodiff import adjoint_gradient_fn

    pc = qt.ParamCircuit(2)
    pc.h(0).damp(0, pc.param())
    with pytest.raises(ValueError, match="noise"):
        adjoint_gradient_fn(pc, tfim_hamiltonian(2))


def test_integer_params_do_not_truncate_constants(env_local):
    """A non-float parameter vector must not drag constant angles (recorded
    as ParamOp floats, e.g. multi_rotate_z with a bound angle) to ints."""
    pc = qt.ParamCircuit(2)
    pc.h(0).multi_rotate_z((0, 1), 0.5).rx(1, pc.param())
    si = np.asarray(qt.state_fn(pc)(jnp.asarray([0], dtype=jnp.int32)))
    sf = np.asarray(qt.state_fn(pc)(jnp.asarray([0.0])))
    np.testing.assert_allclose(si, sf, atol=SV_TOL)


def test_noise_requires_density_mode(env_local):
    pc = qt.ParamCircuit(2)
    pc.damp(0, pc.param())
    with pytest.raises(ValueError, match="density"):
        qt.state_fn(pc)(jnp.asarray([0.1]))


def test_optimize_guard(env_local):
    pc = qt.ParamCircuit(2)
    pc.h(0).rx(1, pc.param())
    with pytest.raises(ValueError, match="static"):
        pc.optimize()


def test_static_angle_mrz_mrp_record_static_gates(env_local):
    """Non-Param angles in multi_rotate_z / multi_rotate_pauli take the
    static GateOp path: the circuit stays fusable (optimize() accepts it)
    and matches the eager API."""
    from quest_tpu.autodiff import ParamOp

    pc = qt.ParamCircuit(4)
    pc.h(0).h(1).h(2).h(3)
    pc.multi_rotate_z((0, 2), 0.41)
    pc.multi_rotate_pauli((0, 1, 3), (1, 2, 3), -0.73)
    pc.multi_rotate_pauli((1, 2), (0, 0), 0.5)  # all-identity: records nothing
    assert not any(isinstance(op, ParamOp) for op in pc.ops)
    pc.optimize()  # must not raise (ADVICE r4: static circuits stay fusable)

    got = np.asarray(qt.state_fn(pc)(jnp.zeros(0)))
    psi = qt.createQureg(4, env_local)
    for t in range(4):
        qt.hadamard(psi, t)
    qt.multiRotateZ(psi, [0, 2], 0.41)
    qt.multiRotatePauli(psi, [0, 1, 3], [1, 2, 3], -0.73)
    qt.multiRotatePauli(psi, [1, 2], [0, 0], 0.5)
    want = np.stack([np.asarray(psi.amps[0]), np.asarray(psi.amps[1])])
    np.testing.assert_allclose(got, want, atol=SV_TOL)


def test_adjoint_gradient_identity_pauli_string(env_local):
    """An all-identity multiRotatePauli applies nothing (reference
    convention), so its adjoint-method gradient must be exactly zero and
    agree with jax.grad (ADVICE r4)."""
    pc = qt.ParamCircuit(3)
    t = pc.params(2)
    pc.h(0).ry(1, t[0])
    pc.multi_rotate_pauli((0, 1, 2), (0, 0, 0), t[1])  # all PAULI_I
    h = tfim_hamiltonian(3, field=0.5)
    params = jnp.asarray([0.37, 1.21])
    e_adj, g_adj = qt.adjoint_gradient_fn(pc, h)(params)
    g_jax = jax.grad(qt.expectation_fn(pc, h))(params)
    np.testing.assert_allclose(np.asarray(g_adj), np.asarray(g_jax), atol=PS_TOL)
    assert abs(float(g_adj[1])) < PS_TOL  # identity string: dE/dtheta == 0
