"""Perf-regression ledger (quest_tpu/obs/regress.py + bench.py --compare):

- row recovery from the REAL committed BENCH_r0*.json history — including
  the driver-wrapped rounds whose only payload is a front-truncated output
  tail (r03-r05) and the timeout round with no payload at all (r01);
- the gate semantics: exit 0 on the real history, nonzero on an injected
  25% regression of a headline row (the acceptance contract, also wired
  as the CI ``bench-regress`` job's self-test), per-row tolerance
  overrides, platform comparability, and validation-only rows reporting
  without gating;
- the CLI (``python bench.py --compare``) end to end via compare_main.
"""

from __future__ import annotations

import json
import os
import sys

import pytest

from quest_tpu.obs import regress

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)                   # for `import bench`


def _round(label, rows, platform="tpu"):
    return {"label": label, "path": label, "rc": 0, "platform": platform,
            "rows": {r["name"]: r for r in rows}, "skipped": [],
            "recovered": False}


def _row(name, value, platform="tpu", validation_only=False):
    return {"name": name, "value": float(value), "platform": platform,
            "validation_only": validation_only}


# ---------------------------------------------------------------------------
# loading the real committed history
# ---------------------------------------------------------------------------

def test_real_history_rows_recovered():
    hist = regress.load_history()
    assert [h["label"] for h in hist] == [
        "BENCH_r01", "BENCH_r02", "BENCH_r03", "BENCH_r04", "BENCH_r05"]
    by = {h["label"]: h for h in hist}
    assert by["BENCH_r01"]["rows"] == {}          # the rc=124 timeout round
    assert by["BENCH_r01"]["rc"] == 124
    assert by["BENCH_r02"]["rows"]["headline"]["value"] == pytest.approx(
        5.43e10, rel=0.01)
    # r03-r05 carry only truncated tails; the scan recovers the suffix
    assert by["BENCH_r03"]["recovered"]
    assert len(by["BENCH_r03"]["rows"]) >= 8
    assert by["BENCH_r05"]["rows"]["qft_30q_f32_public_api"]["value"] \
        == pytest.approx(2.59e11, rel=0.01)
    # the CPU-mesh validation row is marked and platform-resolved
    r5shard = by["BENCH_r05"]["rows"]["qft_20q_f32_cpu8shard"]
    assert r5shard["platform"] == "cpu" and r5shard["validation_only"]


def test_real_history_gate_passes_and_injection_fails():
    """The acceptance pair: the committed r01-r05 trajectory holds no
    gating regression; scaling a headline row by 0.75 (a 25% regression)
    flips the gate."""
    hist = regress.load_history()
    current, priors = hist[-1], hist[:-1]
    report = regress.compare(current, priors)
    assert report["ok"], [r for r in report["rows"]
                          if r["status"] == "regressed"]
    assert report["summary"]["unrecoverable_prior_rounds"] == ["BENCH_r01"]
    # inject: 25% off a headline row that HAS a comparable prior
    hist2 = regress.load_history()
    hist2[-1]["rows"]["qft_28q_f32_inplace_ordered"]["value"] *= 0.75
    bad = regress.compare(hist2[-1], hist2[:-1])
    assert not bad["ok"]
    (reg,) = [r for r in bad["rows"] if r["status"] == "regressed"]
    assert reg["name"] == "qft_28q_f32_inplace_ordered"
    assert reg["code"] == regress.PERF_REGRESSION
    assert reg["gating"]


def test_recover_rows_from_truncated_text():
    full = json.dumps({
        "metric": "m", "value": 1.0, "config": {"platform": "tpu"},
        "matrix": [{"name": "a", "value": 2.0, "config": {}},
                   {"name": "b", "value": 3.0, "config": {}}]})
    headline, rows = regress.recover_rows(full)
    assert headline["value"] == 1.0
    assert [r["name"] for r in rows] == ["a", "b"]
    # front truncation mid-object: the broken first row is dropped, the
    # complete suffix survives — never invented, never doubled
    cut = full[full.find('"name": "a"') + 5:]
    headline2, rows2 = regress.recover_rows(cut)
    assert headline2 is None
    assert [r["name"] for r in rows2] == ["b"]
    assert regress.recover_rows("no json here") == (None, [])


def test_load_round_accepts_raw_bench_document(tmp_path):
    doc = {"metric": "m", "value": 5e9, "config": {"platform": "cpu"},
           "matrix": [{"name": "x", "value": 1e9, "config": {}},
                      {"name": "broken", "error": "boom"}]}
    p = tmp_path / "run.json"
    p.write_text(json.dumps(doc))
    rnd = regress.load_round(str(p))
    assert rnd["platform"] == "cpu"
    assert rnd["rows"]["headline"]["value"] == 5e9
    assert rnd["rows"]["x"]["platform"] == "cpu"    # round default applied
    assert rnd["skipped"] == [{"name": "broken", "error": "boom"}]


# ---------------------------------------------------------------------------
# compare semantics
# ---------------------------------------------------------------------------

def test_tolerance_default_and_per_row_override():
    prior = _round("r1", [_row("a", 100.0), _row("b", 100.0)])
    cur = _round("r2", [_row("a", 79.0), _row("b", 79.0)])
    rep = regress.compare(cur, [prior])
    assert not rep["ok"]                       # 21% > 20% default
    assert {r["name"]: r["status"] for r in rep["rows"]} \
        == {"a": "regressed", "b": "regressed"}
    rep2 = regress.compare(cur, [prior], row_tolerances={"a": 0.3, "b": 0.3})
    assert rep2["ok"]
    rep3 = regress.compare(cur, [prior], default_tolerance=0.25)
    assert rep3["ok"]
    # the built-in noisy-row defaults (docs/OBSERVABILITY.md table)
    noisy = regress.compare(
        _round("r2", [_row("serve_vqe_16q_batch64", 65.0)]),
        [_round("r1", [_row("serve_vqe_16q_batch64", 100.0)])])
    assert noisy["ok"]                         # 35% < the 40% override
    assert noisy["rows"][0]["tolerance"] == pytest.approx(0.40)


def test_best_comparable_prior_across_rounds_and_platforms():
    priors = [
        _round("r1", [_row("a", 120.0)]),      # the best prior: r1, not r2
        _round("r2", [_row("a", 90.0), _row("cpu_only", 50.0, "cpu")]),
    ]
    cur = _round("r3", [_row("a", 100.0), _row("cpu_only", 10.0, "tpu")])
    rep = regress.compare(cur, priors)
    a = [r for r in rep["rows"] if r["name"] == "a"][0]
    assert a["best_prior"] == 120.0 and a["best_prior_round"] == "r1"
    assert a["status"] == "ok"                 # 100/120 = 0.83 within 20%
    # a tpu row never gates against a cpu prior: no comparable prior = new
    c = [r for r in rep["rows"] if r["name"] == "cpu_only"][0]
    assert c["status"] == "new" and c["best_prior"] is None
    # unknown platform is a wildcard (the pre-provenance rounds)
    rep2 = regress.compare(
        _round("r3", [_row("a", 50.0, platform="unknown")]),
        [_round("r1", [_row("a", 100.0)])])
    assert not rep2["ok"]


def test_validation_only_rows_report_but_do_not_gate():
    prior = _round("r1", [_row("mesh", 100.0, "cpu", validation_only=True)])
    cur = _round("r2", [_row("mesh", 40.0, "cpu", validation_only=True)])
    rep = regress.compare(cur, [prior])
    assert rep["ok"]                           # reported, not gating
    assert rep["rows"][0]["status"] == "regressed"
    assert not rep["rows"][0]["gating"]
    assert rep["summary"]["gating_regressions"] == 0
    strict = regress.compare(cur, [prior], include_validation=True)
    assert not strict["ok"]


# ---------------------------------------------------------------------------
# the CLI
# ---------------------------------------------------------------------------

def test_compare_cli_end_to_end(tmp_path, capsys):
    import bench
    out = tmp_path / "report.json"
    rc = bench.compare_main(["--compare", "--out", str(out)])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["metric"] == "bench_compare" and doc["ok"]
    assert json.loads(out.read_text()) == doc    # the CI artifact
    # the self-test flag: inject a 25% regression, the gate must fail
    rc = bench.compare_main(["--compare", "--inject",
                             "qft_28q_f32_inplace_ordered=0.75"])
    assert rc == 1
    doc = json.loads(capsys.readouterr().out)
    assert not doc["ok"]
    (reg,) = [r for r in doc["rows"] if r["status"] == "regressed"]
    assert reg["name"] == "qft_28q_f32_inplace_ordered"
    # unknown row name in --inject is a usage error, not a silent pass
    with pytest.raises(SystemExit):
        bench.compare_main(["--compare", "--inject", "nope=0.5"])
    capsys.readouterr()
