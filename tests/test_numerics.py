"""Numeric-health telemetry (quest_tpu/obs/numerics.py + the serve/deploy
wiring): probe kernels, the ulp-band drift ledger, the bit-identity
contract of probe-instrumented programs on every engine path, the serve
integration (numeric_health records, NaN flight dumps, the one scrape),
the deploy router's NaN quarantine, and the calc_total_prob API parity
surface."""

from __future__ import annotations

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import quest_tpu as qt
from quest_tpu.circuit import qft_circuit, random_circuit
from quest_tpu.obs import numerics as num
from quest_tpu.serve import CompileCache, QuESTService
from quest_tpu.serve.cache import CacheOptions
from quest_tpu.serve.selftest import vqe_ansatz
from quest_tpu.validation import ErrorCode, QuESTError


def _zero_state(n, dtype=jnp.float64):
    return jnp.zeros((2, 1 << n), dtype).at[0, 0].set(1.0)


# ---------------------------------------------------------------------------
# probe kernels
# ---------------------------------------------------------------------------

class TestProbeKernels:
    def test_statevec_probe_of_basis_state(self):
        p = num.probe_dict(num.state_probe_vector(_zero_state(4)))
        assert p["norm"] == pytest.approx(1.0, abs=1e-15)
        assert p["max_amp2"] == pytest.approx(1.0, abs=1e-15)
        assert p["nan_count"] == 0 and p["inf_count"] == 0
        assert p["herm_dev"] == 0.0

    def test_statevec_probe_counts_nan_and_inf(self):
        st = np.zeros((2, 16))
        st[0, 0] = 1.0
        st[0, 3] = np.nan
        st[1, 5] = np.inf
        p = num.probe_dict(num.state_probe_vector(jnp.asarray(st)))
        assert p["nan_count"] == 1
        assert p["inf_count"] == 1

    def test_densmatr_probe_trace_and_hermiticity(self, env_local):
        q = qt.createDensityQureg(3, env_local)
        qt.hadamard(q, 0)
        qt.controlledNot(q, 0, 1)
        qt.mixDamping(q, 1, 0.3)
        p = num.probe_dict(num.densmatr_probe_vector(q.amps, 3))
        assert p["norm"] == pytest.approx(1.0, abs=1e-12)   # trace
        assert p["herm_dev"] < 1e-12
        assert p["nan_count"] == 0
        qt.destroyQureg(q, env_local)

    def test_densmatr_probe_detects_nonhermitian(self):
        n = 3
        rho = np.zeros((2, 1 << (2 * n)))
        for k in range(1 << n):
            rho[0, k + (k << n)] = 1.0 / (1 << n)
        bad = num.inject_nonhermitian(rho, n, eps=1e-3)
        p = num.probe_dict(num.densmatr_probe_vector(jnp.asarray(bad), n))
        assert p["herm_dev"] == pytest.approx(1e-3, rel=1e-6)
        assert p["norm"] == pytest.approx(1.0, abs=1e-12)   # trace intact

    def test_ulp_band_scales_with_depth_and_precision(self):
        assert num.ulp_band(100, "float64") > num.ulp_band(1, "float64")
        assert num.ulp_band(10, "float32") > num.ulp_band(10, "float64")
        # sqrt growth, not linear
        assert num.ulp_band(400, "float64") == pytest.approx(
            2 * num.ulp_band(100, "float64"))


# ---------------------------------------------------------------------------
# the numeric drift ledger
# ---------------------------------------------------------------------------

class TestNumericLedger:
    def test_clean_record_has_no_findings(self):
        led = num.NumericLedger()
        rec = led.record("clean", num.state_probe_vector(_zero_state(4)),
                         num_ops=8, warn=False)
        assert rec.findings == ()
        assert led.snapshot() == {"records": 1, "probed_total": 1,
                                  "nan_total": 0, "drift_total": 0}

    def test_scaled_state_trips_drift(self):
        led = num.NumericLedger()
        bad = num.inject_scale(np.asarray(_zero_state(4)), 1.001)
        rec = led.record("scaled", num.state_probe_vector(jnp.asarray(bad)),
                         num_ops=8, warn=False)
        assert any(num.NUMERIC_DRIFT in f for f in rec.findings)
        assert led.snapshot()["drift_total"] == 1

    def test_nan_trips_and_wins_over_drift(self):
        led = num.NumericLedger()
        bad = num.inject_nan(np.asarray(_zero_state(4)))
        rec = led.record("nan", num.state_probe_vector(jnp.asarray(bad)),
                         num_ops=8, warn=False)
        assert any(num.NUMERIC_NAN in f for f in rec.findings)
        # a NaN norm must not ALSO report as drift noise
        assert not any(num.NUMERIC_DRIFT in f for f in rec.findings)
        assert led.snapshot()["nan_total"] == 1

    def test_nonhermitian_density_trips(self):
        led = num.NumericLedger()
        n = 3
        rho = np.zeros((2, 1 << (2 * n)))
        for k in range(1 << n):
            rho[0, k + (k << n)] = 1.0 / (1 << n)
        rec = led.record(
            "herm", num.densmatr_probe_vector(
                jnp.asarray(num.inject_nonhermitian(rho, n)), n),
            kind="densmatr", num_qubits=n, num_ops=8, warn=False)
        assert any("Hermiticity" in f for f in rec.findings)

    def test_by_class_aggregation(self):
        led = num.NumericLedger()
        clean = num.state_probe_vector(_zero_state(4))
        bad = num.state_probe_vector(jnp.asarray(num.inject_nan(
            np.asarray(_zero_state(4)))))
        led.record("a", clean, class_key="ck1", num_ops=4, warn=False)
        led.record("b", clean, class_key="ck1", num_ops=4, warn=False)
        led.record("c", bad, class_key="ck2", num_ops=4, warn=False)
        agg = led.by_class()
        assert agg["ck1"]["count"] == 2 and agg["ck1"]["nan_records"] == 0
        assert agg["ck2"]["nan_records"] == 1

    def test_band_scales_with_expected_norm(self):
        """Rounding drift is relative to the state's magnitude: a tenant's
        100x-scaled input (expected norm 1e4) must be judged against a
        1e4-scaled band, not the unit-scale one."""
        led = num.NumericLedger()
        st = np.asarray(_zero_state(4)) * 100.0
        # perturb by ~10 unit-scale bands: real rounding noise at this
        # magnitude, far inside the SCALED band
        st[0, 0] = np.nextafter(st[0, 0], np.inf)
        rec = led.record("scaled_tenant", num.state_probe_vector(
            jnp.asarray(st)), num_ops=100, expected_norm=1e4, warn=False)
        assert rec.findings == ()
        assert rec.band == pytest.approx(
            1e4 * num.ulp_band(100, "float64"))

    def test_warns_with_code(self):
        led = num.NumericLedger()
        bad = num.state_probe_vector(jnp.asarray(num.inject_nan(
            np.asarray(_zero_state(4)))))
        with pytest.warns(RuntimeWarning, match="O_NUMERIC_NAN"):
            led.record("nan", bad, num_ops=4)

    def test_corruption_selftest(self):
        rep = num.corruption_selftest()
        assert rep["ok"], rep


# ---------------------------------------------------------------------------
# bit-identity contract: instrumented primary output == uninstrumented,
# per engine path (the serving contract's numeric twin)
# ---------------------------------------------------------------------------

class TestBitIdentity:
    def test_xla_single_program(self):
        cache = CompileCache()
        c = random_circuit(6, depth=2, seed=3)
        ops = tuple(c.key())
        st = _zero_state(6)
        entry = cache.entry_for(ops, 6)
        params = cache._check_params(entry, qt.circuit.param_vector(ops))
        plain = np.asarray(cache.single_program(entry, st).call(st, params))
        probed, pv = cache.single_probed_program(entry, st).call(st, params)
        assert np.array_equal(np.asarray(probed), plain)
        assert num.probe_dict(pv)["norm"] == pytest.approx(1.0, abs=1e-12)

    def test_batched_map_program(self):
        cache = CompileCache()
        led = num.NumericLedger()
        svc = QuESTService(max_batch=8, max_delay_ms=20, cache=cache,
                           probes=True, numeric_ledger=led, start=False)
        circuits = [vqe_ansatz(6, 1, seed=s) for s in range(4)]
        futs = [svc.submit(c) for c in circuits]
        svc.start()
        assert svc.drain(timeout=300)
        oracle = CompileCache()
        for c, f in zip(circuits, futs):
            res = f.result(timeout=60)
            want = np.asarray(oracle.execute(c.key(), _zero_state(6),
                                             num_qubits=6))
            assert np.array_equal(res.state, want)
            assert res.numeric_health is not None
            assert res.numeric_health["findings"] == []
        assert res.batch_size == 4          # actually co-batched
        svc.shutdown()

    def test_scheduled_mesh_program(self):
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 virtual devices")
        cache = CompileCache()
        c = qft_circuit(8)
        ops = tuple(c.key())
        opts = CacheOptions(num_devices=8)
        st = _zero_state(8)
        entry = cache.entry_for(ops, 8, opts)
        assert entry.skeleton is not None
        params = cache._check_params(entry, qt.circuit.param_vector(ops))
        plain = np.asarray(cache.single_program(entry, st).call(st, params))
        probed, pv = cache.single_probed_program(entry, st).call(st, params)
        assert np.array_equal(np.asarray(probed), plain)
        assert num.probe_dict(pv)["nan_count"] == 0

    def test_epoch_pallas_per_pass(self):
        from quest_tpu.ops import epoch_pallas as _ep
        c = qft_circuit(10)
        ops = tuple(c.key())
        st = _zero_state(10, jnp.float32)
        base = np.asarray(_ep.jit_program(ops)(st))
        out, points, plan = num.epoch_pass_probes(ops, 10, st)
        assert np.array_equal(np.asarray(out), base)
        # the probe-point count independently confirms the planner's
        # fused-pass boundaries: one probe per Pallas pass + XLA segment
        xla_segments = sum(1 for s in plan["segments"]
                           if s["engine"] == "xla")
        assert len(points) == plan["pallas_passes"] + xla_segments
        assert all(p["nan_count"] == 0 for p in points)
        assert points[-1]["norm"] == pytest.approx(1.0, rel=1e-5)


# ---------------------------------------------------------------------------
# serve integration
# ---------------------------------------------------------------------------

class TestServeNumericHealth:
    def test_unprobed_requests_carry_no_health(self):
        svc = QuESTService(max_batch=2, max_delay_ms=5,
                           cache=CompileCache(), probes=False, start=False)
        fut = svc.submit(qft_circuit(5))
        svc.start()
        assert svc.drain(timeout=120)
        assert fut.result(timeout=60).numeric_health is None
        svc.shutdown()

    def test_per_submit_override(self):
        led = num.NumericLedger()
        svc = QuESTService(max_batch=2, max_delay_ms=5,
                           cache=CompileCache(), probes=False,
                           numeric_ledger=led, start=False)
        fut = svc.submit(qft_circuit(5), probes=True)
        svc.start()
        assert svc.drain(timeout=120)
        assert fut.result(timeout=60).numeric_health is not None
        assert led.snapshot()["probed_total"] == 1
        svc.shutdown()

    def test_env_var_default(self, monkeypatch):
        monkeypatch.setenv("QUEST_TPU_NUMERIC_PROBES", "1")
        svc = QuESTService(max_batch=2, cache=CompileCache(), start=False)
        assert svc.default_probes
        svc.shutdown(drain=False)

    def test_nan_outcome_dumps_flight_ring(self):
        led = num.NumericLedger()
        svc = QuESTService(max_batch=2, max_delay_ms=5,
                           cache=CompileCache(), probes=True,
                           numeric_ledger=led, start=False)
        bad = np.zeros((2, 32))
        bad[0, 0] = np.nan
        fut = svc.submit(qft_circuit(5), initial_state=bad)
        svc.start()
        assert svc.drain(timeout=120)
        res = fut.result(timeout=60)
        assert res.numeric_health["nan_count"] > 0
        assert any(num.NUMERIC_NAN in f
                   for f in res.numeric_health["findings"])
        assert svc.flight_recorder.last_dump is not None
        assert svc.flight_recorder.last_dump["reason"] == num.NUMERIC_NAN
        # the ring record carries the health payload for the post-mortem
        recs = [r for r in svc.flight_recorder.records()
                if r.numeric_health is not None]
        assert recs and recs[0].numeric_health["nan_count"] > 0
        assert led.snapshot()["nan_total"] == 1
        svc.shutdown()

    def test_non_unit_initial_state_is_not_drift(self):
        """A legal caller-supplied initial state need not be unit-norm;
        the drift baseline is the request's OWN input norm, so a scaled
        (but finite) input must not read as a kernel miscompile."""
        led = num.NumericLedger()
        svc = QuESTService(max_batch=2, max_delay_ms=5,
                           cache=CompileCache(), probes=True,
                           numeric_ledger=led, start=False)
        st = np.zeros((2, 32))
        st[0, 0] = 0.9                      # norm 0.81, deliberately
        fut = svc.submit(qft_circuit(5), initial_state=st)
        svc.start()
        assert svc.drain(timeout=120)
        health = fut.result(timeout=60).numeric_health
        assert health["findings"] == []
        assert health["norm"] == pytest.approx(0.81, abs=1e-12)
        assert led.snapshot()["drift_total"] == 0
        svc.shutdown()

    def test_one_scrape_carries_numeric_gauges(self):
        led = num.NumericLedger()
        svc = QuESTService(max_batch=2, max_delay_ms=5,
                           cache=CompileCache(), probes=True,
                           numeric_ledger=led, start=False)
        fut = svc.submit(qft_circuit(5))
        svc.start()
        assert svc.drain(timeout=120)
        fut.result(timeout=60)
        from quest_tpu.serve.metrics import parse_prometheus
        parsed = parse_prometheus(svc.prometheus())
        assert parsed["quest_serve_numeric_probed_total"][""] == 1
        assert parsed["quest_serve_numeric_ledger_nan_total"][""] == 0
        d = svc.metrics_dict()
        assert d["numeric"]["probed_total"] == 1
        assert d["numeric"]["by_class"]
        svc.shutdown()

    def test_probed_and_unprobed_do_not_cobatch(self):
        led = num.NumericLedger()
        svc = QuESTService(max_batch=8, max_delay_ms=20,
                           cache=CompileCache(), probes=False,
                           numeric_ledger=led, start=False)
        c = qft_circuit(5)
        f1 = svc.submit(c, probes=True)
        f2 = svc.submit(c, probes=False)
        svc.start()
        assert svc.drain(timeout=120)
        r1, r2 = f1.result(timeout=60), f2.result(timeout=60)
        assert r1.numeric_health is not None
        assert r2.numeric_health is None
        assert r1.batch_size == 1 and r2.batch_size == 1
        # ... but they share one SLO/trace class identity
        assert np.array_equal(r1.state, r2.state)
        svc.shutdown()


# ---------------------------------------------------------------------------
# deploy router quarantine
# ---------------------------------------------------------------------------

class _FakeReplica:
    def __init__(self, index, service):
        self.index = index
        self.service = service

    def health(self):
        return self.service.slo.health()


def _wait_for(cond, timeout=10.0):
    """Poll until ``cond()`` — Future done-callbacks (the router's
    feedback channel) run AFTER result() can already return in the
    submitting thread, so feedback-dependent asserts must wait."""
    import time as _time
    end = _time.monotonic() + timeout
    while _time.monotonic() < end:
        if cond():
            return True
        _time.sleep(0.01)
    return cond()


class TestRouterQuarantine:
    def test_repeated_nan_quarantines_placement(self):
        from quest_tpu.deploy import Router, RouterConfig
        caches = [CompileCache(), CompileCache()]
        svcs = [QuESTService(max_batch=2, max_delay_ms=5, cache=caches[i],
                             probes=True, numeric_ledger=num.NumericLedger(),
                             start=True) for i in range(2)]
        try:
            replicas = [_FakeReplica(i, s) for i, s in enumerate(svcs)]
            router = Router(replicas, RouterConfig(quarantine_nans=2,
                                                   quarantine_s=300.0))
            c = qft_circuit(5)
            ck = router.class_key(c)
            bad = np.zeros((2, 32))
            bad[0, 0] = np.nan
            first = router.route(c)[0].index
            for _ in range(2):
                router.submit(c, initial_state=bad).result(timeout=60)
            assert _wait_for(lambda: router.snapshot()["quarantined"])
            snap = router.snapshot()
            assert snap["quarantined"] == [f"{ck}@{first}"]
            assert ck not in snap["placements"]
            # the next request re-places away from the quarantined pair
            replica, decision = router.route(c)
            assert replica.index != first
            assert decision["quarantine_skipped"] == [first]
        finally:
            for s in svcs:
                s.shutdown()

    def test_stale_strike_does_not_combine_with_fresh_nan(self):
        """A strike older than quarantine_s is not 'consecutive' with a
        fresh NaN: the window decays, and route()'s prune sweep drops the
        stale entry so the dict cannot grow for the process lifetime."""
        from quest_tpu.deploy import Router, RouterConfig
        svc = QuESTService(max_batch=2, max_delay_ms=5,
                           cache=CompileCache(), probes=True,
                           numeric_ledger=num.NumericLedger(), start=True)
        try:
            router = Router([_FakeReplica(0, svc)],
                            RouterConfig(quarantine_nans=2,
                                         quarantine_s=300.0))
            c = qft_circuit(5)
            ck = router.class_key(c)
            router.report_numeric(ck, 0, ok=False)
            # age the strike past the window, then strike again
            with router._lock:
                strikes, t = router._nan_strikes[(ck, 0)]
                router._nan_strikes[(ck, 0)] = (strikes, t - 301.0)
            router.report_numeric(ck, 0, ok=False)
            assert router.snapshot()["quarantined"] == []
            with router._lock:
                assert router._nan_strikes[(ck, 0)][0] == 1
            # the aged-out form is also pruned by the route() sweep
            with router._lock:
                strikes, t = router._nan_strikes[(ck, 0)]
                router._nan_strikes[(ck, 0)] = (strikes, t - 301.0)
            router.route(c)
            with router._lock:
                assert (ck, 0) not in router._nan_strikes
        finally:
            svc.shutdown()

    def test_clean_outcome_resets_strikes(self):
        from quest_tpu.deploy import Router, RouterConfig
        svc = QuESTService(max_batch=2, max_delay_ms=5,
                           cache=CompileCache(), probes=True,
                           numeric_ledger=num.NumericLedger(), start=True)
        try:
            router = Router([_FakeReplica(0, svc)],
                            RouterConfig(quarantine_nans=2))
            c = qft_circuit(5)
            ck = router.class_key(c)

            def strikes():
                with router._lock:
                    pair = router._nan_strikes.get((ck, 0))
                    return pair[0] if pair else 0

            bad = np.zeros((2, 32))
            bad[0, 0] = np.nan
            router.submit(c, initial_state=bad).result(timeout=60)
            assert _wait_for(lambda: strikes() == 1)
            router.submit(c).result(timeout=60)           # clean: resets
            assert _wait_for(lambda: strikes() == 0)
            router.submit(c, initial_state=bad).result(timeout=60)
            assert _wait_for(lambda: strikes() == 1)
            assert router.snapshot()["quarantined"] == []
        finally:
            svc.shutdown()


# ---------------------------------------------------------------------------
# API parity: calc_total_prob / calc_purity / calc_fidelity
# ---------------------------------------------------------------------------

class TestHealthAPI:
    def test_calc_total_prob_statevec_and_density(self, env_local):
        q = qt.createQureg(4, env_local)
        qt.hadamard(q, 0)
        assert qt.calc_total_prob(q) == pytest.approx(1.0, abs=1e-12)
        rho = qt.createDensityQureg(3, env_local)
        qt.mixDepolarising(rho, 0, 0.3)
        assert qt.calc_total_prob(rho) == pytest.approx(1.0, abs=1e-12)
        qt.destroyQureg(q, env_local)
        qt.destroyQureg(rho, env_local)

    def test_destroyed_register_raises_validation_error(self, env_local):
        q = qt.createQureg(3, env_local)
        qt.destroyQureg(q, env_local)
        with pytest.raises(QuESTError) as e:
            qt.calc_total_prob(q)
        assert e.value.code == ErrorCode.QUREG_NOT_INITIALISED
        with pytest.raises(QuESTError):
            qt.calc_purity(q)

    def test_calc_purity_validates_density(self, env_local):
        q = qt.createQureg(3, env_local)
        with pytest.raises(QuESTError) as e:
            qt.calc_purity(q)
        assert e.value.code == ErrorCode.DEFINED_ONLY_FOR_DENSMATRS
        qt.destroyQureg(q, env_local)

    def test_calc_fidelity_matches_camel_surface(self, env_local):
        rho = qt.createDensityQureg(3, env_local)
        psi = qt.createQureg(3, env_local)
        qt.hadamard(psi, 1)
        got = qt.calc_fidelity(rho, psi)
        assert got == pytest.approx(qt.calcFidelity(rho, psi))
        qt.destroyQureg(rho, env_local)
        qt.destroyQureg(psi, env_local)

    def test_destroyed_fidelity_reference_raises(self, env_local):
        rho = qt.createDensityQureg(3, env_local)
        psi = qt.createQureg(3, env_local)
        qt.destroyQureg(psi, env_local)
        with pytest.raises(QuESTError) as e:
            qt.calc_fidelity(rho, psi)
        assert e.value.code == ErrorCode.QUREG_NOT_INITIALISED
        qt.destroyQureg(rho, env_local)


# ---------------------------------------------------------------------------
# the --numeric-report CLI (one-JSON-document contract)
# ---------------------------------------------------------------------------

class TestNumericReportCLI:
    def test_one_document_with_numeric_sections(self, capsys):
        from quest_tpu.analysis.__main__ import main
        num.global_numeric_ledger().clear()
        rc = main(["--qft", "6", "--numeric-report", "--no-hints", "--json"])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 0
        row = doc["numeric_report"][0]
        assert row["bit_identical"]
        assert row["ledger"]["findings"] == []
        led = doc["numeric_ledger"]
        assert led["probed_total"] >= 1
        assert led["nan_total"] == 0 and led["drift_total"] == 0
        assert doc["summary"]["counts"]["ERROR"] == 0
