"""Calibration loop tests (quest_tpu/obs/calibrate.py + the planner's
calibration-aware models + the ledger's fitted wall band).

The acceptance spine of PR 9:

- profile save/load round-trip, schema-validated (a corrupted document
  must refuse to load);
- planner override monotonicity: raising a fitted efficiency never flips
  an engine decision TOWARD the slower engine;
- deterministic decisions: loading the same profile twice reproduces
  identical ``select_engine``/``schedule`` outputs;
- the ADVERSARIAL flip: a profile with inverted efficiencies provably
  flips an engine decision — the proof the planner is reading measured
  constants, not the hard-coded defaults;
- the ledger band fix: with a profile loaded the wall band is checked on
  ANY platform against the profile's fitted residual band, and every
  record carries calibration provenance;
- a fast end-to-end harness smoke (reduced repeats, no Pallas/f64) that
  the fitted profile is schema-valid and activatable.
"""

from __future__ import annotations

import json

import pytest

from quest_tpu import obs, qft_circuit
from quest_tpu.obs import calibrate as cal
from quest_tpu.parallel import planner


def _profile(effs=None, **kw):
    base = {"f32_gate": 0.18, "f64_gate": 0.065, "pallas_epoch": 0.29}
    base.update(effs or {})
    return cal.make_profile(efficiencies=base, **kw)


# ---------------------------------------------------------------------------
# profile persistence + schema
# ---------------------------------------------------------------------------

def test_profile_roundtrip_schema_validated(tmp_path):
    prof = _profile({"f32_gate": 0.042},
                    fit_residuals={"f32_gate": 2.5, "f64_gate": 1.5,
                                   "pallas_epoch": 1.1},
                    collective_bytes_per_sec={"permute": 8e7,
                                              "reshard": 5e7},
                    measurements={"harness": {"repeats": 2}})
    assert cal.validate_profile(prof.as_dict()) == []
    path = tmp_path / "profile.json"
    doc = cal.save_profile(prof, str(path))
    assert doc["profile_id"] == prof.profile_id
    loaded = cal.load_profile(str(path))
    assert loaded == prof           # frozen dataclass: exact field equality
    assert loaded.profile_id == prof.profile_id
    assert loaded.wall_band == prof.wall_band
    # the file is plain JSON: an offline consumer reads it without us
    raw = json.loads(path.read_text())
    assert raw["format"] == cal.PROFILE_FORMAT
    assert raw["efficiencies"]["f32_gate"] == pytest.approx(0.042)


def test_profile_schema_rejections(tmp_path):
    prof = _profile()
    doc = prof.as_dict()
    # a hand-edited efficiency breaks the content hash: tamper-evident
    doc["efficiencies"]["f32_gate"] = 0.99
    assert any("content hash" in p for p in cal.validate_profile(doc))
    # missing a required engine class
    doc2 = prof.as_dict()
    del doc2["efficiencies"]["pallas_epoch"]
    assert any("pallas_epoch" in p for p in cal.validate_profile(doc2))
    # bad band ordering
    doc3 = prof.as_dict()
    doc3["wall_band"] = [3.0, 0.5]
    assert any("wall_band" in p for p in cal.validate_profile(doc3))
    # load_profile refuses an invalid document outright
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(doc))
    with pytest.raises(ValueError, match="not a valid"):
        cal.load_profile(str(bad))
    # wrong format tag
    assert cal.validate_profile({"format": "something-else"})


def test_profile_staleness_clock():
    import time
    old = _profile(created_epoch_s=time.time() - 10 * 86400,
                   stale_after_s=7 * 86400)
    assert old.stale()
    assert old.age_s() > 9 * 86400
    fresh = _profile()
    assert not fresh.stale()
    s = old.summary()
    assert s["stale"] and s["profile_id"] == old.profile_id


# ---------------------------------------------------------------------------
# activation + the planner reading fitted constants
# ---------------------------------------------------------------------------

def test_activation_scopes_and_restores():
    assert planner.efficiency_for("f32_gate") == \
        planner.MEASURED_EFFICIENCY["f32_gate"]
    prof = _profile({"f32_gate": 0.5})
    with cal.use_profile(prof):
        assert planner.efficiency_for("f32_gate") == 0.5
        assert cal.active_profile() is prof
        prov = planner.calibration_provenance()
        assert prov["source"] == "profile"
        assert prov["profile_id"] == prof.profile_id
    assert planner.efficiency_for("f32_gate") == \
        planner.MEASURED_EFFICIENCY["f32_gate"]
    assert planner.calibration_provenance() == {"source": "default"}


def test_time_model_uses_fitted_constants():
    c = qft_circuit(12)
    base = sum(t.total_s for t in planner.time_model(c, 1))
    # doubling the fitted efficiency must exactly halve modeled compute
    prof = _profile({"f32_gate": planner.MEASURED_EFFICIENCY["f32_gate"]
                     * 2.0})
    with cal.use_profile(prof):
        fitted = sum(t.total_s for t in planner.time_model(c, 1))
    assert fitted == pytest.approx(base / 2.0, rel=1e-12)


def test_time_model_uses_fitted_collective_bandwidth():
    c = qft_circuit(12)
    prof = _profile(collective_bytes_per_sec={"permute": 1e6,
                                              "reshard": 1e6})
    with cal.use_profile(prof):
        times = planner.time_model(c, 8)
        comm = [t for t in times if t.comm != "none"]
        assert comm, "the 12q QFT over x8 must model comm events"
        for t in comm:
            # fitted: comm seconds == bytes / fitted bw, no topology factor
            plan_bytes = t.comm_s * 1e6
            assert plan_bytes > 0


def test_efficiency_rescales_across_chip_specs():
    """A fitted efficiency is relative to the profile's reference chip:
    consumed against a DIFFERENT ChipSpec it must rescale by the
    reference-peak ratio so the implied (measured) pass seconds are
    preserved — a v5e profile under --chip v5p must not silently
    mis-scale predictions."""
    prof = _profile({"f32_gate": 0.2}, chip="v5e")
    with cal.use_profile(prof):
        e_v5e = planner.efficiency_for("f32_gate", planner.V5E)
        e_v5p = planner.efficiency_for("f32_gate", planner.V5P)
    assert e_v5e == pytest.approx(0.2)
    # same implied pass seconds: eff x chip peak is invariant
    assert e_v5e * planner.V5E.hbm_bytes_per_sec == pytest.approx(
        e_v5p * planner.V5P.hbm_bytes_per_sec)
    # chip=None (bare class read) returns the stored value unscaled
    with cal.use_profile(prof):
        assert planner.efficiency_for("f32_gate") == pytest.approx(0.2)


def test_collective_fit_cancels_latency():
    """The two-point collective fit recovers the true bandwidth from
    latency-dominated probes: with t = latency + bytes/bw the plain
    bytes/seconds ratio undershoots bw badly, the slope is exact."""
    from quest_tpu.obs.calibrate import _fit_collective_points
    latency, bw = 1e-3, 1e9
    pts = [(16_384, latency + 16_384 / bw),
           (1_048_576, latency + 1_048_576 / bw)]
    fitted, kind, _, _ = _fit_collective_points(pts)
    assert kind == "two_point_slope"
    assert fitted == pytest.approx(bw, rel=1e-9)
    # the naive ratio would have been ~60x off for the small probe
    assert pts[0][0] / pts[0][1] < bw / 50
    # noise case: large probe timed no slower -> conservative ratio
    fitted, kind, _, _ = _fit_collective_points(
        [(16_384, 2e-3), (1_048_576, 2e-3)])
    assert kind == "ratio_fallback"
    assert fitted == pytest.approx(1_048_576 / 2e-3)


def test_select_engine_carries_calibration_provenance():
    c = qft_circuit(17)
    choice = planner.select_engine(c, 1, backend="tpu")
    assert choice["calibration"] == {"source": "default"}
    prof = _profile()
    with cal.use_profile(prof):
        choice = planner.select_engine(c, 1, backend="tpu")
        assert choice["calibration"]["source"] == "profile"
        assert choice["calibration"]["profile_id"] == prof.profile_id
        # engine_summary and schedule_savings surface the same stamp
        summ = planner.engine_summary(c, 1)
        assert summ["calibration"]["profile_id"] == prof.profile_id
        from quest_tpu.parallel.scheduler import schedule_savings
        report = schedule_savings(qft_circuit(12), 8)
        assert report["calibration"]["profile_id"] == prof.profile_id


def test_compile_circuit_carries_calibration():
    from quest_tpu.circuit import compile_circuit
    prof = _profile()
    with cal.use_profile(prof):
        run = compile_circuit(qft_circuit(6))
        assert run.engine_calibration["source"] == "profile"
        assert run.engine_calibration["profile_id"] == prof.profile_id


# ---------------------------------------------------------------------------
# the adversarial flip + monotonicity + determinism
# ---------------------------------------------------------------------------

def test_inverted_profile_flips_engine_decision():
    """The acceptance proof: an adversarial profile whose efficiencies
    invert the engines' ranking must flip ``select_engine``'s pick — the
    planner is reading measured constants, not the defaults."""
    c = qft_circuit(17)
    default = planner.select_engine(c, 1, backend="tpu")
    assert default["engine"] == "pallas"    # 1 fused pass vs 153: model win
    inverted = _profile({"f32_gate": 0.9, "pallas_epoch": 1e-4})
    with cal.use_profile(inverted):
        flipped = planner.select_engine(c, 1, backend="tpu")
    assert flipped["engine"] == "xla"
    assert "slower" in flipped["reason"]
    assert flipped["calibration"]["profile_id"] == inverted.profile_id


def test_efficiency_monotonicity_never_flips_toward_slower():
    """Raising the fitted pallas efficiency (everything else pinned) can
    only move the decision TOWARD the engine that got faster: once pallas
    is chosen at some efficiency, it stays chosen at every higher one."""
    c = qft_circuit(17)
    picks = []
    for scale in (1e-5, 1e-4, 1e-3, 1e-2, 0.05, 0.29, 0.9):
        prof = _profile({"f32_gate": 0.18, "pallas_epoch": scale})
        with cal.use_profile(prof):
            picks.append(planner.select_engine(c, 1,
                                               backend="tpu")["engine"])
    # no pallas -> xla transition anywhere along the rising-efficiency walk
    seen_pallas = False
    for engine in picks:
        if engine == "pallas":
            seen_pallas = True
        assert not (seen_pallas and engine == "xla"), picks
    assert picks[-1] == "pallas", picks


def test_same_profile_twice_is_deterministic(tmp_path):
    """Loading the same profile twice reproduces identical
    select_engine and schedule outputs — calibration must never make
    deployments flap."""
    prof = _profile({"f32_gate": 0.07, "pallas_epoch": 0.2},
                    collective_bytes_per_sec={"permute": 7.7e7,
                                              "reshard": 4.2e7})
    path = tmp_path / "p.json"
    cal.save_profile(prof, str(path))
    c_engine = qft_circuit(17)
    c_sched = qft_circuit(14)
    outs = []
    for _ in range(2):
        loaded = cal.load_profile(str(path))
        with cal.use_profile(loaded):
            choice = planner.select_engine(c_engine, 1, backend="tpu")
            sched = c_sched.schedule(8)
            outs.append((choice["engine"], choice["reason"],
                         choice["calibration"]["profile_id"],
                         tuple(sched.ops)))
    assert outs[0] == outs[1]


# ---------------------------------------------------------------------------
# the ledger band fix (satellite): fitted band on ANY platform
# ---------------------------------------------------------------------------

def test_ledger_checks_wall_on_any_platform_with_profile():
    prof = _profile(fit_residuals={"f32_gate": 2.0, "f64_gate": 2.0,
                                   "pallas_epoch": 2.0})
    lo, hi = prof.wall_band
    led = obs.Ledger()
    with cal.use_profile(prof):
        good = led.record("in_band", platform="cpu",
                          predicted_seconds=1.0,
                          measured_seconds=(lo + hi) / 2, warn=False)
        assert good.wall_checked and good.findings == ()
        assert good.wall_band == (lo, hi)
        assert good.calibration["profile_id"] == prof.profile_id
        with pytest.warns(RuntimeWarning, match="O_MODEL_DRIFT"):
            bad = led.record("out_of_band", platform="cpu",
                             predicted_seconds=1.0,
                             measured_seconds=hi * 2.0)
        assert bad.wall_checked and len(bad.findings) == 1
        assert prof.profile_id in bad.findings[0]
        assert "analysis --calibrate" in bad.findings[0]
    # without the profile the legacy gate stands: CPU walls unjudged
    ungated = led.record("cpu_default", platform="cpu",
                         predicted_seconds=1.0, measured_seconds=hi * 2.0,
                         warn=False)
    assert not ungated.wall_checked and ungated.findings == ()
    assert ungated.calibration == {"source": "default"}


def test_ledger_record_carries_runtime_counters():
    led = obs.Ledger()
    rec = led.record("with_counters", platform="cpu",
                     compile_seconds=1.25, hbm_peak_bytes=123456,
                     warn=False)
    d = rec.as_dict()
    assert d["compile_seconds"] == 1.25
    assert d["hbm_peak_bytes"] == 123456


# ---------------------------------------------------------------------------
# runtime counters + the scrape gauges
# ---------------------------------------------------------------------------

def test_runtime_counters_and_snapshot_gauges():
    from quest_tpu.obs.counters import RuntimeCounters
    c = RuntimeCounters()
    c.record_compile(1.5)
    c.record_compile(0.5)
    c.record_dispatch(0.01)
    c.record_hbm(100, 200)
    c.record_hbm(50, 150)       # peak is a high-water mark
    snap = c.snapshot()
    assert snap["compiles_total"] == 2
    assert snap["compile_seconds_total"] == pytest.approx(2.0)
    assert snap["dispatches_total"] == 1
    assert snap["hbm_peak_bytes"] == 200
    assert snap["hbm_bytes_in_use"] == 50
    # the obs snapshot is all-numeric (the Prometheus gauge contract) and
    # reports calibration staleness
    prof = _profile()
    with cal.use_profile(prof):
        s = obs.obs_snapshot()
        assert s["calibration_loaded"] == 1
        assert s["calibration_age_s"] >= 0
        assert all(isinstance(v, (int, float)) for v in s.values())
    s = obs.obs_snapshot()
    assert s["calibration_loaded"] == 0 and s["calibration_age_s"] == -1.0


def test_serve_scrape_carries_calibration_gauges():
    from quest_tpu.serve import QuESTService
    from quest_tpu.serve.metrics import parse_prometheus
    prof = _profile()
    with cal.use_profile(prof):
        svc = QuESTService(start=False)
        try:
            parsed = parse_prometheus(svc.prometheus())
        finally:
            svc.shutdown(drain=False)
    assert parsed["quest_serve_obs_calibration_loaded"][""] == 1.0
    assert parsed["quest_serve_obs_calibration_stale"][""] == 0.0
    assert "quest_serve_obs_compile_seconds_total" in parsed


# ---------------------------------------------------------------------------
# the harness end-to-end (fast settings) + env autoload
# ---------------------------------------------------------------------------

def test_run_calibration_fast_smoke(tmp_path):
    prof = cal.run_calibration(num_qubits=12, repeats=1, iters=2,
                               include_f64=False, include_pallas=False,
                               collectives=False)
    assert cal.validate_profile(prof.as_dict()) == []
    for clsname in cal.REQUIRED_CLASSES:
        assert prof.efficiencies[clsname] > 0
    # the widened envelope's pass kinds are ALWAYS priced in the profile —
    # fitted where measured, else derived off the block-pass correction
    for clsname in ("pallas_epoch_pack", "pallas_epoch_small"):
        assert prof.efficiencies[clsname] > 0
        assert clsname in prof.measurements["derived"]
    assert all(r >= 1.0 for r in prof.fit_residuals.values())
    lo, hi = prof.wall_band
    assert 0 < lo < 1 < hi
    # derived classes are recorded as derived, measured ones are not
    assert "pallas_epoch" in prof.measurements["derived"]
    assert "f32_gate" not in prof.measurements["derived"]
    # fitted constants activate end-to-end
    path = tmp_path / "fast.json"
    cal.save_profile(prof, str(path))
    with cal.use_profile(cal.load_profile(str(path))):
        assert planner.efficiency_for("f32_gate") == \
            prof.efficiencies["f32_gate"]


def test_run_calibration_measures_small_geometry():
    """include_pallas at n=12 runs the degenerate single-block microbench:
    the pallas_epoch_small class is FITTED from a real interpret-mode row,
    not derived, and the row carries the new pass-kind metadata."""
    prof = cal.run_calibration(num_qubits=12, repeats=1, iters=1,
                               include_f64=False, include_pallas=True,
                               collectives=False)
    assert cal.validate_profile(prof.as_dict()) == []
    assert "pallas_epoch_small" not in prof.measurements["derived"]
    assert prof.efficiencies["pallas_epoch_small"] > 0
    row = prof.measurements["pallas_block_lane"]
    assert row["engine_class"] == "pallas_epoch_small"
    assert row["num_qubits"] == 12
    # no high qubits at n=12: the pack class stays derived
    assert "pallas_epoch_pack" in prof.measurements["derived"]


def test_env_autoload(tmp_path, monkeypatch):
    prof = _profile({"f32_gate": 0.123})
    path = tmp_path / "env.json"
    cal.save_profile(prof, str(path))
    monkeypatch.setattr(cal, "_ACTIVE", None)
    monkeypatch.setattr(cal, "_ENV_CHECKED", False)
    monkeypatch.setenv("QUEST_TPU_CALIBRATION", str(path))
    try:
        loaded = cal.active_profile()
        assert loaded is not None and loaded.profile_id == prof.profile_id
        assert planner.efficiency_for("f32_gate") == pytest.approx(0.123)
    finally:
        cal.deactivate()
    # a bad path warns (once) and falls back to defaults, never raises
    monkeypatch.setattr(cal, "_ACTIVE", None)
    monkeypatch.setattr(cal, "_ENV_CHECKED", False)
    monkeypatch.setenv("QUEST_TPU_CALIBRATION", str(tmp_path / "nope.json"))
    try:
        with pytest.warns(RuntimeWarning, match="QUEST_TPU_CALIBRATION"):
            assert cal.active_profile() is None
        assert planner.efficiency_for("f32_gate") == \
            planner.MEASURED_EFFICIENCY["f32_gate"]
    finally:
        cal.deactivate()


def test_merged_trace_report_sections():
    """obs/export.py trace_report renders a MERGED multi-process document
    with per-process sections and the clock offset noted (the satellite:
    no more assuming a single-process recorder)."""
    import copy
    rec = obs.TraceRecorder(enabled=True)
    with rec.span("work", step=1):
        pass
    import quest_tpu.obs.aggregate as agg
    sh0 = agg.process_shard(rec, align_clock=False)
    sh1 = copy.deepcopy(sh0)
    sh1["process_index"] = 1
    sh1["clock_offset_s"] = 0.0035
    sh1["host"] = "replica-b"
    merged = obs.merge_shards([sh0, sh1])
    assert obs.validate_chrome_trace(merged) == []
    text = obs.trace_report(merged)
    assert "2 process(es)" in text
    assert "process 1" in text and "replica-b" in text
    assert "+0.003500s" in text
    assert text.count("work") >= 2
    # the degenerate single-shard merge renders without process sections
    single = obs.trace_report(obs.merge_shards([sh0]))
    assert "1 process(es)" in single and "clock offset" not in single
