"""Serve SLO monitor (quest_tpu/obs/slo.py) + its service wiring:

- the burn-rate formula (miss_rate / error budget) over both windows,
  window aging, and the O_SLO_BURN / saturation warning triggers — all on
  injected timestamps so the math is checked exactly;
- per-class windowed latency views;
- QuESTService integration: deadline-carrying requests feed the hit rate,
  a deadline drop burns budget AND dumps the flight ring with reason
  E_DEADLINE_EXCEEDED (the PR 8 satellite regression: deadline drops
  previously left no dump), metrics_dict()["slo"] and the single
  Prometheus scrape carry the gauges;
- the hot-path overhead budget: observe() stays microseconds-cheap (the
  PR 7 < 1% serve-bench budget covers the always-on monitor).
"""

from __future__ import annotations

import time

import pytest

from quest_tpu.obs.slo import SLO_BURN, SLOConfig, SLOMonitor
from quest_tpu.serve import CompileCache, QuESTService
from quest_tpu.serve.metrics import parse_prometheus
from quest_tpu.serve.selftest import vqe_ansatz
from quest_tpu.validation import QuESTError


def _monitor(**kw):
    return SLOMonitor(SLOConfig(**kw))


# ---------------------------------------------------------------------------
# the formula, on injected clocks
# ---------------------------------------------------------------------------

def test_burn_rate_formula_exact():
    """target 0.99 -> budget 0.01; 2 misses in 10 deadline'd requests is
    miss_rate 0.2 -> burn 20x.  No-deadline samples don't touch budget."""
    m = _monitor(deadline_hit_target=0.99, window_s=60, long_window_s=600,
                 burn_warn=10.0)
    t0 = 1000.0
    for i in range(8):
        m.observe("ckA", 0.010, deadline_ok=True, now=t0 + i)
    for i in range(2):
        m.observe("ckA", 0.500, deadline_ok=False, now=t0 + 8 + i)
    for i in range(5):
        m.observe("ckB", 0.001, deadline_ok=None, now=t0 + i)  # no budget
    snap = m.snapshot(now=t0 + 10)
    d = snap["deadline"]
    assert d["window_hits"] == 8 and d["window_misses"] == 2
    assert d["hit_rate"] == pytest.approx(0.8)
    assert d["burn_rate"] == pytest.approx(0.2 / 0.01)        # 20x
    assert d["long_burn_rate"] == pytest.approx(20.0)
    assert d["hits_total"] == 8 and d["misses_total"] == 2
    # burn 20 >= burn_warn 10: the early warning fires with the numbers
    burn_warns = [w for w in snap["warnings"]
                  if "error budget" in w["detail"]]
    assert len(burn_warns) == 1 and burn_warns[0]["code"] == SLO_BURN
    assert "20.0x" in burn_warns[0]["detail"]


def test_windows_age_out_and_long_window_keeps_context():
    m = _monitor(deadline_hit_target=0.9, window_s=60, long_window_s=600)
    t0 = 5000.0
    m.observe("ck", 0.1, deadline_ok=False, now=t0)            # old miss
    m.observe("ck", 0.1, deadline_ok=True, now=t0 + 120)       # recent hit
    snap = m.snapshot(now=t0 + 130)
    assert snap["deadline"]["window_misses"] == 0              # aged out
    assert snap["deadline"]["hit_rate"] == 1.0
    assert snap["deadline"]["long_hit_rate"] == pytest.approx(0.5)
    assert snap["deadline"]["long_burn_rate"] == pytest.approx(5.0)
    assert snap["warnings"] == []        # short window clean: no page
    # totals never age (the cumulative truth stays in the counters)
    assert snap["deadline"]["misses_total"] == 1
    # with NO deadline'd samples at all, the objective trivially holds
    empty = _monitor().snapshot(now=0.0)
    assert empty["deadline"]["hit_rate"] == 1.0
    assert empty["deadline"]["burn_rate"] == 0.0


def test_per_class_windowed_latency():
    m = _monitor(window_s=60)
    t0 = 100.0
    for i in range(100):
        m.observe("fast", 0.001 * (i + 1), now=t0)
    m.observe("slow", 2.0, now=t0)
    m.observe("gone", 9.0, now=t0 - 120)          # outside the window
    snap = m.snapshot(now=t0 + 1)
    assert set(snap["classes"]) == {"fast", "slow"}
    fast = snap["classes"]["fast"]
    assert fast["count"] == 100
    assert fast["p50_s"] == pytest.approx(0.050, abs=0.002)
    assert fast["p99_s"] == pytest.approx(0.099, abs=0.002)
    assert fast["max_s"] == pytest.approx(0.100)
    assert snap["classes"]["slow"]["count"] == 1


def test_queue_saturation_gauge_and_warning():
    m = _monitor(window_s=60, saturation_warn=0.8)
    t0 = 10.0
    m.observe_queue(10, 100, now=t0)
    snap = m.snapshot(now=t0 + 1)
    assert snap["queue"]["saturation"] == pytest.approx(0.1)
    assert snap["warnings"] == []
    m.observe_queue(90, 100, now=t0 + 2)          # peak crosses the line
    m.observe_queue(20, 100, now=t0 + 3)
    snap = m.snapshot(now=t0 + 4)
    assert snap["queue"]["saturation"] == pytest.approx(0.2)   # latest
    assert snap["queue"]["peak_saturation"] == pytest.approx(0.9)
    sat_warns = [w for w in snap["warnings"] if "saturation" in w["detail"]]
    assert len(sat_warns) == 1 and sat_warns[0]["code"] == SLO_BURN


def test_gauges_flatten_for_prometheus():
    m = _monitor(deadline_hit_target=0.99)
    m.observe("ck", 0.1, deadline_ok=False, now=1.0)
    g = m.gauges(now=2.0)
    assert g["deadline_hit_rate"] == 0.0
    assert g["burn_rate"] == pytest.approx(100.0)
    assert g["burn_warnings"] >= 1.0
    assert set(g) == {"deadline_hit_rate", "deadline_misses_total",
                      "burn_rate", "long_burn_rate", "queue_saturation",
                      "queue_peak_saturation", "burn_warnings"}


def test_sample_store_is_bounded():
    from quest_tpu.obs import slo as slo_mod
    m = _monitor()
    for i in range(slo_mod._MAX_SAMPLES + 10):
        m.observe("ck", 0.001, now=float(i))
        m.observe_queue(1, 10, now=float(i))
    assert len(m._samples) <= slo_mod._MAX_SAMPLES
    assert len(m._saturation) <= slo_mod._MAX_SAMPLES


def test_observe_overhead_within_budget():
    """The monitor is ALWAYS on: one observe per completed request must
    stay microseconds-cheap.  Budget: < 20 us/call keeps 64 requests'
    samples under 1.3 ms against the >= 1 s serve-bench batch wall — the
    same < 1% envelope the PR 7 disabled-span contract lives in."""
    m = _monitor()
    reps = 20_000
    t0 = time.perf_counter()
    for i in range(reps):
        m.observe("ck", 0.001, deadline_ok=True)
    per_call = (time.perf_counter() - t0) / reps
    assert per_call < 20e-6, f"observe costs {per_call * 1e6:.2f}us"


# ---------------------------------------------------------------------------
# the lock-free health() snapshot (the deploy router's hot-path read)
# ---------------------------------------------------------------------------

def test_health_snapshot_matches_injected_samples():
    m = _monitor(deadline_hit_target=0.99, window_s=60.0)
    t0 = time.monotonic()
    for i in range(80):
        m.observe("ck", 0.004, deadline_ok=(i % 10 != 0), now=t0)
    m.observe_queue(30, 100, now=t0)
    h = m.health(now=t0)
    assert h["saturation"] == pytest.approx(0.30)
    assert h["window_hits"] == 72 and h["window_misses"] == 8
    # miss_rate 0.1 over budget 0.01 => burn 10x, same formula snapshot uses
    assert h["burn_rate"] == pytest.approx(10.0)
    # every latency in the (0.0025, 0.005] bucket: p99 reports its edge
    assert h["p99_s"] == pytest.approx(0.005)
    assert h["window_samples"] == 80


def test_health_window_ages_out():
    m = _monitor(window_s=60.0)
    t0 = time.monotonic()
    m.observe("ck", 5.0, deadline_ok=False, now=t0 - 300)   # ancient miss
    m.observe("ck", 0.001, deadline_ok=True, now=t0)
    h = m.health(now=t0)
    assert h["window_misses"] == 0 and h["window_hits"] == 1
    assert h["burn_rate"] == 0.0


def test_health_read_overhead_within_budget():
    """health() is read PER ROUTING DECISION — it must stay as cheap as
    observe(): < 20 us/call, no lock taken (the ring walk is ~500 plain
    int reads)."""
    m = _monitor()
    for i in range(5000):
        m.observe("ck", 0.001 * (i % 11), deadline_ok=(i % 7 != 0))
        m.observe_queue(i % 60, 100)
    reps = 20_000
    t0 = time.perf_counter()
    for _ in range(reps):
        m.health()
    per_call = (time.perf_counter() - t0) / reps
    assert per_call < 20e-6, f"health costs {per_call * 1e6:.2f}us"


# ---------------------------------------------------------------------------
# service wiring
# ---------------------------------------------------------------------------

def _small_service(**kw):
    kw.setdefault("max_batch", 8)
    kw.setdefault("max_delay_ms", 5)
    kw.setdefault("cache", CompileCache())
    kw.setdefault("start", False)
    return QuESTService(**kw)


def test_service_slo_block_and_scrape():
    svc = _small_service()
    futs = [svc.submit(vqe_ansatz(5, 1, seed=s), deadline_ms=600_000)
            for s in range(3)]
    futs.append(svc.submit(vqe_ansatz(5, 1, seed=9)))   # no objective
    svc.start()
    assert svc.drain(timeout=300)
    for f in futs:
        f.result(timeout=60)
    d = svc.metrics_dict()
    slo = d["slo"]
    assert slo["deadline"]["hits_total"] == 3
    assert slo["deadline"]["hit_rate"] == 1.0
    assert slo["deadline"]["burn_rate"] == 0.0
    assert slo["warnings"] == []
    # all four requests are the same structural class; the windowed class
    # view carries its latency
    (ck,) = slo["classes"]
    assert slo["classes"][ck]["count"] == 4
    assert slo["queue"]["peak_saturation"] > 0
    parsed = parse_prometheus(svc.prometheus())
    assert parsed["quest_serve_slo_deadline_hit_rate"][""] == 1.0
    assert parsed["quest_serve_slo_burn_rate"][""] == 0.0
    assert "quest_serve_slo_queue_saturation" in parsed
    svc.shutdown()


def test_late_completion_burns_budget_even_when_admitted_in_time():
    """Admission-time deadline enforcement lets a punctually-admitted
    request still FINISH late (the first request eats the class compile).
    Wherever the lateness lands — dropped at admission or completed past
    deadline — the SLO must record a miss; a hit would blind the
    burn-rate warning to slow-execution incidents."""
    import numpy as np
    svc = _small_service(max_delay_ms=1)
    # 30 ms deadline vs a cold-compile execution (hundreds of ms on CPU):
    # the request is admitted almost immediately but cannot finish in time
    fut = svc.submit(vqe_ansatz(5, 1, seed=0), deadline_ms=30)
    svc.start()
    assert svc.drain(timeout=300)
    try:
        res = fut.result(timeout=60)
        assert isinstance(res.state, np.ndarray)   # late, but delivered
    except QuESTError as err:                      # or dropped at admission
        assert err.code == "E_DEADLINE_EXCEEDED"
    slo = svc.metrics_dict()["slo"]
    assert slo["deadline"]["misses_total"] == 1
    assert slo["deadline"]["hits_total"] == 0
    svc.shutdown()


def test_execution_error_burns_budget_for_deadlined_requests():
    """A deadline'd request that dies in a worker-side execution error
    consumed its budget too — without this, a crash-loop outage reads as
    a 1.0 hit rate while 100% of deadline'd requests fail."""
    import numpy as np
    n = 4
    svc = _small_service()
    fut = svc.submit(vqe_ansatz(n, 1, seed=0), shots=4,
                     initial_state=np.zeros((2, 1 << n)),  # unnormalisable
                     deadline_ms=600_000)
    svc.start()
    assert svc.drain(timeout=120)
    assert isinstance(fut.exception(timeout=60), ValueError)
    slo = svc.metrics_dict()["slo"]
    assert slo["deadline"]["misses_total"] == 1
    assert slo["deadline"]["hits_total"] == 0
    svc.shutdown()


def test_deadline_drop_burns_budget_and_dumps_flight_ring():
    """The satellite regression: a deadline-exceeded request must (a) feed
    the SLO monitor as a miss and (b) dump the flight ring with reason
    E_DEADLINE_EXCEEDED — previously only E_QUEUE_FULL bounces and
    execution errors dumped, so the most latency-shaped failure mode left
    no post-mortem."""
    svc = _small_service()
    expired = [svc.submit(vqe_ansatz(5, 1, seed=s), deadline_ms=1)
               for s in range(2)]
    alive = svc.submit(vqe_ansatz(5, 1, seed=7), deadline_ms=600_000)
    time.sleep(0.05)
    svc.start()
    assert svc.drain(timeout=300)
    for f in expired:
        with pytest.raises(QuESTError) as err:
            f.result(timeout=60)
        assert err.value.code == "E_DEADLINE_EXCEEDED"
    assert alive.result(timeout=60).state is not None
    # the flight ring dumped ONCE for the batch's drops (not once per
    # drop), with the distinct deadline outcome on each dropped record
    assert svc.flight_recorder.dumps == 1
    dump = svc.flight_recorder.last_dump
    assert dump["reason"] == "E_DEADLINE_EXCEEDED"
    outcomes = [r["outcome"] for r in dump["records"]]
    assert outcomes.count("deadline") == 2
    # budget burned: 2 misses / 3 deadline'd requests
    slo = svc.metrics_dict()["slo"]
    assert slo["deadline"]["misses_total"] == 2
    assert slo["deadline"]["hits_total"] == 1
    assert slo["deadline"]["hit_rate"] == pytest.approx(1.0 / 3.0)
    assert slo["deadline"]["burn_rate"] > 100     # way past sustainable
    assert any(w["code"] == SLO_BURN for w in slo["warnings"])
    svc.shutdown()
