"""Density-matrix noise channels on the Pallas epoch engine (PR 15).

The DensityCircuit IR records a density circuit DIRECTLY as its
Choi-doubled 2n-qubit program (mirrored unitary + conjugate shadow;
channels as superoperator ops on the paired (q, q+n) wires), and the epoch
executor lowers the channels as fused elementwise superoperator stages
(ops/epoch_pallas.py ``_apply_super_spec``) — kernels run in interpret
mode here, Mosaic-compiled on a chip.

Covers: the doubled IR against the eager decoherence oracle (bitwise),
host superop builders against the traced channels, the epoch engine
against the XLA engine on noisy circuits across the geometry regimes
(degenerate block / full block+pack incl. widened-column pack superops),
the O(1)-passes-per-layer pin for the headline 14q damping+depol layer,
arbitrary non-unitary 2-target payloads through the superop stage, the
density window of select_engine, the superoperator window domain of
check_density_lowering/check_density_plan (clean + two adversarial
mutations), Kraus admission (E_INVALID_KRAUS_OPS from apply_kraus_map,
record time and serve submit), the probed density serving path (trace +
Hermiticity health, probability-sweep class sharing, rho-diagonal
sampling), per-pass density probes, the analyzer's channel-aware payload
validation, circuit_stats density reporting, and scheduler metadata
carry-through.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax.numpy as jnp

from quest_tpu.circuit import (DensityCircuit, GateOp, _run_ops,
                               compile_circuit, op_param_count, param_vector,
                               validate_density_operands)
from quest_tpu.ops import decoherence as deco
from quest_tpu.ops import epoch_pallas as ep
from quest_tpu.parallel import planner
from quest_tpu.validation import ErrorCode, QuESTError


def _haar(rng, k: int = 1) -> np.ndarray:
    d = 1 << k
    g = rng.normal(size=(d, d)) + 1j * rng.normal(size=(d, d))
    u, r = np.linalg.qr(g)
    return u * (np.diag(r) / np.abs(np.diag(r)))


def _kraus_damp(p: float) -> list:
    return [np.diag([1.0, np.sqrt(1.0 - p)]),
            np.array([[0.0, np.sqrt(p)], [0.0, 0.0]])]


def _noisy(n: int, seed: int = 0, kraus: bool = True) -> DensityCircuit:
    rng = np.random.default_rng(seed)
    dc = DensityCircuit(n)
    for q in range(n):
        dc.unitary(q, _haar(rng))
    for q in range(0, n, 2):
        dc.damp(q, 0.04 + 0.01 * q)
    for q in range(1, n, 2):
        dc.depolarise(q, 0.03)
    dc.dephase(0, 0.1)
    if n >= 4:
        dc.two_qubit_dephase(1, 3, 0.05)
    if kraus:
        dc.kraus((n - 1,), _kraus_damp(0.2))
    return dc


def _rand_state(n_register: int, seed: int = 7) -> jnp.ndarray:
    rng = np.random.default_rng(seed)
    st = rng.normal(size=(2, 1 << n_register)).astype(np.float32)
    st /= np.sqrt(np.sum(st * st))
    return jnp.asarray(st)


# ---------------------------------------------------------------------------
# IR: the Choi-doubling against the eager decoherence oracle
# ---------------------------------------------------------------------------

def test_doubled_ir_matches_eager_oracle():
    """DensityCircuit's recorded op list reproduces the eager decoherence
    path (mix_damping / mix_depolarising / mix_dephasing /
    mix_two_qubit_dephasing / apply_kraus_map) to f64 rounding — the same
    engine kernels in one fused program vs per-op dispatches, so anything
    beyond last-ulp FMA-contraction drift is a doubling bug."""
    from quest_tpu.ops import apply as ap
    n = 5
    rng = np.random.default_rng(11)
    us = [_haar(rng) for _ in range(n)]
    dc = DensityCircuit(n)
    for q, u in enumerate(us):
        dc.unitary(q, u)
    dc.damp(0, 0.1)
    dc.depolarise(1, 0.07)
    dc.dephase(2, 0.2)
    dc.two_qubit_dephase(3, 4, 0.12)
    dc.kraus((2,), _kraus_damp(0.3))

    st = jnp.zeros((2, 1 << (2 * n)), jnp.float64).at[0, 0].set(1.0)
    s = st
    for q, u in enumerate(us):
        s = ap.apply_matrix(s, jnp.asarray(ap.mat_pair(u)), (q,))
        s = ap.apply_matrix(s, jnp.asarray(ap.mat_pair(u.conj())), (q + n,))
    s = deco.mix_damping(s, jnp.asarray(0.1), 0, n)
    s = deco.mix_depolarising(s, jnp.asarray(0.07), 1, n)
    s = deco.mix_dephasing(s, jnp.asarray(0.2), 2, n)
    s = deco.mix_two_qubit_dephasing(s, jnp.asarray(0.12), 3, 4, n)
    s = deco.apply_kraus_map(s, _kraus_damp(0.3), (2,), n)

    got = _run_ops(st, dc.key())
    np.testing.assert_allclose(np.asarray(got), np.asarray(s), atol=1e-12)
    dim = 1 << n
    trace = float(np.sum(np.asarray(got[0]).reshape(dim, dim).diagonal()))
    assert abs(trace - 1.0) < 1e-12


def test_host_superop_builders_match_traced_channels():
    """The static builders DensityCircuit records are the same maps the
    traced mix_* channels apply (drift between the twins would split the
    doubled-circuit path from the eager API)."""
    n, q, p = 3, 1, 0.23
    st = _rand_state(2 * n, 3).astype(jnp.float64)
    pairs = [
        (deco.damping_superop(p), deco.mix_damping),
        (deco.depolarising_superop(p), deco.mix_depolarising),
    ]
    for sp, fn in pairs:
        want = fn(st, jnp.asarray(p), q, n)
        got = deco._superop_apply(st, jnp.asarray(sp), (q, q + n), None)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-12)
    from quest_tpu.ops.apply import apply_diagonal
    dd = deco.dephasing_diag(p)
    want = deco.mix_dephasing(st, jnp.asarray(p), q, n)
    got = apply_diagonal(st, jnp.asarray(dd), (q, q + n))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-12)


def test_density_circuit_rejects_out_of_range_wires_and_bad_probs():
    dc = DensityCircuit(4)
    with pytest.raises(QuESTError):
        dc.unitary(4, np.eye(2))          # bra wires are not addressable
    with pytest.raises(QuESTError):
        dc.damp(0, 1.5)
    with pytest.raises(QuESTError):
        dc.depolarise(0, 0.9)             # > 3/4
    # channel targets get the same record-time contract as unitary wires
    with pytest.raises(QuESTError) as e:
        dc.damp(4, 0.1)                   # density wire out of range
    assert e.value.code == ErrorCode.INVALID_TARGET_QUBIT
    with pytest.raises(QuESTError) as e:
        dc.two_qubit_dephase(1, 1, 0.05)  # duplicate density targets
    assert e.value.code == ErrorCode.TARGETS_NOT_UNIQUE
    with pytest.raises(QuESTError):
        dc.kraus((4,), _kraus_damp(0.1))
    assert dc.ops == [] and dc.channel_slots == set()  # nothing recorded


# ---------------------------------------------------------------------------
# epoch engine: fused superoperator passes vs the XLA engine
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("n", [5, 6, 8, 9])
def test_epoch_engine_matches_xla_on_noisy_circuits(n):
    """Forced-pallas (interpret) vs the XLA engine on mixed noisy density
    circuits: n=5..8 exercise the degenerate single-block geometry (whole
    window incl. every channel in ONE fused pass), n=9 (18 register
    qubits) the full block+pack geometry with widened-column pack
    superoperator stages."""
    dc = _noisy(n, seed=n)
    plan = ep.plan_circuit(dc.key(), 2 * n)
    assert plan.xla_ops == 0, plan.summary()
    assert plan.super_stages >= 3
    st = _rand_state(2 * n, seed=n)
    want = np.asarray(compile_circuit(dc, engine="xla")(st))
    got = np.asarray(compile_circuit(dc, engine="pallas")(st))
    assert np.abs(got - want).max() < 5e-5


def test_degenerate_geometry_one_pass_per_noisy_window():
    dc = _noisy(7, seed=2)
    plan = ep.plan_circuit(dc.key(), 14)
    assert plan.pallas_passes == 1
    assert plan.xla_ops == 0
    s = plan.summary()
    assert s["super_passes"] == 1 and s["super_stages"] >= 5


def test_headline_14q_damping_depol_layer_is_o1_passes():
    """The acceptance pin: a depth-5 damping+depolarising layer on a
    14-density-qubit register (the densmatr_14q_damping_depol_f32 bench
    workload — 42 ops/layer on the doubled register) compiles to THREE
    fused passes per layer, zero XLA fallbacks, and models faster than
    the per-gate XLA engine."""
    rng = np.random.default_rng(7)
    n, depth = 14, 5
    dc = DensityCircuit(n)
    for _ in range(depth):
        for q in range(n):
            dc.unitary(q, _haar(rng))
        for q in range(0, n, 2):
            dc.damp(q, 0.02)
        for q in range(1, n, 2):
            dc.depolarise(q, 0.02)
    assert len(dc.ops) == depth * (2 * n + n)
    plan = ep.plan_circuit(dc.key(), 2 * n)
    assert plan.xla_ops == 0, plan.summary()
    assert plan.pallas_passes == 3 * depth, plan.summary()
    assert plan.super_stages == depth * n  # every channel fused
    model = planner.engine_time_model(dc)
    assert model["pallas_seconds"] < model["xla_seconds"] / 3


def test_superop_stage_handles_arbitrary_nonunitary_payloads():
    """The superop stage is a general 2-target dense lowering: random
    NON-unitary (and non-trace-preserving) 4x4 payloads on cross-group
    pairs run through the block and pack superop paths and match XLA."""
    from quest_tpu.circuit import Circuit
    rng = np.random.default_rng(5)
    n = 18
    c = Circuit(n)
    for pair in [(0, 14), (3, 17), (9, 15)]:   # lane-fiber, cols-pack, ...
        m = (rng.normal(size=(4, 4)) + 1j * rng.normal(size=(4, 4))) * 0.4
        mp = np.stack([m.real, m.imag])
        c.ops.append(GateOp("matrix", pair, (), (), tuple(mp.ravel()),
                            mp.shape))
    plan = ep.plan_circuit(c.key(), n)
    assert plan.xla_ops == 0, plan.summary()
    assert plan.super_stages == 3
    st = _rand_state(n, 9)
    want = np.asarray(compile_circuit(c, engine="xla")(st))
    got = np.asarray(compile_circuit(c, engine="pallas")(st))
    assert np.abs(got - want).max() < 5e-5


def test_unitary_cross_group_plans_unchanged_by_super_stage():
    """The superop route only fires where the odd-bit (CSD) decomposition
    cannot: a unitary cross-group window plans exactly as before (no
    super stages)."""
    from quest_tpu.circuit import Circuit
    rng = np.random.default_rng(6)
    c = Circuit(18)
    c.multi_qubit_unitary((2, 12), _haar(rng, 2))
    plan = ep.plan_circuit(c.key(), 18)
    assert plan.super_stages == 0
    assert plan.xla_ops == 0


def test_select_engine_density_window_reason():
    dc = DensityCircuit(16)       # 32 register qubits: one past the ceiling
    dc.unitary(0, np.eye(2))
    choice = planner.select_engine(dc, 1, backend="tpu")
    assert choice["engine"] == "xla"
    assert "density register outside 5 <= n <= 15" in choice["reason"]
    with pytest.raises(QuESTError):
        planner.select_engine(dc, 1, requested="pallas")


def test_engine_time_model_prices_super_passes():
    """Full-geometry (n >= 17 register) super-carrying block passes are
    priced at the slower ``pallas_epoch_super`` class — strictly more
    modeled seconds than the same pass count at the plain block class —
    and the breakdown reports the split."""
    dc = _noisy(9, seed=4)
    model = planner.engine_time_model(dc)
    bd = model["pallas_pass_breakdown"]
    assert bd["super_passes"] >= 1 and bd["super_stages"] >= 5
    state_bytes = (1 << model["num_qubits"]) * 8
    plain_all = (
        bd["block_passes"] * 2.0 * state_bytes
        / (planner.V5E.hbm_bytes_per_sec
           * planner.MEASURED_EFFICIENCY["pallas_epoch"])
        + bd["pack_passes"] * 2.0 * state_bytes
        / (planner.V5E.hbm_bytes_per_sec
           * planner.MEASURED_EFFICIENCY["pallas_epoch_pack"]))
    assert model["pallas_seconds"] > plain_all
    # ...while still modeling far below the per-gate XLA engine
    assert model["pallas_seconds"] < model["xla_seconds"] / 2


# ---------------------------------------------------------------------------
# the superoperator window domain (analysis/equivalence.py)
# ---------------------------------------------------------------------------

def test_check_density_plan_clean():
    from quest_tpu.analysis import check_density_plan
    dc = _noisy(6, seed=8)
    assert check_density_plan(dc) == []


def test_density_lowering_proof_is_engine_independent():
    """The Choi-doubling proof runs OUTSIDE the epoch envelope too: a
    4-density-qubit circuit (8 register qubits — below the [10, 30]
    window) still verifies, and a planted wrong-conjugate mutation in it
    is still refuted (review-found: the CLI used to skip the density half
    for out-of-window registers)."""
    from quest_tpu.analysis import check_density_lowering
    dc = _noisy(4, seed=12, kraus=False)
    assert not ep.epoch_supported(8)
    assert check_density_lowering(dc) == []
    mut = DensityCircuit(4)
    mut.ops = list(dc.ops)
    mut.channel_slots = set(dc.channel_slots)
    mut.channel_log = list(dc.channel_log)
    for i, op in enumerate(mut.ops):
        if (op.kind == "matrix" and i not in mut.channel_slots
                and op.targets[0] >= 4):
            p = op.payload()
            mut.ops[i] = GateOp(op.kind, op.targets, op.controls,
                                op.control_states,
                                tuple(np.stack([p[0], -p[1]]).ravel()),
                                op.shape)
            break
    assert any(d.code == "V_SEMANTICS_CHANGED"
               for d in check_density_lowering(mut))


def test_density_circuit_optimize_refused():
    """Record-time fusion would orphan the channel metadata and the
    mirrored pairing — DensityCircuit refuses it with a clean error."""
    dc = _noisy(5, seed=2)
    with pytest.raises(QuESTError) as e:
        dc.optimize()
    assert e.value.code == ErrorCode.INVALID_SCHEDULE_OPTION
    assert "DensityCircuit.optimize" in str(e.value)


def test_check_density_lowering_catches_wrong_conjugate():
    from quest_tpu.analysis import check_density_lowering
    dc = _noisy(6, seed=8)
    mut = DensityCircuit(6)
    mut.ops = list(dc.ops)
    mut.channel_slots = set(dc.channel_slots)
    mut.channel_log = list(dc.channel_log)
    for i, op in enumerate(mut.ops):
        if (op.kind == "matrix" and i not in mut.channel_slots
                and op.targets[0] >= 6):
            p = op.payload()          # un-conjugate the shadow: U ⊗ U
            mut.ops[i] = GateOp(op.kind, op.targets, op.controls,
                                op.control_states,
                                tuple(np.stack([p[0], -p[1]]).ravel()),
                                op.shape)
            break
    found = check_density_lowering(mut)
    assert any(d.code == "V_SEMANTICS_CHANGED" for d in found)


def test_check_density_lowering_catches_corrupted_channel():
    from quest_tpu.analysis import check_density_lowering
    dc = _noisy(6, seed=8)
    mut = DensityCircuit(6)
    mut.ops = list(dc.ops)
    mut.channel_slots = set(dc.channel_slots)
    mut.channel_log = list(dc.channel_log)
    ci = next(i for i in sorted(mut.channel_slots)
              if mut.ops[i].kind == "matrix")
    op = mut.ops[ci]
    p = op.payload()
    p[0, 0, 3] *= 2.0                 # wrong coupling: not the Kraus map
    mut.ops[ci] = GateOp(op.kind, op.targets, op.controls,
                         op.control_states, tuple(p.ravel()), op.shape)
    found = check_density_lowering(mut)
    assert any(d.code == "V_SEMANTICS_CHANGED" for d in found)


def test_examples_density_factory_proves_and_probes():
    import sys
    sys.path.insert(0, "examples")
    try:
        from circuits import density_noise_9q
    finally:
        sys.path.pop(0)
    from quest_tpu.analysis import check_density_plan, probe_epoch_execution
    dc = density_noise_9q()
    assert check_density_plan(dc) == []
    assert probe_epoch_execution(dc) == []
    plan = ep.plan_circuit(dc.key(), 18)
    assert plan.xla_ops == 0 and plan.super_stages >= 10


# ---------------------------------------------------------------------------
# Kraus admission (E_INVALID_KRAUS_OPS)
# ---------------------------------------------------------------------------

def test_apply_kraus_map_rejects_non_trace_preserving():
    st = jnp.zeros((2, 1 << 6), jnp.float64).at[0, 0].set(1.0)
    with pytest.raises(QuESTError) as e:
        deco.apply_kraus_map(st, [np.eye(2) * 1.2], (0,), 3)
    assert e.value.code == ErrorCode.INVALID_KRAUS_OPS
    # a valid map still applies
    out = deco.apply_kraus_map(st, _kraus_damp(0.25), (0,), 3)
    assert np.isfinite(np.asarray(out)).all()


def test_density_circuit_kraus_rejects_at_record_time():
    dc = DensityCircuit(3)
    with pytest.raises(QuESTError) as e:
        dc.kraus((0,), [np.diag([1.0, 0.5])])
    assert e.value.code == ErrorCode.INVALID_KRAUS_OPS


def test_validate_density_operands_accepts_f32_roundtripped_params():
    """An operand vector rounded through float32 — exactly the precision
    the compiled f32 plane executables consume — must pass admission: the
    trace-preservation tolerance is scaled to the loosest working
    precision, not f64 (review-found: a 1e-8 tolerance bounced valid
    f32-rounded probability sweeps)."""
    dc = _noisy(5, seed=1)
    pv = param_vector(dc.ops).astype(np.float32).astype(np.float64)
    validate_density_operands(dc, pv)     # must not raise


def test_validate_density_operands_catches_corrupted_slice():
    dc = _noisy(5, seed=1)
    validate_density_operands(dc)     # recorded payloads are clean
    pv = param_vector(dc.ops).copy()
    off = 0
    for i, op in enumerate(dc.ops):
        if i in dc.channel_slots and op.kind == "matrix":
            pv[off] = 3.0
            break
        off += op_param_count(op)
    with pytest.raises(QuESTError) as e:
        validate_density_operands(dc, pv)
    assert e.value.code == ErrorCode.INVALID_KRAUS_OPS


# ---------------------------------------------------------------------------
# serving: noisy structural classes
# ---------------------------------------------------------------------------

def test_serve_density_probability_sweep_one_class():
    """A probability sweep of one noisy skeleton serves as ONE structural
    class (probabilities ride the operand vector): hit rate >= 0.9, every
    probed batch carries a clean densmatr health record (trace ~ 1,
    Hermiticity within band), results bit-identical to serial, samples
    drawn from rho's diagonal, and a non-trace-preserving params override
    bounces at admission."""
    from quest_tpu.serve import QuESTService
    from quest_tpu.serve.cache import CompileCache
    rng = np.random.default_rng(21)
    n = 5
    gates = [_haar(rng) for _ in range(n)]

    def noisy(pd, pp, pz):
        dc = DensityCircuit(n)
        for q in range(n):
            dc.unitary(q, gates[q])
        for q in range(0, n, 2):
            dc.damp(q, pd)
        for q in range(1, n, 2):
            dc.depolarise(q, pp)
        dc.dephase(0, pz)
        return dc

    svc = QuESTService(max_batch=8, max_delay_ms=5.0, probes=True,
                       cache=CompileCache())
    sweep = [(0.01 * i, 0.004 * i, 0.02 * i) for i in range(1, 21)]
    circs = [noisy(*p) for p in sweep]
    futs = [svc.submit(c, shots=8) for c in circs]
    res = [f.result(timeout=300) for f in futs]
    svc.drain(timeout=300)
    snap = svc._cache.snapshot()
    assert snap["hit_rate"] >= 0.9, snap
    st = jnp.zeros((2, 1 << (2 * n)), jnp.float64).at[0, 0].set(1.0)
    dim = 1 << n
    for c, r in zip(circs, res):
        assert r.numeric_health is not None
        assert r.numeric_health["kind"] == "densmatr"
        assert not r.numeric_health["findings"], r.numeric_health
        assert abs(r.numeric_health["norm"] - 1.0) < 1e-6
        assert np.array_equal(np.asarray(_run_ops(st, c.key())), r.state)
        # samples come from rho's diagonal
        diag = np.asarray(r.state[0]).reshape(dim, dim).diagonal()
        assert all(diag[o] > 0 for o in r.samples)
    bad = param_vector(circs[0].ops).copy()
    off = 0
    for i, op in enumerate(circs[0].ops):
        if i in circs[0].channel_slots and op.kind == "matrix":
            bad[off] = 9.0
            break
        off += op_param_count(op)
    with pytest.raises(QuESTError) as e:
        svc.submit(circs[0], params=bad)
    assert e.value.code == ErrorCode.INVALID_KRAUS_OPS
    svc.shutdown()


def test_grafted_probe_density_matches_densmatr_probe():
    from quest_tpu.obs import numerics as num
    st = _rand_state(8, 13).astype(jnp.float64)
    got = np.asarray(num.grafted_probe(st, density_qubits=4))
    want = np.asarray(num.densmatr_probe_vector(st, 4))
    np.testing.assert_allclose(got, want, atol=0)


def test_epoch_pass_probes_density_per_pass_trace():
    """Per-pass density probes: every fused-pass boundary reports trace +
    Hermiticity (the plan has no deferred perms), the point count equals
    the plan's pass count, and the final state matches the uninstrumented
    program bit-for-bit."""
    from quest_tpu.obs import numerics as num
    dc = _noisy(6, seed=3, kraus=False)
    st = jnp.zeros((2, 1 << 12), jnp.float32).at[0, 0].set(1.0)
    out, points, summary = num.epoch_pass_probes(dc.key(), 12, st,
                                                 density_qubits=6)
    assert len(points) == summary["pallas_passes"]
    assert all("trace" in p and "herm_dev" in p for p in points)
    assert abs(points[-1]["trace"] - 1.0) < 1e-5
    assert points[-1]["herm_dev"] < 1e-5
    want = np.asarray(compile_circuit(dc, engine="pallas")(st))
    assert np.array_equal(np.asarray(out), want)


# ---------------------------------------------------------------------------
# analysis / profiling / scheduling surfaces
# ---------------------------------------------------------------------------

def test_analyzer_accepts_channels_and_catches_corruption():
    from quest_tpu.analysis import analyze_circuit
    dc = _noisy(6, seed=8)
    found = analyze_circuit(dc, hints=False)
    errors = [d for d in found if d.severity.name == "ERROR"]
    assert errors == [], [str(d) for d in errors]
    # a corrupted channel payload is E_INVALID_KRAUS_OPS, not NON_UNITARY
    mut = DensityCircuit(6)
    mut.ops = list(dc.ops)
    mut.channel_slots = set(dc.channel_slots)
    mut.channel_log = list(dc.channel_log)
    ci = next(i for i in sorted(mut.channel_slots)
              if mut.ops[i].kind == "matrix")
    op = mut.ops[ci]
    p = op.payload()
    p[0, 0, 0] = 0.2
    mut.ops[ci] = GateOp(op.kind, op.targets, op.controls,
                         op.control_states, tuple(p.ravel()), op.shape)
    found = analyze_circuit(mut, hints=False)
    assert any(d.code == ErrorCode.INVALID_KRAUS_OPS for d in found)


def test_circuit_stats_reports_density_super_passes():
    from quest_tpu.utils.profiling import circuit_stats
    dc = _noisy(7, seed=5)
    stats = circuit_stats(dc)
    assert stats.engine == "pallas"
    assert stats.density_qubits == 7
    assert stats.super_stages >= 5 and stats.super_passes >= 1
    assert stats.hbm_passes == ep.plan_circuit(dc.key(), 14).hbm_passes
    assert stats.bytes_per_pass == 2 * (1 << 14) * 4
    assert "density 7q doubled" in str(stats)


def test_schedule_carries_density_metadata():
    dc = _noisy(5, seed=6)
    sched = dc.schedule(1)
    assert getattr(sched, "density_qubits", None) == 5
    assert len(sched.channel_slots) == len(dc.channel_slots)
    kinds = sorted(rec[1] for rec in sched.channel_log)
    assert kinds == sorted(rec[1] for rec in dc.channel_log)


def test_apply_circuit_density_path():
    import quest_tpu as qt
    n = 4
    dc = _noisy(n, seed=9, kraus=False)
    env = qt.createQuESTEnv()
    rho = qt.createDensityQureg(n, env)
    qt.apply_circuit(rho, dc)
    tr = float(np.asarray(qt.calcTotalProb(rho)))
    assert abs(tr - 1.0) < 1e-10
    psi = qt.createQureg(n, env)
    with pytest.raises(QuESTError):
        qt.apply_circuit(psi, dc)     # statevector qureg: wrong register
