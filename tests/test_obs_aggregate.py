"""Cross-process trace aggregation (quest_tpu/obs/aggregate.py):

- the DEGENERATE contract: merging the single shard of a single-process
  run is the identity — byte-identical JSON to ``chrome_trace()``;
- clock-skew alignment: spans recording the same epoch instant on hosts
  with (synthetically) skewed clocks land on the same merged timestamp,
  property-tested over random skews/offsets;
- REAL two-process merge à la tests/test_multihost.py: two OS processes
  under one ``jax.distributed`` coordinator each record + save a shard,
  and the merged document carries a track per process, globally-unique
  namespaced span ids, zero orphans across processes, and request spans
  correlated by the shared ``request_id`` — validated by the extended
  ``validate_chrome_trace``;
- the extended validator itself: cross-process parent links, undeclared
  process tracks and missing process metadata are each a reported problem.

The workers do NOT run cross-process computations: the pinned jaxlib's
CPU backend cannot (docs/DESIGN.md "Known stack regressions"), which is
also why ``broadcast_host_epoch`` degrades to offset 0.0 there — the
degradation path is itself exercised by the worker calling the default
``align_clock=True``.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time

import pytest

from quest_tpu import obs
from quest_tpu.obs import aggregate

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def traced():
    obs.enable_tracing()
    obs.reset_tracing()
    yield obs.recorder()
    obs.disable_tracing()
    obs.reset_tracing()


# ---------------------------------------------------------------------------
# degenerate single-process merge
# ---------------------------------------------------------------------------

def test_single_process_merge_is_byte_identical(traced):
    with obs.request(3):
        with obs.span("outer", phase="x"):
            with obs.span("inner"):
                pass
    obs.emit_span("retro", t0=time.perf_counter(), dur=0.25, request_id=4)
    direct = obs.chrome_trace()
    merged = aggregate.merge_shards([aggregate.process_shard()])
    assert json.dumps(merged, sort_keys=False) \
        == json.dumps(direct, sort_keys=False)
    assert obs.validate_chrome_trace(merged) == []


def test_shard_save_load_roundtrip(traced, tmp_path):
    with obs.span("s"):
        pass
    path = str(tmp_path / "shard.json")
    written = aggregate.save_shard(path)
    loaded = aggregate.load_shard(path)
    assert loaded == json.loads(json.dumps(written))  # JSON-stable
    assert loaded["format"] == aggregate.SHARD_FORMAT
    assert loaded["process_index"] == 0 and loaded["process_count"] == 1
    assert loaded["clock_offset_s"] == 0.0  # single-process: no broadcast
    # merging from files == merging in memory
    assert aggregate.merge_files([path]) == aggregate.merge_shards([loaded])
    with pytest.raises(ValueError, match="not a quest-tpu-trace-shard"):
        bad = tmp_path / "bad.json"
        bad.write_text("{}")
        aggregate.load_shard(str(bad))


# ---------------------------------------------------------------------------
# clock-skew alignment
# ---------------------------------------------------------------------------

def _synthetic_shard(pindex, *, t0_perf, t0_epoch, offset, spans):
    """A hand-built shard: ``spans`` is [(name, t0_perf_s, dur, rid)]."""
    return {"format": aggregate.SHARD_FORMAT,
            "process_index": pindex, "process_count": 2,
            "host": f"host{pindex}", "t0_perf": t0_perf,
            "t0_epoch": t0_epoch, "clock_offset_s": offset,
            "dropped": 0,
            "spans": [{"name": name, "span_id": i + 1, "parent_id": None,
                       "request_id": rid, "t0": t0, "dur": dur,
                       "thread": "MainThread", "attrs": {}}
                      for i, (name, t0, dur, rid) in enumerate(spans)]}


def test_clock_skew_alignment_property():
    """Two hosts record the same wall-clock instant; whatever the skew
    between their clocks, the merged timestamps agree (to float noise)
    once each shard's broadcast-estimated offset is applied."""
    import random
    rng = random.Random(7)
    for _ in range(50):
        # ground truth: an event happens at true epoch instant T
        T = 1.7e9 + rng.uniform(0, 1e6)
        skew = rng.uniform(-300.0, 300.0)       # host1's clock error
        # process 0: clock exact; trace origin a bit before T
        t0_epoch_0 = T - rng.uniform(0.1, 5.0)
        sh0 = _synthetic_shard(
            0, t0_perf=rng.uniform(0, 1e4), t0_epoch=t0_epoch_0, offset=0.0,
            spans=[("evt", 0.0, 0.001, 9)])
        sh0["spans"][0]["t0"] = sh0["t0_perf"] + (T - t0_epoch_0)
        # process 1: its epoch clock reads true+skew; the broadcast
        # estimated exactly that skew as its offset
        t0_epoch_1_local = (T + skew) - rng.uniform(0.1, 5.0)
        sh1 = _synthetic_shard(
            1, t0_perf=rng.uniform(0, 1e4), t0_epoch=t0_epoch_1_local,
            offset=skew, spans=[("evt", 0.0, 0.001, 9)])
        sh1["spans"][0]["t0"] = sh1["t0_perf"] \
            + ((T + skew) - t0_epoch_1_local)
        doc = aggregate.merge_shards([sh0, sh1])
        evts = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        assert len(evts) == 2
        ts = sorted(e["ts"] for e in evts)
        # both tracks place the instant at the same merged microsecond
        # (float noise: the epoch numbers are ~1e9 s and ts is in us)
        assert abs(ts[1] - ts[0]) < 1.0, (skew, ts)
        assert obs.validate_chrome_trace(doc) == []


def test_merge_two_shards_tracks_and_namespacing():
    sh0 = _synthetic_shard(0, t0_perf=0.0, t0_epoch=100.0, offset=0.0,
                           spans=[("a", 0.5, 0.1, 1), ("b", 0.7, 0.1, None)])
    sh1 = _synthetic_shard(1, t0_perf=50.0, t0_epoch=100.2, offset=0.2,
                           spans=[("a", 50.5, 0.1, 1)])
    doc = aggregate.merge_shards([sh1, sh0])       # order must not matter
    assert obs.validate_chrome_trace(doc) == []
    assert doc["otherData"]["processes"] == [0, 1]
    assert doc["otherData"]["clock_offsets_s"] == {"0": 0.0, "1": 0.2}
    evts = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert {e["pid"] for e in evts} == {1, 2}
    # namespaced ids stay globally unique; process 0 keeps raw ids
    ids = [e["args"]["span_id"] for e in evts]
    assert len(set(ids)) == 3
    p0_ids = [e["args"]["span_id"] for e in evts if e["pid"] == 1]
    assert p0_ids == [1, 2]
    # request correlation across tracks: the shared request_id survives
    rid1 = [e for e in evts if e["args"]["request_id"] == 1]
    assert {e["pid"] for e in rid1} == {1, 2}
    # both "a" spans recorded the same aligned instant (100.5 on process
    # 0's clock): same merged ts across tracks
    a_ts = [e["ts"] for e in evts if e["name"] == "a"]
    assert abs(a_ts[0] - a_ts[1]) < 1e-6
    # process metadata names both tracks
    metas = [e for e in doc["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "process_name"]
    assert {m["pid"] for m in metas} == {1, 2}
    with pytest.raises(ValueError, match="two shards claim"):
        aggregate.merge_shards([sh0, sh0])


# ---------------------------------------------------------------------------
# extended validator
# ---------------------------------------------------------------------------

def test_validator_rejects_cross_process_parent():
    sh0 = _synthetic_shard(0, t0_perf=0.0, t0_epoch=100.0, offset=0.0,
                           spans=[("root", 0.5, 0.1, None)])
    sh1 = _synthetic_shard(1, t0_perf=0.0, t0_epoch=100.0, offset=0.0,
                           spans=[("child", 0.6, 0.1, None)])
    doc = aggregate.merge_shards([sh0, sh1])
    # hand-corrupt: the process-1 span claims the process-0 root as parent
    child = [e for e in doc["traceEvents"]
             if e.get("ph") == "X" and e["pid"] == 2][0]
    child["args"]["parent_id"] = 1
    problems = obs.validate_chrome_trace(doc)
    assert any("across process tracks" in p for p in problems)


def test_validator_enforces_declared_process_contract():
    sh0 = _synthetic_shard(0, t0_perf=0.0, t0_epoch=100.0, offset=0.0,
                           spans=[("a", 0.5, 0.1, None)])
    sh1 = _synthetic_shard(1, t0_perf=0.0, t0_epoch=100.0, offset=0.0,
                           spans=[("b", 0.6, 0.1, None)])
    doc = aggregate.merge_shards([sh0, sh1])
    # an event on a track nobody declared
    stray = dict(doc["traceEvents"][-1])
    stray = {**stray, "pid": 9,
             "args": {**stray["args"], "span_id": 777}}
    doc2 = {**doc, "traceEvents": doc["traceEvents"] + [stray]}
    assert any("undeclared process track" in p
               for p in obs.validate_chrome_trace(doc2))
    # a declared process with its name meta stripped
    doc3 = {**doc, "traceEvents": [
        e for e in doc["traceEvents"]
        if not (e.get("ph") == "M" and e.get("name") == "process_name"
                and e.get("pid") == 2)]}
    assert any("no process_name meta" in p
               for p in obs.validate_chrome_trace(doc3))
    # a declared process with no clock offset recorded
    doc4 = json.loads(json.dumps(doc))
    del doc4["otherData"]["clock_offsets_s"]["1"]
    assert any("no clock offset" in p
               for p in obs.validate_chrome_trace(doc4))


# ---------------------------------------------------------------------------
# REAL two-process capture (a la tests/test_multihost.py)
# ---------------------------------------------------------------------------

AGG_WORKER = r"""
import os, sys, time
pid = int(sys.argv[1]); port = sys.argv[2]; out = sys.argv[3]
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
os.environ.pop("JAX_PLATFORMS", None)
sys.path.insert(0, @REPO@)

import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(coordinator_address="127.0.0.1:" + port,
                           num_processes=2, process_id=pid)
assert jax.process_count() == 2

import jax.numpy as jnp
from quest_tpu import obs
from quest_tpu.obs import aggregate

obs.enable_tracing()
obs.reset_tracing()
# request 7 is served across BOTH processes (the multi-replica routing
# shape): each process records its own execution spans under the same
# request_id, plus one private local-work span.  Device work stays
# process-local: the pinned jaxlib cannot run cross-process CPU
# computations (docs/DESIGN.md "Known stack regressions").
with obs.request(7):
    with obs.span("serve.request_part", process=pid):
        x = jnp.arange(8.0) * (pid + 1)
        float(x.sum())
with obs.span("local.work", process=pid):
    time.sleep(0.01)
# align_clock=True exercises broadcast_host_epoch: on this stack the CPU
# broadcast degrades to offset 0.0 instead of raising
shard = aggregate.save_shard(out)
assert shard["process_index"] == pid and shard["process_count"] == 2
print("AGGWORKER%d OK spans=%d offset=%r"
      % (pid, len(shard["spans"]), shard["clock_offset_s"]))
"""


@pytest.mark.skipif(sys.platform != "linux", reason="needs local TCP coordinator")
def test_two_process_capture_merges_into_one_trace(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    src = tmp_path / "agg_worker.py"
    src.write_text(AGG_WORKER.replace("@REPO@", repr(REPO)))
    shards = [str(tmp_path / f"shard{p}.json") for p in (0, 1)]
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen([sys.executable, str(src), str(p), str(port),
                          shards[p]],
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                         text=True, cwd=REPO, env=env)
        for p in (0, 1)
    ]
    for p_i, proc in enumerate(procs):
        try:
            out, err = proc.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("aggregation workers timed out (coordinator hang?)")
        assert proc.returncode == 0, \
            f"worker {p_i} failed\nstdout:\n{out}\nstderr:\n{err[-2000:]}"
        assert f"AGGWORKER{p_i} OK" in out

    doc = aggregate.merge_files(shards)
    assert obs.validate_chrome_trace(doc) == []
    assert doc["otherData"]["processes"] == [0, 1]
    evts = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert {e["pid"] for e in evts} == {1, 2}          # a track per process
    assert len({e["args"]["span_id"] for e in evts}) == len(evts) == 4
    # the cross-process request: both tracks carry request 7's spans
    parts = [e for e in evts if e["name"] == "serve.request_part"]
    assert {e["args"]["request_id"] for e in parts} == {7}
    assert {e["pid"] for e in parts} == {1, 2}
    # same host, both offsets 0.0: the two capture windows overlap, so the
    # aligned timelines must too (a gross misalignment would separate them
    # by the ~seconds of process startup skew)
    assert abs(parts[0]["ts"] - parts[1]["ts"]) < 60e6
