"""The deployment subsystem (quest_tpu/deploy): the persistent executable
store (provenance-gated loads, staleness refusal, warm-up economics), the
SLO-aware class-affinity router (rendezvous placement, shed policy,
eviction re-placement), and the replica pool's labeled one-scrape contract.

Adversarial coverage mirrors the calibrate/equivalence suites: a corrupted
provenance header must be REFUSED before its payload is deserialized
(counted ``persist_stale``), and a replica that evicted a class under byte
pressure must lose that class's traffic on the next miss report — stale
affinity must never re-warm the evicting replica by habit."""

from __future__ import annotations

import hashlib
import json
import struct
import time

import numpy as np

from conftest import ON_ACCELERATOR  # noqa: F401

import jax.numpy as jnp

import quest_tpu as qt  # noqa: F401 (x64 + precision config)
from quest_tpu.circuit import qft_circuit, random_circuit
from quest_tpu.deploy import (ExecutableStore, Replica, ReplicaPool,
                              RouterConfig, broadcast_hot_keys, entry_key,
                              live_provenance, validate_entry_header)
from quest_tpu.deploy.selftest import coldstart_compare, shed_gate
from quest_tpu.obs import global_counters
from quest_tpu.serve import CompileCache
from quest_tpu.serve.metrics import parse_prometheus
from quest_tpu.serve.selftest import vqe_ansatz

DTYPE = jnp.float32 if ON_ACCELERATOR else jnp.float64


def zero_state(n):
    return jnp.zeros((2, 1 << n), DTYPE).at[0, 0].set(1.0)


def _corrupt_header(store, key, mutate):
    """Rewrite one store file's header through ``mutate(header_dict)``,
    leaving the payload bytes untouched."""
    path = store._path(key)
    with open(path, "rb") as fh:
        blob = fh.read()
    (hlen,) = struct.unpack(">I", blob[8:12])
    header = json.loads(blob[12:12 + hlen].decode())
    mutate(header)
    hjson = json.dumps(header, sort_keys=True).encode()
    with open(path, "wb") as fh:
        fh.write(blob[:8] + struct.pack(">I", len(hjson)) + hjson
                 + blob[12 + hlen:])


# ---------------------------------------------------------------------------
# persistent store: round trip + warm-up economics
# ---------------------------------------------------------------------------

def test_store_roundtrip_zero_compiles_bit_identical(tmp_path):
    store = ExecutableStore(str(tmp_path))
    producer = CompileCache().attach_store(store)
    circ = vqe_ansatz(5, 1, seed=3)
    want = np.asarray(producer.execute(circ.key(), zero_state(5),
                                       num_qubits=5))
    assert producer.stats["persist_saves"] >= 1
    assert store.snapshot()["entries"] >= 1

    cold = CompileCache().attach_store(ExecutableStore(str(tmp_path),
                                                       readonly=True))
    before = global_counters().snapshot()["compiles_total"]
    got = np.asarray(cold.execute(circ.key(), zero_state(5), num_qubits=5))
    after = global_counters().snapshot()["compiles_total"]
    assert np.array_equal(got, want)      # the loaded EXECUTABLE answers
    assert cold.stats["compiles"] == 0
    assert cold.stats["persist_hits"] == 1
    assert after == before                # nothing compiled process-wide


def test_store_warm_preloads_entry_and_programs(tmp_path):
    store = ExecutableStore(str(tmp_path))
    producer = CompileCache().attach_store(store)
    circ = qft_circuit(6)
    producer.execute(circ.key(), zero_state(6), num_qubits=6)

    cold = CompileCache()
    summary = store.warm(cold)
    assert summary["loaded"] >= 1 and summary["refused"] == 0
    # the warmed class is a HIT on first contact — warm-up is provisioning
    cold.execute(circ.key(), zero_state(6), num_qubits=6)
    assert cold.stats["hits"] == 1 and cold.stats["misses"] == 0
    assert cold.stats["compiles"] == 0


def test_coldstart_warm_strictly_beats_cold(tmp_path):
    reps = [("vqe5", vqe_ansatz(5, 1, seed=0)), ("qft6", qft_circuit(6))]
    rep = coldstart_compare(str(tmp_path), reps,
                            dtype=DTYPE)
    assert rep["warm"]["compiles"] == 0
    assert rep["warm"]["global_compiles_delta"] == 0
    assert rep["warm"]["persist_hits"] > 0
    assert rep["cold"]["compiles"] >= len(reps)
    assert (rep["warm"]["coldstart_seconds"]
            < rep["cold"]["coldstart_seconds"])


# ---------------------------------------------------------------------------
# staleness bugfix-by-construction (adversarial)
# ---------------------------------------------------------------------------

def test_stale_provenance_refused_recompiles_and_counts(tmp_path):
    store = ExecutableStore(str(tmp_path))
    producer = CompileCache().attach_store(store)
    circ = vqe_ansatz(5, 1, seed=7)
    want = np.asarray(producer.execute(circ.key(), zero_state(5),
                                       num_qubits=5))
    keys = store.keys()
    assert keys
    # an executable "from" a different jaxlib: undefined at run time, so
    # the load path must refuse it BEFORE deserializing anything
    for key in keys:
        _corrupt_header(store, key,
                        lambda h: h["provenance"].update(jaxlib="0.0.1"))
    hdr = store.read_header(keys[0])
    problems = validate_entry_header(hdr, live_provenance())
    assert any("jaxlib" in p for p in problems), problems

    consumer = CompileCache().attach_store(ExecutableStore(str(tmp_path)))
    got = np.asarray(consumer.execute(circ.key(), zero_state(5),
                                      num_qubits=5))
    assert np.array_equal(got, want)           # refused => recompiled, same answer
    assert consumer.stats["persist_hits"] == 0
    assert consumer.stats["persist_stale"] >= 1   # the counted miss
    assert consumer.stats["compiles"] >= 1


def test_calibration_provenance_mismatch_refuses(tmp_path):
    store = ExecutableStore(str(tmp_path))
    producer = CompileCache().attach_store(store)
    circ = qft_circuit(5)
    producer.execute(circ.key(), zero_state(5), num_qubits=5)
    for key in store.keys():
        _corrupt_header(store, key, lambda h: h["provenance"].update(
            calibration="deadbeef0000"))
    cold = CompileCache()
    summary = ExecutableStore(str(tmp_path)).warm(cold)
    assert summary["loaded"] == 0
    assert summary["refused"] == summary["requested"] > 0
    assert cold.stats["persist_hits"] == 0


def test_tampered_payload_digest_refused(tmp_path):
    store = ExecutableStore(str(tmp_path))
    producer = CompileCache().attach_store(store)
    producer.execute(qft_circuit(5).key(), zero_state(5), num_qubits=5)
    key = store.keys()[0]
    skey_tag = _stored_identity(store, key)   # recovered while still valid
    path = store._path(key)
    blob = bytearray(open(path, "rb").read())
    blob[-1] ^= 0xFF                      # one flipped payload byte
    open(path, "wb").write(bytes(blob))
    fresh = ExecutableStore(str(tmp_path))
    status, call, _ = fresh.fetch(*skey_tag)
    # fetch by the real identity: file present, digest wrong => stale —
    # refused by the sha256 check BEFORE any deserialization touches it
    assert status == "stale" and call is None
    assert fresh.stats["stale"] == 1


def _stored_identity(store, key):
    """The (skey, tag) of one UNTAMPERED store file, read back from its
    own payload."""
    import pickle
    with open(store._path(key), "rb") as fh:
        fh.read(8)
        (hlen,) = struct.unpack(">I", fh.read(4))
        fh.read(hlen)
        payload = fh.read()
    skey, tag = pickle.loads(payload)[:2]
    assert entry_key(skey, tag) == key
    return skey, tag


def test_store_header_schema_validator():
    assert validate_entry_header({}) != []
    assert "format" in " ".join(validate_entry_header({"format": "nope"}))
    ok_header = {"format": "quest-tpu-executable-v1", "key": "k",
                 "payload_sha256": "x", "payload_bytes": 1,
                 "provenance": live_provenance(), "created_epoch_s": 0.0}
    assert validate_entry_header(ok_header) == []
    assert validate_entry_header(ok_header, live_provenance()) == []


# ---------------------------------------------------------------------------
# router: affinity, shed, eviction re-placement
# ---------------------------------------------------------------------------

def _mini_pool(**kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_delay_ms", 2.0)
    kw.setdefault("dtype", DTYPE)
    return ReplicaPool(2, **kw)


def test_affinity_is_sticky_and_deterministic():
    pool = _mini_pool(start=False)
    try:
        circ = vqe_ansatz(4, 1, seed=0)
        ck = pool.router.class_key(circ)
        order1 = pool.router.candidates(ck)
        order2 = pool.router.candidates(ck)
        assert order1 == order2 and set(order1) == {0, 1}
        r1, d1 = pool.router.route(circ)
        r2, d2 = pool.router.route(circ)
        assert r1.index == r2.index == order1[0]
        assert not d1["sticky"] and d2["sticky"]
    finally:
        pool.shutdown(drain=False)


def test_router_sheds_saturated_replica_for_deadline_traffic():
    pool = _mini_pool(start=False, max_queue=8)
    try:
        probe = qft_circuit(5)
        ck = pool.router.class_key(probe)
        affinity = pool.router.candidates(ck)[0]
        sat = next(r for r in pool.replicas if r.index == affinity)
        filler = random_circuit(4, depth=1, seed=0)
        for _ in range(7):
            sat.service.submit(filler)
        assert sat.service.queue_saturation() >= 0.8
        replica, decision = pool.router.route(probe, deadline_ms=1000.0)
        assert replica.index != affinity
        assert decision["shed_from"][0]["replica"] == affinity
        assert decision["shed_from"][0]["reason"] == "saturation"
        # deadline-FREE traffic to a merely-burning replica sticks; but a
        # saturated queue sheds everything — saturation risks bounces
        replica2, _ = pool.router.route(probe)
        assert replica2.index != affinity
        # a shed must NOT rewrite the sticky placement: the class returns
        # to its affinity replica the moment the queue drains
        assert ck not in pool.router.snapshot()["placements"]
        sat.service.start()
        assert sat.service.drain(timeout=120)
        recovered, d3 = pool.router.route(probe, deadline_ms=1000.0)
        assert recovered.index == affinity and not d3["shed_from"]
    finally:
        pool.shutdown(drain=False)


def test_broadcast_hot_keys_oversized_single_key_degrades():
    # a single key too big for the buffer must degrade to no hints, not
    # spin forever in the truncation loop
    assert broadcast_hot_keys(["k" * 100], max_bytes=64) == []


def test_health_p99_overflow_stays_finite_json():
    from quest_tpu.obs.slo import SLOMonitor
    m = SLOMonitor()
    m.observe("ck", 45.0, deadline_ok=True)      # beyond the top bucket
    h = m.health()
    assert h["p99_s"] == 30.0                    # clamped top edge, not inf
    json.dumps(h)                                # strict-JSON-serializable


def test_shed_gate_beats_saturated_baseline():
    shed = shed_gate(qft_circuit(6), probes=4, fillers=7, max_queue=8)
    assert shed["routed_away"]
    assert shed["shed_decisions"] > 0
    assert shed["deployment_hit_rate"] > shed["baseline_hit_rate"]
    assert shed["deployment_hit_rate"] == 1.0


def test_eviction_miss_report_re_places_class(tmp_path):
    # replica caches sized so ONE extra class evicts the previous one
    pool = _mini_pool(start=True, cache_max_bytes=1)
    try:
        a = vqe_ansatz(4, 1, seed=1)
        ck = pool.router.class_key(a)
        home = pool.router.candidates(ck)[0]
        # two requests: miss (compile) then confirmed hit on the home replica
        pool.submit(a).result(timeout=120)
        r2 = pool.submit(a).result(timeout=120)
        assert r2.cache_outcome == "hit"
        assert pool.router.snapshot()["placements"][ck] == home
        # class B lands DIRECTLY on the home replica and evicts A (byte
        # budget of 1: newest entry only)
        b = qft_circuit(4)
        home_replica = next(r for r in pool.replicas if r.index == home)
        home_replica.service.submit(b).result(timeout=120)
        assert home_replica.cache.stats["evictions"] >= 1
        # next A request still routes home (stale affinity...), MISSES, and
        # the miss report must drop the placement + cool the pair
        r3 = pool.submit(a).result(timeout=120)
        assert r3.cache_outcome == "miss"
        deadline = time.monotonic() + 5.0
        while (ck in pool.router.snapshot()["placements"]
               and time.monotonic() < deadline):
            time.sleep(0.01)        # the done-callback runs on the worker
        assert ck not in pool.router.snapshot()["placements"]
        assert pool.metrics.counter("replaced_total",
                                    labels={"replica": str(home)}) == 1
        # ...so the NEXT request re-places off the evicting replica
        replica, decision = pool.router.route(a)
        assert replica.index != home
        assert str(home) in " ".join(
            str(i) for i in decision["cooldown_skipped"])
    finally:
        pool.shutdown(drain=False)


def test_queue_full_bounce_retries_next_candidate():
    pool = _mini_pool(start=False, max_queue=2,
                      router_config=RouterConfig(shed_saturation=2.0))
    try:
        # shed disabled (threshold 2.0): the router will aim at the
        # affinity replica even when full, so the bounce path must save it
        circ = vqe_ansatz(4, 1, seed=2)
        ck = pool.router.class_key(circ)
        affinity = pool.router.candidates(ck)[0]
        sat = next(r for r in pool.replicas if r.index == affinity)
        for _ in range(2):
            sat.service.submit(random_circuit(4, depth=1, seed=0))
        fut = pool.submit(circ)     # affinity bounces -> retried elsewhere
        assert pool.metrics.counter_total("bounce_retries_total") == 1
        # routed_total attributes the replica that ACCEPTED, not the bounce
        other = pool.router.candidates(ck)[1]
        assert pool.metrics.counter("routed_total",
                                    labels={"replica": str(other)}) == 1
        assert pool.metrics.counter("routed_total",
                                    labels={"replica": str(affinity)}) == 0
        pool.start()
        assert pool.drain(timeout=120)
        assert fut.exception() is None
    finally:
        pool.shutdown(drain=False)


# ---------------------------------------------------------------------------
# pool: labeled scrape, broadcast, seeds
# ---------------------------------------------------------------------------

def test_pool_labeled_scrape_parses_with_replica_series(tmp_path):
    pool = _mini_pool(store_dir=str(tmp_path))
    try:
        futs = [pool.submit(vqe_ansatz(4, 1, seed=s)) for s in range(6)]
        assert pool.drain(timeout=240)
        for f in futs:
            f.result(timeout=60)
        parsed = parse_prometheus(pool.prometheus())
        hit = parsed["quest_serve_cache_hit_rate"]
        assert set(hit) == {'replica="0"', 'replica="1"'}
        routed = parsed["quest_serve_routed_total"]
        assert sum(routed.values()) == 6
        assert all("replica=" in ls for ls in routed)
        assert "quest_serve_slo_burn_rate" in parsed
        assert "quest_serve_store_saves" in parsed
    finally:
        pool.shutdown(drain=False)


def test_replica_seeds_differ():
    pool = _mini_pool(start=False, seed=5)
    try:
        assert [r.service.seed for r in pool.replicas] == [5, 6]
    finally:
        pool.shutdown(drain=False)


def test_broadcast_hot_keys_single_process_identity():
    keys = [hashlib.sha256(str(i).encode()).hexdigest()[:24]
            for i in range(5)]
    assert broadcast_hot_keys(keys) == sorted(keys)
    # oversized lists truncate deterministically instead of raising
    big = [hashlib.sha256(str(i).encode()).hexdigest()[:24]
           for i in range(4000)]
    out = broadcast_hot_keys(big, max_bytes=1 << 12)
    assert out == sorted(big)[:len(out)] and 0 < len(out) < len(big)


def test_process_replica_single_process_identity(tmp_path):
    """process_replica names THIS process's replica by jax.process_index()
    (0 outside a coordinator) and labels its registry accordingly."""
    from quest_tpu.deploy import process_replica
    rep = process_replica(store_dir=str(tmp_path), dtype=DTYPE,
                          max_batch=4, start=True)
    try:
        assert rep.index == 0
        assert rep.store is not None
        rep.service.submit(qft_circuit(4)).result(timeout=120)
        assert rep.store.snapshot()["entries"] >= 1
        parsed = parse_prometheus(rep.service.prometheus())
        routed = parsed["quest_serve_requests_completed_total"]
        assert routed == {'replica="0"': 1.0}
    finally:
        rep.shutdown(drain=False)


def test_replica_hot_keys_match_store_keys(tmp_path):
    store = ExecutableStore(str(tmp_path))
    rep = Replica(0, store=store, dtype=DTYPE, start=True)
    try:
        rep.service.submit(qft_circuit(5)).result(timeout=120)
        hot = rep.hot_keys()
        assert hot and set(hot) <= set(store.keys())
    finally:
        rep.shutdown(drain=False)
