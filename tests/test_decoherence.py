"""Decoherence channels on density matrices, mirroring the reference's
test_decoherence.cpp (10 TEST_CASEs).  Each channel is checked against the
Kraus-sum oracle on a random density matrix."""

from __future__ import annotations

import numpy as np
import pytest

import quest_tpu as qt
from oracle import (DM_TOL, NUM_QUBITS, I2, X, Y, Z, apply_channel, assert_dm,
                    dm, random_density_matrix, random_kraus_map, set_dm)

N = NUM_QUBITS


@pytest.fixture
def rho_q(env):
    rho = random_density_matrix(N)
    dq = qt.createDensityQureg(N, env)
    set_dm(dq, rho)
    return dq, rho


def test_mixDephasing(env, rho_q):
    dq, rho = rho_q
    p = 0.2
    for t in range(N):
        set_dm(dq, rho)
        qt.mixDephasing(dq, t, p)
        kraus = [np.sqrt(1 - p) * I2, np.sqrt(p) * Z]
        assert_dm(dq, apply_channel(rho, N, [t], kraus))
    psi = qt.createQureg(N, env)
    with pytest.raises(qt.QuESTError, match="density matrices"):
        qt.mixDephasing(psi, 0, p)
    with pytest.raises(qt.QuESTError, match="dephase error"):
        qt.mixDephasing(dq, 0, 0.6)


def test_mixTwoQubitDephasing(env, rho_q):
    dq, rho = rho_q
    p = 0.3
    for q1, q2 in [(0, 1), (1, 4), (3, 2)]:
        set_dm(dq, rho)
        qt.mixTwoQubitDephasing(dq, q1, q2, p)
        # (1-p) rho + p/3 (Z1 + Z2 + Z1Z2 conjugations)
        i4 = np.eye(4, dtype=complex)
        z1 = np.kron(I2, Z)  # acts on q1 (q1 = least significant target bit)
        z2 = np.kron(Z, I2)
        kraus = [np.sqrt(1 - p) * i4, np.sqrt(p / 3) * z1, np.sqrt(p / 3) * z2,
                 np.sqrt(p / 3) * (z1 @ z2)]
        assert_dm(dq, apply_channel(rho, N, [q1, q2], kraus))
    with pytest.raises(qt.QuESTError, match="dephase error"):
        qt.mixTwoQubitDephasing(dq, 0, 1, 0.8)


def test_mixDepolarising(env, rho_q):
    dq, rho = rho_q
    p = 0.4
    for t in range(N):
        set_dm(dq, rho)
        qt.mixDepolarising(dq, t, p)
        kraus = [np.sqrt(1 - p) * I2, np.sqrt(p / 3) * X, np.sqrt(p / 3) * Y,
                 np.sqrt(p / 3) * Z]
        assert_dm(dq, apply_channel(rho, N, [t], kraus))
    with pytest.raises(qt.QuESTError, match="depolarising error"):
        qt.mixDepolarising(dq, 0, 0.8)


def test_mixTwoQubitDepolarising(env, rho_q):
    dq, rho = rho_q
    p = 0.5
    for q1, q2 in [(0, 1), (2, 4)]:
        set_dm(dq, rho)
        qt.mixTwoQubitDepolarising(dq, q1, q2, p)
        # (1-p) rho + p/15 sum over the 15 non-identity two-qubit Paulis
        paulis = [I2, X, Y, Z]
        expected = (1 - p) * rho
        for i in range(4):
            for j in range(4):
                if i == 0 and j == 0:
                    continue
                sigma = np.kron(paulis[j], paulis[i])  # i on q1, j on q2
                expected += (p / 15) * apply_channel(rho, N, [q1, q2], [sigma])
        assert_dm(dq, expected)
    with pytest.raises(qt.QuESTError, match="two-qubit depolarising"):
        qt.mixTwoQubitDepolarising(dq, 0, 1, 0.95)


def test_mixDamping(env, rho_q):
    dq, rho = rho_q
    p = 0.35
    for t in range(N):
        set_dm(dq, rho)
        qt.mixDamping(dq, t, p)
        k0 = np.array([[1, 0], [0, np.sqrt(1 - p)]], dtype=complex)
        k1 = np.array([[0, np.sqrt(p)], [0, 0]], dtype=complex)
        assert_dm(dq, apply_channel(rho, N, [t], [k0, k1]))
    with pytest.raises(qt.QuESTError, match="[Pp]robabilities"):
        qt.mixDamping(dq, 0, 1.2)


def test_mixPauli(env, rho_q):
    dq, rho = rho_q
    px, py, pz = 0.1, 0.15, 0.05
    for t in range(N):
        set_dm(dq, rho)
        qt.mixPauli(dq, t, px, py, pz)
        kraus = [np.sqrt(1 - px - py - pz) * I2, np.sqrt(px) * X,
                 np.sqrt(py) * Y, np.sqrt(pz) * Z]
        assert_dm(dq, apply_channel(rho, N, [t], kraus))
    # probability of any single error cannot exceed the no-error probability
    with pytest.raises(qt.QuESTError, match="cannot exceed the probability"):
        qt.mixPauli(dq, 0, 0.6, 0.3, 0.05)


def test_mixKrausMap(env, rho_q):
    dq, rho = rho_q
    np.random.seed(3)
    ops = random_kraus_map(1, 3)
    for t in (0, 2, N - 1):
        set_dm(dq, rho)
        qt.mixKrausMap(dq, t, ops, len(ops))
        assert_dm(dq, apply_channel(rho, N, [t], ops))
    with pytest.raises(qt.QuESTError, match="trace preserving"):
        qt.mixKrausMap(dq, 0, [2 * np.eye(2)], 1)
    with pytest.raises(qt.QuESTError, match="single qubit Kraus"):
        qt.mixKrausMap(dq, 0, [np.eye(2)] * 5, 5)


def test_mixTwoQubitKrausMap(env, rho_q):
    dq, rho = rho_q
    np.random.seed(5)
    ops = random_kraus_map(2, 4)
    for q1, q2 in [(0, 1), (3, 1)]:
        set_dm(dq, rho)
        qt.mixTwoQubitKrausMap(dq, q1, q2, ops, len(ops))
        assert_dm(dq, apply_channel(rho, N, [q1, q2], ops))


def test_mixMultiQubitKrausMap(env, rho_q):
    dq, rho = rho_q
    np.random.seed(9)
    for targets in [(0,), (1, 3), (0, 2, 4)]:
        ops = random_kraus_map(len(targets), 2)
        set_dm(dq, rho)
        qt.mixMultiQubitKrausMap(dq, list(targets), len(targets), ops, len(ops))
        assert_dm(dq, apply_channel(rho, N, list(targets), ops))


def test_mixDensityMatrix(env, rho_q):
    dq, rho = rho_q
    other_rho = random_density_matrix(N)
    other = qt.createDensityQureg(N, env)
    set_dm(other, other_rho)
    p = 0.42
    qt.mixDensityMatrix(dq, p, other)
    assert_dm(dq, (1 - p) * rho + p * other_rho)
    psi = qt.createQureg(N, env)
    with pytest.raises(qt.QuESTError, match="density matrices"):
        qt.mixDensityMatrix(psi, p, other)
