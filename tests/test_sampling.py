"""Joint outcome distributions and multi-shot sampling (TPU-native
extensions: calcProbOfAllOutcomes / sampleOutcomes — the reference's v3.2
surface queries one qubit at a time)."""

from __future__ import annotations

import numpy as np
import pytest

import quest_tpu as qt
from oracle import NUM_QUBITS, SV_TOL, random_density_matrix, random_statevector, set_dm, set_sv

N = NUM_QUBITS


def _oracle_probs(weights: np.ndarray, qubits) -> np.ndarray:
    """Independent reduction: loop over every state index."""
    out = np.zeros(1 << len(qubits))
    for k, w in enumerate(weights):
        idx = 0
        for i, q in enumerate(qubits):
            idx |= ((k >> q) & 1) << i
        out[idx] += w
    return out


@pytest.mark.parametrize("qubits", [[0], [2], [0, 1], [3, 1], [4, 0, 2],
                                    list(range(N))])
def test_prob_all_outcomes_statevector(env, qubits):
    psi = qt.createQureg(N, env)
    vec = random_statevector(N)
    set_sv(psi, vec)
    got = qt.calcProbOfAllOutcomes(psi, qubits)
    np.testing.assert_allclose(got, _oracle_probs(np.abs(vec) ** 2, qubits),
                               atol=10 * SV_TOL)
    assert np.sum(got) == pytest.approx(1.0, abs=10 * SV_TOL)


@pytest.mark.parametrize("qubits", [[1], [2, 0], [0, 1, 3]])
def test_prob_all_outcomes_density(env, qubits):
    rho_q = qt.createDensityQureg(N, env)
    rho = random_density_matrix(N)
    set_dm(rho_q, rho)
    got = qt.calcProbOfAllOutcomes(rho_q, qubits)
    np.testing.assert_allclose(got, _oracle_probs(np.real(np.diag(rho)), qubits),
                               atol=10 * SV_TOL)


def test_prob_all_outcomes_ordering(env_local):
    """Outcome index bit i must be qubits[i]: |01> (qubit 0 = 1) seen through
    qubits=[1,0] is outcome 0b10."""
    psi = qt.createQureg(2, env_local)
    qt.initClassicalState(psi, 1)
    p = qt.calcProbOfAllOutcomes(psi, [1, 0])
    np.testing.assert_allclose(p, [0.0, 0.0, 1.0, 0.0], atol=SV_TOL)


def test_prob_all_outcomes_validation(env_local):
    psi = qt.createQureg(3, env_local)
    with pytest.raises(qt.QuESTError):
        qt.calcProbOfAllOutcomes(psi, [0, 3])
    with pytest.raises(qt.QuESTError):
        qt.calcProbOfAllOutcomes(psi, [1, 1])


def test_sample_outcomes_deterministic_and_reproducible(env_local):
    psi = qt.createQureg(3, env_local)
    qt.initClassicalState(psi, 5)
    s = qt.sampleOutcomes(psi, 64)
    assert np.all(s == 5)  # deterministic state: every shot is |101>
    qt.initPlusState(psi)
    qt.seedQuEST([123])
    a = qt.sampleOutcomes(psi, 50)
    qt.seedQuEST([123])
    b = qt.sampleOutcomes(psi, 50)
    np.testing.assert_array_equal(a, b)
    # sampling must not collapse the state
    assert qt.calcProbOfOutcome(psi, 0, 0) == pytest.approx(0.5, abs=SV_TOL)


def test_sample_outcomes_frequencies(env):
    """Empirical frequencies converge to the analytic distribution."""
    psi = qt.createQureg(N, env)
    vec = random_statevector(N)
    set_sv(psi, vec)
    qubits = [0, 2, 4]
    qt.seedQuEST([7])
    shots = 20000
    s = qt.sampleOutcomes(psi, shots, qubits)
    freq = np.bincount(s, minlength=8) / shots
    np.testing.assert_allclose(freq, _oracle_probs(np.abs(vec) ** 2, qubits),
                               atol=0.02)


def test_sample_outcomes_density(env_local):
    rho = qt.createDensityQureg(2, env_local)
    qt.pauliX(rho, 1)  # |10><10|
    s = qt.sampleOutcomes(rho, 16)
    assert np.all(s == 2)


def test_sample_outcomes_subset_bits(env_local):
    psi = qt.createQureg(3, env_local)
    qt.initClassicalState(psi, 0b110)
    np.testing.assert_array_equal(qt.sampleOutcomes(psi, 4, [1]), [1, 1, 1, 1])
    np.testing.assert_array_equal(qt.sampleOutcomes(psi, 4, [0]), [0, 0, 0, 0])
    np.testing.assert_array_equal(qt.sampleOutcomes(psi, 4, [2, 0]), [1, 1, 1, 1])


def test_sample_outcomes_validation(env_local):
    psi = qt.createQureg(2, env_local)
    with pytest.raises(ValueError):
        qt.sampleOutcomes(psi, 0)
    with pytest.raises(qt.QuESTError):
        qt.sampleOutcomes(psi, 4, [5])
