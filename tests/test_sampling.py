"""Joint outcome distributions and multi-shot sampling (TPU-native
extensions: calcProbOfAllOutcomes / sampleOutcomes — the reference's v3.2
surface queries one qubit at a time)."""

from __future__ import annotations

import numpy as np
import pytest

import quest_tpu as qt
from oracle import NUM_QUBITS, SV_TOL, random_density_matrix, random_statevector, set_dm, set_sv

N = NUM_QUBITS


def _oracle_probs(weights: np.ndarray, qubits) -> np.ndarray:
    """Independent reduction: loop over every state index."""
    out = np.zeros(1 << len(qubits))
    for k, w in enumerate(weights):
        idx = 0
        for i, q in enumerate(qubits):
            idx |= ((k >> q) & 1) << i
        out[idx] += w
    return out


@pytest.mark.parametrize("qubits", [[0], [2], [0, 1], [3, 1], [4, 0, 2],
                                    list(range(N))])
def test_prob_all_outcomes_statevector(env, qubits):
    psi = qt.createQureg(N, env)
    vec = random_statevector(N)
    set_sv(psi, vec)
    got = qt.calcProbOfAllOutcomes(psi, qubits)
    np.testing.assert_allclose(got, _oracle_probs(np.abs(vec) ** 2, qubits),
                               atol=10 * SV_TOL)
    assert np.sum(got) == pytest.approx(1.0, abs=10 * SV_TOL)


@pytest.mark.parametrize("qubits", [[1], [2, 0], [0, 1, 3]])
def test_prob_all_outcomes_density(env, qubits):
    rho_q = qt.createDensityQureg(N, env)
    rho = random_density_matrix(N)
    set_dm(rho_q, rho)
    got = qt.calcProbOfAllOutcomes(rho_q, qubits)
    np.testing.assert_allclose(got, _oracle_probs(np.real(np.diag(rho)), qubits),
                               atol=10 * SV_TOL)


def test_prob_all_outcomes_ordering(env_local):
    """Outcome index bit i must be qubits[i]: |01> (qubit 0 = 1) seen through
    qubits=[1,0] is outcome 0b10."""
    psi = qt.createQureg(2, env_local)
    qt.initClassicalState(psi, 1)
    p = qt.calcProbOfAllOutcomes(psi, [1, 0])
    np.testing.assert_allclose(p, [0.0, 0.0, 1.0, 0.0], atol=SV_TOL)


def test_prob_all_outcomes_validation(env_local):
    psi = qt.createQureg(3, env_local)
    with pytest.raises(qt.QuESTError):
        qt.calcProbOfAllOutcomes(psi, [0, 3])
    with pytest.raises(qt.QuESTError):
        qt.calcProbOfAllOutcomes(psi, [1, 1])


def test_sample_outcomes_deterministic_and_reproducible(env_local):
    psi = qt.createQureg(3, env_local)
    qt.initClassicalState(psi, 5)
    s = qt.sampleOutcomes(psi, 64)
    assert np.all(s == 5)  # deterministic state: every shot is |101>
    qt.initPlusState(psi)
    qt.seedQuEST([123])
    a = qt.sampleOutcomes(psi, 50)
    qt.seedQuEST([123])
    b = qt.sampleOutcomes(psi, 50)
    np.testing.assert_array_equal(a, b)
    # sampling must not collapse the state
    assert qt.calcProbOfOutcome(psi, 0, 0) == pytest.approx(0.5, abs=SV_TOL)


def test_sample_outcomes_frequencies(env):
    """Empirical frequencies converge to the analytic distribution."""
    psi = qt.createQureg(N, env)
    vec = random_statevector(N)
    set_sv(psi, vec)
    qubits = [0, 2, 4]
    qt.seedQuEST([7])
    shots = 20000
    s = qt.sampleOutcomes(psi, shots, qubits)
    freq = np.bincount(s, minlength=8) / shots
    np.testing.assert_allclose(freq, _oracle_probs(np.abs(vec) ** 2, qubits),
                               atol=0.02)


def test_sample_outcomes_density(env_local):
    rho = qt.createDensityQureg(2, env_local)
    qt.pauliX(rho, 1)  # |10><10|
    s = qt.sampleOutcomes(rho, 16)
    assert np.all(s == 2)


def test_sample_outcomes_subset_bits(env_local):
    psi = qt.createQureg(3, env_local)
    qt.initClassicalState(psi, 0b110)
    np.testing.assert_array_equal(qt.sampleOutcomes(psi, 4, [1]), [1, 1, 1, 1])
    np.testing.assert_array_equal(qt.sampleOutcomes(psi, 4, [0]), [0, 0, 0, 0])
    np.testing.assert_array_equal(qt.sampleOutcomes(psi, 4, [2, 0]), [1, 1, 1, 1])


def test_sample_outcomes_validation(env_local):
    psi = qt.createQureg(2, env_local)
    with pytest.raises(ValueError):
        qt.sampleOutcomes(psi, 0)
    with pytest.raises(qt.QuESTError):
        qt.sampleOutcomes(psi, 4, [5])


# ---------------------------------------------------------------------------
# batched MT19937 stream parity (sampleOutcomes' vectorized draw path)
# ---------------------------------------------------------------------------

def test_batch_rng_stream_parity():
    """genrand_int32_batch reproduces the scalar stream draw-for-draw across
    seedings, block boundaries (624), and interleaved scalar/batch calls."""
    from quest_tpu.rng import MT19937

    for seed in ([123], [0xDEADBEEF, 42], list(range(10))):
        a, b = MT19937(), MT19937()
        a.init_by_array(seed)
        b.init_by_array(seed)
        scalar = [a.genrand_int32() for _ in range(2000)]
        batch = b.genrand_int32_batch(2000)
        assert scalar == [int(x) for x in batch]

    # interleaving: scalar draws leave mid-block state the batch must honor
    a, b = MT19937(), MT19937()
    a.init_by_array([7])
    b.init_by_array([7])
    stream_a, stream_b = [], []
    for k in (1, 3, 620, 5, 624, 1249, 2):
        stream_a.extend(a.genrand_int32() for _ in range(k))
        stream_b.extend(int(x) for x in b.genrand_int32_batch(k))
        stream_a.append(a.genrand_int32())
        stream_b.append(b.genrand_int32())
    assert stream_a == stream_b

    # unseeded batch matches unseeded scalar (both auto-seed 5489)
    a, b = MT19937(), MT19937()
    assert [a.genrand_int32() for _ in range(700)] == \
        [int(x) for x in b.genrand_int32_batch(700)]

    # real1 scaling identical
    a, b = MT19937(), MT19937()
    a.init_by_array([9])
    b.init_by_array([9])
    r = b.genrand_real1_batch(100)
    assert [a.genrand_real1() for _ in range(100)] == list(r)


def test_sample_outcomes_large_shot_batch(env_local):
    """1e6 shots complete fast (vectorized draws) and match the scalar
    stream's first draws."""
    import time as _time
    from quest_tpu.rng import MT19937

    psi = qt.createQureg(4, env_local)
    qt.initPlusState(psi)
    qt.seedQuEST([31415])
    t0 = _time.perf_counter()
    s = qt.sampleOutcomes(psi, 1_000_000)
    dt = _time.perf_counter() - t0
    assert s.shape == (1_000_000,)
    assert dt < 10.0, f"1e6 shots took {dt:.1f}s — host loop regression"
    # first outcomes agree with a hand-rolled scalar draw of the same stream
    ref = MT19937()
    ref.init_by_array([31415])
    probs = np.full(16, 1 / 16)
    cdf = np.cumsum(probs)
    expect = [int(np.searchsorted(cdf, ref.genrand_real1() * cdf[-1], side="right"))
              for _ in range(50)]
    expect = [min(e, 15) for e in expect]
    assert list(s[:50]) == expect
