"""Non-unitary gates: measurement and collapse, mirroring the reference's
test_gates.cpp (3 TEST_CASEs)."""

from __future__ import annotations

import numpy as np
import pytest

import quest_tpu as qt
from oracle import (DM_TOL, NUM_QUBITS, SV_TOL, assert_dm, assert_sv, dm,
                    random_density_matrix, random_statevector, set_dm, set_sv, sv)

N = NUM_QUBITS


def test_collapseToOutcome(env):
    vec = random_statevector(N)
    rho = random_density_matrix(N)
    for t in range(N):
        for outcome in (0, 1):
            # statevector
            psi = qt.createQureg(N, env)
            set_sv(psi, vec)
            mask = np.array([(i >> t) & 1 == outcome for i in range(1 << N)])
            prob = float(np.sum(np.abs(vec[mask]) ** 2))
            got = qt.collapseToOutcome(psi, t, outcome)
            assert got == pytest.approx(prob, abs=SV_TOL)
            expected = np.where(mask, vec, 0.0) / np.sqrt(prob)
            assert_sv(psi, expected)
            # density matrix
            dq = qt.createDensityQureg(N, env)
            set_dm(dq, rho)
            probd = float(np.real(sum(rho[i, i] for i in range(1 << N)
                                      if ((i >> t) & 1) == outcome)))
            gotd = qt.collapseToOutcome(dq, t, outcome)
            assert gotd == pytest.approx(probd, abs=SV_TOL)
            keep = np.array([((i >> t) & 1) == outcome for i in range(1 << N)])
            expected_rho = np.where(np.outer(keep, keep), rho, 0.0) / probd
            assert_dm(dq, expected_rho)
    # input validation (ref: test_gates.cpp collapseToOutcome section)
    psi = qt.createQureg(N, env)
    with pytest.raises(qt.QuESTError, match="Invalid measurement outcome"):
        qt.collapseToOutcome(psi, 0, 2)
    with pytest.raises(qt.QuESTError, match="Invalid target"):
        qt.collapseToOutcome(psi, N, 0)
    qt.initClassicalState(psi, 0)  # P(qubit 0 = 1) is 0
    with pytest.raises(qt.QuESTError, match="zero probability"):
        qt.collapseToOutcome(psi, 0, 1)


def test_measure(env):
    # outcome distribution on |+>^N: both outcomes occur; state collapses
    for t in (0, N - 1):
        counts = [0, 0]
        for _ in range(10):
            psi = qt.createQureg(N, env)
            qt.initPlusState(psi)
            out = qt.measure(psi, t)
            counts[out] += 1
            # post-measurement state is normalised and consistent
            assert qt.calcProbOfOutcome(psi, t, out) == pytest.approx(1.0, abs=SV_TOL)
        assert counts[0] + counts[1] == 10
    # deterministic on a classical state
    psi = qt.createQureg(N, env)
    qt.initClassicalState(psi, 0b10110)
    for t, expect in [(0, 0), (1, 1), (2, 1), (3, 0), (4, 1)]:
        assert qt.measure(psi, t) == expect
    # density matrix
    rho = qt.createDensityQureg(N, env)
    qt.initClassicalState(rho, 0b00101)
    assert qt.measure(rho, 0) == 1
    assert qt.measure(rho, 1) == 0
    with pytest.raises(qt.QuESTError, match="Invalid target"):
        qt.measure(psi, -1)


def test_measureWithStats(env):
    psi = qt.createQureg(N, env)
    qt.initPlusState(psi)
    out, prob = qt.measureWithStats(psi, 2)
    assert out in (0, 1)
    assert prob == pytest.approx(0.5, abs=SV_TOL)
    # repeated measurement of the same qubit is deterministic with prob 1
    out2, prob2 = qt.measureWithStats(psi, 2)
    assert out2 == out
    assert prob2 == pytest.approx(1.0, abs=SV_TOL)
    # density matrix
    rho = qt.createDensityQureg(N, env)
    qt.initPlusState(rho)
    out, prob = qt.measureWithStats(rho, 0)
    assert out in (0, 1)
    assert prob == pytest.approx(0.5, abs=SV_TOL)
    assert qt.calcTotalProb(rho) == pytest.approx(1.0, abs=SV_TOL)
