"""Exhaustive test-case generators, mirroring the reference suite's Catch2
generators (ref: tests/utilities.hpp:864-1016 — sublists / bitsets /
sequences / pauliseqs, implemented in utilities.cpp via combination masks +
std::next_permutation).

The reference GENERATEs every target/control arrangement for every gate at
NUM_QUBITS=5; these helpers give the pytest suite the same coverage.  Order
matters for targets (a k-qubit matrix is not symmetric in its targets), so
``sublists`` yields all *ordered* arrangements; control sets are
order-insensitive so ``subsets`` yields combinations.
"""

from __future__ import annotations

import itertools


def sublists(pool, length, exclude=()):
    """All ordered length-``length`` arrangements of distinct elements of
    ``pool``, minus any in ``exclude`` (ref: SubListGenerator — every
    combination in every permutation)."""
    items = [x for x in pool if x not in exclude]
    return list(itertools.permutations(items, length))


def subsets(pool, length, exclude=()):
    """All unordered length-``length`` subsets (for control groups)."""
    items = [x for x in pool if x not in exclude]
    return list(itertools.combinations(items, length))


def bitsets(num_bits):
    """All bit sequences of the given length (ref: bitsets), LSB-first."""
    return [tuple(reversed(bits))
            for bits in itertools.product((0, 1), repeat=num_bits)]


def pauliseqs(num_paulis):
    """All Pauli-code sequences (ref: pauliseqs): codes 0..3 per slot."""
    return list(itertools.product((0, 1, 2, 3), repeat=num_paulis))


def target_control_cases(n, num_targs, max_ctrls=2):
    """Every ordered target arrangement of size ``num_targs``, each paired
    (cyclically) with a varying control subset of the remaining qubits of
    size 0..``max_ctrls`` — covers every target ordering AND every control
    subset without the full cross-product."""
    cases = []
    for i, targs in enumerate(sublists(range(n), num_targs)):
        ctrl_pool = [q for q in range(n) if q not in targs]
        ctrl_sets = [()]
        for k in range(1, min(max_ctrls, len(ctrl_pool)) + 1):
            ctrl_sets.extend(subsets(ctrl_pool, k))
        cases.append((targs, ctrl_sets[i % len(ctrl_sets)]))
    return cases
