"""Communication-pattern validation: inspect the COMPILED programs of sharded
ops and assert GSPMD inserted exactly the collectives the design claims
(SURVEY §2b mapping: MPI_Sendrecv pairwise exchange -> collective-permute /
all-to-all-style exchange; diagonal ops comm-free; MPI_Allreduce -> all-reduce).

This is evidence the reference could not produce for itself: its comm
schedule was hand-written, ours is checked against the partitioner's output.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import quest_tpu as qt
from quest_tpu.ops import apply as _ap
from quest_tpu.ops import calc as _calc

N = 12  # state qubits; top 3 sharded over the 8-device mesh

COMM_OPS = ("collective-permute", "all-to-all", "all-gather", "all-reduce",
            "reduce-scatter")


def _compiled_text(fn, *args, sharding, pin_out=False):
    shaped = [jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sharding)
              if a.ndim == 2 else a for a in args]
    jitted = jax.jit(fn, out_shardings=sharding) if pin_out else jax.jit(fn)
    return jitted.lower(*shaped).compile().as_text()


@pytest.fixture(scope="module")
def sharding(env_dist):
    return env_dist.sharding


_SHARD_ROW = (1 << N) // 8  # one shard's re-or-im row (8-device mesh)


def _count_comm(text, min_elems=_SHARD_ROW // 2):
    """Count communication ops moving >= min_elems elements: the design
    claims the STATE never moves unnecessarily; tiny factor-side scalar
    collectives (f64[2] etc.) are latency noise, not data motion.  The
    threshold is half a shard row so per-row or half-shard exchanges still
    register.  Async spellings (op-start) count like sync ones."""
    import re
    counts = {}
    for ln in text.splitlines():
        for op in COMM_OPS:
            if f"{op}(" not in ln and f"{op}-start(" not in ln:
                continue
            sizes = [int(np.prod([int(d) for d in dims.split(",")]))
                     for dims in re.findall(r"\w\d*\[([0-9,]+)\]", ln)]
            if sizes and max(sizes) >= min_elems:
                counts[op] = counts.get(op, 0) + 1
            break
    return counts


def test_high_qubit_dense_gate_uses_exchange(sharding):
    """A dense gate on a sharded (top) qubit must lower to a cross-shard
    exchange — the reference's MPI_Sendrecv pairwise path
    (ref: QuEST_cpu_distributed.c:479-507)."""
    u = jnp.asarray(_ap.mat_pair(np.array([[0, 1], [1, 0]])), jnp.float64)

    def f(state):
        return _ap.apply_matrix(state, u, (N - 1,))

    state = jnp.zeros((2, 1 << N), jnp.float64)
    text = _compiled_text(f, state, sharding=sharding)
    comm = _count_comm(text)
    assert comm, f"no communication op in compiled HLO: {text[:400]}"


@pytest.mark.xfail(
    reason="jaxlib 0.4.36's partitioner no longer merges consecutive "
           "same-qubit exchanges (4x all-reduce where earlier stacks "
           "emitted 1); the PR 2 scheduler makes the merge explicitly — "
           "see docs/DESIGN.md 'Known stack regressions'",
    strict=False)
def test_consecutive_sharded_gates_merge_exchanges(sharding):
    """Repeated dense gates on the same sharded qubit compile to FEWER
    exchanges than gates: GSPMD schedules communication over the whole
    program, where the reference's per-gate planner must run one full
    MPI_Sendrecv exchange per gate unconditionally
    (ref: QuEST_cpu_distributed.c:1206-1239) — its own swap-back TODO
    (:1376-1379) is subsumed by the compiler.  Measured on this stack:
    four consecutive top-qubit Haar gates lower to one all-gather + one
    all-reduce; the assertion allows slack for partitioner changes but
    pins the win (< one exchange per gate)."""
    from quest_tpu.circuit import Circuit, _run_ops

    rng = np.random.default_rng(5)
    c = Circuit(N)
    for _ in range(4):
        g = rng.normal(size=(2, 2)) + 1j * rng.normal(size=(2, 2))
        q, r = np.linalg.qr(g)
        c.unitary(N - 1, q * (np.diag(r) / np.abs(np.diag(r))))
    ops = c.key()

    def f(state):
        return _run_ops(state, ops)

    state = jnp.zeros((2, 1 << N), jnp.float64)
    text = _compiled_text(f, state, sharding=sharding, pin_out=True)
    comm = _count_comm(text)
    assert comm, "expected at least one exchange for sharded-qubit gates"
    assert sum(comm.values()) < 4, \
        f"per-gate exchanges not merged: {comm}"


def test_low_qubit_dense_gate_is_shard_local(sharding):
    """A dense gate inside the shard-local block must compile to a program
    with NO communication (the reference's halfMatrixBlockFitsInChunk case,
    ref: QuEST_cpu_distributed.c:356-361)."""
    u = jnp.asarray(_ap.mat_pair(np.array([[0, 1], [1, 0]])), jnp.float64)

    def f(state):
        return _ap.apply_matrix(state, u, (0,))

    state = jnp.zeros((2, 1 << N), jnp.float64)
    text = _compiled_text(f, state, sharding=sharding)
    assert not _count_comm(text), f"unexpected comm: {_count_comm(text)}"


def test_high_qubit_diagonal_gate_is_comm_free(sharding):
    """Diagonal gates never communicate, even on sharded qubits — the
    design's broadcast-multiply claim (the reference's diagonal kernels are
    likewise comm-free, ref: QuEST_cpu.c:2978-3109)."""
    d = jnp.asarray(np.stack([[1.0, -1.0], [0.0, 0.0]]), jnp.float64)

    def f(state):
        return _ap.apply_diagonal(state, d, (N - 1,))

    state = jnp.zeros((2, 1 << N), jnp.float64)
    text = _compiled_text(f, state, sharding=sharding)
    assert not _count_comm(text), f"unexpected comm: {_count_comm(text)}"


def test_total_prob_uses_all_reduce(sharding):
    """The norm reduction lowers to an all-reduce — the reference's
    MPI_Allreduce(SUM) (ref: QuEST_cpu_distributed.c:88)."""
    def f(state):
        return _calc.total_prob_statevec(state)

    state = jnp.zeros((2, 1 << N), jnp.float64)
    text = _compiled_text(f, state, sharding=sharding)
    # the semantically-required collective is a SCALAR all-reduce (f64[],
    # sizeless in HLO text — the reference likewise Allreduces a partial
    # sum, not the state)
    assert any(f"{op}{suffix}(" in text
               for op in ("all-reduce", "reduce-scatter")
               for suffix in ("", "-start"))


def test_prefix_swap_is_resharding_exchange(sharding):
    """Swapping a sharded qubit with a local one is the reference's
    swap-based rerouting (ref: QuEST_cpu_distributed.c:1381-1479) — with the
    canonical output sharding pinned it must lower to a cross-shard
    exchange, not a full gather.  (Unpinned, GSPMD may instead re-label the
    output sharding with zero communication — strictly better than the
    reference's mandatory exchange.)"""
    def f(state):
        return _ap.swap_qubit_amps(state, N - 1, 10)

    state = jnp.zeros((2, 1 << N), jnp.float64)
    text = _compiled_text(f, state, sharding=sharding, pin_out=True)
    comm = _count_comm(text)
    assert comm, "no communication op for a cross-shard swap"
    # the exchange must not round-trip the full state through one device
    assert "all-gather" not in comm or comm.get("all-gather", 0) <= 1


def test_select_control_style_is_comm_free(sharding, monkeypatch):
    """QUEST_TPU_CONTROL_STYLE=select: a dense gate with a control on a
    SHARDED qubit compiles with zero collectives (the default slice-update
    form costs a collective-permute + all-reduce there — measured; the
    select form is the comm profile of the reference's local conditional
    update, ref QuEST_cpu.c:2173), and produces the same state."""
    from quest_tpu.ops import apply as ap

    u = jnp.asarray(_ap.mat_pair(np.array([[0.6, 0.8], [0.8, -0.6]])),
                    jnp.float64)
    rng = np.random.default_rng(3)
    amps = rng.normal(size=(2, 1 << N))
    amps /= np.sqrt((amps ** 2).sum())
    state = jnp.asarray(amps, jnp.float64)

    def f(s):
        return _ap.apply_matrix(s, u, (0,), (N - 1,), (1,))

    want = np.asarray(f(state))

    monkeypatch.setattr(ap, "_CONTROL_STYLE", "select")
    jax.clear_caches()  # retrace so the style takes effect
    try:
        text = _compiled_text(f, state, sharding=sharding, pin_out=True)
        assert not _count_comm(text), _count_comm(text)
        got = np.asarray(f(state))
        np.testing.assert_allclose(got, want, atol=1e-13)

        # the specialised controlled-X path must also avoid its slice form
        def fx(s):
            return _ap.apply_pauli_x(s, 0, (N - 1,), (1,))
        text = _compiled_text(fx, state, sharding=sharding, pin_out=True)
        assert not _count_comm(text), _count_comm(text)
    finally:
        jax.clear_caches()  # drop select-style executables


def test_comm_plan_matches_partitioner(sharding, env_dist):
    """The static planner's per-gate prediction (parallel/planner.py) agrees
    with the partitioner's actual output: every gate it marks 'none' compiles
    with zero collectives, every cross-shard gate compiles with some."""
    from quest_tpu.circuit import Circuit, _apply_one
    from quest_tpu.parallel.planner import comm_plan

    c = Circuit(N)
    c.h(0)                      # shard-local dense
    c.h(N - 1)                  # cross-shard dense
    c.z(N - 1)                  # sharded-qubit diagonal: comm-free
    c.phase_shift(N - 2, 0.3, controls=(N - 1,))  # sharded diag w/ control
    c.cnot(0, 1)                # local
    c.x(0, controls=(N - 1,))   # local target, sharded control: comm
                                # under the default slice style (none
                                # under QUEST_TPU_CONTROL_STYLE=select)
    c.multi_qubit_unitary((1, N - 1), np.asarray(
        [[1, 0, 0, 0], [0, 1, 0, 0], [0, 0, 0, 1], [0, 0, 1, 0]], dtype=complex))

    plans = comm_plan(c, env_dist.num_ranks)
    state = jnp.zeros((2, 1 << N), jnp.float64)
    for plan, op in zip(plans, c.ops):
        text = _compiled_text(lambda s, op=op: _apply_one(s, op), state,
                              sharding=sharding, pin_out=True)
        has_comm = bool(_count_comm(text))
        expected_comm = plan.comm != "none"
        assert has_comm == expected_comm, \
            (plan, _count_comm(text))


def test_pauli_expec_z_terms_comm_free_scalar_reduce(sharding):
    """A Z-string expectation through the structured static-term kernel is
    sign-multiply + reduce: NO state-sized communication, just the scalar
    all-reduce of the partial sum (the reference's MPI_Allreduce)."""
    def f(state):
        # Z on a sharded and a local qubit
        return _calc.expec_pauli_sum_statevec(state, ((0, (1 << (N - 1)) | 1, 0),),
                                              jnp.asarray([1.0]))

    state = jnp.zeros((2, 1 << N), jnp.float64)
    text = _compiled_text(f, state, sharding=sharding)
    counts = _count_comm(text)
    assert not counts, f"state-sized comm in a diagonal-term expectation: {counts}"


def test_pauli_expec_sharded_x_term_uses_exchange(sharding):
    """An X on a SHARDED qubit makes the term's |k^x> move a cross-shard
    flip — the partitioner must spell it as a collective exchange, exactly
    the reference's pairwise MPI_Sendrecv for a high-qubit pauliX
    (ref: QuEST_cpu_distributed.c:1018-1040)."""
    def f(state):
        return _calc.expec_pauli_sum_statevec(state, ((1 << (N - 1), 0, 0),),
                                              jnp.asarray([1.0]))

    state = jnp.zeros((2, 1 << N), jnp.float64)
    text = _compiled_text(f, state, sharding=sharding)
    counts = _count_comm(text)
    assert counts, "expected a cross-shard exchange for the sharded X flip"


def test_apply_pauli_sum_local_terms_comm_free(sharding):
    """apply_pauli_sum with every mask inside the local block keeps all term
    movement shard-local (lane/sublane moves never cross shards)."""
    terms = ((3, 0, 0), (0, 5, 0))  # X-flips and Z-signs on minor qubits

    def f(state):
        return _calc.apply_pauli_sum(state, terms, jnp.asarray([0.5, 0.5]))

    state = jnp.zeros((2, 1 << N), jnp.float64)
    text = _compiled_text(f, state, sharding=sharding, pin_out=True)
    counts = _count_comm(text)
    assert not counts, f"unexpected comm for minor-block terms: {counts}"


def test_deferred_reroute_amortises_exchanges(sharding):
    """SURVEY §7.5 / the reference's own TODO (QuEST_cpu_distributed.c:
    1376-1379): a wide minor-block gate needs reroute swaps that are
    all-to-all exchanges on a sharded state.  The compiled-circuit path
    defers the swap-back, so a SECOND identical wide gate adds ZERO
    state-sized exchanges (it reuses the routing), where the eager per-gate
    path pays the full swap-in/swap-out again."""
    from quest_tpu.circuit import Circuit, compile_circuit
    from oracle import random_unitary

    n = 14  # top 3 qubits sharded on the 8-device mesh
    np.random.seed(9)
    u3 = random_unitary(3)
    mesh_sharding = sharding
    shard_row = (1 << n) // 8

    def counts_for(num_gates):
        c = Circuit(n)
        for _ in range(num_gates):
            c.multi_qubit_unitary((0, 8, 10), u3)
        fn = compile_circuit(c)
        text = _compiled_text(fn, jnp.zeros((2, 1 << n), jnp.float32),
                              sharding=mesh_sharding)
        return sum(_count_comm(text, min_elems=shard_row // 2).values())

    one, two, three = counts_for(1), counts_for(2), counts_for(3)
    assert one > 0  # the routing genuinely communicates on this mesh
    # marginal exchanges of each ADDITIONAL wide gate on the same wires: 0
    assert two == one, (one, two)
    assert three == one, (one, three)

    # The EAGER dispatch path compiles one program per gate; each program
    # pays its own routing exchanges and no cross-program cancellation is
    # possible (within ONE program the partitioner does cancel adjacent
    # swap-back/swap-in pairs — the deferred-perm path makes that guarantee
    # structural instead of CSE-dependent).  Two eager programs therefore
    # cost 2x the exchanges of the two-gate compiled circuit.
    def eager_one_gate_count():
        def fn(s):
            return _ap._apply_matrix_xla(
                s, jnp.asarray(_ap.mat_pair(u3), jnp.float32),
                (0, 8, 10), (), ())
        text = _compiled_text(fn, jnp.zeros((2, 1 << n), jnp.float32),
                              sharding=mesh_sharding)
        return sum(_count_comm(text, min_elems=shard_row // 2).values())

    per_program = eager_one_gate_count()
    assert per_program >= one  # one program >= the whole deferred circuit
    assert 2 * per_program > two, (per_program, two)


def test_eager_sequence_zero_corrective_reshards(env_dist):
    """VERDICT r4 #5: the env sharding is pinned INSIDE each eager op's
    compiled program (api._pinned / ops.apply.constrained_op), so the Qureg
    setter's corrective resharding pass (`qureg._repin`) must never fire
    across an eager create/init/gate/channel/measure sequence on a mesh."""
    from quest_tpu import qureg as qmod

    before = qmod.REPIN_COUNT
    q = qt.createQureg(N, env_dist)
    qt.initPlusState(q)
    qt.hadamard(q, N - 1)
    qt.controlledNot(q, 0, N - 1)
    qt.pauliX(q, N - 2)
    qt.tGate(q, N - 1)
    qt.multiRotateZ(q, [0, 5, N - 1], 0.4)
    qt.swapGate(q, 1, N - 1)
    qt.collapseToOutcome(q, 4, 0)
    qt.seedQuEST([7])
    qt.measure(q, 3)
    rho = qt.createDensityQureg(5, env_dist)
    qt.hadamard(rho, 4)
    qt.mixDamping(rho, 0, 0.1)
    qt.mixDepolarising(rho, 4, 0.1)
    qt.pauliY(rho, 4)
    assert qmod.REPIN_COUNT == before, "corrective reshard fired"
    # the states are still distributed and correct
    assert q.amps.sharding == env_dist.sharding
    assert rho.amps.sharding == env_dist.sharding
    assert qt.calcTotalProb(q) == pytest.approx(1.0, abs=1e-10)
    assert qt.calcTotalProb(rho) == pytest.approx(1.0, abs=1e-10)
