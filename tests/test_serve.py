"""The serving subsystem (quest_tpu/serve): structural keys, the
parameter-lifted compile cache, microbatching, concurrency, RNG isolation,
backpressure/deadlines, and eviction.

Numerics contract under test (docs/SERVING.md): batched execution is
BIT-IDENTICAL to serial per-request execution of the same class program
(the ``lax.map`` lowering keeps the per-element jaxpr identical), and the
lifted program agrees with the constant-embedded eager program to a couple
of f64 ulps (the two compilations may legally differ in FMA contraction —
exact equivalence is machine-proven by the serve audit, also run here)."""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from conftest import ON_ACCELERATOR  # noqa: F401 (platform dtype choice)

import jax
import jax.numpy as jnp

import quest_tpu as qt  # noqa: F401 (x64 + precision config)
from quest_tpu.circuit import (Circuit, GateOp, _run_ops, compile_circuit,
                               op_param_count, param_vector, qft_circuit,
                               random_circuit, structural_op)
from quest_tpu.serve import (CacheOptions, CompileCache, QuESTService,
                             circuit_from_params, parse_prometheus)
from quest_tpu.serve.batch import bucket_size
from quest_tpu.serve.selftest import vqe_ansatz
from quest_tpu.validation import QuESTError

DTYPE = jnp.float32 if ON_ACCELERATOR else jnp.float64
EAGER_ULP = 1e-5 if ON_ACCELERATOR else 1e-14


def zero_state(n):
    return jnp.zeros((2, 1 << n), DTYPE).at[0, 0].set(1.0)


def eager(circuit):
    return np.asarray(_run_ops(zero_state(circuit.num_qubits), circuit.key()))


# ---------------------------------------------------------------------------
# structural keys + parameter lift (satellite 1)
# ---------------------------------------------------------------------------

def test_structural_key_ignores_angles_keeps_structure():
    a = vqe_ansatz(6, 2, seed=0)
    b = vqe_ansatz(6, 2, seed=1)
    assert a.key() != b.key()
    assert a.key(structural=True) == b.key(structural=True)
    # a wire change IS structure
    c = vqe_ansatz(6, 2, seed=0)
    op0 = c.ops[0]
    c.ops[0] = GateOp(op0.kind, (op0.targets[0] + 1,), op0.controls,
                      op0.control_states, op0.matrix, op0.shape)
    assert c.key(structural=True) != a.key(structural=True)


def test_structural_op_keeps_discrete_payloads():
    bp = GateOp("bitperm", (3, 4, 5), (), (), (4.0, 5.0, 3.0), None)
    assert structural_op(bp) is bp          # destination wires are structure
    assert op_param_count(bp) == 0
    rz = Circuit(2).rz(0, 0.3).ops[0]
    s = structural_op(rz)
    assert s.matrix is None and s.shape == rz.shape
    assert op_param_count(s) == op_param_count(rz) == len(rz.matrix)


def test_param_vector_roundtrip():
    c = vqe_ansatz(5, 2, seed=3)
    cache = CompileCache()
    entry = cache.entry_for(c.key(), 5)
    recon = circuit_from_params(5, entry.skeleton, entry.offsets,
                                param_vector(c))
    assert recon.key() == c.key()


def test_donated_program_shared_across_angles(monkeypatch):
    """The angle-recompile defect, fixed at the root: two circuits
    differing ONLY in rotation angles share one compiled donating program
    — trace-count pinned (mirrors PR 2's trace-count test), results still
    per-circuit correct."""
    import quest_tpu.circuit as circuit_mod
    from quest_tpu.serve.cache import global_cache

    global_cache().clear()
    circuit_mod._donated_program.cache_clear()
    c1 = vqe_ansatz(6, 2, seed=11)
    c2 = vqe_ansatz(6, 2, seed=22)
    assert c1.key() != c2.key()
    want1, want2 = eager(c1), eager(c2)   # before the counter: _run_ops
    traces = {"n": 0}                     # traces through the same chain
    real = circuit_mod._run_ops_routed

    def counting(state, ops, params=None, offsets=None):
        traces["n"] += 1
        return real(state, ops, params, offsets)

    monkeypatch.setattr(circuit_mod, "_run_ops_routed", counting)
    run1 = compile_circuit(c1, donate=True)
    run2 = compile_circuit(c2, donate=True)
    got1 = np.asarray(run1(zero_state(6)))
    got2 = np.asarray(run2(zero_state(6)))
    assert traces["n"] == 1, f"structural class traced {traces['n']} times"
    assert np.abs(got1 - want1).max() <= EAGER_ULP
    assert np.abs(got2 - want2).max() <= EAGER_ULP
    assert not np.allclose(got1, got2)      # different angles, different states
    snap = global_cache().snapshot()
    assert snap["compiles"] == 1 and snap["hits"] == 1


# ---------------------------------------------------------------------------
# service: concurrency storm, bit-identity, RNG isolation (satellite 3)
# ---------------------------------------------------------------------------

def _storm_classes():
    return [lambda s: vqe_ansatz(6, 2, seed=s),
            lambda s: random_circuit(7, depth=2, seed=s),
            lambda s: qft_circuit(5)]


def test_threaded_storm_bit_identical_to_serial():
    """>= 64 requests, mixed structural classes, submitted from 8 threads
    into a RUNNING service: every batched result must be bit-identical to
    serial (singleton) execution of the same request, and within ulps of
    the eager oracle."""
    cache = CompileCache()
    svc = QuESTService(max_batch=8, max_delay_ms=5, max_queue=4096,
                       dtype=DTYPE, cache=cache)
    makers = _storm_classes()
    reqs = [(i, makers[i % 3](i // 3)) for i in range(66)]
    futs: dict = {}
    lock = threading.Lock()

    def submitter(chunk):
        for i, c in chunk:
            f = svc.submit(c, shots=8)
            with lock:
                futs[i] = (c, f)

    threads = [threading.Thread(target=submitter, args=(reqs[k::8],))
               for k in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert svc.drain(timeout=300)
    for i, (c, f) in sorted(futs.items()):
        res = f.result(timeout=60)
        serial = np.asarray(cache.execute(c.key(), zero_state(c.num_qubits),
                                          num_qubits=c.num_qubits))
        assert np.array_equal(res.state, serial), \
            f"request {i}: batched != serial"
        assert np.abs(res.state - eager(c)).max() <= EAGER_ULP
    svc.shutdown()
    d = svc.metrics_dict()
    assert d["counters"]["requests_completed_total"] == 66
    assert d["cache_hit_rate"] > 0.9


def test_sample_streams_deterministic_and_isolated():
    """Per-request MT19937 streams: identical (seed, request_id) draws the
    identical samples whatever the batching; different requests draw
    different streams."""
    results = []
    for max_batch in (8, 1):
        cache = CompileCache()
        svc = QuESTService(max_batch=max_batch, max_delay_ms=5, seed=99,
                           dtype=DTYPE, cache=cache, start=False)
        futs = [svc.submit(random_circuit(6, depth=2, seed=s % 4), shots=64)
                for s in range(12)]
        svc.start()
        assert svc.drain(timeout=300)
        results.append([f.result(timeout=60) for f in futs])
        svc.shutdown()
    batched, serial = results
    for a, b in zip(batched, serial):
        assert a.request_id == b.request_id
        assert np.array_equal(a.state, b.state)
        assert np.array_equal(a.samples, b.samples)
    # same circuit (seed 0 twice: requests 0 and 4), different streams
    assert np.array_equal(batched[0].state, batched[4].state)
    assert not np.array_equal(batched[0].samples, batched[4].samples)


# ---------------------------------------------------------------------------
# backpressure, deadlines, shutdown
# ---------------------------------------------------------------------------

def test_queue_full_raises():
    svc = QuESTService(max_queue=3, dtype=DTYPE, cache=CompileCache(),
                       start=False)
    c = qft_circuit(4)
    for _ in range(3):
        svc.submit(c)
    with pytest.raises(QuESTError) as exc:
        svc.submit(c)
    assert exc.value.code == "E_QUEUE_FULL"
    assert svc.metrics.counter("queue_rejected_total") == 1
    svc.start()
    svc.shutdown()


def test_deadline_exceeded_skips_batch_slot():
    svc = QuESTService(dtype=DTYPE, cache=CompileCache(), start=False)
    expired = svc.submit(qft_circuit(4), deadline_ms=1)
    alive = svc.submit(qft_circuit(4), deadline_ms=60_000)
    time.sleep(0.05)
    svc.start()
    assert svc.drain(timeout=120)
    with pytest.raises(QuESTError) as exc:
        expired.result(timeout=30)
    assert exc.value.code == "E_DEADLINE_EXCEEDED"
    assert alive.result(timeout=30).state is not None
    assert svc.metrics.counter("deadline_expired_total") == 1
    svc.shutdown()


def test_cancelled_future_does_not_kill_worker():
    """A tenant's Future.cancel() must never kill the worker or fail its
    co-batched neighbours (found by review: set_exception/set_result on a
    cancelled future raises InvalidStateError)."""
    svc = QuESTService(dtype=DTYPE, cache=CompileCache(), start=False)
    c = qft_circuit(4)
    cancelled_expired = svc.submit(c, deadline_ms=1)
    cancelled = svc.submit(c)
    alive = svc.submit(c)
    assert cancelled_expired.cancel() and cancelled.cancel()
    time.sleep(0.05)
    svc.start()
    assert svc.drain(timeout=120)
    assert alive.result(timeout=30).state is not None   # worker survived
    assert cancelled.cancelled() and cancelled_expired.cancelled()
    late = svc.submit(c)                                # still serving
    assert svc.drain(timeout=120)
    assert late.result(timeout=30).state is not None
    svc.shutdown()


def test_shutdown_without_drain_fails_pending():
    from quest_tpu.validation import ErrorCode, QuESTError
    svc = QuESTService(dtype=DTYPE, cache=CompileCache(), start=False)
    f = svc.submit(qft_circuit(4))
    svc.shutdown(drain=False)
    # pending requests and post-shutdown submits both fail with the CLEAN
    # serving error (E_SERVICE_SHUTDOWN), not a bare RuntimeError — the
    # pool storm contract of tests/test_concurrency.py
    with pytest.raises(QuESTError) as exc:
        f.result(timeout=10)
    assert exc.value.code == ErrorCode.SERVICE_SHUTDOWN
    with pytest.raises(QuESTError) as exc:
        svc.submit(qft_circuit(4))
    assert exc.value.code == ErrorCode.SERVICE_SHUTDOWN


# ---------------------------------------------------------------------------
# cache eviction + accounting (satellite 3's "tiny byte budget")
# ---------------------------------------------------------------------------

def test_cache_eviction_under_tiny_byte_budget():
    cache = CompileCache(max_bytes=1)     # nothing fits; newest survives
    a, b = vqe_ansatz(5, 1, seed=0), qft_circuit(5)
    st = zero_state(5)
    ra1 = np.asarray(cache.execute(a.key(), st, num_qubits=5))
    assert cache.stats["evictions"] == 0
    np.asarray(cache.execute(b.key(), st, num_qubits=5))
    assert cache.stats["evictions"] == 1          # class A pushed out
    assert cache.snapshot()["entries"] == 1
    ra2 = np.asarray(cache.execute(a.key(), st, num_qubits=5))
    assert cache.stats["misses"] == 3             # A recompiled after eviction
    assert cache.stats["evictions"] == 2
    assert np.array_equal(ra1, ra2)               # eviction never changes results
    assert cache.stats["entry_bytes"] >= 0


def test_batch_padding_and_metrics():
    assert [bucket_size(m, 8) for m in (1, 2, 3, 5, 8, 9)] == [1, 2, 4, 8, 8, 8]
    cache = CompileCache()
    svc = QuESTService(max_batch=8, max_delay_ms=5, dtype=DTYPE, cache=cache,
                       start=False)
    futs = [svc.submit(vqe_ansatz(5, 1, seed=s)) for s in range(5)]
    svc.start()
    assert svc.drain(timeout=120)
    for s, f in enumerate(futs):
        res = f.result(timeout=60)
        assert res.batch_size == 5
        assert np.abs(res.state - eager(vqe_ansatz(5, 1, seed=s))).max() \
            <= EAGER_ULP
    d = svc.metrics_dict()
    assert d["counters"]["padded_requests_total"] == 3     # 5 padded to 8
    assert d["histograms"]["batch_size"]["mean"] == 5
    svc.shutdown()


def test_initial_state_stacked_path():
    cache = CompileCache()
    svc = QuESTService(max_batch=4, max_delay_ms=5, dtype=DTYPE, cache=cache,
                       start=False)
    c = vqe_ansatz(5, 1, seed=0)
    states = []
    rng = np.random.default_rng(5)
    for _ in range(3):
        v = rng.normal(size=(2, 32))
        v /= np.sqrt((v ** 2).sum())
        states.append(v)
    futs = [svc.submit(c, initial_state=s) for s in states]
    svc.start()
    assert svc.drain(timeout=120)
    for s, f in zip(states, futs):
        want = np.asarray(_run_ops(jnp.asarray(s, DTYPE), c.key()))
        assert np.abs(f.result(timeout=60).state - want).max() <= EAGER_ULP
    svc.shutdown()


# ---------------------------------------------------------------------------
# scheduler-composed classes (PR 2) + metrics export
# ---------------------------------------------------------------------------

def test_mesh_service_composes_with_scheduler():
    if ON_ACCELERATOR or len(jax.devices()) < 8:
        pytest.skip("needs the 8-virtual-device CPU mesh")
    # 16q: the smallest QFT whose reversal swaps reach the PREFIX wires on
    # an 8-way mesh, so the scheduler fuses them into a bitperm collective
    cache = CompileCache()
    svc = QuESTService(num_devices=8, max_batch=2, max_delay_ms=5,
                       dtype=DTYPE, cache=cache, start=False)
    futs = [svc.submit(qft_circuit(16)) for _ in range(2)]
    svc.start()
    assert svc.drain(timeout=300)
    want = eager(qft_circuit(16))
    for f in futs:
        assert np.abs(f.result(timeout=60).state - want).max() < 1e-10
    # one schedule + one compile for the whole class (2 requests are
    # 1 miss + 1 hit: the schedule search ran ONCE)
    assert cache.stats["misses"] == 1 and cache.stats["compiles"] == 1
    entry = cache.entry_for(qft_circuit(16).key(), 16,
                            CacheOptions(num_devices=8))
    assert any(op.kind == "bitperm" for op in entry.skeleton), \
        "scheduled skeleton should carry the fused swap network"
    # the fused bitperm carries NO lifted operands: its payload is routing
    assert all(off is None for op, off in zip(entry.skeleton, entry.offsets)
               if op.kind == "bitperm")
    svc.shutdown()


def test_prometheus_export_parses_and_counts():
    cache = CompileCache()
    svc = QuESTService(max_batch=4, max_delay_ms=5, dtype=DTYPE, cache=cache,
                       start=False)
    futs = [svc.submit(qft_circuit(4)) for _ in range(4)]
    svc.start()
    assert svc.drain(timeout=120)
    for f in futs:
        f.result(timeout=60)
    text = svc.prometheus()
    parsed = parse_prometheus(text)
    assert parsed["quest_serve_requests_completed_total"][""] == 4
    assert "quest_serve_cache_hit_rate" in parsed
    assert "quest_serve_request_latency_seconds_bucket" in parsed
    d = svc.metrics_dict()
    assert {"count", "sum", "mean", "p50", "p99"} <= \
        set(d["histograms"]["request_latency_seconds"])
    svc.shutdown()


def test_serve_audit_clean():
    """Satellite 2: the parameter lift is machine-proven, not assumed."""
    from quest_tpu.analysis.serve_audit import audit_param_lift
    reports, found = audit_param_lift(
        [("vqe6", vqe_ansatz(6, 2, seed=0), vqe_ansatz(6, 2, seed=1)),
         ("qft6", qft_circuit(6), qft_circuit(6))],
        dtype=DTYPE)
    assert not found, [d.format() for d in found]
    assert all(r["roundtrip_proven"] and r["twin_shares_entry"]
               for r in reports)


def test_serve_audit_catches_divergence(monkeypatch):
    """Adversarial: corrupt the scheduler-provenance slot map (swap two
    operand offsets) — the audit's round-trip proof AND probe must catch
    it (the audit is a real check, not a rubber stamp)."""
    from quest_tpu.analysis.serve_audit import audit_param_lift
    from quest_tpu.serve import cache as cache_mod

    real = cache_mod._provenance_offsets

    def corrupted(orig_ops, sched_ops):
        offsets, total = real(orig_ops, sched_ops)
        slots = [i for i, o in enumerate(offsets) if o is not None]
        out = list(offsets)
        out[slots[0]], out[slots[1]] = out[slots[1]], out[slots[0]]
        return tuple(out), total

    monkeypatch.setattr(cache_mod, "_provenance_offsets", corrupted)
    bad = Circuit(6).ry(0, 0.3).ry(1, 0.9).ry(2, 1.7).ry(3, -0.4)
    _, found = audit_param_lift([("corrupted", bad)], num_devices=8,
                                dtype=DTYPE)
    assert any(d.code == "A_PARAM_LIFT_DIVERGENCE" for d in found), \
        [d.format() for d in found]


# ---------------------------------------------------------------------------
# the acceptance row: 64 x 16q, one compile, serial-identical, PR 5 headline
# ---------------------------------------------------------------------------

def test_vqe16_batch64_single_compile_bit_identical():
    """64 structurally-identical, differently-parameterized 16q circuits
    through QuESTService: exactly ONE XLA compilation (cache counters
    asserted), results bit-identical to serial per-circuit execution and
    ulp-close to the constant-embedded eager oracle (whose exact
    equivalence the serve audit proves)."""
    cache = CompileCache()
    svc = QuESTService(max_batch=64, max_delay_ms=50, max_queue=256,
                       dtype=DTYPE, cache=cache, start=False)
    circuits = [vqe_ansatz(16, 1, seed=s) for s in range(64)]
    assert len({c.key(structural=True) for c in circuits}) == 1
    futs = [svc.submit(c) for c in circuits]
    svc.start()
    assert svc.drain(timeout=600)
    results = [f.result(timeout=60) for f in futs]
    assert cache.stats["compiles"] == 1, cache.snapshot()
    assert cache.stats["misses"] == 1 and cache.stats["hits"] == 63
    assert all(r.batch_size == 64 for r in results)
    # serial oracle AFTER the compile assertion (it adds the singleton
    # program for the same class)
    for c, r in zip(circuits[:8], results[:8]):
        serial = np.asarray(cache.execute(c.key(), zero_state(16),
                                          num_qubits=16))
        assert np.array_equal(r.state, serial)
        assert np.abs(r.state - eager(c)).max() <= EAGER_ULP
    svc.shutdown()
