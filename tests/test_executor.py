"""The pipelined cross-shard executor (parallel/executor.py) and its
ride-alongs: chunked-vs-monolithic bit-identity (the ISSUE property test),
pairwise shard_map engine correctness, chunk-count validation through the
E_* codes, overlap planning/prediction, the layout-only chunking proof,
the overlap-aware planner time model, sub-tile shard comm accounting, and
the compiled-HLO async audit."""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from quest_tpu.circuit import (Circuit, compile_circuit, qft_circuit,
                               random_circuit)
from quest_tpu.ops import apply as ap
from quest_tpu.parallel import executor as ex
from quest_tpu.parallel import planner
from oracle import random_unitary


def _rand_state(n: int, seed: int = 0) -> jax.Array:
    rs = np.random.RandomState(seed)
    st = rs.randn(2, 1 << n)
    st /= np.sqrt((st ** 2).sum())
    return jnp.asarray(st, jnp.float64)


def _mixed_circuit(n: int = 14, seed: int = 3) -> Circuit:
    """Every executor-relevant structure: cross-shard 1q dense gates
    (pairwise engine), repeated wide sharded gates (epoch sandwich),
    diagonals, and a trailing swap network (fused bitperm window)."""
    np.random.seed(seed)
    rs = np.random.RandomState(seed)
    c = Circuit(n)
    c.h(n - 1)
    c.rz(2, 0.31)
    for _ in range(3):
        c.multi_qubit_unitary((n - 2, n - 1), random_unitary(2))
    c.unitary(n - 3, random_unitary(1))
    c.phase_shift(1, 0.7, controls=(0,))
    for q in range(3):
        c.swap(q, n - 1 - q)
    c.unitary(int(rs.randint(0, n - 4)), random_unitary(1))
    return c


# ---------------------------------------------------------------------------
# chunked == monolithic (ISSUE satellite): bit-identical across C, and both
# equal the unscheduled reference
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("devices", [2, 8])
def test_overlapped_bit_identical_across_chunk_counts(devices):
    """pipeline_chunks in {1, 2, 4} (C=1 is the degenerate single-chunk
    path through the same engines) must give BIT-IDENTICAL states —
    chunking is layout-only — and agree with the unscheduled circuit."""
    for seed in (0, 1):
        c = _mixed_circuit(14, seed)
        st = _rand_state(14, 10 + seed)
        want = np.asarray(compile_circuit(c)(st))
        outs = {}
        for chunks in (1, 2, 4):
            run = compile_circuit(c, num_devices=devices, overlap=True,
                                  pipeline_chunks=chunks)
            outs[chunks] = np.asarray(run(st))
            np.testing.assert_allclose(outs[chunks], want, atol=1e-12)
        assert np.array_equal(outs[1], outs[2]), "C=1 vs C=2 not bit-identical"
        assert np.array_equal(outs[2], outs[4]), "C=2 vs C=4 not bit-identical"


def test_overlapped_random_circuits_equivalent():
    for seed in range(2):
        c = random_circuit(12, depth=2, seed=seed)
        st = _rand_state(12, seed)
        want = np.asarray(compile_circuit(c)(st))
        run = compile_circuit(c, num_devices=8, overlap=True,
                              pipeline_chunks=4)
        np.testing.assert_allclose(np.asarray(run(st)), want, atol=1e-12)


def test_overlapped_qft_equivalent():
    c = qft_circuit(14)
    st = _rand_state(14, 7)
    want = np.asarray(compile_circuit(c)(st))
    run = compile_circuit(c, num_devices=8, pipeline_chunks=4)  # implies overlap
    np.testing.assert_allclose(np.asarray(run(st)), want, atol=1e-12)


def test_pairwise_engine_matches_gate_oracle():
    """The explicit shard_map ppermute engine must reproduce the ordinary
    gate engine on a sharded-wire 1q dense gate, at every chunk count."""
    n = 12
    np.random.seed(4)
    u = random_unitary(1)
    c = Circuit(n).unitary(n - 1, u)
    st = _rand_state(n, 4)
    want = np.asarray(
        ap.apply_matrix(st, jnp.asarray(np.stack([u.real, u.imag])),
                        (n - 1,)))
    outs = {}
    for chunks in (1, 2, 4):
        s = c.schedule(8, overlap=True, pipeline_chunks=chunks)
        assert any(e.kind == "pairwise" for e in s._overlap_plan.events)
        outs[chunks] = np.asarray(ex.overlapped_program(s, 8)(st))
        np.testing.assert_allclose(outs[chunks], want, atol=1e-12)
    assert np.array_equal(outs[1], outs[2])
    assert np.array_equal(outs[2], outs[4])


# ---------------------------------------------------------------------------
# chunk-count validation (ISSUE satellite): E_INVALID_SCHEDULE_OPTION
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("bad", [0, -1, 3, 6, 2.0, "4", True])
def test_non_power_of_two_chunks_rejected(bad):
    from quest_tpu.validation import ErrorCode, QuESTError
    c = qft_circuit(8)
    with pytest.raises(QuESTError) as err:
        c.schedule(4, overlap=True, pipeline_chunks=bad)
    assert err.value.code == ErrorCode.INVALID_SCHEDULE_OPTION
    with pytest.raises(QuESTError) as err:
        compile_circuit(c, num_devices=4, pipeline_chunks=bad)
    assert err.value.code == ErrorCode.INVALID_SCHEDULE_OPTION


def test_overlap_without_num_devices_rejected():
    from quest_tpu.validation import ErrorCode, QuESTError
    with pytest.raises(QuESTError) as err:
        compile_circuit(qft_circuit(8), overlap=True)
    assert err.value.code == ErrorCode.INVALID_SCHEDULE_OPTION


def test_schedule_still_rejects_unknown_kwargs_with_overlap():
    from quest_tpu.validation import ErrorCode, QuESTError
    with pytest.raises(QuESTError) as err:
        qft_circuit(8).schedule(4, overlap=True, pipeline_chunk=4)  # typo
    assert err.value.code == ErrorCode.INVALID_SCHEDULE_OPTION
    assert "pipeline_chunk" in str(err.value)


# ---------------------------------------------------------------------------
# overlap planning
# ---------------------------------------------------------------------------

def test_plan_epoch_sandwich_window():
    """A scheduler epoch (bitperm . gates . bitperm) plans as ONE hideable
    window event whose chunk bits avoid every wire the window touches."""
    np.random.seed(0)
    n, devices = 14, 4
    c = Circuit(n)
    for _ in range(3):
        c.multi_qubit_unitary((n - 2, n - 1), random_unitary(2))
    s = c.schedule(devices, overlap=True, pipeline_chunks=4)
    plan = s._overlap_plan
    assert len(plan.events) == 1
    e = plan.events[0]
    assert e.kind == "window" and e.hideable and e.chunks == 4
    assert s.ops[e.start].kind == "bitperm"
    assert s.ops[e.stop - 1] == s.ops[e.start]
    used = set()
    for op in s.ops[e.start:e.stop]:
        used |= set(op.targets) | set(op.controls)
        if op.kind == "bitperm":
            used |= {int(d) for d in op.matrix}
    assert not (set(e.chunk_bits) & used)
    assert all(b < planner.local_qubit_count(n, devices)
               for b in e.chunk_bits)


def test_plan_lone_reshard_not_hideable():
    """A fused swap-network bitperm with no adjacent compute is chunked
    (comm pipelining) but NOT marked hideable — nothing to hide behind."""
    n = 14
    c = Circuit(n)
    for q in range(3):
        c.swap(q, n - 1 - q)
    s = c.schedule(8, overlap=True, pipeline_chunks=2)
    events = s._overlap_plan.events
    assert events and all(not e.hideable for e in events)


def test_plan_degenerate_single_device():
    s = qft_circuit(10).schedule(1, overlap=True, pipeline_chunks=4)
    assert s._overlap_plan.events == ()


# ---------------------------------------------------------------------------
# layout-only chunking proof (analysis/equivalence.py)
# ---------------------------------------------------------------------------

def test_verify_schedule_proves_chunked_lowering():
    from quest_tpu.analysis.equivalence import verify_schedule
    for circuit in (qft_circuit(14), _mixed_circuit(14, 1)):
        diags = verify_schedule(circuit, num_devices=8, overlap=True,
                                pipeline_chunks=4)
        assert diags == [], [d.format() for d in diags]


def test_check_overlap_plan_catches_clobbered_bits():
    """A chunk bit inside the window's wire set is a soundness violation:
    the checker must refuse the plan with V_SEMANTICS_CHANGED."""
    import dataclasses
    from quest_tpu.analysis.diagnostics import AnalysisCode, Severity
    from quest_tpu.analysis.equivalence import check_overlap_plan
    np.random.seed(2)
    n = 14
    c = Circuit(n)
    for _ in range(3):
        c.multi_qubit_unitary((n - 2, n - 1), random_unitary(2))
    s = c.schedule(4, overlap=True, pipeline_chunks=2)
    plan = s._overlap_plan
    e = plan.events[0]
    clobbered = dataclasses.replace(e, chunk_bits=(s.ops[e.start].targets[0],),
                                    chunks=2)
    bad_plan = dataclasses.replace(plan, events=(clobbered,))
    found = check_overlap_plan(s, bad_plan)
    assert found and all(d.code == AnalysisCode.SEMANTICS_CHANGED
                         and d.severity == Severity.ERROR for d in found)
    # the honest plan passes
    assert check_overlap_plan(s, plan) == []


def test_check_overlap_plan_rejects_bad_pairwise():
    import dataclasses
    from quest_tpu.analysis.diagnostics import AnalysisCode
    from quest_tpu.analysis.equivalence import check_overlap_plan
    n = 12
    c = Circuit(n).x(n - 1, controls=(0,))  # controlled: NOT pairwise-safe
    s = c.schedule(8, overlap=True, pipeline_chunks=2)
    fake = ex.ChunkedEvent(0, 1, "pairwise", (), 2, "permute", True)
    bad_plan = dataclasses.replace(s._overlap_plan, events=(fake,))
    found = check_overlap_plan(s, bad_plan)
    assert any(d.code == AnalysisCode.SEMANTICS_CHANGED for d in found)


# ---------------------------------------------------------------------------
# overlap-aware planner cost model
# ---------------------------------------------------------------------------

def test_time_model_serial_is_sum_not_midpoint():
    c = Circuit(16).h(15)
    t = planner.time_model(c, 8, planner.V5E, 1)[0]
    assert t.comm_s > 0
    assert t.total_s == pytest.approx(t.compute_s + t.comm_s)


def test_time_model_pipelined_pairwise_cost():
    c = Circuit(16).h(15)
    t = planner.time_model(c, 8, planner.V5E, 1, pipeline_chunks=4)[0]
    assert t.hideable and t.pipeline_chunks == 4
    assert t.total_s == pytest.approx(
        max(t.compute_s, t.comm_s) + min(t.compute_s, t.comm_s) / 4)
    assert t.total_s < t.compute_s + t.comm_s


def test_predict_overlap_never_slower_and_frac_bounded():
    for circuit in (qft_circuit(16), _mixed_circuit(14, 5)):
        p = ex.predict_overlap(circuit.schedule(8), 8, 4)
        assert p["model_seconds_overlapped"] <= p["model_seconds_serial"]
        assert 0.0 <= p["predicted_hidden_frac"] <= 1.0
        one = ex.predict_overlap(circuit.schedule(8), 8, 1)
        assert one["model_seconds_overlapped"] == pytest.approx(
            one["model_seconds_serial"])


def test_recommend_pipeline_chunks_shapes():
    assert planner.recommend_pipeline_chunks(20, 1) == 1
    for n, d in ((22, 8), (30, 8), (34, 64)):
        c = planner.recommend_pipeline_chunks(n, d)
        assert c >= 1 and (c & (c - 1)) == 0
    # a 30q f32 shard (1 GiB over 8 chips) cannot fit VMEM monolithically:
    # the recommendation must actually chunk
    assert planner.recommend_pipeline_chunks(30, 8) > 1
    # a tiny shard is latency-bound: do not chunk
    assert planner.recommend_pipeline_chunks(14, 8) == 1


# ---------------------------------------------------------------------------
# sub-tile shard comm accounting (ISSUE satellite; found-by-audit in PR 3)
# ---------------------------------------------------------------------------

def test_memory_footprint_flags_sub_tile_shards():
    assert planner.memory_footprint(9, 8)["sub_tile_shard"] is True
    assert planner.memory_footprint(20, 8)["sub_tile_shard"] is False
    assert planner.memory_footprint(9, 1)["sub_tile_shard"] is False


def test_comm_plan_charges_subtile_class():
    """The 9q x 8-device config: 64 amps/shard is below one 8x128 tile, so
    dense gates the wire-position model rates local are charged the
    'subtile' comm class; diagonals stay comm-free."""
    c = Circuit(9).h(0).z(0).cnot(0, 1)
    plans = planner.comm_plan(c, 8)
    assert plans[0].comm == "subtile" and plans[0].bytes_moved > 0
    assert plans[1].comm == "none"          # diagonal: elementwise broadcast
    assert plans[2].comm == "subtile"
    s = planner.comm_summary(c, 8)
    assert s["subtile_events"] == 2
    assert s["comm_events"] == 2
    # same circuit on a tile-sized shard stays local
    big = Circuit(16).h(0).z(0).cnot(0, 1)
    assert all(p.comm == "none" for p in planner.comm_plan(big, 8))
    assert planner.comm_summary(big, 8)["subtile_events"] == 0


def test_analyzer_warns_on_sub_tile_deployment():
    from quest_tpu.analysis import analyze_circuit
    from quest_tpu.analysis.diagnostics import (AnalysisCode, Severity)
    c = Circuit(9).h(0)
    found = analyze_circuit(c, num_devices=8, hints=False)
    hits = [d for d in found if d.code == AnalysisCode.SUBTILE_SHARD]
    assert hits and hits[0].severity == Severity.WARNING
    assert not [d for d in analyze_circuit(Circuit(16).h(0), num_devices=8,
                                           hints=False)
                if d.code == AnalysisCode.SUBTILE_SHARD]


# ---------------------------------------------------------------------------
# compiled-HLO overlap audit (analysis/jaxpr_audit.py)
# ---------------------------------------------------------------------------

def test_count_hlo_async_collectives_parses_separation():
    from quest_tpu.analysis.jaxpr_audit import count_hlo_async_collectives
    hidden = "\n".join([
        "  %s = f32[2,512] collective-permute-start(%x), channel_id=1",
        "  %mul = f32[2,512] multiply(%a, %b)",
        "  %d = f32[2,512] collective-permute-done(%s)",
    ])
    back2back = "\n".join([
        "  %s = f32[2,512] all-to-all-start(%x)",
        "  %d = f32[2,512] all-to-all-done(%s)",
    ])
    # interleaved but fully serialized: start.1; start.2; done.1; done.2 —
    # no compute sits between any start and ITS done, so nothing is hidden
    interleaved = "\n".join([
        "  %s1 = f32[2,512] collective-permute-start(%x), channel_id=1",
        "  %s2 = f32[2,512] collective-permute-start(%y), channel_id=2",
        "  %d1 = f32[2,512] collective-permute-done(%s1)",
        "  %d2 = f32[2,512] collective-permute-done(%s2)",
    ])
    assert count_hlo_async_collectives(hidden) == {"starts": 1,
                                                   "separated": 1}
    assert count_hlo_async_collectives(back2back) == {"starts": 1,
                                                      "separated": 0}
    assert count_hlo_async_collectives(interleaved) == {"starts": 2,
                                                        "separated": 0}
    assert count_hlo_async_collectives("%y = f32[4] add(%a, %b)") == {
        "starts": 0, "separated": 0}


def test_audit_overlap_reports_and_never_errors():
    """On the 8-virtual-device CPU mesh the audit must produce a full
    report; CPU collectives are synchronous, so any finding is the WARNING
    A_COLLECTIVE_NOT_OVERLAPPED (or a count WARNING), never an ERROR."""
    from quest_tpu.analysis.diagnostics import Severity
    from quest_tpu.analysis.jaxpr_audit import audit_overlap
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    c = qft_circuit(14)
    s = c.schedule(8, overlap=True, pipeline_chunks=4)
    report, found = audit_overlap(s, 8, 4)
    assert report["planned_events"] >= 1
    assert report["hlo_collectives"] is not None
    assert report["hlo_async"] is not None
    assert all(d.severity < Severity.ERROR for d in found), \
        [d.format() for d in found]


def test_audit_dispatch_widened_bound_accepts_chunked_lowering():
    """audit_dispatch(pipeline_chunks=C) must not flag a program whose
    measured collective count fits C chunk-sized collectives per event."""
    from quest_tpu.analysis.jaxpr_audit import audit_dispatch
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    c = qft_circuit(14).schedule(8)
    _, strict = audit_dispatch(c, 8, donate=False, label="strict")
    _, widened = audit_dispatch(c, 8, donate=False, pipeline_chunks=4,
                                label="widened")
    assert len(widened) <= len(strict)
    assert not [d for d in widened if d.code == "A_COLLECTIVE_COUNT_MISMATCH"]
