"""The f64 gather engine (ops/apply.py _dense_gather) vs the matmul engine.

On accelerator backends every small f64 dense gate routes through the
XOR-shift gather sum instead of the emulated-f64 dot_general (measured 6-9x
faster on the v5e).  These tests pin its numerics on CPU by calling it
directly against the matmul engine and the superoperator sparsity hints used
by ops/decoherence.py.

Ref analogue: the reference's specialised channel kernels
(QuEST_cpu.c:125-695) are validated by its [decoherence] Catch2 tag; here the
gather engine is additionally cross-checked gate-by-gate against the default
engine, which the full suite already validates against the numpy oracle.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from oracle import random_unitary
from quest_tpu.ops import apply as ap
from quest_tpu.ops import decoherence as deco

N = 12


@pytest.fixture(scope="module")
def state():
    rs = np.random.RandomState(7)
    st = rs.randn(2, 1 << N)
    st /= np.sqrt((st ** 2).sum())
    return jnp.asarray(st, dtype=jnp.float64)


_gather = jax.jit(ap._dense_gather, static_argnums=(2, 3, 4, 5))


CASES = [
    ((3,), (), ()),              # lane target
    ((8,), (), ()),              # sublane target
    ((11,), (), ()),             # prefix target
    ((2, 8), (), ()),            # lane + sublane
    ((3, 11), (), ()),           # lane + prefix
    ((10, 11), (), ()),          # prefix run
    ((6, 7), (0,), (1,)),        # lane/sublane boundary + lane control
    ((3,), (7, 11), (1, 0)),     # sublane + prefix controls, one 0-state
    ((11,), (2,), (1,)),         # prefix target, lane control
    ((1, 4), (6, 10), (0, 1)),   # two targets, mixed controls
]


@pytest.mark.parametrize("targets,controls,cstates", CASES)
def test_gather_matches_matmul_engine(state, targets, controls, cstates):
    rs = np.random.RandomState(hash((targets, controls)) % 2 ** 31)
    k = len(targets)
    u = jnp.asarray(rs.randn(2, 1 << k, 1 << k), dtype=jnp.float64)
    cstates = cstates or (1,) * len(controls)
    want = ap._apply_matrix_xla(state, u, targets, controls, cstates)
    got = _gather(state, u, targets, controls, cstates, None)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=0, atol=1e-13)


def test_gather_beyond_matmul_expansion_cap(state):
    """A wide mixed-block gate the matmul engine cannot expand or reroute on
    a small state (raises CANNOT_FIT) is in-scope for the gather engine —
    check it against a dense numpy application."""
    targets = (0, 5, 9, 11)
    rs = np.random.RandomState(0)
    u = rs.randn(2, 16, 16)
    got = _gather(state, jnp.asarray(u, dtype=jnp.float64), targets, (), (), None)

    # host-side complex assembly: a device-side `re + 1j*im` would build a
    # C128 array, which the TPU rejects at the program boundary
    st = np.asarray(state)
    sv = st[0] + 1j * st[1]
    U = u[0] + 1j * u[1]
    out = np.empty_like(sv)
    for i in range(len(sv)):
        b = sum(((i >> q) & 1) << j for j, q in enumerate(targets))
        acc = 0.0
        for bp in range(16):
            ip = i
            for j, q in enumerate(targets):
                ip = (ip & ~(1 << q)) | (((bp >> j) & 1) << q)
            acc += U[b, bp] * sv[ip]
        out[i] = acc
    g = np.asarray(got)
    np.testing.assert_allclose(g[0] + 1j * g[1], out, rtol=0, atol=1e-12)


@pytest.mark.parametrize("patterns,build", [
    ((0, 3), lambda p: np.stack([np.diag([1 - 2*p/3, 1 - 4*p/3, 1 - 4*p/3, 1 - 2*p/3])
                                 + np.array([[0, 0, 0, 2*p/3], [0]*4, [0]*4,
                                             [2*p/3, 0, 0, 0]]),
                                 np.zeros((4, 4))])),   # depolarising superop
    ((0, 3), lambda p: np.stack([np.array([[1, 0, 0, p],
                                           [0, np.sqrt(1-p), 0, 0],
                                           [0, 0, np.sqrt(1-p), 0],
                                           [0, 0, 0, 1-p]]),
                                 np.zeros((4, 4))])),   # damping superop
])
def test_patterns_hint_equivalence(state, patterns, build):
    s = jnp.asarray(build(0.23), dtype=jnp.float64)
    doubled = (2, 9)
    full = _gather(state, s, doubled, (), (), None)
    hinted = _gather(state, s, doubled, (), (), patterns)
    np.testing.assert_array_equal(np.asarray(full), np.asarray(hinted))


def test_kraus_superop_pattern_autodetect():
    """apply_kraus_map detects the XOR sparsity of structured channels: the
    two-qubit depolarising superoperator uses 4 of 16 patterns."""
    from quest_tpu.matrices import PAULI_MATRICES
    p = 0.3
    ops = []
    for i in range(4):
        for j in range(4):
            fac = np.sqrt(1 - p) if (i == 0 and j == 0) else np.sqrt(p / 15)
            ops.append(fac * np.kron(PAULI_MATRICES[j], PAULI_MATRICES[i]))
    s = deco.kraus_superoperator(ops)
    nz_r, nz_c = np.nonzero((s[0] != 0) | (s[1] != 0))
    ms = sorted({int(b ^ c) for b, c in zip(nz_r, nz_c)})
    assert ms == [0, 5, 10, 15]


def test_density_fused_dispatch_matches_two_pass(state):
    """apply_matrix_density (one program) == gate then conjugated shadow
    (two programs)."""
    nq = N // 2
    rs = np.random.RandomState(3)
    u = jnp.asarray(rs.randn(2, 2, 2), dtype=jnp.float64)
    fused = ap.apply_matrix_density(state, u, (1,), (3,), (1,), nq)
    conj = jnp.stack([u[0], -u[1]])
    two = ap.apply_matrix(state, u, (1,), (3,), (1,))
    two = ap.apply_matrix(two, conj, (1 + nq,), (3 + nq,), (1,))
    # one fused program may contract fma/fusion differently than two programs
    np.testing.assert_allclose(np.asarray(fused), np.asarray(two),
                               rtol=0, atol=1e-13)

    d = jnp.asarray(rs.randn(2, 2), dtype=jnp.float64)
    fused = ap.apply_diagonal_density(state, d, (2,), (), (), nq)
    dconj = jnp.stack([d[0], -d[1]])
    two = ap.apply_diagonal(state, d, (2,))
    two = ap.apply_diagonal(two, dconj, (2 + nq,))
    np.testing.assert_allclose(np.asarray(fused), np.asarray(two),
                               rtol=0, atol=1e-13)


def _seeded_unitary(k_qubits: int, seed: int) -> np.ndarray:
    np.random.seed(seed)
    return random_unitary(k_qubits)


def test_dense_1q_f64_matches_matmul_engine(state, monkeypatch):
    """The specialised f64 single-target kernel (flip/take/lane-perm partner
    move + per-target-bit coefficient broadcast) against the matmul engine,
    for every target class.  The matmul oracle is FORCED (on accelerator
    backends _apply_matrix_xla would otherwise dispatch 1q f64 gates to the
    kernel under test, making the comparison tautological)."""
    up = jnp.asarray(ap.mat_pair(_seeded_unitary(1, 77)), jnp.float64)
    for q in range(N):
        monkeypatch.setattr(ap, "_F64_STYLE", "matmul")
        want = ap._apply_matrix_xla(state, up, (q,), (), ())
        monkeypatch.setattr(ap, "_F64_STYLE", "auto")
        got = ap._dense_1q_f64(state, up, q)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=0, atol=1e-13)


def test_dense_1q_f64_chunked_path(state, monkeypatch):
    """Huge-state chunking (fori_loop over a non-wire axis) is exercised by
    shrinking the chunk threshold; results must be identical (matmul oracle
    forced — see test_dense_1q_f64_matches_matmul_engine)."""
    up = jnp.asarray(ap.mat_pair(_seeded_unitary(1, 78)), jnp.float64)
    monkeypatch.setattr(ap, "_CHUNK_TARGET_BYTES", 1 << 12)
    for q in (0, 5, 8, 10, N - 1):
        monkeypatch.setattr(ap, "_F64_STYLE", "matmul")
        want = ap._apply_matrix_xla(state, up, (q,), (), ())
        monkeypatch.setattr(ap, "_F64_STYLE", "auto")
        got = ap._dense_1q_f64(state, up, q)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=0, atol=1e-13)


@pytest.mark.parametrize("targets", [(0, 1, 2), (3, 8, 11), (2, 5, 9),
                                     (7, 8, 9), (1, 2, 3, 4)])
def test_gather_three_four_targets(state, targets):
    """k=3/4 gather parity vs a dense numpy oracle (the TPU f64 pack policy
    caps fused packs at 2 because WIDER pack programs trip an XLA:TPU
    X64-rewriter miscompile — these cases pin that the engine itself is
    correct, so the cap is purely a backend workaround; see
    docs/DESIGN.md)."""
    k = len(targets)
    u = _seeded_unitary(k, hash(targets) % 2 ** 31)
    up = jnp.asarray(ap.mat_pair(u), jnp.float64)
    # dense oracle: reshape to per-qubit axes, tensordot over the targets
    psi = (np.asarray(state[0]) + 1j * np.asarray(state[1])).reshape((2,) * N)
    # numpy axis j indexes qubit N-1-j (big-endian); the reshaped gate's
    # axes are MSB-first, i.e. targets[k-1] first — pair them accordingly
    axes = tuple(N - 1 - t for t in reversed(targets))
    uk = u.reshape((2,) * (2 * k))
    out = np.tensordot(uk, psi, axes=(tuple(range(k, 2 * k)), axes))
    out = np.moveaxis(out, tuple(range(k)), axes)
    want = out.reshape(-1)
    got = ap._dense_gather(state, up, targets, (), ())
    g_c = np.asarray(got[0]) + 1j * np.asarray(got[1])
    np.testing.assert_allclose(g_c, want, rtol=0, atol=1e-13)


def test_dense_1q_shadow_fused_matches_two_pass(state):
    """The fused density gate+shadow (conj(U) ⊗ U superoperator on
    (q, q+n) through the gather engine) against the two-pass engine.  The
    gather formulation is deliberate: a hand-rolled 4-pattern elementwise
    variant computed a wrong trace on-chip for sublane row bits (the
    X64-rewriter miscompile family — see docs/DESIGN.md)."""
    nq = N // 2
    for q in range(nq):
        u = _seeded_unitary(1, 500 + q)
        up = jnp.asarray(ap.mat_pair(u), jnp.float64)
        upc = jnp.asarray(ap.mat_pair(u.conj()), jnp.float64)
        want = ap._apply_matrix_xla(state, up, (q,), (), ())
        want = ap._apply_matrix_xla(want, upc, (q + nq,), (), ())
        got = ap._dense_1q_f64_shadow(state, up, q, nq)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=0, atol=1e-13)
