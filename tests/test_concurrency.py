"""Concurrency auditor (analysis/concurrency.py) + schedule fuzzer
(analysis/schedfuzz.py): every T_* rule gets one known-bad source
(asserting the stable code) plus clean no-false-positive twins, the repo
self-audit must be clean, the adversarial mutation harness (a removed
``with self._lock:``) must be caught, and the two fuzzer-reproduced races
fixed in this PR — the double-``start()`` check-then-act and the SLO
health-ring store-order tear — are pinned with forced-interleaving
regression tests.  The ReplicaPool shutdown-under-load stress and the
shutdown idempotency contracts live here too.
"""

from __future__ import annotations

import threading
import time

import pytest

from quest_tpu import qft_circuit
from quest_tpu.analysis import concurrency as cc
from quest_tpu.analysis import schedfuzz as sf
from quest_tpu.analysis.diagnostics import AnalysisCode, Severity
from quest_tpu.circuit import Circuit
from quest_tpu.validation import ErrorCode, QuESTError


def codes(diags):
    return [d.code for d in diags]


def audit(src):
    return cc.audit_source(src, "fixture.py")


# ---------------------------------------------------------------------------
# static rules: one bad source per code, clean twins
# ---------------------------------------------------------------------------

_GUARDED_CLEAN = """
import threading

class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []        # guarded-by: _lock

    def put(self, x):
        with self._lock:
            self._items.append(x)

    def snapshot(self):
        with self._lock:
            return list(self._items)
"""


def test_guarded_class_is_clean():
    assert audit(_GUARDED_CLEAN) == []


def test_unguarded_write_flagged():
    src = _GUARDED_CLEAN.replace(
        "    def put(self, x):\n        with self._lock:\n"
        "            self._items.append(x)\n",
        "    def put(self, x):\n        self._items.append(x)\n")
    found = audit(src)
    assert AnalysisCode.UNGUARDED_SHARED_WRITE in codes(found)
    assert all(d.severity == Severity.ERROR for d in found
               if d.code == AnalysisCode.UNGUARDED_SHARED_WRITE)


def test_unguarded_read_is_warning():
    src = _GUARDED_CLEAN.replace(
        "    def snapshot(self):\n        with self._lock:\n"
        "            return list(self._items)\n",
        "    def snapshot(self):\n        return list(self._items)\n")
    found = audit(src)
    assert codes(found) == [AnalysisCode.UNGUARDED_SHARED_READ]
    assert found[0].severity == Severity.WARNING


def test_site_level_lock_free_waiver():
    src = _GUARDED_CLEAN.replace(
        "    def snapshot(self):\n        with self._lock:\n"
        "            return list(self._items)\n",
        "    def snapshot(self):\n"
        "        # lock-free: approximate depth probe for the scrape\n"
        "        return len(self._items)\n")
    assert audit(src) == []


def test_attr_level_lock_free_needs_reason():
    src = """
import threading

class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self.gauge = 0.0    # lock-free: single float store, readers tolerate staleness
        self.bad = 0.0      # lock-free:

    def bump(self):
        self.gauge = 1.0
        self.bad = 1.0
"""
    found = audit(src)
    assert codes(found) == [AnalysisCode.LOCK_FREE_NO_REASON]


def test_inconsistent_guard_under_wrong_lock():
    src = """
import threading

class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._aux = threading.Lock()
        self._items = []    # guarded-by: _lock

    def put(self, x):
        with self._aux:
            self._items.append(x)
"""
    assert AnalysisCode.INCONSISTENT_GUARD in codes(audit(src))


def test_inferred_disjoint_guards_flagged():
    src = """
import threading

class Box:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._items = []

    def put(self, x):
        with self._a:
            self._items.append(x)

    def drop(self):
        with self._b:
            self._items.clear()
"""
    found = audit(src)
    assert AnalysisCode.INCONSISTENT_GUARD in codes(found)
    # and the annotation nudge rides along
    assert AnalysisCode.UNANNOTATED_SHARED_ATTR in codes(found)


def test_lock_order_cycle_across_classes():
    src = """
import threading

class A:
    def __init__(self, b):
        self._lock = threading.Lock()
        self.b = b if b is not None else B(None)

    def poke(self):
        with self._lock:
            self.b.poke()

class B:
    def __init__(self, a):
        self._lock = threading.Lock()
        self.a = a if a is not None else A(None)

    def poke(self):
        with self._lock:
            self.a.poke()
"""
    found = [d for d in audit(src)
             if d.code == AnalysisCode.LOCK_ORDER_CYCLE]
    assert len(found) == 1
    assert "A._lock" in found[0].message and "B._lock" in found[0].message


def test_self_deadlock_on_plain_lock():
    src = """
import threading

class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []    # guarded-by: _lock

    def outer(self):
        with self._lock:
            with self._lock:
                self._items.append(1)
"""
    assert AnalysisCode.LOCK_ORDER_CYCLE in codes(audit(src))
    # the same nesting on an RLock is reentrant: clean
    assert AnalysisCode.LOCK_ORDER_CYCLE not in codes(
        audit(src.replace("threading.Lock()", "threading.RLock()")))


def test_blocking_call_under_lock():
    src = """
import threading, time

class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._out = []      # guarded-by: _lock

    def bad(self, fut):
        with self._lock:
            self._out.append(fut.result())
"""
    assert AnalysisCode.BLOCKING_CALL_UNDER_LOCK in codes(audit(src))


def test_condition_wait_is_not_blocking():
    src = """
import threading

class Box:
    def __init__(self):
        self._cond = threading.Condition()
        self._items = []    # guarded-by: _cond

    def take(self):
        with self._cond:
            while not self._items:
                self._cond.wait(timeout=0.1)
            return self._items.pop()
"""
    assert audit(src) == []


def test_acquire_try_finally_scope_recognized():
    src = """
import threading

class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []    # guarded-by: _lock

    def put(self, x):
        self._lock.acquire()
        try:
            self._items.append(x)
        finally:
            self._lock.release()
"""
    assert audit(src) == []


def test_requires_lock_seeds_scope_and_checks_callers():
    src = """
import threading

class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []    # guarded-by: _lock

    # requires-lock: _lock
    def _evict_locked(self):
        self._items.clear()

    def good(self):
        with self._lock:
            self._evict_locked()

    def bad(self):
        self._evict_locked()
"""
    found = audit(src)
    assert codes(found) == [AnalysisCode.UNGUARDED_SHARED_WRITE]
    assert "_evict_locked" in found[0].message and "bad" in found[0].message


def test_nested_def_does_not_inherit_lock_scope():
    src = """
import threading

class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []    # guarded-by: _lock

    def runner(self):
        with self._lock:
            def later():
                self._items.append(1)
            return later
"""
    assert AnalysisCode.UNGUARDED_SHARED_WRITE in codes(audit(src))


def test_init_only_and_lockless_classes_exempt():
    src = """
import threading

class NoLocks:
    def __init__(self):
        self.items = []

    def put(self, x):
        self.items.append(x)

class ConfigOnly:
    def __init__(self):
        self._lock = threading.Lock()
        self.limit = 10

    def read(self):
        return self.limit
"""
    assert audit(src) == []


# ---------------------------------------------------------------------------
# the repo self-audit + the adversarial mutation harness
# ---------------------------------------------------------------------------

def test_repo_self_audit_is_clean():
    """The acceptance gate: zero findings (ERROR and WARNING both) over
    the annotated serve/deploy/obs surface, plus grad and parallel —
    which own no locks today, and the sweep holds them to it."""
    assert set(cc.AUDIT_SUBPACKAGES) == {"serve", "deploy", "obs",
                                         "grad", "parallel"}
    report, diags = cc.audit_package()
    assert diags == [], [d.format() for d in diags]
    names = {c["name"] for c in report["classes"]}
    # the load-bearing concurrent classes are all audited
    assert {"QuESTService", "CompileCache", "Metrics", "Router",
            "ReplicaPool", "SLOMonitor", "FlightRecorder",
            "TraceRecorder"} <= names
    assert report["lock_graph"]["cycles"] == []


def test_adversarial_mutation_removed_lock_is_flagged():
    """PR 3's mutation-harness pattern: delete one ``with self._lock:``
    from a fixture copy of router.py — the auditor MUST flag the newly
    unguarded write (this is also a CI lint-job step)."""
    import quest_tpu.deploy.router as router_mod
    with open(router_mod.__file__, encoding="utf-8") as fh:
        src = fh.read()
    mutated = cc.strip_first_lock_scope(src)
    assert mutated != src
    found = cc.audit_source(mutated, "router_mutated.py")
    assert AnalysisCode.UNGUARDED_SHARED_WRITE in codes(found)
    # the unmutated source stays clean, so the signal is the mutation
    assert cc.audit_source(src, "router.py") == []


def test_strip_first_lock_scope_requires_a_lock():
    with pytest.raises(ValueError):
        cc.strip_first_lock_scope("x = 1\n")


# ---------------------------------------------------------------------------
# the schedule fuzzer: reproduction power + the canonical scenarios
# ---------------------------------------------------------------------------

_RACY_SRC = """
import threading

class Racy:
    def __init__(self):
        self.flag = False
        self.starts = 0

    def start(self):
        if not self.flag:
            pad_a = 1
            pad_b = pad_a + 1
            pad_c = pad_b + 1
            self.flag = True
            self.starts += 1
            if self.starts > 1:
                raise RuntimeError("double start")
"""


def _load_fixture(tmp_path, name, src):
    path = tmp_path / name
    path.write_text(src)
    ns: dict = {}
    exec(compile(src, str(path), "exec"), ns)  # noqa: S102 — test fixture
    return path, ns


def test_fuzzer_reproduces_check_then_act_race(tmp_path):
    """The harness must be able to FORCE the double-start interleaving a
    plain stress loop almost never hits: some seed interleaves the two
    threads between the check and the set."""
    path, ns = _load_fixture(tmp_path, "racy_mod.py", _RACY_SRC)
    reproduced = False
    for seed in range(10):
        r = ns["Racy"]()
        res = sf.Interleaver(seed=seed, targets=(str(path),)).run(
            [r.start, r.start])
        assert res["completed"]
        if res["errors"]:
            assert "double start" in res["errors"][0]
            reproduced = True
            break
    assert reproduced, "no seed reproduced the check-then-act race"


def test_fuzzer_passes_the_locked_fix(tmp_path):
    fixed = _RACY_SRC.replace(
        "        self.starts = 0\n",
        "        self.starts = 0\n        self._lock = threading.Lock()\n"
    ).replace(
        "        if not self.flag:\n",
        "        with self._lock:\n            if self.flag:\n"
        "                return\n            self.flag = True\n"
    ).replace(
        "            pad_a = 1\n            pad_b = pad_a + 1\n"
        "            pad_c = pad_b + 1\n            self.flag = True\n",
        "")
    path, ns = _load_fixture(tmp_path, "fixed_mod.py", fixed)
    for seed in range(10):
        r = ns["Racy"]()
        res = sf.Interleaver(seed=seed, targets=(str(path),)).run(
            [r.start, r.start])
        assert res["completed"] and not res["errors"], (seed, res)


def test_service_double_start_race_fixed():
    """The real thing: two concurrent ``QuESTService.start()`` calls used
    to double-start the worker thread (RuntimeError: threads can only be
    started once).  Forced interleaving over service.py must find no
    error on any seed now that the check-then-act runs under the
    condition."""
    from quest_tpu.serve.service import QuESTService
    for seed in range(6):
        svc = QuESTService(start=False, max_queue=4)
        res = sf.Interleaver(
            seed=seed, targets=(sf._target("serve/service.py"),)).run(
            [svc.start, svc.start])
        assert res["completed"] and not res["errors"], (seed, res)
        assert svc._worker.is_alive()
        svc.shutdown(drain=False)


def test_fuzzer_reproduces_slo_store_order_tear():
    """The pre-fix ``SLOMonitor.observe`` committed the deadline counters
    BEFORE the latency bucket counts; a lock-free ``health()`` reader
    could then see more deadline'd requests than window samples.  Rebuild
    that store order here and assert the fuzzer reproduces the tear —
    the inverse (current code clean) is pinned by
    test_slo_health_consistent_under_fuzz."""
    from quest_tpu.obs import slo as slo_mod

    def old_order_observe(mon, deadline_ok):
        t = time.monotonic()
        with mon._lock:
            b = mon._health_bucket(t)
            if deadline_ok:             # the buggy order: counters first
                b[1] += 1
            else:
                b[2] += 1
            pad = 0
            pad += 1
            pad += 1
            pad += 1
            pad += 1
            pad += 1
            pad += 1
            # bucket count commits LAST, into an EARLY bucket exactly like
            # the real sub-ms latencies did: the reader walks bc[0..] right
            # after reading the deadline counters, so this is the same
            # few-line tear window the original race had
            b[3][0] += 1
    reproduced = False
    for seed in range(12):
        # a health() ring walk is ~100 traced lines, so the forced-phase
        # budget must cover the whole run or it degrades to free-running
        # and the window is rarely caught (flaked under full-suite load
        # at the 4000 default)
        il = sf.Interleaver(
            seed=seed, targets=(sf._target("obs/slo.py"), __file__),
            max_switches=60000, stall_timeout_s=0.01)
        mon = slo_mod.SLOMonitor()
        mon._lock = il.wrap_lock(mon._lock)
        tears = []

        def writer():
            for i in range(40):
                old_order_observe(mon, i % 2 == 0)

        def reader():
            for _ in range(80):
                h = mon.health()
                if h["window_hits"] + h["window_misses"] \
                        > h["window_samples"]:
                    tears.append(h)
        res = il.run([writer, writer, reader])
        assert res["completed"]
        if tears:
            reproduced = True
            break
    assert reproduced, "no seed reproduced the store-order tear"


@pytest.mark.parametrize("scenario", ["slo_health", "metrics_snapshot",
                                      "queue_saturation", "flight_ring",
                                      "router"])
def test_fuzz_scenarios_clean(scenario):
    fn = {"slo_health": sf.fuzz_slo_health,
          "metrics_snapshot": sf.fuzz_metrics_snapshot,
          "queue_saturation": sf.fuzz_queue_saturation,
          "flight_ring": sf.fuzz_flight_ring,
          "router": sf.fuzz_router}[scenario]
    for seed in (0, 1, 2):
        row = fn(seed=seed)
        assert row["completed"], (scenario, seed, row)
        assert row["violations"] == [], (scenario, seed)
        assert row["errors"] == [], (scenario, seed)


def test_slo_health_consistent_under_fuzz():
    """Regression pin for the fixed store order: the shipped observe()
    never lets a lock-free health() reader see deadlined > samples."""
    for seed in range(4):
        row = sf.fuzz_slo_health(seed=seed, iters=120)
        assert row["violations"] == [], (seed, row["violations"])


# ---------------------------------------------------------------------------
# shutdown contracts: idempotency + the storm stress (tier-1)
# ---------------------------------------------------------------------------

def test_service_shutdown_idempotent():
    from quest_tpu.serve.service import QuESTService
    svc = QuESTService(start=False, max_queue=4)
    f = svc.submit(qft_circuit(3))
    svc.shutdown(drain=False)
    with pytest.raises(QuESTError) as exc:
        f.result(timeout=10)
    assert exc.value.code == ErrorCode.SERVICE_SHUTDOWN
    svc.shutdown(drain=False)           # second call: no-op, no error
    svc.shutdown()                      # and again, with drain
    with pytest.raises(QuESTError) as exc:
        svc.submit(qft_circuit(3))
    assert exc.value.code == ErrorCode.SERVICE_SHUTDOWN


def test_concurrent_start_and_shutdown_never_join_unstarted():
    """Review regression: start() must put Thread.start under the
    condition too, or a racing shutdown() can observe _started and join a
    worker that has not booted yet (RuntimeError: cannot join thread
    before it is started)."""
    from quest_tpu.serve.service import QuESTService
    for seed in range(6):
        svc = QuESTService(start=False, max_queue=4)
        res = sf.Interleaver(
            seed=seed, targets=(sf._target("serve/service.py"),)).run(
            [svc.start, lambda: svc.shutdown(drain=False, timeout=10)])
        assert res["completed"] and not res["errors"], (seed, res)


def test_concurrent_shutdowns_both_mean_stopped():
    """Review regression: a second CONCURRENT shutdown() waits for the
    first teardown instead of returning mid-drain — after either call
    returns, the worker is gone and submits are refused."""
    from quest_tpu.serve.service import QuESTService
    svc = QuESTService(max_queue=8)
    barrier = threading.Barrier(2)

    def stop():
        barrier.wait(5)
        svc.shutdown(timeout=30)
    threads = [threading.Thread(target=stop) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive()
    assert not svc._worker.is_alive()
    with pytest.raises(QuESTError):
        svc.submit(qft_circuit(3))


def test_daemon_thread_leak_unrelated_join_does_not_mask():
    """Review regression: os.path.join (or any non-thread .join) in the
    same function must not satisfy the joined-thread requirement."""
    src = ("import os, threading\n"
           "def storm(root, f):\n"
           "    path = os.path.join(root, f)\n"
           "    t = threading.Thread(target=print)\n"
           "    t.start()\n")
    assert lint(src, "quest_tpu/serve/x.py") == \
        [AnalysisCode.DAEMON_THREAD_LEAK]
    joined = src.replace("    t.start()\n",
                         "    t.start()\n    t.join()\n")
    assert lint(joined, "quest_tpu/serve/x.py") == []


def test_pool_shutdown_idempotent():
    from quest_tpu.deploy.pool import ReplicaPool
    pool = ReplicaPool(2, start=False)
    pool.shutdown(drain=False)
    pool.shutdown(drain=False)          # no-op, not an error
    pool.shutdown()
    with pytest.raises(QuESTError) as exc:
        pool.submit(qft_circuit(3))
    assert exc.value.code == ErrorCode.SERVICE_SHUTDOWN


def test_pool_shutdown_under_load_storm():
    """The tier-1 stress of the satellite contract: a submit storm racing
    ``shutdown(drain=True)`` must not hang, and EVERY future the pool
    accepted resolves — to a result or to a clean QuESTError."""
    import numpy as np

    from quest_tpu.circuit import param_vector
    from quest_tpu.deploy.pool import ReplicaPool
    c = Circuit(3)
    c.rx(0, 0.3)
    c.cnot(0, 1)
    c.rx(2, 0.1)
    base_params = param_vector(c.key())
    pool = ReplicaPool(2, max_queue=64, max_batch=8, max_delay_ms=0.5,
                       dtype=np.float64)
    futures: list = []
    flock = threading.Lock()
    go = threading.Event()

    def storm(base):
        go.wait(5)
        for i in range(30):
            try:
                f = pool.submit(c, params=base_params)
            except QuESTError:
                continue        # bounced or shut down: both clean
            with flock:
                futures.append(f)

    threads = [threading.Thread(target=storm, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    go.set()
    time.sleep(0.05)            # let the storm overlap the shutdown
    t0 = time.monotonic()
    pool.shutdown(drain=True, timeout=60)
    assert time.monotonic() - t0 < 120, "shutdown hung under load"
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive(), "a storm thread hung"
    assert futures, "the storm never got a request in"
    resolved = failed = 0
    for f in futures:
        try:
            r = f.result(timeout=30)
            assert r.state is not None
            resolved += 1
        except QuESTError as exc:
            assert exc.code in (ErrorCode.SERVICE_SHUTDOWN,
                                ErrorCode.QUEUE_FULL,
                                ErrorCode.DEADLINE_EXCEEDED), exc.code
            failed += 1
    assert resolved + failed == len(futures)
    pool.shutdown()             # idempotent after the storm too


# ---------------------------------------------------------------------------
# P_DAEMON_THREAD_LEAK (the purity satellite)
# ---------------------------------------------------------------------------

def lint(src, filename):
    from quest_tpu.analysis import lint_source
    return codes(lint_source(src, filename))


def test_daemon_thread_leak_unjoined():
    src = ("import threading\n"
           "def storm():\n"
           "    t = threading.Thread(target=print)\n"
           "    t.start()\n")
    assert lint(src, "quest_tpu/serve/x.py") == \
        [AnalysisCode.DAEMON_THREAD_LEAK]
    # out of the serve/deploy scope: the rule does not apply
    assert lint(src, "quest_tpu/obs/x.py") == []


def test_daemon_thread_leak_joined_ok():
    src = ("import threading\n"
           "def storm():\n"
           "    ts = [threading.Thread(target=print) for _ in range(2)]\n"
           "    for t in ts:\n"
           "        t.start()\n"
           "    for t in ts:\n"
           "        t.join()\n")
    assert lint(src, "quest_tpu/deploy/x.py") == []


def test_daemon_thread_leak_self_join_ok():
    src = ("import threading\n"
           "class S:\n"
           "    def __init__(self):\n"
           "        self._w = threading.Thread(target=print)\n"
           "    def shutdown(self):\n"
           "        self._w.join()\n")
    assert lint(src, "quest_tpu/serve/x.py") == []


def test_daemon_thread_leak_daemon_comment():
    src = ("import threading\n"
           "def go():\n"
           "    t = threading.Thread(target=print, daemon=True)"
           "  # daemon-ok: monitor outlives nothing\n"
           "    t.start()\n")
    assert lint(src, "quest_tpu/serve/x.py") == []
    bare = src.replace("  # daemon-ok: monitor outlives nothing", "")
    assert lint(bare, "quest_tpu/serve/x.py") == \
        [AnalysisCode.DAEMON_THREAD_LEAK]


def test_serve_worker_thread_passes_the_rule():
    """The shipped worker thread (daemon + joined + commented) is clean —
    the self-lint CI gate stays green with the new rule on."""
    from quest_tpu.analysis import lint_package
    leaks = [d for d in lint_package()
             if d.code == AnalysisCode.DAEMON_THREAD_LEAK]
    assert leaks == []
