"""The native C++ gate-fusion engine: semantic equivalence + actual fusion."""

from __future__ import annotations

import os

import numpy as np
import pytest

import quest_tpu as qt
from quest_tpu import native
from oracle import NUM_QUBITS, random_statevector, set_sv, sv, SV_TOL

N = NUM_QUBITS


@pytest.fixture(scope="module", autouse=True)
def _need_native():
    if not native.available():
        pytest.skip("native fusion library unavailable")


def _equiv(env, circuit, max_pack=1):
    vec = random_statevector(circuit.num_qubits)
    q1 = qt.createQureg(circuit.num_qubits, env)
    q2 = qt.createQureg(circuit.num_qubits, env)
    set_sv(q1, vec)
    set_sv(q2, vec)
    qt.apply_circuit(q1, circuit)
    import copy
    opt = copy.deepcopy(circuit).optimize(max_pack=max_pack)
    qt.apply_circuit(q2, opt)
    np.testing.assert_allclose(sv(q2), sv(q1), atol=SV_TOL)
    return opt


def test_adjacent_1q_gates_merge(env_local):
    c = qt.Circuit(3)
    c.h(0).rz(0, 0.3).ry(0, -0.5).h(1).t(1)
    opt = _equiv(env_local, c)
    # three ops on qubit 0 fuse to one, two on qubit 1 fuse to one
    assert len(opt) == 2


def test_self_inverse_cancellation(env_local):
    c = qt.Circuit(3)
    c.x(0).x(0).swap(1, 2).swap(1, 2).h(0)
    opt = _equiv(env_local, c)
    assert len(opt) == 1  # only the H survives


def test_hh_cancels_to_identity(env_local):
    c = qt.Circuit(2)
    c.h(0).h(0)
    opt = _equiv(env_local, c)
    assert len(opt) == 0


def test_diagonals_commute_and_merge(env_local):
    c = qt.Circuit(4)
    # diagonal on q0, diagonal on q2, then another diagonal on q0 — the
    # commuting sink must merge the two q0 diagonals across the q2 one
    c.rz(0, 0.2).phase_shift(2, 0.5).rz(0, 0.7).s(2)
    opt = _equiv(env_local, c)
    assert len(opt) == 2


def test_cnot_pair_cancels(env_local):
    c = qt.Circuit(3)
    c.cnot(0, 1).cnot(0, 1).ry(2, 0.4)
    opt = _equiv(env_local, c)
    assert len(opt) == 1


def test_controlled_dense_merge(env_local):
    c = qt.Circuit(3)
    c.phase_shift(1, 0.3, controls=(0,)).phase_shift(1, -0.3, controls=(0,))
    opt = _equiv(env_local, c)
    assert len(opt) == 0  # merged then identity-eliminated


def test_disjoint_hop(env_local):
    c = qt.Circuit(4)
    # dense gate on q3 sits between two q0 gates; q0 gates hop across
    c.ry(0, 0.1).ry(3, 0.9).ry(0, 0.2)
    opt = _equiv(env_local, c)
    assert len(opt) == 2


def test_random_circuit_equivalence(env):
    c = qt.random_circuit(N, depth=4, seed=9)
    before = len(c)
    opt = _equiv(env, c)
    assert len(opt) <= before


# ---------------------------------------------------------------------------
# kron packing (max_pack > 1): parallel gates merge into multi-target gates
# ---------------------------------------------------------------------------

def test_pack_parallel_1q_gates(env_local):
    c = qt.Circuit(5)
    for q in range(5):
        c.ry(q, 0.1 * (q + 1))
    opt = _equiv(env_local, c, max_pack=7)
    assert len(opt) == 1
    assert sorted(opt.ops[0].targets) == [0, 1, 2, 3, 4]


def test_pack_respects_width(env_local):
    c = qt.Circuit(5)
    for q in range(5):
        c.ry(q, 0.3)
    opt = _equiv(env_local, c, max_pack=2)
    assert len(opt) == 3  # 2 + 2 + 1


def test_pack_diagonals_and_cz(env_local):
    c = qt.Circuit(6)
    c.cz(0, 1).cz(2, 3).cz(4, 5).rz(0, 0.4)
    opt = _equiv(env_local, c, max_pack=7)
    # CZs absorb their controls into 2q diagonals; all pack with the rz
    assert len(opt) == 1
    assert opt.ops[0].kind == "diagonal"


def test_pack_random_circuit(env):
    c = qt.random_circuit(N, depth=3, seed=31)
    opt = _equiv(env, c, max_pack=7)
    # each depth layer (5 gates + CZs) packs to ~1 dense + 1 diagonal op
    assert len(opt) <= 8


def test_pack_x_y_promotion(env_local):
    c = qt.Circuit(4)
    c.x(0).y(1).h(2).z(3)
    opt = _equiv(env_local, c, max_pack=7)
    assert len(opt) == 1


def test_pack_diag_densify_after_break(env_local):
    """A lone 1q diagonal that scans past a blocker still krons into the
    disjoint dense pack recorded before the break (the fallback path)."""
    c = qt.Circuit(3)
    c.cnot(1, 2).h(0).s(2)
    # s(2) cannot merge with cnot(1,2) (controlled, shares qubit 2) but must
    # densify into the h(0) pack it commuted past -> cnot + dense{0,2}
    opt = _equiv(env_local, c, max_pack=2)
    assert len(opt) == 2


def test_fusion_selftest_binary(tmp_path):
    """Build and run the native fusion self-test (CI additionally runs it
    under ASan/UBSan — the reference's QUEST_MEMCHECK analogue)."""
    import shutil
    import subprocess
    if shutil.which("g++") is None:
        pytest.skip("no C++ compiler")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    binary = tmp_path / "fusion_selftest"
    subprocess.run(["g++", "-O2", "-std=c++17",
                    os.path.join(root, "native", "fusion.cpp"),
                    os.path.join(root, "native", "fusion_selftest.cpp"),
                    "-o", str(binary)], check=True, capture_output=True)
    r = subprocess.run([str(binary)], capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout[-500:]
    assert "all fusion self-tests passed" in r.stdout
