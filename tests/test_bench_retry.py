"""Regression tests for bench.py's transient-error retry policy.

The driver records bench.py's single JSON line as the round's BENCH artifact;
a transient axon-tunnel drop (observed: "INTERNAL: ...remote_compile: read
body: response body closed before all bytes were read") must cost one retry,
not a red config row, while deterministic failures must fail fast and keep
their root cause.  These tests drive the helper directly — no device work.
"""

from __future__ import annotations

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402


def test_transient_failure_is_retried_and_recorded():
    calls = {"n": 0}

    def flaky(*a, **k):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError(
                "INTERNAL: http://127.0.0.1:8103/remote_compile: read body: "
                "response body closed before all bytes were read")
        return 2e8, {"qubits": 24}

    value, cfg, errors = bench._run_config(flaky)
    assert calls["n"] == 2
    assert value == 2e8
    # the JSON stays auditable: the swallowed failure is recorded
    assert cfg["retried"] == 1
    assert "remote_compile" in cfg["retry_error"]
    assert len(errors) == 1


@pytest.mark.parametrize("exc", [
    AssertionError("state not normalised: 0.5"),
    ValueError("bad config"),
])
def test_deterministic_failure_fails_fast_with_root_cause(exc):
    calls = {"n": 0}

    def det(*a, **k):
        calls["n"] += 1
        raise exc

    value, cfg, errors = bench._run_config(det)
    assert value is None and cfg is None
    assert calls["n"] == 1, "deterministic failures must not be re-run"
    assert errors == [f"{type(exc).__name__}: {exc}"]
    assert bench._run_config.last_exc is exc


def test_double_transient_failure_keeps_root_cause_first():
    calls = {"n": 0}

    def twice(*a, **k):
        calls["n"] += 1
        raise OSError("connection reset by peer" if calls["n"] == 1
                      else "RESOURCE_EXHAUSTED: out of memory")

    value, cfg, errors = bench._run_config(twice)
    assert value is None
    assert calls["n"] == 2
    assert "connection reset" in errors[0]  # root cause, not the retry's OOM
    assert "RESOURCE_EXHAUSTED" in errors[1]
