"""Benchmark harness: single-qubit-gate amplitude-update throughput per chip.

Workload: a random-circuit layer (Haar 1-qubit gate per qubit + a CZ ladder),
pre-fused by the native scheduler (native/fusion.cpp) into ~n/7 kron-packed
MXU matmuls, then iterated ``depth`` times INSIDE one jitted
``lax.fori_loop`` — a single device-resident program, so remote-dispatch
latency cannot pollute the measurement.  Timing boundaries read back a scalar
norm, forcing real completion even through async device tunnels.

Metric (the reference's headline unit, BASELINE.md north star
>=1e8 single-qubit-gate amplitude updates / sec / chip):

    value = 2^n * n * depth / wall_seconds / n_chips

Prints exactly one JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}

Env overrides: QUEST_BENCH_QUBITS (default 24), QUEST_BENCH_DEPTH (default
50), QUEST_BENCH_PRECISION (1|2, default 1), QUEST_BENCH_FUSE (default 1).
"""

from __future__ import annotations

import json
import os
import sys
import time

BASELINE_AMPS_PER_SEC = 1e8  # driver target (BASELINE.md north star)


def main() -> None:
    import jax
    import jax.numpy as jnp
    from functools import partial

    platform = jax.devices()[0].platform
    n = int(os.environ.get("QUEST_BENCH_QUBITS", "24"))
    depth = int(os.environ.get("QUEST_BENCH_DEPTH", "50"))
    precision = int(os.environ.get("QUEST_BENCH_PRECISION", "1"))
    fuse = os.environ.get("QUEST_BENCH_FUSE", "1") == "1"
    dtype = jnp.float32 if precision == 1 else jnp.float64

    from quest_tpu.circuit import _apply_one, random_circuit

    circuit = random_circuit(n, depth=1, seed=11)
    if fuse:
        circuit.optimize()  # native kron-packing: ~n/7 MXU matmuls per layer
    ops = circuit.key()

    @partial(jax.jit, static_argnames=())
    def run(state, iters):
        def body(_, s):
            for op in ops:
                s = _apply_one(s, op)
            return s
        s = jax.lax.fori_loop(0, iters, body, state)
        return jnp.sum(s[0] * s[0] + s[1] * s[1])

    state = jnp.zeros((2, 1 << n), dtype=dtype).at[0, 0].set(1.0)

    # warmup: compiles the program; scalar read forces real completion
    float(run(state, 1))

    t0 = time.perf_counter()
    base = float(run(state, 0))  # dispatch + readback overhead
    t_overhead = time.perf_counter() - t0

    t0 = time.perf_counter()
    total = float(run(state, depth))
    dt = time.perf_counter() - t0
    assert abs(total - 1.0) < 1e-2, f"state not normalised: {total}"
    assert abs(base - 1.0) < 1e-2

    compute = max(dt - t_overhead, 1e-9)
    amps_per_sec = (1 << n) * n * depth / compute
    result = {
        "metric": "statevec_1q_gate_amp_updates_per_sec_per_chip",
        "value": amps_per_sec,
        "unit": "amps/s",
        "vs_baseline": amps_per_sec / BASELINE_AMPS_PER_SEC,
        "config": {"qubits": n, "depth": depth, "precision": precision,
                   "fused_ops_per_layer": len(ops), "platform": platform,
                   "seconds": dt, "overhead_seconds": t_overhead},
    }
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
